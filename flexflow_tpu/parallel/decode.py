"""Incremental (KV-cache) decoding for arbitrary PCGs.

The reference's serving story is a Triton prototype that replays a full
forward per request (triton/README.md: "incomplete prototype"); it has no
incremental decode at all. This module gives the TPU build O(1)-per-token
decoding for ANY causal decoder or encoder-decoder PCG — including graphs
imported from HF (mt5), where attention is built from primitive ops
(batch_matmul / softmax / elementwise masks) rather than the fused MHA op.

How: classify every tensor by how the decode position flows through it.

  * live axis    — the axis indexed by decoder position; per step only the
    newest s0 positions are computed (s0 = 1, or prompt_len at prefill).
  * prefix axis  — an axis that ranges over ALL positions so far (the
    key/value axis of attention scores); reads come from a persistent
    cache of shape cap (= max_len) that each step appends to.
  * static       — everything not downstream of the decode input: the
    encoder subgraph, relative-position-bias chains, baked mask
    constants. Computed ONCE at init (with the static graph inputs) and
    sliced per step where a static axis aligns with a live/prefix axis.

Axis info propagates forward from the decode input through a per-op-type
rule table (pointwise ops pass it through; transpose/reshape remap it;
batch_matmul creates/consumes prefix axes). Ops the rules can't prove
exact raise NotImplementedError at build time — the same contract as the
strict seq-pointwise checker this generalizes.

Exactness: a softmax over a prefix axis gets an injected causality/
validity mask (cache position <= query position), which both enforces
causal attention and hides the cache's unwritten tail; for causal models
this reproduces the full forward bit-for-bit modulo float association
(asserted against the full forward in tests/test_serving_qa.py).

Causality of PRIMITIVE-op attention: the injected mask is only exact if
the graph's own attention IS causal, and for imported graphs that fact
lives in baked mask constants. build_plan PROVES it where it can — it
walks the live chain between the score matmul and each prefix softmax
looking for a baked constant aligned to the (query, key) plane whose
strict upper triangle is masked (additive <= -1e4, or all-False for a
boolean where-condition) — and otherwise REFUSES to build unless the
caller passes assume_causal=True. A bidirectional/prefix-LM import
therefore errors at build time instead of silently decoding causally;
the fused-MHA path already rejects non-causal self-attention via its
op params.
"""
from __future__ import annotations

import dataclasses
import warnings
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ff_types import AggrMode, OperatorType
from ..ops.registry import FwdCtx, get_op_def

NEG_INF = -1e30


class DecodeExactnessError(NotImplementedError):
    """Incremental decode cannot prove a step exact for this graph.

    Subclasses NotImplementedError so existing callers keep working; the
    serving layer catches THIS type to fall back (e.g. the batcher keeps
    the training-strategy executables when a decode-searched graph's
    step can't be built) instead of swallowing unrelated bugs."""


# Decode-fallback bookkeeping, mirroring the attention fallback contract
# (ops/attention.py): every occurrence counts toward
# ff_decode_fallback_total{reason=...}; each distinct (site, reason)
# warns once per process. Build/trace-time exactness failures that have
# NO exact recovery still raise (DecodeExactnessError) — but counted, so
# an aborted batcher boot is visible in telemetry instead of silent.
_DECODE_FALLBACK_WARNED: set = set()


def reset_decode_fallback_warnings() -> None:
    """Forget which (site, reason) decode fallbacks already warned
    (tests; a fresh process starts empty)."""
    _DECODE_FALLBACK_WARNED.clear()


def decode_fallback(site: str, reason: str, detail: str) -> None:
    """Count + warn-once for a decode fast path falling back (or, for
    unrecoverable exactness failures, aborting visibly)."""
    from .. import obs

    obs.count("ff_decode_fallback_total",
              help="incremental-decode fast paths that fell back to a "
                   "dense/recovery path (or aborted on an unprovable "
                   "step)",
              reason=reason)
    key = (site, reason)
    if key in _DECODE_FALLBACK_WARNED:
        return
    _DECODE_FALLBACK_WARNED.add(key)
    warnings.warn(
        f"incremental decode on {site or 'a decode graph'} fell back "
        f"({reason}): {detail}"
    )

# pointwise in every axis (rank-preserving): the live/prefix axes pass
# straight through; execution on a slice is the plain forward
_POINTWISE = frozenset({
    OperatorType.OP_EW_ADD, OperatorType.OP_EW_SUB, OperatorType.OP_EW_MUL,
    OperatorType.OP_EW_DIV, OperatorType.OP_EW_MAX, OperatorType.OP_EW_MIN,
    OperatorType.OP_WHERE,
})


@dataclasses.dataclass(frozen=True)
class AxisInfo:
    """Where the decode position lives in a tensor. None = static/full."""

    live: Optional[int] = None
    prefix: Optional[int] = None

    @property
    def is_live(self) -> bool:
        return self.live is not None or self.prefix is not None


@dataclasses.dataclass
class DecodePlan:
    """Build-time product: everything the jitted step needs."""

    live_ops: List  # topo-ordered ops downstream of the decode input
    static_ops: List  # topo-ordered ops computable from static inputs
    info: Dict[int, AxisInfo]  # guid -> axis info (live tensors only)
    cached_guids: List[int]  # tensors consumed at full prefix length
    static_needed: List[int]  # static guids consumed by live ops
    live_len: int  # compiled decoder length L
    decode_pt: object  # the decode-driving input ParallelTensor
    requires_cap_le_live_len: bool  # static slicing present


def _is_unary_pointwise(op) -> bool:
    d = get_op_def(op.op_type)
    # rank-preserving single-input ops whose forward treats every axis as
    # a batch axis: elementwise unaries, cast, dropout(inference), linear
    # (contracts the LAST axis only), embedding lookup, identity
    return op.op_type in (
        OperatorType.OP_CAST, OperatorType.OP_DROPOUT, OperatorType.OP_NOOP,
        OperatorType.OP_IDENTITY,
    ) or (d.num_inputs == 1 and op.op_type.name.startswith(("OP_SCALAR_",))
          ) or op.op_type in (
        OperatorType.OP_EXP, OperatorType.OP_LOG, OperatorType.OP_RELU,
        OperatorType.OP_SIGMOID, OperatorType.OP_TANH, OperatorType.OP_ELU,
        OperatorType.OP_GELU, OperatorType.OP_RSQRT, OperatorType.OP_SQRT,
        OperatorType.OP_SIN, OperatorType.OP_COS, OperatorType.OP_POW,
        OperatorType.OP_PRELU,
    )


def _bcast_axis(in_rank: int, out_rank: int, axis: int) -> int:
    """Right-aligned broadcast: input axis -> output axis position."""
    return axis + (out_rank - in_rank)


class _Propagator:
    """Forward axis-info propagation + build-time validation."""

    def __init__(self, live_len: int):
        self.live_len = live_len
        self.info: Dict[int, AxisInfo] = {}
        self.cached: set = set()
        self.saw_static_slicing = False
        # softmax ops over a prefix axis (primitive-op attention rows):
        # each needs a causality proof or an assume_causal opt-in
        self.prefix_softmaxes: List = []

    def get(self, guid) -> AxisInfo:
        return self.info.get(guid, AxisInfo())

    def visit(self, op):
        t = op.op_type
        ins = [self.get(x.guid) for x in op.inputs]
        in_shapes = [tuple(x.material_shape()) for x in op.inputs]
        out_shapes = [tuple(x.material_shape()) for x in op.outputs]

        def fail(msg):
            raise DecodeExactnessError(
                f"{op.name} ({t.name}): incremental decode can't prove "
                f"exactness — {msg}"
            )

        def set_out(i, info):
            self.info[op.outputs[i].guid] = info

        if t == OperatorType.OP_MULTIHEAD_ATTENTION:
            q, k, v = ins
            if q.live != 1 or q.prefix is not None:
                fail("attention query must be (batch, seq, embed) with the "
                     "live axis at 1")
            if k.is_live or v.is_live:
                # self-attention via the op's own KV cache
                if not (k.live == 1 and v.live == 1 and k.prefix is None
                        and v.prefix is None):
                    fail("attention k/v must be live at axis 1")
                if not op.params.causal:
                    fail("needs causal=True (otherwise each position sees "
                         "the future and the cached prefix is stale)")
            elif op.params.causal:
                # the full forward would tril-mask cross scores; the
                # decode kernel attends the full encoder unmasked
                fail("causal cross-attention has no decode rule")
            # cross-attention: k/v static (encoder side) — full-length
            # K/V computed once, no causal mask (matches the full forward)
            set_out(0, AxisInfo(live=1))
            return

        if _is_unary_pointwise(op) or (
            t == OperatorType.OP_LINEAR
        ) or (
            t == OperatorType.OP_EMBEDDING
            and op.params.aggr == AggrMode.AGGR_MODE_NONE
        ):
            a = ins[0]
            if t == OperatorType.OP_LINEAR and (
                a.live == len(in_shapes[0]) - 1
                or a.prefix == len(in_shapes[0]) - 1
            ):
                fail("linear contracts the live/prefix axis")
            if t == OperatorType.OP_EMBEDDING:
                # (.., L) ids -> (.., L, E): axes keep their positions
                set_out(0, AxisInfo(live=a.live, prefix=a.prefix))
                return
            set_out(0, a)
            return

        if t in (OperatorType.OP_LAYERNORM,):
            a = ins[0]
            nd = len(in_shapes[0])
            if any(ax % nd in (a.live, a.prefix) for ax in op.params.axes):
                fail("layernorm normalizes over the live/prefix axis")
            set_out(0, a)
            return

        if t in (OperatorType.OP_REDUCE_SUM, OperatorType.OP_REDUCE_MEAN,
                 OperatorType.OP_MEAN):
            a = ins[0]
            nd = len(in_shapes[0])
            axes = sorted(ax % nd for ax in op.params.axes)
            if any(ax in (a.live, a.prefix) for ax in axes):
                fail("reduce over the live/prefix axis")
            if getattr(op.params, "keepdims", True):
                set_out(0, a)
            else:
                def drop(axis):
                    if axis is None:
                        return None
                    return axis - sum(1 for ax in axes if ax < axis)
                set_out(0, AxisInfo(live=drop(a.live), prefix=drop(a.prefix)))
            return

        if t == OperatorType.OP_SOFTMAX:
            a = ins[0]
            nd = len(in_shapes[0])
            dim = op.params.dim % nd
            if dim == a.live:
                fail("softmax over the live axis")
            # softmax over the prefix axis is the attention row softmax;
            # the step injects the causality/validity mask there
            if dim == a.prefix:
                self.prefix_softmaxes.append(op)
            set_out(0, a)
            return

        if t == OperatorType.OP_TRANSPOSE:
            a = ins[0]
            perm = list(op.params.perm)

            def remap(axis):
                return None if axis is None else perm.index(axis)
            set_out(0, AxisInfo(live=remap(a.live), prefix=remap(a.prefix)))
            return

        if t in (OperatorType.OP_SQUEEZE, OperatorType.OP_UNSQUEEZE):
            a = ins[0]
            nd_in, nd_out = len(in_shapes[0]), len(out_shapes[0])
            if t == OperatorType.OP_UNSQUEEZE:
                added = sorted(ax % nd_out for ax in op.params.axes)

                def remap(axis):
                    if axis is None:
                        return None
                    for ad in added:
                        if ad <= axis:
                            axis += 1
                    return axis
            else:
                removed = sorted(ax % nd_in for ax in op.params.axes)
                if any(ax in (a.live, a.prefix) for ax in removed):
                    fail("squeeze removes the live/prefix axis")

                def remap(axis):
                    if axis is None:
                        return None
                    return axis - sum(1 for ax in removed if ax < axis)
            set_out(0, AxisInfo(live=remap(a.live), prefix=remap(a.prefix)))
            return

        if t in (OperatorType.OP_RESHAPE, OperatorType.OP_FLAT):
            a = ins[0]
            if a.prefix is not None:
                fail("reshape of a tensor with a prefix axis")
            if a.live is None:
                set_out(0, AxisInfo())
                return
            s_in, s_out = in_shapes[0], out_shapes[0]
            # the live axis must survive as a standalone axis: volumes
            # before/at it must match some output prefix
            pre = int(np.prod(s_in[:a.live], dtype=np.int64))
            out_live = None
            acc = 1
            for i, d in enumerate(s_out):
                if acc == pre and d == s_in[a.live]:
                    out_live = i
                    break
                acc *= d
            if out_live is None:
                fail(f"reshape {s_in}->{s_out} splits/merges the live axis")
            set_out(0, AxisInfo(live=out_live))
            return

        if t in _POINTWISE:
            out_rank = len(out_shapes[0])
            live = prefix = None
            for inf, s in zip(ins, in_shapes):
                if inf.live is not None:
                    al = _bcast_axis(len(s), out_rank, inf.live)
                    if live is not None and live != al:
                        fail("two live inputs broadcast to different axes")
                    live = al
                if inf.prefix is not None:
                    ap = _bcast_axis(len(s), out_rank, inf.prefix)
                    if prefix is not None and prefix != ap:
                        fail("two prefix inputs broadcast to different axes")
                    prefix = ap
            # static operands with a full-length axis aligned to live or
            # prefix get sliced per step — note that slicing happens
            for inf, s in zip(ins, in_shapes):
                if not inf.is_live:
                    for ax, d in enumerate(s):
                        pos = _bcast_axis(len(s), out_rank, ax)
                        if d > 1 and pos in (live, prefix):
                            if d != self.live_len:
                                fail(
                                    f"static operand axis {ax} (size {d}) "
                                    f"aligns with the decode axis but isn't "
                                    f"the compiled decoder length "
                                    f"{self.live_len}"
                                )
                            self.saw_static_slicing = True
            if live is None and prefix is None:
                fail("elementwise op classified live but no live input")
            set_out(0, AxisInfo(live=live, prefix=prefix))
            return

        if t == OperatorType.OP_CONCAT:
            axis = op.params.axis % len(out_shapes[0])
            lives = {inf.live for inf in ins}
            prefixes = {inf.prefix for inf in ins}
            if len(lives) != 1 or len(prefixes) != 1:
                fail("concat mixes live and static inputs")
            a = ins[0]
            if axis in (a.live, a.prefix):
                fail("concat along the live/prefix axis")
            set_out(0, a)
            return

        if t == OperatorType.OP_SPLIT:
            a = ins[0]
            axis = op.params.axis % len(in_shapes[0])
            if axis in (a.live, a.prefix):
                fail("split along the live/prefix axis")
            for i in range(len(op.outputs)):
                set_out(i, a)
            return

        if t == OperatorType.OP_BATCHMATMUL:
            a, b = ins
            ra, rb = len(in_shapes[0]), len(in_shapes[1])
            ro = len(out_shapes[0])
            M, K_a = ra - 2, ra - 1
            K_b, N = rb - 2, rb - 1

            # batch-dim liveness: both operands sliced at the same step —
            # behaves like an elementwise op over the batch dims
            a_batch_live = a.live is not None and a.live < M
            b_batch_live = b.live is not None and b.live < K_b

            if a.prefix is not None and a.prefix == K_a:
                # probs @ V: contract the prefix axis against a cached
                # full-length operand
                if b.is_live:
                    if b.live != K_b or b.prefix is not None:
                        fail("prefix contraction needs the rhs live on its "
                             "contraction axis")
                    self.cached.add(op.inputs[1].guid)
                elif in_shapes[1][K_b] != self.live_len:
                    fail("prefix contraction against a static rhs of the "
                         "wrong length")
                else:
                    self.saw_static_slicing = True
                if a.live is not None and a.live != M and not a_batch_live:
                    fail("unsupported live-axis position in lhs")
                set_out(0, AxisInfo(live=a.live if a.live != K_a else None))
                return
            if a.prefix is not None:
                fail("lhs prefix axis not on the contraction dim")

            if a.live == K_a or (b.is_live and b.live == K_b):
                fail("contraction over a live axis without a prefix lhs")

            out_live = None
            out_prefix = None
            if a_batch_live or b_batch_live:
                la = a.live if a_batch_live else None
                lb = b.live + (ro - rb) if b_batch_live else None
                if la is not None and lb is not None and la != lb:
                    fail("lhs/rhs live on different batch axes")
                out_live = la if la is not None else lb
            if a.live == M:
                if out_live is not None:
                    fail("live axis on both batch and M dims")
                out_live = ro - 2
            if b.is_live and b.live == N:
                # Q @ K^T: rhs is the transposed key matrix, consumed at
                # full prefix length -> the output's N axis is a prefix
                if b.prefix is not None:
                    fail("rhs has both live and prefix axes")
                self.cached.add(op.inputs[1].guid)
                out_prefix = ro - 1
            set_out(0, AxisInfo(live=out_live, prefix=out_prefix))
            return

        fail("op mixes sequence positions and has no decode rule")


def _is_causal_mask_constant(arr, live_ax: int, prefix_ax: int) -> bool:
    """True iff the baked constant masks every future position in the
    (query=live, key=prefix) plane: additive masks have strict-upper
    entries <= -1e4 for every leading index; boolean where-conditions
    (True = keep) have them all False. Entries on/below the diagonal are
    unconstrained — a combined bias+mask (T5-style) still proves causal."""
    v = np.asarray(arr)
    if v.ndim < 2:
        return False
    v = np.moveaxis(v, (live_ax, prefix_ax), (-2, -1))
    L = min(v.shape[-2], v.shape[-1])
    iu = np.triu_indices(n=v.shape[-2], k=1, m=v.shape[-1])
    if iu[0].size == 0:
        return L > 0  # 1x1 plane: nothing future-facing to mask
    upper = v[..., iu[0], iu[1]]
    if v.dtype == np.bool_:
        return not bool(upper.any())
    if not np.issubdtype(v.dtype, np.floating):
        return False
    return bool(np.all(upper <= -1e4))


def _static_chain_causal(guid: int, q_ax: int, k_ax: int, producer,
                         constants, live_len: int, depth: int = 0) -> bool:
    """Does the STATIC value `guid` carry a causal mask on its (q_ax, k_ax)
    plane? Baked constants are checked directly; computed statics (e.g.
    T5's position_bias = relative-bias-embedding + baked causal mask) are
    traced through mask-preserving ops: EW_ADD (adding anything finite to
    a -inf-masked entry keeps it masked), axis-remapping transpose/
    (un)squeeze, and cast/identity. Anything else ends the proof."""
    if depth > 32:
        return False
    if guid in constants:
        _, value = constants[guid]
        if not isinstance(value, np.ndarray):
            return False
        if (value.ndim <= max(q_ax, k_ax)
                or value.shape[q_ax] != live_len
                or value.shape[k_ax] != live_len):
            return False
        return _is_causal_mask_constant(value, q_ax, k_ax)
    p = producer.get(guid)
    if p is None:
        return False  # a graph input: value unknown at build time
    t = p.op_type
    out_rank = len(p.outputs[0].material_shape())
    if t == OperatorType.OP_CAST:
        # only float->float preserves additive-mask semantics (a -1e9 mask
        # cast to bool becomes all-True — the OPPOSITE of masked)
        import numpy as _np
        src_f = _np.issubdtype(p.inputs[0].data_type.np_dtype, _np.floating)
        dst_f = _np.issubdtype(p.outputs[0].data_type.np_dtype, _np.floating)
        if not (src_f and dst_f):
            return False
        return _static_chain_causal(p.inputs[0].guid, q_ax, k_ax, producer,
                                    constants, live_len, depth + 1)
    if getattr(p, "is_parallel_op", False) or t in (
        OperatorType.OP_NOOP, OperatorType.OP_IDENTITY,
        OperatorType.OP_DROPOUT,
    ):
        return _static_chain_causal(p.inputs[0].guid, q_ax, k_ax, producer,
                                    constants, live_len, depth + 1)
    if t in (OperatorType.OP_EW_ADD,):
        for x in p.inputs:
            s = tuple(x.material_shape())
            off = out_rank - len(s)
            qa, ka = q_ax - off, k_ax - off
            if (qa >= 0 and ka >= 0 and s[qa] == live_len
                    and s[ka] == live_len
                    and _static_chain_causal(x.guid, qa, ka, producer,
                                             constants, live_len, depth + 1)):
                return True
        return False
    if t == OperatorType.OP_TRANSPOSE:
        perm = list(p.params.perm)
        return _static_chain_causal(p.inputs[0].guid, perm[q_ax], perm[k_ax],
                                    producer, constants, live_len, depth + 1)
    if t == OperatorType.OP_UNSQUEEZE:
        added = sorted(ax % out_rank for ax in p.params.axes)
        if q_ax in added or k_ax in added:
            return False

        def back(axis):
            return axis - sum(1 for ad in added if ad < axis)
        return _static_chain_causal(p.inputs[0].guid, back(q_ax), back(k_ax),
                                    producer, constants, live_len, depth + 1)
    if t == OperatorType.OP_SQUEEZE:
        in_rank = len(p.inputs[0].material_shape())
        removed = sorted(ax % in_rank for ax in p.params.axes)

        def fwd(axis):
            for r in removed:
                if r <= axis:
                    axis += 1
            return axis
        return _static_chain_causal(p.inputs[0].guid, fwd(q_ax), fwd(k_ax),
                                    producer, constants, live_len, depth + 1)
    return False


def _prove_causal(softmax_op, prop: "_Propagator", live_ops, static_ops,
                  constants, live_len: int) -> bool:
    """Walk the live chain feeding a prefix softmax (back to the score
    matmul that created the prefix axis) and look for a static operand,
    aligned to the (live, prefix) plane, that provably masks the strict
    upper triangle (directly baked, or computed from a baked causal mask —
    _static_chain_causal). Finding one proves the graph's own attention is
    causal, so the injected decode mask reproduces the full forward."""
    producer = {}
    for op in list(live_ops) + list(static_ops):
        for t in op.outputs:
            producer[t.guid] = op

    seen = set()
    stack = [softmax_op.inputs[0].guid]
    while stack:
        guid = stack.pop()
        if guid in seen:
            continue
        seen.add(guid)
        p = producer.get(guid)
        if p is None:
            continue
        if getattr(p, "is_parallel_op", False):
            stack.append(p.inputs[0].guid)
            continue
        out_info = prop.get(p.outputs[0].guid)
        if out_info.prefix is None:
            continue  # left the attention-score region
        made_prefix = all(
            prop.get(x.guid).prefix is None for x in p.inputs
        )
        out_rank = len(p.outputs[0].material_shape())
        # Check this op's non-live operands for a provable mask — but ONLY
        # where the op APPLIES the operand in a mask-preserving way:
        #   * EW_ADD: adding a -inf-masked operand masks the output;
        #   * WHERE(cond, x, y): a tril boolean condition proves causal
        #     only if the else-branch y is itself provably <= -1e4.
        # An EW_SUB of a tril-negative constant would UNMASK the future,
        # and a WHERE with a finite else-branch doesn't mask at all — a
        # causal-looking constant on those ops must not count as proof.
        t = p.op_type
        if t == OperatorType.OP_EW_ADD:
            candidates = list(p.inputs)
        elif t == OperatorType.OP_WHERE and len(p.inputs) == 3:
            y = p.inputs[2]
            y_masked = False
            if y.guid in constants:
                _, yv = constants[y.guid]
                yarr = np.asarray(yv)
                y_masked = (np.issubdtype(yarr.dtype, np.floating)
                            and bool(np.all(yarr <= -1e4)))
            candidates = [p.inputs[0]] if y_masked else []
        else:
            candidates = []
        for x in candidates:
            if prop.get(x.guid).is_live:
                continue
            amap = _static_alignment(
                tuple(x.material_shape()), out_rank, out_info, live_len,
            )
            axes = dict((kind, ax) for ax, kind in amap)
            if "live" in axes and "prefix" in axes and _static_chain_causal(
                x.guid, axes["live"], axes["prefix"], producer, constants,
                live_len,
            ):
                return True
        if not made_prefix:
            for x in p.inputs:
                if prop.get(x.guid).is_live:
                    stack.append(x.guid)
    return False


def build_plan(topo, input_pts, constants, decode_input: Optional[int] = None,
               assume_causal: bool = False):
    """Classify ops/tensors and validate decodability.

    decode_input: index into input_pts of the decode-driven input; default
    is the last input (enc-dec convention: (encoder_ids, decoder_ids)).
    assume_causal: skip the causality proof for primitive-op attention
    (graphs whose masks are computed rather than baked can't be verified
    at build time — the caller vouches that decoder self-attention is
    causal).
    """
    inputs = list(input_pts)
    if decode_input is None:
        decode_input = len(inputs) - 1
    decode_pt = inputs[decode_input]
    live_len = decode_pt.material_shape()[1]

    prop = _Propagator(live_len)
    prop.info[decode_pt.guid] = AxisInfo(live=1)

    live_ops, static_ops = [], []
    for op in topo:
        if op.is_parallel_op:
            # decode runs single-device; parallel ops are identity over an
            # unsharded value (degree bookkeeping only)
            src = op.inputs[0].guid
            if prop.get(src).is_live:
                prop.info[op.outputs[0].guid] = prop.get(src)
                live_ops.append(op)
            else:
                static_ops.append(op)
            continue
        if any(prop.get(x.guid).is_live for x in op.inputs):
            prop.visit(op)
            live_ops.append(op)
        else:
            static_ops.append(op)

    # static guids live ops actually read: outputs of static ops AND
    # static graph inputs consumed directly (e.g. an explicit attention
    # mask input added to live scores)
    static_out = {pt.guid for pt in inputs if pt.guid != decode_pt.guid}
    for op in static_ops:
        for x in op.outputs:
            static_out.add(x.guid)
    needed = []
    for op in live_ops:
        for x in op.inputs:
            if not prop.get(x.guid).is_live and x.guid in static_out:
                if x.guid not in needed:
                    needed.append(x.guid)

    if not assume_causal:
        for sm in prop.prefix_softmaxes:
            if not _prove_causal(sm, prop, live_ops, static_ops, constants,
                                 live_len):
                raise DecodeExactnessError(
                    f"{sm.name} ({sm.op_type.name}): primitive-op attention "
                    "whose causality can't be proven from baked mask "
                    "constants — the decode step would inject a causal "
                    "mask, which is wrong for bidirectional/prefix-LM "
                    "graphs. Pass assume_causal=True to vouch that "
                    "decoder self-attention is causal."
                )
    return DecodePlan(
        live_ops=live_ops,
        static_ops=static_ops,
        info=prop.info,
        cached_guids=sorted(prop.cached),
        static_needed=needed,
        live_len=live_len,
        decode_pt=decode_pt,
        requires_cap_le_live_len=prop.saw_static_slicing,
    )


def _slice_aligned(val, info_axis_map, t, s0, cap, out_rank=None,
                   site: str = ""):
    """Slice a static/full value per its alignment: live-aligned axes take
    [t:t+s0], prefix-aligned axes take [0:cap].

    When `t` is a (b,) vector of per-row positions (continuous batching:
    each decode slot is at its own position), live-aligned axes are sliced
    per row — a vmapped dynamic slice that materializes a leading batch
    axis. `out_rank` (the consuming op's output rank) is then required to
    re-align the result so broadcasting still lines the batch axis up with
    the live stream's axis 0.

    Alignment cases an exact recovery exists for fall back to it with the
    ff_decode_fallback_total{reason} counter + one warning (the
    batch-position live axis at s0=1 turns into a dense per-row gather);
    genuinely unprovable cases raise DecodeExactnessError — still
    counted, so an aborted batcher boot shows up in telemetry."""
    per_row_t = getattr(t, "ndim", 0) == 1
    live_axes = [axis for axis, kind in info_axis_map if kind == "live"]
    for axis, kind in info_axis_map:
        if kind == "prefix":
            val = jax.lax.slice_in_dim(val, 0, cap, axis=axis)
    if not live_axes:
        return val
    if not per_row_t:
        for axis in live_axes:
            val = jax.lax.dynamic_slice_in_dim(val, t, s0, axis=axis)
        return val
    if out_rank is None:
        decode_fallback(site, "no_out_rank",
                        "per-row decode positions need the consuming "
                        "op's output rank to realign a sliced static "
                        "operand — no exact recovery, aborting the build")
        raise DecodeExactnessError(
            "per-row decode positions need the consuming op's output rank "
            "to realign a sliced static operand"
        )
    b = t.shape[0]
    offset = out_rank - val.ndim  # right-aligned broadcast offset
    if any(axis + offset == 0 for axis in live_axes):
        # the live-aligned axis IS the output's batch axis (offset == 0,
        # axis == 0). For single-token steps (s0 == 1 — the only shape
        # per-row positions arrive in) row i of the output reads exactly
        # position t[i]: a dense per-row gather is exact, so recover
        # instead of aborting the batcher boot.
        if s0 == 1 and offset == 0:
            decode_fallback(
                site, "batch_live_gather",
                "a static operand's live-aligned axis coincides with the "
                "batch axis; recovered with a dense per-row gather "
                "(jnp.take over the position vector) instead of the "
                "sliced fast path",
            )
            val = jnp.take(val, t, axis=0)  # (b,) + val.shape[1:]
            rest = [axis for axis in live_axes if axis != 0]
            if rest:
                def slice_rest(v, tt):
                    for axis in rest:
                        v = jax.lax.dynamic_slice_in_dim(
                            v, tt, s0, axis=axis - 1)
                    return v
                val = jax.vmap(slice_rest, in_axes=(0, 0))(val, t)
            return val
        decode_fallback(
            site, "batch_live_block",
            "a static operand's live-aligned axis coincides with the "
            "batch axis and the step has s0 > 1 (a prefill block) — no "
            "exact per-row recovery, aborting the build",
        )
        raise DecodeExactnessError(
            "per-row decode positions: a static operand's live-aligned axis "
            "coincides with the batch axis"
        )
    if offset == 0:
        # the value's axis 0 occupies the batch position
        if val.shape[0] == b:
            def slice_row(v, tt):  # v: one row, axes shifted down by 1
                for axis in live_axes:
                    v = jax.lax.dynamic_slice_in_dim(v, tt, s0, axis=axis - 1)
                return v
            return jax.vmap(slice_row, in_axes=(0, 0))(val, t)
        if val.shape[0] != 1:
            decode_fallback(
                site, "batch_mismatch",
                f"static operand batch axis {val.shape[0]} matches "
                f"neither the decode batch {b} nor 1 — rows cannot be "
                "matched to slots, no exact recovery",
            )
            raise DecodeExactnessError(
                f"static operand batch axis {val.shape[0]} matches neither "
                f"the decode batch {b} nor 1"
            )

    def slice_full(tt):  # closes over val at its original rank
        v = val
        for axis in live_axes:
            v = jax.lax.dynamic_slice_in_dim(v, tt, s0, axis=axis)
        return v

    sliced = jax.vmap(slice_full)(t)  # (b,) + sliced val shape
    if offset == 0:  # drop the original size-1 batch axis
        return jnp.squeeze(sliced, axis=1)
    # no batch axis on the static value: the new leading axis is the
    # batch; pad interior size-1 axes so right-aligned broadcasting puts
    # it at the output's axis 0
    return jnp.reshape(sliced, (b,) + (1,) * (offset - 1) + sliced.shape[1:])


def _static_alignment(shape, out_rank, out_info: AxisInfo, live_len):
    """Which axes of a static operand need slicing against a live stream."""
    plan = []
    for ax, d in enumerate(shape):
        pos = _bcast_axis(len(shape), out_rank, ax)
        if d > 1 and d == live_len:
            if pos == out_info.live:
                plan.append((ax, "live"))
            elif pos == out_info.prefix:
                plan.append((ax, "prefix"))
    return plan
