#!/usr/bin/env bash
# Standalone StrategyTuner sweep (docs/adaptation.md): the self-healing
# re-search/hot-swap loop on 8- and 4-device CPU meshes.
#
#   leg 1  tests/test_tuner.py fast suite on both mesh sizes (trigger
#          hysteresis/cooldown, bit-exact carryover, every fault-injected
#          rollback leg, serving decode-retune exactness)
#   leg 2  the @pytest.mark.slow chaos story tier-1 skips: a run started
#          under a deliberately miscalibrated machine model converges to
#          best-known step time without a restart (ROADMAP old item 1's
#          win condition)
#   leg 3  an end-to-end driver asserting the published accounting: a
#          fault-injected rollback and a committed swap in one telemetry
#          session, ff_strategy_swaps_total{outcome} in metrics.prom
#          covering both, and the swap-boundary instant present in the
#          step-observatory overlay artifact (step_timeline.json)
#
#   scripts/tuner_check.sh                 # full sweep
#   FF_TUNER_DEVICES=8 scripts/tuner_check.sh -k fault
set -euo pipefail
cd "$(dirname "$0")/.."

devices="${FF_TUNER_DEVICES:-8 4}"
for n in $devices; do
    echo "=== tuner sweep: ${n}-device CPU mesh ==="
    # jax_num_cpu_devices needs jax >= 0.4.34; the XLA flag covers older
    env JAX_PLATFORMS=cpu \
        JAX_NUM_CPU_DEVICES="$n" \
        XLA_FLAGS="--xla_force_host_platform_device_count=$n" \
        python -m pytest tests/test_tuner.py -v -m 'not slow' \
        -p no:cacheprovider "$@"
done

echo "=== tuner chaos: miscalibrated start converges without restart ==="
env JAX_PLATFORMS=cpu \
    JAX_NUM_CPU_DEVICES=8 \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_tuner.py -v -m slow -p no:cacheprovider

echo "=== tuner accounting: swap outcomes + overlay boundary artifact ==="
OUT="$(mktemp -d)"
trap 'rm -rf "$OUT"' EXIT
env JAX_PLATFORMS=cpu \
    JAX_NUM_CPU_DEVICES=8 \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    FF_TUNER_CHECK_DIR="$OUT" \
    python - <<'EOF'
import json
import os

import numpy as np

from flexflow_tpu import (
    ActiMode,
    DataType,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
    TunerConfig,
    obs,
)
from flexflow_tpu.obs import TelemetryConfig
from flexflow_tpu.obs.metrics import parse_prometheus
from flexflow_tpu.runtime.resilience import FaultInjector

out = os.environ["FF_TUNER_CHECK_DIR"]


def small_model():
    cfg = FFConfig()
    cfg.batch_size = 8
    m = FFModel(cfg)
    x = m.create_tensor((8, 4), DataType.DT_FLOAT)
    t = m.dense(x, 16, ActiMode.AC_MODE_RELU)
    t = m.dense(t, 3)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.1, momentum=0.9),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              [MetricsType.METRICS_ACCURACY])
    return m


rng = np.random.RandomState(0)
x = rng.randn(64, 4).astype(np.float32)
y = rng.randint(0, 3, (64, 1)).astype(np.int32)
# force a cycle per fit: trigger immediately, accept any simulated win,
# huge guard band so CPU timing noise cannot flip the asserted outcome
tcfg = dict(drift_threshold=-1.0, hysteresis_steps=1, cooldown_steps=3,
            warmup_steps=0, min_win=-100.0, post_swap_steps=2,
            search_budget=4, guard_band=1e9)

with obs.session(TelemetryConfig(dir=out, step_profile=True)):
    # rollback leg first: its model has no committed swap, so the commit
    # leg's capture (run last) publishes the overlay with the boundary
    fi = FaultInjector()
    fi.inject("swap_reshard_corruption", times=1, delta=2.0)
    m_rb = small_model()
    m_rb.fit(x, y, batch_size=8, epochs=2, verbose=False,
             tuner=TunerConfig(**tcfg), fault_injector=fi)
    assert fi.fired.get("swap_reshard_corruption") == 1, fi.fired
    assert m_rb._tuner.outcomes["rolled_back"] >= 1, m_rb._tuner.outcomes

    m_ok = small_model()
    m_ok.fit(x, y, batch_size=8, epochs=2, verbose=False,
             tuner=TunerConfig(**tcfg))
    assert m_ok._tuner.outcomes["committed"] >= 1, m_ok._tuner.outcomes

prom = parse_prometheus(open(os.path.join(out, "metrics.prom")).read())
committed = sum(v for k, v in prom.items()
                if k.startswith("ff_strategy_swaps_total")
                and 'outcome="committed"' in k)
rolled_back = sum(v for k, v in prom.items()
                  if k.startswith("ff_strategy_swaps_total")
                  and 'outcome="rolled_back"' in k)
assert committed >= 1, prom
assert rolled_back >= 1, prom

overlay = json.load(open(os.path.join(out, "step_timeline.json")))
events = overlay.get("traceEvents", overlay)
swaps = [e for e in events if e.get("name") == "strategy_swap"]
assert swaps, "no strategy_swap boundary instant in the overlay"
assert all("fingerprint" in (e.get("args") or {}) for e in swaps), swaps
print("tuner_check accounting: committed=%d rolled_back=%d "
      "overlay_swaps=%d — OK" % (committed, rolled_back, len(swaps)))
EOF

echo "tuner_check: OK"
