"""reference: python/flexflow/keras_exp/models/__init__.py"""
from .model import BaseModel, Model, Sequential  # noqa: F401
from .tensor import Tensor  # noqa: F401
