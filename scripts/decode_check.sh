#!/usr/bin/env bash
# Disaggregated prefill/decode check (docs/serving.md): the decode
# objective must buy something and must be vetted like the training
# strategy. Three stages:
#   1. compile-both-objectives on 8- and 4-device CPU meshes: the
#      decode-searched strategy must DIFFER from the training one, the
#      decode cost model must rank it faster, and the static analyzer
#      (full FFA pass stack incl. FFA509, --fail-on error semantics)
#      must pass over BOTH strategies;
#   2. the decode suite (cost oracle units, paged-kernel parity,
#      batcher exactness, strategy round-trip) on both meshes;
#   3. a decode bench smoke: FF_BENCH_WORKLOAD=decode must emit a
#      decode_tokens_throughput line with the decode strategy ACTIVE,
#      and the regression gate must treat the unpublished series as
#      warn-only.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

for n in 8 4; do
    echo "=== decode_check: compile both objectives, ${n}-device mesh ==="
    env JAX_NUM_CPU_DEVICES="$n" \
        XLA_FLAGS="--xla_force_host_platform_device_count=$n" \
        python - "$n" <<'EOF'
import sys

from flexflow_tpu import (ActiMode, AggrMode, DataType, FFConfig, FFModel,
                          LossType, MetricsType, SGDOptimizer)
from flexflow_tpu.analysis.perf import perf_diagnostics
from flexflow_tpu.search import simulate_runtime

n = int(sys.argv[1])
cfg = FFConfig()
cfg.batch_size = 2
cfg.search_budget = 1
cfg.workersPerNode = n
m = FFModel(cfg)
ids = m.create_tensor((2, 16), DataType.DT_INT32)
t = m.embedding(ids, 29, 16, AggrMode.AGGR_MODE_NONE)
t = m.multihead_attention(t, t, t, 16, 2, causal=True)
t = m.dense(t, 16, ActiMode.AC_MODE_RELU)
t = m.softmax(m.dense(t, 29))
m.compile(SGDOptimizer(lr=0.01),
          LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
          [MetricsType.METRICS_ACCURACY])
m.compile_decode()

train = sorted(tuple(v.dim) for v in m.searched_views.values())
dec = sorted(tuple(v.dim) for v in m.decode_searched_views.values())
assert dec != train, f"decode search found the training strategy: {dec}"
cm = m._build_cost_model(objective="decode")
t_train = simulate_runtime(m.graph, m.searched_views, cm)
t_dec = simulate_runtime(m.decode_graph, m.decode_searched_views, cm)
assert t_dec < t_train, (t_dec, t_train)

for label, graph, views, objective in (
    ("train", m.graph, m.searched_views, "train"),
    ("decode", m.decode_graph, m.decode_searched_views, "decode"),
):
    rep = perf_diagnostics(graph, views=views,
                           cost_model=m._build_cost_model(objective=objective),
                           num_devices=n, objective=objective)
    assert not rep.errors, (
        f"{label} strategy has analyzer errors: "
        + "; ".join(d.format() for d in rep.errors))
    print(f"decode_check[{n}dev] {label}: {len(rep.warnings)} warnings, "
          f"0 errors")
print(f"decode_check[{n}dev]: decode {t_dec:.3e}s vs train-strategy "
      f"{t_train:.3e}s under the decode objective — OK")
EOF

    echo "=== decode_check: decode suite, ${n}-device mesh ==="
    env JAX_NUM_CPU_DEVICES="$n" \
        XLA_FLAGS="--xla_force_host_platform_device_count=$n" \
        python -m pytest tests/test_decode_search.py -q -p no:cacheprovider
done

echo "=== decode_check: bench smoke (FF_BENCH_WORKLOAD=decode) ==="
OUT="$(mktemp -d)"
trap 'rm -rf "$OUT"' EXIT
env FF_BENCH_WORKLOAD=decode FF_BENCH_SMOKE=1 \
    python bench.py | tee "$OUT/bench.json"
python - "$OUT/bench.json" <<'EOF'
import json
import sys

doc = json.load(open(sys.argv[1]))
assert doc["metric"] == "decode_tokens_throughput", doc
assert doc["unit"] == "tokens/s/chip" and doc["value"] > 0, doc
assert doc["decode_strategy_active"] is True, (
    "bench served with the TRAINING strategy — decode executor "
    "incompatible or fallback fired: %r" % (doc,))
print("decode_check bench:", doc["value"], doc["unit"], "— OK")
EOF
python scripts/bench_regression.py "$OUT/bench.json" --history-dir "$OUT"

echo "decode_check: OK"
