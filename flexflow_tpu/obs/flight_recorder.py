"""Crash flight recorder: bounded in-memory ring + forensics bundles.

When a process dies, its telemetry dies with it — the events.jsonl tail
may be unflushed, the metrics page is whatever was last written, and the
KV pool / strategy state that explains the failure is gone. The flight
recorder keeps a bounded ring of the most recent trace events (fed as a
`Tracer` sink, so it sees events even past the tracer's `max_events`
cap) and of the health-relevant metric series (fed by `record_metric`
from step boundaries and sentinel observations), plus a set of named
*providers* — callables that snapshot live state (HBM watermarks,
topology fingerprint, strategy/calibration provenance, KV pool audits)
at dump time only.

On any typed failure (`NonFiniteGradientsError`,
`StrategyDivergenceError`, `KVCacheExhaustedError`, `SliceLossError`,
replica death, tuner rollback) `dump()` writes a forensics bundle into
`<dir>/forensics/` — tmp+`os.replace` with a crc32 over the canonical
payload bytes, the same crash-atomic envelope the artifact store uses —
and appends one line to an append-only `INDEX.jsonl` that survives
elastic restarts (a restarted process keeps appending; the index is the
recovery-time map of every incident the fleet has had in that
directory). Bundles are inspected offline via
`python -m flexflow_tpu.obs forensics` (`--show` / `--validate`);
schema in docs/observability.md.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
import zlib
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

logger = logging.getLogger("flexflow_tpu.obs.flight_recorder")

FORENSICS_DIRNAME = "forensics"
INDEX_FILE = "INDEX.jsonl"
BUNDLE_SCHEMA = 1

# Typed failures worth a bundle, matched by class name anywhere in the
# exception's MRO so this module never imports the runtime packages that
# define them (they import obs).
TYPED_FAILURES = frozenset({
    "NonFiniteGradientsError",
    "StrategyDivergenceError",
    "KVCacheExhaustedError",
    "SliceLossError",
    "CheckpointCorruptionError",
    "CanaryMismatchError",
    "ArtifactCorruptionError",
})

# marker attribute set on an exception after its bundle is written, so
# the same failure propagating through several hooks dumps exactly once
_DUMPED_ATTR = "__ff_forensics_bundle__"


def _canonical_payload_bytes(payload: dict) -> bytes:
    return json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


class FlightRecorder:
    """Bounded ring of recent events + metric samples, dumped on demand.

    Thread-safe; all recording paths are cheap appends to bounded
    deques. Providers run only at dump time and are individually
    guarded — a provider that throws contributes an error string, never
    kills the dump."""

    def __init__(self, dir: str, *, process: Optional[str] = None,
                 capacity: int = 2048, metric_window: int = 512):
        self.dir = dir
        self.process = process or f"pid{os.getpid()}"
        self._events: Deque[dict] = deque(maxlen=max(1, capacity))
        # series -> deque of (unixtime, value)
        self._metrics: Dict[str, Deque[Tuple[float, float]]] = {}
        self._metric_window = max(1, metric_window)
        self._providers: Dict[str, Callable[[], object]] = {}
        self._seq = 0
        self._lock = threading.Lock()

    # -- recording -------------------------------------------------------
    def record_event(self, event: dict) -> None:
        """Tracer-sink entry point (`tracer.add_sink(rec.record_event)`)."""
        with self._lock:
            self._events.append(event)

    def record_metric(self, series: str, value: float,
                      t: Optional[float] = None) -> None:
        with self._lock:
            dq = self._metrics.get(series)
            if dq is None:
                dq = deque(maxlen=self._metric_window)
                self._metrics[series] = dq
            dq.append((time.time() if t is None else t, float(value)))

    def register_provider(self, name: str,
                          fn: Callable[[], object]) -> None:
        """Register a dump-time state snapshotter (KV pool audit,
        strategy provenance, ...). Last registration under a name wins."""
        with self._lock:
            self._providers[name] = fn

    def unregister_provider(self, name: str) -> None:
        with self._lock:
            self._providers.pop(name, None)

    # -- dumping ---------------------------------------------------------
    def snapshot(self) -> dict:
        """The dump payload body, minus envelope/reason: event tail,
        metric time series, and every provider's (guarded) output."""
        with self._lock:
            events = list(self._events)
            metrics = {k: list(v) for k, v in self._metrics.items()}
            providers = dict(self._providers)
        provided: Dict[str, object] = {}
        for name, fn in providers.items():
            try:
                provided[name] = fn()
            except Exception as e:
                provided[name] = {"error": f"{type(e).__name__}: {e}"}
        return {"events": events, "metrics": metrics, "state": provided}

    @property
    def forensics_dir(self) -> str:
        return os.path.join(self.dir, FORENSICS_DIRNAME)

    def dump(self, *, reason: str, error: Optional[BaseException] = None,
             process: Optional[str] = None, extra: Optional[dict] = None,
             ) -> str:
        """Write one forensics bundle; returns its path. Crash-atomic
        (tmp + os.replace, crc32 envelope) and indexed append-only."""
        process = process or self.process
        now = time.time()
        with self._lock:
            self._seq += 1
            seq = self._seq
        payload = dict(self.snapshot())
        payload.update({
            "schema": BUNDLE_SCHEMA,
            "unixtime": now,
            "process": process,
            "pid": os.getpid(),
            "reason": reason,
        })
        if error is not None:
            payload["error"] = {"type": type(error).__name__,
                                "message": str(error)}
        if extra:
            payload["extra"] = extra
        # normalize to pure JSON (default=str for stray objects) so the
        # crc computed here matches a recompute over the re-parsed file
        payload = json.loads(json.dumps(payload, default=str))
        fdir = self.forensics_dir
        os.makedirs(fdir, exist_ok=True)
        name = f"{process}-{int(now * 1000):013d}-{seq:03d}-{reason}.json"
        path = os.path.join(fdir, name)
        crc = zlib.crc32(_canonical_payload_bytes(payload)) & 0xFFFFFFFF
        envelope = {"schema": BUNDLE_SCHEMA, "crc32": crc,
                    "payload": payload}
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(envelope, f)
        os.replace(tmp, path)
        index_line = {"unixtime": now, "file": name, "process": process,
                      "reason": reason, "crc32": crc,
                      "error_type": (type(error).__name__
                                     if error is not None else None)}
        with open(os.path.join(fdir, INDEX_FILE), "a") as f:
            f.write(json.dumps(index_line) + "\n")
            f.flush()
        try:
            from . import count, event
            event("forensics_dump", cat="obs", reason=reason,
                  process=process, file=name)
            count("ff_forensics_dumps_total",
                  help="flight-recorder forensics bundles written",
                  reason=reason)
        except Exception:  # fflint: disable=FFL002 — best-effort signal
            pass
        logger.warning("flight recorder: wrote forensics bundle %s "
                       "(reason=%s)", path, reason)
        return path


# ----------------------------------------------------------------------
# module-level recorder (one per process, like the obs session)
# ----------------------------------------------------------------------
_RECORDER: Optional[FlightRecorder] = None
_INSTALL_LOCK = threading.Lock()


def install(dir: str, *, process: Optional[str] = None,
            capacity: int = 2048, metric_window: int = 512,
            ) -> FlightRecorder:
    """Install the process-wide recorder (replacing any prior one) and
    wire the default providers: topology fingerprint and HBM watermarks
    (both guarded — absent backends degrade to an error string)."""
    global _RECORDER
    rec = FlightRecorder(dir, process=process, capacity=capacity,
                         metric_window=metric_window)
    rec.register_provider("topology", _topology_provider)
    rec.register_provider("hbm_watermarks", _hbm_provider)
    with _INSTALL_LOCK:
        _RECORDER = rec
    return rec


def recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def uninstall(rec: Optional[FlightRecorder] = None) -> None:
    """Remove the process-wide recorder (or only `rec`, if it is still
    the installed one — a session tearing down must not evict a newer
    session's recorder)."""
    global _RECORDER
    with _INSTALL_LOCK:
        if rec is None or _RECORDER is rec:
            _RECORDER = None


def _topology_provider() -> dict:
    from ..runtime.elastic import topology_fingerprint

    return topology_fingerprint()


def _hbm_provider() -> dict:
    from .step_profile import HbmSampler

    s = HbmSampler()
    return {"source": s.source,
            "bytes_by_device": {str(k): int(v)
                                for k, v in s.sample().items()}}


def dump(*, reason: str, error: Optional[BaseException] = None,
         **extra) -> Optional[str]:
    """Dump a bundle through the installed recorder; None when no
    recorder is installed (the disabled path stays silent and cheap)."""
    rec = recorder()
    if rec is None:
        return None
    try:
        return rec.dump(reason=reason, error=error, extra=extra or None)
    except Exception as e:
        logger.error("flight recorder: dump failed (%s)", e)
        return None


def maybe_dump_failure(exc: BaseException, *, reason: Optional[str] = None,
                       **extra) -> Optional[str]:
    """Dump iff `exc` is a typed failure (by class name, anywhere in the
    MRO) that has not already produced a bundle. Returns the bundle path
    or None. Safe to call from multiple hooks on the same exception —
    the first dump marks it."""
    rec = recorder()
    if rec is None:
        return None
    names = {c.__name__ for c in type(exc).__mro__}
    if not (names & TYPED_FAILURES):
        return None
    if getattr(exc, _DUMPED_ATTR, None) is not None:
        return getattr(exc, _DUMPED_ATTR)
    path = dump(reason=reason or type(exc).__name__, error=exc, **extra)
    if path is not None:
        try:
            setattr(exc, _DUMPED_ATTR, path)
        except Exception:  # fflint: disable=FFL002 — slotted exceptions
            pass
    return path


# ----------------------------------------------------------------------
# offline: validation + index reading (the `obs forensics` CLI)
# ----------------------------------------------------------------------
def read_bundle(path: str) -> dict:
    """Load + integrity-check one bundle; returns the payload. Raises
    ValueError on any corruption (bad JSON, schema, crc)."""
    with open(path) as f:
        try:
            envelope = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}: not valid JSON ({e})") from e
    problems = validate_envelope(envelope, path=path)
    if problems:
        raise ValueError("; ".join(problems))
    return envelope["payload"]


def validate_envelope(envelope: object, *, path: str = "<bundle>"
                      ) -> List[str]:
    problems: List[str] = []
    if not isinstance(envelope, dict):
        return [f"{path}: envelope is not an object"]
    payload = envelope.get("payload")
    if not isinstance(payload, dict):
        return [f"{path}: missing payload object"]
    if envelope.get("schema") != BUNDLE_SCHEMA:
        problems.append(f"{path}: schema {envelope.get('schema')!r} "
                        f"!= {BUNDLE_SCHEMA}")
    crc = zlib.crc32(_canonical_payload_bytes(payload)) & 0xFFFFFFFF
    if crc != envelope.get("crc32"):
        problems.append(f"{path}: crc32 mismatch "
                        f"({envelope.get('crc32')!r} recorded, "
                        f"{crc} computed)")
    for key in ("unixtime", "process", "reason", "events", "metrics",
                "state"):
        if key not in payload:
            problems.append(f"{path}: payload missing {key!r}")
    if not isinstance(payload.get("events"), list):
        problems.append(f"{path}: events is not a list")
    return problems


def validate_bundle(path: str) -> List[str]:
    """Problems list for one bundle file (empty = valid)."""
    try:
        with open(path) as f:
            envelope = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return [f"{path}: unreadable ({e})"]
    return validate_envelope(envelope, path=path)


def read_index(dir: str) -> Tuple[List[dict], List[str]]:
    """Parse `<dir>/INDEX.jsonl` (where `dir` is the forensics dir OR a
    telemetry dir containing one). Returns (entries, problems); a
    truncated final line (crash mid-append) is reported, earlier entries
    still parse — append-only means history is never rewritten."""
    fdir = dir
    if not os.path.exists(os.path.join(fdir, INDEX_FILE)):
        sub = os.path.join(dir, FORENSICS_DIRNAME)
        if os.path.exists(os.path.join(sub, INDEX_FILE)):
            fdir = sub
    index_path = os.path.join(fdir, INDEX_FILE)
    entries: List[dict] = []
    problems: List[str] = []
    if not os.path.exists(index_path):
        return entries, [f"{index_path}: no index"]
    with open(index_path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                problems.append(f"{index_path}:{i}: unparseable entry "
                                "(truncated append?)")
                continue
            rec["_dir"] = fdir
            entries.append(rec)
    return entries, problems


def validate_dir(dir: str) -> Tuple[List[dict], List[str]]:
    """Validate every indexed bundle under `dir`. Returns (entries,
    problems): index parse problems, missing bundle files, and per-bundle
    envelope/crc failures; also flags bundles on disk that the index
    does not know about."""
    entries, problems = read_index(dir)
    seen = set()
    for rec in entries:
        fname = rec.get("file")
        if not fname:
            problems.append(f"index entry missing file: {rec!r}")
            continue
        seen.add(fname)
        path = os.path.join(rec["_dir"], fname)
        if not os.path.exists(path):
            problems.append(f"{fname}: indexed but missing on disk")
            continue
        problems.extend(validate_bundle(path))
    if entries:
        fdir = entries[0]["_dir"]
        for fname in sorted(os.listdir(fdir)):
            if (fname.endswith(".json") and fname not in seen
                    and not fname.startswith(".")):
                problems.append(f"{fname}: on disk but not indexed")
    return entries, problems
