"""Gather along axis 1 (reference: examples/python/keras/gather.py —
torch.gather semantics through K.internal.gather)."""
import numpy as np

import flexflow.keras.models
import flexflow.keras.optimizers
from flexflow.keras.layers import Input, Dense, Reshape
from flexflow.keras.backend.internal import gather

from _example_args import example_args


def get_modified_idx(idx, hidden):
    return idx.reshape(-1, 1).repeat(hidden, 1).astype(np.int32)


def top_level_task(args):
    h = 3
    idx = np.array([[5, 7, 9], [8, 4, 0]])
    idx = get_modified_idx(idx, h)  # 6,3

    in0 = Input(shape=(10,), dtype="float32")
    in1 = Input(shape=idx.shape, dtype="int32")
    x0 = Dense(30, activation="relu")(in0)
    x0 = Reshape((10, h))(x0)
    f0 = gather(x0, in1, axis=1)  # B,6,3
    f0 = Reshape((18,))(f0)
    out = Dense(1)(f0)

    model = flexflow.keras.models.Model([in0, in1], out)
    model.compile(optimizer=flexflow.keras.optimizers.Adam(learning_rate=0.001),
                  loss="mean_squared_error", metrics=["mean_squared_error"],
                  batch_size=args.batch_size)
    n = args.num_samples
    model.fit([np.random.randn(n, 10).astype(np.float32),
               idx[None].repeat(n, 0).astype(np.int32)],
              np.random.randn(n, 1).astype(np.float32), epochs=args.epochs)


if __name__ == "__main__":
    print("gather")
    top_level_task(example_args(epochs=2, num_samples=512))
