#!/usr/bin/env bash
# reference: scripts/osdi22ae/bert.sh
source "$(dirname "${BASH_SOURCE[0]}")/common.sh"

echo "Running BERT with a parallelization strategy discovered by Unity"
run_example transformer.py -b 8 --budget 30

echo "Running BERT with data parallelism"
run_example transformer.py -b 8 --budget 30 --only-data-parallel
