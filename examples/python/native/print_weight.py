"""Weight inspection demo (reference:
examples/python/native/print_weight.py — train one step, then inline_map a
dense layer's kernel and print it)."""
from flexflow.core import *  # noqa: F401,F403
import numpy as np


def top_level_task():
    ffconfig = FFConfig()
    ffmodel = FFModel(ffconfig)
    bs = ffconfig.batch_size

    input_tensor = ffmodel.create_tensor([bs, 784], DataType.DT_FLOAT)
    t = ffmodel.dense(input_tensor, 128, ActiMode.AC_MODE_RELU)
    t = ffmodel.dense(t, 10)
    t = ffmodel.softmax(t)

    ffmodel.compile(
        optimizer=SGDOptimizer(ffmodel, 0.01),
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.METRICS_ACCURACY])
    ffmodel.init_layers()

    rng = np.random.RandomState(0)
    x = rng.rand(bs * 4, 784).astype("float32")
    y = rng.randint(0, 10, (bs * 4, 1)).astype("int32")
    ffmodel.fit(x, y, epochs=1, verbose=False)

    dense1 = ffmodel.get_layer_by_id(0)
    kernel = dense1.get_weight_tensor()
    kernel.inline_map(ffmodel, ffconfig)
    arr = kernel.get_array(ffmodel, ffconfig)
    print("dense1 kernel:", arr.shape, "mean", float(arr.mean()))
    kernel.inline_unmap(ffmodel, ffconfig)


if __name__ == "__main__":
    print("print weight")
    top_level_task()
