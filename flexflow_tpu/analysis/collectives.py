"""Collective-consistency analysis.

Lowers each parallel op + MachineView transition to its implied
collective (Combine -> all-gather, Reduction -> all-reduce,
Repartition -> scatter/reshard, Replicate -> broadcast, AllToAll ->
all-to-all) and statically detects the bug classes that otherwise show
up as deadlocks or silently-wrong numbers on device:

  * FFA201 — a sharded tensor crosses a machine-view boundary between
    two compute ops with no parallel op mediating the reshard;
  * FFA202 — a Reduction whose axis does not point at the partial
    (replica) dim it is meant to sum, or that has nothing to reduce;
  * FFA203 — a normalization (softmax) whose reduction axis is
    partitioned: each shard normalizes over a fraction of the axis and
    produces wrong results with no collective to stitch them (this is
    the wrong-softmax-axis defect PR 3's differential verifier could
    only localize by running the model);
  * FFA204 — two collectives with no dependency ordering whose device
    sets partially overlap: the shared devices may issue them in
    different orders than the non-shared ones observe — the classic
    static deadlock / cross-shard order mismatch;
  * FFA205 — a MachineView addressing devices outside the live device
    range;
  * FFA206 — a view whose part count disagrees with the op's output
    degree (warning: lowering demotes it to replication);
  * FFA207 — a WeightShard (FSDP) op whose target carries no shardable
    weights, or whose target's weight-dim degrees disagree with the
    declared shard degree (the implied all-gather/reduce-scatter pair
    would move the wrong bytes, or nothing at all);
  * FFA505 — all-to-all / collective-bytes coverage: an AllToAll whose
    declared exchange degree disagrees with its input sharding (the
    expert-dispatch / Ulysses exchange would move the wrong shards),
    and — the coverage half — any parallel op whose collective kind
    ``estimate_collective_bytes`` has no model for: unknown kinds are a
    typed WARNING diagnostic instead of a silent skip, so the
    ``ff_pcg_collective_bytes`` export can never silently under-report.
"""
from __future__ import annotations

from typing import Dict, Optional

from ..ff_types import OperatorType
from .diagnostics import AnalysisReport, Severity

_COLLECTIVE_OF = {
    OperatorType.OP_REPARTITION: "scatter",
    OperatorType.OP_COMBINE: "all-gather",
    OperatorType.OP_REPLICATE: "broadcast",
    OperatorType.OP_REDUCTION: "all-reduce",
    # exported under ff_pcg_collective_bytes{kind="all_to_all"} — the
    # expert-dispatch / sequence<->head exchange (ROADMAP item 5)
    OperatorType.OP_ALL_TO_ALL: "all_to_all",
    # FSDP/ZeRO weight sharding implies a PAIR per step: all-gather the
    # sharded params on use (fwd + bwd) and reduce-scatter the weight
    # grads (parallel/weight_sharding.py). estimate_collective_bytes
    # reports the two legs separately under the `all_gather` /
    # `reduce_scatter` kinds; the ordering lint treats the op as one
    # collective participant.
    OperatorType.OP_WEIGHT_SHARD: "all-gather/reduce-scatter",
}


def _view_of(op, views: Dict) -> Optional[object]:
    if views:
        v = views.get(op.guid)
        if v is not None:
            return v
    return op.machine_view


def estimate_collective_bytes(graph, views: Optional[Dict] = None,
                              report: Optional[AnalysisReport] = None
                              ) -> "list[dict]":
    """Static per-op collective payload estimate for a placed strategy.

    For each parallel op, the wire bytes its implied collective moves
    per step under the standard ring algorithms (all-reduce 2(p-1)/p of
    the buffer, all-gather/scatter/broadcast (p-1)/p, reduce-scatter
    (p-1)/p, all-to-all (p-1)/p of the buffer exchanged pairwise), where
    p is the participant count (the view's parts, falling back to the
    tensor's parallel degree; an AllToAll uses its declared exchange
    degree). A WeightShard (FSDP) op contributes TWO records over its
    target's full weight bytes: kind ``all_gather`` (the params are
    gathered on use in the forward AND the backward, so 2x(p-1)/p) and
    kind ``reduce_scatter`` (the weight-grad half of the replicated
    strategy's all-reduce). Feeds the telemetry gauge
    ``ff_pcg_collective_bytes`` so a strategy's communication footprint
    is visible without running it.

    report: optional AnalysisReport that receives an FFA505 WARNING for
    every parallel op whose kind has no bytes model here — unknown
    kinds must never silently vanish from the export (they used to)."""
    from ..parallel.weight_sharding import shard_target_weight_bytes

    out = []
    for op in graph.topo_order():
        kind = _COLLECTIVE_OF.get(op.op_type)
        if kind is None:
            if op.is_parallel_op and report is not None:
                report.add(
                    Severity.WARNING, "FFA505",
                    f"parallel op {op.op_type.name} has no collective-"
                    "bytes model — its wire traffic is missing from the "
                    "ff_pcg_collective_bytes export and from every lint "
                    "that keys off it", op=op,
                    fix_hint="teach analysis/collectives._COLLECTIVE_OF "
                             "+ estimate_collective_bytes the kind",
                )
            continue
        if op.op_type == OperatorType.OP_WEIGHT_SHARD:
            p = max(1, op.params.shard_degree)
            wfull = shard_target_weight_bytes(op)
            ring = (p - 1) / p if p > 1 else 0.0
            out.append({"op": op.name, "guid": op.guid,
                        "kind": "all_gather",
                        "bytes": int(2 * wfull * ring), "parts": p})
            out.append({"op": op.name, "guid": op.guid,
                        "kind": "reduce_scatter",
                        "bytes": int(wfull * ring), "parts": p})
            continue
        t = op.inputs[0] if op.inputs else (
            op.outputs[0] if op.outputs else None
        )
        if t is None:
            continue
        # wire traffic moves the tensor at its COMPUTE width: a bf16-
        # annotated activation crosses the fabric at 2 bytes/elt even
        # though its declared storage dtype is fp32 (pre-annotation the
        # two coincide, so fp32 graphs price unchanged)
        full = t.get_volume() * t.effective_itemsize()
        v = _view_of(op, views or {})
        if op.op_type == OperatorType.OP_ALL_TO_ALL:
            # the exchange degree is declared on the op; a view may
            # cover more devices than actually trade shards
            p = max(1, op.params.degree)
        else:
            p = max(1, v.num_parts()) if v is not None else \
                max(1, t.get_total_degree())
        if p <= 1:
            wire = 0
        elif kind == "all-reduce":
            wire = int(full * 2 * (p - 1) / p)
        else:
            # one pass of the buffer over the group: all-gather/scatter/
            # broadcast rings and the pairwise all-to-all exchange all
            # move (p-1)/p of the full payload per device per step
            wire = int(full * (p - 1) / p)
        out.append({"op": op.name, "guid": op.guid, "kind": kind,
                    "bytes": wire, "parts": p})
    return out


def overlappable_grad_syncs(graph) -> set:
    """Guids of ops whose implicit weight-gradient collective is
    statically PROVABLY independent of the backward critical path — the
    set the overlap discount (search/cost_model.py) and the overlapped
    simulator schedule (search/mcmc.simulate_runtime) are allowed to
    hide behind backward compute.

    The proof is structural: in this IR a compute op's weights are read
    only by that op, and the weight gradient the sync reduces is
    consumed only by the optimizer update — no other op's backward can
    observe it, so the collective commutes with every backward task
    scheduled after the producing op's. Excluded: ops governed by an
    OP_WEIGHT_SHARD node (FSDP already owns their reduce-scatter — its
    cost lives on the parallel op, not the sync term) and parallel ops
    (activation-path collectives are dependency-ordered by the graph)."""
    from ..parallel.weight_sharding import weight_shard_target

    covered = set()
    for op in graph.topo_order():
        if op.op_type == OperatorType.OP_WEIGHT_SHARD:
            t = weight_shard_target(op)
            if t is not None:
                covered.add(t.guid)
    return {
        op.guid
        for op in graph.topo_order()
        if op.weights and not op.is_parallel_op and op.guid not in covered
    }


def hideable_backward_compute(graph, views: Optional[Dict] = None,
                              cost_model=None) -> Dict[int, float]:
    """guid -> seconds of backward compute statically independent of
    that op's weight-grad collective: the backward of every
    topologically-EARLIER op runs after this op's backward produces its
    gradient, and none of it reads the synced gradient
    (overlappable_grad_syncs), so all of it can hide the collective.
    Ops whose sync is not overlappable map to 0.0."""
    from ..pcg.machine_view import MachineView

    ov = overlappable_grad_syncs(graph)
    v1 = MachineView(start_device_id=0, dim=(1,), stride=(1,))
    out: Dict[int, float] = {}
    prefix = 0.0
    for op in graph.topo_order():
        out[op.guid] = prefix if op.guid in ov else 0.0
        if cost_model is not None:
            v = _view_of(op, views or {}) or v1
            prefix += cost_model.measure_operator_cost(op, v).backward_time
    return out


def collective_diagnostics(graph, views: Optional[Dict] = None,
                           num_devices: Optional[int] = None
                           ) -> AnalysisReport:
    rep = AnalysisReport()
    views = views or {}
    ops = graph.topo_order()
    index = {op.guid: i for i, op in enumerate(ops)}

    # -- per-op checks ----------------------------------------------------
    for op in ops:
        v = _view_of(op, views)
        if v is not None and num_devices:
            ids = v.device_ids()
            if min(ids) < 0 or max(ids) >= num_devices:
                rep.add(
                    Severity.ERROR, "FFA205",
                    f"view {v!r} addresses device {max(ids)} of "
                    f"{num_devices} live device(s)", op=op,
                    fix_hint="re-search the strategy for the live "
                             "topology (recompile_for_topology)",
                )
        if v is not None and op.outputs:
            deg = op.outputs[0].get_total_degree()
            if deg > 1 and v.num_parts() not in (1, deg):
                rep.add(
                    Severity.WARNING, "FFA206",
                    f"view has {v.num_parts()} parts but output degree is "
                    f"{deg}; lowering demotes the extra shards to "
                    "replication", op=op,
                )
        if op.op_type == OperatorType.OP_REDUCTION:
            _check_reduction_axis(op, rep)
        elif op.op_type == OperatorType.OP_SOFTMAX:
            _check_softmax_axis(op, rep)
        elif op.op_type == OperatorType.OP_WEIGHT_SHARD:
            _check_weight_shard(op, rep)
        elif op.op_type == OperatorType.OP_ALL_TO_ALL:
            _check_all_to_all(op, rep)
        elif op.is_parallel_op and op.op_type not in _COLLECTIVE_OF:
            # coverage half of FFA505: a collective we cannot lower to a
            # kind is invisible to the bytes export AND to the ordering
            # lint below — say so instead of silently skipping
            rep.add(
                Severity.WARNING, "FFA505",
                f"parallel op {op.op_type.name} has no collective-bytes "
                "model — its wire traffic is missing from the "
                "ff_pcg_collective_bytes export and it is excluded from "
                "the cross-shard ordering check", op=op,
                fix_hint="teach analysis/collectives._COLLECTIVE_OF + "
                         "estimate_collective_bytes the kind",
            )

    # -- machine-view transitions -----------------------------------------
    for op in ops:
        vd = _view_of(op, views)
        if vd is None:
            continue
        for e in graph.in_edges(op):
            vs = _view_of(e.src, views)
            if vs is None:
                continue
            if set(vs.device_ids()) == set(vd.device_ids()):
                continue
            if e.src.is_parallel_op or op.is_parallel_op:
                continue  # the parallel op IS the reshard boundary
            t = e.src.outputs[e.src_idx]
            if t.get_total_degree() > 1 and vs.num_parts() != vd.num_parts():
                rep.add(
                    Severity.ERROR, "FFA201",
                    f"sharded tensor (degree {t.get_total_degree()}) moves "
                    f"from {e.src.name} on {vs!r} to {vd!r} with no "
                    "Repartition/Combine between them — the shard layouts "
                    "are incompatible", op=op,
                    fix_hint="insert a Repartition (or let the search do "
                             "it) at the view boundary",
                )
            else:
                rep.add(
                    Severity.WARNING, "FFA201",
                    f"machine-view change from {e.src.name} ({vs!r} -> "
                    f"{vd!r}) implies an inter-device transfer with no "
                    "explicit parallel op", op=op,
                )

    # -- cross-shard collective order -------------------------------------
    # Two collectives with a dependency path execute in a globally agreed
    # order. Independent ones with PARTIALLY overlapping device sets can
    # be issued in different orders by different shards — wrong-result /
    # deadlock territory. Equal or disjoint sets are always safe.
    reach = _reachability(graph, ops, index)
    colls = [
        (op, _view_of(op, views))
        for op in ops
        if op.op_type in _COLLECTIVE_OF and _view_of(op, views) is not None
    ]
    for i in range(len(colls)):
        a, va = colls[i]
        sa = set(va.device_ids())
        for j in range(i + 1, len(colls)):
            b, vb = colls[j]
            if reach[index[b.guid]] & (1 << index[a.guid]) or \
                    reach[index[a.guid]] & (1 << index[b.guid]):
                continue
            sb = set(vb.device_ids())
            inter = sa & sb
            if inter and sa != sb:
                rep.add(
                    Severity.ERROR, "FFA204",
                    f"unordered collectives: {_COLLECTIVE_OF[a.op_type]} on "
                    f"{a.name} (devices {sorted(sa)}) and "
                    f"{_COLLECTIVE_OF[b.op_type]} on {b.name} (devices "
                    f"{sorted(sb)}) share devices {sorted(inter)} but "
                    "neither depends on the other — shards may issue them "
                    "in different orders (deadlock / cross-shard mismatch)",
                    op=b,
                    fix_hint="place both on the same device set or add a "
                             "dependency between them",
                )
    return rep


def _reachability(graph, ops, index):
    """reach[i] = bitmask of ancestor op indices of ops[i] (ops in topo
    order, so every producer precedes its consumers)."""
    prod = graph.producers()
    reach = [0] * len(ops)
    for i, op in enumerate(ops):
        m = 0
        for t in op.inputs:
            p = prod.get(t.guid)
            if p is not None:
                j = index[p[0].guid]
                m |= reach[j] | (1 << j)
        reach[i] = m
    return reach


def _check_reduction_axis(op, rep: AnalysisReport) -> None:
    if not op.inputs:
        return
    in_t = op.inputs[0]
    rdim = op.params.reduction_dim
    replica_idxs = [i for i, d in enumerate(in_t.dims) if d.is_replica_dim]
    if not replica_idxs:
        rep.add(
            Severity.ERROR, "FFA202",
            f"Reduction over dim {rdim} of {in_t.get_shape()!r}, but the "
            "input carries no partial (replica) dim — there is nothing to "
            "sum, or the partial state was lost upstream", op=op,
        )
        return
    if rdim not in replica_idxs:
        rep.add(
            Severity.ERROR, "FFA202",
            f"Reduction axis {rdim} does not point at the partial replica "
            f"dim (at index {replica_idxs[0]}) of {in_t.get_shape()!r} — "
            "the sum would collapse real data and keep the partials",
            op=op,
            fix_hint=f"set reduction_dim={replica_idxs[0]}",
        )
        return
    deg = in_t.dims[rdim].degree
    if op.params.reduction_degree != deg:
        rep.add(
            Severity.ERROR, "FFA202",
            f"reduction_degree {op.params.reduction_degree} != the partial "
            f"dim's degree {deg}", op=op,
        )


def _check_weight_shard(op, rep: AnalysisReport) -> None:
    """FFA207: a WeightShard op's implied all-gather/reduce-scatter pair
    must have real sharded weights behind it (parallel/weight_sharding.py):
    the target (the op producing its input) must carry weights, and every
    sharded weight dim's degree must equal the declared shard degree —
    a mismatched degree means the gathered bytes and the stored shards
    disagree (wrong-result-on-device territory, not a style issue)."""
    from ..parallel.weight_sharding import weight_shard_target

    deg = op.params.shard_degree
    if deg < 2:
        rep.add(
            Severity.ERROR, "FFA207",
            f"WeightShard with shard_degree {deg}: nothing to shard "
            "(degree must be >= 2)", op=op,
        )
        return
    target = weight_shard_target(op)
    if target is None:
        rep.add(
            Severity.ERROR, "FFA207",
            "WeightShard's input is not produced by a weight-carrying op — "
            "there are no parameters to shard, gather, or reduce-scatter",
            op=op,
            fix_hint="insert the WeightShard node directly after the op "
                     "whose weights it shards (insert_weight_shard)",
        )
        return
    any_sharded = False
    for wi, w in enumerate(target.weights):
        for di, d in enumerate(w.dims):
            if d.degree <= 1 or d.is_replica_dim:
                continue
            any_sharded = True
            if d.degree != deg:
                rep.add(
                    Severity.ERROR, "FFA207",
                    f"target {target.name} weight {wi} dim {di} is sharded "
                    f"{d.degree}-way but the WeightShard declares degree "
                    f"{deg} — the all-gather would reassemble the wrong "
                    "number of shards", op=op,
                    fix_hint="make the weight-dim degrees match "
                             "shard_degree (shard_op_weights does)",
                )
    if not any_sharded:
        rep.add(
            Severity.ERROR, "FFA207",
            f"WeightShard declares degree {deg} but no weight dim of "
            f"target {target.name} is sharded — the node is inert and the "
            "memory accounting would be wrong", op=op,
            fix_hint="shard the target's weights (shard_op_weights) or "
                     "drop the node (fsdp_unshard_weights)",
        )


def _check_all_to_all(op, rep: AnalysisReport) -> None:
    """FFA505: the AllToAll exchange (sequence<->head resharding, MoE
    expert dispatch) must agree with its input sharding: the dim being
    gathered must actually be sharded `degree`-ways, and the dim being
    scattered must divide by `degree` — a mismatch moves the wrong
    shards between peers (wrong numbers, not just wrong cost)."""
    if not op.inputs:
        return
    in_t = op.inputs[0]
    p = op.params
    ndim = len(in_t.dims)
    if not (0 <= p.scatter_dim < ndim and 0 <= p.gather_dim < ndim):
        rep.add(
            Severity.ERROR, "FFA505",
            f"all-to-all dims (scatter={p.scatter_dim}, "
            f"gather={p.gather_dim}) out of range for rank-{ndim} input "
            f"{in_t.get_shape()!r}", op=op,
        )
        return
    if p.degree < 2:
        rep.add(
            Severity.ERROR, "FFA505",
            f"all-to-all with degree {p.degree}: nothing to exchange "
            "(degree must be >= 2)", op=op,
        )
        return
    g = in_t.dims[p.gather_dim]
    if g.degree != p.degree:
        rep.add(
            Severity.ERROR, "FFA505",
            f"all-to-all gathers dim {p.gather_dim}, which is sharded "
            f"{g.degree}-way, but declares exchange degree {p.degree} — "
            "each peer would contribute the wrong shard count", op=op,
            fix_hint=f"set degree={g.degree} (the gather dim's actual "
                     "sharding) or reshard the input first",
        )
    s = in_t.dims[p.scatter_dim]
    if s.size % p.degree != 0:
        rep.add(
            Severity.ERROR, "FFA505",
            f"all-to-all scatters dim {p.scatter_dim} (size {s.size}) "
            f"{p.degree}-ways, which does not divide evenly", op=op,
        )


def _check_softmax_axis(op, rep: AnalysisReport) -> None:
    if not op.inputs:
        return
    in_t = op.inputs[0]
    ndim = len(in_t.dims)
    if ndim == 0:
        return
    axis = op.params.dim % ndim if op.params.dim is not None else ndim - 1
    d = in_t.dims[axis]
    if d.degree > 1:
        rep.add(
            Severity.ERROR, "FFA203",
            f"softmax normalizes over dim {axis}, which is partitioned "
            f"{d.degree}-way — each shard normalizes over 1/{d.degree} of "
            "the axis and produces wrong probabilities with no collective "
            "to stitch them", op=op,
            fix_hint="softmax over an unsharded axis (usually the class "
                     "axis, dim=-1), or combine the axis first",
        )
