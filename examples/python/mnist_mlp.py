"""MNIST MLP via the Keras frontend (reference:
examples/python/keras/seq_mnist_mlp.py; accuracy gate like
examples/python/native/accuracy.py ModelAccuracy.MNIST_MLP).
"""
import sys

import numpy as np

sys.path.insert(0, ".")

from flexflow_tpu.frontends import keras


def load_mnist_like(n=4096, seed=0):
    """Synthetic MNIST-shaped separable data (no dataset download in this
    environment)."""
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.int32)[:, None]
    return x, y


def main():
    x, y = load_mnist_like()
    model = keras.Sequential()
    model.add(keras.Input(shape=(784,)))
    model.add(keras.Dense(512, activation="relu"))
    model.add(keras.Dense(512, activation="relu"))
    model.add(keras.Dense(10, activation="softmax"))
    model.compile(
        optimizer=keras.SGD(learning_rate=0.05),
        loss="sparse_categorical_crossentropy",
        metrics=["accuracy"],
        batch_size=64,
    )
    model.fit(
        x, y, batch_size=64, epochs=5,
        callbacks=[keras.callbacks.EpochVerifyMetrics(60.0)],
    )


if __name__ == "__main__":
    main()
