"""CIFAR-10 CNN imported from PyTorch (reference:
examples/python/pytorch/cifar10_cnn.py)."""
import torch.nn as nn

from flexflow.core import *  # noqa: F401,F403
from flexflow.keras.datasets import cifar10
from flexflow.torch.model import PyTorchModel

from _example_args import example_args


class CNN(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 32, 3, padding=1)
        self.conv2 = nn.Conv2d(32, 32, 3, padding=1)
        self.pool1 = nn.MaxPool2d(2, 2)
        self.conv3 = nn.Conv2d(32, 64, 3, padding=1)
        self.conv4 = nn.Conv2d(64, 64, 3, padding=1)
        self.pool2 = nn.MaxPool2d(2, 2)
        self.flat = nn.Flatten()
        self.linear1 = nn.Linear(64 * 8 * 8, 512)
        self.linear2 = nn.Linear(512, 10)
        self.relu = nn.ReLU()
        self.softmax = nn.Softmax(dim=-1)

    def forward(self, x):
        y = self.relu(self.conv1(x))
        y = self.pool1(self.relu(self.conv2(y)))
        y = self.relu(self.conv3(y))
        y = self.pool2(self.relu(self.conv4(y)))
        y = self.relu(self.linear1(self.flat(y)))
        return self.softmax(self.linear2(y))


def top_level_task(args):
    ffconfig = FFConfig()
    ffconfig.batch_size = args.batch_size
    ffmodel = FFModel(ffconfig)
    input_tensor = ffmodel.create_tensor(
        [args.batch_size, 3, 32, 32], DataType.DT_FLOAT)

    torch_model = PyTorchModel(CNN())
    output_tensors = torch_model.torch_to_ff(ffmodel, [input_tensor])

    ffmodel.optimizer = SGDOptimizer(ffmodel, 0.01)
    ffmodel.compile(loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                    metrics=[MetricsType.METRICS_ACCURACY])

    (x_train, y_train), _ = cifar10.load_data(n_train=args.num_samples)
    x_train = x_train.transpose(0, 3, 1, 2).astype("float32") / 255
    y_train = y_train.astype("int32").reshape(-1, 1)
    ffmodel.fit(x=x_train, y=y_train, epochs=args.epochs)


if __name__ == "__main__":
    print("cifar10 cnn (pytorch import)")
    top_level_task(example_args())
