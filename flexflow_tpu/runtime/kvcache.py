# fflint: disable-file=FFL201  — `python -m flexflow_tpu.runtime.kvcache`
# is an auditor CLI whose stdout JSON report IS the contract (CI parses
# it); the print sites live only in the _cli_* helpers at the bottom.
"""Paged KV-cache allocation with content-addressed prefix sharing.

Continuous batching (runtime/serving.py) admits requests into a running
decode batch at token granularity, so the scarce resource is no longer
"a batch slot" but KV-cache memory. This module is the accounting layer
that turns cache growth into an admission signal — the vLLM lesson
(PagedAttention, SOSP'23) applied at the allocator level — extended with
the SGLang/RadixAttention lesson: the system prompt shared by a fleet of
sessions should be materialized ONCE.

  * memory is carved into fixed-size **pages** of `page_size` token
    positions each;
  * every FULL page of prompt tokens is **content-addressed** by a
    rolling hash chain ``h_{i+1} = sha1(h_i || block_i)`` — the key
    commits to the whole prefix, not just the block, so two sequences
    share a page only when everything before it matches too;
  * ``reserve(seq_id, max_tokens, tokens=...)`` first walks
    ``match_prefix`` and attaches already-materialized shared pages with
    their refcounts bumped; only the UNSHARED remainder is charged
    against the admittable budget, which is what lets N sessions with a
    common prefix fit where one used to;
  * a write to a shared page triggers **copy-on-write**
    (``note_write``): allocate-private, rebind, decref — so shared
    pages are immutable by construction. In the serving integration
    only full prompt blocks are ever published and decode writes land
    strictly after the prompt, so steady-state COW traffic is zero and
    the COW path is the safety valve that keeps correctness local;
  * ``release`` **decrefs** instead of freeing: a page returns to the
    free list only when its last holder retires. Double release is a
    typed ``KVCacheAccountingError`` (counted in
    ``ff_kv_accounting_errors_total``), never a silent no-op — failover
    requeue must transfer ownership exactly once;
  * ``audit()`` proves the invariants after every chaos leg: every
    resident page's refcount equals its table bindings, no orphan or
    zero-ref resident pages, no sequence holds a freed page, and
    Σ headroom never exceeds the free list (the no-deadlock guarantee).
    ``python -m flexflow_tpu.runtime.kvcache audit`` runs the same
    checker over ``dump_state()`` JSON offline.

Reservations charge the worst case up front (prompt + max_new_tokens in
pages, minus attached shared pages), so an admitted request can never
deadlock mid-decode waiting for a page held by another admitted request;
``writable=True`` reservations charge the FULL worst case so every
potential copy-on-write is pre-budgeted too.

The physical decode caches today are dense per-slot arrays managed by
`executor.build_decode` (one `max_len`-wide strip per slot); the pool's
page tables map logical (sequence, position) ranges onto page ids so the
accounting — and the sharing — is exact at token granularity and the
layout can move to physically paged storage without touching the
admission logic.

CPU-testable fault sites (`FaultInjector`): ``kv_exhaustion`` makes any
reservation fail as if the pool were full; ``shared_page_corruption``
fails a chain's integrity check (the chain is quarantined and admission
degrades to unshared); ``release_race`` injects a racing second release
(typed double-release surfaces); ``cow_fault`` fails a copy-on-write
before any state mutates (pool stays audit-clean).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from .resilience import ResilienceError


class KVCacheExhaustedError(ResilienceError):
    """A KV-page reservation could not be satisfied: the pool is out of
    pages (or the ``kv_exhaustion`` fault site simulated it). Carries
    enough context for the admission controller to decide between
    backpressure (wait for running sequences to retire) and a shed
    (the request can NEVER fit)."""

    def __init__(self, msg: str, *, pages_needed: int = 0,
                 pages_free: int = 0, never_fits: bool = False):
        super().__init__(msg)
        self.pages_needed = pages_needed
        self.pages_free = pages_free
        self.never_fits = never_fits


class KVCacheAccountingError(ResilienceError):
    """A page-accounting invariant was violated: double release, a write
    without a reservation, copy-on-write without headroom, an injected
    ``cow_fault``/``release_race``, or an ``audit()`` failure. Raising
    typed — instead of silently absorbing — is the contract that makes
    failover refcount bugs debuggable; every raise is counted in
    ``ff_kv_accounting_errors_total{kind=...}``."""

    def __init__(self, msg: str, *, kind: str = "accounting",
                 seq_id: Optional[str] = None):
        super().__init__(msg)
        self.kind = kind
        self.seq_id = seq_id


class SharedPageCorruptionError(KVCacheAccountingError):
    """A content-addressed chain failed its integrity check (the
    ``shared_page_corruption`` fault site). The chain is quarantined —
    unpublished from the index so no future admission can attach it —
    before this is raised."""


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Sizing knobs for the page pool (docs/serving.md "KV-cache
    sizing"). `num_pages * page_size` is the total token-position budget
    across all in-flight sequences; `watermark` holds back a fraction of
    pages from admission so in-flight growth plus a small burst never
    hits the hard edge."""

    num_pages: int
    page_size: int = 16
    watermark: float = 0.0
    # dtype the pooled K/V blocks are materialized in, as a numpy dtype
    # string ("float32", "bfloat16", "int8", ...). None defers to the
    # executor's compute dtype (kv_page_bytes' historical behavior).
    # Quantized caches (int8) double the sessions a byte budget admits
    # relative to fp16/bf16 — see tests/test_precision.py.
    kv_dtype: Optional[str] = None

    def __post_init__(self):
        if self.num_pages <= 0:
            raise ValueError(f"num_pages must be positive: {self.num_pages}")
        if self.page_size <= 0:
            raise ValueError(f"page_size must be positive: {self.page_size}")
        if not 0.0 <= self.watermark < 1.0:
            raise ValueError(f"watermark must be in [0, 1): {self.watermark}")
        if self.kv_dtype is not None:
            import numpy as np

            try:
                np.dtype(self.kv_dtype)
            except TypeError as e:
                raise ValueError(
                    f"kv_dtype {self.kv_dtype!r} is not a numpy dtype "
                    f"name: {e}"
                ) from e
        if self.watermark > 0.0 and self.held_back_pages() >= self.num_pages:
            raise ValueError(
                f"watermark {self.watermark} holds back every page of a "
                f"{self.num_pages}-page pool — nothing is admittable")

    def held_back_pages(self) -> int:
        """Pages the watermark withholds from admission. Rounds UP (a
        positive watermark always holds back at least one page) so tiny
        CPU-test pools still exercise backpressure — `int(n * w)` used
        to floor to 0 below 1/w pages and silently disable the
        watermark."""
        if self.watermark <= 0.0:
            return 0
        return max(1, int(math.ceil(self.num_pages * self.watermark - 1e-9)))

    def pages_for(self, tokens: int) -> int:
        return max(1, -(-int(tokens) // self.page_size))


_HASH_SEED = b"ffkv/1"


def prefix_page_keys(tokens: Sequence[int], page_size: int) -> List[str]:
    """Content-address every FULL `page_size` block of `tokens` with a
    rolling hash chain: ``h_{i+1} = sha1(h_i || block_i)``, key =
    ``hex(h)[:16]``. Chaining means a key commits to the entire prefix
    up to and including its block, so an index hit at block i implies
    blocks 0..i all match — prefix matching is a plain walk, no trie
    needed. A partial tail block gets no key: it is private by
    construction."""
    keys: List[str] = []
    h = _HASH_SEED
    for b in range(len(tokens) // page_size):
        block = tokens[b * page_size:(b + 1) * page_size]
        payload = h + b"".join(
            int(t).to_bytes(8, "little", signed=True) for t in block)
        h = hashlib.sha1(payload).digest()
        keys.append(h.hex()[:16])
    return keys


@dataclasses.dataclass(frozen=True)
class ReserveResult:
    """What `reserve()` admitted: `pages` newly charged against the
    budget, `shared_pages` attached from the content index with their
    refcounts bumped, covering the first `matched_tokens` positions."""

    pages: int
    shared_pages: int = 0
    matched_tokens: int = 0


class _Page:
    """Resident-page metadata: `refs` table bindings hold it; `key` is
    its content-index key when published (None while private)."""

    __slots__ = ("refs", "key")

    def __init__(self, refs: int = 1, key: Optional[str] = None):
        self.refs = refs
        self.key = key


@dataclasses.dataclass(frozen=True)
class AuditViolation:
    kind: str
    detail: str


@dataclasses.dataclass
class AuditReport:
    """Result of a pool invariant sweep; `ok` iff zero violations."""

    violations: List[AuditViolation]
    pages_resident: int
    pages_free: int
    bindings: int

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "pages_resident": self.pages_resident,
            "pages_free": self.pages_free,
            "bindings": self.bindings,
            "violations": [dataclasses.asdict(v) for v in self.violations],
        }


def _audit_structures(num_pages: int, free: List[int],
                      pages: Dict[int, Tuple[int, Optional[str]]],
                      tables: Dict[str, List[int]],
                      index: Dict[str, int],
                      headroom: Dict[str, int]) -> List[AuditViolation]:
    """The invariant checker proper, over plain structures so the live
    `PagePool.audit()` and the offline `audit_state()` CLI run the exact
    same sweep. Each violation kind names one way the pool can rot:
    leaks (page_count_mismatch/orphan_page), double frees
    (freed_page_resident/freed_page_bound), refcount corruption
    (refcount_mismatch/zero_ref_resident), index rot
    (index_dangling/index_mismatch/unindexed_published) and admission
    deadlock (headroom_exceeds_free)."""
    v: List[Tuple[str, str]] = []
    free_set = set(free)
    if len(free_set) != len(free):
        v.append(("free_list_duplicate",
                  f"{len(free) - len(free_set)} duplicate id(s) on the "
                  f"free list"))
    for pid in sorted(free_set):
        if not 0 <= pid < num_pages:
            v.append(("free_list_out_of_range", f"page {pid}"))
    overlap = free_set & set(pages)
    if overlap:
        v.append(("freed_page_resident",
                  f"page(s) {sorted(overlap)} both free and resident"))
    if len(free) + len(pages) != num_pages:
        v.append(("page_count_mismatch",
                  f"{len(free)} free + {len(pages)} resident != "
                  f"{num_pages} total (leak or double-free)"))
    bindings: Dict[int, int] = {}
    for seq, table in sorted(tables.items()):
        seen = set()
        for pid in table:
            bindings[pid] = bindings.get(pid, 0) + 1
            if pid in seen:
                v.append(("duplicate_binding",
                          f"sequence {seq!r} binds page {pid} twice"))
            seen.add(pid)
            if pid in free_set:
                v.append(("freed_page_bound",
                          f"sequence {seq!r} holds freed page {pid}"))
            elif pid not in pages:
                v.append(("unknown_page_bound",
                          f"sequence {seq!r} holds unknown page {pid}"))
    for pid in sorted(pages):
        refs, key = pages[pid]
        n = bindings.get(pid, 0)
        if refs != n:
            v.append(("refcount_mismatch",
                      f"page {pid}: refs={refs} but {n} binding(s)"))
        if refs <= 0:
            v.append(("zero_ref_resident",
                      f"page {pid} resident with refs={refs}"))
        elif n == 0:
            v.append(("orphan_page",
                      f"page {pid} resident with refs={refs} but no "
                      f"binding"))
        if key is not None and index.get(key) != pid:
            v.append(("unindexed_published",
                      f"page {pid} published as {key!r} but the index "
                      f"maps that key to {index.get(key)}"))
    for key in sorted(index):
        pid = index[key]
        if pid not in pages:
            v.append(("index_dangling",
                      f"key {key!r} -> non-resident page {pid}"))
        elif pages[pid][1] != key:
            v.append(("index_mismatch",
                      f"key {key!r} -> page {pid} which is published as "
                      f"{pages[pid][1]!r}"))
    total_headroom = sum(headroom.values())
    if total_headroom > len(free):
        v.append(("headroom_exceeds_free",
                  f"{total_headroom} page(s) of reservation headroom "
                  f"exceed {len(free)} free — an admitted sequence could "
                  f"deadlock mid-decode"))
    for seq in sorted(headroom):
        if headroom[seq] < 0:
            v.append(("negative_headroom",
                      f"sequence {seq!r}: {headroom[seq]}"))
        if seq not in tables:
            v.append(("charge_without_table",
                      f"sequence {seq!r} charged but has no page table"))
    for seq in sorted(tables):
        if seq not in headroom:
            v.append(("table_without_charge",
                      f"sequence {seq!r} has a page table but no charge"))
    return [AuditViolation(kind, detail) for kind, detail in v]


class PagePool:
    """Thread-safe refcounted page allocator with per-sequence page
    tables and a content-addressed shared-prefix index.

    Lifecycle per sequence: ``reserve(seq_id, max_tokens, tokens=...)``
    at admission (the hard budget check + prefix attach),
    ``touch(seq_id, tokens)`` as the sequence grows (materializes
    private pages out of the reservation headroom),
    ``note_write(seq_id, pos)`` before a token write lands (no-op on
    private pages, copy-on-write on shared ones),
    ``publish(seq_id, tokens)`` once the prompt is materialized (makes
    its full blocks matchable), ``release(seq_id)`` at
    retirement/shed/failover (decref; pages free at zero). All are
    O(pages) and safe to call from the batcher, admission and failover
    threads concurrently."""

    def __init__(self, config: KVCacheConfig, *, fault_injector=None):
        self.config = config
        self.fault_injector = fault_injector
        self._lock = threading.Lock()
        self._free: List[int] = list(range(config.num_pages))[::-1]
        self._pages: Dict[int, _Page] = {}
        self._tables: Dict[str, List[int]] = {}
        self._charged: Dict[str, int] = {}
        self._headroom: Dict[str, int] = {}
        self._limit: Dict[str, int] = {}
        self._index: Dict[str, int] = {}
        self.stats = {"reservations": 0, "exhaustions": 0, "released": 0,
                      "prefix_hits": 0, "shared_attached": 0,
                      "published": 0, "cow": 0, "unpublished_on_write": 0,
                      "accounting_errors": 0, "corruptions": 0,
                      "audits": 0}

    # -- introspection ---------------------------------------------------
    @property
    def num_pages(self) -> int:
        return self.config.num_pages

    @property
    def pages_free(self) -> int:
        """Physical pages not spoken for: on the free list and not
        promised to any admitted sequence's remaining headroom. Equals
        `num_pages - pages_reserved` when nothing is shared; with
        sharing it is the true admittable supply (shared-but-resident
        pages whose original charge retired are correctly excluded)."""
        with self._lock:
            return len(self._free) - sum(self._headroom.values())

    @property
    def pages_reserved(self) -> int:
        """Pages charged to admitted sequences (sharing discounts the
        charge, so this can be less than the sum of worst cases)."""
        with self._lock:
            return sum(self._charged.values())

    @property
    def pages_in_use(self) -> int:
        """Table BINDINGS across sequences — what `ff_kv_pages_in_use`
        reports. A page shared by k sequences counts k times here and
        once in `pages_resident`; the auditor proves the two views agree
        with the refcounts."""
        with self._lock:
            return sum(len(t) for t in self._tables.values())

    @property
    def pages_resident(self) -> int:
        """Physically materialized pages (each counted once)."""
        with self._lock:
            return len(self._pages)

    @property
    def pages_shared(self) -> int:
        """Resident pages bound by more than one sequence — the
        `ff_kv_pages_shared` gauge, i.e. the dedup win in pages."""
        with self._lock:
            return sum(1 for m in self._pages.values() if m.refs > 1)

    def snapshot(self) -> Dict[str, int]:
        """Consistent one-lock view of the pool's occupancy — the
        request flight recorder attaches this to kv_reserve/kv_release
        trace events, where separately-locked property reads could tear
        against a concurrent admission."""
        with self._lock:
            used = sum(len(t) for t in self._tables.values())
            reserved = sum(self._charged.values())
            headroom = sum(self._headroom.values())
            shared = sum(1 for m in self._pages.values() if m.refs > 1)
            return {"pages_in_use": used, "pages_reserved": reserved,
                    "pages_free": len(self._free) - headroom,
                    "pages_resident": len(self._pages),
                    "pages_shared": shared}

    def page_table(self, seq_id: str) -> tuple:
        with self._lock:
            return tuple(self._tables.get(seq_id, ()))

    def holds(self, seq_id: str) -> bool:
        with self._lock:
            return seq_id in self._charged

    def page_refs(self, page_id: int) -> int:
        """Refcount of a resident page (0 when free/unknown)."""
        with self._lock:
            meta = self._pages.get(page_id)
            return meta.refs if meta is not None else 0

    def _admittable_locked(self) -> int:
        # held-back watermark pages never count toward admission; the
        # supply is physical (free list minus outstanding headroom), so
        # shared residency is priced correctly
        return (len(self._free) - sum(self._headroom.values())
                - self.config.held_back_pages())

    def can_reserve(self, max_tokens: int,
                    tokens: Optional[Sequence[int]] = None) -> bool:
        need = self.config.pages_for(max_tokens)
        with self._lock:
            if tokens is not None:
                keys = prefix_page_keys(tokens, self.config.page_size)
                need -= len(self._match_locked(keys, need))
            return need <= self._admittable_locked()

    def never_fits(self, max_tokens: int) -> bool:
        """True when the demand exceeds the WHOLE pool — waiting for
        retirements can't help, so the request must be shed."""
        return self.config.pages_for(max_tokens) > (
            self.config.num_pages - self.config.held_back_pages()
        )

    # -- prefix sharing --------------------------------------------------
    def _match_locked(self, keys: List[str], limit: int) -> List[int]:
        pages: List[int] = []
        for key in keys[:limit]:
            pid = self._index.get(key)
            if pid is None:
                break  # chain hash: a miss here means no later block hits
            pages.append(pid)
        return pages

    def _quarantine_chain_locked(self, keys: List[str]) -> int:
        n = 0
        for key in keys:
            pid = self._index.pop(key, None)
            if pid is not None:
                meta = self._pages.get(pid)
                if meta is not None and meta.key == key:
                    meta.key = None
                n += 1
        return n

    def match_prefix(self, tokens: Sequence[int]) -> Tuple[int, tuple]:
        """Longest already-materialized shared prefix of `tokens`:
        ``(matched_tokens, page_ids)``. Read-only — refcounts are bumped
        only by `reserve(..., tokens=...)`, which re-walks the index
        under its own lock (this view can go stale the moment the lock
        drops). Fault site ``shared_page_corruption`` fails the chain's
        integrity check here: the chain is quarantined and the typed
        error raised."""
        keys = prefix_page_keys(tokens, self.config.page_size)
        plan = None
        if self.fault_injector is not None and keys:
            plan = self.fault_injector.fire("shared_page_corruption")
        with self._lock:
            if plan is not None:
                n = self._quarantine_chain_locked(keys)
                self.stats["corruptions"] += 1
                self.stats["accounting_errors"] += 1
                self._note_typed("shared_page_corruption")
            else:
                pages = self._match_locked(keys, len(keys))
        if plan is not None:
            raise SharedPageCorruptionError(
                f"shared-prefix chain failed integrity check (fault "
                f"injection): {n} key(s) quarantined",
                kind="shared_page_corruption")
        return len(pages) * self.config.page_size, tuple(pages)

    def publish(self, seq_id: str, tokens: Sequence[int]) -> int:
        """Make `seq_id`'s materialized FULL blocks of `tokens`
        content-addressable so later admissions can attach them. Returns
        blocks newly published. Publishing is what freezes a page: any
        later write to it goes through copy-on-write."""
        keys = prefix_page_keys(tokens, self.config.page_size)
        published = 0
        with self._lock:
            table = self._tables.get(seq_id)
            if table is None:
                self.stats["accounting_errors"] += 1
                self._note_typed("publish_without_reservation")
                raise KVCacheAccountingError(
                    f"publish for {seq_id!r} which holds no reservation",
                    kind="publish_without_reservation", seq_id=seq_id)
            for i, key in enumerate(keys):
                if i >= len(table):
                    break  # block not materialized yet
                if key in self._index:
                    continue  # chain already published (possibly by us)
                meta = self._pages[table[i]]
                if meta.key is not None:
                    continue  # already addressed under different content
                meta.key = key
                self._index[key] = table[i]
                published += 1
            if published:
                self.stats["published"] += published
        if published:
            self._export()
        return published

    # -- lifecycle -------------------------------------------------------
    def reserve(self, seq_id: str, max_tokens: int, *,
                tokens: Optional[Sequence[int]] = None,
                writable: bool = False) -> ReserveResult:
        """Admit `seq_id` with a worst case of `max_tokens` positions.

        With `tokens` (the prompt) given, already-published prefix pages
        are attached refcounted and DISCOUNTED from the charge — the
        admittable budget only pays for the unshared remainder. With
        `writable=True` the FULL worst case is charged even when pages
        are attached, so every potential copy-on-write is pre-budgeted
        (use this when the caller intends to write inside the shared
        prefix). Raises KVCacheExhaustedError (never silently
        over-commits) when the admittable budget can't cover the charge;
        `never_fits` on the error distinguishes "wait" from "shed"."""
        need = self.config.pages_for(max_tokens)
        if self.fault_injector is not None:
            plan = self.fault_injector.fire("kv_exhaustion")
            if plan is not None:
                self.stats["exhaustions"] += 1
                raise KVCacheExhaustedError(
                    f"kv page pool exhausted (fault injection): "
                    f"{need} page(s) for {seq_id}",
                    pages_needed=need, pages_free=0,
                    never_fits=bool(plan.get("never_fits", False)),
                )
        keys: List[str] = []
        if tokens is not None:
            keys = prefix_page_keys(tokens, self.config.page_size)
        corrupt = None
        if self.fault_injector is not None and keys:
            corrupt = self.fault_injector.fire("shared_page_corruption")
        with self._lock:
            if seq_id in self._charged:
                raise ValueError(f"sequence {seq_id!r} already reserved")
            shared: List[int] = []
            if corrupt is not None:
                # integrity check failed: quarantine the chain and admit
                # unshared — a corrupt shared page must never be attached
                self._quarantine_chain_locked(keys)
                self.stats["corruptions"] += 1
                self.stats["accounting_errors"] += 1
                self._note_typed("shared_page_corruption")
            elif keys:
                shared = self._match_locked(keys, need)
            charge = need if writable else need - len(shared)
            avail = self._admittable_locked()
            if charge > avail:
                self.stats["exhaustions"] += 1
                raise KVCacheExhaustedError(
                    f"kv page pool exhausted: {charge} page(s) needed "
                    f"for {seq_id}, {avail} admittable of "
                    f"{self.config.num_pages}",
                    pages_needed=charge, pages_free=max(0, avail),
                    never_fits=charge > (self.config.num_pages
                                         - self.config.held_back_pages()),
                )
            for pid in shared:
                self._pages[pid].refs += 1
            self._tables[seq_id] = list(shared)
            self._charged[seq_id] = charge
            self._headroom[seq_id] = charge
            self._limit[seq_id] = need
            self.stats["reservations"] += 1
            if shared:
                self.stats["prefix_hits"] += 1
                self.stats["shared_attached"] += len(shared)
                self._note_prefix_hit(len(shared))
        self._export()
        return ReserveResult(
            pages=charge, shared_pages=len(shared),
            matched_tokens=len(shared) * self.config.page_size)

    def touch(self, seq_id: str, tokens: int) -> List[int]:
        """Materialize private pages so positions [0, tokens) are
        backed; returns the newly allocated page ids (empty when already
        covered, including by attached shared pages). Growth beyond the
        reservation is a caller bug and raises — the admission-time
        worst case is the contract that makes mid-decode deadlock
        impossible."""
        with self._lock:
            if seq_id not in self._charged:
                raise KeyError(f"sequence {seq_id!r} holds no reservation")
            table = self._tables[seq_id]
            need = self.config.pages_for(tokens)
            if need > self._limit[seq_id]:
                raise ValueError(
                    f"sequence {seq_id!r} grew to {need} page(s), beyond "
                    f"its reservation of {self._limit[seq_id]}"
                )
            new = []
            while len(table) < need:
                if self._headroom[seq_id] <= 0:
                    self.stats["accounting_errors"] += 1
                    self._note_typed("headroom_underrun")
                    raise KVCacheAccountingError(
                        f"sequence {seq_id!r} materialization exceeds its "
                        f"charged headroom",
                        kind="headroom_underrun", seq_id=seq_id)
                # free list can't underrun: every pop is covered by
                # charged headroom, and Σ headroom <= len(free) always
                pid = self._free.pop()
                self._pages[pid] = _Page()
                self._headroom[seq_id] -= 1
                table.append(pid)
                new.append(pid)
        if new:
            self._export()
        return new

    def note_write(self, seq_id: str, pos: int) -> Optional[int]:
        """Record that a token write is landing at position `pos`.
        Private page: no-op (returns None). Published page with a single
        holder: retracted from the content index and written in place.
        Shared page (refs > 1): COPY-ON-WRITE — a private page is
        allocated out of the reservation headroom, rebound in this
        sequence's table, and the shared page decref'd; returns the new
        page id. Fault site ``cow_fault`` fails the copy BEFORE any
        state mutates, so the pool stays audit-clean for the failover
        path."""
        block = int(pos) // self.config.page_size
        cow_pid = None
        with self._lock:
            table = self._tables.get(seq_id)
            if table is None:
                self.stats["accounting_errors"] += 1
                self._note_typed("write_without_reservation")
                raise KVCacheAccountingError(
                    f"write at position {pos} for {seq_id!r} which holds "
                    f"no reservation",
                    kind="write_without_reservation", seq_id=seq_id)
            if block >= len(table):
                return None  # not materialized yet; touch() allocates private
            pid = table[block]
            meta = self._pages[pid]
            if meta.refs == 1 and meta.key is None:
                return None  # already private
            if meta.refs == 1:
                # sole holder writing a published page: unpublish and
                # write in place — no copy needed
                self._index.pop(meta.key, None)
                meta.key = None
                self.stats["unpublished_on_write"] += 1
                return None
            plan = None
            if self.fault_injector is not None:
                plan = self.fault_injector.fire("cow_fault")
            if plan is not None:
                self.stats["accounting_errors"] += 1
                self._note_typed("cow_fault")
                raise KVCacheAccountingError(
                    f"copy-on-write fault injected for {seq_id!r} block "
                    f"{block}", kind="cow_fault", seq_id=seq_id)
            if self._headroom[seq_id] <= 0:
                self.stats["accounting_errors"] += 1
                self._note_typed("cow_without_headroom")
                raise KVCacheAccountingError(
                    f"copy-on-write for {seq_id!r} block {block} needs a "
                    f"page but the reservation has no headroom (reserve "
                    f"with writable=True to pre-budget shared-prefix "
                    f"writes)", kind="cow_without_headroom", seq_id=seq_id)
            cow_pid = self._free.pop()
            self._headroom[seq_id] -= 1
            self._pages[cow_pid] = _Page()
            table[block] = cow_pid
            meta.refs -= 1  # still >= 1: the other holders keep it
            self.stats["cow"] += 1
        from .. import obs
        obs.count("ff_kv_cow_total",
                  help="KV pages privatized by copy-on-write")
        self._export()
        return cow_pid

    def release(self, seq_id: str, *, missing_ok: bool = False) -> int:
        """Decref `seq_id`'s pages and return its reservation to the
        pool; a page goes back on the free list only at refcount zero.
        Returns pages physically freed. Releasing an unknown or
        already-released sequence raises a typed KVCacheAccountingError
        (counted in ``ff_kv_accounting_errors_total``) — failover and
        retirement must transfer ownership exactly once. Call sites that
        legitimately race a release they cannot observe (e.g. scale-down
        sweeping slots a dying serve loop already freed) pass
        ``missing_ok=True``."""
        with self._lock:
            if seq_id not in self._charged:
                if missing_ok:
                    return 0
                self.stats["accounting_errors"] += 1
                self._note_typed("double_release")
                raise KVCacheAccountingError(
                    f"release of unknown or already-released sequence "
                    f"{seq_id!r} — failover must transfer page ownership "
                    f"exactly once", kind="double_release", seq_id=seq_id)
            table = self._tables.pop(seq_id)
            freed = 0
            for pid in table:
                meta = self._pages[pid]
                meta.refs -= 1
                if meta.refs == 0:
                    if meta.key is not None:
                        self._index.pop(meta.key, None)
                    del self._pages[pid]
                    self._free.append(pid)
                    freed += 1
            del self._charged[seq_id]
            del self._headroom[seq_id]
            del self._limit[seq_id]
            self.stats["released"] += 1
        self._export()
        if self.fault_injector is not None:
            plan = self.fault_injector.fire("release_race")
            if plan is not None:
                # the injected race: a second releaser loses and must
                # surface as a typed accounting error, not corruption
                return self.release(seq_id)
        return freed

    # -- auditing --------------------------------------------------------
    def audit(self, *, raise_on_violation: bool = False) -> AuditReport:
        """Prove the pool's invariants (see `_audit_structures`). Run
        after every chaos leg; any violation bumps
        ``ff_kv_audit_violations_total`` and emits a structured event."""
        with self._lock:
            free = list(self._free)
            pages = {pid: (m.refs, m.key) for pid, m in self._pages.items()}
            tables = {s: list(t) for s, t in self._tables.items()}
            index = dict(self._index)
            headroom = dict(self._headroom)
            self.stats["audits"] += 1
        violations = _audit_structures(self.config.num_pages, free, pages,
                                       tables, index, headroom)
        report = AuditReport(
            violations=violations, pages_resident=len(pages),
            pages_free=len(free),
            bindings=sum(len(t) for t in tables.values()))
        if violations:
            from .. import obs
            obs.count("ff_kv_audit_violations_total", n=len(violations),
                      help="KV pool audit invariant violations")
            obs.event("kv_audit_violation", cat="serving",
                      total=len(violations), first=violations[0].kind)
            if raise_on_violation:
                raise KVCacheAccountingError(
                    f"pool audit failed: {len(violations)} violation(s); "
                    f"first: {violations[0].kind}: {violations[0].detail}",
                    kind="audit")
        return report

    def to_state(self) -> dict:
        """One-lock serializable snapshot of the full allocator state —
        `audit_state()` / the CLI run the same invariant sweep offline
        (post-mortem on a failed chaos leg, cross-process checks)."""
        with self._lock:
            return {
                "version": 1,
                "num_pages": self.config.num_pages,
                "page_size": self.config.page_size,
                "watermark": self.config.watermark,
                "free": list(self._free),
                "pages": {str(pid): {"refs": m.refs, "key": m.key}
                          for pid, m in self._pages.items()},
                "tables": {s: list(t) for s, t in self._tables.items()},
                "charged": dict(self._charged),
                "headroom": dict(self._headroom),
                "limit": dict(self._limit),
                "index": dict(self._index),
                "stats": dict(self.stats),
            }

    def dump_state(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_state(), f, indent=2, sort_keys=True)

    # -- metrics ---------------------------------------------------------
    def _note_typed(self, kind: str) -> None:
        from .. import obs
        obs.count("ff_kv_accounting_errors_total",
                  help="typed KV accounting errors (double release, COW "
                       "faults, corrupt shared chains)", kind=kind)

    def _note_prefix_hit(self, pages: int) -> None:
        from .. import obs
        obs.count("ff_kv_prefix_hits_total",
                  help="admissions that attached a shared KV prefix")
        obs.count("ff_kv_prefix_pages_attached_total", n=pages,
                  help="shared KV pages attached at admission")

    def _export(self) -> None:
        from .. import obs

        snap = self.snapshot()
        obs.gauge_set("ff_kv_pages_in_use", snap["pages_in_use"],
                      help="materialized KV-cache page bindings across "
                           "sequences")
        obs.gauge_set("ff_kv_pages_reserved", snap["pages_reserved"],
                      help="KV-cache pages committed to admitted sequences")
        obs.gauge_set("ff_kv_pages_shared", snap["pages_shared"],
                      help="resident KV pages bound by more than one "
                           "sequence")


def audit_state(state: dict) -> AuditReport:
    """Offline audit of a `PagePool.to_state()` / `dump_state()` JSON
    snapshot — the `python -m flexflow_tpu.runtime.kvcache audit`
    entry point."""
    pages = {int(pid): (int(m["refs"]), m.get("key"))
             for pid, m in state.get("pages", {}).items()}
    tables = {s: [int(p) for p in t]
              for s, t in state.get("tables", {}).items()}
    headroom = {s: int(h) for s, h in state.get("headroom", {}).items()}
    violations = _audit_structures(
        int(state["num_pages"]), [int(p) for p in state.get("free", [])],
        pages, tables, dict(state.get("index", {})), headroom)
    return AuditReport(violations=violations, pages_resident=len(pages),
                       pages_free=len(state.get("free", [])),
                       bindings=sum(len(t) for t in tables.values()))


def kv_page_bytes(model, page_size: int,
                  kv_dtype: Optional[str] = None) -> Optional[int]:
    """Bytes one page costs across the model's self-attention layers
    (2 * page_size * heads * head_dim * itemsize per layer) — the
    docs/serving.md sizing formula, computed from the compiled graph.
    `kv_dtype` (a numpy dtype name, e.g. KVCacheConfig.kv_dtype) prices
    the page at an explicit cache dtype — a quantized int8 pool admits
    ~2x the sessions of an fp16 pool in the same byte budget; None keeps
    the executor's compute dtype (fp32 when unset).
    Returns None when the graph has no fused-MHA self-attention (e.g.
    primitive-op imports, where the cache cost lives in prefix tensors)."""
    import numpy as np

    from ..ff_types import OperatorType

    ex = getattr(model, "executor", None)
    if ex is None:
        return None
    total = 0
    if kv_dtype is not None:
        itemsize = np.dtype(kv_dtype).itemsize
    else:
        itemsize = np.dtype(np.float32).itemsize
        cdt = getattr(ex, "compute_dtype", None)
        if cdt is not None:
            itemsize = np.dtype(cdt).itemsize
    for op in ex.topo:
        if getattr(op, "op_type", None) != OperatorType.OP_MULTIHEAD_ATTENTION:
            continue
        p = op.params
        total += page_size * p.num_heads * (p.qk_head_dim + p.v_head_dim) \
            * itemsize
    return total or None


# ----------------------------------------------------------------------
# auditor CLI: python -m flexflow_tpu.runtime.kvcache {audit,selftest}
# ----------------------------------------------------------------------
def _run_selftest(ops: int, seed: int, chaos: bool) -> int:
    """Randomized reserve/COW/release lifecycle over shared prefixes,
    audited every 100 ops and once at the end; with chaos, all four
    fault sites are armed periodically and only TYPED errors may
    surface. Exit 0 iff every audit is clean and the drained pool is
    empty."""
    import random

    rng = random.Random(seed)
    fi = None
    if chaos:
        from .resilience import FaultInjector
        fi = FaultInjector()
    pool = PagePool(KVCacheConfig(num_pages=64, page_size=4, watermark=0.1),
                    fault_injector=fi)
    prefixes = [[rng.randrange(256) for _ in range(16)] for _ in range(4)]
    live: Dict[str, List[int]] = {}
    violations = typed = 0
    sites = ("cow_fault", "release_race", "shared_page_corruption",
             "kv_exhaustion")
    for op in range(ops):
        if chaos and op % 97 == 13:
            fi.inject(rng.choice(sites), times=1)
        r = rng.random()
        try:
            if (r < 0.5 and len(live) < 12) or not live:
                seq = f"s{op}"
                toks = rng.choice(prefixes) + [
                    rng.randrange(256) for _ in range(rng.randrange(0, 8))]
                pool.reserve(seq, len(toks) + rng.randrange(1, 12),
                             tokens=toks, writable=True)
                pool.touch(seq, len(toks))
                pool.publish(seq, toks)
                live[seq] = toks
            elif r < 0.8:
                seq = rng.choice(sorted(live))
                pool.note_write(seq, rng.randrange(len(live[seq])))
            else:
                seq = rng.choice(sorted(live))
                del live[seq]
                pool.release(seq)
        except KVCacheExhaustedError:
            typed += 1
            if live:  # retire one under pressure and move on
                seq = sorted(live)[0]
                del live[seq]
                try:
                    pool.release(seq)
                except KVCacheAccountingError:  # injected release_race
                    typed += 1
        except KVCacheAccountingError:
            typed += 1
        if op % 100 == 99:
            violations += len(pool.audit().violations)
    for seq in sorted(live):
        pool.release(seq)
    final = pool.audit()
    violations += len(final.violations)
    drained = (pool.pages_in_use == 0 and pool.pages_resident == 0
               and pool.pages_free == pool.config.num_pages)
    summary = {
        "ops": ops, "seed": seed, "chaos": chaos,
        "typed_errors": typed, "violations": violations,
        "drained": drained, "stats": dict(pool.stats),
        "ok": violations == 0 and drained,
    }
    print(json.dumps(summary, indent=2, sort_keys=True))
    return 0 if summary["ok"] else 1


def _cli_audit(paths: List[str]) -> int:
    if not paths:
        # no snapshots: audit a built-in deterministic lifecycle so the
        # bare `audit` invocation is still a meaningful exit-code check
        return _run_selftest(ops=500, seed=0, chaos=False)
    rc = 0
    for path in paths:
        with open(path) as f:
            state = json.load(f)
        report = audit_state(state)
        out = dict(report.to_dict(), file=path)
        print(json.dumps(out, indent=2, sort_keys=True))
        if not report.ok:
            rc = 1
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m flexflow_tpu.runtime.kvcache",
        description="KV page-pool auditor: prove refcount/leak/"
                    "double-free invariants over dumped pool state or a "
                    "randomized chaos lifecycle.")
    sub = p.add_subparsers(dest="cmd")
    pa = sub.add_parser(
        "audit", help="audit PagePool.dump_state() JSON snapshots "
                      "(no files: audit a built-in lifecycle)")
    pa.add_argument("states", nargs="*",
                    help="JSON files written by PagePool.dump_state()")
    ps = sub.add_parser(
        "selftest", help="randomized reserve/COW/release hammer with "
                         "chaos sites, audited every 100 ops")
    ps.add_argument("--ops", type=int, default=2000)
    ps.add_argument("--seed", type=int, default=0)
    ps.add_argument("--no-chaos", action="store_true")
    args = p.parse_args(argv)
    if args.cmd == "audit":
        return _cli_audit(args.states)
    if args.cmd == "selftest":
        return _run_selftest(args.ops, args.seed, chaos=not args.no_chaos)
    p.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
