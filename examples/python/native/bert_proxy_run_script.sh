#!/bin/bash
# reference: examples/python/native/bert_proxy_run_script.sh
set -euo pipefail
cd "$(dirname "${BASH_SOURCE[0]}")"
PYTHONPATH="$(cd ../../.. && pwd)" python bert_proxy_native.py "$@"
