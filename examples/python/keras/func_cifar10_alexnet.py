"""CIFAR-10 AlexNet, functional API (reference:
examples/python/keras/func_cifar10_alexnet.py — images upscaled to 229x229;
here resized with numpy repeat since PIL isn't required)."""
import numpy as np

from flexflow.keras.models import Model
from flexflow.keras.layers import (
    Input, Conv2D, MaxPooling2D, Flatten, Dense, Activation)
import flexflow.keras.optimizers
from flexflow.keras.datasets import cifar10

from accuracy import ModelAccuracy
from _example_args import example_args, verify_callbacks


def top_level_task(args):
    num_classes = 10
    (x_train, y_train), _ = cifar10.load_data(n_train=args.num_samples)
    x_train = x_train.transpose(0, 3, 1, 2).astype("float32") / 255  # NCHW
    # nearest-neighbour upscale 32 -> 224 (7x) instead of PIL's 229
    x_train = x_train.repeat(7, axis=2).repeat(7, axis=3)
    y_train = y_train.astype("int32").reshape(-1, 1)

    input_tensor = Input(shape=(3, 224, 224))
    x = Conv2D(filters=64, kernel_size=(11, 11), strides=(4, 4),
               padding=(2, 2), activation="relu")(input_tensor)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2), padding="valid")(x)
    x = Conv2D(filters=192, kernel_size=(5, 5), strides=(1, 1),
               padding=(2, 2), activation="relu")(x)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2), padding="valid")(x)
    x = Conv2D(filters=384, kernel_size=(3, 3), strides=(1, 1),
               padding=(1, 1), activation="relu")(x)
    x = Conv2D(filters=256, kernel_size=(3, 3), strides=(1, 1),
               padding=(1, 1), activation="relu")(x)
    x = Conv2D(filters=256, kernel_size=(3, 3), strides=(1, 1),
               padding=(1, 1), activation="relu")(x)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2), padding="valid")(x)
    x = Flatten()(x)
    x = Dense(4096, activation="relu")(x)
    x = Dense(4096, activation="relu")(x)
    out = Activation("softmax")(Dense(num_classes)(x))

    model = Model(input_tensor, out)
    opt = flexflow.keras.optimizers.SGD(learning_rate=0.01)
    model.compile(optimizer=opt, loss="sparse_categorical_crossentropy",
                  metrics=["accuracy", "sparse_categorical_crossentropy"],
                  batch_size=args.batch_size)
    model.fit(x_train, y_train, epochs=args.epochs,
              callbacks=verify_callbacks(args, ModelAccuracy.CIFAR10_ALEXNET))


if __name__ == "__main__":
    print("Functional API, cifar10 alexnet")
    top_level_task(example_args(num_samples=1024))
