"""Element-wise unary, binary, and scalar operators.

TPU-native equivalent of reference src/ops/element_unary.cc (720 LoC),
element_binary.cc (812 LoC) and their CUDA kernels. On TPU each of these is a
single VPU-mapped jnp op that XLA fuses into neighbors, so the whole family
collapses into a dispatch table. Broadcast semantics follow the reference's
element_binary broadcast support.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ff_types import OperatorType
from .registry import register_op

# ---------------------------------------------------------------------------
# Unary (reference: element_unary.cc; OP list ffconst.h)
# ---------------------------------------------------------------------------

_UNARY_FNS = {
    OperatorType.OP_EXP: jnp.exp,
    OperatorType.OP_LOG: jnp.log,
    OperatorType.OP_RELU: jax.nn.relu,
    OperatorType.OP_SIGMOID: jax.nn.sigmoid,
    OperatorType.OP_TANH: jnp.tanh,
    OperatorType.OP_ELU: jax.nn.elu,
    OperatorType.OP_GELU: jax.nn.gelu,
    OperatorType.OP_RSQRT: lambda x: jax.lax.rsqrt(x),
    OperatorType.OP_SQRT: jnp.sqrt,
    OperatorType.OP_SIN: jnp.sin,
    OperatorType.OP_COS: jnp.cos,
    OperatorType.OP_IDENTITY: lambda x: x,
    OperatorType.OP_CEIL: jnp.ceil,
    OperatorType.OP_ROUND: jnp.round,
    OperatorType.OP_LOGICAL_NOT: jnp.logical_not,
    OperatorType.OP_LEAKYRELU: lambda x: jax.nn.leaky_relu(x, 0.01),
}


@dataclasses.dataclass(frozen=True)
class ElementUnaryParams:
    """reference: include/flexflow/ops/element_unary_params.h"""

    op_type: OperatorType
    inplace: bool = False
    scalar: float = 0.0


def _unary_infer(params, in_shapes, in_dtypes):
    return [in_shapes[0]], [in_dtypes[0]]


def _unary_forward(params: ElementUnaryParams, weights, inputs, ctx):
    (x,) = inputs
    t = params.op_type
    if t == OperatorType.OP_POW:
        return [jnp.power(x, params.scalar)]
    if t == OperatorType.OP_SCALAR_MULTIPLY:
        return [x * params.scalar]
    if t == OperatorType.OP_SCALAR_ADD:
        return [x + params.scalar]
    if t == OperatorType.OP_SCALAR_SUB:
        return [x - params.scalar]
    if t == OperatorType.OP_SCALAR_TRUE_DIV:
        return [x / params.scalar]
    if t == OperatorType.OP_SCALAR_FLOOR_DIV:
        return [jnp.floor_divide(x, params.scalar)]
    return [_UNARY_FNS[t](x)]


for _t in list(_UNARY_FNS) + [
    OperatorType.OP_POW,
    OperatorType.OP_SCALAR_MULTIPLY,
    OperatorType.OP_SCALAR_ADD,
    OperatorType.OP_SCALAR_SUB,
    OperatorType.OP_SCALAR_TRUE_DIV,
    OperatorType.OP_SCALAR_FLOOR_DIV,
]:
    register_op(_t, f"ElementUnary_{_t.name}", infer=_unary_infer,
                forward=_unary_forward, seq_pointwise=True)

# ---------------------------------------------------------------------------
# Binary (reference: element_binary.cc with broadcast support)
# ---------------------------------------------------------------------------

_BINARY_FNS = {
    OperatorType.OP_EW_ADD: jnp.add,
    OperatorType.OP_EW_SUB: jnp.subtract,
    OperatorType.OP_EW_MUL: jnp.multiply,
    OperatorType.OP_EW_DIV: jnp.divide,
    OperatorType.OP_EW_MAX: jnp.maximum,
    OperatorType.OP_EW_MIN: jnp.minimum,
    OperatorType.OP_EW_EQUAL: jnp.equal,
    OperatorType.OP_EW_GREATER: jnp.greater,
    OperatorType.OP_EW_LESS: jnp.less,
}


@dataclasses.dataclass(frozen=True)
class ElementBinaryParams:
    """reference: include/flexflow/ops/element_binary_params.h"""

    op_type: OperatorType
    inplace_a: bool = False


def _binary_infer(params, in_shapes, in_dtypes):
    a, b = in_shapes
    out = np.broadcast_shapes(tuple(a), tuple(b))
    dt = in_dtypes[0]
    if params.op_type in (
        OperatorType.OP_EW_EQUAL,
        OperatorType.OP_EW_GREATER,
        OperatorType.OP_EW_LESS,
    ):
        from ..ff_types import DataType

        dt = DataType.DT_BOOLEAN
    return [tuple(out)], [dt]


def _binary_forward(params: ElementBinaryParams, weights, inputs, ctx):
    a, b = inputs
    return [_BINARY_FNS[params.op_type](a, b)]


for _t in _BINARY_FNS:
    register_op(
        _t, f"ElementBinary_{_t.name}", infer=_binary_infer, forward=_binary_forward,
        num_inputs=2, seq_pointwise=True,
    )


# -- PReLU (learnable per-channel negative slope; ONNX frontend op) ---------
import dataclasses as _dc

from .registry import WeightSpec


@_dc.dataclass(frozen=True)
class PReluParams:
    pass


def _prelu_channels(shape):
    # channel dim: NCHW conv layout for 4-D (conv2d.py is NCHW), else last
    return shape[1] if len(shape) == 4 else shape[-1]


def _prelu_weights(params, in_shapes, in_dtypes):
    (s,) = in_shapes
    return [WeightSpec("alpha", (_prelu_channels(s),), in_dtypes[0], "constant:0.25")]


def _prelu_forward(params, weights, inputs, ctx):
    (x,) = inputs
    a = weights["alpha"].astype(x.dtype)
    if x.ndim == 4:  # broadcast per-channel over NCHW spatial dims
        a = a.reshape(1, -1, 1, 1)
    return [jnp.where(x >= 0, x, a * x)]


register_op(
    OperatorType.OP_PRELU,
    "PReLU",
    infer=lambda p, s, dt: ([s[0]], [dt[0]]),
    weights=_prelu_weights,
    forward=_prelu_forward,
)
