"""Python wrapper over the native prefetching data loader."""
from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from . import get_lib


class NativeDataLoader:
    """Shuffled, prefetched batch iterator over an in-memory dataset
    (native equivalent of SingleDataLoader's sequential slicing; reference
    python/flexflow_dataloader.cc)."""

    def __init__(self, array: np.ndarray, batch_size: int, *, shuffle: bool = True,
                 seed: int = 0, queue_depth: int = 4):
        lib = get_lib()
        assert lib is not None, "native library unavailable"
        self._lib = lib
        self.array = np.ascontiguousarray(array)
        self.batch_size = batch_size
        self.sample_shape = self.array.shape[1:]
        sample_bytes = int(self.array.dtype.itemsize * np.prod(self.sample_shape or (1,)))
        self._out = np.empty((batch_size,) + self.sample_shape, self.array.dtype)
        self._handle = lib.ffdl_create(
            self.array.ctypes.data_as(ctypes.c_void_p),
            self.array.shape[0],
            sample_bytes,
            batch_size,
            1 if shuffle else 0,
            seed,
            queue_depth,
        )
        assert self._handle, "ffdl_create failed"

    @property
    def num_batches(self) -> int:
        return self._lib.ffdl_batches_per_epoch(self._handle)

    def next_batch(self) -> Optional[np.ndarray]:
        idx = self._lib.ffdl_next(
            self._handle, self._out.ctypes.data_as(ctypes.c_void_p)
        )
        if idx < 0:
            return None
        return self._out.copy()

    def reset(self):
        self._lib.ffdl_reset(self._handle)

    def __iter__(self):
        self.reset()
        while True:
            b = self.next_batch()
            if b is None:
                return
            yield b

    def __del__(self):
        try:
            if getattr(self, "_handle", None):
                self._lib.ffdl_destroy(self._handle)
                self._handle = None
        except Exception:  # fflint: disable=FFL002 — best-effort destructor
            pass
