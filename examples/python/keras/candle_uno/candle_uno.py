"""CANDLE Uno drug-response model, keras frontend (reference:
examples/python/keras/candle_uno/candle_uno.py + uno.py — multi-tower
feature encoders concatenated into a regression head; the reference's data
pipeline is replaced with synthetic feature tensors of the published
dimensions)."""
import numpy as np

from flexflow.keras.models import Model
from flexflow.keras.layers import Input, Dense, Concatenate
import flexflow.keras.optimizers

import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from _example_args import example_args  # noqa: E402

FEATURE_SHAPES = {"cell.rnaseq": 942, "drug1.descriptors": 5270,
                  "drug1.fingerprints": 2048}


def feature_tower(name, width, dense_layers=(1000, 1000, 1000)):
    inp = Input(shape=(width,), name=f"input.{name}")
    x = inp
    for i, units in enumerate(dense_layers):
        x = Dense(units, activation="relu", name=f"{name}.dense{i}")(x)
    return inp, x


def top_level_task(args):
    towers = [feature_tower(n, w) for n, w in FEATURE_SHAPES.items()]
    merged = Concatenate(axis=1)([t[1] for t in towers])
    x = merged
    for i in range(3):
        x = Dense(1000, activation="relu", name=f"top.dense{i}")(x)
    out = Dense(1, name="response")(x)

    model = Model([t[0] for t in towers], out)
    model.compile(optimizer=flexflow.keras.optimizers.SGD(learning_rate=0.01),
                  loss="mean_squared_error", metrics=["mean_squared_error"],
                  batch_size=args.batch_size)
    n = args.num_samples
    xs = [np.random.randn(n, w).astype(np.float32)
          for w in FEATURE_SHAPES.values()]
    y = np.random.randn(n, 1).astype(np.float32)
    model.fit(xs, y, epochs=args.epochs)


if __name__ == "__main__":
    print("candle uno")
    top_level_task(example_args(epochs=2, num_samples=512, batch_size=32))
