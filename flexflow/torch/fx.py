"""Shim: `flexflow.torch.fx` — the module name bootcamp_demo and the
torch.nn shim import (`import flexflow.torch.fx as fx;
fx.torch_to_flexflow(model, path)`). The reference repo never shipped this
file (python/flexflow/torch/ has only model.py), leaving those entry points
broken there; here it simply fronts the working exporter."""
from flexflow_tpu.frontends.torch.model import (  # noqa: F401
    PyTorchModel,
    torch_to_flexflow,
)
