"""DLRM model builder (recommendation: embeddings + MLPs + interaction).

Same network shape as reference examples/cpp/DLRM/dlrm.cc (defaults
dlrm.cc:27-41: 4 embedding tables of 1M rows × 64, bottom MLP 4-64-64,
top MLP 64-64-2 with sigmoid on the last layer, 'cat' interaction).
Embedding tables shard over the vocab dim — the reference's parameter
parallelism (embedding.cc:132-200) — via the weight's "vocab" tag.
"""
from __future__ import annotations

from typing import List, Sequence

from ..core.model import FFModel
from ..ff_types import ActiMode, AggrMode, DataType


def create_mlp(model: FFModel, input_t, layers: Sequence[int], sigmoid_layer: int):
    """reference: dlrm.cc:44-63"""
    t = input_t
    for i, dim in enumerate(layers):
        act = (
            ActiMode.AC_MODE_SIGMOID if i == sigmoid_layer else ActiMode.AC_MODE_RELU
        )
        t = model.dense(t, dim, act)
    return t


def create_emb(model: FFModel, input_t, vocab_size: int, feature_size: int):
    """reference: dlrm.cc:67-79 (embedding_bag sum aggregation)"""
    return model.embedding(
        input_t, vocab_size, feature_size, AggrMode.AGGR_MODE_SUM
    )


def build_dlrm(
    model: FFModel,
    batch_size: int,
    embedding_sizes: Sequence[int] = (1000000,) * 4,
    embedding_bag_size: int = 1,
    sparse_feature_size: int = 64,
    mlp_bot: Sequence[int] = (4, 64, 64),
    mlp_top: Sequence[int] = (64, 64, 2),
    arch_interaction_op: str = "cat",
):
    """reference: dlrm.cc top_level_task wiring."""
    sparse_inputs = [
        model.create_tensor((batch_size, embedding_bag_size), DataType.DT_INT32,
                            name=f"sparse_{i}")
        for i in range(len(embedding_sizes))
    ]
    dense_input = model.create_tensor(
        (batch_size, mlp_bot[0]), DataType.DT_FLOAT, name="dense"
    )
    ly = [
        create_emb(model, s, v, sparse_feature_size)
        for s, v in zip(sparse_inputs, embedding_sizes)
    ]
    x = create_mlp(model, dense_input, mlp_bot[1:], -1)
    if arch_interaction_op == "cat":
        z = model.concat([x] + ly, axis=-1)
    else:
        raise NotImplementedError(f"interaction {arch_interaction_op}")
    p = create_mlp(model, z, mlp_top, len(mlp_top) - 1)
    return sparse_inputs + [dense_input], p
