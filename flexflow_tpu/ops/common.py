"""Shared helpers for op forwards."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ff_types import ActiMode


def apply_activation(mode: ActiMode, x):
    """Fused activations (reference: ops use cudnnActivationForward; see
    linear_kernels.cu / conv_2d_kernels.cu). XLA fuses these into the matmul
    epilogue automatically."""
    if mode == ActiMode.AC_MODE_NONE:
        return x
    if mode == ActiMode.AC_MODE_RELU:
        return jax.nn.relu(x)
    if mode == ActiMode.AC_MODE_SIGMOID:
        return jax.nn.sigmoid(x)
    if mode == ActiMode.AC_MODE_TANH:
        return jnp.tanh(x)
    if mode == ActiMode.AC_MODE_GELU:
        return jax.nn.gelu(x)
    raise ValueError(f"unknown activation {mode}")
