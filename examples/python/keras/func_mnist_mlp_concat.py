"""MNIST MLP with concatenated branches (reference:
examples/python/keras/func_mnist_mlp_concat.py)."""
from flexflow.keras.models import Model
from flexflow.keras.layers import Input, Dense, Activation, Concatenate
import flexflow.keras.optimizers
from _mnist import load_mnist

from accuracy import ModelAccuracy
from _example_args import example_args, verify_callbacks


def top_level_task(args):
    num_classes = 10
    x_train, y_train = load_mnist(args.num_samples)

    input_tensor = Input(shape=(784,))
    b1 = Dense(256, activation="relu")(input_tensor)
    b2 = Dense(256, activation="relu")(input_tensor)
    merged = Concatenate(axis=1)([b1, b2])
    x = Dense(256, activation="relu")(merged)
    out = Activation("softmax")(Dense(num_classes)(x))

    model = Model(input_tensor, out)
    opt = flexflow.keras.optimizers.SGD(learning_rate=0.01)
    model.compile(optimizer=opt, loss="sparse_categorical_crossentropy",
                  metrics=["accuracy", "sparse_categorical_crossentropy"],
                  batch_size=args.batch_size)
    model.fit(x_train, y_train, epochs=args.epochs,
              callbacks=verify_callbacks(args, ModelAccuracy.MNIST_MLP))


if __name__ == "__main__":
    print("Functional API, mnist mlp concat")
    top_level_task(example_args())
