#!/usr/bin/env bash
# Numerical-trust sweep (ISSUE 3): the strategy-equivalence verifier over
# searched model-zoo graphs on CPU meshes, plus the checkpoint-integrity
# and SDC-canary fault-injection stories — including the
# @pytest.mark.slow zoo sweep that tier-1 skips. The outer loop varies
# the process-level device count so the differential verifier checks
# searched strategies against genuinely different meshes, not just the
# default 8-device one. Use before touching the search, the lowering,
# the parallel ops, or the checkpoint/canary paths:
#
#   scripts/verify_check.sh                  # full sweep (8, 4-device meshes)
#   FF_VERIFY_DEVICES=8 scripts/verify_check.sh -k strategy
set -euo pipefail
cd "$(dirname "$0")/.."

devices="${FF_VERIFY_DEVICES:-8 4}"
for n in $devices; do
    echo "=== verify sweep: ${n}-device CPU mesh ==="
    env JAX_PLATFORMS=cpu \
        JAX_NUM_CPU_DEVICES="$n" \
        XLA_FLAGS="--xla_force_host_platform_device_count=$n" \
        python -m pytest tests/test_verify.py -v -p no:cacheprovider "$@"
done
