"""Shim: reference python/flexflow/keras/layers/ (all layer classes)."""
from flexflow_tpu.frontends.keras.layers import *  # noqa: F401,F403
