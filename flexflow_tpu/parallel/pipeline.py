"""GPipe-style SPMD pipeline parallelism over a mesh axis.

The reference DECLARES pipeline parallelism but never implements it:
`OP_PIPELINE` exists only as an enum (ffconst.h:158) and task IDs
(model.h:190-192) with no source file (SURVEY §2.3). This module supplies
the capability TPU-natively, the way XLA wants it expressed: every device
runs the SAME program (SPMD), stage placement is a sharding of the stacked
layer weights over a "pipe" mesh axis, and activations move between stages
with `lax.ppermute` hops over the ICI ring.

Schedule: GPipe. The local batch is split into `n_micro` microbatches; for
`n_micro + n_stages - 1` ticks, each device (stage) computes its layer
group on the activation it holds, then the ring rotates activations one hop
so stage s+1 sees stage s's output next tick. Stage 0 injects a fresh
microbatch each of the first `n_micro` ticks; the last stage collects
finished microbatches. The whole schedule is a `lax.scan`, so jax.grad
differentiates it — backward is automatically the reverse pipeline
(ppermute transposes to the opposite rotation).

Bubble fraction is (n_stages-1)/(n_micro+n_stages-1), the GPipe figure;
raise num_microbatches to amortize.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.4.35 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

# lax.pcast / lax.pvary exist only on newer jax (the varying-manual-axes
# rep type system); older releases draw no replicated/varying distinction
# inside shard_map, so identity is the correct fallback for both
_pcast = getattr(lax, "pcast", lambda x, _axes, to=None: x)
_pvary = getattr(lax, "pvary", lambda x, _axes: x)
# without pcast there is no way to give every lax.switch branch one rep
# type, so the old releases' rep checker must be off for the nonuniform
# (switch-based) pipeline; the kwarg only exists there, hence the gate
_NONUNIFORM_SHARD_MAP_KW = (
    {} if hasattr(lax, "pcast") else {"check_rep": False}
)


def scan_blocks(block_fn: Callable, stacked_params, x):
    """Degenerate (single-stage) path: run all stacked layers sequentially.
    `stacked_params` leaves have a leading num_layers dim."""

    def body(h, layer_w):
        return block_fn(layer_w, h), None

    out, _ = lax.scan(body, x, stacked_params)
    return out


def _stage_apply(block_fn: Callable, local_params, h):
    """Apply this stage's layer group (leaves have leading layers/stage dim)."""

    def body(c, layer_w):
        return block_fn(layer_w, c), None

    out, _ = lax.scan(body, h, local_params)
    return out


def gpipe_spmd(
    block_fn: Callable,
    stacked_params,
    x,
    *,
    n_stages: int,
    n_micro: int,
    mesh,
    axis_name: str = "pipe",
    data_axis: str = "data",
):
    """Run `n_stages * layers_per_stage` stacked blocks as a GPipe pipeline.

    stacked_params: pytree whose leaves have leading dim num_layers,
    sharded over `axis_name`. x: (batch, ...) activation, sharded over
    `data_axis` on dim 0. Returns the same-shaped output, replicated over
    the pipe axis (every stage ends up with the full result via psum of a
    buffer that is zero off the last stage).
    """
    num_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    assert num_layers % n_stages == 0, (
        f"{num_layers} layers not divisible into {n_stages} stages"
    )
    dp = mesh.shape.get(data_axis, 1)
    b_local = x.shape[0] // dp
    # clamp the schedule to what the local batch can supply: the largest
    # divisor of b_local not exceeding the requested microbatch count
    n_micro = max(1, min(n_micro, b_local))
    while b_local % n_micro:
        n_micro -= 1

    def pipelined(local_params, x_local):
        stage = lax.axis_index(axis_name)
        mb = x_local.shape[0] // n_micro
        mbs = x_local.reshape((n_micro, mb) + x_local.shape[1:])
        ticks = n_micro + n_stages - 1
        # carries become pipe-varying inside the loop (ppermute / stage
        # predicates), so the initial zeros must carry that vma type too
        zero_x = _pcast(jnp.zeros_like(mbs[0]), (axis_name,), to="varying")
        zero_out = _pcast(jnp.zeros_like(mbs), (axis_name,), to="varying")
        perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

        def tick(carry, t):
            x_cur, outbuf = carry
            inj = lax.dynamic_index_in_dim(
                mbs, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False
            )
            x_in = jnp.where(stage == 0, inj, x_cur)
            y = _stage_apply(block_fn, local_params, x_in)
            out_idx = t - (n_stages - 1)
            oi = jnp.clip(out_idx, 0, n_micro - 1)
            old = lax.dynamic_index_in_dim(outbuf, oi, 0, keepdims=False)
            valid = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
            outbuf = lax.dynamic_update_index_in_dim(
                outbuf, jnp.where(valid, y, old), oi, 0
            )
            x_next = lax.ppermute(y, axis_name, perm)
            return (x_next, outbuf), None

        (_, outbuf), _ = lax.scan(tick, (zero_x, zero_out), jnp.arange(ticks))
        # off-last-stage buffers are all zeros -> psum replicates the result
        out = lax.psum(outbuf, axis_name)
        return out.reshape(x_local.shape)

    param_specs = jax.tree_util.tree_map(
        lambda l: P(*((axis_name,) + (None,) * (l.ndim - 1))), stacked_params
    )
    x_spec = P(*((data_axis,) + (None,) * (x.ndim - 1)))
    fn = shard_map(
        pipelined,
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=x_spec,
    )
    return fn(stacked_params, x)


# ---------------------------------------------------------------------------
# Generalized pipeline over an ARBITRARY PCG (non-uniform models, CNNs)
# ---------------------------------------------------------------------------
# The block-stack path above needs identical layers (stage placement = a
# sharding of stacked weights). For an arbitrary op chain the stages are
# heterogeneous: different subgraphs, different activation shapes. Under
# SPMD that becomes: every device runs `lax.switch` over its stage index
# (each branch = one stage's subgraph), and inter-stage activations travel
# in a FIXED-SIZE flat f32 buffer (padded to the widest cut) so ppermute
# has one uniform carrier type. Weights stay replicated over the pipe axis
# — this trades the block-stack path's weight-memory sharding for
# generality (compute still pipelines; the reference has neither:
# OP_PIPELINE is enum-only, ffconst.h:158).

import dataclasses
from typing import Any, List, Tuple


@dataclasses.dataclass
class PcgPipelinePlan:
    """Stage partition of a PCG's compute ops (contiguous in topo order)."""

    stages: List[List]  # per stage: PCGOps
    # per cut s (between stage s and s+1): [(guid, shape_wo_batch, dtype)]
    cuts: List[List[Tuple[int, Tuple[int, ...], Any]]]
    buf_elems: int  # flat f32 elems per sample, max over cuts + output
    out_guid: int
    out_shape: Tuple[int, ...]  # global shape
    out_dtype: Any
    n_stages: int
    # parallel-op output guid -> producing compute tensor guid (identity
    # bookkeeping resolved at plan time)
    alias: dict = dataclasses.field(default_factory=dict)


def balanced_linear_partition(costs: List[float], k: int) -> List[int]:
    """Contiguous partition of `costs` into k groups minimizing the max
    group sum (classic linear-partition DP) — this is how "the search
    proposes the cut": op costs come from the analytic cost model.
    Returns cut indices: group j = ops[cut[j]:cut[j+1]]."""
    n = len(costs)
    k = min(k, n)
    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + c)

    def group(a, b):
        return prefix[b] - prefix[a]

    INF = float("inf")
    dp = [[INF] * (k + 1) for _ in range(n + 1)]
    cut = [[0] * (k + 1) for _ in range(n + 1)]
    dp[0][0] = 0.0
    for i in range(1, n + 1):
        for j in range(1, min(i, k) + 1):
            for m in range(j - 1, i):
                v = max(dp[m][j - 1], group(m, i))
                if v < dp[i][j]:
                    dp[i][j] = v
                    cut[i][j] = m
    bounds = [n]
    i, j = n, k
    while j > 0:
        i = cut[i][j]
        bounds.append(i)
        j -= 1
    return list(reversed(bounds))


def gpipe_pcg(
    plan: PcgPipelinePlan,
    stage_runners: List,  # stage s: fn(params, vals_dict) -> vals_dict
    params,
    input_arrays: List,  # global graph inputs, batch-leading
    input_guids: List[int],
    mesh,
    *,
    n_micro: int = 0,
    axis_name: str = "pipe",
    data_axis: str = "data",
):
    """Run the planned stages as a GPipe schedule. Inputs are injected at
    stage 0 (ints allowed — they bypass the f32 cut buffer); the final
    output returns replicated over the pipe axis."""
    n_stages = plan.n_stages
    dp = mesh.shape.get(data_axis, 1)
    batch = input_arrays[0].shape[0]
    b_local = batch // dp
    n_micro = n_micro or n_stages
    n_micro = max(1, min(n_micro, b_local))
    while b_local % n_micro:
        n_micro -= 1
    out_flat = 1
    for s in plan.out_shape[1:]:
        out_flat *= s
    buf_elems = max(plan.buf_elems, out_flat)

    def unpack(buf, cut, mb):
        vals = {}
        off = 0
        for guid, shp, dt in cut:
            size = 1
            for s in shp:
                size *= s
            vals[guid] = buf[:, off:off + size].reshape((mb,) + shp).astype(dt)
            off += size
        return vals

    def pack(vals, cut, mb):
        parts = [
            vals[guid].astype(jnp.float32).reshape(mb, -1)
            for guid, _, _ in cut
        ]
        flat = (jnp.concatenate(parts, axis=1) if parts
                else jnp.zeros((mb, 0), jnp.float32))
        pad = buf_elems - flat.shape[1]
        if pad:
            flat = jnp.pad(flat, ((0, 0), (0, pad)))
        return flat

    def pipelined(params, *inputs_local):
        # Make the replicated params VARYING up front: consumed as-is
        # inside the scan they'd each get an implicit pvary whose
        # transpose is a per-tick psum INSIDE the backward While loop,
        # racing the reverse ppermute across devices (observed XLA:CPU
        # rendezvous deadlock: half the mesh at an allreduce, half at a
        # permute). One explicit pvary here moves the whole param-grad
        # psum after the scan, where it is data-dependent on every
        # ppermute and cannot race.
        axes = (data_axis, axis_name)
        params = jax.tree_util.tree_map(
            lambda l: _pvary(l, axes), params
        )
        stage = lax.axis_index(axis_name)
        mb = inputs_local[0].shape[0] // n_micro
        mbs = [a.reshape((n_micro, mb) + a.shape[1:]) for a in inputs_local]
        ticks = n_micro + n_stages - 1
        # carriers are varying over BOTH the pipe axis (ppermute/stage
        # predicates) and the data axis (they mix with data-sharded
        # activations inside the branches)
        zero_buf = _pcast(
            jnp.zeros((mb, buf_elems), jnp.float32),
            (data_axis, axis_name), to="varying",
        )
        zero_out = _pcast(
            jnp.zeros((n_micro, mb, out_flat), jnp.float32),
            (data_axis, axis_name), to="varying",
        )
        perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

        def make_branch(s):
            def branch(buf, inj, t):
                if s == 0:
                    vals = dict(zip(input_guids, inj))
                else:
                    vals = unpack(buf, plan.cuts[s - 1], mb)
                vals = stage_runners[s](params, vals, t)
                if s == n_stages - 1:
                    out = vals[plan.out_guid].astype(jnp.float32)
                    flat = out.reshape(mb, -1)
                    pad = buf_elems - flat.shape[1]
                    return jnp.pad(flat, ((0, 0), (0, pad)))
                return pack(vals, plan.cuts[s], mb)
            return branch

        branches = [make_branch(s) for s in range(n_stages)]

        def tick(carry, t):
            buf, outbuf = carry
            # injected inputs must carry the pipe-varying vma type so every
            # switch branch (buf-derived or inj-derived) has one output type
            inj = [
                _pcast(
                    lax.dynamic_index_in_dim(
                        m, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False
                    ),
                    (axis_name,), to="varying",
                )
                for m in mbs
            ]
            y = lax.switch(stage, branches, buf, inj, t)
            out_idx = t - (n_stages - 1)
            oi = jnp.clip(out_idx, 0, n_micro - 1)
            old = lax.dynamic_index_in_dim(outbuf, oi, 0, keepdims=False)
            valid = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
            outbuf = lax.dynamic_update_index_in_dim(
                outbuf, jnp.where(valid, y[:, :out_flat], old), oi, 0
            )
            buf_next = lax.ppermute(y, axis_name, perm)
            return (buf_next, outbuf), None

        # unrolled: the tick count is small (n_micro + n_stages - 1) and
        # XLA:CPU's thunk executor races independent collectives across
        # devices when they sit inside a While body (observed deadlock:
        # half the mesh at the param-grad allreduce, half at a ppermute);
        # a flat thunk graph gives every device one static order
        (_, outbuf), _ = lax.scan(tick, (zero_buf, zero_out),
                                  jnp.arange(ticks), unroll=True)
        out = lax.psum(outbuf, axis_name)
        local_shape = (b_local,) + tuple(plan.out_shape[1:])
        return out.reshape(local_shape).astype(plan.out_dtype)

    in_specs = tuple(
        P(*((data_axis,) + (None,) * (a.ndim - 1))) for a in input_arrays
    )
    param_specs = jax.tree_util.tree_map(lambda _: P(), params)
    out_spec = P(*((data_axis,) + (None,) * (len(plan.out_shape) - 1)))
    fn = shard_map(
        pipelined,
        mesh=mesh,
        in_specs=(param_specs,) + in_specs,
        out_specs=out_spec,
        **_NONUNIFORM_SHARD_MAP_KW,
    )
    return fn(params, *input_arrays)
