"""PyTorch / ONNX example-suite smoke tests (reference:
tests/multi_gpu_tests.sh runs examples/python/pytorch and /onnx scripts;
pass criterion "trains without crashing" — SURVEY §4). The ONNX scripts also
exercise the self-contained protobuf wire codec end to end: export a real
.onnx file, re-parse it, train.

All scripts run in ONE subprocess (tests/_example_runner.py) — a fresh
interpreter per script costs ~10s of jax import each on this host; the
parametrized tests below just report each script's recorded result."""
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

CASES = [
    ("pytorch", "mnist_mlp.py"),        # .ff file export + replay
    ("pytorch", "mnist_mlp_torch2.py"),  # live fx trace
    ("pytorch", "resnet.py"),           # residual adds + batchnorm
    ("pytorch", "regnet.py"),           # grouped convs
    ("onnx", "mnist_mlp.py"),           # torch-layout Gemm transB
    ("onnx", "mnist_mlp_keras.py"),     # keras-layout MatMul
    ("onnx", "resnet.py"),              # Conv/BN/Add/GlobalAveragePool
    ("keras_exp", "func_mnist_mlp.py"),  # keras_exp Model over ONNX export
    ("keras_exp", "func_mnist_mlp_live.py"),  # LIVE model, vendored converter
    ("keras_exp", "func_cifar10_cnn_concat.py"),  # + conv towers, Concat
    ("native", "mnist_mlp_attach.py"),  # stepwise loop + per-batch attach
    ("native", "demo_gather.py"),       # gather + attached index/label
    ("native", "print_layers.py"),      # inline_map / set_weights APIs
    ("native", "tensor_attach.py"),     # attach round trip
]


@pytest.fixture(scope="module")
def frontend_results(tmp_path_factory):
    base = tmp_path_factory.mktemp("frontend_examples")
    cases = []
    for tree, script in CASES:
        tree_dir = os.path.join(ROOT, "examples", "python", tree)
        workdir = base / f"{tree}_{script}".replace(".py", "")
        workdir.mkdir()
        cases.append({
            "name": f"{tree}/{script}",
            "path": os.path.join(tree_dir, script),
            "argv": ["--epochs", "1", "--num-samples", "96",
                     "--batch-size", "32"],
            "cwd": str(workdir),  # exported .ff/.onnx artifacts land here
            "extra_sys_path": [tree_dir, ROOT],
        })
    spec = base / "spec.json"
    results = base / "results.json"
    spec.write_text(json.dumps({"cases": cases}))
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tests", "_example_runner.py"),
         str(spec), str(results)],
        capture_output=True, text=True, timeout=2400,
        env=dict(os.environ, PYTHONPATH=ROOT),
    )
    assert results.exists(), (
        f"example runner died: rc={proc.returncode}\n{proc.stdout}\n"
        f"{proc.stderr}"
    )
    return json.loads(results.read_text())


@pytest.mark.parametrize("tree,script", CASES)
def test_frontend_example(tree, script, frontend_results):
    res = frontend_results[f"{tree}/{script}"]
    assert res["ok"], f"{tree}/{script} failed:\n{res['output']}"


def test_onnx_proto_roundtrip(tmp_path):
    """Wire-format codec: serialize → parse preserves graph + tensors."""
    import numpy as np

    from flexflow_tpu.frontends.onnx import proto

    w = np.arange(12, dtype=np.float32).reshape(3, 4)
    idx = np.array([-1, 5], dtype=np.int64)
    node = proto.make_node("Gemm", ["x", "w"], ["y"], name="g", transB=1,
                           alpha=0.5, pads=[0, 1, 2, 3])
    graph = proto.make_graph(
        [node], "g",
        [proto.make_tensor_value_info("x", proto.TensorProto.FLOAT, ["N", 3])],
        [proto.make_tensor_value_info("y", proto.TensorProto.FLOAT, ["N", 4])],
        initializer=[proto.from_array(w, "w"), proto.from_array(idx, "idx")],
    )
    path = str(tmp_path / "m.onnx")
    proto.save_model(proto.make_model(graph), path)
    m = proto.load_model(path)
    assert m.graph.node[0].op_type == "Gemm"
    attrs = {a.name: a for a in m.graph.node[0].attribute}
    assert attrs["transB"].i == 1
    assert attrs["alpha"].f == 0.5
    assert list(attrs["pads"].ints) == [0, 1, 2, 3]
    assert np.array_equal(proto.to_array(m.graph.initializer[0]), w)
    assert np.array_equal(proto.to_array(m.graph.initializer[1]), idx)
    assert m.graph.input[0].type.tensor_type.shape.dim[0].dim_param == "N"
    assert m.graph.input[0].type.tensor_type.shape.dim[1].dim_value == 3
