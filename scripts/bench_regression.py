#!/usr/bin/env python3
"""Phase-aware bench-regression gate: the headline throughput against
BASELINE.json's published number, and each bench phase (fwd/bwd/opt/sync
seconds per step, bench.py's phases_s_per_step) against the previous
committed round — with the regression attributed to the phase that moved.

Reads the measurement from (first match wins):
  --bench-json FILE   a bench.py JSON line, or a driver BENCH_r*.json
                      artifact (the {"parsed": {...}} wrapper)
  stdin ("-")         a bench.py JSON line piped in
  BENCH_r*.json       the newest committed round artifact in the repo root

Exit code is 1 on any regression (headline below tolerance, or a phase
slower than its per-phase tolerance vs the previous round) unless
--warn-only, which downgrades every failure to a GitHub Actions
::warning:: annotation and exits 0. Phases missing on either side (old
rounds predate phases_s_per_step) skip silently — the headline gate
still applies.

Usage:
  python scripts/bench_regression.py                      # newest round
  python bench.py | python scripts/bench_regression.py -  # fresh run
  python scripts/bench_regression.py --tolerance 0.10 \
      --phase-tolerance fwd=0.10 --phase-tolerance sync=0.30
  python scripts/bench_regression.py --warn-only          # never fails
"""
import argparse
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PHASES = ("fwd", "bwd", "opt", "sync")
# opt/sync are the smallest slices of the step and the noisiest to time
# (the sync estimate is static on one chip) — give them more headroom
DEFAULT_PHASE_TOLERANCES = {"fwd": 0.15, "bwd": 0.15,
                            "opt": 0.25, "sync": 0.25}


def load_measurement(src):
    """-> (doc, where): the bench.py JSON dict from a line file, driver
    artifact, stdin, or the newest committed round."""
    if src == "-":
        doc = json.loads(sys.stdin.read())
        where = "stdin"
    elif src:
        with open(src) as f:
            doc = json.load(f)
        where = src
    else:
        rounds = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
        if not rounds:
            return None, None
        with open(rounds[-1]) as f:
            doc = json.load(f)
        where = os.path.basename(rounds[-1])
    if "parsed" in doc:  # driver artifact wraps the bench line
        doc = doc["parsed"] or {}
    return doc, where


def load_baseline(metric, backend=None, smoke=False):
    """Published baseline for EXACTLY this metric on this hardware tier.
    A new series (the zoo workloads: moe_train_throughput,
    longctx_train_throughput) has no published number until the driver
    records one — the caller treats that as warn-only and skips the
    headline gate, instead of comparing a zoo workload against the
    transformer baseline.

    Bare published.<metric> entries belong to published.tier (the
    driver's axon/TPU pool; rounds that predate the backend field were
    all measured there). A round measured on another backend only gates
    against an explicitly scoped published.<metric>@<backend> entry —
    a CPU-session round vs a TPU baseline is a hardware difference, not
    a regression. FF_BENCH_SMOKE rounds scope one step further
    (<metric>@<backend>+smoke): smoke shapes amortize warmup
    differently, so they never compare against full-run numbers."""
    try:
        with open(os.path.join(REPO, "BASELINE.json")) as f:
            published = json.load(f).get("published", {}) or {}
    except (OSError, ValueError):
        return None
    tier = published.get("tier") or "axon"
    if smoke:
        v = published.get(f"{metric}@{backend or tier}+smoke")
        if isinstance(v, (int, float)) and v > 0:
            return float(v)
        return None
    if backend:
        v = published.get(f"{metric}@{backend}")
        if isinstance(v, (int, float)) and v > 0:
            return float(v)
        if backend != tier:
            return None
    v = published.get(metric)
    if isinstance(v, (int, float)) and v > 0:
        return float(v)
    return None


def previous_phases(where, history_dir=REPO, metric=None, backend=None,
                    smoke=False):
    """The newest committed round OTHER than the one under test that
    carries phases_s_per_step for the SAME metric and backend ->
    (phases dict, round label) or (None, None). Rounds that predate the
    metric/backend fields count as transformer rounds on the driver's
    axon tier — comparing a CPU moe round's phases against them would
    attribute a hardware/workload difference to a code change."""
    try:
        from flexflow_tpu.obs.step_profile import load_bench_history
    except ImportError:
        return None, None

    history = load_bench_history(history_dir)
    want_metric = metric or "transformer_train_throughput"
    want_backend = backend or "axon"
    for r in reversed(history):
        if where and os.path.basename(r["path"]) == os.path.basename(where):
            continue
        if (r.get("metric") or "transformer_train_throughput") != want_metric:
            continue
        if (r.get("backend") or "axon") != want_backend:
            continue
        if bool(r.get("smoke")) != bool(smoke):
            continue
        if isinstance(r.get("phases"), dict):
            return r["phases"], f"r{r['round']:02d}"
    return None, None


def parse_phase_tolerances(pairs):
    tol = dict(DEFAULT_PHASE_TOLERANCES)
    for pair in pairs or ():
        name, _, frac = pair.partition("=")
        if name not in PHASES or not frac:
            raise SystemExit(
                f"bench_regression: bad --phase-tolerance {pair!r} "
                f"(want one of {'/'.join(PHASES)}=FRACTION)")
        tol[name] = float(frac)
    return tol


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="phase-aware bench vs baseline/previous-round gate")
    ap.add_argument("bench_json", nargs="?", default=None,
                    help="bench JSON line file, driver artifact, or - for "
                         "stdin (default: newest BENCH_r*.json)")
    ap.add_argument("--tolerance", type=float, default=0.15,
                    help="allowed fractional headline drop below baseline "
                         "(default 0.15)")
    ap.add_argument("--phase-tolerance", action="append", metavar="PH=FRAC",
                    help="per-phase allowed fractional slowdown vs the "
                         "previous round, e.g. fwd=0.10 (repeatable; "
                         f"defaults {DEFAULT_PHASE_TOLERANCES})")
    ap.add_argument("--history-dir", default=REPO,
                    help="directory holding the BENCH_r*.json round "
                         "artifacts the phase gate compares against "
                         "(default: repo root)")
    ap.add_argument("--warn-only", action="store_true",
                    help="downgrade regressions to ::warning:: annotations "
                         "and exit 0")
    args = ap.parse_args(argv)
    phase_tol = parse_phase_tolerances(args.phase_tolerance)

    doc, where = load_measurement(args.bench_json)
    if doc is None:
        print("bench_regression: no measurement found "
              "(no BENCH_r*.json rounds); nothing to compare")
        return 0
    value = doc.get("value")
    if not isinstance(value, (int, float)) or value <= 0:
        print(f"bench_regression: no usable value in {where}; "
              "nothing to compare")
        return 0
    metric = doc.get("metric", "transformer_train_throughput")
    backend = doc.get("backend")
    smoke = bool(doc.get("smoke"))
    failures = []

    # ---- headline gate: throughput vs the published baseline ----------
    baseline = load_baseline(metric, backend, smoke)
    if baseline is None:
        # absent series are warn-only, never a failure: annotate so the
        # missing baseline is visible in the Actions summary and move on
        scope = f"{metric}@{backend}" if backend else metric
        if smoke:
            scope += "+smoke"
        print(f"::warning title=bench baseline::BASELINE.json has no "
              f"published value for {scope}; headline gate skipped "
              "(new series stay warn-only until a baseline is recorded "
              "on this hardware tier)")
    else:
        ratio = value / baseline
        line = (f"bench_regression: {metric} = {value:.3f} vs baseline "
                f"{baseline:.3f} ({where}); ratio {ratio:.3f}, "
                f"tolerance -{args.tolerance:.0%}")
        if ratio < 1.0 - args.tolerance:
            failures.append(line)
        else:
            print(f"{line} — OK")

    # ---- phase gate: seconds per step vs the previous round -----------
    cur_phases = doc.get("phases_s_per_step")
    if not isinstance(cur_phases, dict):
        print(f"bench_regression: {where} has no phases_s_per_step; "
              "skipping the phase gate")
    else:
        prev, prev_label = previous_phases(where, args.history_dir,
                                           metric, backend, smoke)
        if prev is None:
            print("bench_regression: no previous round carries "
                  "phases_s_per_step; skipping the phase gate")
        else:
            grew = {}
            for ph in PHASES:
                a, b = prev.get(ph), cur_phases.get(ph)
                if not isinstance(a, (int, float)) or a <= 0 \
                        or not isinstance(b, (int, float)):
                    continue
                r = b / a
                line = (f"bench_regression: phase {ph} = {b * 1e3:.3f} ms "
                        f"vs {a * 1e3:.3f} ms ({prev_label}); ratio "
                        f"{r:.3f}, tolerance +{phase_tol[ph]:.0%}")
                if b > a:
                    grew[ph] = b - a
                if r > 1.0 + phase_tol[ph]:
                    failures.append(line)
                else:
                    print(f"{line} — OK")
            if grew:
                total = sum(grew.values())
                dominant = max(grew, key=grew.get)
                print(f"bench_regression: step grew {total * 1e3:.3f} ms; "
                      f"dominant phase {dominant} "
                      f"({grew[dominant] / total:.0%} of the growth)")

    for line in failures:
        print(f"::warning title=bench regression::{line}")
    if failures and not args.warn_only:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
