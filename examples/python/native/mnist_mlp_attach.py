"""MNIST MLP driven by the stepwise cffi loop with per-batch tensor attach
(reference: examples/python/native/mnist_mlp_attach.py — input/label bound
via set_tensor each iteration, then forward / zero_gradients / backward /
update)."""
from flexflow.core import *  # noqa: F401,F403
import numpy as np
from flexflow.keras.datasets import mnist


def next_batch(idx, x_train, tensor, ffconfig, ffmodel):
    start = idx * ffconfig.batch_size
    tensor.set_tensor(ffmodel, x_train[start:start + ffconfig.batch_size])


def top_level_task(num_samples=2048, epochs=None):
    ffconfig = FFConfig()
    print("Python API batchSize(%d) workersPerNodes(%d) numNodes(%d)" % (
        ffconfig.batch_size, ffconfig.workers_per_node, ffconfig.num_nodes))
    ffmodel = FFModel(ffconfig)

    input_tensor = ffmodel.create_tensor(
        [ffconfig.batch_size, 784], DataType.DT_FLOAT)

    t = ffmodel.dense(input_tensor, 512, ActiMode.AC_MODE_RELU)
    t = ffmodel.dense(t, 512, ActiMode.AC_MODE_RELU)
    t = ffmodel.dense(t, 10)
    t = ffmodel.softmax(t)

    ffmodel.optimizer = SGDOptimizer(ffmodel, 0.01)
    ffmodel.compile(
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.METRICS_ACCURACY,
                 MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY])
    label_tensor = ffmodel.label_tensor

    (x_train, y_train), _ = mnist.load_data()
    x_train = x_train[:num_samples].reshape(-1, 784).astype("float32") / 255
    y_train = y_train[:num_samples].astype("int32").reshape(-1, 1)

    next_batch(0, x_train, input_tensor, ffconfig, ffmodel)
    next_batch(0, y_train, label_tensor, ffconfig, ffmodel)

    ffmodel.init_layers()
    epochs = epochs or ffconfig.epochs

    ts_start = ffconfig.get_current_time()
    for epoch in range(epochs):
        ffmodel.reset_metrics()
        iterations = num_samples // ffconfig.batch_size
        for it in range(iterations):
            ffconfig.begin_trace(111)
            next_batch(it, x_train, input_tensor, ffconfig, ffmodel)
            next_batch(it, y_train, label_tensor, ffconfig, ffmodel)
            ffmodel.forward()
            ffmodel.zero_gradients()
            ffmodel.backward()
            ffmodel.update()
            ffconfig.end_trace(111)
    ts_end = ffconfig.get_current_time()
    run_time = 1e-6 * (ts_end - ts_start)
    print("epochs %d, ELAPSED TIME = %.4fs, THROUGHPUT = %.2f samples/s\n" % (
        epochs, run_time, num_samples * epochs / run_time))

    # weight introspection after training (reference: get_layer_by_id /
    # get_bias_tensor tail of mnist_mlp_attach.py)
    dense1 = ffmodel.get_layer_by_id(0)
    bias = dense1.get_bias_tensor()
    print("dense1 bias shape:", bias.get_weights(ffmodel).shape)


if __name__ == "__main__":
    print("mnist mlp attach")
    top_level_task()
