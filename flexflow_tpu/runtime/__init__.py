"""Runtime services: checkpointing, recompile triggers, profiling,
strategy IO (TPU-native equivalents of reference src/runtime/ services +
the checkpoint upgrade SURVEY §5 calls for), and the fault-tolerance
layer (resilience: preemption-safe checkpointing, step guards,
retry/backoff, fault injection)."""
from .checkpoint import (  # noqa: F401
    load_checkpoint_meta,
    restore_checkpoint,
    save_checkpoint,
)
from .recompile import RecompileState, recompile_on_condition  # noqa: F401
from .resilience import (  # noqa: F401
    CheckpointManager,
    FaultInjector,
    InferenceTimeout,
    NonFiniteGradientsError,
    PreemptionSignal,
    ResilienceError,
    RetryPolicy,
    StepGuardConfig,
    TrainingPreempted,
    restore_latest,
    retry,
)
from .tuner import (  # noqa: F401
    StrategyTuner,
    SwapError,
    TunerConfig,
    strategy_fingerprint,
)
from .strategy_io import (  # noqa: F401
    apply_imported_strategy,
    export_strategy,
    import_strategy,
)
from .kvcache import (  # noqa: F401
    KVCacheConfig,
    KVCacheExhaustedError,
    PagePool,
)
from .verify import (  # noqa: F401
    CanaryConfig,
    CanaryMismatchError,
    CheckpointCorruptionError,
    InvariantViolationError,
    NotCompiledError,
    ServingConfigError,
    StrategyDivergenceError,
    VerificationError,
    verify_checkpoint,
    verify_strategy,
)
