"""DLRM training throughput on the real chip (reference config:
scripts/osdi22ae/dlrm.sh; 4 embedding tables of 1M x 64 + bottom/top MLPs)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _harness import run_throughput


def build(model, batch):
    from flexflow_tpu.models.dlrm import build_dlrm

    build_dlrm(model, batch)


if __name__ == "__main__":
    run_throughput(build, metric="dlrm_train_throughput",
                   batch=64, label_classes=2, spd=25)
