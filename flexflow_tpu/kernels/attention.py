"""Attention kernels: chunked online-softmax attention, Pallas flash
attention, and ring attention for sequence/context parallelism.

These replace the reference's cuDNN `cudnnMultiHeadAttnForward` path
(src/ops/attention.cc + attention.cu) with TPU-native kernels, and add the
long-context capability the reference lacks entirely (SURVEY §5: no ring
attention / sequence parallelism there).

Three tiers:
  * chunked_attention — lax.scan over KV chunks with running (max, sum,
    acc): O(seq) memory, jax-differentiable, what XLA fuses well. Default
    for long sequences on any backend.
  * flash_attention  — Pallas TPU kernel for the forward (blocked QK^T on
    the MXU, VMEM-resident accumulators), custom_vjp whose backward reuses
    chunked_attention's VJP (same math, exact gradients).
  * ring_attention   — shard_map over a seq-sharded mesh axis: each step
    computes a partial-attention block against the resident KV shard, then
    ppermutes KV around the ring (compute/ICI overlap is XLA's job);
    online-softmax merge keeps exactness. Differentiable through scan +
    ppermute.

Layout: (batch, seq, heads, head_dim) — "bshd".
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _chunk_scan(q, k, v, *, causal: bool, chunk_size: int, q_offset=0,
                kv_offset=0):
    """Online-softmax accumulation over KV chunks. q: (b, sq, h, d)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    dv = v.shape[-1]                  # v_head_dim may differ from qk's d
    n_chunks = max(1, (sk + chunk_size - 1) // chunk_size)
    pad = n_chunks * chunk_size - sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    scale = 1.0 / math.sqrt(d)
    kc = k.reshape(b, n_chunks, chunk_size, h, d).transpose(1, 0, 2, 3, 4)
    vc = v.reshape(b, n_chunks, chunk_size, h, dv).transpose(1, 0, 2, 3, 4)

    q_pos = q_offset + jnp.arange(sq)

    def body(carry, inputs):
        m_prev, l_prev, acc_prev = carry
        ci, k_blk, v_blk = inputs
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                       preferred_element_type=jnp.float32) * scale
        kv_pos = kv_offset + ci * chunk_size + jnp.arange(chunk_size)
        mask = kv_pos[None, :] <= (sk + kv_offset - 1)  # padding mask
        if causal:
            mask = mask & (kv_pos[None, :] <= q_pos[:, None])
        s = jnp.where(mask[None, None, :, :], s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)  # (b,h,q)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[..., None])
        l_new = l_prev * jnp.exp(m_prev - m_new) + jnp.sum(p, axis=-1)
        acc_new = acc_prev * jnp.exp(m_prev - m_new)[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        return (m_new, l_new, acc_new), None

    # Derive carries from q so they inherit q's varying manual axes when
    # running inside shard_map (fresh zeros would be unvarying and scan
    # would reject the carry type mismatch).
    zq = 0.0 * q.astype(jnp.float32).transpose(0, 2, 1, 3)  # (b,h,sq,d)
    m0 = zq[..., 0] + NEG_INF
    l0 = zq[..., 0]
    a0 = jnp.broadcast_to(zq[..., :1], zq.shape[:-1] + (dv,))  # (b,h,sq,dv)
    (m, l, acc), _ = lax.scan(
        body, (m0, l0, a0), (jnp.arange(n_chunks), kc, vc)
    )
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype), m, l


def chunked_attention(q, k, v, *, causal: bool = False, chunk_size: int = 256):
    """Memory-efficient exact attention. (b, s, h, d) -> (b, s, h, d)."""
    out, _, _ = _chunk_scan(q, k, v, causal=causal,
                            chunk_size=min(chunk_size, k.shape[1]))
    return out


# ---------------------------------------------------------------------------
# Counter-based dropout bits (shared by the Pallas kernels and the dense
# reference path)
# ---------------------------------------------------------------------------
# The mask is a pure function of (seeds, element index): each score element
# (row, q, k) hashes its flat index with two 32-bit seeds drawn from the op's
# PRNG key, and keeps the probability iff the hash clears the drop threshold.
# Because the bits are counter-based, the flash kernels regenerate the exact
# same mask per block (forward AND backward) from the block offsets alone —
# no O(s^2) mask tensor ever touches HBM — and the dense path can materialize
# the identical mask for parity tests. Index arithmetic is uint32 with
# wraparound on both sides, so the two paths can never disagree.

def _mix32(h):
    """murmur3-style 32-bit finalizer (jnp uint32, wraps)."""
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x7FEB352D)
    h = h ^ (h >> 15)
    h = h * jnp.uint32(0x846CA68B)
    h = h ^ (h >> 16)
    return h


def _keep_bits(idx, s0, s1):
    """uint32 hash of a flat element index under two uint32 seeds."""
    h = (idx * jnp.uint32(0x9E3779B1)) ^ s0
    h = _mix32(h)
    h = h ^ s1
    return _mix32(h)


def _drop_threshold(rate: float) -> int:
    """Keep an element iff hash >= threshold: P(drop) == rate."""
    return min(0xFFFFFFFF, int(round(float(rate) * 4294967296.0)))


def dropout_seeds(rng):
    """Two uint32 seeds for the counter-based mask, drawn from a jax
    PRNG key (deterministic per key; works for both old uint32[2] keys
    and new-style typed keys)."""
    return jax.random.bits(rng, (2,), jnp.uint32)


def attention_dropout_mask(seeds, rate: float, bh: int, sq: int, sk: int):
    """The FULL (bh, sq, sk) keep-mask the flash kernels apply blockwise.

    `bh` rows follow the folded (batch*heads, b-major) layout; the dense
    path reshapes its (b, h, sq, sk) probs tensor to match. This is the
    parity oracle: flash-with-dropout under `seeds` equals dense attention
    masked with exactly this array."""
    if rate <= 0.0:
        return jnp.ones((bh, sq, sk), bool)
    s0 = seeds[0].astype(jnp.uint32)
    s1 = seeds[1].astype(jnp.uint32)
    row = lax.broadcasted_iota(jnp.uint32, (bh, sq, sk), 0)
    qp = lax.broadcasted_iota(jnp.uint32, (bh, sq, sk), 1)
    kp = lax.broadcasted_iota(jnp.uint32, (bh, sq, sk), 2)
    idx = (row * jnp.uint32(sq) + qp) * jnp.uint32(sk) + kp
    return _keep_bits(idx, s0, s1) >= jnp.uint32(_drop_threshold(rate))


def _keep_tile(seed_ref, row_u, sq: int, sk: int, kv_off, tile_q: int,
               tile_k: int, rate: float):
    """In-kernel keep-mask for one (tile_q, tile_k) score tile of row
    `row_u` (uint32 scalar), with the kv axis offset by `kv_off` — the
    blockwise view of attention_dropout_mask."""
    s0 = seed_ref[0]
    s1 = seed_ref[1]
    qp = lax.broadcasted_iota(jnp.uint32, (tile_q, tile_k), 0)
    kp = jnp.uint32(kv_off) + lax.broadcasted_iota(
        jnp.uint32, (tile_q, tile_k), 1
    )
    idx = (row_u * jnp.uint32(sq) + qp) * jnp.uint32(sk) + kp
    return _keep_bits(idx, s0, s1) >= jnp.uint32(_drop_threshold(rate))


# ---------------------------------------------------------------------------
# Pallas flash-attention forward
# ---------------------------------------------------------------------------

def _causal_mask(s, *, q_axis: int, kv_axis: int, kv_offset=0):
    """Apply the causal mask to a score tile; used (axis-swapped) by the
    forward, dq, and dkv kernels so they can never disagree."""
    q_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, q_axis)
    kv_pos = kv_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, kv_axis)
    return jnp.where(kv_pos <= q_pos, s, NEG_INF)


def _flash_fwd_kernel(*refs, causal: bool, scale: float, g: int,
                      dropout: float = 0.0):
    """One program = g (batch*head) rows (g unrolled — measured 206→131 us
    at the bench shape by amortizing per-program overhead). Q/K/V for the
    whole row are VMEM resident (the fused path is capped to shapes where
    that holds), so each score tile is ONE MXU dot followed by a row
    softmax — no online accumulation. Dots take the inputs' dtype (bf16
    on the mixed-precision path = native MXU rate) and accumulate f32;
    scores/probs never touch HBM, which is what makes this beat the XLA
    dense path (134 MB of f32 scores per layer at the bench shape).

    dropout > 0 threads the counter-based keep-mask (_keep_tile) into the
    prob tile after the softmax statistics: l and the saved lse stay
    UNdropped (the standard flash-dropout scheme), only the p @ v
    contraction sees the masked/rescaled probs — so the mask never exists
    outside VMEM and the backward regenerates it bit-identically."""
    if dropout > 0.0:
        q_ref, k_ref, v_ref, seed_ref, o_ref, lse_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref = refs
        seed_ref = None
    inv_keep = 1.0 / (1.0 - dropout) if dropout > 0.0 else 1.0
    for i in range(g):
        q = q_ref[i]                      # (seq_q, d), input dtype
        k = k_ref[i]                      # (seq_k, d)
        sq, sk = q.shape[0], k.shape[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                         # (seq_q, seq_k) f32
        if causal:
            s = _causal_mask(s, q_axis=0, kv_axis=1)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        if dropout > 0.0:
            row_u = (pl.program_id(0) * g + i).astype(jnp.uint32)
            keep = _keep_tile(seed_ref, row_u, sq, sk, 0, sq, sk, dropout)
            p = jnp.where(keep, p * inv_keep, 0.0)
        o = jnp.dot(p.astype(q.dtype), v_ref[i],
                    preferred_element_type=jnp.float32)
        o_ref[i] = (o / jnp.maximum(l, 1e-30).astype(jnp.float32)).astype(
            o_ref.dtype
        )
        # log-sum-exp per query row, the backward's softmax residual;
        # stored (1, seq_q) — lanes-major, so the block shape (g, 1,
        # seq_q) satisfies the Mosaic (sublane, lane) tiling rule
        lse_ref[i] = (m + jnp.log(jnp.maximum(l, 1e-30))).T


def _flash_bwd_kernel(*refs, causal: bool, scale: float,
                      g: int, bk: int, dropout: float = 0.0):
    """Fused dq/dk/dv for g (batch*head) rows in ONE program: the prob
    tile is recomputed from q/k and the saved lse exactly once (the old
    split dq/dkv kernels each recomputed it), delta = rowsum(do*o) is
    computed in VMEM, and the transposed contractions for dk/dv avoid
    materializing pᵀ. Measured 541→306 us fwd+bwd at the bench shape.

    The kv axis is tiled at `bk` (unrolled — shapes are static): only a
    (seq_q, bk) slab of the score/prob/ds tiles is live at a time, which
    is what lets g=4 fit VMEM (full seq_k tiles capped g at 2; round-2
    measured the full-tile g=4 variant REGRESSING on VMEM pressure).

    dropout > 0 regenerates the forward's counter-based keep-mask per
    (row, kv-block) — same seeds, same indices, so bit-identical — and
    applies it where the chain rule puts it: dP = D ∘ (dO Vᵀ) before the
    softmax backward, and dV = (P ∘ D)ᵀ dO. delta = rowsum(dO ∘ O)
    already equals rowsum(P ∘ dP) under dropout, so the ds formula is
    unchanged."""
    if dropout > 0.0:
        (q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, seed_ref,
         dq_ref, dk_ref, dv_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
         dq_ref, dk_ref, dv_ref) = refs
        seed_ref = None
    inv_keep = 1.0 / (1.0 - dropout) if dropout > 0.0 else 1.0
    n_blocks = (k_ref.shape[1] + bk - 1) // bk
    sk_total = k_ref.shape[1]
    for i in range(g):
        q = q_ref[i]
        do = do_ref[i]
        delta = jnp.sum(
            do.astype(jnp.float32) * o_ref[i].astype(jnp.float32),
            axis=-1, keepdims=True,
        )                                 # (seq_q, 1)
        lse_col = lse_ref[i].T            # lse (1, seq_q) -> column
        dq_acc = None
        for j in range(n_blocks):
            if causal and j * bk > q_ref.shape[1] - 1:
                # block entirely above the diagonal: p == 0 exactly —
                # skip its four dots, just zero the dk/dv slabs
                dk_ref[i, j * bk:(j + 1) * bk] = jnp.zeros_like(
                    dk_ref[i, j * bk:(j + 1) * bk])
                dv_ref[i, j * bk:(j + 1) * bk] = jnp.zeros_like(
                    dv_ref[i, j * bk:(j + 1) * bk])
                continue
            k = k_ref[i, j * bk:(j + 1) * bk]
            v = v_ref[i, j * bk:(j + 1) * bk]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                     # (seq_q, bk)
            if causal:
                s = _causal_mask(s, q_axis=0, kv_axis=1, kv_offset=j * bk)
            p = jnp.exp(s - lse_col)
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if dropout > 0.0:
                row_u = (pl.program_id(0) * g + i).astype(jnp.uint32)
                keep = _keep_tile(seed_ref, row_u, q.shape[0], sk_total,
                                  j * bk, q.shape[0], k.shape[0], dropout)
                dp = jnp.where(keep, dp * inv_keep, 0.0)
                pb = jnp.where(keep, p * inv_keep, 0.0).astype(q.dtype)
            else:
                pb = p.astype(q.dtype)
            ds = p * (dp - delta)
            dsb = ds.astype(q.dtype)
            dq = jnp.dot(dsb, k, preferred_element_type=jnp.float32)
            dq_acc = dq if dq_acc is None else dq_acc + dq
            dk = jax.lax.dot_general(
                dsb, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dk_ref[i, j * bk:(j + 1) * bk] = (dk * scale).astype(dk_ref.dtype)
            dv = jax.lax.dot_general(
                pb, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dv_ref[i, j * bk:(j + 1) * bk] = dv.astype(dv_ref.dtype)
        dq_ref[i] = (dq_acc * scale).astype(dq_ref.dtype)


try:  # Pallas import is lazy-safe: CPU tests run interpret mode
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    HAS_PALLAS = True
except Exception:  # pragma: no cover
    HAS_PALLAS = False


def _bhsd_to_fold(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _fold_to_bhsd(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


# The fused path keeps the full (seq_q, seq_k) f32 score tile plus Q/K/V
# in VMEM per program; past this limit fall back to chunked_attention
# (long-context single-chip) or ring attention (sequence-parallel).
FLASH_FUSED_MAX_TILE = 1024 * 1024


def flash_supported(seq_q: int, seq_k: int) -> bool:
    return seq_q * seq_k <= FLASH_FUSED_MAX_TILE


def _pick_g(bh: int, sq: int, sk: int, budget: int, cap: int) -> int:
    """Rows per program: batch (b*h) rows until the f32 score tiles hit
    the VMEM budget (floats) or the measured sweet spot `cap`. Measured on
    v5e at 512x512/d64: fwd best at g=4, fused bwd (4 extra tiles live)
    at g=2; g=8 regresses — VMEM pressure beats overhead amortization."""
    g = 1
    for cand in (2, 4, 8):
        if cand > cap or bh % cand or cand * sq * sk > budget:
            break
        g = cand
    return g


def _flash_fwd_folded(qf, kf, vf, *, causal: bool, interpret: bool,
                      dropout: float = 0.0, seeds=None):
    """Core forward on (b*h, s, d) folded operands."""
    bh, sq, d = qf.shape
    sk = kf.shape[1]
    dv = vf.shape[-1]                 # v_head_dim may differ from qk's d
    g = _pick_g(bh, sq, sk, budget=2 * 1024 * 1024, cap=4)
    scale = 1.0 / math.sqrt(d)
    kernel = functools.partial(_flash_fwd_kernel, causal=causal, scale=scale,
                               g=g, dropout=dropout)
    in_specs = [
        pl.BlockSpec((g, sq, d), lambda i: (i, 0, 0)),
        pl.BlockSpec((g, sk, d), lambda i: (i, 0, 0)),
        pl.BlockSpec((g, sk, dv), lambda i: (i, 0, 0)),
    ]
    args = (qf, kf, vf)
    if dropout > 0.0:
        # two uint32 seeds ride in SMEM; the mask itself is regenerated
        # per score tile from counters (never materialized in HBM)
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args = args + (jnp.asarray(seeds, jnp.uint32),)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh // g,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((g, sq, dv), lambda i: (i, 0, 0)),
            pl.BlockSpec((g, 1, sq), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, dv), qf.dtype),
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return out, lse


def _flash_bwd_folded(qf, kf, vf, of, lse, dof, *, causal: bool,
                      interpret: bool, dropout: float = 0.0, seeds=None):
    """Core backward on (b*h, s, d) folded operands."""
    bh, sq, d = qf.shape
    sk = kf.shape[1]
    dv_d = vf.shape[-1]               # v_head_dim may differ from qk's d
    # Default: FULL kv tile at g=2. The kv-blocked variant (bk < sk, which
    # halves live VMEM and admits g=4) was the round-2 verdict's suggested
    # retry; measured on v5e at the bench shape (benchmarks/
    # flash_kernel_sweep.py, harness floor subtracted): g2/full 248 us,
    # g4/bk256 284 us, g4/full 446 us, g8/bk128 297 us — the full-tile g=2
    # schedule stays the fastest, so blocking ships as an env-tunable
    # (FF_FLASH_BWD_BK / FF_FLASH_BWD_G, 0 = auto) rather than the default.
    bk = int(os.environ.get("FF_FLASH_BWD_BK", "0")) or sk
    if bk <= 0 or bk > sk:
        bk = sk
    gg = int(os.environ.get("FF_FLASH_BWD_G", "0"))
    if gg <= 0 or bh % gg:
        # invalid override (non-divisor g would truncate the grid and leave
        # gradient rows unwritten) -> auto
        gg = _pick_g(bh, sq, bk, budget=1024 * 1024, cap=2)
    scale = 1.0 / math.sqrt(d)
    in_specs = [
        pl.BlockSpec((gg, sq, d), lambda i: (i, 0, 0)),
        pl.BlockSpec((gg, sk, d), lambda i: (i, 0, 0)),
        pl.BlockSpec((gg, sk, dv_d), lambda i: (i, 0, 0)),
        pl.BlockSpec((gg, sq, dv_d), lambda i: (i, 0, 0)),
        pl.BlockSpec((gg, sq, dv_d), lambda i: (i, 0, 0)),
        pl.BlockSpec((gg, 1, sq), lambda i: (i, 0, 0)),
    ]
    args = (qf, kf, vf, dof, of, lse)
    if dropout > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args = args + (jnp.asarray(seeds, jnp.uint32),)
    dq, dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_kernel, causal=causal, scale=scale,
                          g=gg, bk=bk, dropout=dropout),
        grid=(bh // gg,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((gg, sq, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((gg, sk, d), lambda i: (i, 0, 0)),
            pl.BlockSpec((gg, sk, dv_d), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), qf.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), kf.dtype),
            jax.ShapeDtypeStruct((bh, sk, dv_d), vf.dtype),
        ],
        interpret=interpret,
    )(*args)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_folded_core(qf, kf, vf, seeds, causal, interpret, dropout):
    out, _ = _flash_fwd_folded(qf, kf, vf, causal=causal,
                               interpret=interpret, dropout=dropout,
                               seeds=seeds)
    return out


def _flash_folded_vjp_fwd(qf, kf, vf, seeds, causal, interpret, dropout):
    out, lse = _flash_fwd_folded(qf, kf, vf, causal=causal,
                                 interpret=interpret, dropout=dropout,
                                 seeds=seeds)
    return out, (qf, kf, vf, out, lse, seeds)


def _flash_folded_vjp_bwd(causal, interpret, dropout, res, g):
    qf, kf, vf, out, lse, seeds = res
    dq, dk, dv = _flash_bwd_folded(qf, kf, vf, out, lse, g, causal=causal,
                                   interpret=interpret, dropout=dropout,
                                   seeds=seeds)
    return dq, dk, dv, None  # seeds are integral: no cotangent


_flash_folded_core.defvjp(_flash_folded_vjp_fwd, _flash_folded_vjp_bwd)


def flash_attention_folded(qf, kf, vf, causal: bool = False,
                           interpret: bool = False, *,
                           dropout: float = 0.0, seeds=None):
    """flash_attention on PRE-FOLDED (batch*heads, seq, head_dim)
    operands. The MHA op's fast path projects q/k/v straight into this
    layout (einsum "bse,ehd->bhsd" + free reshape), so the per-layer
    fold/unfold transposes of the bshd wrapper never materialize.

    dropout/seeds thread attention dropout INTO the kernels: the
    counter-based keep-mask (attention_dropout_mask with these `seeds`,
    two uint32s from dropout_seeds(rng)) is regenerated per VMEM tile in
    the forward and the backward, so dropout no longer forces the
    dense-materialized path."""
    assert flash_supported(qf.shape[1], kf.shape[1]), (
        "sequence too long for the fused VMEM tile — use chunked_attention "
        "or ring_attention"
    )
    dropout = float(dropout)
    if dropout > 0.0 and seeds is None:
        raise ValueError("flash dropout needs seeds (dropout_seeds(rng))")
    if seeds is None:
        seeds = jnp.zeros((2,), jnp.uint32)
    return _flash_folded_core(qf, kf, vf, seeds, causal, interpret, dropout)


def flash_attention(q, k, v, causal: bool = False, block_q: int = 256,
                    block_k: int = 256, interpret: bool = False, *,
                    dropout: float = 0.0, seeds=None):
    """Fused Pallas attention: forward AND backward keep scores/probs in
    VMEM (the backward recomputes the prob tile from the saved per-row
    log-sum-exp — the standard flash-attention scheme) and batch several
    (batch*head) rows per program (_pick_g). Requires
    flash_supported(seq_q, seq_k); block_q/block_k are accepted for
    signature stability but rows are processed as whole tiles. Routes
    through the folded core, so gradients and RNG-threaded dropout
    (dropout/seeds) behave identically to flash_attention_folded."""
    b, _, h, _ = q.shape
    out = flash_attention_folded(
        _bhsd_to_fold(q), _bhsd_to_fold(k), _bhsd_to_fold(v),
        causal=causal, interpret=interpret, dropout=dropout, seeds=seeds,
    )
    return _fold_to_bhsd(out, b, h)


def local_attention(q, k, v, *, causal: bool = False,
                    interpret: bool = False):
    """The single device-local streaming dispatch: fused Pallas kernel on
    TPU while its VMEM tile fits, chunked scan otherwise. Both the MHA
    op's streaming branch (ops/attention.py) and ulysses_attention route
    through here so the selection policy cannot drift between them."""
    if (HAS_PALLAS and not interpret and jax.default_backend() == "tpu"
            and flash_supported(q.shape[1], k.shape[1])):
        return flash_attention(q, k, v, causal)
    return chunked_attention(q, k, v, causal=causal)


# ---------------------------------------------------------------------------
# Ring attention (sequence/context parallelism over a mesh axis)
# ---------------------------------------------------------------------------

def ulysses_attention(q, k, v, axis_name: str, *, causal: bool = False,
                      interpret: bool = False):
    """DeepSpeed-Ulysses-style sequence parallelism: q/k/v arrive sharded
    along the sequence dim over `axis_name` (LOCAL shards, inside
    shard_map). One all_to_all re-shards sequence->heads so each device
    holds the FULL sequence for num_heads/n heads, local fused attention
    runs, and a second all_to_all restores the seq sharding. Two
    all_to_alls over ICI instead of ring's n-1 ppermutes — wins when
    heads divide the axis and the full-seq score tile still fits.

    No reference equivalent (SURVEY §5: sequence parallelism absent
    there); the head-scatter recipe follows the public Ulysses pattern
    (PAPERS.md)."""
    # psum of the literal 1 constant-folds to the axis size on every
    # jax we support; lax.axis_size only exists on newer releases
    n = getattr(lax, "axis_size", lambda a: lax.psum(1, a))(axis_name)
    h = q.shape[2]
    assert h % n == 0, f"heads {h} must divide the {axis_name} axis {n}"
    # (b, s/n, h, d) -> (b, s, h/n, d)
    def scatter_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def gather_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    out = local_attention(qh, kh, vh, causal=causal, interpret=interpret)
    return gather_heads(out)


def ring_attention(q, k, v, axis_name: str, *, causal: bool = False,
                   chunk_size: int = 256):
    """Exact attention when q/k/v are sharded along the sequence dim over
    `axis_name`. Must be called inside shard_map (q/k/v are the LOCAL
    shards). Each of the `n` steps attends against the resident KV shard,
    then rotates KV one hop around the ring (lax.ppermute over ICI),
    merging partial results with online softmax.

    No reference equivalent — this is the TPU build's first-class CP
    (SURVEY §5 gap); the blockwise formulation follows the public
    ring-attention recipe (PAPERS.md)."""
    # psum of the literal 1 constant-folds to the axis size on every
    # jax we support; lax.axis_size only exists on newer releases
    n = getattr(lax, "axis_size", lambda a: lax.psum(1, a))(axis_name)
    idx = lax.axis_index(axis_name)
    b, sq_local, h, d = q.shape

    def step(carry, i):
        m, l, acc, k_cur, v_cur = carry
        # whose shard is resident this step
        src = (idx - i) % n
        kv_off = src * sq_local
        out_blk, m_blk, l_blk = _chunk_scan(
            q, k_cur, v_cur, causal=causal,
            chunk_size=min(chunk_size, sq_local),
            q_offset=idx * sq_local, kv_offset=kv_off,
        )
        acc_blk = out_blk.transpose(0, 2, 1, 3).astype(jnp.float32) * \
            jnp.maximum(l_blk[..., None], 1e-30)
        m_new = jnp.maximum(m, m_blk)
        alpha_old = jnp.exp(m - m_new)
        alpha_blk = jnp.exp(m_blk - m_new)
        l_new = l * alpha_old + l_blk * alpha_blk
        acc_new = acc * alpha_old[..., None] + acc_blk * alpha_blk[..., None]
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_nxt, v_nxt), None

    zq = 0.0 * q.astype(jnp.float32).transpose(0, 2, 1, 3)  # (b,h,sq,d)
    m0 = zq[..., 0] + NEG_INF
    l0 = zq[..., 0]
    a0 = jnp.broadcast_to(zq[..., :1], zq.shape[:-1] + (v.shape[-1],))
    (m, l, acc, _, _), _ = lax.scan(step, (m0, l0, a0, k, v), jnp.arange(n))
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)
