"""Runtime configuration.

TPU-native analogue of the reference FFConfig (include/flexflow/config.h:92-160,
parse_args src/runtime/model.cc:3556). Instead of Legion `-ll:gpu` worker
counts, we describe a TPU mesh: number of chips visible to this process plus a
logical multi-host topology for the strategy search. Flags keep the reference's
spellings so reference launch scripts port over directly.
"""
from __future__ import annotations

import dataclasses
import sys
from typing import List, Optional

import jax

from .ff_types import CompMode


@dataclasses.dataclass
class FFConfig:
    """Global run configuration.

    Mirrors reference config.h:92-160 field-for-field where meaningful on TPU;
    `workersPerNode` counts TPU chips instead of GPUs.
    """

    epochs: int = 1
    batch_size: int = 64
    numNodes: int = 1
    workersPerNode: int = 0  # 0 = all visible devices
    cpusPerNode: int = 1
    learning_rate: float = 0.01
    weight_decay: float = 0.0001
    # Strategy-search knobs (reference config.h:128-160)
    search_budget: int = -1
    search_alpha: float = 1.2
    # Cost-model side of comm/compute overlap: when True the search costs
    # overlappable collectives (weight-grad syncs that are statically
    # independent of the backward critical path) at
    # max(0, comm - hideable_compute) instead of additively, so it
    # PREFERS strategies whose collectives hide
    # (search/cost_model.py; analysis/collectives.overlappable_grad_syncs
    # is the static proof). Off by default so searched strategies stay
    # reproducible against earlier rounds; --overlap-backward-update
    # turns both sides on.
    search_overlap_backward_update: bool = False
    # Slice-loss survivability bias (search/survivability.py): on
    # hierarchical multi-slice machines the search multiplies a
    # candidate's cost by 1 + penalty * (fraction of weight bytes whose
    # shards cross the slice boundary), preferring strategies where only
    # data-parallel replicas span slices — a preemption then shrinks the
    # run instead of forcing a full reshard (FFA601 lints what remains).
    # -1.0 = auto: 0.25 on hierarchical multi-node machines, 0 elsewhere.
    # 0 disables; larger = stronger preference (still not a hard
    # constraint — a cross-slice strategy that is MUCH faster per step
    # can outbid the penalty).
    search_survivability_penalty: float = -1.0
    # Executed-step side (reference config.h:133 overlap_backward_update):
    # decompose the data-parallel gradient all-reduce into per-weight
    # reduce-scatter + sharded optimizer update + all-gather of updated
    # params, so each layer's collective overlaps earlier layers'
    # backward matmuls and optimizer state shards ZeRO-1 style
    # (parallel/executor.py set_overlap_grad_sync). Numerically
    # equivalent to the all-reduce step; on by default (inert on a
    # single chip / data degree 1).
    overlap_backward_update: bool = True
    computationMode: CompMode = CompMode.COMP_MODE_TRAINING
    only_data_parallel: bool = False
    enable_sample_parallel: bool = True
    enable_parameter_parallel: bool = False
    enable_attribute_parallel: bool = False
    enable_inplace_optimizations: bool = False
    # TPU addition: sequence/context parallelism as a first-class strategy
    enable_sequence_parallel: bool = False
    # Manual strategy degrees (no-search path). data_parallel_degree 0 =
    # fill remaining devices. The Unity search overrides these.
    tensor_parallel_degree: int = 1
    sequence_parallel_degree: int = 1
    # Pipeline parallelism (TPU addition — the reference's OP_PIPELINE is
    # enum-only): stages for transformer_blocks stacks, and microbatches
    # per pipeline flush (0 = one per stage).
    pipeline_parallel_degree: int = 1
    num_microbatches: int = 0
    # FSDP/ZeRO weight sharding (parallel/weight_sharding.py): shard
    # parameters + optimizer state this many ways over the "fsdp" mesh
    # axis, carved out of the data-parallel workers (must divide the data
    # degree; clamped otherwise). 1 = fully replicated weights (the old
    # behavior). The Unity memory-lambda search can also introduce weight
    # sharding on its own (search/substitution.py fsdp_shard_weights).
    fsdp_degree: int = 1
    # Recompute memory-heavy op internals (attention scores/probs) in the
    # backward instead of saving them (jax.checkpoint). Exact math; trades
    # FLOPs for HBM. Off by default — at benchmark shapes the stored-probs
    # backward is faster (measured 316 vs 245 samples/s at seq 512); turn
    # on for long sequences / big models where residuals exceed HBM.
    remat: bool = False
    expert_parallel_degree: int = 1
    # bf16 compute with f32 master weights (TPU-native mixed precision).
    # Off by default so numerical-alignment tests match f32 references;
    # benchmarks turn it on.
    allow_mixed_precision: bool = False
    # Store gradients in bf16 under mixed precision (the standard AMP
    # recipe: half-width grad store + f32 master weights; the f32->bf16
    # convert fuses into the grad matmuls' epilogues). Measured
    # single-chip-neutral on the Transformer bench (XLA already fuses the
    # f32 grad path); the win is cross-chip grad reduce-scatters riding
    # ICI/DCN at half width. None = follow allow_mixed_precision; set
    # False to force f32 gradient storage.
    bf16_grads: Optional[bool] = None
    # End-to-end static drift budget (analysis/precision.py FFA705): the
    # accumulated ulp-scaled quantization error a searched strategy may
    # statically incur along its longest path. None = the pass default
    # (precision.DEFAULT_DRIFT_BUDGET). runtime/verify.py derives the
    # differential verifier's per-dtype tolerances from the SAME budget
    # (tolerance_from_budget), so tightening it makes both the static
    # lint and the runtime check stricter together.
    precision_drift_budget: Optional[float] = None
    simulator_work_space_size: int = 64 * 1024 * 1024
    search_num_nodes: int = -1
    search_num_workers: int = -1
    base_optimize_threshold: int = 10
    enable_control_replication: bool = True
    python_data_loader_type: int = 2
    perform_fusion: bool = False
    profiling: bool = False
    # Unity search costs ops by on-device microbenchmarks instead of the
    # analytic roofline (reference: the Simulator always measures,
    # simulator.cc:489; here it's opt-in because it pays real compiles)
    measure_operator_costs: bool = False
    # persist measured-search microbenchmarks across runs (reference: the
    # Simulator's cached measurements); empty = in-memory only
    measured_cache_path: str = ""
    export_strategy_file: str = ""
    import_strategy_file: str = ""
    export_strategy_computation_graph_file: str = ""
    substitution_json_path: Optional[str] = None
    machine_model_version: int = 0
    machine_model_file: str = ""
    simulator_segment_size: int = 16777216
    simulator_max_num_segments: int = 1
    enable_propagation: bool = False
    perform_memory_search: bool = False
    device_mem: int = 0  # bytes of HBM per chip for the memory-aware search
    seed: int = 0
    iterations: int = 1
    # Steps fused into one XLA dispatch by fit() (lax.scan driver — the
    # Legion trace-replay analog). 1 = one host dispatch per batch.
    iterations_per_dispatch: int = 1

    def __post_init__(self):
        if self.workersPerNode == 0:
            try:
                self.workersPerNode = max(1, jax.local_device_count())
            except Exception:  # pragma: no cover - no backend at all
                self.workersPerNode = 1
        if self.numNodes == 1:
            try:
                # multi-host (runtime/distributed.py): one "node" per
                # process, like the reference's one-Legion-rank-per-host
                self.numNodes = max(1, jax.process_count())
            except Exception:  # pragma: no cover  # fflint: disable=FFL002
                pass
        argv = sys.argv[1:]
        if argv:
            self.parse_args(argv)

    # -- reference: model.cc:3556 parse_args ------------------------------
    def parse_args(self, argv: List[str]) -> None:
        i = 0
        take = lambda: argv[i + 1]  # noqa: E731
        while i < len(argv):
            a = argv[i]
            try:
                if a in ("-e", "--epochs"):
                    self.epochs = int(take()); i += 1
                elif a in ("-b", "--batch-size"):
                    self.batch_size = int(take()); i += 1
                elif a == "--lr" or a == "-lr":
                    self.learning_rate = float(take()); i += 1
                elif a == "--wd" or a == "-wd":
                    self.weight_decay = float(take()); i += 1
                elif a in ("-p", "--print-freq"):
                    i += 1
                elif a in ("-ll:gpu", "-ll:tpu"):
                    self.workersPerNode = int(take()); i += 1
                elif a == "-ll:cpu":
                    self.cpusPerNode = int(take()); i += 1
                elif a == "--nodes":
                    self.numNodes = int(take()); i += 1
                elif a == "--budget" or a == "--search-budget":
                    self.search_budget = int(take()); i += 1
                elif a == "--alpha" or a == "--search-alpha":
                    self.search_alpha = float(take()); i += 1
                elif a == "--only-data-parallel":
                    self.only_data_parallel = True
                elif a == "--enable-parameter-parallel":
                    self.enable_parameter_parallel = True
                elif a == "--enable-attribute-parallel":
                    self.enable_attribute_parallel = True
                elif a == "--enable-sequence-parallel":
                    self.enable_sequence_parallel = True
                elif a == "--fusion":
                    self.perform_fusion = True
                elif a == "--profiling":
                    self.profiling = True
                elif a == "--measured-search":
                    self.measure_operator_costs = True
                elif a == "--measured-cache":
                    self.measured_cache_path = take(); i += 1
                elif a == "--search-num-nodes":
                    self.search_num_nodes = int(take()); i += 1
                elif a == "--search-num-workers":
                    self.search_num_workers = int(take()); i += 1
                elif a == "--export" or a == "--export-strategy":
                    self.export_strategy_file = take(); i += 1
                elif a == "--import" or a == "--import-strategy":
                    self.import_strategy_file = take(); i += 1
                elif a == "--memory-search":
                    self.perform_memory_search = True
                elif a == "--overlap-backward-update":
                    self.overlap_backward_update = True
                    self.search_overlap_backward_update = True
                elif a == "--no-overlap-backward-update":
                    self.overlap_backward_update = False
                    self.search_overlap_backward_update = False
                elif a == "--fsdp-degree":
                    self.fsdp_degree = int(take()); i += 1
                elif a == "--machine-model-version":
                    self.machine_model_version = int(take()); i += 1
                elif a == "--machine-model-file":
                    self.machine_model_file = take(); i += 1
                elif a == "--substitution-json":
                    self.substitution_json_path = take(); i += 1
                elif a == "--simulator-workspace-size":
                    self.simulator_work_space_size = int(take()); i += 1
                elif a == "--iterations":
                    self.iterations = int(take()); i += 1
                elif a == "--iterations-per-dispatch":
                    self.iterations_per_dispatch = int(take()); i += 1
                # silently skip unknown flags (Legion-style passthrough)
            except (IndexError, ValueError):
                pass
            i += 1

    # snake_case aliases matching the reference cffi property names
    # (flexflow_cffi.py:526 FFConfig.batch_size/workers_per_node/num_nodes),
    # so `from flexflow.core import *` scripts read config fields verbatim.
    @property
    def workers_per_node(self) -> int:
        if self.workersPerNode > 0:
            return self.workersPerNode
        return len(jax.devices())

    @property
    def num_nodes(self) -> int:
        return self.numNodes

    @property
    def cpus_per_node(self) -> int:
        return self.cpusPerNode

    @property
    def numWorkers(self) -> int:
        """Total chips in the (possibly hypothetical) machine."""
        if self.search_num_nodes > 0 and self.search_num_workers > 0:
            return self.search_num_nodes * self.search_num_workers
        return self.numNodes * self.workersPerNode

    # getter-method spellings used by older reference scripts
    # (bootcamp_demo/ff_alexnet_cifar10.py calls ffconfig.get_batch_size()
    # etc., predating the cffi property API at flexflow_cffi.py:536-549)
    def get_batch_size(self) -> int:
        return self.batch_size

    def get_epochs(self) -> int:
        return self.epochs

    def get_workers_per_node(self) -> int:
        return self.workers_per_node

    def get_num_nodes(self) -> int:
        return self.num_nodes

    def get_current_time(self) -> float:
        import time

        return time.time() * 1e6  # microseconds, like Realm::Clock

    def begin_trace(self, trace_id: int) -> None:
        """reference: flexflow_cffi.py:2093 (Legion trace capture around a
        training iteration). XLA's compiled-executable cache plays that
        role here — the first jitted call traces, later ones replay — so
        these are accepted no-ops for drop-in script compat."""

    def end_trace(self, trace_id: int) -> None:
        """See begin_trace."""


@dataclasses.dataclass
class FFIterationConfig:
    """Per-iteration config (reference: config.h:162-167)."""

    seq_length: int = -1

    def reset(self):
        self.seq_length = -1
