"""Built-in parallelization strategies applied to a PCG.

The reference reaches a parallelized PCG either through the Unity search or
through `--only-data-parallel` lowering (model.cc:2637-2642, which inserts a
batch-dim Repartition). These passes are the no-search equivalents: they
assign degrees/parallel_idx to ParallelTensor dims in place. The search
(flexflow_tpu/search/) produces the same annotations via MachineViews.

Axis indices refer to the mesh axis list (parallel/mesh.py AXIS_NAMES order
as built for the run).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from ..ff_types import OperatorType
from ..pcg.graph import Graph
from ..pcg.op import PCGOp


def apply_data_parallel(graph: Graph, degree: int, axis_idx: int = 0) -> None:
    """Shard dim 0 (sample dim) of every activation tensor by `degree`.

    reference: FFModel::get_basic_data_parallel_config (model.h:250) +
    the OP_INPUT Repartition insertion (model.cc:2637)."""
    if degree <= 1:
        return
    tensors = list(graph.input_tensors())
    for op in graph.ops:
        tensors.extend(op.outputs)
    for t in tensors:
        if t.num_dims == 0:
            continue
        d0 = t.dims[0]
        if d0.size % degree == 0 and not d0.is_replica_dim:
            d0.degree = degree
            d0.parallel_idx = axis_idx
    # weights stay replicated (degree 1) — XLA all-reduces their grads.


def assign_mesh_axes(graph: Graph, max_devices: int) -> Dict[str, int]:
    """Lower a searched PCG (tensor degrees set by substitutions, views by
    the DP) to GSPMD mesh axes.

    The reference executes heterogeneous per-op MachineViews via Legion task
    placement; under one SPMD program we map degrees onto named mesh axes:
    sample-dim degrees -> "data", channel/head/weight degrees -> "model",
    WeightShard-targeted weight degrees -> "fsdp", axis_tag-carrying
    degrees (expert/seq substitution generators) -> their named axis,
    with the expert axis absorbing the data axis when their degrees
    match (the dispatch all-to-all reshards within the same device
    group). A dim whose degree
    doesn't equal its axis size can't shard evenly under NamedSharding and
    is demoted to replicated (round-1 lowering limit; the reference's
    fully heterogeneous placements would need per-segment programs).
    Block-stack (pipeline) ops keep their stage axis: their num_stages
    params were fixed at graph build from config, so the mesh must carry a
    matching "pipe" axis or the GPipe path silently degrades to the
    sequential scan.

    FSDP: when the fsdp degree divides the batch degree (the ZeRO case
    the fsdp substitutions construct — batch and weights sharded over the
    SAME workers), the fsdp axis is carved out of the data axis: mesh
    data size becomes data_deg/fsdp_deg and the batch dim lowers to the
    ("data", "fsdp") tuple (parallel/mesh.py). Otherwise fsdp is its own
    device factor (weights sharded, batch replicated over the group —
    memory-only sharding, still exact)."""
    from .weight_sharding import fsdp_degree_of, sharded_weight_records

    pipe_deg = 1
    for op in graph.ops:
        stages = getattr(op.params, "num_stages", 1)
        if stages > 1:
            pipe_deg = max(pipe_deg, stages)
    fsdp_deg = fsdp_degree_of(graph)
    fsdp_weights = sharded_weight_records(graph) if fsdp_deg > 1 else {}
    data_deg, model_deg = 1, 1
    expert_deg, seq_deg = 1, 1
    tensors = list(graph.input_tensors())
    for op in graph.ops:
        tensors.extend(op.outputs)
        tensors.extend(op.weights)
    # classify: activation dim0 = data; fsdp-targeted weight dims = fsdp;
    # axis_tag-carrying dims (the expert/seq substitution generators) =
    # their named axis; everything else = model
    weight_guids = {w.guid for op in graph.ops for w in op.weights}
    for t in tensors:
        is_weight = t.guid in weight_guids
        for i, d in enumerate(t.dims):
            if d.degree <= 1 or d.is_replica_dim:
                continue
            tag = getattr(d, "axis_tag", None)
            if tag == "expert":
                expert_deg = max(expert_deg, d.degree)
            elif tag == "seq":
                seq_deg = max(seq_deg, d.degree)
            elif i == 0 and not is_weight:
                data_deg = max(data_deg, d.degree)
            elif is_weight and t.guid in fsdp_weights \
                    and d.degree == fsdp_deg:
                pass  # owned by the fsdp axis, not model
            else:
                model_deg = max(model_deg, d.degree)

    def devices_needed(dd: int, fd: int, ed: int) -> int:
        # fsdp rides the data workers when it divides the batch degree
        # (ZeRO); otherwise it's an extra device factor. The expert axis
        # absorbs the data axis when their degrees match (the dispatch
        # all-to-all reshards within the same device group — merge rule
        # below); otherwise it is its own orthogonal factor, like seq.
        e = 1 if ed == dd else ed
        if fd > 1 and dd % fd == 0:
            return dd * e * model_deg * pipe_deg * seq_deg
        return dd * fd * e * model_deg * pipe_deg * seq_deg

    # shrink data, then model, then seq, then drop fsdp, then expert,
    # before sacrificing the user's requested pipeline degree; pipe is
    # last. Exception: while the expert dispatch rides the data axis
    # (equal degrees — the all-to-all NEEDS its input batch-sharded at
    # the expert degree), shrink model first so the pair survives.
    while devices_needed(data_deg, fsdp_deg, expert_deg) > max_devices \
            and model_deg > 1 and expert_deg > 1 and expert_deg == data_deg:
        model_deg //= 2
    while devices_needed(data_deg, fsdp_deg, expert_deg) > max_devices \
            and data_deg > 1:
        data_deg //= 2
    while devices_needed(data_deg, fsdp_deg, expert_deg) > max_devices \
            and model_deg > 1:
        model_deg //= 2
    while devices_needed(data_deg, fsdp_deg, expert_deg) > max_devices \
            and seq_deg > 1:
        seq_deg //= 2
    if devices_needed(data_deg, fsdp_deg, expert_deg) > max_devices \
            and fsdp_deg > 1:
        fsdp_deg = 1  # weight dims demote to replicated below
        fsdp_weights = {}
    if devices_needed(data_deg, fsdp_deg, expert_deg) > max_devices \
            and expert_deg > 1:
        expert_deg = 1  # expert dims demote to replicated below
    if devices_needed(data_deg, fsdp_deg, expert_deg) > max_devices:
        from .. import obs

        obs.progress(
            f"[flexflow_tpu] warning: dropping pipeline degree {pipe_deg} "
            f"(needs {pipe_deg} devices, have {max_devices}); block-stack "
            f"ops fall back to the sequential scan",
            name="pipeline_degree_dropped", cat="compile",
            requested=pipe_deg, devices=max_devices,
        )
        pipe_deg = 1  # ops degrade to the sequential scan path, still correct
    # WeightShard reconciliation: the fsdp axis carries ONE degree
    # (fsdp_degree_of: largest wins), so nodes at any other degree —
    # mixed-degree winners — and every node once the ladder dropped fsdp
    # would come out of the demotion below inert (declared shard degree
    # with no sharded weight dims: FFA207). Back them out the way the
    # fsdp_unshard_weights substitution does: restore the target's
    # replicated weights and splice the identity node out of the graph.
    stale_ws = [op for op in graph.ops
                if op.op_type == OperatorType.OP_WEIGHT_SHARD
                and (fsdp_deg == 1 or op.params.shard_degree != fsdp_deg)]
    if stale_ws:
        from .weight_sharding import unshard_op_weights, weight_shard_target

        drop = {op.guid for op in stale_ws}
        for ws in stale_ws:
            target = weight_shard_target(ws)
            if target is not None:
                unshard_op_weights(target)
            out_t, in_t = ws.outputs[0], ws.inputs[0]
            for o in graph.ops:
                for i, t in enumerate(o.inputs):
                    if t.guid == out_t.guid:
                        o.inputs[i] = in_t
        graph.ops = [o for o in graph.ops if o.guid not in drop]
        graph._producer_cache = None
        fsdp_weights = {g: r for g, r in fsdp_weights.items()
                        if r[0].guid not in drop}
    joint = fsdp_deg > 1 and data_deg % fsdp_deg == 0
    # Expert axis: the expert-parallel substitutions (search/
    # substitution.py partition_experts_alltoall) either compose with
    # partition_batch at the SAME degree — the all-to-all reshards the
    # batch-sharded tokens within the data device group, so the expert
    # axis absorbs the data axis (same devices, renamed) — or run with
    # the batch unsharded, where expert is its own device factor like
    # seq. Under joint fsdp the merge still holds — the fsdp group is a
    # subdivision of the same workers, so the expert axis takes the
    # CARVED size and expert/batch dims lower to the ("expert", "fsdp")
    # tuple (pspec_for_parallel_tensor), exactly the ZeRO batch rule
    # with the data axis renamed.
    merge_expert = expert_deg > 1 and expert_deg == data_deg \
        and (fsdp_deg == 1 or joint)
    solo_expert = expert_deg > 1 and expert_deg != data_deg
    axes = {"data": data_deg // fsdp_deg if joint else data_deg,
            "model": model_deg}
    data_idx, expert_idx = 0, None
    if merge_expert:
        axes["expert"] = axes["data"]  # carved size under joint fsdp
        axes["data"] = 1
        expert_idx = len(axes) - 1
        data_idx = expert_idx  # batch dims ride the renamed axis
    elif solo_expert:
        axes["expert"] = expert_deg
        expert_idx = len(axes) - 1
    seq_idx = None
    if seq_deg > 1:
        axes["seq"] = seq_deg
        seq_idx = len(axes) - 1
    fsdp_idx = None
    if fsdp_deg > 1:
        axes["fsdp"] = fsdp_deg
        fsdp_idx = len(axes) - 1
    for t in tensors:
        is_weight = t.guid in weight_guids
        for i, d in enumerate(t.dims):
            if d.degree <= 1:
                continue
            if d.is_replica_dim:
                d.parallel_idx = -1
                continue
            tag = getattr(d, "axis_tag", None)
            if tag == "expert":
                if expert_idx is not None and d.degree == expert_deg:
                    d.parallel_idx = expert_idx
                else:
                    d.degree, d.parallel_idx = 1, -1
            elif tag == "seq":
                if seq_idx is not None and d.degree == seq_deg:
                    d.parallel_idx = seq_idx
                else:
                    d.degree, d.parallel_idx = 1, -1
            elif i == 0 and not is_weight:
                if d.degree == data_deg and data_deg > 1:
                    d.parallel_idx = data_idx
                else:
                    d.degree, d.parallel_idx = 1, -1
            elif is_weight and fsdp_idx is not None \
                    and t.guid in fsdp_weights and d.degree == fsdp_deg:
                d.parallel_idx = fsdp_idx
            else:
                if d.degree == model_deg and model_deg > 1:
                    d.parallel_idx = 1
                else:
                    d.degree, d.parallel_idx = 1, -1
    # demotion reconciliation: an AllToAll whose scatter dim was demoted
    # above must not keep declaring the searched exchange degree — the
    # strategy validators (FFA104/FFA505) compare params against dims,
    # and a degree-1 exchange lowers to the identity reshard
    for op in graph.ops:
        if op.op_type != OperatorType.OP_ALL_TO_ALL or not op.outputs:
            continue
        p = op.params
        if 0 <= p.scatter_dim < len(op.outputs[0].dims):
            actual = op.outputs[0].dims[p.scatter_dim].degree
            if actual != p.degree:
                op.params = dataclasses.replace(p, degree=actual)
    if pipe_deg > 1:
        axes["pipe"] = pipe_deg
        apply_pipeline_parallel(graph, pipe_deg, axis_idx=len(axes) - 1)
    return axes


def apply_tensor_parallel(graph: Graph, degree: int, axis_idx: int = 1) -> None:
    """Megatron-style tensor/model parallelism via weight-dim sharding.

    reference equivalents: Linear replica-dim model parallelism
    (model.cc:1979 map_linear_weight + Replicate/Reduction pairs) and
    attention attribute parallelism over heads (substitution.cc:1764-1770).
    Here: shard weight dims tagged "out_channel"/"head"/"vocab" over the
    model mesh axis; GSPMD inserts the Replicate/Reduction collectives the
    reference materializes as parallel ops.

    Activations: the hidden dim of LINEAR outputs is sharded to keep the
    matmul local (column-parallel); attention output stays replicated (the
    wo einsum contracts the head dim, producing the reduction)."""
    if degree <= 1:
        return
    for op in graph.ops:
        tags_list = getattr(op, "weight_tags", [])
        shard_out = False
        for wpt, tags in zip(op.weights, tags_list):
            for i, tag in enumerate(tags):
                if tag in ("out_channel", "head", "vocab") and (
                    wpt.dims[i].size % degree == 0
                ):
                    wpt.dims[i].degree = degree
                    wpt.dims[i].parallel_idx = axis_idx
                    if tag == "out_channel":
                        shard_out = True
                    break  # one sharded dim per weight
        if shard_out and op.op_type == OperatorType.OP_LINEAR:
            for t in op.outputs:
                last = t.dims[-1]
                if last.size % degree == 0:
                    last.degree = degree
                    last.parallel_idx = axis_idx


def apply_expert_parallel(graph: Graph, degree: int, axis_idx: int) -> None:
    """Expert parallelism: distinct experts' dense ops run on distinct mesh
    slots (reference: MoE ops get distinct MachineViews, SURVEY §2.3). Under
    SPMD we shard the leading expert-capacity dim of group_by outputs."""
    if degree <= 1:
        return
    for op in graph.ops:
        if op.op_type == OperatorType.OP_GROUP_BY:
            for t in op.outputs:
                if t.dims[0].size % degree == 0:
                    t.dims[0].degree = degree
                    t.dims[0].parallel_idx = axis_idx


def apply_pipeline_parallel(graph: Graph, degree: int, axis_idx: int) -> None:
    """Pipeline parallelism: shard the leading (layer) dim of block-stack
    weights over the pipe mesh axis — stage placement AS a sharding.

    No reference equivalent (OP_PIPELINE is enum-only there, ffconst.h:158);
    execution is parallel/pipeline.py's GPipe schedule."""
    if degree <= 1:
        return
    for op in graph.ops:
        for wpt, tags in zip(op.weights, getattr(op, "weight_tags", [])):
            for i, tag in enumerate(tags):
                if tag == "pipeline_stage" and wpt.dims[i].size % degree == 0:
                    wpt.dims[i].degree = degree
                    wpt.dims[i].parallel_idx = axis_idx
                    break


def apply_weight_sharding(graph: Graph, degree: int, axis_idx: int) -> int:
    """FSDP/ZeRO weight sharding as a manual strategy (config.fsdp_degree;
    no reference equivalent — the reference always replicates weights
    within a model-parallel group): shard every eligible op's parameters
    (and thereby gradient buffers + optimizer-state slots, which inherit
    the sharding) over the ``fsdp`` mesh axis and insert the WeightShard
    bookkeeping nodes. See parallel/weight_sharding.py for semantics."""
    from .weight_sharding import apply_weight_sharding as _apply

    return _apply(graph, degree, axis_idx)


def apply_sequence_parallel(
    graph: Graph, degree: int, axis_idx: int, seq_dim: int = 1
) -> None:
    """Shard the sequence dim of 3-D activations (batch, seq, hidden).

    No reference equivalent (SURVEY §5: sequence parallelism absent there);
    this is the TPU build's first-class SP strategy. Attention ops handle the
    resharding internally (ring attention / all-to-all in kernels/)."""
    if degree <= 1:
        return
    for op in graph.ops:
        for t in op.outputs:
            if t.num_dims == 3 and t.dims[seq_dim].size % degree == 0:
                t.dims[seq_dim].degree = degree
                t.dims[seq_dim].parallel_idx = axis_idx
