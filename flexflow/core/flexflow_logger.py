"""Shim: reference python/flexflow/core/flexflow_logger.py — the `fflogger`
console logger (INFO to stdout, ERROR+ to stderr) that reference scripts and
the keras_exp frontend import."""
import logging
import sys


class ConsoleHandler(logging.StreamHandler):
    """stdout for routine records, stderr for ERROR and above (reference:
    flexflow_logger.py ConsoleHandler)."""

    def emit(self, record):
        self.stream = sys.stderr if record.levelno >= logging.ERROR else sys.stdout
        logging.StreamHandler.emit(self, record)

    def flush(self):
        if (self.stream and hasattr(self.stream, "flush")
                and not getattr(self.stream, "closed", False)):
            logging.StreamHandler.flush(self)


def setup_custom_logger(name):
    logger = logging.getLogger(name)
    logger.setLevel(logging.INFO)
    logger.propagate = 0
    if not logger.handlers:
        formatter = logging.Formatter(
            fmt="%(levelname)s - %(module)s - %(message)s"
        )
        ch = ConsoleHandler()
        ch.setLevel(logging.DEBUG)
        ch.setFormatter(formatter)
        logger.addHandler(ch)
    return logger


fflogger = setup_custom_logger("fflogger")
