"""Slice-loss survivability of a parallelization strategy.

On preemptible multi-slice machines (the machine-model hierarchy of
search/network.py; reference simulator.h:212-376) the common failure is
losing a WHOLE slice at once. Whether that failure is cheap or
catastrophic is a property of the searched strategy:

  * **survivable** — only data-parallel replicas cross the slice
    boundary: every weight shard set is complete within each slice, so
    losing a slice just drops replicas and the run shrinks onto the
    survivors (runtime/elastic.py restore path, PR 2) without touching
    model state.
  * **not survivable** — model/FSDP weight shards cross slices: the
    lost slice held shard pieces that exist nowhere else, so recovery is
    a full reshard/restore from checkpoint, not a shrink.

This module classifies a (graph, views) strategy statically, feeds the
FFA6xx analysis diagnostics (analysis/perf.py), and supplies the
configurable cost penalty (`CostModel.survivability_penalty`, config
knob ``search_survivability_penalty``) that biases the DP and MCMC
searches toward survivable strategies on hierarchical machines — a
bias, deliberately not a hard constraint: when cross-slice sharding is
the only way a model fits, the search may still pick it and the lint
tells the operator what that choice costs at failure time.

The per-slice check assumes the canonical mesh device order
(parallel/mesh.py): the data axis is outermost, so each data replica
occupies a contiguous device block and "per-slice device count divides
the weight partition degree" means each slice holds complete shard
sets. Strategies outside that layout are classified conservatively
(not survivable).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple


# statuses, roughly from safest to worst
STATELESS = "stateless"            # op has no weights — nothing to lose
CONFINED = "confined"              # view spans a single slice
REPLICATED = "replicated"          # weights replicated: pure DP across slices
SURVIVABLE_SHARDED = "survivable_sharded"  # shard sets complete per slice
CROSS_SLICE_SHARDED = "cross_slice_sharded"  # shards span the boundary
UNPLACED = "unplaced"              # no machine view recorded for the op


@dataclasses.dataclass(frozen=True)
class OpSurvivability:
    guid: int
    name: str
    status: str
    detail: str = ""
    weight_bytes: int = 0
    partition_degree: int = 1
    spanned_slices: Tuple[int, ...] = ()
    per_slice_devices: Tuple[int, ...] = ()

    @property
    def survivable(self) -> bool:
        return self.status != CROSS_SLICE_SHARDED


@dataclasses.dataclass(frozen=True)
class StrategySurvivability:
    ops: Tuple[OpSurvivability, ...]
    num_slices: int

    @property
    def survivable(self) -> bool:
        return all(o.survivable for o in self.ops)

    @property
    def unsurvivable_ops(self) -> Tuple[OpSurvivability, ...]:
        return tuple(o for o in self.ops if not o.survivable)

    @property
    def spans_slices(self) -> bool:
        return any(len(o.spanned_slices) > 1 for o in self.ops)

    @property
    def total_weight_bytes(self) -> int:
        return sum(o.weight_bytes for o in self.ops)

    @property
    def unsurvivable_weight_bytes(self) -> int:
        return sum(o.weight_bytes for o in self.unsurvivable_ops)


def weight_bytes(op) -> int:
    """Logical (unsharded) parameter bytes held by `op`."""
    total = 0
    for w in getattr(op, "weights", ()) or ():
        n = 1
        for s in w.material_shape():
            n *= s
        total += n * w.data_type.size
    return total


def weight_partition_degree(op) -> int:
    """How many distinct shard pieces the op's weights are split into:
    the max over its weights of the product of non-replica dim degrees.
    1 = fully replicated (pure DP); >1 = model/FSDP-sharded (weight
    sharding — parallel/weight_sharding.py — records its degrees on
    these same dims, so FSDP is caught by the same rule)."""
    best = 1
    for w in getattr(op, "weights", ()) or ():
        d = 1
        for dim in w.dims:
            if not dim.is_replica_dim:
                d *= dim.degree
        best = max(best, d)
    return best


def _op_label(op) -> str:
    name = getattr(op, "name", None)
    if name:
        return str(name)
    ot = getattr(op, "op_type", None)
    return getattr(ot, "name", str(ot))


def op_survivability(op, view, slice_of) -> OpSurvivability:
    """Classify one op's placement. `slice_of(device_id)` maps a flat
    device id to its fault-domain index (machine.node_of, or
    FaultDomainMap.slice_of)."""
    guid = getattr(op, "guid", -1)
    label = _op_label(op)
    wbytes = weight_bytes(op)
    if view is None:
        return OpSurvivability(guid, label, UNPLACED, weight_bytes=wbytes)
    per: Dict[int, int] = {}
    for d in view.device_ids():
        s = slice_of(d)
        per[-1 if s is None else int(s)] = per.get(
            -1 if s is None else int(s), 0) + 1
    spanned = tuple(sorted(per))
    counts = tuple(per[s] for s in spanned)
    if len(spanned) <= 1:
        return OpSurvivability(guid, label, CONFINED, weight_bytes=wbytes,
                               spanned_slices=spanned,
                               per_slice_devices=counts)
    if wbytes == 0:
        return OpSurvivability(guid, label, STATELESS,
                               spanned_slices=spanned,
                               per_slice_devices=counts)
    p = weight_partition_degree(op)
    if p == 1:
        return OpSurvivability(
            guid, label, REPLICATED, weight_bytes=wbytes,
            partition_degree=1, spanned_slices=spanned,
            per_slice_devices=counts,
            detail="weights replicated: only DP replicas cross slices",
        )
    if all(c % p == 0 for c in counts):
        return OpSurvivability(
            guid, label, SURVIVABLE_SHARDED, weight_bytes=wbytes,
            partition_degree=p, spanned_slices=spanned,
            per_slice_devices=counts,
            detail=f"{p}-way weight shard sets complete within each slice",
        )
    return OpSurvivability(
        guid, label, CROSS_SLICE_SHARDED, weight_bytes=wbytes,
        partition_degree=p, spanned_slices=spanned,
        per_slice_devices=counts,
        detail=(
            f"weights sharded {p}-way across slices {list(spanned)} "
            f"(per-slice devices {list(counts)}): a lost slice takes "
            "shard pieces that exist nowhere else"
        ),
    )


def strategy_survivability(graph, views: Optional[Dict], *,
                           machine=None,
                           fault_domains=None) -> StrategySurvivability:
    """Classify every op of a strategy. Provide either a MachineModel
    (slices = machine nodes) or a FaultDomainMap; machine wins when both
    are given (it is what the search placed against)."""
    if machine is not None:
        n_slices = machine.num_nodes
        slice_of = machine.node_of
    elif fault_domains is not None:
        n_slices = fault_domains.num_slices
        slice_of = fault_domains.slice_of
    else:
        raise ValueError("need a machine model or a FaultDomainMap")
    views = views or {}
    out: List[OpSurvivability] = []
    for op in graph.topo_order():
        v = views.get(op.guid)
        if v is None:  # same fallback as analysis/collectives._view_of
            v = getattr(op, "machine_view", None)
        out.append(op_survivability(op, v, slice_of))
    return StrategySurvivability(ops=tuple(out), num_slices=n_slices)


def survivability_cost_factor(graph, views: Optional[Dict],
                              cost_model) -> float:
    """Multiplicative penalty the searches apply to a candidate's cost:
    1.0 for survivable strategies (or single-slice machines, or a zero
    penalty knob), else 1 + penalty * (fraction of weight bytes whose
    shards cross the slice boundary). Proportional, so sharding ONE
    small embedding across slices costs less bias than sharding the
    whole trunk — the search trades failure-domain hygiene against real
    step time instead of forbidding anything."""
    pen = float(getattr(cost_model, "survivability_penalty", 0.0) or 0.0)
    machine = getattr(cost_model, "machine", None)
    if pen <= 0.0 or machine is None or machine.num_nodes <= 1:
        return 1.0
    s = strategy_survivability(graph, views, machine=machine)
    total = s.total_weight_bytes
    if total <= 0 or s.survivable:
        return 1.0
    return 1.0 + pen * (s.unsurvivable_weight_bytes / float(total))
