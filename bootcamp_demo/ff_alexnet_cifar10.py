"""Bootcamp demo, step 2: train the exported AlexNet on CIFAR-10 with
FlexFlow-TPU (reference: bootcamp_demo/ff_alexnet_cifar10.py — this is
BASELINE.md's AlexNet/CIFAR-10 throughput config).

Run: python bootcamp_demo/ff_alexnet_cifar10.py -e 1 -b 64
(exports alexnet.ff first if it is missing)
"""
import os

import numpy as np
from PIL import Image

from flexflow.core import *  # noqa: F401,F403
from flexflow.keras.datasets import cifar10
from flexflow.torch.model import PyTorchModel


def top_level_task():
    ffconfig = FFConfig()
    print(
        "Python API batchSize(%d) workersPerNodes(%d) numNodes(%d)"
        % (
            ffconfig.get_batch_size(),
            ffconfig.get_workers_per_node(),
            ffconfig.get_num_nodes(),
        )
    )
    ffmodel = FFModel(ffconfig)

    dims_input = [ffconfig.get_batch_size(), 3, 229, 229]
    input_tensor = ffmodel.create_tensor(dims_input, DataType.DT_FLOAT)

    if not os.path.exists("alexnet.ff"):
        from torch_alexnet_cifar10 import AlexNet
        import flexflow.torch.fx as fx

        fx.torch_to_flexflow(AlexNet(num_classes=10), "alexnet.ff")

    torch_model = PyTorchModel("alexnet.ff")
    torch_model.apply(ffmodel, [input_tensor])

    ffoptimizer = SGDOptimizer(ffmodel, 0.01)
    ffmodel.set_sgd_optimizer(ffoptimizer)
    ffmodel.compile(
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[
            MetricsType.METRICS_ACCURACY,
            MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY,
        ],
    )
    label_tensor = ffmodel.get_label_tensor()

    num_samples = int(os.environ.get("BOOTCAMP_NUM_SAMPLES", 10000))
    (x_train, y_train), _ = cifar10.load_data(num_samples)
    x_train = x_train[:num_samples]
    y_train = y_train[:num_samples]
    num_samples = x_train.shape[0]
    if x_train.shape[1] == 3:  # reference layout: (N, 3, 32, 32)
        x_train = x_train.transpose(0, 2, 3, 1)

    full_input_np = np.zeros((num_samples, 3, 229, 229), dtype=np.float32)
    for i in range(num_samples):
        pil_image = Image.fromarray(x_train[i].astype(np.uint8))
        pil_image = pil_image.resize((229, 229), Image.NEAREST)
        full_input_np[i] = np.array(pil_image, np.float32).transpose(2, 0, 1)
    full_input_np /= 255

    full_label_np = y_train.astype("int32").reshape(num_samples, 1)

    dataloader_input = ffmodel.create_data_loader(input_tensor, full_input_np)
    dataloader_label = ffmodel.create_data_loader(label_tensor, full_label_np)

    num_samples = dataloader_input.num_samples

    ffmodel.init_layers()

    epochs = ffconfig.get_epochs()

    ts_start = ffconfig.get_current_time()
    ffmodel.fit(x=dataloader_input, y=dataloader_label, epochs=epochs)
    ts_end = ffconfig.get_current_time()
    run_time = 1e-6 * (ts_end - ts_start)
    print(
        "epochs %d, ELAPSED TIME = %.4fs, THROUGHPUT = %.2f samples/s\n"
        % (epochs, run_time, num_samples * epochs / run_time)
    )


if __name__ == "__main__":
    print("alexnet cifar10")
    top_level_task()
