"""Fleet observatory tests (flexflow_tpu/obs/fleet.py, obs/anomaly.py,
obs/flight_recorder.py): spool atomicity + integrity, cross-process
rollup semantics (counter conservation, gauge identity labels, histogram
reservoir merge), staleness classification, the anomaly sentinel's
warmup/hysteresis/false-positive guarantees, forensics bundle schema and
the restart-surviving index, plus the `obs fleet` / `obs forensics` CLI
round-trips. Pure obs-layer tests — no model build, no mesh."""
import json
import os
import subprocess
import sys
import threading
import zlib

import pytest

import flexflow_tpu.obs as obs
from flexflow_tpu.obs import flight_recorder as fr
from flexflow_tpu.obs.anomaly import AnomalySentinel, GapDetector, \
    SeriesDetector
from flexflow_tpu.obs.fleet import (
    FleetAggregator,
    MetricSpool,
    SpoolCorruptionError,
    read_spool,
)
from flexflow_tpu.obs.metrics import (
    MetricsRegistry,
    merge_histogram_states,
    parse_prometheus_labeled,
)
from flexflow_tpu.runtime.fault_domains import FaultDomainMap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_session():
    obs.finish()
    yield
    obs.finish()


def make_registry(requests=5.0, depth=3.0):
    reg = MetricsRegistry()
    reg.counter("ff_serving_requests_total",
                help="serving requests answered").inc(requests)
    reg.gauge("ff_serving_queue_depth").set(depth)
    h = reg.histogram("ff_serving_latency_seconds")
    for v in (0.1, 0.2, 0.3):
        h.observe(v)
    return reg


# ---------------------------------------------------------------------
# spool write/read
# ---------------------------------------------------------------------

def test_spool_write_read_roundtrip(tmp_path):
    sp = MetricSpool(str(tmp_path), "proc-a", registry=make_registry(),
                     replica="replica0", slice_id=1)
    path = sp.write(health={"ok": True}, provenance={"sig": "abc"})
    assert path.endswith("proc-a.spool.json")
    payload = read_spool(path)
    assert payload["process"] == "proc-a"
    assert payload["replica"] == "replica0"
    assert payload["slice"] == 1
    assert payload["status"] == "live"
    assert payload["health"] == {"ok": True}
    names = {rec["name"] for rec in payload["series"]}
    assert "ff_serving_requests_total" in names
    # histograms carry full mergeable state, not a lossy summary
    hist = next(r for r in payload["series"]
                if r["name"] == "ff_serving_latency_seconds")
    assert hist["kind"] == "histogram"
    assert hist["state"]["count"] == 3


def test_spool_corruption_detected(tmp_path):
    sp = MetricSpool(str(tmp_path), "p", registry=make_registry())
    path = sp.write()
    env = json.load(open(path))
    env["payload"]["series"][0]["value"] = 999.0  # crc now stale
    json.dump(env, open(path, "w"))
    with pytest.raises(SpoolCorruptionError, match="crc32"):
        read_spool(path)
    # the aggregator degrades, never throws: corrupt spool -> dead record
    # with the error preserved, and the meta-series counts it
    view = FleetAggregator(str(tmp_path)).aggregate()
    rec = view.records[0]
    assert rec.state == "dead" and "crc32" in rec.error
    assert view.registry.find("ff_fleet_spools_corrupt").value == 1.0


def test_spool_truncated_file_detected(tmp_path):
    sp = MetricSpool(str(tmp_path), "p", registry=make_registry())
    path = sp.write()
    raw = open(path).read()
    open(path, "w").write(raw[: len(raw) // 2])
    with pytest.raises(SpoolCorruptionError):
        read_spool(path)


def test_spool_concurrent_writer_never_torn(tmp_path):
    """os.replace keeps every read whole: a reader hammering the spool
    while a writer rewrites it must never see a torn/corrupt file."""
    sp = MetricSpool(str(tmp_path), "p", registry=make_registry())
    sp.write()
    stop = threading.Event()
    errors = []

    def writer():
        i = 0
        while not stop.is_set():
            sp.write(health={"beat": i})
            i += 1

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        for _ in range(300):
            try:
                payload = read_spool(sp.path)
                assert payload["process"] == "p"
            except SpoolCorruptionError as e:
                errors.append(str(e))
    finally:
        stop.set()
        t.join(timeout=5.0)
    assert not errors, errors[:3]


# ---------------------------------------------------------------------
# aggregation semantics
# ---------------------------------------------------------------------

def test_counter_conservation_including_dead_spool(tmp_path):
    """A killed process's terminal spool still contributes its tally:
    the rollup conserves counts across the death."""
    MetricSpool(str(tmp_path), "a", registry=make_registry(5)).write()
    MetricSpool(str(tmp_path), "b", registry=make_registry(7)).write()
    MetricSpool(str(tmp_path), "dead-c",
                registry=make_registry(11)).write(status="dead")
    view = FleetAggregator(str(tmp_path)).aggregate()
    assert view.states()["dead-c"] == "dead"
    assert view.counter_total("ff_serving_requests_total") == 23.0


def test_gauges_keep_process_identity(tmp_path):
    domains = FaultDomainMap.from_devices(8, 4).with_hosts(
        {"a": 0, "b": 1})
    MetricSpool(str(tmp_path), "a", registry=make_registry(depth=2),
                replica="replica0").write()
    MetricSpool(str(tmp_path), "b", registry=make_registry(depth=9),
                replica="replica1").write()
    view = FleetAggregator(str(tmp_path),
                           fault_domains=domains).aggregate()
    a = view.registry.find("ff_serving_queue_depth", process="a",
                           replica="replica0", slice="0")
    b = view.registry.find("ff_serving_queue_depth", process="b",
                           replica="replica1", slice="1")
    assert a is not None and a.value == 2.0
    assert b is not None and b.value == 9.0


def test_histogram_merge_across_spools(tmp_path):
    r1, r2 = MetricsRegistry(), MetricsRegistry()
    for v in (0.1, 0.1, 0.1):
        r1.histogram("ff_lat").observe(v)
    for v in (5.0, 5.0, 5.0):
        r2.histogram("ff_lat").observe(v)
    MetricSpool(str(tmp_path), "a", registry=r1).write()
    MetricSpool(str(tmp_path), "b", registry=r2).write()
    view = FleetAggregator(str(tmp_path)).aggregate()
    merged = view.registry.find("ff_lat")
    assert merged.count == 6
    # fleet percentiles span the union of both processes' samples
    assert merged.quantile(0.1) <= 0.2
    assert merged.quantile(0.9) >= 4.0


def test_stale_and_dead_age_windows(tmp_path):
    sp = MetricSpool(str(tmp_path), "p", registry=make_registry())
    sp.write()
    now = read_spool(sp.path)["unixtime"]
    agg = FleetAggregator(str(tmp_path), staleness_s=10.0, death_s=30.0)
    assert agg.scan(now=now + 1)[0].state == "live"
    assert agg.scan(now=now + 11)[0].state == "stale"
    assert agg.scan(now=now + 31)[0].state == "dead"


def test_terminal_status_overrides_age(tmp_path):
    """A fresh spool that declares status dead/exited classifies
    immediately — no waiting out the staleness window."""
    MetricSpool(str(tmp_path), "x",
                registry=make_registry()).write(status="exited")
    MetricSpool(str(tmp_path), "y",
                registry=make_registry()).write(status="dead")
    states = FleetAggregator(str(tmp_path)).aggregate().states()
    assert states == {"x": "exited", "y": "dead"}


def test_classify_slice_loss(tmp_path):
    """Both processes of one slice stale -> the fleet page reads it as a
    slice loss, not two unrelated hiccups."""
    domains = FaultDomainMap.from_devices(8, 4).with_hosts(
        {"a": 0, "b": 0, "c": 1, "d": 1})
    for p in ("a", "b"):
        MetricSpool(str(tmp_path), p,
                    registry=make_registry()).write(status="dead")
    for p in ("c", "d"):
        MetricSpool(str(tmp_path), p, registry=make_registry()).write()
    view = FleetAggregator(str(tmp_path),
                           fault_domains=domains).aggregate()
    assert view.classification is not None
    assert view.classification.kind == "slice_loss"
    assert view.classification.lost_slices == (0,)
    assert view.registry.find("ff_fleet_lost_slices").value == 1.0


def test_observe_into_feeds_gap_detectors(tmp_path):
    sp = MetricSpool(str(tmp_path), "p", registry=make_registry())
    sp.write()
    now = read_spool(sp.path)["unixtime"]
    agg = FleetAggregator(str(tmp_path), staleness_s=10.0)
    sentinel = AnomalySentinel(emit=False)
    agg.observe_into(sentinel, now=now + 1)  # fresh: quiet
    assert sentinel.recent() == []
    agg.observe_into(sentinel, now=now + 20)  # past staleness: fires
    hits = sentinel.recent(series_prefix="heartbeat_gap:p")
    assert len(hits) == 1 and hits[0].kind == "gap"


def test_fleet_table_lists_processes(tmp_path):
    MetricSpool(str(tmp_path), "p0", registry=make_registry(42),
                replica="replica0").write()
    table = FleetAggregator(str(tmp_path)).aggregate().table()
    assert "p0" in table and "replica0" in table and "42" in table


# ---------------------------------------------------------------------
# metrics satellites: histogram merge + labeled prometheus round-trip
# ---------------------------------------------------------------------

def test_merge_histogram_states_units():
    r = MetricsRegistry()
    h = r.histogram("h")
    for v in (0.1, 0.2):
        h.observe(v)
    s1 = h.state()
    s2 = json.loads(json.dumps(s1))  # a serialization round-trip merges
    merged = merge_histogram_states([s1, s2])
    assert merged["count"] == 4
    assert merged["sum"] == pytest.approx(0.6)
    bad = dict(s2, buckets=[1.0, 2.0], counts=[1, 1])
    with pytest.raises(ValueError, match="edges differ"):
        merge_histogram_states([s1, bad])


def test_parse_prometheus_labeled_roundtrip():
    reg = MetricsRegistry()
    reg.counter("ff_x_total", a="1", b="two").inc(3)
    reg.counter("ff_x_total").inc(4)
    reg.gauge("ff_g", process="p0").set(2.5)
    series = parse_prometheus_labeled(reg.to_prometheus())
    assert series[("ff_x_total", (("a", "1"), ("b", "two")))] == 3.0
    assert series[("ff_x_total", ())] == 4.0
    assert series[("ff_g", (("process", "p0"),))] == 2.5


# ---------------------------------------------------------------------
# anomaly sentinel
# ---------------------------------------------------------------------

def test_detector_warmup_never_fires():
    det = SeriesDetector("s", warmup=8, hysteresis=1)
    for i in range(7):
        assert det.observe(0.0, now=float(i)) is None
    # 8th sample is a huge spike but the window is still warming
    assert det.observe(1000.0, now=7.5) is None


def test_detector_spike_and_hysteresis():
    det = SeriesDetector("s", warmup=8, hysteresis=2, min_delta=0.5)
    for i in range(10):
        det.observe(1.0, now=float(i))
    # first breach arms hysteresis, second fires
    assert det.observe(50.0, now=10.0) is None
    a = det.observe(50.0, now=11.0)
    assert a is not None and a.kind == "spike" and a.baseline == 1.0
    assert a.tag == "s:spike"


def test_detector_false_positive_bound():
    """Stationary noise must not page anyone: a seeded random walk well
    inside the z-threshold yields zero verdicts over 500 samples."""
    import numpy as np

    rng = np.random.RandomState(7)
    det = SeriesDetector("s", warmup=8, hysteresis=2, min_delta=0.0)
    fired = sum(
        det.observe(10.0 + 0.5 * rng.randn(), now=float(i)) is not None
        for i in range(500))
    assert fired == 0


def test_detector_min_delta_floor_on_constant_baseline():
    """mad == 0 (exactly-constant history) defers to the absolute
    min_delta floor: a +1 blip on an all-zero queue is not an incident,
    a +8 jump is."""
    det = SeriesDetector("s", warmup=4, hysteresis=1, min_delta=4.0)
    for i in range(6):
        det.observe(0.0, now=float(i))
    assert det.observe(1.0, now=6.0) is None
    a = det.observe(8.0, now=7.0)
    assert a is not None and a.kind == "spike"


def test_detector_direction_high_ignores_drops():
    det = SeriesDetector("s", warmup=4, hysteresis=1, min_delta=1.0,
                         direction="high")
    for i in range(6):
        det.observe(10.0, now=float(i))
    assert det.observe(0.0, now=6.0) is None  # recovery, not an incident
    assert det.observe(100.0, now=7.0) is not None


def test_gap_detector_fires_and_cools_down():
    det = GapDetector("hb", limit_s=5.0, cooldown_s=60.0)
    assert det.observe(3.0, now=0.0) is None
    a = det.observe(7.0, now=1.0)
    assert a is not None and a.kind == "gap" and a.score > 1.0
    # inside cooldown the still-open gap does not re-page
    assert det.observe(9.0, now=2.0) is None


def test_sentinel_history_blame_and_callback():
    seen = []
    s = AnomalySentinel(emit=False, on_anomaly=seen.append)
    for i in range(10):
        s.observe("queue", 0.0, now=float(i), warmup=4, hysteresis=1,
                  min_delta=1.0)
    s.observe("queue", 50.0, now=10.0)
    assert len(seen) == 1
    assert s.blame(now=11.0) == "queue:spike"
    assert s.blame(now=1000.0, max_age_s=5.0) is None
    assert s.recent(series_prefix="other") == []


def test_sentinel_emits_counter_into_session(tmp_path):
    from flexflow_tpu import TelemetryConfig

    tel = obs.start(TelemetryConfig(dir=str(tmp_path / "tel")))
    s = AnomalySentinel()
    for i in range(10):
        s.observe("q", 0.0, now=float(i), warmup=4, hysteresis=1,
                  min_delta=1.0)
    s.observe("q", 9.0, now=10.0)
    found = tel.metrics.find("ff_anomalies_total", series="q",
                             kind="spike")
    assert found is not None and found.value == 1.0


# ---------------------------------------------------------------------
# flight recorder + forensics bundles
# ---------------------------------------------------------------------

def test_recorder_ring_bound_and_tracer_sink(tmp_path):
    from flexflow_tpu.obs.tracer import Tracer

    dropped = []
    tracer = Tracer(max_events=5, on_drop=lambda n: dropped.append(n))
    rec = fr.FlightRecorder(str(tmp_path), capacity=8)
    tracer.add_sink(rec.record_event)
    for i in range(20):
        tracer.emit({"ts": float(i), "ph": "i", "name": f"e{i}",
                     "cat": "test", "tid": 0, "args": {}})
    # the trace file capped at 5, live drop counter saw the rest...
    assert tracer.dropped == 15 and sum(dropped) == 15
    # ...but the recorder's ring kept the freshest tail past the cap
    snap = rec.snapshot()
    assert len(snap["events"]) == 8
    assert snap["events"][-1]["name"] == "e19"


def test_dump_bundle_schema_validate_and_corruption(tmp_path):
    rec = fr.FlightRecorder(str(tmp_path), process="t")
    rec.record_metric("lat", 1.5)
    rec.register_provider("pool", lambda: {"pages": 3})
    path = rec.dump(reason="unit", error=RuntimeError("boom"),
                    extra={"replica": "replica1"})
    assert fr.validate_bundle(path) == []
    payload = fr.read_bundle(path)
    assert payload["reason"] == "unit"
    assert payload["error"]["type"] == "RuntimeError"
    assert payload["state"]["pool"] == {"pages": 3}
    assert payload["extra"]["replica"] == "replica1"
    entries, problems = fr.validate_dir(str(tmp_path))
    assert len(entries) == 1 and problems == []
    # flip one payload byte: crc catches it
    env = json.load(open(path))
    env["payload"]["reason"] = "tampered"
    json.dump(env, open(path, "w"))
    assert any("crc32" in p for p in fr.validate_bundle(path))
    _, problems = fr.validate_dir(str(tmp_path))
    assert problems


def test_forensics_index_survives_restart(tmp_path):
    rec = fr.install(str(tmp_path), process="run1")
    rec.dump(reason="first")
    fr.uninstall(rec)
    rec2 = fr.install(str(tmp_path), process="run2")
    rec2.dump(reason="second")
    fr.uninstall(rec2)
    entries, problems = fr.read_index(str(tmp_path))
    assert problems == []
    assert [e["reason"] for e in entries] == ["first", "second"]
    # append-only index tolerates a truncated (crash mid-append) tail
    idx = os.path.join(str(tmp_path), fr.FORENSICS_DIRNAME, fr.INDEX_FILE)
    with open(idx, "a") as f:
        f.write('{"unixtime": 1.0, "file": "trunc')
    entries, problems = fr.read_index(str(tmp_path))
    assert len(entries) == 2 and len(problems) == 1


def test_maybe_dump_failure_typed_and_deduped(tmp_path):
    class KVCacheExhaustedError(RuntimeError):
        pass

    rec = fr.install(str(tmp_path), process="t")
    try:
        exc = KVCacheExhaustedError("9 pages short")
        first = fr.maybe_dump_failure(exc, request="r1")
        assert first is not None
        # the SAME exception propagating through another handler does
        # not dump twice — it reports the bundle the first hook wrote
        assert fr.maybe_dump_failure(exc) == first
        # untyped failures stay silent
        assert fr.maybe_dump_failure(ValueError("nope")) is None
    finally:
        fr.uninstall(rec)
    entries, _ = fr.read_index(str(tmp_path))
    assert len(entries) == 1
    assert entries[0]["error_type"] == "KVCacheExhaustedError"


def test_dump_without_recorder_is_noop():
    assert fr.dump(reason="nobody-home") is None
    assert obs.forensics_dump("nobody-home") is None


# ---------------------------------------------------------------------
# tracer drop counter + SLO replica label (satellites 1-2)
# ---------------------------------------------------------------------

def test_session_counts_dropped_trace_events(tmp_path):
    from flexflow_tpu import TelemetryConfig

    tel = obs.start(TelemetryConfig(dir=str(tmp_path / "tel"),
                                    max_events=3))
    for i in range(10):
        obs.event(f"e{i}", cat="test")
    found = tel.metrics.find("ff_trace_events_dropped_total")
    assert found is not None and found.value >= 1.0
    assert found.value == tel.tracer.dropped


def test_slo_violations_carry_replica_label(tmp_path):
    from flexflow_tpu import TelemetryConfig
    from flexflow_tpu.obs.request_trace import SLOMonitor

    tel = obs.start(TelemetryConfig(dir=str(tmp_path / "tel")))
    mon = SLOMonitor(ttft_target_s=0.01)
    mon.observe(ttft_s=0.5, replica="replica2")
    mon.observe(ttft_s=0.5)  # back-compat: unlabeled without a replica
    labeled = tel.metrics.find("ff_slo_violations_total", slo="ttft",
                               replica="replica2")
    plain = tel.metrics.find("ff_slo_violations_total", slo="ttft")
    assert labeled is not None and labeled.value == 1.0
    assert plain is not None and plain.value == 1.0
    # the sentinel's p95 feed sees every ttft sample
    assert mon.ttft.count == 2


# ---------------------------------------------------------------------
# CLI round-trips
# ---------------------------------------------------------------------

def _run_cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "flexflow_tpu.obs", *argv],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})


def test_cli_fleet_table_and_prom(tmp_path):
    spool = tmp_path / "spool"
    MetricSpool(str(spool), "p0", registry=make_registry(13),
                replica="replica0").write()
    MetricSpool(str(spool), "p1",
                registry=make_registry(4)).write(status="exited")
    prom = tmp_path / "fleet.prom"
    res = _run_cli("fleet", str(spool), "--prom", str(prom))
    assert res.returncode == 0, res.stderr
    assert "p0" in res.stdout and "exited" in res.stdout
    series = parse_prometheus_labeled(open(prom).read())
    assert series[("ff_serving_requests_total", ())] == 17.0
    assert series[("ff_fleet_processes", (("state", "live"),))] == 1.0


def test_cli_fleet_exit_code_on_corrupt_spool(tmp_path):
    spool = tmp_path / "spool"
    sp = MetricSpool(str(spool), "p0", registry=make_registry())
    sp.write()
    open(sp.path, "w").write("{ nope")
    res = _run_cli("fleet", str(spool))
    assert res.returncode == 1
    assert "CORRUPT" in res.stdout


def test_cli_forensics_validate_show_and_corruption(tmp_path):
    rec = fr.install(str(tmp_path), process="cli")
    rec.record_metric("lat", 2.0)
    path = rec.dump(reason="unit", extra={"replica": "replica0"})
    fr.uninstall(rec)
    res = _run_cli("forensics", str(tmp_path), "--validate")
    assert res.returncode == 0, res.stderr
    assert "0 problem(s)" in res.stdout
    res = _run_cli("forensics", str(tmp_path), "--show", "latest")
    assert res.returncode == 0, res.stderr
    assert "reason:  unit" in res.stdout
    env = json.load(open(path))
    env["crc32"] = (env["crc32"] + 1) & 0xFFFFFFFF
    json.dump(env, open(path, "w"))
    res = _run_cli("forensics", str(tmp_path), "--validate")
    assert res.returncode == 1
    assert "crc32" in res.stdout


def test_spool_crc_matches_canonical_bytes(tmp_path):
    """The envelope crc is over canonical sorted-key JSON — the exact
    bytes a reader recomputes, so equality is byte-precise."""
    sp = MetricSpool(str(tmp_path), "p", registry=make_registry())
    env = json.load(open(sp.write()))
    canon = json.dumps(env["payload"], sort_keys=True,
                       separators=(",", ":")).encode()
    assert env["crc32"] == zlib.crc32(canon) & 0xFFFFFFFF
