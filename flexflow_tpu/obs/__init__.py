"""Unified telemetry: structured event tracing, metrics export, and
strategy-search explainability.

The reference surfaces runtime behaviour through `-lg:prof` profiles,
per-op event timing prints and the simulator's timeline export (SURVEY
§5); this package unifies the TPU-native equivalents behind one API:

  * `obs.tracer` — low-overhead span tracer -> structured JSONL event
    log, exportable to Chrome-trace/Perfetto (spans around compile,
    every search decision, per-step execution, checkpoints, elastic
    re-search, guard/canary/watchdog firings);
  * `obs.metrics` — counter/gauge/histogram registry -> Prometheus text
    file + JSONL (step wall time, samples/s/chip, grad norm, loss
    scale, skip/retry counts, serving latency percentiles, PCG-derived
    static gauges);
  * `obs.explain_strategy(model)` — joins the recorded search
    trajectory with on-device `profile_ops` measurements to rank ops by
    |simulated − measured| cost and feed the miscalibration back into
    the next search.

Wire-up: ``model.fit(..., telemetry=TelemetryConfig(dir=...))`` runs one
session end to end; ``python -m flexflow_tpu.obs`` converts/summarizes
the artifacts. With no session active every helper here is a cheap
no-op — `tracer()` returns the shared NULL_TRACER (no per-call
allocation) and the counter/gauge helpers return after one global read.
"""
from __future__ import annotations

import contextlib
import sys
from typing import Optional

from .anomaly import Anomaly, AnomalySentinel  # noqa: F401
from .calibration import CalibrationStore, resolve_calibration  # noqa: F401
from .fleet import FleetAggregator, MetricSpool  # noqa: F401
from .metrics import (  # noqa: F401
    MetricsRegistry,
    merge_histogram_states,
    parse_prometheus,
    parse_prometheus_labeled,
)
from .request_trace import (  # noqa: F401
    NULL_REQUEST_TRACE,
    RequestTrace,
    SLOMonitor,
    mint_request_trace,
    record_request_stages,
)
from .telemetry import Telemetry, TelemetryConfig  # noqa: F401
from .tracer import (  # noqa: F401
    NULL_TRACER,
    Tracer,
    _NULL_SPAN,
    read_events_jsonl,
    to_chrome_trace,
    validate_event,
)
from .trajectory import SearchTrajectory  # noqa: F401

_ACTIVE: Optional[Telemetry] = None


# ----------------------------------------------------------------------
# session lifecycle
# ----------------------------------------------------------------------
def start(config: TelemetryConfig) -> Telemetry:
    """Start (and globally register) a telemetry session. One session is
    active per process; starting over a live one finishes it first."""
    global _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.finish()
    _ACTIVE = Telemetry(config)
    return _ACTIVE


def finish() -> None:
    """Finish the active session: flush events.jsonl, write metrics.prom
    / metrics.jsonl and the Perfetto trace.json."""
    global _ACTIVE
    if _ACTIVE is not None:
        _ACTIVE.finish()
        _ACTIVE = None


def active() -> Optional[Telemetry]:
    return _ACTIVE


@contextlib.contextmanager
def session(config: TelemetryConfig):
    tel = start(config)
    try:
        yield tel
    finally:
        if _ACTIVE is tel:
            finish()
        else:  # someone else already rotated the session
            tel.finish()


# ----------------------------------------------------------------------
# cheap emission helpers (no-ops when no session is active)
# ----------------------------------------------------------------------
def tracer():
    """The active session's tracer, or the shared no-op NULL_TRACER."""
    t = _ACTIVE
    return t.tracer if t is not None else NULL_TRACER


def span(name: str, cat: str = "runtime", **args):
    """Context manager timing a span; a shared no-op when inactive."""
    t = _ACTIVE
    if t is None:
        return _NULL_SPAN
    return t.tracer.span(name, cat, **args)


def event(name: str, cat: str = "runtime", **args) -> None:
    """Instant event; dropped when inactive."""
    t = _ACTIVE
    if t is not None:
        t.tracer.instant(name, cat, **args)


def count(name: str, n: float = 1.0, help: str = "", **labels) -> None:
    t = _ACTIVE
    if t is not None:
        t.metrics.counter(name, help, **labels).inc(n)


def gauge_set(name: str, value: float, help: str = "", **labels) -> None:
    t = _ACTIVE
    if t is not None:
        t.metrics.gauge(name, help, **labels).set(value)


def observe(name: str, value: float, help: str = "", **labels) -> None:
    t = _ACTIVE
    if t is not None:
        t.metrics.histogram(name, help, **labels).observe(value)


def forensics_dump(reason: str, error: Optional[BaseException] = None,
                   **extra) -> Optional[str]:
    """Dump a flight-recorder forensics bundle (obs/flight_recorder.py);
    None when no recorder is installed."""
    from . import flight_recorder as _fr

    return _fr.dump(reason=reason, error=error, **extra)


def record_failure(exc: BaseException, **extra) -> Optional[str]:
    """Dump a forensics bundle iff `exc` is a typed runtime failure (at
    most once per exception instance); None otherwise."""
    from . import flight_recorder as _fr

    return _fr.maybe_dump_failure(exc, **extra)


# ----------------------------------------------------------------------
# structured progress logger (the fit/eval print() replacement)
# ----------------------------------------------------------------------
def progress(msg: str, *, verbose: bool = True, name: str = "log",
             cat: str = "train", **fields) -> None:
    """Human-readable progress line + structured telemetry event.

    This is THE sink for library progress output (fflint FFL201 forbids
    bare print() elsewhere in flexflow_tpu/): at default verbosity the
    line prints exactly as before, and when a telemetry session is
    active the same information lands in the event log as structured
    fields."""
    if verbose:
        print(msg, file=sys.stdout)  # fflint: disable=FFL201
    t = _ACTIVE
    if t is not None:
        t.tracer.instant(name, cat, message=msg, **fields)


def explain_strategy(model, x=None, **kw):
    """See obs/explain.py (imported lazily: it pulls in jax)."""
    from .explain import explain_strategy as _impl

    return _impl(model, x, **kw)


def capture_step_profile(model, x, y, **kw):
    """See obs/step_profile.py (imported lazily: it pulls in jax)."""
    from .step_profile import capture_step_profile as _impl

    return _impl(model, x, y, **kw)
