"""Net2Net MNIST MLP with Sequential API (reference:
examples/python/keras/seq_mnist_mlp_net2net.py — weights pulled by index)."""
from flexflow.keras.models import Sequential
from flexflow.keras.layers import Dense, Activation
import flexflow.keras.optimizers
from _mnist import load_mnist

from accuracy import ModelAccuracy
from _example_args import example_args, verify_callbacks


def build(num_classes):
    model = Sequential()
    model.add(Dense(512, input_shape=(784,), activation="relu"))
    model.add(Dense(512, activation="relu"))
    model.add(Dense(num_classes))
    model.add(Activation("softmax"))
    return model


def top_level_task(args):
    num_classes = 10
    x_train, y_train = load_mnist(args.num_samples)

    teacher = build(num_classes)
    teacher.compile(optimizer=flexflow.keras.optimizers.SGD(learning_rate=0.01),
                    loss="sparse_categorical_crossentropy",
                    metrics=["accuracy", "sparse_categorical_crossentropy"],
                    batch_size=args.batch_size)
    teacher.fit(x_train, y_train, epochs=args.epochs)

    d1 = teacher.get_layer(index=0).get_weights(teacher.ffmodel)
    d2 = teacher.get_layer(index=1).get_weights(teacher.ffmodel)
    d3 = teacher.get_layer(index=2).get_weights(teacher.ffmodel)

    student = build(num_classes)
    student.compile(optimizer=flexflow.keras.optimizers.SGD(learning_rate=0.01),
                    loss="sparse_categorical_crossentropy",
                    metrics=["accuracy", "sparse_categorical_crossentropy"],
                    batch_size=args.batch_size)
    student.get_layer(index=0).set_weights(d1)
    student.get_layer(index=1).set_weights(d2)
    student.get_layer(index=2).set_weights(d3)
    student.fit(x_train, y_train, epochs=args.epochs,
                callbacks=verify_callbacks(args, ModelAccuracy.MNIST_MLP))


if __name__ == "__main__":
    print("Sequential model, mnist mlp net2net")
    top_level_task(example_args())
