"""On-device operator microbenchmarks for the measured-mode cost model.

The reference's Simulator measures every operator's fwd/bwd on the GPU and
caches by (op-params, machine-view) hash (simulator.cc:489-537,
Op::measure_operator_cost per op, inner_measure_operator_cost
operator.h:127 — cudaEvent timing with warmup + repeats). This module is
the TPU equivalent: jit the op's forward (and its VJP) at the view's
per-shard shapes, run R repetitions inside ONE lax.scan dispatch (the
remote-TPU tunnel makes per-call host timing meaningless), and feed the
(fwd, bwd) seconds into CostModel.measured so the Unity search steers by
real silicon instead of the analytic roofline.

Enable with FFConfig.measure_operator_costs (argv: --measured-search).
"""
from __future__ import annotations

import time
import warnings
from typing import Dict, Optional, Tuple

import numpy as np

from ..ff_types import DataType
from ..ops.registry import FwdCtx, get_op_def


def _local_shape(pt) -> Tuple[int, ...]:
    """The per-shard material shape under the tensor's sharding degrees."""
    return tuple(
        d.size // max(1, d.degree)
        for d in pt.dims
        if not d.is_replica_dim
    )


def _dummy(shape, data_type: DataType, rng: np.random.RandomState):
    import jax.numpy as jnp

    dt = data_type.jnp_dtype
    if data_type in (DataType.DT_INT32, DataType.DT_INT64):
        return jnp.asarray(rng.randint(0, 2, shape), dt)
    return jnp.asarray(rng.rand(*shape).astype(np.float32), dt)


def _chain_first_float(ws: Dict, ins: list, feedback):
    """Tie one float operand to the scan carry NONLINEARLY so XLA cannot
    hoist the measured op out of the repetition loop. A perturbation
    linear in the carry is not enough: dot distributes over addition, so
    (x + c*eps) @ W rewrites to the loop-invariant x@W plus a hoisted
    rank-1 correction, and the 'measurement' collapses to a scale-add
    (observed on TPU: a 4096x1024x1024 gemm timed at a physically
    impossible 2879 TF/s). sin(c + iota) is elementwise-nonlinear in c,
    so even a distributing rewrite must run a same-shape matmul every
    iteration. The 1e-30 scale keeps it numerically inert."""
    import jax
    import jax.numpy as jnp

    def tie(a):
        mix = jax.lax.broadcasted_iota(
            jnp.float32, a.shape, max(0, a.ndim - 1)
        )
        d = jnp.sin(feedback.astype(jnp.float32) + mix) * 1e-30
        return (a.astype(jnp.float32) + d).astype(a.dtype)

    for i, a in enumerate(ins):
        if jnp.issubdtype(a.dtype, jnp.floating):
            ins = list(ins)
            ins[i] = tie(a)
            return ws, ins
    for k in ws:
        if jnp.issubdtype(ws[k].dtype, jnp.floating):
            ws = dict(ws)
            ws[k] = tie(ws[k])
            return ws, ins
    return ws, ins


class OperatorMeasurer:
    """Times op fwd/bwd on the current default jax device.

    Cached by (op_type, params, local input/weight shapes) — the view
    enters only through the shard shapes, like the reference's strict
    hash (simulator.cc strict_hash_to_operator_cost)."""

    def __init__(self, *, repeats: int = 50, warmup: int = 1,
                 compute_dtype=None, differenced: Optional[bool] = None,
                 cache_path: Optional[str] = None):
        self.repeats = repeats
        self.warmup = warmup
        self.compute_dtype = compute_dtype
        # R-vs-4R differencing cancels the remote-TPU tunnel's ~100ms
        # dispatch/fetch constant but costs extra compiles per op. Off the
        # tunnel (cpu tests) dispatch is microseconds: time one scan
        # directly — same cache semantics, ~6x fewer XLA compiles.
        # None = decide from the backend at first measurement (deciding
        # here would force jax backend init at construction time).
        self._differenced = differenced
        self._cache: Dict[Tuple, Tuple[float, float]] = {}
        self._warned: set = set()
        # disk persistence (reference: the Simulator caches its on-device
        # microbenchmarks across runs, simulator.cc:489-537): measurements
        # survive process restarts, so repeated --measured-search compiles
        # pay the silicon cost once per (op, shard-shape)
        self.cache_path = cache_path
        self._disk: Dict[str, Tuple[float, float]] = {}
        self._disk_loaded = False

    def _cache_meta(self) -> Dict[str, str]:
        import jax

        return {
            "device": jax.devices()[0].device_kind,
            "dtype": str(self.compute_dtype or "f32"),
        }

    def _load_disk(self) -> None:
        """Lazy (first measurement): the cache is only valid for the SAME
        device kind and compute dtype — timings from another chip replayed
        silently would poison every downstream cost."""
        self._disk_loaded = True
        if not self.cache_path:
            return
        import json
        import os

        if not os.path.exists(self.cache_path):
            return
        try:
            with open(self.cache_path) as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            warnings.warn(
                f"measured-search: ignoring unreadable cache "
                f"{self.cache_path}: {e}"
            )
            return
        meta = data.pop("__meta__", None)
        if meta is not None and meta != self._cache_meta():
            warnings.warn(
                f"measured-search: cache {self.cache_path} was measured on "
                f"{meta} but this run is {self._cache_meta()} — ignoring it"
            )
            return
        if meta is None:
            warnings.warn(
                f"measured-search: cache {self.cache_path} has no device "
                "metadata (older format); assuming it matches this device"
            )
        self._disk = {k: tuple(v) for k, v in data.items()}

    @staticmethod
    def _disk_key(key) -> str:
        op_type, params, shard_shapes, w_shapes, parts = key
        return f"{op_type.name}|{params!r}|{shard_shapes}|{w_shapes}|{parts}"

    def _disk_put(self, key, fb) -> None:
        if not self.cache_path:
            return
        import json

        self._disk[self._disk_key(key)] = fb
        try:
            payload = {"__meta__": self._cache_meta()}
            payload.update({k: list(v) for k, v in self._disk.items()})
            with open(self.cache_path, "w") as f:
                json.dump(payload, f, indent=0)
        except OSError as e:
            warnings.warn(f"measured-search: cache write failed: {e}")

    @property
    def differenced(self) -> bool:
        if self._differenced is None:
            import jax

            self._differenced = jax.default_backend() == "tpu"
        return self._differenced

    def __call__(self, op, view, *, force: bool = False) -> Tuple[float, float]:
        """force=True bypasses the cache READ (a fresh measurement still
        lands in the cache) — used when re-measuring outliers at higher
        repeat counts."""
        parts = max(1, view.num_parts())
        shard_shapes = tuple(_local_shape(t) for t in op.inputs)
        w_shapes = tuple(_local_shape(w) for w in op.weights)
        key = (op.op_type, op.params, shard_shapes, w_shapes, parts)
        if not self._disk_loaded:
            self._load_disk()
        if not force:
            if key in self._cache:
                return self._cache[key]
            disk = self._disk.get(self._disk_key(key))
            if disk is not None:
                self._cache[key] = disk
                return disk
        try:
            fb = self._measure(op, shard_shapes, w_shapes)
        except Exception as e:
            # un-runnable standalone (e.g. params that disagree with local
            # weight shards): analytic fallback — but say so ONCE per op
            # type, or measured mode silently degrades to the roofline
            if op.op_type not in self._warned:
                self._warned.add(op.op_type)
                warnings.warn(
                    f"measured-search: {op.op_type.name} fell back to the "
                    f"analytic cost model ({type(e).__name__}: {e})"
                )
            fb = None
        if fb is None:
            fb = (float("nan"), float("nan"))
        else:
            self._disk_put(key, fb)
        self._cache[key] = fb
        return fb

    def _measure(self, op, shard_shapes, w_shapes):
        import jax
        import jax.numpy as jnp

        if op.is_parallel_op or not op.inputs:
            return None
        opdef = get_op_def(op.op_type)
        rng = np.random.RandomState(0)
        inputs = [
            _dummy(s, t.data_type, rng)
            for s, t in zip(shard_shapes, op.inputs)
        ]
        # weight names from the WeightSpecs (so dict lookups in the
        # forward resolve), shapes from the op's ParallelTensors at their
        # PER-SHARD sizes — a channel-split kernel must be timed at
        # out_channels/degree, not full size
        specs = opdef.weights(
            op.params,
            [tuple(s) for s in shard_shapes],
            [t.data_type for t in op.inputs],
        ) if opdef.weights else []
        weights = {
            spec.name: _dummy(ws, w.data_type, rng)
            for spec, ws, w in zip(specs, w_shapes, op.weights)
        }
        ctx = FwdCtx(training=False, rng=None, seq_length=-1,
                     compute_dtype=self.compute_dtype, aux_losses=None,
                     n_devices=1, mesh=None)
        R = self.repeats

        def fwd_once(ws, ins):
            outs = opdef.forward(op.params, ws, ins, ctx)
            return sum(jnp.sum(o.astype(jnp.float32)) for o in outs)

        diffable = [i for i, a in enumerate(inputs)
                    if jnp.issubdtype(a.dtype, jnp.floating)]

        def fwd_body(c, _):
            ws2, ins2 = _chain_first_float(weights, inputs, c)
            return c + fwd_once(ws2, ins2) * 1e-9, ()

        def bwd_body(c, _):
            def loss(ws_, dins):
                full = list(inputs)
                for i, v in zip(diffable, dins):
                    full[i] = v
                return fwd_once(ws_, full)

            ws2, ins2 = _chain_first_float(weights, inputs, c)
            g = jax.grad(loss, argnums=(0, 1))(
                ws2, [ins2[i] for i in diffable]
            )
            leaves = jax.tree_util.tree_leaves(g)
            return c + sum(
                jnp.sum(l.astype(jnp.float32)) for l in leaves
            ) * 1e-9, ()

        def run(body, length):
            fn = jax.jit(lambda: jax.lax.scan(
                body, jnp.float32(0.0), None, length=length)[0])
            for _ in range(self.warmup):
                float(fn())
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                float(fn())
                best = min(best, time.perf_counter() - t0)
            return best

        def per_rep_seconds(body):
            """Time scans of R and 4R reps and difference them: the fixed
            dispatch + device->host fetch (milliseconds through the
            remote-TPU tunnel) cancels, leaving pure per-repetition op
            time (the reference's cudaEvent bracket equivalent). R grows
            until the differenced signal clears the tunnel's jitter, and
            each point is a min-of-3. Non-differenced mode (off-tunnel
            backends) times one scan directly."""
            if not self.differenced:
                return max(run(body, R) / R, 1e-9)
            reps = R
            while True:
                t1 = run(body, reps)
                t4 = run(body, 4 * reps)
                signal = t4 - t1
                if signal > 20e-3 or reps >= 4096:
                    return max(signal / (3 * reps), 1e-9)
                reps *= 4

        fwd_t = per_rep_seconds(fwd_body)
        try:
            total_t = per_rep_seconds(bwd_body)  # grad includes a forward
            bwd_t = max(total_t - fwd_t, 0.1 * fwd_t)
        except Exception:
            bwd_t = 2.0 * fwd_t
        return fwd_t, bwd_t


def attach_measured_mode(cost_model, *, repeats: int = 50,
                         compute_dtype=None,
                         cache_path: Optional[str] = None) -> None:
    """Wire an OperatorMeasurer into a CostModel: every cost-cache miss
    first tries real silicon; NaN (unmeasurable) falls back to the
    analytic roofline. cache_path persists measurements across runs."""
    import jax

    backend = jax.default_backend()
    if backend != "tpu":
        warnings.warn(
            f"measured-search is timing ops on the '{backend}' backend; "
            "mixing those times with the machine model's TPU link costs "
            "skews the search — use for testing only"
        )
    cost_model.measure_fn = OperatorMeasurer(
        repeats=repeats, compute_dtype=compute_dtype, cache_path=cache_path
    )
