"""Softmax operator.

TPU-native equivalent of reference src/ops/softmax.cc (cuDNN softmax with a
`softmax_dim`): jax.nn.softmax, which XLA lowers to the standard
max-subtract/exp/sum fusion on the VPU.
"""
from __future__ import annotations

import dataclasses

import jax

from ..ff_types import OperatorType
from .registry import register_op


@dataclasses.dataclass(frozen=True)
class SoftmaxParams:
    """reference: include/flexflow/ops/softmax_params.h"""

    dim: int = -1


def _infer(params, in_shapes, in_dtypes):
    return [in_shapes[0]], [in_dtypes[0]]


def _forward(params: SoftmaxParams, weights, inputs, ctx):
    (x,) = inputs
    return [jax.nn.softmax(x, axis=params.dim)]


def _softmax_seq_pointwise(params, op):
    """Per-position only when the softmax axis is NOT the sequence axis
    (axis 1 of a rank>=3 (batch, seq, ...) tensor)."""
    nd = len(op.inputs[0].material_shape())
    return nd < 3 or params.dim % nd != 1


register_op(OperatorType.OP_SOFTMAX, "Softmax", infer=_infer, forward=_forward,
            seq_pointwise=_softmax_seq_pointwise)
