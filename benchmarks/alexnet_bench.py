"""AlexNet/CIFAR-10 training throughput on the real chip — the second
headline config (BASELINE.md: bootcamp_demo/ff_alexnet_cifar10.py prints
THROUGHPUT; reference input layout 3x229x229, batch 64)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _harness import run_throughput


def build(model, batch):
    from flexflow_tpu.models.alexnet import build_alexnet

    build_alexnet(model, batch_size=batch, num_classes=10,
                  height=229, width=229)


if __name__ == "__main__":
    run_throughput(build, metric="alexnet_cifar10_train_throughput",
                   batch=64, label_classes=10, spd=25)
