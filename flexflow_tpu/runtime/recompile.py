"""Dynamic recompilation triggers.

TPU-native equivalent of the reference RecompileState
(include/flexflow/recompile.h:26-41; FFModel::recompile_on_condition,
model.cc:2422): a user-supplied trigger predicate is checked each epoch;
when it fires, an alter function mutates the model and the framework
re-compiles. The reference's MoE example uses this to rebalance experts
mid-training (examples/cpp/mixture_of_experts/moe.cc:65-98).

On TPU "recompile" = re-lower the layer graph, re-run the strategy pass (or
search), re-jit — weights carry over by op name.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import numpy as np


class RecompileState:
    """reference: recompile.h:26-41 RecompileState{trigger_func, alter_func}."""

    def __init__(
        self,
        trigger_func: Callable[["FFModel"], bool],
        alter_func: Optional[Callable[["FFModel"], None]] = None,
    ):
        self.trigger_func = trigger_func
        self.alter_func = alter_func
        self.recompilations = 0

    def trigger(self, model) -> bool:
        return bool(self.trigger_func(model))

    def alter(self, model) -> None:
        if self.alter_func is not None:
            self.alter_func(model)


def recompile_on_condition(model, state: RecompileState) -> bool:
    """Check the trigger; on fire, alter + re-compile preserving weights
    (reference: model.cc:2422 — the reference mutates once; we re-lower)."""
    if not state.trigger(model):
        return False
    # snapshot weights by (op name, weight name)
    old_params = {
        name: {w: np.asarray(v) for w, v in wd.items()}
        for name, wd in model.state.params.items()
    }
    old_step = model.state.step
    state.alter(model)
    model.compile(
        optimizer=model.optimizer,
        loss_type=model.loss_type,
        metrics=model.metrics_obj.measures if model.metrics_obj else (),
        comp_mode=model.comp_mode,
    )
    # restore surviving weights
    for name, wd in model.state.params.items():
        if name not in old_params:
            continue
        for w_name, new in wd.items():
            old = old_params[name].get(w_name)
            if old is not None and tuple(old.shape) == tuple(new.shape):
                model.state.params[name][w_name] = jax.device_put(
                    old.astype(np.asarray(new).dtype), new.sharding
                )
    model.state.step = old_step
    state.recompilations += 1
    return True
