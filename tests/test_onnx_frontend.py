"""ONNX frontend tests using lightweight protobuf test-doubles (the onnx
package is not in this image; the importer is duck-typed over .graph)."""
import numpy as np
import pytest

from flexflow_tpu import DataType, FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.frontends.onnx import ONNXModel


class Attr:
    def __init__(self, name, **kw):
        self.name = name
        self.type = kw.pop("type", 0)
        self.i = kw.get("i", 0)
        self.f = kw.get("f", 0.0)
        self.s = kw.get("s", b"")
        self.ints = kw.get("ints", [])
        self.floats = kw.get("floats", [])


class Node:
    def __init__(self, op_type, inputs, outputs, attrs=()):
        self.op_type = op_type
        self.input = list(inputs)
        self.output = list(outputs)
        self.attribute = list(attrs)


class Value:
    def __init__(self, name):
        self.name = name


class Init:
    def __init__(self, name, array):
        self.name = name
        self.data = array


class GraphDouble:
    def __init__(self, nodes, initializers, outputs):
        self.node = nodes
        self.initializer = initializers
        self.output = [Value(o) for o in outputs]


class ModelDouble:
    def __init__(self, graph):
        self.graph = graph


def test_onnx_mlp_import():
    rng = np.random.RandomState(0)
    w1 = rng.randn(16, 32).astype(np.float32)
    b1 = rng.randn(32).astype(np.float32)
    w2 = rng.randn(32, 4).astype(np.float32)
    graph = GraphDouble(
        nodes=[
            Node("Gemm", ["x", "w1", "b1"], ["h"]),
            Node("Relu", ["h"], ["hr"]),
            Node("MatMul", ["hr", "w2"], ["logits"]),
            Node("Softmax", ["logits"], ["probs"],
                 attrs=[Attr("axis", i=-1, type=2)]),  # AttributeProto INT
        ],
        initializers=[Init("w1", w1), Init("b1", b1), Init("w2", w2)],
        outputs=["probs"],
    )
    cfg = FFConfig()
    cfg.batch_size = 8
    ff = FFModel(cfg)
    x = ff.create_tensor((8, 16), DataType.DT_FLOAT)
    om = ONNXModel(ModelDouble(graph))
    out = om.apply(ff, {"x": x})
    ff.compile(optimizer=SGDOptimizer(lr=0.0),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[])
    om.load_weights(ff)
    xv = rng.randn(8, 16).astype(np.float32)
    ours = ff.predict(xv, batch_size=8)
    # numpy reference
    ref = np.maximum(xv @ w1 + b1, 0) @ w2
    e = np.exp(ref - ref.max(-1, keepdims=True))
    ref = e / e.sum(-1, keepdims=True)
    np.testing.assert_allclose(ours, ref, atol=1e-5)


def _apply_graph(graph, in_shape=(8, 16)):
    cfg = FFConfig()
    cfg.batch_size = in_shape[0]
    ff = FFModel(cfg)
    x = ff.create_tensor(list(in_shape), DataType.DT_FLOAT)
    om = ONNXModel(ModelDouble(graph))
    out = om.apply(ff, {"x": x})
    ff.compile(optimizer=SGDOptimizer(lr=0.0),
               loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
               metrics=[])
    om.load_weights(ff)
    return ff, om


def test_onnx_bias_fold_trainable():
    """keras2onnx dense layout MatMul→Add(1-D bias) folds to ONE dense layer
    with a trainable bias (the reference's ONNXModelKeras drops these
    biases, onnx/model.py:343-345)."""
    rng = np.random.RandomState(0)
    w = rng.randn(16, 4).astype(np.float32)
    b = rng.randn(4).astype(np.float32)
    graph = GraphDouble(
        nodes=[Node("MatMul", ["x", "w"], ["mm"]),
               Node("Add", ["mm", "b"], ["y"])],
        initializers=[Init("w", w), Init("b", b)],
        outputs=["y"],
    )
    ff, om = _apply_graph(graph)
    dense_layers = [l for l in ff.layers if len(l.weights) == 2]
    assert len(dense_layers) == 1, "MatMul+Add should fold to one dense"
    xv = rng.randn(8, 16).astype(np.float32)
    np.testing.assert_allclose(ff.predict(xv, batch_size=8), xv @ w + b,
                               atol=1e-5)


def test_onnx_scalar_add_stays_constant():
    """A broadcastable shape-(1,) Add operand must NOT fold into a
    trainable bias — it stays a baked constant."""
    rng = np.random.RandomState(1)
    w = rng.randn(16, 4).astype(np.float32)
    c = np.array([2.5], np.float32)
    graph = GraphDouble(
        nodes=[Node("MatMul", ["x", "w"], ["mm"]),
               Node("Add", ["mm", "c"], ["y"])],
        initializers=[Init("w", w), Init("c", c)],
        outputs=["y"],
    )
    ff, om = _apply_graph(graph)
    xv = rng.randn(8, 16).astype(np.float32)
    np.testing.assert_allclose(ff.predict(xv, batch_size=8), xv @ w + 2.5,
                               atol=1e-5)


def test_onnx_prebias_tap_not_folded():
    """When the MatMul output itself is a graph output, the fold must not
    alias the pre-bias name to the post-bias tensor."""
    rng = np.random.RandomState(2)
    w = rng.randn(16, 4).astype(np.float32)
    b = rng.randn(4).astype(np.float32)
    graph = GraphDouble(
        nodes=[Node("MatMul", ["x", "w"], ["mm"]),
               Node("Add", ["mm", "b"], ["y"])],
        initializers=[Init("w", w), Init("b", b)],
        outputs=["mm", "y"],
    )
    cfg = FFConfig()
    cfg.batch_size = 8
    ff = FFModel(cfg)
    x = ff.create_tensor([8, 16], DataType.DT_FLOAT)
    om = ONNXModel(ModelDouble(graph))
    outs = om.apply(ff, {"x": x})
    assert isinstance(outs, list) and len(outs) == 2
    assert outs[0] is not outs[1], "pre-bias tap aliased to biased output"


def test_onnx_constant_node_weights_fold_and_lift():
    """Constant-node weights (the other keras2onnx layout): pre-scan
    registers them before the fold planner, so MatMul+Add(bias) still
    folds; a non-bias Constant Add operand lifts to a baked constant
    instead of crashing on the raw ndarray left in env."""
    from flexflow_tpu.frontends.onnx import proto

    rng = np.random.RandomState(3)
    w = rng.randn(16, 4).astype(np.float32)
    b = rng.randn(4).astype(np.float32)

    def const_node(arr, out):
        t = proto.from_array(arr, out)
        return Node("Constant", [], [out],
                    attrs=[type("A", (), {"name": "value", "t": t})()])

    graph = GraphDouble(
        nodes=[const_node(w, "w"), const_node(b, "b"),
               const_node(np.array([1.5], np.float32), "c"),
               Node("MatMul", ["x", "w"], ["mm"]),
               Node("Add", ["mm", "b"], ["y"]),
               Node("Add", ["y", "c"], ["z"])],
        initializers=[],
        outputs=["z"],
    )
    ff, om = _apply_graph(graph)
    dense_layers = [l for l in ff.layers if len(l.weights) == 2]
    assert len(dense_layers) == 1, "Constant-node MatMul+Add should fold"
    xv = rng.randn(8, 16).astype(np.float32)
    np.testing.assert_allclose(ff.predict(xv, batch_size=8),
                               xv @ w + b + 1.5, atol=1e-5)
