"""CANDLE-Uno drug-response regressor — per-feature towers + deep head
(reference: examples/cpp/candle_uno/candle_uno.cc;
scripts/osdi22ae/candle_uno.sh).

Usage: python examples/python/candle_uno.py -b 64
"""
import sys

import numpy as np

sys.path.insert(0, ".")

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models.misc import build_candle_uno


def main():
    ffconfig = FFConfig()
    model = FFModel(ffconfig)
    shapes = (942, 5270, 2048)
    build_candle_uno(model, ffconfig.batch_size, feature_shapes=shapes)
    model.compile(
        optimizer=SGDOptimizer(lr=0.001),
        loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR],
    )
    n = ffconfig.batch_size * 2
    rng = np.random.RandomState(0)
    xs = [rng.randn(n, s).astype(np.float32) for s in shapes]
    y = rng.randn(n, 1).astype(np.float32)
    model.fit(xs, y, epochs=ffconfig.epochs)


if __name__ == "__main__":
    main()
