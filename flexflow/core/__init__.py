"""`flexflow.core` — the reference's cffi star-import surface
(python/flexflow/core/flexflow_cffi.py via core/__init__.py) mapped onto
flexflow_tpu.

Covers the names reference native-python examples use with
`from flexflow.core import *`: FFConfig, FFModel, Tensor, SingleDataLoader,
optimizers (with the reference's `SGDOptimizer(ffmodel, lr)` signatures),
initializers, and every enum. The reference's Legion bootstrap
(flexflow_top.py) has no equivalent here — jax owns process/device setup.
"""
from __future__ import annotations

from flexflow_tpu import (  # noqa: F401
    ActiMode,
    AggrMode,
    BatchScheduler,
    CompMode,
    ConstantInitializer,
    DataType,
    FFConfig,
    FFIterationConfig,
    FFModel,
    GlorotUniformInitializer,
    Initializer,
    Layer,
    LossType,
    Metrics,
    MetricsType,
    NormInitializer,
    OneInitializer,
    OperatorType,
    Optimizer,
    ParameterSyncType,
    PerfMetrics,
    PoolType,
    SingleDataLoader,
    Tensor,
    UniformInitializer,
    ZeroInitializer,
    restore_checkpoint,
    save_checkpoint,
)
from flexflow_tpu.core.optimizers import (
    AdamOptimizer as _CoreAdam,
    SGDOptimizer as _CoreSGD,
)
from flexflow_tpu.ff_types import RegularizerMode  # noqa: F401

from .flexflow_logger import fflogger  # noqa: F401


def _drop_ffmodel(args):
    """The reference cffi optimizers take the FFModel as first arg
    (flexflow_cffi.py SGDOptimizer(ffmodel, ...)); ours are model-free
    dataclasses. Accept both calling conventions."""
    if args and isinstance(args[0], FFModel):
        return args[1:]
    return args


class SGDOptimizer(_CoreSGD):
    """reference cffi: SGDOptimizer(ffmodel, lr, momentum, nesterov, wd)."""

    def __init__(self, *args, **kw):
        args = _drop_ffmodel(args)
        super().__init__(*args, **kw)


class AdamOptimizer(_CoreAdam):
    """reference cffi: AdamOptimizer(ffmodel, alpha, beta1, beta2, wd, eps)."""

    def __init__(self, *args, **kw):
        args = _drop_ffmodel(args)
        super().__init__(*args, **kw)


def get_legion_runtime():  # pragma: no cover - parity stub
    """Legion runtime handle (reference flexflow_cffi). No Legion here."""
    return None


def init_flexflow_runtime(*a, **kw):  # pragma: no cover - parity stub
    """reference: starts the Legion runtime. jax needs no explicit start."""
    return None
