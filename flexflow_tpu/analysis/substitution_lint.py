"""Substitution-rule soundness lint.

Symbolically checks every declarative rewrite rule (TASO-style JSON,
search/substitutions/*.json) at load time instead of letting a broken
rule blow up — or silently mis-rewrite — deep inside the search:

  * interface arity: tensor refs must point backwards at existing ops,
    mapped outputs must be in range, rules need sources and outputs;
  * sharding preservation under symbolic degrees: each side of the rule
    is abstract-interpreted over a symbolic sharding state (external
    input dims are free symbols, parallel ops transform them) and every
    mapped output's src/dst states are unified — two concrete degrees
    that disagree (e.g. partition-by-2 answered by combine-by-4) make
    the rule unsound; symbol-vs-concrete differences become match-time
    preconditions, exactly how the reference's pattern matcher treats
    them;
  * required params: an AllToAll destination without scatter/gather
    dims would KeyError mid-search.

Codes: FFA401 arity/reference, FFA402 unsound sharding, FFA403
unsupported op type (warning — the loader skips these, like the
reference), FFA404 missing required param, FFA405 dead pattern output
(warning), FFA406 dst op with no param source (warning), FFA407
unsound precision substitution (bad PM_PRECISION value, or a
low-precision accumulating dst op that does not declare its
PM_ACCUM_PRECISION).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..ff_types import DataType, OperatorType
from .diagnostics import AnalysisReport, Severity

_PARALLEL_TYPES = {
    OperatorType.OP_REPARTITION,
    OperatorType.OP_COMBINE,
    OperatorType.OP_REPLICATE,
    OperatorType.OP_REDUCTION,
    OperatorType.OP_ALL_TO_ALL,
    OperatorType.OP_WEIGHT_SHARD,
}

# symbolic degree of external input k's dim d
Sym = Tuple[str, int, object]


def _sym(k: int, dim) -> Sym:
    return ("in", k, dim)


@dataclasses.dataclass
class _ShardState:
    """Sharding state of one tensor: overrides on top of a symbolic base
    (base = external-input index whose unwritten dims are free symbols;
    None = fully fresh tensor, unwritten dims unsharded)."""

    base: Optional[int] = None
    over: Dict[object, object] = dataclasses.field(default_factory=dict)
    replica: object = 1  # replica-dim degree product (int or Sym)

    def lookup(self, dim):
        if dim in self.over:
            return self.over[dim]
        if self.base is not None:
            return _sym(self.base, dim)
        return 1

    def child(self) -> "_ShardState":
        return _ShardState(self.base, dict(self.over), self.replica)


class _RuleCtx:
    def __init__(self, rule, rep: AnalysisReport):
        self.rule = rule
        self.rep = rep
        self.pre: Dict[Sym, int] = {}  # match-time preconditions

    def _name(self):
        return self.rule.name

    def error(self, code, msg, fix_hint=None):
        self.rep.add(Severity.ERROR, code, f"rule {self._name()!r}: {msg}",
                     fix_hint=fix_hint)

    def warn(self, code, msg):
        self.rep.add(Severity.WARNING, code, f"rule {self._name()!r}: {msg}")

    def require(self, val, expect: int, what: str):
        """val must equal `expect`: concrete mismatch = unsound; a symbol
        becomes a precondition (and conflicting preconditions are
        unsound)."""
        if isinstance(val, int):
            if val != expect:
                self.error("FFA402", f"{what}: requires degree {expect} but "
                                     f"the dim carries {val}")
            return
        prev = self.pre.get(val)
        if prev is not None and prev != expect:
            self.error("FFA402", f"{what}: conflicting preconditions on "
                                 f"input dim {val[1:]}: {prev} vs {expect}")
        self.pre[val] = expect


def _transform(pat, in_states: List[_ShardState], ctx: _RuleCtx,
               rank_hint: int) -> _ShardState:
    t = pat.op_type
    if not in_states:
        return _ShardState()
    st = in_states[0].child()
    p = pat.params
    if t == OperatorType.OP_REPARTITION:
        st.over[p.get("PM_PARALLEL_DIM", 0)] = p.get("PM_PARALLEL_DEGREE", 2)
        return st
    if t == OperatorType.OP_COMBINE:
        d = p.get("PM_PARALLEL_DIM", 0)
        g = p.get("PM_PARALLEL_DEGREE", 2)
        ctx.require(st.lookup(d), g, f"Combine(dim={d}, degree={g})")
        st.over[d] = 1
        return st
    if t == OperatorType.OP_REPLICATE:
        g = p.get("PM_PARALLEL_DEGREE", 2)
        if isinstance(st.replica, int):
            st.replica = st.replica * g
        return st
    if t == OperatorType.OP_REDUCTION:
        g = p.get("PM_PARALLEL_DEGREE", 2)
        if isinstance(st.replica, int):
            if st.replica % g != 0:
                ctx.error("FFA402", f"Reduction(degree={g}) but the tensor "
                                    f"carries replica degree {st.replica}")
            else:
                st.replica //= g
        return st
    if t == OperatorType.OP_WEIGHT_SHARD:
        # identity on the activation's sharding state: WeightShard moves
        # parameter STORAGE onto the fsdp axis (weight_sharding.py) and
        # never reshards the flowing tensor. Requires an explicit degree
        # >= 2 — a degree-less rule would silently build a 2-way default.
        deg = p.get("PM_PARALLEL_DEGREE")
        if not isinstance(deg, int) or deg < 2:
            ctx.error("FFA404", "WeightShard needs PM_PARALLEL_DEGREE >= 2",
                      fix_hint="add PM_PARALLEL_DEGREE to the dst op's "
                               "para list")
        return st
    if t == OperatorType.OP_ALL_TO_ALL:
        s, g = p.get("PM_SCATTER_DIM"), p.get("PM_GATHER_DIM")
        deg = p.get("PM_PARALLEL_DEGREE", 2)
        if s is None or g is None:
            ctx.error("FFA404", "AllToAll needs PM_SCATTER_DIM and "
                                "PM_GATHER_DIM",
                      fix_hint="add both dims to the dst op's para list")
            return st
        ctx.require(st.lookup(g), deg,
                    f"AllToAll gather dim {g} (degree {deg})")
        ctx.require(st.lookup(s), 1, f"AllToAll scatter dim {s}")
        st.over[g] = 1
        st.over[s] = deg
        return st
    # -- compute ops ------------------------------------------------------
    if t == OperatorType.OP_BATCHMATMUL and len(in_states) == 2:
        a, b = in_states
        n_dim, k_dim = rank_hint - 1, rank_hint - 2
        va = a.lookup(n_dim)
        if isinstance(va, int) and va > 1:
            ctx.error("FFA402", "batchmatmul lhs contraction dim "
                                f"{n_dim} partitioned {va}-way: partial "
                                "sums need an OP_REDUCTION, not plain "
                                "degree propagation")
        st = a.child()
        st.over[n_dim] = 1
        for dim, v in b.over.items():
            if dim == n_dim:
                st.over[n_dim] = v
            elif dim == k_dim:
                if isinstance(v, int) and v > 1:
                    ctx.error("FFA402", "batchmatmul rhs contraction dim "
                                        f"{k_dim} partitioned {v}-way: "
                                        "needs an OP_REDUCTION")
            else:
                st.over[dim] = v
        return st
    if t == OperatorType.OP_LINEAR:
        st.over["last"] = 1  # fresh out-channel dim (weight-owned)
        return st
    if t == OperatorType.OP_CONV2D:
        st.over[1] = 1  # fresh NCHW channel dim
        return st
    if t == OperatorType.OP_GROUP_BY:
        # expert dispatch [tokens, d] -> n x [capacity, d]: the capacity
        # dim is fresh (NOT the token dim — it must come out unsharded),
        # the hidden dim keeps the token input's sharding
        st.over[0] = 1
        return st
    if t == OperatorType.OP_AGGREGATE:
        # expert combine: the token dim follows the gate input, the
        # hidden dim follows the expert tensors, capacity disappears
        exp = in_states[4] if len(in_states) > 4 else in_states[-1]
        out = _ShardState()
        out.over[0] = in_states[0].lookup(0)
        out.over[1] = exp.lookup(1)
        return out
    if t == OperatorType.OP_TOPK:
        st.over["last"] = 1  # fresh k dim
        return st
    # rank-preserving default (activations, softmax, elementwise,
    # attention, embedding, split, noop, ...)
    return st


def _rank_hint(rule) -> int:
    """Best-effort rank for batchmatmul dim arithmetic: the largest
    concrete dim index any pattern in the rule mentions, plus one."""
    hi = 2
    for pat in rule.src_ops + rule.dst_ops:
        for key in ("PM_PARALLEL_DIM", "PM_SCATTER_DIM", "PM_GATHER_DIM"):
            v = pat.params.get(key)
            if isinstance(v, int):
                hi = max(hi, v + 1)
    return hi


def _eval_side(ops, ctx: _RuleCtx, side: str,
               rank: int) -> List[Optional[_ShardState]]:
    states: List[Optional[_ShardState]] = []
    for oi, pat in enumerate(ops):
        in_states: List[_ShardState] = []
        for ri, ref in enumerate(pat.inputs):
            if ref.ts_id < 0:
                ctx.error("FFA401", f"{side}Op[{oi}] input {ri}: negative "
                                    f"tsId {ref.ts_id}")
                in_states.append(_ShardState())
            elif ref.op_id < 0:
                in_states.append(_ShardState(base=-1 - ref.op_id))
            elif ref.op_id >= oi:
                ctx.error("FFA401", f"{side}Op[{oi}] input {ri} references "
                                    f"op {ref.op_id}, which is not defined "
                                    "yet (refs must point backwards)")
                in_states.append(_ShardState())
            elif states[ref.op_id] is None:
                in_states.append(_ShardState())
            else:
                in_states.append(states[ref.op_id])
        if pat.op_type is None:
            states.append(None)
            continue
        states.append(_transform(pat, in_states, ctx, rank))
    return states


# Valid targets for a PM_PRECISION / PM_ACCUM_PRECISION declaration: the
# float members of DataType (a rule that stamps DT_INT32 as a compute
# dtype is nonsense, and an out-of-enum int raises deep in apply_rule).
_FLOAT_DTYPES = {
    int(DataType.DT_HALF),
    int(DataType.DT_BF16),
    int(DataType.DT_FLOAT),
    int(DataType.DT_DOUBLE),
}
_LOW_PRECISION = {int(DataType.DT_HALF), int(DataType.DT_BF16)}


def _lint_precision(rule, ctx: _RuleCtx) -> None:
    """FFA407: precision-rewrite soundness.

    A substitution that narrows compute precision must (a) name a real
    float dtype and (b), when the destination op accumulates (matmul /
    attention / reductions — see analysis.precision), declare the accum
    dtype it keeps wide, so the FFA702 invariant survives the rewrite.
    """
    from .precision import _ACCUMULATING

    for side, ops in (("src", rule.src_ops), ("dst", rule.dst_ops)):
        for oi, pat in enumerate(ops):
            for key in ("PM_PRECISION", "PM_ACCUM_PRECISION"):
                v = pat.params.get(key)
                if v is not None and v not in _FLOAT_DTYPES:
                    ctx.error(
                        "FFA407",
                        f"{side}Op[{oi}] ({pat.type_str}): {key}={v!r} is "
                        "not a float DataType member",
                        fix_hint="use the int value of DT_HALF/DT_BF16/"
                                 "DT_FLOAT/DT_DOUBLE",
                    )
    for oi, pat in enumerate(rule.dst_ops):
        prec = pat.params.get("PM_PRECISION")
        if prec in _LOW_PRECISION and pat.op_type in _ACCUMULATING \
                and pat.params.get("PM_ACCUM_PRECISION") is None:
            ctx.error(
                "FFA407",
                f"dstOp[{oi}] ({pat.type_str}) narrows compute to "
                f"{DataType(prec).name} but declares no accumulator "
                "dtype for an accumulating op",
                fix_hint="add PM_ACCUM_PRECISION (typically DT_FLOAT) "
                         "to the dst op's para list",
            )


def lint_rule(rule) -> AnalysisReport:
    rep = AnalysisReport()
    ctx = _RuleCtx(rule, rep)
    if not rule.src_ops:
        ctx.error("FFA401", "no source pattern ops")
    if not rule.dst_ops:
        ctx.error("FFA401", "no destination ops")
    _lint_precision(rule, ctx)
    if not rule.mapped_outputs:
        # legal in the reference wire format (matches only sites whose
        # outputs have no outside consumers) but almost always a mistake
        ctx.warn("FFA405", "no mapped outputs — the rewrite can only "
                           "match ops whose outputs nobody consumes")
    if not rule.supported:
        bad = sorted({p.type_str for p in rule.src_ops + rule.dst_ops
                      if p.op_type is None})
        ctx.warn("FFA403", f"unsupported op type(s) {bad}; the loader "
                           "skips this rule")
        return rep  # cannot reason about unknown semantics
    if rep.errors:
        return rep
    # Tensor ranks are not declared in the rule schema, and batchmatmul's
    # dim roles (batch / contraction / column) depend on them. Interpret
    # charitably: a rule is sound if SOME rank makes it sound — apply_rule
    # rejects mismatched-rank sites at match time (its contraction-dim
    # guard), so only a rule broken at EVERY rank is truly unsound.
    base = _rank_hint(rule)
    has_bmm = any(p.op_type == OperatorType.OP_BATCHMATMUL
                  for p in rule.src_ops + rule.dst_ops)
    candidates = [base + k for k in range(3)] if has_bmm else [base]
    attempt = None
    for rank in candidates:
        attempt = _lint_rule_at_rank(rule, rank)
        if attempt.ok:
            break
    rep.extend(attempt)
    return rep


def _lint_rule_at_rank(rule, rank: int) -> AnalysisReport:
    rep = AnalysisReport()
    ctx = _RuleCtx(rule, rep)
    src_states = _eval_side(rule.src_ops, ctx, "src", rank)
    dst_states = _eval_side(rule.dst_ops, ctx, "dst", rank)

    # dst compute ops need a same-typed src op to inherit params from
    # (apply_rule raises KeyError at every site otherwise = dead rule)
    src_types = [p.op_type for p in rule.src_ops]
    for oi, pat in enumerate(rule.dst_ops):
        if pat.op_type in _PARALLEL_TYPES or \
                pat.op_type == OperatorType.OP_NOOP or \
                "PM_MERGE" in pat.params:
            continue
        if pat.op_type == OperatorType.OP_SPLIT and any(
                "PM_MERGE" in d.params for d in rule.dst_ops):
            continue
        if pat.op_type not in src_types:
            ctx.warn("FFA406", f"dstOp[{oi}] ({pat.type_str}) has no "
                               "source op of the same type to inherit "
                               "params from; the rule can never apply")

    # dead pattern outputs: a src output neither consumed inside the
    # pattern nor mapped restricts matching to zero-consumer sites
    consumed = {(r.op_id, r.ts_id) for p in rule.src_ops for r in p.inputs
                if r.op_id >= 0}
    mapped_src = {(s, ts) for (s, ts, _, _) in rule.mapped_outputs}
    for oi in range(len(rule.src_ops)):
        if (oi, 0) not in consumed and (oi, 0) not in mapped_src:
            ctx.warn("FFA405", f"srcOp[{oi}] output 0 is neither consumed "
                               "by the pattern nor a mapped output")

    # unify mapped outputs
    for mi, (s_op, s_ts, d_op, d_ts) in enumerate(rule.mapped_outputs):
        if not (0 <= s_op < len(rule.src_ops)):
            ctx.error("FFA401", f"mappedOutput[{mi}]: srcOpId {s_op} out "
                                f"of range ({len(rule.src_ops)} src ops)")
            continue
        if not (0 <= d_op < len(rule.dst_ops)):
            ctx.error("FFA401", f"mappedOutput[{mi}]: dstOpId {d_op} out "
                                f"of range ({len(rule.dst_ops)} dst ops)")
            continue
        ss, ds = src_states[s_op], dst_states[d_op]
        if ss is None or ds is None:
            continue
        for dim in sorted(set(ss.over) | set(ds.over), key=str):
            va, vb = ss.lookup(dim), ds.lookup(dim)
            if va == vb:
                continue
            if isinstance(va, int) and isinstance(vb, int):
                ctx.error(
                    "FFA402",
                    f"mappedOutput[{mi}] (srcOp[{s_op}] -> dstOp[{d_op}]) "
                    f"is not sharding-preserving on dim {dim}: src degree "
                    f"{va}, dst degree {vb}",
                    fix_hint="balance the partition/combine degrees on "
                             "both sides of the rule",
                )
            elif isinstance(va, int):
                ctx.require(vb, va, f"mappedOutput[{mi}] dim {dim}")
            elif isinstance(vb, int):
                ctx.require(va, vb, f"mappedOutput[{mi}] dim {dim}")
        ra, rb = ss.replica, ds.replica
        if isinstance(ra, int) and isinstance(rb, int) and ra != rb:
            ctx.error("FFA402", f"mappedOutput[{mi}]: replica degree "
                                f"{ra} (src) != {rb} (dst)")
    return rep


def lint_rules(rules) -> AnalysisReport:
    rep = AnalysisReport()
    for rule in rules:
        rep.extend(lint_rule(rule))
    return rep


def analyze_rules_path(path: str) -> AnalysisReport:
    """Lint one substitution-collection JSON file. Malformed JSON becomes
    FFA401 diagnostics rather than raising, so the CLI can report every
    file it was given."""
    from ..search.substitution_loader import (
        SubstitutionRuleError,
        load_rule_collection_from_path,
    )

    try:
        rules = load_rule_collection_from_path(path, validate=False)
    except SubstitutionRuleError as e:
        rep = AnalysisReport()
        rep.add(Severity.ERROR, "FFA401", str(e))
        return rep
    except (OSError, ValueError) as e:
        rep = AnalysisReport()
        rep.add(Severity.ERROR, "FFA401", f"{path}: {e}")
        return rep
    return lint_rules(rules)
