"""Decode-objective strategy search, the paged flash-decode kernel, and
disaggregated prefill/decode serving (ISSUE: Splitwise/DistServe through
the repo's own PCG search).

The contract: single-token decode is HBM-bandwidth-bound where training
is MXU-bound, so (1) the decode cost oracle must price a token's BYTES,
not the padded sequence's FLOPs; (2) compile_decode() must be able to
pick a DIFFERENT strategy than training and the decode objective must
rank it faster; (3) the paged kernel is bit-for-bit checked against the
dense masked reference across ragged per-slot positions (including a
freshly admitted 1-token slot mid-stream); (4) the ContinuousBatcher
stays EXACT vs incremental_generate with the decode-searched strategy
active; (5) the second strategy round-trips through strategy_io; (6) a
first-publication decode series is warn-only in the bench gate."""
import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from flexflow_tpu import (
    ActiMode,
    AggrMode,
    DataType,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.ff_types import OperatorType
from flexflow_tpu.pcg.lowering import layers_to_pcg
from flexflow_tpu.pcg.machine_view import MachineView
from flexflow_tpu.search import CostModel, MachineModel, simulate_runtime

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
VOCAB, SEQ, HIDDEN, HEADS = 29, 16, 16, 2


def build_lm(batch=2, seq=SEQ, layers=1, workers=None):
    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.search_budget = 1
    if workers:
        cfg.workersPerNode = workers
    m = FFModel(cfg)
    ids = m.create_tensor((batch, seq), DataType.DT_INT32)
    t = m.embedding(ids, VOCAB, HIDDEN, AggrMode.AGGR_MODE_NONE)
    for _ in range(layers):
        t = m.multihead_attention(t, t, t, HIDDEN, HEADS, causal=True)
        t = m.dense(t, HIDDEN, ActiMode.AC_MODE_RELU)
    t = m.softmax(m.dense(t, VOCAB))
    m.compile(SGDOptimizer(lr=0.01),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              [MetricsType.METRICS_ACCURACY])
    return m


def transformer_graph(seq=64, batch=8, hidden=128, heads=8):
    model = FFModel(FFConfig())
    x = model.create_tensor((batch, seq, hidden), DataType.DT_FLOAT)
    t = model.multihead_attention(x, x, x, hidden, heads)
    t = model.dense(t, hidden, ActiMode.AC_MODE_RELU)
    t = model.dense(t, hidden)
    graph, _ = layers_to_pcg(model.layers)
    return graph


# ---------------------------------------------------------------------------
# decode cost objective (search/cost_model.py)
# ---------------------------------------------------------------------------

def test_decode_objective_prices_one_token_not_the_sequence():
    """Decode cost of an op must not grow with sequence length (one
    token streams the same weights regardless), while the training
    objective prices the whole padded sequence. And a decode step has no
    backward and no weight-grad sync."""
    machine = MachineModel(num_nodes=1, workers_per_node=4)
    cm_dec = CostModel(machine, objective="decode")
    cm_train = CostModel(machine)
    v = MachineView(start_device_id=0, dim=(1,), stride=(1,))

    def dense_op(g):
        return [o for o in g.ops if o.op_type == OperatorType.OP_LINEAR][0]

    g64, g256 = transformer_graph(seq=64), transformer_graph(seq=256)
    d64 = cm_dec.measure_operator_cost(dense_op(g64), v)
    d256 = cm_dec.measure_operator_cost(dense_op(g256), v)
    assert d64.forward_time == pytest.approx(d256.forward_time, rel=1e-9)
    assert d64.backward_time == 0.0 and d64.sync_time == 0.0
    t64 = cm_train.measure_operator_cost(dense_op(g64), v)
    t256 = cm_train.measure_operator_cost(dense_op(g256), v)
    assert t256.forward_time > t64.forward_time * 2
    # per-token decode is far cheaper than a full training forward
    assert d64.forward_time < t64.forward_time


def test_decode_objective_ranks_memory_bound_ops_by_bytes():
    """A weight-heavy, FLOPs-light op (embedding lookup) must dominate a
    FLOPs-heavy op under the decode objective: the token streams the
    whole table shard but multiplies almost nothing."""
    machine = MachineModel(num_nodes=1, workers_per_node=4)
    cm = CostModel(machine, objective="decode")
    from flexflow_tpu.search.cost_model import op_decode_bytes

    m = FFModel(FFConfig())
    ids = m.create_tensor((2, 16), DataType.DT_INT32)
    t = m.embedding(ids, 50000, 64, AggrMode.AGGR_MODE_NONE)
    t = m.dense(t, 64, ActiMode.AC_MODE_RELU)
    g, _ = layers_to_pcg(m.layers)
    emb = [o for o in g.ops if o.op_type == OperatorType.OP_EMBEDDING][0]
    den = [o for o in g.ops if o.op_type == OperatorType.OP_LINEAR][0]
    assert op_decode_bytes(emb) > op_decode_bytes(den)
    v = MachineView(start_device_id=0, dim=(1,), stride=(1,))
    assert cm.measure_operator_cost(emb, v).forward_time > \
        cm.measure_operator_cost(den, v).forward_time


def test_cost_objective_validated():
    machine = MachineModel(num_nodes=1, workers_per_node=4)
    with pytest.raises(ValueError):
        CostModel(machine, objective="tokens")


# ---------------------------------------------------------------------------
# compile_decode: the second searched strategy
# ---------------------------------------------------------------------------

@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 CPU devices")
def test_compile_decode_selects_a_different_faster_strategy():
    """The acceptance gate: on an 8-device mesh the decode-objective
    search picks a strategy that DIFFERS from the training one, and the
    decode cost model ranks it strictly faster than the training
    strategy (both priced by the same simulator under the decode
    objective)."""
    m = build_lm(workers=8)
    m.compile_decode()
    assert m.decode_executor is not None
    train_degs = sorted(
        tuple(v.dim) for v in (m.searched_views or {}).values())
    dec_degs = sorted(
        tuple(v.dim) for v in (m.decode_searched_views or {}).values())
    assert train_degs != dec_degs, (
        f"decode search should pick a different strategy: {dec_degs}")
    cm = m._build_cost_model(objective="decode")
    t_train = simulate_runtime(m.graph, m.searched_views, cm)
    t_dec = simulate_runtime(m.decode_graph, m.decode_searched_views, cm)
    assert t_dec < t_train, (
        f"decode objective must rank its own strategy faster: "
        f"{t_dec} vs {t_train}")
    # the search recorded its own trajectory, separate from training's
    assert m.decode_trajectory is not None
    phases = {e.get("name") for e in m.decode_trajectory.of_kind("phase")}
    assert "decode_strategy_search" in phases


def test_compile_decode_strategy_roundtrips_through_strategy_io(tmp_path):
    path = str(tmp_path / "decode_strategy.json")
    m = build_lm()
    m.compile_decode(export_path=path)
    exported = {tuple(v.dim) for v in m.decode_searched_views.values()}

    m2 = build_lm()
    m2.compile_decode(strategy_path=path)
    imported = {tuple(v.dim) for v in m2.decode_searched_views.values()}
    assert imported == exported
    assert m2.decode_executor is not None


# ---------------------------------------------------------------------------
# paged flash-decode kernel (kernels/decode.py) — interpret-mode parity
# ---------------------------------------------------------------------------

def test_paged_flash_decode_matches_dense_reference():
    from flexflow_tpu.kernels.attention import HAS_PALLAS
    if not HAS_PALLAS:
        pytest.skip("Pallas unavailable")
    from flexflow_tpu.kernels.decode import (
        paged_decode_reference,
        paged_flash_decode,
    )

    b, h, d, page, pp = 3, 2, 8, 4, 4
    rng = np.random.RandomState(0)
    q = rng.randn(b, h, d).astype(np.float32)
    pool_k = rng.randn(h, b * pp, page, d).astype(np.float32)
    pool_v = rng.randn(h, b * pp, page, d).astype(np.float32)
    # scattered, non-contiguous page assignment per slot
    table = rng.permutation(b * pp)[: b * pp].reshape(b, pp).astype(np.int32)
    # ragged positions: a long-running slot, a freshly admitted 1-token
    # slot (mid-stream admission), and a mid-stream one
    lengths = np.array([10, 1, 7], np.int32)
    out = paged_flash_decode(q, pool_k, pool_v, table, lengths,
                             interpret=True)
    ref = paged_decode_reference(q, pool_k, pool_v, table, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_paged_view_of_cache_matches_dense_attention():
    """The serving adapter: dense per-slot caches viewed as a paged pool
    must reproduce plain masked attention over the dense caches."""
    from flexflow_tpu.kernels.attention import HAS_PALLAS
    if not HAS_PALLAS:
        pytest.skip("Pallas unavailable")
    import jax.numpy as jnp

    from flexflow_tpu.kernels.decode import (
        decode_page_size,
        paged_flash_decode,
        paged_view_of_cache,
    )

    b, max_len, h, d = 2, 12, 2, 8
    rng = np.random.RandomState(1)
    kc = rng.randn(b, max_len, h, d).astype(np.float32)
    vc = rng.randn(b, max_len, h, d).astype(np.float32)
    q = rng.randn(b, h, d).astype(np.float32)
    lengths = np.array([5, 9], np.int32)
    ps = decode_page_size(max_len, preferred=4)
    assert max_len % ps == 0
    kp, vp, table = paged_view_of_cache(jnp.asarray(kc), jnp.asarray(vc), ps)
    out = np.asarray(paged_flash_decode(q, kp, vp, table, lengths,
                                        interpret=True))
    # dense oracle straight off the original caches
    s = np.einsum("bhd,bthd->bht", q, kc) / np.sqrt(d)
    mask = np.arange(max_len)[None, None, :] < lengths[:, None, None]
    s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bht,bthd->bhd", p, vc)
    np.testing.assert_allclose(out, ref, atol=1e-5)
    with pytest.raises(ValueError):
        paged_view_of_cache(jnp.asarray(kc), jnp.asarray(vc), 5)


def test_decode_impl_env_gates_paged_path(monkeypatch):
    """FF_DECODE_IMPL=paged runs generation through the paged kernel
    (interpret mode on CPU) and must stay EXACT vs the dense masked
    path; unknown values raise. Each impl gets a FRESH model — the env
    knob is read at trace time and the jitted decode step is cached per
    executor, so flipping it under a cached build would be a no-op."""
    from flexflow_tpu.runtime.serving import incremental_generate

    prompt = np.array([[3, 1, 4]], np.int32)
    monkeypatch.setenv("FF_DECODE_IMPL", "dense")
    ref = incremental_generate(build_lm(), prompt, max_new_tokens=5)
    monkeypatch.setenv("FF_DECODE_IMPL", "paged")
    out = incremental_generate(build_lm(), prompt, max_new_tokens=5)
    np.testing.assert_array_equal(out, ref)
    monkeypatch.setenv("FF_DECODE_IMPL", "wat")
    with pytest.raises(ValueError):
        incremental_generate(build_lm(), prompt, max_new_tokens=1)


# ---------------------------------------------------------------------------
# disaggregated serving (runtime/serving.py)
# ---------------------------------------------------------------------------

def test_batcher_exact_with_decode_strategy_active():
    from flexflow_tpu.runtime.serving import (
        AdmissionQueue,
        ContinuousBatcher,
        GenerationRequest,
        ServingConfig,
        incremental_generate,
    )

    m = build_lm()
    m.compile_decode()
    q = AdmissionQueue(max_depth=16)
    b = ContinuousBatcher(
        m, ServingConfig(max_len=SEQ, slots=3, page_size=4,
                         precompile=False, default_deadline_s=120.0), q,
    ).start()
    assert b.decode_strategy_active, (
        "batched decode should lower from the decode-searched strategy")
    rng = np.random.RandomState(0)
    cases = []
    try:
        for _ in range(5):
            plen = int(rng.randint(1, 6))
            new = int(rng.randint(1, 6))
            prompt = rng.randint(0, VOCAB, plen).astype(np.int32)
            req = GenerationRequest(prompt, new, deadline_s=120.0)
            q.offer(req)
            cases.append((prompt, new, req))
        for prompt, new, req in cases:
            out = req.result(timeout=300.0)
            ref = incremental_generate(m, prompt[None], max_new_tokens=new)
            np.testing.assert_array_equal(out, ref[0])
    finally:
        b.stop()


def test_decode_strategy_path_via_serving_config(tmp_path):
    """ServingConfig.decode_strategy_path imports the second strategy at
    batcher construction when the model was only compile()d."""
    from flexflow_tpu.runtime.serving import (
        AdmissionQueue,
        ContinuousBatcher,
        ServingConfig,
    )

    path = str(tmp_path / "dec.json")
    build_lm().compile_decode(export_path=path)

    m = build_lm()
    assert m.decode_executor is None
    b = ContinuousBatcher(
        m, ServingConfig(max_len=SEQ, slots=2, page_size=4,
                         precompile=False, decode_strategy_path=path),
        AdmissionQueue(max_depth=4),
    )
    assert m.decode_executor is not None
    assert b.decode_strategy_active


def test_incompatible_decode_executor_falls_back_counted():
    """A decode executor whose graph cannot consume the training param
    store must NOT be swapped in: the batcher falls back to the training
    lowering, counts ff_decode_fallback_total and stays functional."""
    from flexflow_tpu import obs
    from flexflow_tpu.obs.telemetry import TelemetryConfig
    from flexflow_tpu.parallel.decode import reset_decode_fallback_warnings
    from flexflow_tpu.runtime.serving import (
        AdmissionQueue,
        ContinuousBatcher,
        ServingConfig,
    )
    import tempfile

    m = build_lm()
    m.compile_decode()
    # sabotage: rename a weight-bearing decode-graph op so its weights
    # can't be found in the training param store
    for op in m.decode_executor.topo:
        if op.weights and not op.is_parallel_op:
            op.name = op.name + "_rewritten"
            break
    reset_decode_fallback_warnings()
    with tempfile.TemporaryDirectory() as td, \
            obs.session(TelemetryConfig(dir=td)):
        with pytest.warns(UserWarning, match="decode_strategy_incompatible"):
            b = ContinuousBatcher(
                m, ServingConfig(max_len=SEQ, slots=2, page_size=4,
                                 precompile=False),
                AdmissionQueue(max_depth=4),
            )
        assert not b.decode_strategy_active
        c = obs.active().metrics.find(
            "ff_decode_fallback_total",
            reason="decode_strategy_incompatible",
        )
        assert c is not None and c.value >= 1.0


# ---------------------------------------------------------------------------
# bench gate: first publication of the decode series is warn-only
# ---------------------------------------------------------------------------

def test_bench_regression_decode_series_warn_only(tmp_path):
    line = json.dumps({
        "metric": "decode_tokens_throughput", "value": 512.0,
        "unit": "tokens/s/chip", "phases_s_per_step": None,
    })
    script = os.path.join(REPO, "scripts", "bench_regression.py")
    r = subprocess.run(
        [sys.executable, script, "-", "--history-dir", str(tmp_path)],
        input=line, capture_output=True, text=True,
        env=os.environ.copy(), timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "no published value for decode_tokens_throughput" in r.stdout.replace("\n", " ")
