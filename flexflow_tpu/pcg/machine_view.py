"""MachineView: which devices an operator's shards land on.

TPU-native re-design of the reference MachineView / MachineResource
(include/flexflow/machine_view.h:14-96). The reference assigns Legion index
points to GPUs via (start_device_id, dim[], stride[]); on TPU the same concept
is "which sub-grid of the device mesh does this op occupy, and how are the
op's parallel degrees laid out over mesh axes". We keep the reference's
shape (ndims/dim/stride/start_device_id) because the strategy search
enumerates views exactly the way the reference does
(FFModel::register_all_machine_views, src/runtime/model.cc), and lower a view
to a jax.sharding spec at execution time.
"""
from __future__ import annotations

import dataclasses
from typing import List, Tuple


@dataclasses.dataclass(frozen=True)
class MachineView:
    """A strided grid of device ids (reference: machine_view.h:14-49)."""

    device_type: str = "TPU"  # reference has GPU/CPU
    start_device_id: int = 0
    dim: Tuple[int, ...] = (1,)
    stride: Tuple[int, ...] = (1,)

    def __post_init__(self):
        # hash() is on the DP search's innermost memo-key path (tens of
        # millions of calls on a 300-op PCG) — precompute once
        object.__setattr__(self, "_hash", hash(
            (self.device_type, self.start_device_id, self.dim, self.stride)
        ))

    @property
    def ndims(self) -> int:
        return len(self.dim)

    def num_parts(self) -> int:
        n = 1
        for d in self.dim:
            n *= d
        return n

    def get_device_id(self, idx: Tuple[int, ...]) -> int:
        """Map an index-space point to a linear device id
        (reference: machine_view.h:24-33)."""
        assert len(idx) == self.ndims
        dev = self.start_device_id
        for i, p in enumerate(idx):
            dev += p * self.stride[i]
        return dev

    def device_ids(self) -> List[int]:
        ids = []

        def rec(i, base):
            if i == self.ndims:
                ids.append(base)
                return
            for p in range(self.dim[i]):
                rec(i + 1, base + p * self.stride[i])

        rec(0, self.start_device_id)
        return ids

    def hash(self) -> int:
        return self._hash

    def __repr__(self):
        return (
            f"MachineView<start={self.start_device_id} dim={list(self.dim)} "
            f"stride={list(self.stride)}>"
        )


@dataclasses.dataclass(frozen=True)
class MachineResource:
    """The machine (sub-)slice available to a search subproblem
    (reference: machine_view.h:51-60)."""

    num_nodes: int
    all_procs_per_node: int  # physical chips per node
    available_procs_per_node: int  # chips this subproblem may use
    start_gpu_id: int = 0
    start_node_id: int = 0

    def num_procs(self) -> int:
        return self.num_nodes * self.available_procs_per_node

    def is_valid_machine_view(self, view: MachineView) -> bool:
        """reference: machine_view.cc MachineResource::is_valid_machine_view.
        The local-proc window STARTS at start_gpu_id's local offset — the
        two halves of a vertical machine split must be DISJOINT device
        sets, or "concurrent" towers would silently share chips (and no
        boundary transfer or congestion could ever be priced between
        them)."""
        lo = self.start_gpu_id % self.all_procs_per_node
        for dev_id in (view.start_device_id, view.device_ids()[-1]):
            node = dev_id // self.all_procs_per_node
            local = dev_id % self.all_procs_per_node
            if node < self.start_node_id or node >= self.start_node_id + self.num_nodes:
                return False
            if local < lo or local >= lo + self.available_procs_per_node:
                return False
        return True

    def hash(self) -> int:
        return hash(
            (
                self.num_nodes,
                self.all_procs_per_node,
                self.available_procs_per_node,
                self.start_gpu_id,
                self.start_node_id,
            )
        )


def make_1d_view(start: int, degree: int, stride: int = 1) -> MachineView:
    return MachineView(start_device_id=start, dim=(degree,), stride=(stride,))


def enumerate_machine_views(num_nodes: int, procs_per_node: int) -> List[MachineView]:
    """Enumerate candidate views the way the reference pre-registers them
    (reference: FFModel::register_all_machine_views, model.cc — all 1-D views
    of every degree that evenly tiles the machine, intra- and inter-node).
    """
    total = num_nodes * procs_per_node
    views: List[MachineView] = []
    # intra-node contiguous views
    for degree in range(1, procs_per_node + 1):
        if procs_per_node % degree != 0 and degree != 1:
            pass  # reference allows any degree that fits; keep all that fit
        for start in range(0, total):
            if start % procs_per_node + degree <= procs_per_node:
                views.append(make_1d_view(start, degree, 1))
    # inter-node strided views (one proc per node run)
    for degree in range(2, num_nodes + 1):
        for start_node in range(0, num_nodes - degree + 1):
            for local in range(procs_per_node):
                views.append(
                    make_1d_view(
                        start_node * procs_per_node + local, degree, procs_per_node
                    )
                )
    # multi-node contiguous views (whole-node groups: the full-machine
    # data-parallel view lives here)
    for n in range(2, num_nodes + 1):
        degree = n * procs_per_node
        for start_node in range(0, num_nodes - n + 1):
            views.append(make_1d_view(start_node * procs_per_node, degree, 1))
    # dedupe
    seen = set()
    out = []
    for v in views:
        h = v.hash()
        if h not in seen:
            seen.add(h)
            out.append(v)
    return out
