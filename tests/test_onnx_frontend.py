"""ONNX frontend tests using lightweight protobuf test-doubles (the onnx
package is not in this image; the importer is duck-typed over .graph)."""
import numpy as np
import pytest

from flexflow_tpu import DataType, FFConfig, FFModel, LossType, SGDOptimizer
from flexflow_tpu.frontends.onnx import ONNXModel


class Attr:
    def __init__(self, name, **kw):
        self.name = name
        self.type = kw.pop("type", 0)
        self.i = kw.get("i", 0)
        self.f = kw.get("f", 0.0)
        self.s = kw.get("s", b"")
        self.ints = kw.get("ints", [])
        self.floats = kw.get("floats", [])


class Node:
    def __init__(self, op_type, inputs, outputs, attrs=()):
        self.op_type = op_type
        self.input = list(inputs)
        self.output = list(outputs)
        self.attribute = list(attrs)


class Value:
    def __init__(self, name):
        self.name = name


class Init:
    def __init__(self, name, array):
        self.name = name
        self.data = array


class GraphDouble:
    def __init__(self, nodes, initializers, outputs):
        self.node = nodes
        self.initializer = initializers
        self.output = [Value(o) for o in outputs]


class ModelDouble:
    def __init__(self, graph):
        self.graph = graph


def test_onnx_mlp_import():
    rng = np.random.RandomState(0)
    w1 = rng.randn(16, 32).astype(np.float32)
    b1 = rng.randn(32).astype(np.float32)
    w2 = rng.randn(32, 4).astype(np.float32)
    graph = GraphDouble(
        nodes=[
            Node("Gemm", ["x", "w1", "b1"], ["h"]),
            Node("Relu", ["h"], ["hr"]),
            Node("MatMul", ["hr", "w2"], ["logits"]),
            Node("Softmax", ["logits"], ["probs"],
                 attrs=[Attr("axis", i=-1, type=2)]),  # AttributeProto INT
        ],
        initializers=[Init("w1", w1), Init("b1", b1), Init("w2", w2)],
        outputs=["probs"],
    )
    cfg = FFConfig()
    cfg.batch_size = 8
    ff = FFModel(cfg)
    x = ff.create_tensor((8, 16), DataType.DT_FLOAT)
    om = ONNXModel(ModelDouble(graph))
    out = om.apply(ff, {"x": x})
    ff.compile(optimizer=SGDOptimizer(lr=0.0),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[])
    om.load_weights(ff)
    xv = rng.randn(8, 16).astype(np.float32)
    ours = ff.predict(xv, batch_size=8)
    # numpy reference
    ref = np.maximum(xv @ w1 + b1, 0) @ w2
    e = np.exp(ref - ref.max(-1, keepdims=True))
    ref = e / e.sum(-1, keepdims=True)
    np.testing.assert_allclose(ours, ref, atol=1e-5)
