"""Fleet observatory: cross-process metric spools + aggregation.

Every telemetry surface before this module is per-process; the fleet
view is built from *spools* — each process (or logical process: a
serving replica, a ReplicaSet controller) periodically snapshots its
`MetricsRegistry.export_state()` plus health/provenance into one file in
a shared spool directory. Writes are crash-atomic in the artifact-store
idiom: serialize to `<path>.tmp.<pid>`, `os.replace` into place, with a
crc32 over the canonical payload bytes in the envelope — a reader never
sees a torn spool, only the previous complete one.

`FleetAggregator` scans the directory and merges the live spools into
one registry with the rollup semantics the fleet page needs:

- **counters** are summed across processes into one unlabeled series —
  by construction the rollup conserves counts (a killed replica's final
  spool still contributes its tally; nothing is silently lost);
- **gauges** keep per-process identity: each series gains
  `{process,replica,slice}` labels (slice resolved through a
  `FaultDomainMap`, treating spool process names as hosts);
- **histograms** merge bucket counts and reservoirs via
  `Histogram.merge_state`, so fleet percentiles are computed over the
  union of every process's recent samples.

Staleness is classified from spool heartbeat age (`live` under
`staleness_s`, `stale` under `death_s`, `dead` beyond — or immediately
when a final spool declares status `dead`/`exited`), and the stale set
feeds `FaultDomainMap.classify_stale` so "both processes of slice 1 are
stale" reads as a slice loss, not two unrelated hiccups. The merged
page is exported with `ff_fleet_*` meta-series (process states,
heartbeat ages, spool read errors) and served by the
`python -m flexflow_tpu.obs fleet` CLI (table / `--prom` / `--watch`).
Format details: docs/observability.md ("Fleet observatory").
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from .metrics import MetricsRegistry

logger = logging.getLogger("flexflow_tpu.obs.fleet")

SPOOL_SCHEMA = 1
SPOOL_SUFFIX = ".spool.json"

# process states, in increasing order of concern
STATE_LIVE = "live"
STATE_STALE = "stale"
STATE_DEAD = "dead"
STATE_EXITED = "exited"  # clean shutdown (final spool said so)


class SpoolCorruptionError(RuntimeError):
    """A spool file failed its integrity check (schema / crc / JSON)."""


def _canonical_payload_bytes(payload: dict) -> bytes:
    return json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


class MetricSpool:
    """Per-process atomic spool writer.

    `write()` snapshots either the attached registry
    (`registry.export_state()`) or caller-supplied series records into
    `<dir>/<process>.spool.json`. Call it from a periodic loop (the
    serving autoscaler tick, the telemetry spool thread) and once more
    at shutdown with a terminal status so the aggregator can tell a
    clean exit from a death."""

    def __init__(self, dir: str, process: str, *,
                 registry: Optional[MetricsRegistry] = None,
                 replica: Optional[str] = None,
                 slice_id: Optional[int] = None):
        self.dir = dir
        self.process = process
        self.registry = registry
        self.replica = replica
        self.slice_id = slice_id
        os.makedirs(dir, exist_ok=True)

    @property
    def path(self) -> str:
        return os.path.join(self.dir, self.process + SPOOL_SUFFIX)

    def write(self, *, series: Optional[List[dict]] = None,
              status: str = STATE_LIVE,
              health: Optional[dict] = None,
              provenance: Optional[dict] = None) -> str:
        if series is None:
            series = (self.registry.export_state()
                      if self.registry is not None else [])
        payload = {
            "schema": SPOOL_SCHEMA,
            "process": self.process,
            "pid": os.getpid(),
            "replica": self.replica,
            "slice": self.slice_id,
            "unixtime": time.time(),
            "status": status,
            "health": health or {},
            "provenance": provenance or {},
            "series": series,
        }
        payload = json.loads(json.dumps(payload, default=str))
        envelope = {
            "schema": SPOOL_SCHEMA,
            "crc32": zlib.crc32(_canonical_payload_bytes(payload))
            & 0xFFFFFFFF,
            "payload": payload,
        }
        path = self.path
        tmp = path + f".tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            json.dump(envelope, f)
        os.replace(tmp, path)
        return path


def read_spool(path: str) -> dict:
    """Load + integrity-check one spool; returns the payload or raises
    SpoolCorruptionError. Thanks to the atomic replace, a concurrent
    writer can never make this raise — only a genuinely damaged file."""
    try:
        with open(path) as f:
            envelope = json.load(f)
    except json.JSONDecodeError as e:
        raise SpoolCorruptionError(f"{path}: not valid JSON ({e})") from e
    if not isinstance(envelope, dict) or "payload" not in envelope:
        raise SpoolCorruptionError(f"{path}: missing payload")
    payload = envelope["payload"]
    if envelope.get("schema") != SPOOL_SCHEMA:
        raise SpoolCorruptionError(
            f"{path}: schema {envelope.get('schema')!r} != {SPOOL_SCHEMA}")
    crc = zlib.crc32(_canonical_payload_bytes(payload)) & 0xFFFFFFFF
    if crc != envelope.get("crc32"):
        raise SpoolCorruptionError(
            f"{path}: crc32 mismatch ({envelope.get('crc32')!r} recorded, "
            f"{crc} computed)")
    return payload


@dataclasses.dataclass
class SpoolRecord:
    """One scanned spool: its payload plus the aggregator's verdict."""

    process: str
    path: str
    state: str  # live | stale | dead | exited
    age_s: float
    payload: Optional[dict] = None  # None when corrupt
    error: Optional[str] = None

    @property
    def replica(self) -> Optional[str]:
        return (self.payload or {}).get("replica")

    @property
    def slice_id(self) -> Optional[int]:
        return (self.payload or {}).get("slice")


@dataclasses.dataclass
class FleetView:
    """One aggregation pass: scanned records + the merged registry."""

    records: List[SpoolRecord]
    registry: MetricsRegistry
    classification: Optional[object] = None  # FailureClassification
    generated_at: float = 0.0

    def to_prometheus(self) -> str:
        return self.registry.to_prometheus()

    def counter_total(self, name: str, **labels) -> float:
        s = self.registry.find(name, **labels)
        return 0.0 if s is None else s.value

    def states(self) -> Dict[str, str]:
        return {r.process: r.state for r in self.records}

    def table(self) -> str:
        """Human-readable fleet table (the CLI's live view)."""
        cols = ("process", "state", "age", "replica", "slice", "requests")
        rows: List[Tuple[str, ...]] = [cols]
        for r in sorted(self.records, key=lambda r: r.process):
            requests = ""
            for rec in (r.payload or {}).get("series", []):
                if (rec.get("name") == "ff_serving_requests_total"
                        and rec.get("kind") == "counter"):
                    requests = str(int(rec.get("value", 0)))
                    break
            rows.append((
                r.process, r.state, f"{r.age_s:.1f}s",
                str(r.replica or "-"), str(r.slice_id
                                           if r.slice_id is not None
                                           else "-"),
                requests or "-",
            ))
        widths = [max(len(row[i]) for row in rows) for i in range(len(cols))]
        lines = ["  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
                 for row in rows]
        if self.classification is not None:
            lines.append("")
            lines.append(f"classification: {self.classification.describe()}")
        return "\n".join(lines)


class FleetAggregator:
    """Scan a spool directory and merge it into one fleet registry."""

    def __init__(self, dir: str, *, staleness_s: float = 10.0,
                 death_s: float = 30.0, fault_domains=None):
        self.dir = dir
        self.staleness_s = staleness_s
        self.death_s = death_s
        self.fault_domains = fault_domains

    # -- scanning --------------------------------------------------------
    def scan(self, now: Optional[float] = None) -> List[SpoolRecord]:
        now = time.time() if now is None else now
        records: List[SpoolRecord] = []
        if not os.path.isdir(self.dir):
            return records
        for fname in sorted(os.listdir(self.dir)):
            if not fname.endswith(SPOOL_SUFFIX):
                continue
            path = os.path.join(self.dir, fname)
            process = fname[: -len(SPOOL_SUFFIX)]
            try:
                payload = read_spool(path)
            except (SpoolCorruptionError, OSError) as e:
                records.append(SpoolRecord(
                    process=process, path=path, state=STATE_DEAD,
                    age_s=float("inf"), payload=None, error=str(e)))
                continue
            age = max(0.0, now - float(payload.get("unixtime", 0.0)))
            status = payload.get("status", STATE_LIVE)
            if status in (STATE_DEAD, STATE_EXITED):
                state = status  # the final spool already said so
            elif age >= self.death_s:
                state = STATE_DEAD
            elif age >= self.staleness_s:
                state = STATE_STALE
            else:
                state = STATE_LIVE
            records.append(SpoolRecord(process=process, path=path,
                                       state=state, age_s=age,
                                       payload=payload))
        return records

    # -- merging ---------------------------------------------------------
    def aggregate(self, records: Optional[List[SpoolRecord]] = None,
                  now: Optional[float] = None) -> FleetView:
        now = time.time() if now is None else now
        if records is None:
            records = self.scan(now)
        reg = MetricsRegistry()
        merge_conflicts = 0
        for r in records:
            if r.payload is None:
                continue
            ident = self._identity_labels(r)
            for rec in r.payload.get("series", []):
                name = rec.get("name")
                kind = rec.get("kind")
                labels = dict(rec.get("labels") or {})
                try:
                    if kind == "counter":
                        reg.counter(name, **labels).inc(
                            float(rec.get("value", 0.0)))
                    elif kind == "gauge":
                        reg.gauge(name, **labels, **ident).set(
                            float(rec.get("value", 0.0)))
                    elif kind == "histogram":
                        reg.histogram(name, **labels).merge_state(
                            rec["state"])
                except (ValueError, KeyError, TypeError) as e:
                    merge_conflicts += 1
                    logger.warning("fleet merge: skipping %s from %s (%s)",
                                   name, r.process, e)
        self._meta_series(reg, records, merge_conflicts, now)
        classification = self._classify(records)
        if classification is not None and classification.kind != "ok":
            reg.gauge("ff_fleet_lost_slices",
                      help="slices with every process stale/dead").set(
                          len(classification.lost_slices))
        return FleetView(records=records, registry=reg,
                         classification=classification, generated_at=now)

    def _identity_labels(self, r: SpoolRecord) -> Dict[str, str]:
        ident = {"process": r.process}
        if r.replica:
            ident["replica"] = str(r.replica)
        slice_id = r.slice_id
        if slice_id is None and self.fault_domains is not None:
            labels = self.fault_domains.host_labels(r.process)
            if labels:
                ident.update(labels)
        elif slice_id is not None:
            ident["slice"] = str(slice_id)
        return ident

    def _meta_series(self, reg: MetricsRegistry,
                     records: List[SpoolRecord],
                     merge_conflicts: int, now: float) -> None:
        by_state: Dict[str, int] = {}
        corrupt = 0
        for r in records:
            by_state[r.state] = by_state.get(r.state, 0) + 1
            if r.error is not None:
                corrupt += 1
            else:
                reg.gauge("ff_fleet_heartbeat_age_seconds",
                          help="seconds since each process's last spool",
                          process=r.process).set(r.age_s)
                reg.gauge("ff_fleet_process_up",
                          help="1 when the process's spool is live",
                          process=r.process).set(
                              1.0 if r.state == STATE_LIVE else 0.0)
        for state in (STATE_LIVE, STATE_STALE, STATE_DEAD, STATE_EXITED):
            reg.gauge("ff_fleet_processes",
                      help="spooled processes by health state",
                      state=state).set(by_state.get(state, 0))
        reg.gauge("ff_fleet_spools_corrupt",
                  help="spool files that failed integrity checks").set(
                      corrupt)
        reg.gauge("ff_fleet_merge_conflicts",
                  help="series skipped during merge (e.g. bucket "
                       "mismatch)").set(merge_conflicts)
        reg.gauge("ff_fleet_last_aggregate_unixtime",
                  help="when this fleet page was generated").set(now)

    def _classify(self, records: List[SpoolRecord]):
        if self.fault_domains is None:
            return None
        stale = [r.process for r in records
                 if r.state in (STATE_STALE, STATE_DEAD)]
        known = getattr(self.fault_domains, "hosts", None) or {}
        stale = [p for p in stale if p in known]
        try:
            return self.fault_domains.classify_stale(stale)
        except Exception as e:
            logger.warning("fleet classify_stale failed (%s)", e)
            return None

    # -- sentinel feed ---------------------------------------------------
    def observe_into(self, sentinel, records: Optional[List[SpoolRecord]]
                     = None, now: Optional[float] = None) -> None:
        """Feed per-process heartbeat gaps into an `AnomalySentinel`
        (`heartbeat_gap:<process>` gap detectors at the staleness
        limit), so a quietly-degrading process fires before the death
        window closes."""
        now = time.time() if now is None else now
        if records is None:
            records = self.scan(now)
        for r in records:
            if r.error is not None or r.state == STATE_EXITED:
                continue
            sentinel.observe_gap(f"heartbeat_gap:{r.process}", r.age_s,
                                 limit_s=self.staleness_s, now=now)
