"""Native (C++) runtime components with ctypes bindings.

The reference's runtime core is C++ (SURVEY §2 language note); this package
holds the pieces where native code genuinely pays on TPU hosts: the
prefetching data loader (src/dataloader.cc — GIL-free shuffled batch
gather, reference python/flexflow_dataloader.cc) and the task-graph
simulator + MCMC annealing loop (src/simulator.cc — reference
src/runtime/simulator.cc + model.cc mcmc_optimize).

The shared library is built on first use with g++ (cached next to the
sources); every consumer has a pure-Python fallback so the framework works
without a toolchain.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src")
_LIB_PATH = os.path.join(_HERE, "libffnative.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _sources():
    return sorted(
        os.path.join(_SRC, f) for f in os.listdir(_SRC) if f.endswith(".cc")
    )


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(os.path.getmtime(s) > lib_mtime for s in _sources())


def build(force: bool = False) -> Optional[str]:
    """Compile the native library. Returns its path or None on failure."""
    global _build_failed
    with _lock:
        if not force and not _needs_build():
            return _LIB_PATH
        cmd = [
            "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
            "-o", _LIB_PATH, *_sources(),
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            _build_failed = False
            return _LIB_PATH
        except Exception:
            _build_failed = True
            return None


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    path = build()
    if path is None:
        return None
    with _lock:
        if _lib is None:
            lib = ctypes.CDLL(path)
            _configure(lib)
            _lib = lib
    return _lib


def _configure(lib: ctypes.CDLL) -> None:
    i64 = ctypes.c_int64
    u64 = ctypes.c_uint64
    dbl = ctypes.c_double
    ptr = ctypes.c_void_p
    # dataloader
    lib.ffdl_create.restype = ptr
    lib.ffdl_create.argtypes = [ptr, i64, i64, i64, ctypes.c_int, u64, i64]
    lib.ffdl_next.restype = i64
    lib.ffdl_next.argtypes = [ptr, ptr]
    lib.ffdl_reset.argtypes = [ptr]
    lib.ffdl_batches_per_epoch.restype = i64
    lib.ffdl_batches_per_epoch.argtypes = [ptr]
    lib.ffdl_destroy.argtypes = [ptr]
    # simulator
    I64P = ctypes.POINTER(i64)
    DP = ctypes.POINTER(dbl)
    lib.ffsim_create.restype = ptr
    lib.ffsim_create.argtypes = [
        i64, i64, I64P, I64P, I64P, i64, I64P, I64P, i64, I64P, I64P, I64P,
        i64, DP, DP, DP, dbl, dbl,
    ]
    lib.ffsim_simulate.restype = dbl
    lib.ffsim_simulate.argtypes = [ptr, I64P]
    lib.ffsim_mcmc.restype = dbl
    lib.ffsim_mcmc.argtypes = [ptr, I64P, i64, dbl, u64]
    lib.ffsim_destroy.argtypes = [ptr]


def available() -> bool:
    return get_lib() is not None
