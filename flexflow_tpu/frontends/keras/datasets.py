"""Keras-style dataset loaders (reference: python/flexflow/keras/datasets —
mnist/cifar10/reuters wrappers).

This environment has no network egress, so each loader first looks for a
local copy (path or KERAS_DATA_DIR), then falls back to a deterministic
synthetic dataset with the right shapes/dtypes so examples and tests run
anywhere. The synthetic data is linearly separable so models actually train.
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np


def _synthetic_classification(n, shape, classes, seed):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, *shape).astype(np.float32)
    w = rng.randn(int(np.prod(shape)), classes).astype(np.float32)
    y = np.argmax(x.reshape(n, -1) @ w, axis=1).astype(np.int64)
    return x, y


def _try_npz(name: str):
    root = os.environ.get("KERAS_DATA_DIR", os.path.expanduser("~/.keras/datasets"))
    path = os.path.join(root, name)
    if os.path.exists(path):
        return np.load(path, allow_pickle=True)
    return None


class mnist:
    @staticmethod
    def load_data(n_train: int = 8192, n_test: int = 1024, seed: int = 0):
        d = _try_npz("mnist.npz")
        if d is not None:
            return (d["x_train"], d["y_train"]), (d["x_test"], d["y_test"])
        xtr, ytr = _synthetic_classification(n_train, (28, 28), 10, seed)
        xte, yte = _synthetic_classification(n_test, (28, 28), 10, seed + 1)
        return ((xtr * 255).astype(np.uint8), ytr), ((xte * 255).astype(np.uint8), yte)


class cifar10:
    @staticmethod
    def load_data(n_train: int = 8192, n_test: int = 1024, seed: int = 0):
        d = _try_npz("cifar10.npz")
        if d is not None:
            return (d["x_train"], d["y_train"]), (d["x_test"], d["y_test"])
        xtr, ytr = _synthetic_classification(n_train, (32, 32, 3), 10, seed)
        xte, yte = _synthetic_classification(n_test, (32, 32, 3), 10, seed + 1)
        return (
            ((xtr * 255).astype(np.uint8), ytr[:, None]),
            ((xte * 255).astype(np.uint8), yte[:, None]),
        )


class reuters:
    @staticmethod
    def load_data(num_words: int = 10000, n_train: int = 8192, n_test: int = 1024,
                  maxlen: int = 80, seed: int = 0):
        rng = np.random.RandomState(seed)
        xtr = rng.randint(1, num_words, (n_train, maxlen)).astype(np.int64)
        ytr = rng.randint(0, 46, (n_train,)).astype(np.int64)
        xte = rng.randint(1, num_words, (n_test, maxlen)).astype(np.int64)
        yte = rng.randint(0, 46, (n_test,)).astype(np.int64)
        return (xtr, ytr), (xte, yte)
