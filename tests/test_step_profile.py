"""Step-observatory tests (flexflow_tpu/obs/step_profile.py): in-situ
capture of the real jitted training step (instrumented CPU fallback),
the simulated/measured overlay, overlap-realization measurement + its
calibration write-through, HBM watermark reconciliation, counter-event
round-trip, and the BENCH-history regression attribution."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from flexflow_tpu import (
    ActiMode,
    DataType,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
    TelemetryConfig,
)
import flexflow_tpu.obs as obs
from flexflow_tpu.obs.step_profile import (
    MEASURED_CAT,
    OVERLAY_FILE,
    HbmSampler,
    bench_regression_attribution,
    capture_step_profile,
    load_bench_history,
)
from flexflow_tpu.obs.tracer import (
    Tracer,
    read_events_jsonl,
    to_chrome_trace,
    validate_event,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_session():
    obs.finish()
    yield
    obs.finish()


def small_model():
    """Default config (no search) -> manual lowering -> data degree =
    ndev, so the capture actually measures grad-sync collectives."""
    cfg = FFConfig()
    cfg.batch_size = 8
    m = FFModel(cfg)
    x = m.create_tensor((8, 4), DataType.DT_FLOAT)
    t = m.dense(x, 16, ActiMode.AC_MODE_RELU)
    t = m.dense(t, 3)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.1),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              [MetricsType.METRICS_ACCURACY])
    return m


def data(n=32, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, 4).astype(np.float32),
            rng.randint(0, 3, (n, 1)).astype(np.int32))


@pytest.fixture(scope="module")
def captured():
    """One capture shared by the read-only assertions (the capture jits
    the fused + serial steps and every isolated collective — too slow to
    repeat per test)."""
    m = small_model()
    x, y = data()
    prof = capture_step_profile(m, x, y, batch_size=8, repeats=1, warmup=1)
    return m, prof


# ----------------------------------------------------------------------
# capture: CPU fallback, event schema, realization bounds
# ----------------------------------------------------------------------
def test_cpu_capture_falls_back_to_instrumented(captured):
    _, prof = captured
    assert prof.mode == "instrumented"
    assert prof.backend == "cpu"
    assert prof.step_wall_s > 0
    assert prof.serial_step_wall_s > 0


def test_capture_events_are_schema_valid(captured):
    _, prof = captured
    assert prof.events, "capture produced no timeline events"
    for e in prof.events:
        assert validate_event(e) == [], e
        assert e["cat"] == MEASURED_CAT
    names = {e["name"] for e in prof.events}
    # forward, backward, and grad-sync spans of the two dense layers
    assert "op_linear_0" in names
    assert "op_linear_0.bwd" in names
    assert "op_linear_0.grad_sync" in names


def test_collectives_measured_on_data_parallel_mesh(captured):
    m, prof = captured
    assert prof.data_degree == m.executor.mesh.shape["data"] > 1
    assert prof.collectives, "no grad-sync collectives measured"
    for c in prof.collectives:
        assert c.sync_s > 0
        assert c.wire_bytes > 0
        assert 0.0 <= c.hidden_s <= c.sync_s + 1e-12
        assert c.exposed_s >= 0.0
    bw = prof.collective_bandwidths()
    assert bw and all(v > 0 for v in bw.values())


def test_realized_ratio_bounds(captured):
    _, prof = captured
    r = prof.realized_ratio
    assert r is not None
    assert 0.0 <= r <= 1.0


def test_grad_sync_spans_carry_attribution_args(captured):
    _, prof = captured
    syncs = [e for e in prof.events if e["name"].endswith(".grad_sync")]
    assert len(syncs) == len(prof.collectives)
    for e in syncs:
        a = e["args"]
        assert a["source"] == "measured_isolated"
        assert a["hidden_s"] + a["exposed_s"] == pytest.approx(e["dur"])
        assert a["bytes_per_s"] > 0


# ----------------------------------------------------------------------
# overlay: one file, two process groups, shared timebase
# ----------------------------------------------------------------------
def test_overlay_has_both_process_groups(tmp_path, captured):
    from flexflow_tpu.obs.step_profile import export_overlay

    m, prof = captured
    path = export_overlay(prof, m, str(tmp_path / "overlay.json"))
    doc = json.load(open(path))
    evs = doc["traceEvents"]
    groups = {e["args"]["name"] for e in evs if e.get("ph") == "M"}
    assert {"simulated", "measured"} <= groups
    spans = [e for e in evs if e.get("ph") == "X"]
    assert min(e["ts"] for e in spans) == 0.0  # rebased shared timebase
    pid_names = {e["pid"]: e["args"]["name"] for e in evs
                 if e.get("ph") == "M"}
    by_group = {g: 0 for g in ("simulated", "measured")}
    for e in spans:
        g = pid_names.get(e["pid"])
        if g in by_group:
            by_group[g] += 1
    assert by_group["simulated"] > 0 and by_group["measured"] > 0


# ----------------------------------------------------------------------
# HBM: sampler fallback + reconciliation ratio
# ----------------------------------------------------------------------
def test_hbm_sampler_cpu_fallback(captured):
    _, prof = captured
    assert prof.hbm is not None
    # CPU devices have no memory_stats -> live_arrays allocator estimate
    assert prof.hbm.source == "live_arrays"
    assert prof.hbm.measured_peak > 0
    assert prof.hbm.peak_bytes  # per-device watermarks


def test_hbm_static_accuracy_ratio(captured):
    _, prof = captured
    acc = prof.hbm.static_accuracy
    assert acc is not None and acc > 0
    assert acc == pytest.approx(
        prof.hbm.static_peak / prof.hbm.measured_peak)


def test_hbm_sampler_direct():
    import jax

    s = HbmSampler(jax.local_devices())
    s.sample()
    assert s.source in ("memory_stats", "live_arrays")
    assert s.peak and all(v >= 0 for v in s.peak.values())


def test_memory_reconciliation_diagnostics():
    from flexflow_tpu.analysis.memory import (
        memory_reconciliation_diagnostics,
    )

    rep, ratio = memory_reconciliation_diagnostics(
        {0: 800}, {0: 1000}, source="live_arrays")
    assert ratio == pytest.approx(0.8)
    assert any(d.severity.name == "WARNING" for d in rep)  # under-predicts
    rep2, ratio2 = memory_reconciliation_diagnostics({}, {0: 1000})
    assert ratio2 is None
    assert not rep2.warnings


# ----------------------------------------------------------------------
# telemetry session: publish + calibration write-through
# ----------------------------------------------------------------------
def test_fit_step_profile_session_artifacts(tmp_path):
    m = small_model()
    x, y = data()
    teldir = str(tmp_path / "tel")
    calib = str(tmp_path / "calib.json")
    m.fit(x, y, batch_size=8, epochs=1, verbose=False,
          telemetry=TelemetryConfig(dir=teldir, step_profile=True,
                                    step_profile_repeats=1,
                                    calibration_path=calib))
    events, problems = read_events_jsonl(os.path.join(teldir,
                                                      "events.jsonl"))
    assert not problems
    counters = [e for e in events if e["ph"] == "C"]
    assert counters, "no hbm_bytes counter tracks"
    assert any(e["name"] == "step_profile" for e in events)
    overlay = json.load(open(os.path.join(teldir, OVERLAY_FILE)))
    groups = {e["args"]["name"] for e in overlay["traceEvents"]
              if e.get("ph") == "M"}
    assert {"simulated", "measured"} <= groups
    prom = open(os.path.join(teldir, "metrics.prom")).read()
    assert "ff_overlap_realized_ratio" in prom
    assert "ff_hbm_peak_bytes" in prom
    assert "ff_hbm_static_accuracy" in prom
    glb = json.load(open(calib))["globals"]
    assert 0 < glb["overlap_efficiency"] <= 1.0
    assert glb["collective_bytes_per_s"]


def test_calibration_write_through_to_fresh_process(tmp_path):
    """The acceptance loop: a session capture writes the measured
    overlap efficiency + collective bandwidths, and a FRESH process's
    compile(calibration=...) prices overlap from them (reported in the
    cost model's provenance)."""
    m = small_model()
    x, y = data()
    calib = str(tmp_path / "calib.json")
    m.fit(x, y, batch_size=8, epochs=1, verbose=False,
          telemetry=TelemetryConfig(dir=str(tmp_path / "tel"),
                                    step_profile=True,
                                    step_profile_repeats=1,
                                    calibration_path=calib))
    code = f"""
import json
from flexflow_tpu import (ActiMode, DataType, FFConfig, FFModel, LossType,
                          MetricsType, SGDOptimizer)
cfg = FFConfig()
cfg.batch_size = 8
m = FFModel(cfg)
x = m.create_tensor((8, 4), DataType.DT_FLOAT)
t = m.dense(x, 16, ActiMode.AC_MODE_RELU)
t = m.softmax(m.dense(t, 3))
m.compile(SGDOptimizer(lr=0.1),
          LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
          [MetricsType.METRICS_ACCURACY], calibration={calib!r})
print(json.dumps(m._build_cost_model().provenance()))
"""
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=os.environ.copy(), timeout=300)
    assert r.returncode == 0, r.stderr
    prov = json.loads(r.stdout.strip().splitlines()[-1])
    assert prov["overlap_efficiency_source"] == "calibration_store"
    assert 0 < prov["overlap_efficiency"] <= 1.0
    assert prov["collective_bytes_per_s"]


# ----------------------------------------------------------------------
# counter events (satellite: tracer ph="C")
# ----------------------------------------------------------------------
def test_counter_event_round_trip(tmp_path):
    path = str(tmp_path / "events.jsonl")
    tr = Tracer(path)
    tr.counter("hbm_bytes", cat="measured", tid=3, device3=123.0)
    tr.flush()
    events, problems = read_events_jsonl(path)
    assert not problems
    [e] = events
    assert e["ph"] == "C"
    assert e["args"] == {"device3": 123.0}
    chrome = to_chrome_trace(events)
    entry = next(c for c in chrome["traceEvents"] if c.get("ph") == "C")
    assert entry["args"] == {"device3": 123.0}  # series pass through
    assert "s" not in entry  # instant-scope key must not leak onto C


def test_counter_event_validation():
    ok = {"ts": 0.0, "ph": "C", "name": "n", "cat": "c",
          "tid": 0, "args": {"v": 1.0}}
    assert validate_event(ok) == []
    bad_empty = dict(ok, args={})
    assert validate_event(bad_empty)
    bad_value = dict(ok, args={"v": "high"})
    assert validate_event(bad_value)
    bad_bool = dict(ok, args={"v": True})
    assert validate_event(bad_bool)


# ----------------------------------------------------------------------
# bench history + regression attribution
# ----------------------------------------------------------------------
def _round(tmp_path, n, value, phases=None, **extra):
    doc = {"n": n, "parsed": {"metric": "transformer_train_throughput",
                              "value": value, "unit": "samples/s/chip",
                              **extra}}
    if phases is not None:
        doc["parsed"]["phases_s_per_step"] = phases
    with open(tmp_path / f"BENCH_r{n:02d}.json", "w") as f:
        json.dump(doc, f)


def test_load_bench_history_tolerates_old_rounds(tmp_path):
    _round(tmp_path, 1, 100.0)  # old round: no phases/n_chips/backend
    _round(tmp_path, 2, 110.0, phases={"fwd": 0.02, "bwd": 0.04,
                                       "opt": 0.002, "sync": 0.001},
           n_chips=1, backend="tpu", jax_version="0.4.37")
    hist = load_bench_history(str(tmp_path))
    assert [r["round"] for r in hist] == [1, 2]
    assert hist[0]["phases"] is None and hist[0]["n_chips"] is None
    assert hist[1]["phases"]["fwd"] == 0.02
    assert hist[1]["backend"] == "tpu"


def test_bench_regression_attribution(tmp_path):
    _round(tmp_path, 1, 100.0, phases={"fwd": 0.020, "bwd": 0.040,
                                       "opt": 0.002, "sync": 0.001})
    _round(tmp_path, 2, 80.0, phases={"fwd": 0.032, "bwd": 0.041,
                                      "opt": 0.002, "sync": 0.001})
    att = bench_regression_attribution(load_bench_history(str(tmp_path)),
                                       tolerance=0.05)
    assert att["status"] == "ok"
    assert att["regressed"]
    assert att["throughput_ratio"] == pytest.approx(0.8)
    assert att["dominant_phase"] == "fwd"
    fwd = att["phases"]["fwd"]
    assert fwd["delta_s"] == pytest.approx(0.012)
    assert fwd["share_of_regression"] > 0.9


def test_bench_regression_attribution_insufficient(tmp_path):
    _round(tmp_path, 1, 100.0)
    att = bench_regression_attribution(load_bench_history(str(tmp_path)))
    assert att["status"] == "insufficient_history"


# ----------------------------------------------------------------------
# CLI + gate script
# ----------------------------------------------------------------------
def test_cli_bench_subcommand(tmp_path):
    _round(tmp_path, 1, 100.0, phases={"fwd": 0.02, "bwd": 0.04,
                                       "opt": 0.002, "sync": 0.001})
    _round(tmp_path, 2, 90.0, phases={"fwd": 0.025, "bwd": 0.04,
                                      "opt": 0.002, "sync": 0.001})
    r = subprocess.run(
        [sys.executable, "-m", "flexflow_tpu.obs", "bench",
         "--src", str(tmp_path), "--tolerance", "0.05", "--strict"],
        capture_output=True, text=True, env=os.environ.copy(), timeout=300)
    assert r.returncode == 1, r.stdout + r.stderr  # regressed + --strict
    assert "dominant phase: fwd" in r.stdout


def test_cli_summary_reports_step_observatory(tmp_path):
    m = small_model()
    x, y = data()
    teldir = str(tmp_path / "tel")
    m.fit(x, y, batch_size=8, epochs=1, verbose=False,
          telemetry=TelemetryConfig(dir=teldir, step_profile=True,
                                    step_profile_repeats=1))
    r = subprocess.run(
        [sys.executable, "-m", "flexflow_tpu.obs", "summary",
         os.path.join(teldir, "events.jsonl")],
        capture_output=True, text=True, env=os.environ.copy(), timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "step observatory" in r.stdout
    assert "overlap realization" in r.stdout
    assert "measured-vs-simulated drift" in r.stdout


def test_bench_regression_script_phase_gate(tmp_path):
    _round(tmp_path, 6, 480.0, phases={"fwd": 0.020, "bwd": 0.040,
                                       "opt": 0.002, "sync": 0.001})
    line = json.dumps({"metric": "transformer_train_throughput",
                       "value": 470.0, "unit": "samples/s/chip",
                       "phases_s_per_step": {"fwd": 0.026, "bwd": 0.041,
                                             "opt": 0.002, "sync": 0.001}})
    script = os.path.join(REPO, "scripts", "bench_regression.py")
    r = subprocess.run(
        [sys.executable, script, "-", "--history-dir", str(tmp_path)],
        input=line, capture_output=True, text=True,
        env=os.environ.copy(), timeout=300)
    assert r.returncode == 1, r.stdout + r.stderr  # fwd +30% > 15%
    assert "phase fwd" in r.stdout
    r2 = subprocess.run(
        [sys.executable, script, "-", "--history-dir", str(tmp_path),
         "--warn-only"],
        input=line, capture_output=True, text=True,
        env=os.environ.copy(), timeout=300)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    r3 = subprocess.run(
        [sys.executable, script, "-", "--history-dir", str(tmp_path),
         "--phase-tolerance", "fwd=0.5"],
        input=line, capture_output=True, text=True,
        env=os.environ.copy(), timeout=300)
    assert r3.returncode == 0, r3.stdout + r3.stderr


# ----------------------------------------------------------------------
# explain: in-situ join
# ----------------------------------------------------------------------
def test_explain_joins_in_situ_measurements(captured):
    m, prof = captured
    exp = obs.explain_strategy(m, repeats=1, warmup=1, step_profile=prof)
    rows = [r for r in exp.rows if r.get("insitu_total_s") is not None]
    assert rows, "no explain row joined an in-situ measurement"
    for r in rows:
        assert r["insitu_total_s"] > 0
        assert r["insitu_source"] == "instrumented"
    assert "insitu ms" in exp.summary(5)


# ----------------------------------------------------------------------
# overlap-realization analysis (FFA506)
# ----------------------------------------------------------------------
def test_overlap_realization_diagnostics(captured):
    from flexflow_tpu.analysis.perf import overlap_realization_diagnostics

    _, prof = captured
    rep = overlap_realization_diagnostics(prof)
    assert any(d.code == "FFA506" for d in rep)
    # realized on CPU is far below the assumed discount -> must warn
    if prof.realized_ratio is not None and \
            prof.realized_ratio < prof.assumed_efficiency - 0.1:
        assert rep.warnings
