"""Step hot-path perf features (ISSUE 8): comm/compute-overlapped
gradient sync (reduce-scatter + sharded update + all-gather), RNG-threaded
flash dropout, and the search's overlappable-collective discount.

All on the virtual CPU mesh: the flash kernels run in interpret mode, the
overlapped step runs on the conftest's 8-device mesh (any data degree > 1
works, so the 8/4-device perf_check.sh sweep passes too)."""
import math
import warnings as warnings_mod

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu.kernels.attention import (
    attention_dropout_mask,
    dropout_seeds,
    flash_attention_folded,
)

RNG = np.random.RandomState(0)


# ---------------------------------------------------------------------------
# RNG-threaded flash dropout (kernels/attention.py, interpret mode)
# ---------------------------------------------------------------------------

def _dense_dropout_ref(qf, kf, vf, seeds, rate, causal):
    """The dense path's math with the SAME counter-based mask the flash
    kernels regenerate blockwise — the parity oracle."""
    bh, sq, d = qf.shape
    sk = kf.shape[1]
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) / math.sqrt(d)
    if causal:
        tri = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(tri[None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    keep = attention_dropout_mask(seeds, rate, bh, sq, sk)
    p = jnp.where(keep, p * (1.0 / (1.0 - rate)), 0.0)
    return jnp.einsum("bqk,bkd->bqd", p, vf)


def _folded_qkv(bh=4, sq=32, sk=32, d=16):
    return (
        jnp.asarray(RNG.randn(bh, sq, d).astype(np.float32)),
        jnp.asarray(RNG.randn(bh, sk, d).astype(np.float32)),
        jnp.asarray(RNG.randn(bh, sk, d).astype(np.float32)),
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_dropout_forward_matches_dense(causal):
    qf, kf, vf = _folded_qkv()
    seeds = dropout_seeds(jax.random.PRNGKey(42))
    rate = 0.3
    ours = flash_attention_folded(qf, kf, vf, causal, True,
                                  dropout=rate, seeds=seeds)
    ref = _dense_dropout_ref(qf, kf, vf, seeds, rate, causal)
    # same mask by construction: a single mask disagreement would shift
    # an output element by a full prob*value, far outside this atol
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref),
                               atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_dropout_backward_matches_dense(causal):
    qf, kf, vf = _folded_qkv()
    seeds = dropout_seeds(jax.random.PRNGKey(7))
    rate = 0.25

    def ours_loss(q_, k_, v_):
        return jnp.sum(flash_attention_folded(
            q_, k_, v_, causal, True, dropout=rate, seeds=seeds) ** 2)

    def ref_loss(q_, k_, v_):
        return jnp.sum(_dense_dropout_ref(q_, k_, v_, seeds, rate,
                                          causal) ** 2)

    g1 = jax.grad(ours_loss, argnums=(0, 1, 2))(qf, kf, vf)
    g2 = jax.grad(ref_loss, argnums=(0, 1, 2))(qf, kf, vf)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


def test_flash_dropout_blocked_backward_matches_dense(monkeypatch):
    """The kv-blocked backward schedule (FF_FLASH_BWD_BK) must regenerate
    the same mask per block — offsets, not materialization."""
    monkeypatch.setenv("FF_FLASH_BWD_BK", "8")
    qf, kf, vf = _folded_qkv(bh=2, sq=16, sk=32)
    seeds = dropout_seeds(jax.random.PRNGKey(3))
    rate = 0.4
    g1 = jax.grad(lambda k_: jnp.sum(flash_attention_folded(
        qf, k_, vf, False, True, dropout=rate, seeds=seeds) ** 2))(kf)
    g2 = jax.grad(lambda k_: jnp.sum(_dense_dropout_ref(
        qf, k_, vf, seeds, rate, False) ** 2))(kf)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=3e-4)


def test_dropout_mask_deterministic_and_rate():
    seeds = dropout_seeds(jax.random.PRNGKey(0))
    m1 = attention_dropout_mask(seeds, 0.3, 32, 64, 64)
    m2 = attention_dropout_mask(seeds, 0.3, 32, 64, 64)
    np.testing.assert_array_equal(np.asarray(m1), np.asarray(m2))
    frac = float(jnp.mean(m1))
    assert 0.67 < frac < 0.73, f"keep fraction {frac} far from 0.7"
    other = attention_dropout_mask(
        dropout_seeds(jax.random.PRNGKey(1)), 0.3, 32, 64, 64)
    assert not bool(jnp.all(m1 == other)), "different keys, same mask"


def test_flash_dropout_needs_seeds():
    qf, kf, vf = _folded_qkv(bh=2, sq=8, sk=8, d=8)
    with pytest.raises(ValueError, match="seeds"):
        flash_attention_folded(qf, kf, vf, False, True, dropout=0.5)


def test_dense_path_uses_shared_mask():
    """The MHA op's dense dropout path draws the SAME counter-based mask
    (ops/attention.py) — pinned by recomputing it from the op's rng."""
    from flexflow_tpu.ff_types import DataType, OperatorType
    from flexflow_tpu.ops import attention as mha
    from flexflow_tpu.ops.registry import FwdCtx, get_op_def

    params = mha.MultiHeadAttentionParams(embed_dim=16, num_heads=2,
                                          dropout=0.5)
    opdef = get_op_def(OperatorType.OP_MULTIHEAD_ATTENTION)
    x = jnp.asarray(RNG.randn(2, 8, 16).astype(np.float32))
    ws = opdef.weights(params, [(2, 8, 16)] * 3, [DataType.DT_FLOAT] * 3)
    key = jax.random.PRNGKey(5)
    weights = {}
    for w in ws:
        key, sub = jax.random.split(key)
        weights[w.name] = jax.random.normal(sub, w.shape, jnp.float32) * 0.1
    rng = jax.random.PRNGKey(11)
    ctx = FwdCtx(training=True, rng=rng, op_name="mha0")
    out, = opdef.forward(params, weights, [x, x, x], ctx)
    out2, = opdef.forward(params, weights, [x, x, x], ctx)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
    # a different key must flip some mask bits -> different output
    ctx2 = FwdCtx(training=True, rng=jax.random.PRNGKey(12), op_name="mha0")
    out3, = opdef.forward(params, weights, [x, x, x], ctx2)
    assert not np.allclose(np.asarray(out), np.asarray(out3))


# ---------------------------------------------------------------------------
# dropout-fallback warn-once + metric (ops/attention.py satellite)
# ---------------------------------------------------------------------------

def test_dropout_fallback_warns_once_and_counts(monkeypatch, tmp_path):
    from flexflow_tpu import obs
    from flexflow_tpu.ff_types import DataType, OperatorType
    from flexflow_tpu.obs import TelemetryConfig
    from flexflow_tpu.ops import attention as mha
    from flexflow_tpu.ops.registry import FwdCtx, get_op_def

    monkeypatch.setenv("FF_ATTENTION_IMPL", "chunked")
    mha.reset_attention_fallback_warnings()
    params = mha.MultiHeadAttentionParams(embed_dim=16, num_heads=2,
                                          dropout=0.5)
    opdef = get_op_def(OperatorType.OP_MULTIHEAD_ATTENTION)
    x = jnp.asarray(RNG.randn(2, 8, 16).astype(np.float32))
    ws = opdef.weights(params, [(2, 8, 16)] * 3, [DataType.DT_FLOAT] * 3)
    key = jax.random.PRNGKey(5)
    weights = {}
    for w in ws:
        key, sub = jax.random.split(key)
        weights[w.name] = jax.random.normal(sub, w.shape, jnp.float32) * 0.1

    with obs.session(TelemetryConfig(dir=str(tmp_path / "tel"))):
        ctx = FwdCtx(training=True, rng=key, op_name="layer0")
        with pytest.warns(UserWarning, match="dense path"):
            opdef.forward(params, weights, [x, x, x], ctx)
        # same (impl, layer, reason): warning deduped, metric still counts
        with warnings_mod.catch_warnings():
            warnings_mod.simplefilter("error")
            opdef.forward(params, weights, [x, x, x], ctx)
        # a DIFFERENT layer warns again
        ctx1 = FwdCtx(training=True, rng=key, op_name="layer1")
        with pytest.warns(UserWarning, match="layer1"):
            opdef.forward(params, weights, [x, x, x], ctx1)
        c = obs.active().metrics.find("ff_attention_fallback_total",
                                      reason="kernel")
        assert c is not None and c.value == 3.0


# ---------------------------------------------------------------------------
# overlapped RS/update/AG step (parallel/executor.py tentpole)
# ---------------------------------------------------------------------------

def _data_degree() -> int:
    return len(jax.devices())


def _small_model(overlap: bool, optimizer):
    from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType
    from flexflow_tpu.ff_types import ActiMode, DataType

    cfg = FFConfig()
    cfg.batch_size = 8
    cfg.overlap_backward_update = overlap
    m = FFModel(cfg)
    x = m.create_tensor((8, 16), DataType.DT_FLOAT, name="x")
    t = m.dense(x, 32, ActiMode.AC_MODE_RELU)
    t = m.dense(t, 16, ActiMode.AC_MODE_NONE)
    m.compile(
        optimizer=optimizer,
        loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR],
    )
    return m


def _run_steps(model, *, steps=3, guard=False):
    import dataclasses

    from flexflow_tpu.runtime.resilience import StepGuardConfig

    ex = model.executor
    if guard:
        ex.set_step_guard(StepGuardConfig())
    st = model.state
    if guard:
        st = dataclasses.replace(st, guard=ex.init_guard_state())
    step = ex.build_train_step(donate=False)
    X = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    Y = np.random.RandomState(1).randn(8, 16).astype(np.float32)
    xb = ex.shard_batch(ex.input_pts[0], X)
    yb = ex.put_replicated(Y)
    key = ex.put_replicated(jax.random.PRNGKey(7))
    partials = None
    for _ in range(steps):
        st, partials = step(st, [xb], yb, key)
    return st, partials


def _assert_states_close(s0, s1):
    for a, b in zip(jax.tree_util.tree_leaves(s0.params),
                    jax.tree_util.tree_leaves(s1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-6, atol=1e-7)
    o0 = [x for x in jax.tree_util.tree_leaves(s0.opt_state)
          if x is not None]
    o1 = [x for x in jax.tree_util.tree_leaves(s1.opt_state)
          if x is not None]
    for a, b in zip(o0, o1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-6, atol=1e-7)


@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="overlap needs a data degree > 1")
@pytest.mark.parametrize("guard", [False, True])
def test_overlapped_step_matches_allreduce_sgd(guard):
    from flexflow_tpu import SGDOptimizer

    m0 = _small_model(False, SGDOptimizer(lr=0.05, momentum=0.9))
    s0, p0 = _run_steps(m0, guard=guard)
    m1 = _small_model(True, SGDOptimizer(lr=0.05, momentum=0.9))
    assert m1.executor._overlap_specs(), "no weights eligible for overlap"
    s1, p1 = _run_steps(m1, guard=guard)
    _assert_states_close(s0, s1)
    np.testing.assert_allclose(float(p0["loss"]), float(p1["loss"]),
                               rtol=1e-5)
    if guard:
        # the fused per-shard guard norm equals the full-tree norm
        np.testing.assert_allclose(float(p0["grad_norm"]),
                                   float(p1["grad_norm"]), rtol=1e-5)
        assert float(p1["skipped"]) == 0.0


@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="overlap needs a data degree > 1")
@pytest.mark.parametrize("guard", [False, True])
def test_overlapped_step_matches_allreduce_adam(guard):
    from flexflow_tpu.core.optimizers import AdamOptimizer

    m0 = _small_model(False, AdamOptimizer(alpha=1e-3))
    s0, _ = _run_steps(m0, guard=guard)
    m1 = _small_model(True, AdamOptimizer(alpha=1e-3))
    s1, _ = _run_steps(m1, guard=guard)
    _assert_states_close(s0, s1)


@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="overlap needs a data degree > 1")
def test_overlap_shards_optimizer_state_zero1():
    """The sharded update never gathers m/v: optimizer state LIVES
    sharded over the data axis (ZeRO-1), before and after a step."""
    from flexflow_tpu.core.optimizers import AdamOptimizer

    m = _small_model(True, AdamOptimizer(alpha=1e-3))
    d = _data_degree()
    op_name = next(iter(m.state.params))

    def assert_sharded(leaf):
        spec = leaf.sharding.spec
        assert len(spec) >= 1 and spec[0] == "data", spec
        shard = leaf.addressable_shards[0].data.shape
        assert shard[0] == leaf.shape[0] // d

    assert_sharded(m.state.opt_state["m"][op_name]["kernel"])
    st, _ = _run_steps(m, steps=1)
    assert_sharded(st.opt_state["m"][op_name]["kernel"])
    # params stay replicated (all-gathered after the sharded update)
    p = st.params[op_name]["kernel"]
    assert p.sharding.spec == jax.sharding.PartitionSpec()


@pytest.mark.skipif(len(jax.devices()) < 2,
                    reason="overlap needs a data degree > 1")
def test_overlap_scan_driver_matches_stepwise():
    """build_train_scan shares the step program, so the fused multi-step
    driver sees the same overlapped schedule."""
    from flexflow_tpu import SGDOptimizer

    m = _small_model(True, SGDOptimizer(lr=0.05))
    ex = m.executor
    X = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    Y = np.random.RandomState(1).randn(8, 16).astype(np.float32)
    keys = jax.random.split(jax.random.PRNGKey(7), 3)

    scan = ex.build_train_scan()
    xs = [ex.shard_batch_stack(ex.input_pts[0],
                               np.broadcast_to(X, (3,) + X.shape))]
    ys = ex.put_replicated(np.broadcast_to(Y, (3,) + Y.shape))
    st_scan, _ = scan(m.state, xs, ys, ex.put_replicated(keys))

    m2 = _small_model(True, SGDOptimizer(lr=0.05))
    ex2 = m2.executor
    step = ex2.build_train_step(donate=False)
    st = m2.state
    xb = ex2.shard_batch(ex2.input_pts[0], X)
    yb = ex2.put_replicated(Y)
    for i in range(3):
        st, _ = step(st, [xb], yb, ex2.put_replicated(keys[i]))
    _assert_states_close(st_scan, st)


def test_set_overlap_grad_sync_invalidates_cache():
    from flexflow_tpu import SGDOptimizer

    m = _small_model(True, SGDOptimizer(lr=0.05))
    ex = m.executor
    f1 = ex.build_train_step()
    ex.set_overlap_grad_sync(False)
    assert ex._overlap_specs() == {}
    f2 = ex.build_train_step()
    assert f1 is not f2
    ex.set_overlap_grad_sync(False)  # no-op keeps the cache
    assert ex.build_train_step() is f2


# ---------------------------------------------------------------------------
# cost-model overlappable discount (search satellite of the tentpole)
# ---------------------------------------------------------------------------

def _linear_graph():
    """A data-parallel PCG with weight ops (non-zero sync), sharded over
    every device of the process mesh."""
    from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType
    from flexflow_tpu import SGDOptimizer
    from flexflow_tpu.ff_types import ActiMode, DataType

    cfg = FFConfig()
    cfg.batch_size = 8
    m = FFModel(cfg)
    x = m.create_tensor((8, 16), DataType.DT_FLOAT, name="x")
    t = m.dense(x, 64, ActiMode.AC_MODE_RELU)
    t = m.dense(t, 16, ActiMode.AC_MODE_NONE)
    m.compile(optimizer=SGDOptimizer(lr=0.1),
              loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
              metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR])
    return m.graph


def _machine():
    from flexflow_tpu.search.machine_model import MachineModel

    return MachineModel(num_nodes=1, workers_per_node=len(jax.devices()))


def _dp_view():
    from flexflow_tpu.pcg.machine_view import MachineView

    return MachineView(start_device_id=0, dim=(len(jax.devices()),),
                       stride=(1,))


def _dp_views(graph, machine):
    from flexflow_tpu.search.mcmc import MCMCSearch
    from flexflow_tpu.search.cost_model import CostModel

    return MCMCSearch(CostModel(machine)).data_parallel_start(graph)


def test_discount_bounded_and_never_negative():
    from flexflow_tpu.search.cost_model import CostModel

    graph = _linear_graph()
    machine = _machine()
    plain = CostModel(machine)
    disc = CostModel(machine, overlap_backward_update=True)
    view = _dp_view()
    saw_sync = False
    for op in graph.topo_order():
        if op.is_parallel_op:
            continue
        c0 = plain.measure_operator_cost(op, view)
        c1 = disc.measure_operator_cost(op, view)
        assert c1.total_time <= c0.total_time + 1e-18
        assert c1.total_time >= c1.forward_time + c1.backward_time - 1e-18
        assert c1.hidden_sync_time >= 0.0
        assert c1.hidden_sync_time <= c1.sync_time + 1e-18
        if c0.sync_time > 0:
            saw_sync = True
            assert c1.hidden_sync_time > 0.0
        if c0.sync_time == 0:
            assert c1.total_time == pytest.approx(c0.total_time)
    assert saw_sync, "graph produced no weight-grad sync to discount"


def test_discount_efficiency_scales():
    from flexflow_tpu.search.cost_model import CostModel

    graph = _linear_graph()
    machine = _machine()
    full = CostModel(machine, overlap_backward_update=True,
                     overlap_efficiency=1.0)
    half = CostModel(machine, overlap_backward_update=True,
                     overlap_efficiency=0.5)
    view = _dp_view()
    for op in graph.topo_order():
        cf = full.measure_operator_cost(op, view)
        ch = half.measure_operator_cost(op, view)
        assert ch.hidden_sync_time <= cf.hidden_sync_time + 1e-18


def test_calibration_rejects_bad_overlap_efficiency():
    from flexflow_tpu.search.cost_model import validate_calibration

    with pytest.raises(ValueError, match="overlap_efficiency"):
        validate_calibration({"overlap_efficiency": 0.0})
    validate_calibration({"overlap_efficiency": 0.9})


def test_simulate_runtime_overlap_discount():
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.mcmc import simulate_runtime

    graph = _linear_graph()
    machine = _machine()
    cm = CostModel(machine)
    views = _dp_views(graph, machine)
    serial = simulate_runtime(graph, views, cm,
                              overlap_backward_update=False)
    overlapped = simulate_runtime(graph, views, cm,
                                  overlap_backward_update=True)
    assert 0.0 < overlapped < serial
    # hiding can reclaim at most the total sync time — never more
    total_sync = sum(
        cm.measure_operator_cost(op, views[op.guid]).sync_time
        for op in graph.topo_order()
    )
    assert total_sync > 0.0
    assert overlapped >= serial - total_sync - 1e-18


def test_simulate_runtime_follows_cost_model_flag():
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.mcmc import simulate_runtime

    graph = _linear_graph()
    machine = _machine()
    views = _dp_views(graph, machine)
    serial_cm = CostModel(machine)
    # overlap flag on the cost model is picked up by default...
    ov_cm = CostModel(machine, overlap_backward_update=True)
    assert simulate_runtime(graph, views, ov_cm) <= \
        simulate_runtime(graph, views, serial_cm)
    # ...and an explicit argument overrides it
    assert simulate_runtime(
        graph, views, ov_cm, overlap_backward_update=False
    ) == pytest.approx(simulate_runtime(graph, views, serial_cm))


def test_overlappable_grad_syncs_static_proof():
    from flexflow_tpu.analysis.collectives import (
        hideable_backward_compute,
        overlappable_grad_syncs,
    )
    from flexflow_tpu.search.cost_model import CostModel

    graph = _linear_graph()
    ov = overlappable_grad_syncs(graph)
    weight_ops = [op for op in graph.topo_order()
                  if op.weights and not op.is_parallel_op]
    assert {op.guid for op in weight_ops} == ov
    for op in graph.topo_order():
        if op.is_parallel_op:
            assert op.guid not in ov
    cm = CostModel(_machine())
    hide = hideable_backward_compute(graph, None, cm)
    # later ops (reverse-topo-earlier backward) have MORE hideable compute
    guids = [op.guid for op in graph.topo_order() if op.guid in ov]
    hides = [hide[g] for g in guids]
    assert hides == sorted(hides)
    assert hides[-1] > 0.0


def test_fsdp_target_excluded_from_overlap():
    """A WeightShard-governed op's sync is FSDP's reduce-scatter, not an
    overlappable all-reduce — it must not be double-discounted."""
    from flexflow_tpu.analysis.collectives import overlappable_grad_syncs
    from flexflow_tpu.parallel.weight_sharding import insert_weight_shard

    graph = _linear_graph()
    weight_ops = [op for op in graph.topo_order()
                  if op.weights and not op.is_parallel_op]
    target = weight_ops[0]
    insert_weight_shard(graph, target, 2)
    ov = overlappable_grad_syncs(graph)
    assert target.guid not in ov
    assert all(op.guid in ov for op in weight_ops[1:])


# ---------------------------------------------------------------------------
# Perfetto overlap evidence (runtime/profiler.py)
# ---------------------------------------------------------------------------

def test_simulated_timeline_shows_collective_compute_overlap(tmp_path):
    import json

    from flexflow_tpu.obs.tracer import to_chrome_trace
    from flexflow_tpu.runtime.profiler import (
        export_simulated_timeline,
        simulated_timeline_events,
    )
    from flexflow_tpu.search.cost_model import CostModel

    graph = _linear_graph()
    machine = _machine()
    cm = CostModel(machine)
    views = _dp_views(graph, machine)
    events = simulated_timeline_events(graph, views, cm,
                                       overlap_sync=True)
    syncs = [e for e in events if e["name"].endswith(".grad_sync")
             and e["args"].get("overlapped")]
    bwds = [e for e in events if e["name"].endswith(".bwd")]
    assert syncs and bwds
    comm_tid = syncs[0]["tid"]
    assert all(e["tid"] == comm_tid for e in syncs)
    assert comm_tid not in {e["tid"] for e in bwds}
    # at least one collective span is CONCURRENT with a backward span
    overlap_found = any(
        s["ts"] < b["ts"] + b["dur"] and b["ts"] < s["ts"] + s["dur"]
        for s in syncs for b in bwds
    )
    assert overlap_found, "no collective span concurrent with backward"
    # the export round-trips through the shared Chrome-trace schema
    path = str(tmp_path / "overlap_trace.json")
    export_simulated_timeline(graph, views, cm, path, overlap_sync=True)
    trace = json.load(open(path))
    names = {e.get("name") for e in trace["traceEvents"]}
    assert any(str(n).endswith(".grad_sync") for n in names)
    # default (non-overlap) export unchanged: no comm-channel spans
    base = simulated_timeline_events(graph, views, cm)
    assert not any(e["name"].endswith(".grad_sync") for e in base)
    assert to_chrome_trace(base)["traceEvents"]


# ---------------------------------------------------------------------------
# explain worklist (obs satellite)
# ---------------------------------------------------------------------------

def test_explain_worklist_shape():
    from flexflow_tpu.obs.explain import StrategyExplanation

    rows = [
        {"name": f"op{i}", "op_type": "OP_LINEAR", "parts": 1,
         "sim_fwd_s": 1e-5, "sim_bwd_s": 2e-5, "sim_total_s": 3e-5,
         "meas_fwd_s": 1e-4, "meas_bwd_s": 2e-4, "meas_total_s": 3e-4,
         "abs_err_s": (5 - i) * 1e-4, "ratio": 10.0, "_key": ("k", i)}
        for i in range(5)
    ]
    exp = StrategyExplanation(rows, {}, None)
    wl = exp.worklist(3)
    assert [w["rank"] for w in wl] == [1, 2, 3]
    assert [w["name"] for w in wl] == ["op0", "op1", "op2"]
    assert all("_key" not in w for w in wl)


def test_obs_cli_has_explain_subcommand():
    from flexflow_tpu.obs.__main__ import main

    with pytest.raises(SystemExit):
        main(["explain", "--bogus-flag-that-does-not-exist"])
