"""Enum vocabulary (reference: python/flexflow/type.py)."""
from flexflow_tpu.ff_types import (  # noqa: F401
    ActiMode,
    AggrMode,
    CompMode,
    DataType,
    LossType,
    MetricsType,
    OperatorType,
    ParameterSyncType,
    PoolType,
    RegularizerMode,
)

# reference type.py:59 names the operator enum `OpType`
OpType = OperatorType


def enum_to_int(enum_cls, enum_item) -> int:
    """reference type.py:117"""
    return int(enum_item.value)


def int_to_enum(enum_cls, value):
    """reference type.py:127"""
    return enum_cls(value)


def enum_to_str(enum_cls, enum_item) -> str:
    """reference type.py:134"""
    return enum_item.name


def str_to_enum(enum_cls, value):
    """reference type.py:138"""
    return enum_cls[value]
