"""Substantiate the XLA gemm ceiling the bench analysis leans on.

BASELINE.md's attainable-step estimate prices the transformer bench's
projection/FFN gemms at "XLA's observed ~175 TF/s ceiling" — this
artifact MEASURES that number on the current device for exactly the
bench config's gemm shapes (hidden 1024, seq 512, batch 8 → m = 4096
rows), bf16 inputs with f32 accumulation, using the same
scan-differencing methodology as the calibrated microbenchmarks
(search/measure.py — additive carries are invalid for linear ops, the
elementwise sin tie prevents XLA from hoisting the matmul).

Run ON A REAL CHIP from the repo root (no PYTHONPATH):
    python benchmarks/gemm_ceiling.py
"""
import json
import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

import numpy as np


def main():
    import jax

    from flexflow_tpu.ff_types import ActiMode, DataType, OperatorType
    from flexflow_tpu.ops.linear import LinearParams
    from flexflow_tpu.pcg.machine_view import MachineView
    from flexflow_tpu.pcg.op import PCGOp
    from flexflow_tpu.pcg.parallel_tensor import ParallelDim, ParallelTensor
    from flexflow_tpu.search.machine_model import MachineModel
    from flexflow_tpu.search.measure import OperatorMeasurer

    peak_tf = MachineModel().chip.peak_flops_bf16 / 1e12
    print(f"device: {jax.devices()[0].device_kind}", flush=True)
    meas = OperatorMeasurer(repeats=256, compute_dtype=jax.numpy.bfloat16)
    view = MachineView(start_device_id=0, dim=(1,), stride=(1,))

    # the bench transformer's per-layer gemm shapes (m = batch*seq = 4096)
    shapes = [
        ("proj_1024x1024", 4096, 1024, 1024),   # q/k/v/o projections (x4)
        ("ffn_up_1024x4096", 4096, 1024, 4096),  # FFN in (x1)
        ("ffn_dn_4096x1024", 4096, 4096, 1024),  # FFN out (x1)
    ]
    results = []
    for name, m, k, n in shapes:
        x = ParallelTensor(dims=[ParallelDim(size=m, degree=1),
                                 ParallelDim(size=k, degree=1)],
                           data_type=DataType.DT_FLOAT)
        op = PCGOp(OperatorType.OP_LINEAR,
                   LinearParams(out_channels=n, use_bias=False,
                                activation=ActiMode.AC_MODE_NONE),
                   [x], name=f"gemm_{name}")
        w = ParallelTensor(dims=[ParallelDim(size=k, degree=1),
                                 ParallelDim(size=n, degree=1)],
                           data_type=DataType.DT_FLOAT, owner_op=op)
        op.weights.append(w)
        op.weight_names.append("kernel")
        op.weight_tags = [("in_channel", "out_channel")]
        out = ParallelTensor(dims=[ParallelDim(size=m, degree=1),
                                   ParallelDim(size=n, degree=1)],
                             data_type=DataType.DT_FLOAT, owner_op=op)
        op.outputs.append(out)

        fwd_s, bwd_s = meas(op, view)
        fl = 2.0 * m * k * n
        # backward of a linear = dgrad + wgrad, 2x the forward flops; a
        # rate above ~1.2x peak is differencing noise (the scan carry
        # only ties the forward output — bwd can be hoisted), report null
        bwd_tf = (round(2 * fl / bwd_s / 1e12, 1)
                  if bwd_s == bwd_s and bwd_s > 0 else None)
        if bwd_tf is not None and bwd_tf > 1.2 * peak_tf:
            bwd_tf = None
        rec = {
            "shape": name, "m": m, "k": k, "n": n,
            "fwd_us": round(fwd_s * 1e6, 1),
            "bwd_us": round(bwd_s * 1e6, 1),
            "fwd_tflops": round(fl / fwd_s / 1e12, 1),
            "bwd_tflops": bwd_tf,
        }
        results.append(rec)
        print(json.dumps(rec), flush=True)

    # per-layer gemm budget for the bench config: 4 projections + 2 FFN
    layer_fwd = 4 * results[0]["fwd_us"] + results[1]["fwd_us"] + \
        results[2]["fwd_us"]
    flops_fwd = (4 * 2.0 * 4096 * 1024 * 1024
                 + 2 * 2.0 * 4096 * 1024 * 4096)
    print(json.dumps({
        "metric": "xla_gemm_ceiling",
        "per_layer_gemm_fwd_us": round(layer_fwd, 1),
        "weighted_fwd_tflops": round(flops_fwd / (layer_fwd * 1e-6) / 1e12,
                                     1),
        "unit": "TF/s",
    }), flush=True)
    chain()


def chain(l_short: int = 8, l_long: int = 32, iters: int = 40):
    """Sustained rate for a DEPENDENT chain of the bench's actual gemm
    class — every gemm in the bench model is m=4096, k/n=1024 (the FFN is
    hidden->hidden 1024, NOT 4096-wide; the isolated single-gemm rows
    above overstate this class via cross-iteration pipelining, flagged in
    BASELINE.md). Chained gemms serialize like the model's layers do, so
    this is the honest in-context ceiling for the step's gemm budget.

    Methodology (the naive version of this measurement reported a
    physically-inconsistent 53 TF/s): the carry tie-in must be CHEAP — a
    whole-tensor sin tie costs ~0.5 ms/iteration of VPU transcendentals
    and swamps the gemm delta — so only one (8,128) tile is perturbed
    nonlinearly; and per-iteration overhead is cancelled by DIFFERENCING
    two chain depths (median of 5 runs — the remote-TPU tunnel adds
    multi-ms dispatch jitter that medians suppress)."""
    import statistics
    import time

    import jax
    import jax.numpy as jnp

    h = 1024
    rng = np.random.RandomState(0)
    x0 = jnp.asarray(rng.randn(8, 512, h), jnp.bfloat16)  # bench act shape

    def tie(a, c):
        tile = a[:, :8, :128].astype(jnp.float32)
        pert = jnp.sin(tile + c) * 1e-30 + tile
        return a.at[:, :8, :128].set(pert.astype(a.dtype))

    def fwd_chain(x, ws):
        hcur = x
        for i, W in enumerate(ws):
            hcur = jnp.dot(hcur, W, preferred_element_type=jnp.float32)
            if i % 2 == 0:  # alternate relu like the model's FFN-in layers
                hcur = jax.nn.relu(hcur)
            hcur = hcur.astype(x.dtype)
        return hcur

    def timed(layers, mode):
        Ws = [jnp.asarray(rng.randn(h, h) * 0.03, jnp.bfloat16)
              for _ in range(layers)]
        if mode == "fwd":
            def body(c, _):
                out = fwd_chain(tie(x0, c), Ws)
                return c + out.astype(jnp.float32).sum() * 1e-9, ()
        else:
            def body(c, _):
                def loss(ws):
                    return fwd_chain(tie(x0, c), ws).astype(
                        jnp.float32).sum()
                gs = jax.grad(loss)(Ws)
                return c + sum(
                    g.astype(jnp.float32).sum() for g in gs) * 1e-9, ()

        def fn(c0):
            c, _ = jax.lax.scan(body, c0, None, length=iters)
            return c

        jfn = jax.jit(fn)
        float(jfn(jnp.float32(0.0)))  # compile+warm
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            float(jfn(jnp.float32(1.0)))
            ts.append(time.perf_counter() - t0)
        return statistics.median(ts)

    for mode, eq in (("fwd", 1), ("fwdbwd", 3)):
        d = timed(l_long, mode) - timed(l_short, mode)
        per_gemm = d / iters / (l_long - l_short) / eq
        print(json.dumps({
            "metric": f"gemm_chain_{mode}",
            "layers_differenced": [l_short, l_long],
            "per_gemm_equiv_us": round(per_gemm * 1e6, 2),
            "sustained_tflops": round(
                2.0 * 4096 * h * h / per_gemm / 1e12, 1),
        }), flush=True)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "chain":
        chain()
    else:
        main()
