"""Analytic cost model for the strategy search.

TPU-native replacement for the reference Simulator (src/runtime/simulator.cc,
1880 LoC): the reference microbenchmarks every op's fwd/bwd on-device per
(op-params, machine-view) and caches it (simulator.cc:489 measure_operator_cost).
On TPU, per-op on-device timing is unrepresentative (XLA fuses across op
boundaries) and unavailable at search time (search runs on host), so the cost
of an op is computed from an analytic roofline over its FLOPs/bytes, and
communication from the machine model's link/collective costs. A measured-mode
cache (timing jitted single ops on a real chip) can override entries — same
shape as the reference's `CostMetrics` cache keyed by params+view hash.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..ff_types import DataType, OperatorType, PARALLEL_OP_TYPES
from ..pcg.machine_view import MachineView
from ..pcg.op import PCGOp
from .machine_model import MachineModel


class CostObjective:
    """What workload the cost oracle prices an op for (ROADMAP item 3 —
    "run the Unity search twice per model with different cost
    objectives"; the Splitwise/DistServe disaggregation insight).

      TRAIN  — the classic per-step price: padded MXU FLOPs vs HBM
               roofline, backward + weight-grad sync included.
      DECODE — one single-token decode step: cost is the HBM roofline
               over the bytes the step actually streams (weights per
               shard + the KV-cache-resident K/V re-read per token +
               1-token activation slices), no backward, no grad sync,
               and collectives priced latency-bound (per-token messages
               are KB-sized, so hop latency dominates bandwidth).
    """

    TRAIN = "train"
    DECODE = "decode"
    ALL = (TRAIN, DECODE)

    @staticmethod
    def validate(objective: str) -> str:
        if objective not in CostObjective.ALL:
            raise ValueError(
                f"objective={objective!r}: expected one of "
                f"{'/'.join(CostObjective.ALL)}"
            )
        return objective


@dataclasses.dataclass
class CostMetrics:
    """reference: simulator.h:54-88 CostMetrics"""

    forward_time: float = 0.0
    backward_time: float = 0.0
    sync_time: float = 0.0  # weight-grad allreduce
    inputs_memory: int = 0
    outputs_memory: int = 0
    weights_memory: int = 0
    # seconds of sync_time the overlapped schedule hides behind backward
    # compute (0 unless the cost model runs with overlap_backward_update;
    # never exceeds sync_time, so total_time is never below fwd + bwd)
    hidden_sync_time: float = 0.0

    @property
    def total_time(self) -> float:
        exposed = max(0.0, self.sync_time - self.hidden_sync_time)
        return self.forward_time + self.backward_time + exposed

    @property
    def total_memory(self) -> int:
        return self.inputs_memory + self.outputs_memory + self.weights_memory


def _vol(shape) -> int:
    v = 1
    for s in shape:
        v *= int(s)
    return v


def op_flops(op: PCGOp) -> float:
    """Forward FLOPs of the whole (unsharded) op."""
    t = op.op_type
    in_shapes = [x.material_shape() for x in op.inputs]
    out_shapes = [x.material_shape() for x in op.outputs]
    if t == OperatorType.OP_LINEAR:
        (s,) = in_shapes
        return 2.0 * _vol(s) * op.params.out_channels
    if t == OperatorType.OP_CONV2D:
        o = out_shapes[0]  # (N, Cout, OH, OW)
        cin = in_shapes[0][1]
        p = op.params
        return 2.0 * _vol(o) * cin * p.kernel_h * p.kernel_w / max(1, p.groups)
    if t == OperatorType.OP_BATCHMATMUL:
        a, b = in_shapes
        return 2.0 * _vol(a) * b[-1]
    if t == OperatorType.OP_MULTIHEAD_ATTENTION:
        q, k, v = in_shapes
        p = op.params
        h, d = p.num_heads, p.qk_head_dim
        bq, sq, eq = q[0], q[1], q[2]
        sk = k[1]
        proj = 2.0 * bq * sq * eq * h * d * 3  # q,k,v projections
        scores = 2.0 * bq * h * sq * sk * d
        av = 2.0 * bq * h * sq * sk * p.v_head_dim
        out = 2.0 * bq * sq * h * p.v_head_dim * p.embed_dim
        return proj + scores + av + out
    if t in (OperatorType.OP_GROUP_BY, OperatorType.OP_AGGREGATE,
             OperatorType.OP_AGG_SPEC):
        # dispatch/combine einsum ~ tokens × experts × capacity × dim
        total_out = sum(_vol(s) for s in out_shapes)
        return 2.0 * total_out * max(1, in_shapes[0][0])
    # elementwise / data movement: negligible flops (1 per element)
    return float(sum(_vol(s) for s in out_shapes))


# MXU tile quanta (the public scaling-book tile-quantization rule): the
# systolic array is 128 lanes wide (output/contraction dims), with 8-row
# sublanes. op_padded_flops prices shards at these quanta, and the
# static padding lint (analysis/perf.py FFA503) keys off the SAME
# constants so the search and the analyzer can never disagree about
# which shard extents pad.
MXU_LANES = 128
MXU_SUBLANES = 8


def _pad(v, q: int) -> float:
    return float(math.ceil(max(1, int(v)) / q) * q)


def _shard_shape(t) -> List[int]:
    """Per-device shard extents: size/degree per dim (replica dims keep
    their size — every replica computes the full extent)."""
    return [max(1, d.size // max(1, d.degree)) if not d.is_replica_dim
            else d.size for d in t.dims]


def op_padded_flops(op: PCGOp, parts: int = 1) -> float:
    """PER-SHARD MXU-effective FLOPs: the systolic array is 128 lanes
    wide (output channels), 128 deep (contraction), with 8-row sublanes;
    a matmul whose dims are not tile multiples runs at the PADDED
    shape's cost (the public scaling-book tile-quantization rule, and
    what our own silicon measurements show: head_dim-64 attention
    matmuls cap at ~98 TF/s = half the 197 TF/s peak, BASELINE.md).
    Padding applies to the SHARD shape, not the logical one — splitting
    a 128-wide gemm two ways leaves each 64-wide shard paying a full
    tile, so over-sharding narrow dims correctly stops helping. This is
    also what makes merge-parallel-ops rewrites pay on TPU: 96- and
    32-wide gemms each stream a full 128-lane tile, merged they fill
    one. Ops with no MXU shape return plain per-shard flops."""
    t = op.op_type
    if t == OperatorType.OP_LINEAR and op.inputs and op.outputs:
        si = _shard_shape(op.inputs[0])
        # replica dims are dropped from the OUTPUT: a partial-sum output
        # (row-parallel linear, contraction sharded) marks its pending
        # reduction with a replica dim, but each device only computes its
        # contraction slice — the /degree is already in si[-1]. Truly
        # duplicated compute (replicated input) shows up as an UNSHARDED
        # si[-1], so dropping the dim never under-prices replication.
        so = [x for x, d in zip(_shard_shape(op.outputs[0]),
                                op.outputs[0].dims) if not d.is_replica_dim]
        return 2.0 * _pad(_vol(so[:-1]), MXU_SUBLANES) * _pad(si[-1], MXU_LANES) * _pad(so[-1], MXU_LANES)
    if t == OperatorType.OP_CONV2D and op.inputs and op.outputs:
        si = _shard_shape(op.inputs[0])   # (N, Cin, H, W) shard
        so = _shard_shape(op.outputs[0])  # (N, Cout, OH, OW) shard
        p = op.params
        contraction = si[1] * p.kernel_h * p.kernel_w // max(1, p.groups)
        return 2.0 * _pad(so[0] * so[2] * so[3], MXU_SUBLANES) * _pad(contraction, MXU_LANES) \
            * _pad(so[1], MXU_LANES)
    if t == OperatorType.OP_BATCHMATMUL and len(op.inputs) == 2:
        sa = _shard_shape(op.inputs[0])
        sb = _shard_shape(op.inputs[1])
        # each batch element is a SEPARATE MXU gemm, so the 8-row sublane
        # padding applies per batch element (exactly like the MHA branch's
        # bq*h*_pad(sq,8) below), not once to the flattened batch*rows
        # product — flattening under-priced small-rows batched matmuls
        return 2.0 * _vol(sa[:-2]) * _pad(sa[-2], MXU_SUBLANES) * _pad(sa[-1], MXU_LANES) \
            * _pad(sb[-1], MXU_LANES)
    if t == OperatorType.OP_MULTIHEAD_ATTENTION and len(op.inputs) == 3:
        q, k = op.inputs[0], op.inputs[1]
        p = op.params
        bq = _shard_shape(q)[0]
        # seq/embed from the material (non-replica) dims, as op_flops
        # does — a leading replica dim on q/k would shift raw indices
        qm = [d.size for d in q.dims if not d.is_replica_dim]
        km = [d.size for d in k.dims if not d.is_replica_dim]
        sq, eq = qm[1], qm[2]
        sk = km[1]
        # head-sharded MHA (weight-only degrees) keeps its full-h price —
        # the DP grants it single-part views, so charging one shard here
        # would let a TP candidate undercut without paying its devices
        h, d = p.num_heads, p.qk_head_dim
        proj = 2.0 * _pad(bq * sq, MXU_SUBLANES) * _pad(eq, MXU_LANES) * _pad(h * d, MXU_LANES) * 3
        scores = 2.0 * bq * h * _pad(sq, MXU_SUBLANES) * _pad(d, MXU_LANES) * _pad(sk, MXU_LANES)
        av = 2.0 * bq * h * _pad(sq, MXU_SUBLANES) * _pad(sk, MXU_LANES) * _pad(p.v_head_dim, MXU_LANES)
        out = 2.0 * _pad(bq * sq, MXU_SUBLANES) * _pad(h * p.v_head_dim, MXU_LANES) * _pad(p.embed_dim, MXU_LANES)
        return proj + scores + av + out
    return op_flops(op) / max(1, parts)


def op_bytes(op: PCGOp) -> float:
    """HBM traffic of the whole op (inputs + outputs + weights, once).

    Activations move at their COMPUTE width (analysis/precision.py
    annotations — a bf16 flow streams 2 bytes/elt); weights stay at
    their declared storage width, because the fp32 master copy is what
    the op actually reads from HBM under AMP."""
    n = 0
    for x in op.inputs:
        n += _vol(x.material_shape()) * x.effective_itemsize()
    for x in op.outputs:
        n += _vol(x.material_shape()) * x.effective_itemsize()
    for w in op.weights:
        n += _vol(w.material_shape()) * w.data_type.size
    return float(n)


def op_weight_bytes(op: PCGOp) -> int:
    return sum(_vol(w.material_shape()) * w.data_type.size for w in op.weights)


def _seq_extent(t) -> int:
    """The sequence extent of an activation tensor under the repo's
    (batch, seq, ...) convention — 1 for tensors with no seq axis."""
    s = t.material_shape()
    return int(s[1]) if len(s) >= 3 else 1


def op_decode_bytes(op: PCGOp) -> float:
    """HBM bytes ONE single-token decode step streams for this op,
    unsharded (the decode-objective analog of op_bytes): every weight is
    read once per step; an MHA op re-reads its KV-cache-resident K/V in
    full (the cache length is stood in for by the graph's compiled seq
    extent — same tensors, same bytes); activations contribute only
    their 1-token slice (full volume over the seq extent). This is what
    makes decode memory-bound where training is compute-bound: at batch
    1 the weights dominate and the FLOPs term of the roofline collapses.
    """
    n = float(op_weight_bytes(op))
    if op.op_type == OperatorType.OP_MULTIHEAD_ATTENTION \
            and len(op.inputs) >= 3:
        # the persistent (b, max_len, h, d) K/V pair the step attends
        # over — byte-equivalent to the full k/v inputs; the cache is
        # materialized at the compute width (bf16 under AMP)
        for x in op.inputs[1:3]:
            n += _vol(x.material_shape()) * x.effective_itemsize()
    for x in list(op.inputs) + list(op.outputs):
        n += _vol(x.material_shape()) * x.effective_itemsize() \
            / max(1, _seq_extent(x))
    return n


_DEFAULT_CALIBRATION: Optional[dict] = None
_DEFAULT_CALIBRATION_LOADED = False


def validate_calibration(cal: dict) -> dict:
    """Reject out-of-range calibration values at load time: efficiencies
    must lie in (0, 1] (a 0.0 or negative value would otherwise silently
    produce infinite/negative op costs) and bwd/fwd ratios must be
    positive."""
    def check_eff(name, v):
        if v is None:
            return
        if not isinstance(v, (int, float)) or not (0.0 < v <= 1.0):
            raise ValueError(
                f"calibration {name}={v!r} outside (0, 1]"
            )

    if not isinstance(cal, dict):
        raise ValueError(f"calibration must be a dict, got {type(cal)}")
    # fraction of an overlappable collective that actually hides behind
    # backward compute on silicon (the overlap discount's calibration
    # knob — tuned from the explain-worklist loop, docs/performance.md)
    check_eff("overlap_efficiency", cal.get("overlap_efficiency"))
    op_class = cal.get("op_class", {})
    if not isinstance(op_class, dict):
        raise ValueError("calibration op_class must be a dict")
    check_eff("mxu_efficiency", cal.get("mxu_efficiency"))
    check_eff("hbm_efficiency", cal.get("hbm_efficiency"))
    for op_name, cls in op_class.items():
        if not isinstance(cls, dict):
            raise ValueError(
                f"calibration op_class[{op_name}] must be a dict"
            )
        check_eff(f"op_class[{op_name}].mxu_efficiency",
                  cls.get("mxu_efficiency"))
        check_eff(f"op_class[{op_name}].hbm_efficiency",
                  cls.get("hbm_efficiency"))
        ratio = cls.get("bwd_over_fwd")
        if ratio is not None and (
                not isinstance(ratio, (int, float)) or ratio <= 0):
            raise ValueError(
                f"calibration op_class[{op_name}].bwd_over_fwd={ratio!r} "
                "must be positive"
            )
    return cal


def load_default_calibration() -> Optional[dict]:
    """The shipped on-silicon calibration (tools/calibrate_cost_model.py
    output, flexflow_tpu/search/calibration_v5e.json): per-op-class
    efficiencies fitted from measured fwd/bwd times on a real v5e chip —
    the analytic analog of the reference shipping its simulator tuned
    against real GPU microbenchmarks."""
    global _DEFAULT_CALIBRATION, _DEFAULT_CALIBRATION_LOADED
    if not _DEFAULT_CALIBRATION_LOADED:
        _DEFAULT_CALIBRATION_LOADED = True
        import json
        import os

        path = os.path.join(os.path.dirname(__file__),
                            "calibration_v5e.json")
        if os.path.exists(path):
            try:
                with open(path) as f:
                    _DEFAULT_CALIBRATION = validate_calibration(json.load(f))
            except (OSError, ValueError):
                _DEFAULT_CALIBRATION = None
    return _DEFAULT_CALIBRATION


def apply_calibration(cm, *, profiled=None, overlap_efficiency=None,
                      collective_bandwidths=None):
    """The measured-calibration refresh seam: write in-situ measurements
    onto a CostModel in place and return it. Both compile-time oracle
    construction (core/model.py _build_cost_model) and the online
    re-search (runtime/tuner.py) funnel through here, so a drift-updated
    oracle is priced exactly the way the original compile's was.

    profiled: {op_cost_key: (fwd_s, bwd_s)} measured per-op seconds
    (obs/explain.py) — serial-view costs resolve to these instead of the
    analytic roofline. overlap_efficiency / collective_bandwidths: the
    CalibrationStore's measured globals (step observatory write-through).
    """
    if profiled:
        from ..obs.explain import attach_profiled_costs

        attach_profiled_costs(cm, profiled)
    if overlap_efficiency is not None:
        cm.overlap_efficiency = float(overlap_efficiency)
        cm.overlap_efficiency_source = "calibration_store"
    if collective_bandwidths:
        cm.calibrated_collective_bandwidths = {
            k: float(v) for k, v in collective_bandwidths.items()
        }
    return cm


class CostModel:
    """Per-(op, machine-view) cost oracle with memoization
    (reference: Simulator::measure_operator_cost's hash_map cache,
    simulator.cc:489-537 + strict_hash_to_operator_cost).

    calibration: None loads the shipped per-op-class efficiency fit
    (calibration_v5e.json); False disables calibration; a dict or a JSON
    path supplies a custom one. The fit refines the roofline's fixed
    mxu/hbm efficiency constants per op class where silicon measurements
    say otherwise."""

    def __init__(self, machine: MachineModel, *, bf16: bool = True,
                 calibration=None, overlap_backward_update: bool = False,
                 overlap_efficiency: Optional[float] = None,
                 survivability_penalty: float = 0.0,
                 objective: str = CostObjective.TRAIN):
        self.machine = machine
        self.bf16 = bf16
        # what workload an op's price describes: the training step
        # (default) or one single-token decode step (CostObjective.DECODE
        # — HBM-roofline bytes, no backward/sync, latency-bound
        # collectives). Per-instance, so the two searches a model runs
        # (compile() + compile_decode()) can never share a cache entry.
        self.objective = CostObjective.validate(objective)
        # slice-loss survivability bias (search/survivability.py, config
        # knob search_survivability_penalty): >0 on hierarchical
        # machines makes DP/MCMC multiply a candidate's cost by
        # 1 + penalty * (cross-slice-sharded weight fraction), steering
        # the search toward strategies where only data-parallel replicas
        # cross the slice boundary. 0 disables the bias entirely.
        self.survivability_penalty = float(survivability_penalty)
        # "overlappable" discount (config.search_overlap_backward_update):
        # a weight-gradient sync collective is statically independent of
        # the backward critical path — the gradient it reduces feeds ONLY
        # the optimizer update, and every topologically-earlier op's
        # backward cannot read it (analysis/collectives.
        # overlappable_grad_syncs is the graph-level proof) — so the
        # overlapped executor hides it behind dependent backward matmuls
        # and the search should price only the EXPOSED remainder:
        # max(0, sync - overlap_efficiency * backward). Explicit parallel
        # ops (Repartition/Combine/...) sit on the activation path and
        # keep their full price.
        self.overlap_backward_update = overlap_backward_update
        if calibration is None:
            calibration = load_default_calibration()
        elif calibration is False:
            calibration = None
        elif isinstance(calibration, str):
            import json

            with open(calibration) as f:
                calibration = validate_calibration(json.load(f))
        elif isinstance(calibration, dict):
            validate_calibration(calibration)
        self.calibration = calibration
        if overlap_efficiency is None:
            overlap_efficiency = (calibration or {}).get(
                "overlap_efficiency", 1.0
            )
        self.overlap_efficiency = float(overlap_efficiency)
        self._cache: Dict[Tuple, CostMetrics] = {}
        self._xfer_cache: Dict[Tuple, float] = {}
        # measured-mode overrides: key -> (fwd, bwd) seconds
        self.measured: Dict[Tuple, Tuple[float, float]] = {}
        # optional on-device microbenchmark oracle (search/measure.py,
        # reference: Simulator::measure_operator_cost's real timing path)
        self.measure_fn = None
        # provenance: where the measured oracle came from (set by
        # obs.explain.attach_profiled_costs — an on-disk calibration
        # store's path or "profiled(in-memory)"), and how often the
        # search actually priced an op from measurement vs the analytic
        # roofline — the ratio perf audits report so "calibrated" is a
        # checked claim, not an assumption
        self.calibration_source: Optional[str] = None
        self.measured_hits = 0
        self.analytic_hits = 0
        # in-situ calibrated globals (obs/step_profile.py write-through
        # via the calibration store): where overlap_efficiency came from
        # and the measured per-kind collective bandwidths the oracle was
        # handed — provenance() reports both so "priced from reality" is
        # a checkable claim
        self.overlap_efficiency_source = (
            "calibration" if (calibration or {}).get("overlap_efficiency")
            is not None else "default"
        )
        self.calibrated_collective_bandwidths: Dict[str, float] = {}

    def provenance(self) -> dict:
        """How this oracle priced ops so far: measurement vs analytic
        roofline (cache-cold queries only — memoized repeats don't
        re-count), plus the calibrated globals (overlap efficiency and
        any measured collective bandwidths the calibration store fed
        in). analysis/perf.py attaches this to its report when a
        measured source is present."""
        total = self.measured_hits + self.analytic_hits
        return {
            "source": self.calibration_source,
            "measured_ops": len(self.measured),
            "measured_hits": self.measured_hits,
            "analytic_hits": self.analytic_hits,
            "measured_fraction": (self.measured_hits / total)
            if total else 0.0,
            "overlap_efficiency": self.overlap_efficiency,
            "overlap_efficiency_source": self.overlap_efficiency_source,
            "collective_bytes_per_s":
                dict(self.calibrated_collective_bandwidths),
        }

    def _calibration_class(self, op_type, flops=None,
                           membytes=None) -> Optional[dict]:
        """The fitted entry for this op, shape-regime aware: a class may
        ship a separate '<NAME>@mem' fit for its memory-bound shapes
        (VERDICT r2 #8 — OP_LINEAR's implied efficiencies spanned 6x
        between compute- and memory-bound shapes; one scalar can't serve
        both). Regime decided by the UNCALIBRATED roofline."""
        if not self.calibration:
            return None
        cls_map = self.calibration.get("op_class", {})
        name = op_type.name
        if flops is not None and membytes is not None and \
                f"{name}@mem" in cls_map:
            peak = (self.machine.chip.peak_flops_bf16 if self.bf16
                    else self.machine.chip.peak_flops_f32)
            t_f = flops / peak
            t_m = membytes / self.machine.chip.hbm_bandwidth
            if t_m > t_f:
                name = f"{name}@mem"
        return cls_map.get(name)

    def _calibrated_efficiencies(self, op_type, flops=None, membytes=None
                                 ) -> Tuple[Optional[float],
                                            Optional[float]]:
        """(mxu_eff, hbm_eff) overrides for this op class, if fitted."""
        if not self.calibration:
            return None, None
        cls = self._calibration_class(op_type, flops, membytes)
        g_m = self.calibration.get("mxu_efficiency")
        g_h = self.calibration.get("hbm_efficiency")
        if cls:
            return cls.get("mxu_efficiency", g_m), cls.get("hbm_efficiency",
                                                           g_h)
        return g_m, g_h

    def _key(self, op: PCGOp, view: MachineView):
        # weights are part of the key: their sharding degrees decide the
        # gradient-sync term (a channel-split table syncs nothing; a
        # replicated one allreduces the full table)
        return (
            op.op_type,
            op.params,
            tuple(t.shape_key() for t in op.inputs),
            tuple(w.shape_key() for w in op.weights),
            view.hash(),
        )

    def _measure_decode_cost(self, op: PCGOp, view: MachineView,
                             key) -> CostMetrics:
        """Price ONE single-token decode step of `op` under `view`: the
        HBM roofline over the bytes the step streams per device. Weights
        divide by their OWN shard degree (a head/channel-split weight is
        the thing decode sharding actually buys — each chip streams
        1/degree of the matrix per token); the KV-cache-resident K/V
        divide by the batch degree × the head-shard degree (the two axes
        that tile the cache); 1-token activation slices divide by the
        view's parts. FLOPs are the UNPADDED per-token count — a 1-token
        gemm never fills an MXU tile, and padding it would misprice
        decode as compute-bound, which is exactly the mistake the decode
        objective exists to avoid. No backward, no weight-grad sync."""
        parts = max(1, view.num_parts())
        seq = max(1, _seq_extent(op.outputs[0])) if op.outputs else 1
        flops = op_flops(op) / seq / parts
        membytes = 0.0
        for w in op.weights:
            membytes += _vol(w.material_shape()) * w.data_type.size \
                / max(1, w.get_total_degree())
        if op.op_type == OperatorType.OP_MULTIHEAD_ATTENTION \
                and len(op.inputs) >= 3:
            batch_deg = 1
            if op.outputs and op.outputs[0].dims:
                batch_deg = max(1, op.outputs[0].dims[0].degree)
            head_deg = max(
                [max(1, w.get_total_degree()) for w in op.weights] or [1]
            )
            kv = sum(_vol(x.material_shape()) * x.effective_itemsize()
                     for x in op.inputs[1:3])
            membytes += kv / max(1, batch_deg * head_deg)
        for x in list(op.inputs) + list(op.outputs):
            membytes += _vol(x.material_shape()) * x.effective_itemsize() \
                / max(1, _seq_extent(x)) / parts
        mxu_eff, hbm_eff = self._calibrated_efficiencies(
            op.op_type, flops, membytes
        )
        self.analytic_hits += 1
        fwd = self.machine.compute_cost(
            flops, membytes, self.bf16, mxu_eff=mxu_eff, hbm_eff=hbm_eff,
        )
        wmem = 0
        for w in op.weights:
            w_b = _vol(w.material_shape()) * w.data_type.size
            wmem += int(w_b / max(1, w.get_total_degree()))
        cm = CostMetrics(
            forward_time=fwd,
            backward_time=0.0,
            sync_time=0.0,
            inputs_memory=int(
                sum(_vol(t.material_shape()) * t.effective_itemsize()
                    for t in op.inputs) / parts
            ),
            outputs_memory=int(
                sum(_vol(t.material_shape()) * t.effective_itemsize()
                    for t in op.outputs) / parts
            ),
            weights_memory=wmem,
        )
        self._cache[key] = cm
        return cm

    def measure_operator_cost(self, op: PCGOp, view: MachineView) -> CostMetrics:
        key = self._key(op, view)
        if key in self._cache:
            return self._cache[key]
        if self.objective == CostObjective.DECODE:
            return self._measure_decode_cost(op, view, key)
        parts = max(1, view.num_parts())
        # MXU time is paid at the tile-quantized SHARD shape; the padded
        # count only describes the shard when the tensor degrees actually
        # match the view's parts (they do for DP-granted views;
        # unsharded-tensor-on-wide-view callers fall back to plain /parts)
        out_deg = op.outputs[0].get_total_degree() if op.outputs else 1
        if out_deg == parts:
            flops = op_padded_flops(op, parts)
        else:
            flops = op_flops(op) / parts
        membytes = op_bytes(op) / parts
        if key not in self.measured and self.measure_fn is not None:
            m_fwd, m_bwd = self.measure_fn(op, view)
            if m_fwd == m_fwd:  # not NaN -> measurable on device
                self.measured[key] = (m_fwd, m_bwd)
        if key in self.measured:
            self.measured_hits += 1
            fwd, bwd = self.measured[key]
        else:
            self.analytic_hits += 1
            mxu_eff, hbm_eff = self._calibrated_efficiencies(
                op.op_type, flops, membytes
            )
            fwd = self.machine.compute_cost(
                flops, membytes, self.bf16,
                mxu_eff=mxu_eff, hbm_eff=hbm_eff,
            )
            # backward ≈ 2× forward for weight ops (dgrad+wgrad), ≈ forward
            # for the rest (reference measures both; ratio matches its
            # observed GEMM fwd:bwd split); calibration refines per class
            ratio = None
            cls = self._calibration_class(op.op_type, flops, membytes)
            if cls:
                ratio = cls.get("bwd_over_fwd")
            if ratio is None:
                ratio = 2.0 if op.weights else 1.0
            bwd = ratio * fwd
        # Ring-attention ICI rotation (Liu et al., Ring Attention): a
        # seq-sharded attention op keeps K/V resident and rotates each
        # shard around the seq ring — (sd-1) steps of kv_bytes/sd each,
        # i.e. kv_bytes*(sd-1)/sd total wire time, which is EXACTLY the
        # all_to_all_cost formula; routing it through the machine model
        # means the hierarchical slice-crossing override prices rings
        # that straddle slices too (search/network.py). Backward rotates
        # twice (the dK/dV accumulation makes a second pass).
        if op.op_type == OperatorType.OP_MULTIHEAD_ATTENTION \
                and op.outputs and len(op.outputs[0].dims) == 3 \
                and op.outputs[0].dims[1].degree > 1 and len(op.inputs) >= 2:
            sd = op.outputs[0].dims[1].degree
            group = view.device_ids()[:sd]
            if len(group) >= 2:
                kv_bytes = 2 * _vol(op.inputs[1].material_shape()) \
                    * op.inputs[1].effective_itemsize()
                rot = self.machine.all_to_all_cost(kv_bytes, group)
                fwd += rot
                bwd += 2 * rot
        # weight gradient sync (reference: NCCL allreduce per weight per
        # view, optimizer.cc nccl_update_task). Per weight: a sharded
        # weight only syncs across its REPLICAS — each device owns
        # bytes/degree, and with `degree` shards over `parts` devices the
        # replica group for one shard is every degree-th device (strided,
        # so a group can span nodes and pay DCN). Fully sharded weights
        # (parameter parallelism, e.g. DLRM embedding tables) sync nothing;
        # replicated weights coexisting with sharded ones (a row-parallel
        # Linear's bias) still pay their own full allreduce.
        sync = 0.0
        wbytes = op_weight_bytes(op)
        if wbytes and parts > 1:
            ids = view.device_ids()
            for w in op.weights:
                w_bytes = _vol(w.material_shape()) * w.data_type.size
                w_deg = max(1, w.get_total_degree())
                replicas = max(1, parts // w_deg)
                if replicas > 1:
                    group = ids[::w_deg][:replicas]
                    sync += self.machine.allreduce_cost(w_bytes / w_deg, group)
        hidden = 0.0
        if sync > 0.0 and self.overlap_backward_update:
            # overlappable discount: the exposed sync is what the comm
            # channel can't hide behind this op's share of backward
            # compute (the machine model owns the overlap seam so
            # topology-aware models can refine it)
            exposed = self.machine.exposed_comm_time(
                sync, bwd, self.overlap_efficiency
            )
            hidden = sync - exposed
        # Per-device weight bytes divide by the weight's OWN shard degree,
        # never by the view's part count: a replicated weight under a
        # data-parallel view lives in FULL on every replica (dividing by
        # `parts`, as rounds 3-6 did, made the memory search believe DP
        # already shards state — so the lambda loop admitted strategies
        # the static analyzer correctly rejects with FFA301, and weight
        # sharding looked pointless). A dim-sharded weight (tensor-
        # parallel channel/head splits, FSDP/ZeRO weight sharding) holds
        # bytes/degree per device regardless of how the view tiles the
        # activations — the same rule analysis/memory._shard_bytes uses,
        # so the search and the static HBM gate price the same bytes.
        wmem = 0
        for w in op.weights:
            w_b = _vol(w.material_shape()) * w.data_type.size
            wmem += int(w_b / max(1, w.get_total_degree()))
        cm = CostMetrics(
            forward_time=fwd,
            backward_time=bwd,
            sync_time=sync,
            hidden_sync_time=hidden,
            inputs_memory=int(
                sum(_vol(t.material_shape()) * t.data_type.size for t in op.inputs)
                / parts
            ),
            outputs_memory=int(
                sum(_vol(t.material_shape()) * t.data_type.size for t in op.outputs)
                / parts
            ),
            weights_memory=wmem,
        )
        self._cache[key] = cm
        return cm

    def estimate_xfer_cost(
        self,
        tensor,
        src_view: Optional[MachineView],
        dst_view: Optional[MachineView],
    ) -> float:
        """Resharding cost of moving `tensor` from src_view's layout to
        dst_view's (reference: SearchHelper::estimate_xfer_cost — Legion
        region movement; here: the collective XLA would insert)."""
        if src_view is None or dst_view is None:
            return 0.0
        if src_view.hash() == dst_view.hash():
            return 0.0
        total = _vol(tensor.material_shape()) * tensor.data_type.size
        if self.objective == CostObjective.DECODE:
            # a decode step only moves the 1-token slice of the
            # activation; xfer_cost's link-latency term then dominates,
            # which is the point — resharding per token is expensive in
            # hops, not bytes
            total /= max(1, _seq_extent(tensor))
        key = (total, src_view.hash(), dst_view.hash())
        cached = self._xfer_cache.get(key)
        if cached is not None:
            return cached
        src_ids, dst_ids = src_view.device_ids(), dst_view.device_ids()
        # per-destination bytes: each dst shard gathers its slice
        per_dst = total / max(1, len(dst_ids))
        worst = 0.0
        for i, d in enumerate(dst_ids):
            s = src_ids[i % len(src_ids)]
            worst = max(worst, self.machine.xfer_cost(per_dst, s, d))
        self._xfer_cache[key] = worst
        return worst

    def concurrent_xfer_penalty(self, flows) -> float:
        """Congestion surcharge for transfers that happen AT THE SAME TIME
        (an op pulling several inputs; concurrent nonsequence halves
        pulling their boundary tensors; a diamond sink draining its
        towers). flows: [(tensor, src_view, dst_view), ...].

        Priced through the machine's concurrent_flows_cost (the
        topology-aware link-sharing model, network.py — reference:
        EnhancedMachineModel congestion over shared comm devices,
        machine_model.cc): penalty = finish time of the flow SET minus the
        slowest flow alone, i.e. exactly the cost the independent
        per-transfer estimates miss. Flat machine models (no
        concurrent_flows_cost) price zero — link sharing is invisible to
        them by construction."""
        conc_fn = getattr(self.machine, "concurrent_flows_cost", None)
        if conc_fn is None:
            return 0.0
        pt_flows = []
        for tensor, src_view, dst_view in flows:
            if src_view is None or dst_view is None:
                continue
            if src_view.hash() == dst_view.hash():
                continue
            total = _vol(tensor.material_shape()) * tensor.data_type.size
            if total <= 0:
                continue
            dst_ids = dst_view.device_ids()
            per_dst = total / max(1, len(dst_ids))
            pt_flows.append((per_dst, src_view.start_device_id,
                             dst_view.start_device_id))
        if len(pt_flows) < 2:
            return 0.0
        key = ("conc", tuple(sorted(pt_flows)))
        cached = self._xfer_cache.get(key)
        if cached is not None:
            return cached
        together = conc_fn(pt_flows)
        alone = max(conc_fn([f]) for f in pt_flows)
        penalty = max(0.0, together - alone)
        self._xfer_cache[key] = penalty
        return penalty

    def parallel_op_cost(self, op: PCGOp, view=None) -> float:
        """Cost of an explicit parallel op node (reshard collectives),
        priced through the machine model's collective methods so a
        topology-aware machine (hop distances, DCN hierarchy) changes the
        number — the reference's EnhancedMachineModel routes these through
        its per-link comm devices (machine_model.cc)."""
        t = op.op_type
        if t not in PARALLEL_OP_TYPES:
            return 0.0
        x = op.inputs[0]
        total = _vol(x.material_shape()) * x.data_type.size
        m = self.machine

        def group(deg):
            if view is not None:
                ids = view.device_ids()
                if len(ids) >= deg:
                    return ids[:deg]
            return range(deg)

        if self.objective == CostObjective.DECODE:
            # per-token messages over the latency-bound collective model:
            # one decode step moves the 1-token slice, and at KB sizes the
            # ring's hop latency (not bandwidth) is the price — the term
            # that makes a per-token all-reduce on the critical path
            # costly no matter how narrow the message is
            total /= max(1, _seq_extent(x))
            if t == OperatorType.OP_REPLICATE:
                deg = op.params.replicate_degree
                return m.latency_bound_collective_cost(
                    "replicate", total, group(deg))
            if t == OperatorType.OP_REDUCTION:
                deg = op.params.reduction_degree
                return m.latency_bound_collective_cost(
                    "allreduce", total / deg, group(deg))
            if t == OperatorType.OP_ALL_TO_ALL:
                deg = op.params.degree
                return m.latency_bound_collective_cost(
                    "all_to_all", total, group(deg))
            if t == OperatorType.OP_WEIGHT_SHARD:
                # decode pays ONE gather-on-use of the full weight per
                # token (no backward re-gather, no gradient
                # reduce-scatter) — still ruinous at batch 1, which is
                # why the decode search avoids FSDP nodes
                from ..parallel.weight_sharding import \
                    shard_target_weight_bytes

                deg = op.params.shard_degree
                wbytes = shard_target_weight_bytes(op)
                return m.latency_bound_collective_cost(
                    "all_gather", wbytes, group(deg))
            deg = getattr(op.params, "repartition_degree",
                          getattr(op.params, "combine_degree", 2))
            return m.latency_bound_collective_cost(
                "reshard", total, group(deg))

        if t == OperatorType.OP_WEIGHT_SHARD:
            # FSDP/ZeRO per-step collectives over the TARGET op's full
            # weight bytes (parallel/weight_sharding.py): all-gather the
            # sharded params on use in the forward AND the backward, plus
            # one reduce-scatter of the weight gradients — 3(p-1)/p wire
            # bytes vs the replicated strategy's 2(p-1)/p all-reduce
            # (which measure_operator_cost's sync term stops charging once
            # the weight is sharded). Strictly slower on runtime, so only
            # the memory-lambda loop picks it.
            from ..parallel.weight_sharding import shard_target_weight_bytes

            deg = op.params.shard_degree
            wbytes = shard_target_weight_bytes(op)
            g = group(deg)
            return (2.0 * m.all_gather_cost(wbytes, g)
                    + m.reduce_scatter_cost(wbytes, g))
        if t == OperatorType.OP_REPLICATE:
            deg = op.params.replicate_degree
            return m.replicate_cost(total, group(deg))
        if t == OperatorType.OP_REDUCTION:
            deg = op.params.reduction_degree
            return m.allreduce_cost(total / deg, group(deg))
        if t == OperatorType.OP_ALL_TO_ALL:
            deg = op.params.degree
            return m.all_to_all_cost(total, group(deg))
        deg = getattr(op.params, "repartition_degree",
                      getattr(op.params, "combine_degree", 2))
        return m.reshard_cost(total, group(deg))
