"""Numerical-trust layer: strategy-equivalence verification, checkpoint
integrity checksums, and an online SDC/determinism canary.

The framework's core premise (FlexFlow MLSys'19 / Unity OSDI'22) is that an
auto-searched PCG strategy — substitutions plus Repartition / Combine /
Replicate / Reduction ops and MachineViews — is *semantically equivalent*
to the serial program. PR 1-2 made runs survive crashes and topology
changes; nothing made the surviving run *trustworthy*: a wrong sharding
rule, a dropped activation in a substitution, or a flipped bit from a
faulty core ("Cores that don't count", HotOS'21; MegaScale, NSDI'24)
silently degrades convergence instead of failing. Three defenses:

* **Differential strategy verifier** — `verify_strategy(model, data,
  steps=K)` runs K train steps of the searched strategy AND a fully-serial
  single-device reference built from the same layer list, from identical
  parameters and RNG, and compares loss, global grad norm and final params
  under per-dtype tolerances. On divergence it bisects over the PCG's
  matched op prefix (executing both forwards and probing intermediate
  outputs) to name the first diverging op. Exposed as
  `fit(verify_strategy="preflight")` and standalone.

* **Checkpoint integrity** — `save_checkpoint` writes per-tensor crc32 +
  dtype/shape checksums into the meta sidecar; `restore_checkpoint`
  verifies them and raises a typed `CheckpointCorruptionError` naming the
  corrupt tensor, which makes `CheckpointManager.restore_latest` fall back
  to the previous intact checkpoint. `verify_checkpoint(path)` is the
  offline audit (`python -m flexflow_tpu.runtime.verify <path>`).

* **SDC/determinism canary** — `CanaryConfig(every_n_steps, mode)` makes
  the resilient fit loop periodically re-execute the step function on the
  cached inputs from the same pre-step state and compare the two results
  bitwise (``"determinism"``) or within tolerance (``"sdc"``), plus cheap
  per-step invariants (param-norm drift, loss-delta bounds, finite loss).
  Violations escalate through the existing checkpoint-and-raise machinery
  (`CanaryMismatchError` / `InvariantViolationError`). The FaultInjector
  site ``bitflip`` corrupts one weight tensor (live state, or the
  just-written checkpoint with ``target="disk"``) so both detection paths
  are exercised on CPU in CI (tests/test_verify.py,
  scripts/verify_check.sh).
"""
from __future__ import annotations

import dataclasses
import logging
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .resilience import ResilienceError

logger = logging.getLogger("flexflow_tpu.runtime.verify")


# ----------------------------------------------------------------------
# typed errors
# ----------------------------------------------------------------------
class NotCompiledError(RuntimeError):
    """An API that needs a compiled model (executor + state) was called
    before `FFModel.compile()` — replaces bare asserts that vanish under
    ``python -O`` and gave no hint of the fix."""


class ServingConfigError(ValueError):
    """A serving request does not fit the compiled model: wrong input
    shape, batch/beam count over the compiled capacity, or a generation
    length over the decode cap."""


class VerificationError(RuntimeError):
    """Base class for numerical-trust failures."""


class StrategyDivergenceError(VerificationError):
    """The searched strategy's execution diverged from the serial
    reference beyond tolerance. `diverging_op` names the first PCG op
    whose forward output differs (None when only the backward/optimizer
    step diverges); `verdict` carries the full comparison report."""

    def __init__(self, msg: str, *, diverging_op: Optional[str] = None,
                 verdict: Optional["StrategyVerdict"] = None):
        super().__init__(msg)
        self.diverging_op = diverging_op
        self.verdict = verdict


class CheckpointCorruptionError(VerificationError):
    """A restored tensor's bytes do not match the checksum recorded at
    save time — on-disk corruption (bad storage, truncation, bitrot).
    `tensors` names every mismatching tensor path."""

    def __init__(self, msg: str, *, path: str = "",
                 tensors: Optional[List[str]] = None):
        super().__init__(msg)
        self.path = path
        self.tensors = list(tensors or [])


class CanaryMismatchError(VerificationError, ResilienceError):
    """The SDC/determinism canary re-executed a step on identical inputs
    and state and got a different answer — non-deterministic execution or
    silent data corruption from a faulty core. fit() reverts to the
    pre-step state, flushes a checkpoint (checkpoint_path) and raises."""

    def __init__(self, msg: str, *, step: int = 0,
                 mismatches: Optional[List[str]] = None):
        super().__init__(msg)
        self.step = step
        self.mismatches = list(mismatches or [])
        self.checkpoint_path: Optional[str] = None


class InvariantViolationError(VerificationError, ResilienceError):
    """A cheap per-step training invariant failed (param-norm drift over
    the configured ratio, loss delta over the bound, non-finite loss).
    Same checkpoint-and-raise escalation as the canary."""

    def __init__(self, msg: str, *, step: int = 0, invariant: str = ""):
        super().__init__(msg)
        self.step = step
        self.invariant = invariant
        self.checkpoint_path: Optional[str] = None


# ----------------------------------------------------------------------
# per-dtype tolerances
# ----------------------------------------------------------------------
# (rtol, atol) for comparing two executions of the "same" math whose
# reduction/summation orders legally differ (a sharded matmul's partial
# sums vs the serial one's single accumulation).
DTYPE_TOLERANCES: Dict[str, Tuple[float, float]] = {
    "float64": (1e-12, 1e-12),
    "float32": (2e-4, 1e-5),
    "bfloat16": (5e-2, 5e-2),
    "float16": (5e-3, 5e-3),
}
_DEFAULT_TOL = (2e-4, 1e-5)

# How much of the static FFA705 drift budget (analysis/precision.py) a
# legal sharding-induced reorder is allowed to consume. The budget bounds
# accumulated ulp-scaled roundoff along the longest compute path; a
# reordered-but-equivalent strategy should stay well inside it, so the
# verify tolerance is capped at this fraction of the budget. At the
# default budget (0.25) the cap is 5e-2 — exactly the bf16 table row —
# so the table governs until someone TIGHTENS the budget, at which point
# verification tightens with it (the two knobs share
# FFConfig.precision_drift_budget).
DRIFT_TO_TOLERANCE = 0.2


def tolerance_for(dtype, rtol: Optional[float] = None,
                  atol: Optional[float] = None) -> Tuple[float, float]:
    """The (rtol, atol) pair for `dtype`, with explicit overrides
    winning over the per-dtype table."""
    base = DTYPE_TOLERANCES.get(np.dtype(dtype).name if dtype is not None
                                else "float32", _DEFAULT_TOL)
    return (base[0] if rtol is None else rtol,
            base[1] if atol is None else atol)


def tolerance_from_budget(dtype_key: str,
                          drift_budget: Optional[float]) -> Tuple[float,
                                                                  float]:
    """Derive the (rtol, atol) pair for `dtype_key` from the static drift
    budget: the per-dtype table row, capped at DRIFT_TO_TOLERANCE of the
    budget. None uses the analyzer's default budget."""
    from ..analysis.precision import DEFAULT_DRIFT_BUDGET

    base = DTYPE_TOLERANCES.get(dtype_key, _DEFAULT_TOL)
    budget = DEFAULT_DRIFT_BUDGET if drift_budget is None else drift_budget
    cap = max(budget, 0.0) * DRIFT_TO_TOLERANCE
    return (min(base[0], cap), min(base[1], cap))


# ----------------------------------------------------------------------
# checkpoint integrity checksums
# ----------------------------------------------------------------------
CHECKSUM_ALGO = "crc32"


def _flat_path(path) -> str:
    """A stable human-readable key for a pytree leaf path:
    ``params/dense_1/kernel``, ``opt_state/1/m/...``."""
    import jax.tree_util as jtu

    parts = []
    for k in path:
        if isinstance(k, jtu.DictKey):
            parts.append(str(k.key))
        elif isinstance(k, jtu.SequenceKey):
            parts.append(str(k.idx))
        elif isinstance(k, jtu.GetAttrKey):
            parts.append(str(k.name))
        else:  # FlattenedIndexKey and friends
            parts.append(str(getattr(k, "key", k)))
    return "/".join(parts)


def _array_crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def tensor_checksums(tree) -> Dict[str, dict]:
    """Per-tensor content checksums for a host-side state tree:
    ``{flat_path: {"crc32": int, "dtype": str, "shape": [..]}}``. Only
    array leaves are recorded (None optimizer slots, plain ints skip)."""
    import jax.tree_util as jtu

    flat, _ = jtu.tree_flatten_with_path(tree, is_leaf=lambda x: x is None)
    out: Dict[str, dict] = {}
    for path, leaf in flat:
        if leaf is None:
            continue
        arr = np.asarray(leaf)
        out[_flat_path(path)] = {
            "crc32": _array_crc(arr),
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
        }
    return out


def verify_checksums(tree, integrity: dict, *, path: str = "") -> None:
    """Check a restored host tree against the sidecar's ``integrity``
    record. Raises CheckpointCorruptionError naming every tensor whose
    bytes/dtype/shape differ from what was written, or that went missing
    entirely."""
    recorded = integrity.get("tensors", {})
    live = tensor_checksums(tree)
    bad: List[str] = []
    for name, rec in recorded.items():
        got = live.get(name)
        if got is None:
            bad.append(f"{name} (missing from checkpoint)")
        elif (got["crc32"] != rec["crc32"] or got["dtype"] != rec["dtype"]
              or list(got["shape"]) != list(rec["shape"])):
            bad.append(name)
    if bad:
        raise CheckpointCorruptionError(
            f"checkpoint {path or '<tree>'} failed integrity verification: "
            f"{len(bad)} corrupt tensor(s): " + ", ".join(sorted(bad)),
            path=path, tensors=sorted(bad),
        )


def verify_checkpoint(path: str) -> dict:
    """Offline integrity audit of one checkpoint directory. Returns
    ``{"ok", "path", "checked", "corrupt", "has_integrity"}``; checkpoints
    from before the integrity sidecar report ``has_integrity=False`` and
    ok=True (nothing to verify against). Runnable standalone:
    ``python -m flexflow_tpu.runtime.verify <path>``."""
    import os

    from .checkpoint import _restore_to_host, load_checkpoint_meta

    path = os.path.abspath(path)
    meta = load_checkpoint_meta(path) or {}
    integrity = meta.get("integrity")
    report = {"ok": True, "path": path, "checked": 0, "corrupt": [],
              "has_integrity": integrity is not None}
    if integrity is None:
        return report
    tree = _restore_to_host(path)
    report["checked"] = len(integrity.get("tensors", {}))
    try:
        verify_checksums(tree, integrity, path=path)
    except CheckpointCorruptionError as e:
        report["ok"] = False
        report["corrupt"] = e.tensors
    return report


# ----------------------------------------------------------------------
# bit flips (SDC simulation)
# ----------------------------------------------------------------------
def bitflip_array(arr, *, bit: int = 6, index: int = 3) -> np.ndarray:
    """A host copy of `arr` with one bit flipped in its raw byte stream —
    the CPU-testable stand-in for a faulty core's silent corruption. The
    default (bit 6 of byte 3) lands in a float32 element's exponent, so
    SDC-mode tolerance checks catch it too, not just bitwise ones."""
    a = np.array(arr, copy=True)
    if a.nbytes == 0:
        return a
    flat = a.reshape(-1).view(np.uint8)
    flat[index % flat.size] ^= np.uint8(1 << (bit % 8))
    return a


def bitflip_params(params, *, op: Optional[str] = None,
                   weight: Optional[str] = None, bit: int = 6,
                   index: int = 3):
    """Corrupt ONE weight tensor in a params tree (the FaultInjector
    ``bitflip`` site's live-state consumer). Returns (new_params,
    "op/weight"). Targets the named op/weight, defaulting to the first in
    sorted order. Device arrays are re-put with their original sharding."""
    import jax

    op_names = sorted(params)
    if not op_names:
        raise ValueError("bitflip_params: empty params tree")
    opn = op if op is not None else op_names[0]
    wd = params[opn]
    wn = weight if weight is not None else sorted(wd)[0]
    old = wd[wn]
    flipped = bitflip_array(np.asarray(old), bit=bit, index=index)
    if isinstance(old, jax.Array):
        flipped = jax.device_put(flipped, old.sharding)
    new_params = dict(params)
    new_params[opn] = dict(wd)
    new_params[opn][wn] = flipped
    return new_params, f"{opn}/{wn}"


def corrupt_checkpoint_tensor(path: str, *, tensor: Optional[str] = None,
                              bit: int = 6, index: int = 3) -> str:
    """Flip one bit of one stored tensor in an on-disk checkpoint WITHOUT
    touching its integrity sidecar — the disk-corruption half of the
    ``bitflip`` fault site (``target="disk"``). Re-serializes the loaded
    tree so the corruption lives at the array level regardless of the
    storage format's own framing/compression. Returns the corrupted
    tensor's params path."""
    import jax.tree_util as jtu

    from .checkpoint import _checkpointer, _restore_to_host

    tree = _restore_to_host(path)
    params = tree.get("params") if isinstance(tree, dict) else None
    if not params:
        raise ValueError(f"checkpoint {path} has no params tree to corrupt")
    if tensor is None:
        flat, _ = jtu.tree_flatten_with_path(params)
        target_path, leaf = flat[0]
        name = _flat_path(target_path)
    else:
        name = tensor
        node: Any = params
        for part in name.split("/"):
            node = node[part]
        leaf = node
    flipped = bitflip_array(np.asarray(leaf), bit=bit, index=index)
    node = params
    parts = name.split("/")
    for part in parts[:-1]:
        node = node[part]
    node[parts[-1]] = flipped
    _checkpointer().save(path, tree, force=True)
    return "params/" + name


# ----------------------------------------------------------------------
# SDC / determinism canary
# ----------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class CanaryConfig:
    """Online execution-integrity canary for the resilient fit loop.

    Every `every_n_steps` optimizer steps, the step function is re-executed
    on the SAME cached inputs from the SAME pre-step state and the two
    results compared:

    * ``mode="determinism"`` — bitwise equality. Any difference means the
      step program is non-deterministic (or a core corrupted one run).
    * ``mode="sdc"`` — per-dtype tolerance comparison (`rtol`/`atol`
      override the table). Catches large corruptions while tolerating
      benign non-determinism (e.g. non-deterministic scatter orders).

    `check_invariants` additionally enables cheap per-step sanity bounds:
    a non-finite loss, a global param norm growing more than
    `max_param_norm_ratio`x in one step, or (when set) a loss delta over
    `max_loss_delta`, each raise InvariantViolationError through
    checkpoint-and-raise. Overhead: the canary step costs one extra
    dispatch per cadence; invariants cost one tiny norm dispatch + a
    scalar fetch per step (the resilient loop already syncs per step)."""

    every_n_steps: int = 100
    mode: str = "determinism"  # or "sdc"
    rtol: Optional[float] = None
    atol: Optional[float] = None
    check_invariants: bool = True
    max_param_norm_ratio: float = 50.0
    max_loss_delta: Optional[float] = None

    def __post_init__(self):
        if self.mode not in ("determinism", "sdc"):
            raise ValueError(
                f"CanaryConfig.mode must be 'determinism' or 'sdc', "
                f"got {self.mode!r}"
            )


def compare_step_results(a, b, *, mode: str = "determinism",
                         rtol: Optional[float] = None,
                         atol: Optional[float] = None,
                         max_report: int = 5) -> List[str]:
    """Compare two executions' result trees (params and/or metric
    partials). Returns mismatch descriptions (empty = consistent).
    Determinism mode compares raw bytes; sdc mode uses per-dtype
    tolerances."""
    import jax.tree_util as jtu

    fa, _ = jtu.tree_flatten_with_path(a, is_leaf=lambda x: x is None)
    fb, _ = jtu.tree_flatten_with_path(b, is_leaf=lambda x: x is None)
    bad: List[str] = []
    for (pa, la), (pb, lb) in zip(fa, fb):
        if la is None or lb is None:
            continue
        xa, xb = np.asarray(la), np.asarray(lb)
        name = _flat_path(pa)
        if mode == "determinism":
            if xa.tobytes() != xb.tobytes():
                diff = _max_abs_diff(xa, xb)
                bad.append(f"{name} (bitwise, max|Δ|={diff:.3g})")
        else:
            r, t = tolerance_for(xa.dtype, rtol, atol)
            if not np.allclose(xa.astype(np.float64), xb.astype(np.float64),
                               rtol=r, atol=t, equal_nan=True):
                bad.append(f"{name} (max|Δ|={_max_abs_diff(xa, xb):.3g} "
                           f"> rtol={r:g}/atol={t:g})")
        if len(bad) >= max_report:
            bad.append("...")
            break
    return bad


def _max_abs_diff(a: np.ndarray, b: np.ndarray) -> float:
    try:
        d = np.abs(a.astype(np.float64) - b.astype(np.float64))
        return float(np.nanmax(d)) if d.size else 0.0
    except (TypeError, ValueError):
        return float("nan")


# ----------------------------------------------------------------------
# differential strategy verifier
# ----------------------------------------------------------------------
@dataclasses.dataclass
class StrategyVerdict:
    """Result of a differential strategy verification run."""

    ok: bool
    steps: int
    loss_diffs: List[float] = dataclasses.field(default_factory=list)
    grad_norm_diff: float = 0.0
    max_param_diff: float = 0.0
    param_mismatches: List[str] = dataclasses.field(default_factory=list)
    unmatched_weights: List[str] = dataclasses.field(default_factory=list)
    diverging_op: Optional[str] = None
    validator_problems: List[str] = dataclasses.field(default_factory=list)
    rtol: float = 0.0
    atol: float = 0.0

    def summary(self) -> str:
        head = ("strategy VERIFIED" if self.ok
                else "strategy DIVERGED from serial reference")
        lines = [
            f"{head}: {self.steps} step(s), "
            f"max loss diff {max(self.loss_diffs) if self.loss_diffs else 0.0:.3g}, "
            f"grad-norm diff {self.grad_norm_diff:.3g}, "
            f"max param diff {self.max_param_diff:.3g} "
            f"(rtol={self.rtol:g}, atol={self.atol:g})"
        ]
        if self.diverging_op:
            lines.append(f"first diverging op: {self.diverging_op}")
        if self.param_mismatches:
            lines.append("param mismatches: "
                         + ", ".join(self.param_mismatches[:5]))
        if self.unmatched_weights:
            lines.append(f"{len(self.unmatched_weights)} weight(s) had no "
                         "name match between the searched and serial graphs "
                         "(substitution renamed/merged them) and were "
                         "excluded: "
                         + ", ".join(self.unmatched_weights[:5]))
        if self.validator_problems:
            lines.append("structural validator: "
                         + "; ".join(self.validator_problems[:5]))
        return "\n".join(lines)


def build_reference_executor(model):
    """A fully-serial single-device executor for `model`'s layer list —
    the ground truth the searched strategy is checked against. Re-lowers
    the layers to a fresh PCG (no search, no parallel ops, degree 1
    everywhere) exactly as compile() does before the strategy rewrite, so
    op names line up with the searched graph's by construction."""
    from ..parallel.executor import PCGExecutor
    from ..parallel.mesh import build_mesh
    from ..pcg.lowering import layers_to_pcg

    if getattr(model, "executor", None) is None:
        raise NotCompiledError("verify_strategy: compile() the model first")
    graph, _ = layers_to_pcg(model.layers)
    if model.config.perform_fusion:
        from ..pcg.fusion import apply_fusion

        graph = apply_fusion(graph)
    mesh = build_mesh({"data": 1})
    inputs = graph.input_tensors()
    ordered = [inputs[i] for i in model._input_positions]
    constants = {
        inputs[i].guid: (inputs[i], v)
        for i, v in model._constant_positions.items()
    }
    return PCGExecutor(
        graph, mesh, model.optimizer, model.loss_type, model.metrics_obj,
        compute_dtype=model.executor.compute_dtype,
        grad_dtype=model.executor.grad_dtype,
        seed=model.config.seed,
        input_order=ordered,
        constants=constants,
    )


def _host_params(params) -> Dict[str, Dict[str, np.ndarray]]:
    import jax

    # np.array(copy=True), NOT np.asarray: device_get on the CPU backend
    # returns zero-copy views into live buffers, and these snapshots must
    # survive later donated train-step dispatches (tools/fflint.py FFL101)
    return {
        opn: {wn: np.array(jax.device_get(w), copy=True)
              for wn, w in wd.items()}
        for opn, wd in params.items()
    }


def _copy_named_state(ex, params_host, net_host):
    """Build a TrainState for executor `ex` whose weights/buffers are
    name-matched copies of the given host trees (fresh optimizer state).
    Returns (state, unmatched) — unmatched weights keep their fresh init
    and are excluded from the comparison."""
    import jax

    from ..parallel.executor import TrainState

    params = ex.init_params()
    unmatched: List[str] = []
    for opn, wd in params.items():
        for wn, like in wd.items():
            src = params_host.get(opn, {}).get(wn)
            if src is None or tuple(src.shape) != tuple(like.shape):
                unmatched.append(f"{opn}/{wn}")
                continue
            wd[wn] = jax.device_put(src.astype(like.dtype), like.sharding)
    net = ex.init_net_state()
    for opn, bufs in net.items():
        for bn, like in bufs.items():
            src = (net_host or {}).get(opn, {}).get(bn)
            if src is not None and tuple(np.shape(src)) == tuple(like.shape):
                bufs[bn] = jax.device_put(
                    np.asarray(src).astype(like.dtype), like.sharding
                )
    return TrainState(params=params, opt_state=ex.optimizer.init_state(params),
                      net_state=net), unmatched


def _guard_free_step(ex):
    """An UNDONATED, guard-free jitted train step for an executor —
    verification must not consume the live state's buffers and must not
    require guard extras in the signature."""
    import jax

    saved = ex.step_guard
    ex.step_guard = None
    try:
        fn = ex._make_step()
    finally:
        ex.step_guard = saved
    return jax.jit(fn)


def _matched_compare_params(a_host, b_host, skip, rtol, atol):
    """Name-matched param comparison. Returns (max_diff, mismatches)."""
    worst = 0.0
    bad: List[str] = []
    for opn, wd in a_host.items():
        for wn, va in wd.items():
            key = f"{opn}/{wn}"
            if key in skip:
                continue
            vb = b_host.get(opn, {}).get(wn)
            if vb is None or tuple(vb.shape) != tuple(va.shape):
                continue
            d = _max_abs_diff(va, vb)
            worst = max(worst, d) if np.isfinite(d) else float("inf")
            if not np.allclose(va.astype(np.float64), vb.astype(np.float64),
                               rtol=rtol, atol=atol, equal_nan=True):
                bad.append(f"{key} (max|Δ|={d:.3g})")
    return worst, bad


def find_first_divergence(model, ref_ex, strat_state, ref_state, batch,
                          *, rtol: float, atol: float) -> Optional[str]:
    """Name the first PCG op whose forward output diverges between the
    searched strategy and the serial reference, by bisecting over the
    matched op prefix (both full forwards execute once; the bisection
    probes cached intermediate outputs, so localization costs O(log n)
    array comparisons, not n). None when every matched forward output
    agrees — the divergence is then in the backward/optimizer step."""
    ex = model.executor
    if ex.pipeline_plan is not None:
        return None  # stage internals live per-device; no op-level probe
    bx = [ex.shard_batch(pt, np.asarray(a, pt.data_type.np_dtype))
          for pt, a in zip(ex.input_pts, batch[:-1])]
    bref = [ref_ex.shard_batch(pt, np.asarray(a, pt.data_type.np_dtype))
            for pt, a in zip(ref_ex.input_pts, batch[:-1])]
    # training=False: localization must not depend on dropout RNG streams,
    # whose per-op fold-in indices differ when a substitution changed the
    # compute-op count
    vals_s = ex.apply(strat_state.params, ex._input_vals(bx),
                      training=False, rng=None,
                      net_state=strat_state.net_state)
    vals_r = ref_ex.apply(ref_state.params, ref_ex._input_vals(bref),
                          training=False, rng=None,
                          net_state=ref_state.net_state)
    ref_by_name = {}
    for op in ref_ex.topo:
        if not op.is_parallel_op and op.outputs:
            ref_by_name[op.name] = op
    matched = []
    for op in ex.topo:
        if op.is_parallel_op or not op.outputs:
            continue
        rop = ref_by_name.get(op.name)
        if rop is None:
            continue
        if (tuple(op.outputs[0].material_shape())
                != tuple(rop.outputs[0].material_shape())):
            continue
        matched.append((op, rop))
    if not matched:
        return None

    def diverges(i: int) -> bool:
        op, rop = matched[i]
        a = np.asarray(vals_s[op.outputs[0].guid], np.float64)
        b = np.asarray(vals_r[rop.outputs[0].guid], np.float64)
        return not np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=True)

    lo, hi = 0, len(matched) - 1
    if not diverges(hi):
        return None
    while lo < hi:
        mid = (lo + hi) // 2
        if diverges(mid):
            hi = mid
        else:
            lo = mid + 1
    op = matched[lo][0]
    return f"{op.name} ({op.op_type.name})"


def verify_strategy(model, data, *, steps: int = 2,
                    batch_size: Optional[int] = None,
                    rtol: Optional[float] = None,
                    atol: Optional[float] = None,
                    localize: bool = True,
                    raise_on_divergence: bool = False,
                    verbose: bool = False) -> StrategyVerdict:
    """Differential verification of a compiled model's parallelization
    strategy: run `steps` train steps of the searched/lowered strategy AND
    a serial single-device reference from identical parameters, buffers
    and RNG, and compare per-step loss, first-step global grad norm, and
    final parameters under per-dtype tolerances (the model's compute
    dtype picks the row; `rtol`/`atol` override).

    `data` is ``(x, y)`` with x an array or list of arrays, exactly as
    `fit` takes them. The model's live state is NOT advanced or mutated.
    On divergence, `localize=True` bisects the PCG's matched op prefix to
    name the first diverging op. `raise_on_divergence` turns a failed
    verdict into StrategyDivergenceError — what
    ``fit(verify_strategy="preflight")`` uses."""
    import jax

    if getattr(model, "executor", None) is None or model.state is None:
        raise NotCompiledError("verify_strategy: compile() the model first")
    ex = model.executor
    x, y = data
    xs = list(x) if isinstance(x, (list, tuple)) else [x]
    bs = batch_size or model.config.batch_size
    n = xs[0].shape[0]
    if n < bs:
        raise ValueError(
            f"verify_strategy: dataset has {n} samples < batch_size {bs}"
        )
    # tolerance keyed by the model's COMPUTE dtype (mixed-precision math
    # legitimately reorders bf16 roundoff across shardings), then capped
    # by the static drift budget: tightening
    # FFConfig.precision_drift_budget tightens what verification accepts
    base = tolerance_from_budget(
        "bfloat16" if ex.compute_dtype is not None else "float32",
        getattr(model.config, "precision_drift_budget", None),
    )
    r = base[0] if rtol is None else rtol
    t = base[1] if atol is None else atol

    problems: List[str] = []
    views = getattr(model, "searched_views", None)
    if views:
        from ..search import run_strategy_validators

        problems = run_strategy_validators(
            model.graph, views, model.executor.mesh.size
        )

    ref_ex = build_reference_executor(model)
    params_host = _host_params(model.state.params)
    net_host = {
        opn: {bn: np.array(jax.device_get(b), copy=True)
              for bn, b in bufs.items()}
        for opn, bufs in (model.state.net_state or {}).items()
    }
    from ..parallel.executor import TrainState, global_grad_norm

    strat_state = TrainState(
        params=model.state.params,
        opt_state=ex.optimizer.init_state(model.state.params),
        net_state=model.state.net_state,
    )
    ref_state, unmatched = _copy_named_state(ref_ex, params_host, net_host)
    skip = set(unmatched)

    # snapshots for divergence localization: the forward probe must run
    # from IDENTICAL params (the pre-step states) — after K steps both
    # sides have trained through different gradients, and every op
    # downstream of a weight would look "diverged"
    init_strat_state, init_ref_state = strat_state, ref_state
    strat_step = _guard_free_step(ex)
    ref_step = _guard_free_step(ref_ex)
    label_dt = model.label_tensor.data_type.np_dtype

    def batches():
        nb = n // bs
        for i in range(nb):
            yield [a[i * bs:(i + 1) * bs] for a in list(xs) + [y]]

    verdict = StrategyVerdict(ok=True, steps=0, rtol=r, atol=t,
                              unmatched_weights=unmatched,
                              validator_problems=problems)
    key = jax.random.PRNGKey(model.config.seed + 7919)
    first_batch = None
    gnorm_diff = 0.0
    it = batches()
    for k in range(steps):
        try:
            batch = next(it)
        except StopIteration:
            it = batches()
            batch = next(it)
        if first_batch is None:
            first_batch = batch
        bx_s = [ex.shard_batch(pt, np.asarray(a, pt.data_type.np_dtype))
                for pt, a in zip(ex.input_pts, batch[:-1])]
        bx_r = [ref_ex.shard_batch(pt, np.asarray(a, pt.data_type.np_dtype))
                for pt, a in zip(ref_ex.input_pts, batch[:-1])]
        by_s = ex.put_replicated(np.asarray(batch[-1]).astype(label_dt))
        by_r = ref_ex.put_replicated(np.asarray(batch[-1]).astype(label_dt))
        key, sub = jax.random.split(key)
        if k == 0:
            # first-step global grad norms (one extra dispatch per side)
            gs = ex.build_grad_step()
            gr = ref_ex.build_grad_step()
            g_s, _ = gs(strat_state.params, bx_s, by_s,
                        strat_state.net_state)
            g_r, _ = gr(ref_state.params, bx_r, by_r, ref_state.net_state)
            n_s = float(np.asarray(global_grad_norm(g_s)))
            n_r = float(np.asarray(global_grad_norm(g_r)))
            gnorm_diff = abs(n_s - n_r)
            if not np.isclose(n_s, n_r, rtol=r, atol=max(t, r * abs(n_r))):
                verdict.ok = False
        strat_state, p_s = strat_step(strat_state, bx_s, by_s,
                                      ex.put_replicated(sub))
        ref_state, p_r = ref_step(ref_state, bx_r, by_r,
                                  ref_ex.put_replicated(sub))
        loss_s = float(jax.device_get(p_s["loss"]))
        loss_r = float(jax.device_get(p_r["loss"]))
        verdict.loss_diffs.append(abs(loss_s - loss_r))
        verdict.steps = k + 1
        if not np.isclose(loss_s, loss_r, rtol=r,
                          atol=max(t, r * abs(loss_r))):
            verdict.ok = False
    verdict.grad_norm_diff = gnorm_diff
    a_host = _host_params(strat_state.params)
    b_host = _host_params(ref_state.params)
    verdict.max_param_diff, verdict.param_mismatches = \
        _matched_compare_params(a_host, b_host, skip, r, t)
    if verdict.param_mismatches:
        verdict.ok = False
    if not verdict.ok and localize and first_batch is not None:
        verdict.diverging_op = find_first_divergence(
            model, ref_ex, init_strat_state, init_ref_state, first_batch,
            rtol=r, atol=t,
        )
    if verbose:
        from .. import obs

        obs.progress(
            "[verify] " + verdict.summary().replace("\n", "\n[verify] "),
            name="verify_verdict", cat="runtime", ok=verdict.ok,
            diverging_op=verdict.diverging_op,
        )
    if raise_on_divergence and not verdict.ok:
        raise StrategyDivergenceError(
            "searched strategy is NOT equivalent to the serial reference:\n"
            + verdict.summary(),
            diverging_op=verdict.diverging_op, verdict=verdict,
        )
    return verdict


# ----------------------------------------------------------------------
# structural strategy validator (registered with the search hook)
# ----------------------------------------------------------------------
def validate_searched_strategy(graph, views, num_devices: int) -> List[str]:
    """Structural checks on a searched strategy: every MachineView must
    address only live devices, and no tensor's total parallel degree may
    exceed the device count. Registered as a default strategy validator
    (search.register_strategy_validator) so compile() flags an insane
    search result before it is lowered."""
    from .elastic import validate_machine_views

    problems = list(validate_machine_views(views or {}, num_devices))
    for op in getattr(graph, "ops", []) or []:
        for tensor in op.outputs:
            degree = 1
            for d in getattr(tensor, "dims", ()):
                degree *= max(1, int(getattr(d, "degree", 1)))
            if degree > num_devices:
                problems.append(
                    f"op {op.name}: output degree product {degree} exceeds "
                    f"{num_devices} device(s)"
                )
    return problems


def _main(argv: List[str]) -> int:
    import json as _json

    if not argv:
        print("usage: python -m flexflow_tpu.runtime.verify "  # fflint: disable=FFL201
              "<checkpoint-path> [...]")
        return 2
    rc = 0
    for p in argv:
        rep = verify_checkpoint(p)
        print(_json.dumps(rep, indent=2))  # fflint: disable=FFL201
        if not rep["ok"]:
            rc = 1
    return rc


if __name__ == "__main__":
    import sys

    sys.exit(_main(sys.argv[1:]))
