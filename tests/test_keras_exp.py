"""keras_exp frontend (reference: python/flexflow/keras_exp/models/model.py,
examples/python/keras_exp/func_mnist_mlp.py). The reference path is
tf.keras → keras2onnx → ONNXModelKeras; TF isn't installed here, so these
tests exercise the same BaseModel/Model pipeline from a pre-exported ONNX
ModelProto built with the self-contained proto codec."""
from types import SimpleNamespace

import numpy as np

from flexflow_tpu.config import FFConfig
from flexflow_tpu.frontends.keras_exp.models import Model
from flexflow_tpu.frontends.onnx import proto


def _mlp_proto(dims=(784, 64, 10), seed=0):
    """keras2onnx-style MLP: MatMul with (in, out) kernels + Relu + Softmax."""
    rng = np.random.RandomState(seed)
    nodes, inits = [], []
    prev = "input_1"
    for i in range(len(dims) - 1):
        w = (rng.randn(dims[i], dims[i + 1]) / np.sqrt(dims[i])).astype(
            np.float32)
        inits.append(proto.from_array(w, f"dense_{i}/kernel"))
        nodes.append(proto.make_node("MatMul", [prev, f"dense_{i}/kernel"],
                                     [f"mm{i}"], name=f"MatMul_{i}"))
        prev = f"mm{i}"
        if i < len(dims) - 2:
            nodes.append(proto.make_node("Relu", [prev], [f"relu{i}"],
                                         name=f"Relu_{i}"))
            prev = f"relu{i}"
    nodes.append(proto.make_node("Softmax", [prev], ["out"], name="Softmax_0",
                                 axis=-1))
    graph = proto.make_graph(
        nodes, "keras_model",
        [proto.make_tensor_value_info("input_1", proto.TensorProto.FLOAT,
                                      ["N", dims[0]])],
        [proto.make_tensor_value_info("out", proto.TensorProto.FLOAT,
                                      ["N", dims[-1]])],
        initializer=inits)
    return proto.make_model(graph)


def test_keras_exp_mnist_mlp_trains():
    cfg = FFConfig()
    cfg.batch_size = 16
    model = Model(
        inputs={1: SimpleNamespace(shape=(None, 784), dtype="float32")},
        onnx_model=_mlp_proto(),
        ffconfig=cfg,
    )
    model.compile(optimizer="SGD", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy", "sparse_categorical_crossentropy"])
    assert "MatMul" in model.summary()

    rng = np.random.RandomState(0)
    x = rng.rand(64, 784).astype(np.float32)
    y = rng.randint(0, 10, (64, 1)).astype(np.int32)
    pm0 = model.fit(x, y, batch_size=16, epochs=1)
    loss0 = pm0.sparse_cce_loss
    pm = model.fit(x, y, epochs=3)
    assert pm.sparse_cce_loss < loss0, (pm.sparse_cce_loss, loss0)


def test_keras_exp_tf_optimizer_duck_typing():
    """A tf.keras-style optimizer object (hyperparams exposing .numpy())
    converts without tensorflow installed."""
    cfg = FFConfig()
    cfg.batch_size = 8
    fake_var = SimpleNamespace(numpy=lambda: 0.05)
    tf_like_sgd = type("SGD", (), {"learning_rate": fake_var,
                                   "momentum": SimpleNamespace(numpy=lambda: 0.9),
                                   "nesterov": False})()
    model = Model(
        inputs={1: SimpleNamespace(shape=(None, 784), dtype="float32")},
        onnx_model=_mlp_proto(),
        ffconfig=cfg,
    )
    model.compile(optimizer=tf_like_sgd, loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    opt = model._base_model._ffoptimizer
    assert opt.learning_rate == 0.05 and opt.momentum == 0.9


def test_keras_exp_live_model_converts_without_tensorflow():
    """VERDICT r2 #10: the TF-import branch, un-gated. A LIVE functional
    keras model (flexflow_tpu's keras frontend satisfies the tensor
    contract) converts through the vendored keras->ONNX converter
    (keras2onnx_min) — covering the layer subset the reference's
    keras_exp examples use — then compiles and trains, with no
    tensorflow, tf2onnx, or keras2onnx installed."""
    from flexflow_tpu.frontends.keras import layers as L

    x_img = L.Input((3, 16, 16))
    t = L.Conv2D(8, 3, padding="same", activation="relu")(x_img)
    t = L.MaxPooling2D(2)(t)
    t = L.Flatten()(t)
    x_vec = L.Input((12,))
    v = L.Dense(8)(x_vec)
    v = L.Activation("relu")(v)
    merged = L.Concatenate(axis=1)([t, v])
    out = L.Dense(10, activation="softmax")(merged)

    cfg = FFConfig()
    cfg.batch_size = 8
    model = Model(inputs={1: x_img, 2: x_vec}, outputs=out, ffconfig=cfg)
    model.compile(optimizer="SGD", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy", "sparse_categorical_crossentropy"])
    s = model.summary()
    assert "Conv" in s and "Gemm" in s and "Concat" in s

    rng = np.random.RandomState(0)
    xi = rng.rand(32, 3, 16, 16).astype(np.float32)
    xv = rng.rand(32, 12).astype(np.float32)
    y = rng.randint(0, 10, (32, 1)).astype(np.int32)
    pm0 = model.fit([xi, xv], y, batch_size=8, epochs=1)
    loss0 = pm0.sparse_cce_loss
    pm = model.fit([xi, xv], y, epochs=4)
    assert pm.sparse_cce_loss < loss0


def test_keras_exp_vendored_conversion_numeric_parity():
    """The vendored converter's embedded weights are REAL model weights:
    a Dense-only conversion's forward must equal the numpy computation
    with the ONNX initializers it emitted."""
    from flexflow_tpu.frontends.keras import layers as L
    from flexflow_tpu.frontends.keras_exp.keras2onnx_min import keras_to_onnx
    from flexflow_tpu.frontends.onnx import proto as P

    x_in = L.Input((6,))
    out = L.Dense(4, use_bias=True)(x_in)

    class Live:
        inputs = [x_in]
        outputs = [out]

    m = keras_to_onnx(Live(), "parity")
    inits = {t.name: P.to_array(t) for t in m.graph.initializer}
    (wname,) = [n for n in inits if n.startswith("W")]
    w = inits[wname]  # (out, in) — Gemm transB=1
    assert w.shape == (4, 6)

    cfg = FFConfig()
    cfg.batch_size = 4
    model = Model(inputs={1: SimpleNamespace(shape=(None, 6))},
                  onnx_model=m, ffconfig=cfg)
    model.compile(optimizer="SGD", loss="mean_squared_error",
                  metrics=["mean_squared_error"])
    rng = np.random.RandomState(1)
    x = rng.rand(4, 6).astype(np.float32)
    ff = model.ffmodel
    fwd = ff.executor.build_forward()
    got = np.asarray(fwd(ff.state.params, [x]))
    np.testing.assert_allclose(got, x @ w.T, rtol=1e-5, atol=1e-5)
