"""Declarative substitution loader tests (reference:
tests/unit/test_substitution_loader.cc builds an in-memory rule and checks
loading; we also parse the reference's shipped rule collection)."""
import os

import numpy as np
import pytest

from flexflow_tpu import ActiMode, DataType, FFConfig, FFModel
from flexflow_tpu.ff_types import OperatorType
from flexflow_tpu.pcg.lowering import layers_to_pcg
from flexflow_tpu.search.substitution_loader import (
    Rule,
    apply_rule,
    load_rule_collection,
    load_rule_collection_from_path,
    rules_to_substitutions,
)

REF_JSON = "/root/reference/substitutions/graph_subst_3_v2.json"


def make_inmemory_rule():
    """A partition->combine identity-ish rewrite over a linear op (the
    in-memory-rule pattern of the reference unit test)."""
    return {
        "rule": [
            {
                "_t": "Rule",
                "name": "partition_linear_combine_2",
                "srcOp": [
                    {
                        "_t": "Operator",
                        "type": "OP_LINEAR",
                        "input": [{"_t": "Tensor", "opId": -1, "tsId": 0}],
                        "para": [],
                    }
                ],
                "dstOp": [
                    {
                        "_t": "Operator",
                        "type": "OP_PARTITION",
                        "input": [{"_t": "Tensor", "opId": -1, "tsId": 0}],
                        "para": [
                            {"_t": "Parameter", "key": "PM_PARALLEL_DIM", "value": 0},
                            {"_t": "Parameter", "key": "PM_PARALLEL_DEGREE", "value": 2},
                        ],
                    },
                    {
                        "_t": "Operator",
                        "type": "OP_LINEAR",
                        "input": [{"_t": "Tensor", "opId": 0, "tsId": 0}],
                        "para": [],
                    },
                    {
                        "_t": "Operator",
                        "type": "OP_COMBINE",
                        "input": [{"_t": "Tensor", "opId": 1, "tsId": 0}],
                        "para": [
                            {"_t": "Parameter", "key": "PM_PARALLEL_DIM", "value": 0},
                            {"_t": "Parameter", "key": "PM_PARALLEL_DEGREE", "value": 2},
                        ],
                    },
                ],
                "mappedOutput": [
                    {"_t": "MapOutput", "srcOpId": 0, "srcTsId": 0,
                     "dstOpId": 2, "dstTsId": 0}
                ],
            }
        ]
    }


def test_inmemory_rule_loads_and_applies():
    rules = load_rule_collection(make_inmemory_rule())
    assert len(rules) == 1 and rules[0].supported
    model = FFModel(FFConfig())
    x = model.create_tensor((64, 32), DataType.DT_FLOAT)
    model.dense(x, 16)
    graph, _ = layers_to_pcg(model.layers)
    cands = list(apply_rule(graph, rules[0]))
    assert len(cands) == 1
    g2 = cands[0]
    types = [o.op_type for o in g2.topo_order()]
    assert types == [
        OperatorType.OP_REPARTITION,
        OperatorType.OP_LINEAR,
        OperatorType.OP_COMBINE,
    ]
    # the batch dim is now partitioned between partition and combine
    lin = g2.topo_order()[1]
    assert lin.inputs[0].dims[0].degree == 2


@pytest.mark.skipif(not os.path.exists(REF_JSON), reason="reference not mounted")
def test_reference_rule_collection_parses():
    rules = load_rule_collection_from_path(REF_JSON)
    assert len(rules) > 100
    supported = [r for r in rules if r.supported]
    assert len(supported) > 0
    subs = rules_to_substitutions(supported[:20])
    assert subs
