"""Keras-compatible Model / Sequential.

TPU-native equivalent of the reference's keras model classes
(python/flexflow/keras/models/base_model.py:128 compile, :198 fit;
sequential.py, model.py): traverse the deferred Keras layer graph, replay it
through FFModel, then delegate compile/fit/evaluate/predict to the core.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import numpy as np

from ...config import FFConfig
from ...core.model import FFModel
from ...ff_types import DataType, LossType, MetricsType
from .layers import Input, KerasTensor, Layer
from .optimizers import Optimizer as KerasOptimizer, SGD


_LOSS_MAP = {
    "categorical_crossentropy": LossType.LOSS_CATEGORICAL_CROSSENTROPY,
    "sparse_categorical_crossentropy": LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
    "mse": LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
    "identity": LossType.LOSS_IDENTITY,
}

_METRIC_MAP = {
    "accuracy": MetricsType.METRICS_ACCURACY,
    "sparse_categorical_crossentropy": MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY,
    "categorical_crossentropy": MetricsType.METRICS_CATEGORICAL_CROSSENTROPY,
    "mean_squared_error": MetricsType.METRICS_MEAN_SQUARED_ERROR,
    "root_mean_squared_error": MetricsType.METRICS_ROOT_MEAN_SQUARED_ERROR,
    "mean_absolute_error": MetricsType.METRICS_MEAN_ABSOLUTE_ERROR,
    # Keras short aliases
    "acc": MetricsType.METRICS_ACCURACY,
    "mse": MetricsType.METRICS_MEAN_SQUARED_ERROR,
    "rmse": MetricsType.METRICS_ROOT_MEAN_SQUARED_ERROR,
    "mae": MetricsType.METRICS_MEAN_ABSOLUTE_ERROR,
}


class Model:
    """Functional-API model (reference: keras/models/model.py)."""

    def __init__(self, inputs=None, outputs=None, name: str = "model"):
        self.name = name
        self.inputs: List[KerasTensor] = (
            list(inputs) if isinstance(inputs, (list, tuple)) else ([inputs] if inputs else [])
        )
        self.outputs: List[KerasTensor] = (
            list(outputs) if isinstance(outputs, (list, tuple)) else ([outputs] if outputs else [])
        )
        self.ffmodel: Optional[FFModel] = None
        self.ffconfig = FFConfig()
        self._callbacks = []

    # -- graph replay ----------------------------------------------------
    def _toposort_layers(self) -> List[Layer]:
        order: List[Layer] = []
        visited = set()

        def visit(t: KerasTensor):
            layer = t.source_layer
            if layer is None or id(layer) in visited:
                return
            visited.add(id(layer))
            for it in layer.inbound:
                visit(it)
            order.append(layer)

        for out in self.outputs:
            visit(out)
        return order

    def _build_ff(self, batch_size: int):
        self.ffconfig.batch_size = batch_size
        ffmodel = FFModel(self.ffconfig)
        tensor_of = {}
        for kt in self.inputs:
            dtype = getattr(kt, "dtype", DataType.DT_FLOAT)
            tensor_of[id(kt)] = ffmodel.create_tensor(
                (batch_size,) + kt.shape, dtype
            )
        for layer in self._toposort_layers():
            ff_ins = [tensor_of[id(t)] for t in layer.inbound]
            outs = layer.build_ff(ffmodel, ff_ins)
            for kt, ft in zip(layer.outputs, outs):
                tensor_of[id(kt)] = ft
        self.ffmodel = ffmodel
        return ffmodel

    # -- keras API -------------------------------------------------------
    def compile(self, optimizer="sgd", loss=None, metrics=(), batch_size=None, **kw):
        """reference: base_model.py:128"""
        bs = batch_size or self.ffconfig.batch_size
        ffmodel = self._build_ff(bs)
        if isinstance(optimizer, str):
            optimizer = {"sgd": SGD(), "adam": __import__(
                "flexflow_tpu.frontends.keras.optimizers", fromlist=["Adam"]
            ).Adam()}[optimizer.lower()]
        core_opt = (
            optimizer.to_core() if isinstance(optimizer, KerasOptimizer) else optimizer
        )
        # strings, LossType/MetricsType enums, or keras loss/metric objects
        # carrying `.type` (losses.py / metrics.py) are all accepted
        if isinstance(loss, str):
            loss_type = _LOSS_MAP[loss]
        elif hasattr(loss, "type") and loss.type is not None:
            loss_type = loss.type
        else:
            loss_type = loss
        ms = []
        for m in metrics:
            if isinstance(m, str):
                ms.append(_METRIC_MAP[m])
            elif hasattr(m, "type") and not isinstance(m, MetricsType):
                ms.append(m.type)
            else:
                ms.append(m)
        ffmodel.compile(optimizer=core_opt, loss_type=loss_type, metrics=ms)
        return self

    def fit(self, x=None, y=None, batch_size=None, epochs=1, verbose=True,
            callbacks=None, **kw):
        """reference: base_model.py:198"""
        assert self.ffmodel is not None, "call compile() first"
        cbs = list(callbacks or [])
        for cb in cbs:
            cb.set_model(self)
            cb.on_train_begin()
        pm = None
        for epoch in range(epochs):
            for cb in cbs:
                cb.on_epoch_begin(epoch)
            pm = self.ffmodel.fit(x, y, batch_size=batch_size, epochs=1,
                                  verbose=verbose)
            logs = {
                "accuracy": pm.get_accuracy(),
                "loss": pm.sparse_cce_loss or pm.cce_loss or pm.mse_loss,
            }
            for cb in cbs:
                cb.on_epoch_end(epoch, logs)
        for cb in cbs:
            cb.on_train_end()
        return pm

    def evaluate(self, x=None, y=None, batch_size=None, **kw):
        return self.ffmodel.eval(x, y, batch_size=batch_size)

    def predict(self, x, batch_size=None, **kw):
        return self.ffmodel.predict(x, batch_size=batch_size)

    def summary(self) -> str:
        lines = [f'Model: "{self.name}"', "_" * 60]
        for layer in self._toposort_layers():
            shapes = [t.shape for t in layer.outputs]
            lines.append(f"{layer.name:<30}{type(layer).__name__:<18}{shapes}")
        text = "\n".join(lines)
        # keras API parity: Model.summary() prints by contract
        print(text)  # fflint: disable=FFL201
        return text

    def __call__(self, inputs):
        """Use a built model as a layer (reference: nested-model examples,
        e.g. examples/python/keras/seq_mnist_cnn_nested.py — a Sequential /
        functional Model is wired into another model's graph). Re-wires this
        model's layers onto the given input tensors and returns the mapped
        output tensor(s)."""
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        assert len(ins) == len(self.inputs), (
            f"model {self.name} expects {len(self.inputs)} inputs, got {len(ins)}"
        )
        if getattr(self, "_called_as_layer", False):
            # Layer objects are re-wired in place, so a second call would
            # corrupt the graph built by the first (no keras-style layer
            # sharing). Fail loudly instead of silently mis-building.
            raise NotImplementedError(
                f"model {self.name} was already called on tensors once; "
                "re-calling a model (weight sharing) is not supported — "
                "build a fresh model instead"
            )
        self._called_as_layer = True
        mapping = {id(kt): new for kt, new in zip(self.inputs, ins)}
        order = self._toposort_layers()
        old_model_outs = list(self.outputs)
        for layer in order:
            old_outs = list(layer.outputs)
            new_ins = [mapping[id(t)] for t in layer.inbound]
            res = layer(new_ins if len(new_ins) > 1 else new_ins[0])
            new_outs = res if isinstance(res, (list, tuple)) else [res]
            for o, n in zip(old_outs, new_outs):
                mapping[id(o)] = n
        new_model_outs = [mapping[id(o)] for o in old_model_outs]
        self.inputs = list(ins)
        self.outputs = new_model_outs
        return new_model_outs[0] if len(new_model_outs) == 1 else new_model_outs

    @property
    def layers(self) -> List[Layer]:
        return self._toposort_layers()

    def get_layer(self, name: Optional[str] = None, index: Optional[int] = None):
        """reference: base_model.py get_layer(name=, index=) — used by the
        net2net examples to pull teacher weights."""
        layers = self._toposort_layers()
        if index is not None:
            return layers[index]
        for layer in layers:
            if layer.name == name:
                return layer
        raise ValueError(f"no layer named {name!r}")


class Sequential(Model):
    """reference: keras/models/sequential.py"""

    def __init__(self, layers: Optional[Sequence[Layer]] = None, name="sequential"):
        super().__init__(name=name)
        self._stack: List[Layer] = []
        for l in layers or []:
            self.add(l)

    def add(self, layer_or_input):
        if isinstance(layer_or_input, KerasTensor):
            self.inputs = [layer_or_input]
            self._last = layer_or_input
            return
        if isinstance(layer_or_input, Model):
            # nested model (reference: seq_mnist_cnn_nested.py)
            m = layer_or_input
            if not self.inputs:
                self.inputs = list(m.inputs)
                self._last = m.outputs[0]
            else:
                self._last = m(self._last)
            self.outputs = [self._last]
            return
        if not self.inputs:
            # first layer must declare input_shape
            shape = getattr(layer_or_input, "input_shape", None)
            assert shape is not None, (
                "first Sequential layer needs input_shape= or add(Input(...))"
            )
            inp = Input(shape)
            self.inputs = [inp]
            self._last = inp
        self._stack.append(layer_or_input)
        self._last = layer_or_input(self._last)
        self.outputs = [self._last]
