"""Workload-zoo tests: the searched MoE + long-context models as
first-class citizens (ISSUE 14). Fast cases cover the MoE balance loss
reaching the gradient, the FFA507/FFA508 expert-capacity lint, the
declarative expert-routing rules (shipped collections validate; a
malformed one is rejected at load), the all-to-all collective-bytes
export, and the ring/ulysses sequence-parallel fallback accounting.
Slow cases push both zoo models through search + verify_strategy on the
8-device CPU mesh and assert the searched strategy beats pure data
parallelism under the cost model (scripts/zoo_check.sh runs them)."""
import warnings as warnings_mod

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu import (
    FFConfig,
    FFModel,
    LossType,
    SGDOptimizer,
)
from flexflow_tpu import models as zoo
from flexflow_tpu.pcg.lowering import layers_to_pcg

RNG = np.random.RandomState(0)


def _make(batch, budget=0):
    cfg = FFConfig()
    cfg.batch_size = batch
    if budget:
        cfg.search_budget = budget
    return FFModel(cfg)


def _compile_moe_classifier(lambda_bal):
    m = _make(8)
    zoo.build_moe(m, 8, input_dim=16, num_classes=4, num_exp=4,
                  num_select=2, hidden=16, lambda_bal=lambda_bal)
    m.compile(SGDOptimizer(lr=0.05),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])
    return m


# ---------------------------------------------------------------------------
# satellite 1: the lambda_bal balance loss reaches the gradient
# ---------------------------------------------------------------------------

def test_moe_lambda_bal_reaches_gradient():
    """Two identically-seeded MoE models differing ONLY in lambda_bal must
    produce different gradients from the same batch — the balance aux loss
    flows through fit()'s loss (executor loss_of sums aux_out), so a zero
    diff means the aux term was silently dropped from the objective."""
    m0 = _compile_moe_classifier(0.0)
    m1 = _compile_moe_classifier(5.0)
    rng = np.random.RandomState(7)
    x_np = rng.randn(8, 16).astype(np.float32)
    y = jnp.asarray(rng.randint(0, 4, (8, 1)), jnp.int32)

    leaves = []
    for m in (m0, m1):
        ex = m.executor
        x = ex.shard_batch(ex.input_pts[0], x_np)
        grads, _ = ex.build_grad_step()(m.state.params, [x], y)
        leaves.append(jax.tree_util.tree_leaves(grads))
    # same seed => identical init; the graphs differ only in lambda_bal
    p0 = jax.tree_util.tree_leaves(m0.state.params)
    p1 = jax.tree_util.tree_leaves(m1.state.params)
    assert all(np.allclose(np.asarray(a), np.asarray(b))
               for a, b in zip(p0, p1)), "init must match for the diff test"
    diff = max(
        float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
        for a, b in zip(*leaves)
    )
    assert diff > 1e-8, (
        "gradients identical with and without lambda_bal — the balance "
        "aux loss never reached the training objective")


# ---------------------------------------------------------------------------
# satellite 4: seeded-defect cases for the FFA507/FFA508 capacity lint
# ---------------------------------------------------------------------------

def _moe_graph(alpha):
    m = _make(16)
    zoo.build_moe(m, 16, input_dim=16, num_classes=4, num_exp=4,
                  num_select=2, hidden=16, alpha=alpha)
    g, _ = layers_to_pcg(m.layers)
    return g


def _codes(rep):
    return [d.code for d in rep.diagnostics]


def test_capacity_lint_flags_token_dropping():
    from flexflow_tpu.analysis.perf import perf_diagnostics

    # alpha=0.5: 4 experts x cap 4 = 16 slots for 32 routed assignments
    rep = perf_diagnostics(_moe_graph(0.5))
    assert "FFA507" in _codes(rep)
    d = next(d for d in rep.diagnostics if d.code == "FFA507")
    assert "statically dropped" in d.message


def test_capacity_lint_flags_indivisible_degree():
    from flexflow_tpu.analysis.perf import perf_diagnostics
    from flexflow_tpu.analysis.diagnostics import Severity

    # alpha=2.0 bakes capacity 16; expert degree 3 can't shard it evenly
    rep = perf_diagnostics(_moe_graph(2.0), expert_degree=3)
    errs = [d for d in rep.diagnostics if d.code == "FFA508"]
    assert errs and all(d.severity == Severity.ERROR for d in errs)


def test_capacity_lint_clean_dispatch_passes():
    from flexflow_tpu.analysis.perf import perf_diagnostics

    # dropless capacity, degree 2 divides cap 16: neither code fires
    rep = perf_diagnostics(_moe_graph(2.0), expert_degree=2)
    assert "FFA507" not in _codes(rep)
    assert "FFA508" not in _codes(rep)


# ---------------------------------------------------------------------------
# satellite 4: declarative expert-routing rules — shipped collections are
# FFA4xx-clean, malformed ones are rejected at load time
# ---------------------------------------------------------------------------

def test_shipped_zoo_rule_collections_validate():
    import os

    from flexflow_tpu.search.substitution_loader import (
        load_rule_collection_from_path,
        moe_capacity_rules_path,
        zoo_rules_path,
    )

    for path in (zoo_rules_path(), moe_capacity_rules_path()):
        assert os.path.exists(path), path
        rules = load_rule_collection_from_path(path, validate=True)
        assert rules, f"{path} loaded no rules"


def test_malformed_expert_rule_rejected():
    from flexflow_tpu.search.substitution_loader import (
        SubstitutionRuleError,
        load_rule_collection,
    )

    # an expert-dispatch rewrite whose AllToAll forgets PM_GATHER_DIM:
    # load_rule_collection(validate=True) must reject it with the FFA404
    # missing-required-param code instead of KeyError'ing in the search
    rule = {
        "rule": [{
            "name": "bad_expert_dispatch",
            "srcOp": [{
                "type": "OP_PARTITION",
                "input": [{"opId": -1, "tsId": 0}],
                "para": [{"key": "PM_PARALLEL_DIM", "value": 1},
                         {"key": "PM_PARALLEL_DEGREE", "value": 2}],
            }],
            "dstOp": [{
                "type": "OP_ALL_TO_ALL",
                "input": [{"opId": -1, "tsId": 0}],
                "para": [{"key": "PM_SCATTER_DIM", "value": 1},
                         {"key": "PM_PARALLEL_DEGREE", "value": 2}],
            }],
            "mappedOutput": [{"srcOpId": 0, "srcTsId": 0,
                              "dstOpId": 0, "dstTsId": 0}],
        }]
    }
    with pytest.raises(SubstitutionRuleError, match="FFA404"):
        load_rule_collection(rule, validate=True)


# ---------------------------------------------------------------------------
# tentpole: the expert dispatch prices as all-to-all wire bytes
# ---------------------------------------------------------------------------

def test_expert_dispatch_exports_all_to_all_bytes():
    from flexflow_tpu.analysis.collectives import estimate_collective_bytes
    from flexflow_tpu.search.substitution import (
        partition_batch,
        partition_experts_alltoall,
    )

    # alpha=1.2 bakes capacity 10: partition_batch(4) can't shard the
    # capacity dim, so the dispatch stays whole and the expert rewrite
    # applies (the same shape the searched transformer config hits)
    g = _moe_graph(1.2)
    g_dp = next(partition_batch(4).apply(g))
    g_ep = next(partition_experts_alltoall(4).apply(g_dp), None)
    assert g_ep is not None, "expert all-to-all rewrite found no dispatch"
    recs = [r for r in estimate_collective_bytes(g_ep)
            if r["kind"] == "all_to_all"]
    assert recs and all(r["bytes"] > 0 for r in recs), (
        "searched expert dispatch must export nonzero "
        'ff_pcg_collective_bytes{kind="all_to_all"}')


# ---------------------------------------------------------------------------
# satellite 2: ring/ulysses fall back to dense with the same counter +
# deduped warning as the dropout fallbacks
# ---------------------------------------------------------------------------

def test_ring_fallback_counts_and_dedups(monkeypatch, tmp_path):
    from flexflow_tpu import obs
    from flexflow_tpu.ff_types import DataType, OperatorType
    from flexflow_tpu.obs import TelemetryConfig
    from flexflow_tpu.ops import attention as mha
    from flexflow_tpu.ops.registry import FwdCtx, get_op_def

    monkeypatch.setenv("FF_ATTENTION_IMPL", "ring")
    mha.reset_attention_fallback_warnings()
    params = mha.MultiHeadAttentionParams(embed_dim=16, num_heads=2)
    opdef = get_op_def(OperatorType.OP_MULTIHEAD_ATTENTION)
    x = jnp.asarray(RNG.randn(2, 8, 16).astype(np.float32))
    ws = opdef.weights(params, [(2, 8, 16)] * 3, [DataType.DT_FLOAT] * 3)
    key = jax.random.PRNGKey(5)
    weights = {}
    for w in ws:
        key, sub = jax.random.split(key)
        weights[w.name] = jax.random.normal(sub, w.shape, jnp.float32) * 0.1

    with obs.session(TelemetryConfig(dir=str(tmp_path / "tel"))):
        # no seq-sharded mesh in ctx -> requested SP can't lower: sp_mesh
        ctx = FwdCtx(training=True, rng=key, op_name="layer0")
        with pytest.warns(UserWarning, match="sequence parallelism"):
            opdef.forward(params, weights, [x, x, x], ctx)
        # same (impl, layer, reason): deduped, but the counter still moves
        with warnings_mod.catch_warnings():
            warnings_mod.simplefilter("error")
            opdef.forward(params, weights, [x, x, x], ctx)
        ctx1 = FwdCtx(training=True, rng=key, op_name="layer1")
        with pytest.warns(UserWarning, match="layer1"):
            opdef.forward(params, weights, [x, x, x], ctx1)
        c = obs.active().metrics.find("ff_attention_fallback_total",
                                      reason="sp_mesh")
        assert c is not None and c.value == 3.0


# ---------------------------------------------------------------------------
# slow: both zoo models — search beats pure DP, strategy verifies vs serial
# ---------------------------------------------------------------------------

def _pure_dp_cost(model, dp_degree):
    """Cost of the --only-data-parallel lowering of `model`'s SERIAL graph
    under the same cost oracle the search used."""
    from flexflow_tpu.pcg.machine_view import MachineResource
    from flexflow_tpu.search import SearchHelper
    from flexflow_tpu.search.substitution import partition_batch

    cost_model = model._build_cost_model()
    machine = cost_model.machine
    sh = SearchHelper(cost_model)
    res = MachineResource(
        num_nodes=machine.num_nodes,
        all_procs_per_node=machine.workers_per_node,
        available_procs_per_node=machine.workers_per_node,
    )
    g, _ = layers_to_pcg(model.layers)
    g_dp = next(partition_batch(dp_degree).apply(g))
    return sh.graph_cost(g_dp, res).cost


@pytest.mark.slow
def test_moe_transformer_searched_strategy_verifies():
    from flexflow_tpu.analysis.collectives import estimate_collective_bytes
    from flexflow_tpu.runtime.verify import verify_strategy

    m = _make(16, budget=24)
    zoo.build_moe_transformer(
        m, batch_size=16, seq_length=64, hidden_size=768, num_heads=4,
        num_layers=2, num_experts=4, top_k=2, capacity_factor=1.2,
        lambda_bal=0.04,
    )
    m.compile(SGDOptimizer(lr=0.05),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])

    # acceptance: the searched strategy must beat pure data parallelism
    dp = _pure_dp_cost(m, min(16, len(jax.devices())))
    assert m.searched_cost < dp, (
        f"searched {m.searched_cost:.3f} not better than pure DP {dp:.3f}")
    # and the expert dispatch shows up as all-to-all wire bytes
    a2a = sum(r["bytes"] for r in
              estimate_collective_bytes(m.graph, m.searched_views)
              if r["kind"] == "all_to_all")
    assert a2a > 0, "searched MoE strategy exports no all_to_all bytes"

    rng = np.random.RandomState(0)
    x = rng.randn(16, 64, 768).astype(np.float32)
    y = rng.randint(0, 10, (16, 64, 1)).astype(np.int32)
    v = verify_strategy(m, (x, y), steps=3)
    assert v.ok, f"strategy verification failed: {v}"
    assert not v.validator_problems, v.validator_problems


@pytest.mark.slow
def test_long_context_transformer_searched_strategy_verifies():
    from flexflow_tpu.runtime.verify import verify_strategy

    m = _make(4, budget=24)
    zoo.build_long_context_transformer(
        m, batch_size=4, seq_length=512, hidden_size=64, num_heads=8,
        num_layers=2,
    )
    m.compile(SGDOptimizer(lr=0.05),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])

    # batch 4 caps pure DP at degree 4 on the 8-device mesh
    dp = _pure_dp_cost(m, min(4, len(jax.devices())))
    assert m.searched_cost < dp, (
        f"searched {m.searched_cost:.3f} not better than pure DP {dp:.3f}")

    rng = np.random.RandomState(0)
    x = rng.randn(4, 512, 64).astype(np.float32)
    y = rng.randint(0, 10, (4, 512, 1)).astype(np.int32)
    v = verify_strategy(m, (x, y), steps=3)
    assert v.ok, f"strategy verification failed: {v}"
    assert not v.validator_problems, v.validator_problems
