"""Transformer weak-scaling on the v5e-32 machine model — the "why is
the 32-chip unity number the same as the 8-chip one?" answer (round-3
docs; VERDICT r2 #7 follow-up).

At the OSDI bert.sh batch (64), 24 of 32 chips buy nothing: the grad
allreduce of the replicated weights (~302 MB f32) dominates any extra
batch split, so the searched strategy saturates at the 8-chip hybrid.
Scaling the batch with the machine (64@8 -> 256@32, constant per-chip
batch — weak scaling) restores work per chip and the search finds wider
strategies. This mirrors the reference's own artifact choices: bert.sh
runs batch 8 on 4 GPUs and the paper's large-cluster wins use
correspondingly larger batches.

    python benchmarks/weak_scaling.py
"""
import json
import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))

from unity_speedup import run  # noqa: E402  (same cost/search harness)


def main():
    from flexflow_tpu.models.transformer import build_transformer
    from flexflow_tpu.search import MachineModel, parse_machine_config

    root = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    v5e8 = MachineModel(num_nodes=1, workers_per_node=8)
    v5e32 = parse_machine_config(os.path.join(root, "machine_config_v5e32"))

    cases = [
        ("transformer_b64@v5e8", v5e8, 64, [2, 4, 8]),
        ("transformer_b64@v5e32", v5e32, 64, [2, 4, 8, 16, 32]),
        ("transformer_b256@v5e32", v5e32, 256, [2, 4, 8, 16, 32]),
    ]
    out = []
    for name, machine, batch, degrees in cases:
        s = run(name, lambda m, b=batch: build_transformer(m, batch_size=b),
                machine, degrees, budget=20)
        out.append((name, s))
    print(json.dumps({"metric": "transformer_weak_scaling",
                      "speedups": dict(out)}), flush=True)


if __name__ == "__main__":
    main()
