#!/usr/bin/env bash
# Fleet observatory end-to-end check (docs/observability.md "Fleet
# observatory"): drive a 3-replica ReplicaSet through the overload ramp
# with a mid-ramp replica kill, every replica spooling its counters into
# a shared fleet directory, and assert the cross-process contract:
#
#   1. the fleet rollup CONSERVES request counts through the kill — the
#      victim's final tally survives in its terminal spool, so summed
#      ff_serving_requests_total equals the client's completed count;
#   2. the killed replica's spool classifies stale/dead, never live;
#   3. the scale-up the ramp provokes names the anomaly the sentinel
#      blamed it on (replica_scale_up event carries a non-empty
#      `anomaly` tag);
#   4. the replica death dumped a forensics bundle naming the victim,
#      and `obs forensics --validate` accepts the whole bundle dir;
#   5. the `obs fleet` CLI renders the same spools as a table and a
#      parseable Prometheus page with the ff_fleet_* meta-series.
#
# Runs on the virtual CPU mesh; CI wires it into the lint workflow.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export JAX_NUM_CPU_DEVICES="${JAX_NUM_CPU_DEVICES:-4}"
# jax<0.5 ignores JAX_NUM_CPU_DEVICES; the XLA flag is what actually
# multiplies the host platform (same fallback as tests/conftest.py)
case "${XLA_FLAGS:-}" in *xla_force_host_platform_device_count*) ;; *)
    export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=$JAX_NUM_CPU_DEVICES"
;; esac
WORKDIR="$(mktemp -d)"
trap 'rm -rf "$WORKDIR"' EXIT
SPOOL="$WORKDIR/spool"
TEL="$WORKDIR/tel"

# the load harness judges criteria 1-4 itself (verify_fleet); headroom
# of one replica above the floor lets the ramp trigger exactly the
# scale-up criterion 3 needs. --p99-factor is opened wide on purpose:
# the latency bound is serving_check.sh's gate — this leg gates the
# fleet accounting, and a tight bound here would just double-fail CPU
# runner noise.
python scripts/load_check.py \
    --replicas 3 --max-replicas 4 \
    --warm-s 3 --ramp-s 6 --post-s 2 \
    --search-budget 1 --p99-factor 40 \
    --fleet-spool "$SPOOL" --expect-scale-up \
    --telemetry-dir "$TEL" --request-sample-rate 1.0 \
    --json "$WORKDIR/load.json" >/dev/null
echo "fleet_check: load leg OK (criteria judged in-harness)"

# the fleet CLI must render the SAME spools: a table naming every
# process, and a Prometheus page whose rollup + meta-series parse
python -m flexflow_tpu.obs fleet "$SPOOL" --prom "$WORKDIR/fleet.prom" \
    > "$WORKDIR/fleet.table"
grep -q "replicaset" "$WORKDIR/fleet.table" \
    || { echo "fleet_check: controller spool missing from table"; exit 1; }
python - "$WORKDIR/fleet.prom" "$WORKDIR/load.json" <<'EOF'
import json
import sys

from flexflow_tpu.obs.metrics import parse_prometheus_labeled

page = open(sys.argv[1]).read()
series = parse_prometheus_labeled(page)
names = {name for name, _ in series}
for want in ("ff_fleet_heartbeat_age_seconds", "ff_fleet_processes",
             "ff_fleet_spools_corrupt", "ff_serving_requests_total"):
    assert want in names, f"fleet page missing {want}: {sorted(names)}"
assert series[("ff_fleet_spools_corrupt", ())] == 0.0
summary = json.load(open(sys.argv[2]))
expected = summary["fleet"]["expected_requests"]
total = series[("ff_serving_requests_total", ())]
assert total == expected, (
    f"CLI rollup {total} != in-harness expectation {expected}")
by_state = {lab: v for (name, lab), v in series.items()
            if name == "ff_fleet_processes"}
assert sum(by_state.values()) == summary["fleet"]["spooled_processes"]
print(f"fleet_check: CLI page OK ({len(series)} series, "
      f"{total:.0f} requests conserved)")
EOF

# the forensics CLI must accept every bundle the run dumped
python -m flexflow_tpu.obs forensics "$TEL" --validate >/dev/null
python -m flexflow_tpu.obs forensics "$TEL" --show latest >/dev/null
echo "fleet_check: forensics CLI OK"
echo "fleet_check: OK"
