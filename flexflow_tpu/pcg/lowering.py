"""Layer graph → PCG lowering.

TPU-native equivalent of FFModel::create_operators_from_layers
(reference: src/runtime/model.cc:2785 + create_operator_from_layer
model.cc:2605): each deferred Layer becomes a PCGOp with ParallelTensor
inputs/outputs/weights (all degree 1 at this point; parallelization passes or
the strategy search assign degrees afterwards).
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from ..core.tensor import Layer, Tensor
from ..ff_types import OperatorType
from ..ops.registry import get_op_def
from .graph import Graph
from .op import PCGOp
from .parallel_tensor import ParallelDim, ParallelTensor


def tensor_to_parallel(t: Tensor) -> ParallelTensor:
    dims = [ParallelDim(size=s, degree=1) for s in t.dims]
    return ParallelTensor(dims=dims, data_type=t.data_type)


def layers_to_pcg(layers: List[Layer]) -> Tuple[Graph, Dict[int, int]]:
    """Lower layers to a Graph.

    Returns (graph, tensor_map) where tensor_map maps Layer-IR tensor guid →
    ParallelTensor guid, so the model can find PCG tensors for its
    user-visible tensors (inputs, logits, weights).
    """
    graph = Graph()
    pt_by_guid: Dict[int, ParallelTensor] = {}
    tensor_map: Dict[int, int] = {}

    def get_pt(t: Tensor) -> ParallelTensor:
        if t.guid not in tensor_map:
            pt = tensor_to_parallel(t)
            tensor_map[t.guid] = pt.guid
            pt_by_guid[pt.guid] = pt
        return pt_by_guid[tensor_map[t.guid]]

    for layer in layers:
        in_pts = [get_pt(t) for t in layer.inputs]
        op = PCGOp(
            layer.op_type,
            layer.params,
            in_pts,
            name=layer.name,
            layer_guid=layer.guid,
        )
        opdef = get_op_def(layer.op_type)
        in_shapes = [pt.material_shape() for pt in in_pts]
        in_dtypes = [pt.data_type for pt in in_pts]
        out_shapes, out_dtypes = opdef.infer(layer.params, in_shapes, in_dtypes)
        assert len(out_shapes) == len(layer.outputs), (
            f"{layer.name}: infer produced {len(out_shapes)} outputs, "
            f"layer has {len(layer.outputs)}"
        )
        for t, shape, dt in zip(layer.outputs, out_shapes, out_dtypes):
            pt = ParallelTensor(
                dims=[ParallelDim(size=s, degree=1) for s in shape],
                data_type=dt,
                owner_op=op,
            )
            op.outputs.append(pt)
            tensor_map[t.guid] = pt.guid
            pt_by_guid[pt.guid] = pt
        op.weight_tags = []
        for spec in opdef.weights(layer.params, in_shapes, in_dtypes):
            wpt = ParallelTensor(
                dims=[ParallelDim(size=s, degree=1) for s in spec.shape],
                data_type=spec.dtype,
                owner_op=op,
                create_gradients=True,
            )
            op.weights.append(wpt)
            op.weight_names.append(spec.name)
            op.weight_tags.append(spec.parallel_dim_tags)
            init = layer.initializers.get(spec.name, spec.initializer)
            op.initializers[spec.name] = init
        # map layer weight tensors (if the frontend exposed them)
        for wt, wpt in zip(layer.weights, op.weights):
            tensor_map[wt.guid] = wpt.guid
        graph.add_op(op)
    return graph, tensor_map
