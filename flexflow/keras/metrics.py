"""Shim: reference python/flexflow/keras/metrics.py surface."""
from flexflow_tpu.frontends.keras.metrics import *  # noqa: F401,F403
