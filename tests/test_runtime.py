"""Runtime services tests: checkpoint/resume, recompile triggers, profiler."""
import numpy as np
import pytest

from flexflow_tpu import (
    ActiMode,
    DataType,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.runtime import (
    RecompileState,
    recompile_on_condition,
    restore_checkpoint,
    save_checkpoint,
)


def small_model(hidden=16):
    cfg = FFConfig()
    cfg.batch_size = 8
    m = FFModel(cfg)
    x = m.create_tensor((8, 4), DataType.DT_FLOAT)
    t = m.dense(x, hidden, ActiMode.AC_MODE_RELU)
    t = m.dense(t, 3)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.1, momentum=0.9),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              [MetricsType.METRICS_ACCURACY])
    return m


def train_steps(m, n=3, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(8 * n, 4).astype(np.float32)
    y = rng.randint(0, 3, (8 * n, 1)).astype(np.int32)
    m.fit(x, y, batch_size=8, epochs=1, verbose=False)


def test_checkpoint_roundtrip(tmp_path):
    m = small_model()
    train_steps(m)
    w_before = {
        name: {k: np.asarray(v) for k, v in wd.items()}
        for name, wd in m.state.params.items()
    }
    path = str(tmp_path / "ckpt")
    save_checkpoint(m, path, step=42)

    m2 = small_model()
    step = restore_checkpoint(m2, path)
    assert step == 42
    for name, wd in w_before.items():
        for k, v in wd.items():
            np.testing.assert_allclose(
                np.asarray(m2.state.params[name][k]), v, atol=1e-6
            )
    # momentum buffers restored too
    assert m2.state.opt_state["v"] is not None


def test_checkpoint_topology_mismatch(tmp_path):
    m = small_model()
    path = str(tmp_path / "ckpt")
    save_checkpoint(m, path)
    m2 = small_model(hidden=16)
    restore_checkpoint(m2, path)  # same topology ok
    cfg = FFConfig()
    cfg.batch_size = 8
    m3 = FFModel(cfg)
    x = m3.create_tensor((8, 4), DataType.DT_FLOAT)
    t = m3.dense(x, 16, ActiMode.AC_MODE_RELU)
    t = m3.dense(t, 16, ActiMode.AC_MODE_RELU)  # extra layer
    t = m3.softmax(m3.dense(t, 3))
    m3.compile(SGDOptimizer(), LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])
    with pytest.raises(ValueError, match="topology mismatch"):
        restore_checkpoint(m3, path)


def test_recompile_trigger_preserves_weights():
    m = small_model()
    train_steps(m)
    kernel_before = np.asarray(m.state.params[m.layers[0].name]["kernel"])
    fired = RecompileState(trigger_func=lambda model: True)
    assert recompile_on_condition(m, fired)
    assert fired.recompilations == 1
    np.testing.assert_allclose(
        np.asarray(m.state.params[m.layers[0].name]["kernel"]),
        kernel_before, atol=1e-6,
    )
    train_steps(m)  # still trains after recompile


def test_profiler_per_op_times():
    from flexflow_tpu.runtime.profiler import profile_ops

    m = small_model()
    rng = np.random.RandomState(0)
    times = profile_ops(m, [rng.randn(8, 4).astype(np.float32)])
    assert len(times) == len(m.graph.ops)
    assert all(t >= 0 for t in times.values())


def test_scan_driver_matches_stepwise():
    """build_train_scan (multi-step lax.scan dispatch — the Legion
    trace-replay analog, flexflow_cffi.py:2093-2102) must be numerically
    identical to the same batches driven one step per dispatch, and
    fit(iterations_per_dispatch>1) must take that path end to end."""
    import jax

    rng = np.random.RandomState(0)
    x = rng.randn(32, 4).astype(np.float32)
    y = rng.randint(0, 3, (32, 1)).astype(np.int32)

    m1 = small_model()
    m1.fit(x, y, batch_size=8, epochs=1, verbose=False)

    m2 = small_model()
    m2.config.iterations_per_dispatch = 2  # 4 batches -> 2 scan dispatches
    m2.fit(x, y, batch_size=8, epochs=1, verbose=False)

    l1 = jax.tree_util.tree_leaves(m1.state.params)
    l2 = jax.tree_util.tree_leaves(m2.state.params)
    assert len(l1) == len(l2)
    for a, b in zip(l1, l2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    # metric folding across stacked partials matches the stepwise fold
    assert m1.perf_metrics.get_accuracy() == m2.perf_metrics.get_accuracy()

    # tail chunk shorter than spd (3 batches, spd=2) still trains
    m3 = small_model()
    m3.config.iterations_per_dispatch = 2
    m3.fit(x[:24], y[:24], batch_size=8, epochs=1, verbose=False)


def test_scan_driver_matches_stepwise_with_dropout():
    """Stochastic ops too: fit passes one rng key per step into the scan,
    split in the same order as the stepwise path, so dropout masks (and
    therefore trained weights) are identical whatever the dispatch
    grouping."""
    import jax

    from flexflow_tpu.ff_types import ActiMode

    def dropout_model():
        cfg = FFConfig()
        cfg.batch_size = 8
        m = FFModel(cfg)
        x = m.create_tensor((8, 4), DataType.DT_FLOAT)
        t = m.dense(x, 16, ActiMode.AC_MODE_RELU)
        t = m.dropout(t, rate=0.5, seed=0)
        t = m.dense(t, 3)
        t = m.softmax(t)
        m.compile(SGDOptimizer(lr=0.1),
                  LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  [MetricsType.METRICS_ACCURACY])
        return m

    rng = np.random.RandomState(1)
    x = rng.randn(32, 4).astype(np.float32)
    y = rng.randint(0, 3, (32, 1)).astype(np.int32)

    m1 = dropout_model()
    m1.fit(x, y, batch_size=8, epochs=1, verbose=False)
    m2 = dropout_model()
    m2.config.iterations_per_dispatch = 4
    m2.fit(x, y, batch_size=8, epochs=1, verbose=False)
    for a, b in zip(jax.tree_util.tree_leaves(m1.state.params),
                    jax.tree_util.tree_leaves(m2.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_forward_seq_length_truncates_batch_matmul():
    """FFIterationConfig.seq_length parity (reference: config.h:162,
    forward(seq_length) model.h:771 truncates BatchMatmul's seq dims):
    forward(seq_length=N) must equal running on inputs truncated to N."""
    import jax.numpy as jnp

    from flexflow_tpu import (DataType, FFConfig, FFModel, LossType,
                              MetricsType, SGDOptimizer)
    from flexflow_tpu.ops.batch_matmul import BatchMatmulParams
    from flexflow_tpu.ff_types import OperatorType

    cfg = FFConfig()
    cfg.batch_size = 2
    m = FFModel(cfg)
    a = m.create_tensor((2, 8, 4), DataType.DT_FLOAT)
    b = m.create_tensor((2, 4, 8), DataType.DT_FLOAT)
    out = m.batch_matmul(a, b, a_seq_length_dim=1, b_seq_length_dim=2)
    m.compile(SGDOptimizer(lr=0.01),
              LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
              [MetricsType.METRICS_MEAN_SQUARED_ERROR])
    rng = np.random.RandomState(0)
    av = rng.randn(2, 8, 4).astype(np.float32)
    bv = rng.randn(2, 4, 8).astype(np.float32)
    a.set_tensor(m, av)
    b.set_tensor(m, bv)
    full = np.asarray(m.forward())
    trunc = np.asarray(m.forward(seq_length=4))
    want = np.einsum("bik,bkj->bij", av[:, :4], bv[:, :, :4])
    np.testing.assert_allclose(trunc, want, rtol=1e-5, atol=1e-5)
    assert trunc.shape != full.shape


def test_backward_seq_length_truncates_labels():
    """backward(seq_length=N)/compute_metrics must truncate labels to the
    logits' sequence length instead of shape-erroring."""
    from flexflow_tpu import (DataType, FFConfig, FFModel, LossType,
                              MetricsType, SGDOptimizer)

    cfg = FFConfig()
    cfg.batch_size = 2
    m = FFModel(cfg)
    a = m.create_tensor((2, 8, 4), DataType.DT_FLOAT)
    b = m.create_tensor((2, 4, 8), DataType.DT_FLOAT)
    m.batch_matmul(a, b, a_seq_length_dim=1, b_seq_length_dim=2)
    m.compile(SGDOptimizer(lr=0.01),
              LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
              [MetricsType.METRICS_MEAN_SQUARED_ERROR])
    rng = np.random.RandomState(0)
    a.set_tensor(m, rng.randn(2, 8, 4).astype(np.float32))
    b.set_tensor(m, rng.randn(2, 4, 8).astype(np.float32))
    m.label_tensor.set_tensor(m, rng.randn(2, 8, 8).astype(np.float32))
    m.forward(seq_length=4)
    m.compute_metrics()        # truncated logits vs full labels
    m.zero_gradients()
    m.backward(seq_length=4)   # grad step truncates labels too
    m.update()
