"""Slice-granular fault domains.

On multi-slice TPU machines the slice — not the host — is the unit that
fails: a preemption notice or a DCN partition takes out ALL chips of one
slice at once, while the other slices keep running. The machine model
(search/machine_model.py, search/network.py) already prices that
hierarchy for the *search*; this module gives the *runtime* the same
shape so failures can be classified by the domain they hit:

  * **host loss within a slice** — some but not all of a slice's hosts
    went stale. The slice is degraded but its peers are fine; the right
    move is to restart the lost host in place (orchestrator concern) or
    shrink within the slice.
  * **whole-slice loss** — every host of a slice is stale (or a
    preemption notice named the slice). Model state sharded across
    slices would now be unrecoverable from the survivors; pure
    data-parallel replicas just drop. fit()'s failover shrinks onto the
    surviving slices and re-searches (runtime/elastic.py).

`FaultDomainMap` is the shared vocabulary: slice index -> device ids
(plus an optional host -> slice mapping for heartbeat transports that
see hosts, not devices). It is derived from the searched machine model
(`from_machine`), a machine-config file (`from_config`), or given
explicitly (`from_devices`); consumers are `HealthMonitor` /
`FileHeartbeat` staleness classification, `topology_fingerprint` /
`validate_machine_views` (runtime/elastic.py), the checkpoint sidecar,
and the survivability lint (search/survivability.py, FFA6xx).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class FailureClassification:
    """What a set of stale hosts means in fault-domain terms.

    kind is one of:
      * ``"ok"``         — nothing stale.
      * ``"host_loss"``  — stale hosts, but every affected slice still
                           has at least one live host (restart in place).
      * ``"slice_loss"`` — at least one slice lost ALL of its hosts
                           (shrink onto the survivors).
    """

    kind: str
    stale_hosts: Tuple[str, ...] = ()
    lost_slices: Tuple[int, ...] = ()
    degraded_slices: Tuple[int, ...] = ()
    surviving_devices: int = 0

    def describe(self) -> str:
        if self.kind == "ok":
            return "all fault domains healthy"
        if self.kind == "slice_loss":
            return (
                f"whole-slice loss: slice(s) {list(self.lost_slices)} lost all "
                f"hosts ({list(self.stale_hosts)}); {self.surviving_devices} "
                "device(s) survive"
            )
        return (
            f"host loss within slice(s) {list(self.degraded_slices)}: "
            f"stale host(s) {list(self.stale_hosts)}; slice peers still alive"
        )


@dataclasses.dataclass(frozen=True)
class FaultDomainMap:
    """Slice index -> device ids (and optionally host id -> slice index).

    Device ids are the flat global ids the machine model and MachineViews
    use (0..num_devices-1). Slices are disjoint; together they cover the
    machine. Immutable — derive a new map with `with_hosts` to attach a
    host mapping."""

    slices: Tuple[Tuple[int, ...], ...]
    hosts: Optional[Mapping[str, int]] = None  # host id -> slice index

    # -- constructors ----------------------------------------------------
    @classmethod
    def from_machine(cls, machine) -> "FaultDomainMap":
        """Derive from a MachineModel: each node (slice, in multi-slice
        configs) is one fault domain."""
        per = machine.workers_per_node
        slices = tuple(
            tuple(range(n * per, (n + 1) * per))
            for n in range(machine.num_nodes)
        )
        return cls(slices=slices)

    @classmethod
    def from_config(cls, path: str) -> "FaultDomainMap":
        """Derive from a machine-config file (e.g.
        ``machine_config_multislice``) via parse_machine_config."""
        from ..search.machine_model import parse_machine_config

        return cls.from_machine(parse_machine_config(path))

    @classmethod
    def from_devices(cls, num_devices: int,
                     devices_per_slice: int) -> "FaultDomainMap":
        """Partition ``num_devices`` flat ids into equal contiguous
        slices of ``devices_per_slice``."""
        if devices_per_slice <= 0 or num_devices % devices_per_slice:
            raise ValueError(
                f"{num_devices} devices do not divide into slices of "
                f"{devices_per_slice}"
            )
        return cls(slices=tuple(
            tuple(range(s, s + devices_per_slice))
            for s in range(0, num_devices, devices_per_slice)
        ))

    def with_hosts(self, hosts: Mapping[str, int]) -> "FaultDomainMap":
        """Attach a host-id -> slice-index mapping (for heartbeat
        transports like FileHeartbeat that identify hosts, not devices)."""
        return dataclasses.replace(self, hosts=dict(hosts))

    # -- queries ---------------------------------------------------------
    @property
    def num_slices(self) -> int:
        return len(self.slices)

    @property
    def num_devices(self) -> int:
        return sum(len(s) for s in self.slices)

    def devices_in_slice(self, slice_idx: int) -> Tuple[int, ...]:
        return self.slices[slice_idx]

    def slice_of(self, device_id: int) -> Optional[int]:
        """Slice index holding ``device_id`` (None when outside the map —
        e.g. a stale view addressing a device that no longer exists)."""
        for i, devs in enumerate(self.slices):
            if device_id in devs:
                return i
        return None

    def slice_of_host(self, host_id: str) -> Optional[int]:
        if self.hosts is None:
            return None
        return self.hosts.get(host_id)

    def host_labels(self, host_id: str) -> Dict[str, str]:
        """Metric labels pinning ``host_id`` to its fault domain — what
        the fleet aggregator (obs/fleet.py) stamps on per-process gauges
        so a fleet page groups by slice. Empty for unknown hosts (no
        misleading label beats a wrong one)."""
        s = self.slice_of_host(host_id)
        return {} if s is None else {"slice": str(s)}

    def surviving_devices(self, lost_slices: Iterable[int]) -> Tuple[int, ...]:
        lost = set(lost_slices)
        out: List[int] = []
        for i, devs in enumerate(self.slices):
            if i not in lost:
                out.extend(devs)
        return tuple(out)

    # -- failure classification ------------------------------------------
    def classify_stale(
        self, stale_hosts: Sequence[str]
    ) -> FailureClassification:
        """Aggregate per-host staleness (HealthMonitor heartbeat output)
        into fault-domain terms. Hosts map to slices via `hosts`; a host
        the map doesn't know counts as a degraded unknown domain
        (conservative: host_loss, never silently ignored)."""
        if not stale_hosts:
            return FailureClassification(
                kind="ok", surviving_devices=self.num_devices)
        stale_by_slice: Dict[int, set] = {}
        unknown: List[str] = []
        for h in stale_hosts:
            s = self.slice_of_host(h)
            if s is None:
                unknown.append(h)
            else:
                stale_by_slice.setdefault(s, set()).add(h)
        hosts_by_slice: Dict[int, set] = {}
        for h, s in (self.hosts or {}).items():
            hosts_by_slice.setdefault(s, set()).add(h)
        lost = tuple(sorted(
            s for s, stale in stale_by_slice.items()
            if hosts_by_slice.get(s) and stale >= hosts_by_slice[s]
        ))
        degraded = tuple(sorted(
            s for s in stale_by_slice if s not in lost
        ))
        kind = "slice_loss" if lost else "host_loss"
        return FailureClassification(
            kind=kind,
            stale_hosts=tuple(stale_hosts),
            lost_slices=lost,
            degraded_slices=degraded,
            surviving_devices=len(self.surviving_devices(lost)),
        )

    # -- (de)serialization (checkpoint sidecar) --------------------------
    def to_json(self) -> dict:
        out: dict = {"slices": [list(s) for s in self.slices]}
        if self.hosts is not None:
            out["hosts"] = dict(self.hosts)
        return out

    @classmethod
    def from_json(cls, data: Optional[dict]) -> Optional["FaultDomainMap"]:
        if not data or "slices" not in data:
            return None
        return cls(
            slices=tuple(tuple(int(d) for d in s) for s in data["slices"]),
            hosts={str(k): int(v) for k, v in data["hosts"].items()}
            if data.get("hosts") else None,
        )
