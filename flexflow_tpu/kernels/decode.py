"""Paged flash-decode attention kernel (the serving hot path).

One query token per slot attends over a PAGED KV pool: K/V live in
fixed-size physical pages, each slot's logical sequence is a list of
page indices (the vLLM PagedAttention layout), and the kernel walks a
slot's pages with an online softmax — no (seq x seq) score tensor, no
dense gather of the pool, and dead pages past the slot's length are
skipped, so a freshly admitted request costs one page of work while a
long-running neighbor streams its whole cache.

Grid: ``(slots, heads, pages_per_slot)`` with the page axis innermost.
The page table and per-slot lengths ride as SCALAR-PREFETCH operands
(pltpu.PrefetchScalarGridSpec): the K/V BlockSpec index_map reads
``page_table[slot, page]`` to DMA exactly the physical page the slot
needs next — the gather happens in the block pipeline, not as a
materialized jnp.take. Running (max, sum, acc) live in VMEM scratch
across the page axis; the output row is written once, on the last page.

Layouts:
  q          (slots, heads, head_dim)           — one token per slot
  k/v pages  (heads, num_pages, page_size, d)   — head-major pool
  page_table (slots, pages_per_slot) int32      — physical page ids;
             entries past a slot's live pages MUST still be in range
             (0 is fine) — the kernel masks them, the DMA does not.
  lengths    (slots,) int32                     — tokens live per slot
             (positions t attend to pos <= t, i.e. length = t + 1)

``paged_view_of_cache`` adapts the batcher's dense per-slot caches
(slots, max_len, heads, d) into this layout as a pure reshape/transpose
(every slot's pages are contiguous in its own cache strip), so the
serving path gets the kernel without a separate pool allocator; a real
PagePool-backed pool (runtime/kvcache.py page tables) drops in with the
same signature.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from .attention import HAS_PALLAS, NEG_INF

if HAS_PALLAS:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu


def _paged_decode_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                         m_ref, l_ref, acc_ref, *, page_size: int,
                         scale: float):
    """One program = one (slot, head, page) cell. Scratch (m, l, acc)
    persists across the innermost page axis; pl.when gates init on the
    first page, the online-softmax update on live pages only, and the
    normalized write-out on the last page."""
    s_id = pl.program_id(0)
    page = pl.program_id(2)
    n_pages = pl.num_programs(2)

    @pl.when(page == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    length = len_ref[s_id]
    start = page * page_size

    @pl.when(start < length)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)          # (1, d)
        k = k_ref[0, 0].astype(jnp.float32)       # (page_size, d)
        v = v_ref[0, 0].astype(jnp.float32)       # (page_size, dv)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                 # (1, page_size)
        pos = start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < length, s, NEG_INF)
        m_prev = m_ref[...]                       # (1, 1)
        l_prev = l_ref[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        m_ref[...] = m_new
        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32,
        )

    @pl.when(page == n_pages - 1)
    def _finish():
        o_ref[0] = (acc_ref[...] /
                    jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


def paged_flash_decode(q, k_pages, v_pages, page_table, lengths, *,
                       interpret: bool = False):
    """Single-token attention over the paged KV pool.

    q (slots, heads, d); k_pages/v_pages (heads, num_pages, page_size,
    d/dv); page_table (slots, pages_per_slot) int32; lengths (slots,)
    int32. Returns (slots, heads, dv). Requires Pallas (interpret=True
    runs the same kernel on CPU)."""
    assert HAS_PALLAS, "paged_flash_decode needs Pallas (jax.experimental)"
    b, h, d = q.shape
    page_size = k_pages.shape[2]
    dv = v_pages.shape[-1]
    n_pages = page_table.shape[1]
    scale = 1.0 / math.sqrt(d)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, n_pages),
        in_specs=[
            pl.BlockSpec((1, 1, d), lambda s, hh, i, pt, ln: (s, hh, 0)),
            pl.BlockSpec((1, 1, page_size, d),
                         lambda s, hh, i, pt, ln: (hh, pt[s, i], 0, 0)),
            pl.BlockSpec((1, 1, page_size, dv),
                         lambda s, hh, i, pt, ln: (hh, pt[s, i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, dv),
                               lambda s, hh, i, pt, ln: (s, hh, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, dv), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_decode_kernel, page_size=page_size,
                          scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, dv), q.dtype),
        interpret=interpret,
    )(jnp.asarray(page_table, jnp.int32), jnp.asarray(lengths, jnp.int32),
      q, k_pages, v_pages)


def paged_decode_reference(q, k_pages, v_pages, page_table, lengths):
    """Dense parity oracle: gather every slot's pages, mask positions
    past its length, one softmax. O(slots * pages * page_size) memory —
    test-sized only."""
    b, h, d = q.shape
    page_size = k_pages.shape[2]
    n_pages = page_table.shape[1]
    # (slots, heads, n_pages*page_size, d)
    k = jnp.take(k_pages, page_table, axis=1).transpose(1, 0, 2, 3, 4)
    v = jnp.take(v_pages, page_table, axis=1).transpose(1, 0, 2, 3, 4)
    k = k.reshape(b, h, n_pages * page_size, d)
    v = v.reshape(b, h, n_pages * page_size, v_pages.shape[-1])
    s = jnp.einsum("bhd,bhtd->bht", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / math.sqrt(d)
    pos = jnp.arange(n_pages * page_size)[None, None, :]
    s = jnp.where(pos < lengths[:, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bht,bhtd->bhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def paged_view_of_cache(k_cache, v_cache, page_size: int):
    """View the batcher's dense per-slot caches (slots, max_len, heads,
    d) as a paged pool: slot b's logical page i is physical page
    ``b * pages_per_slot + i`` — a reshape/transpose, no copy semantics
    beyond XLA's layout change. Requires page_size | max_len."""
    b, max_len, h, d = k_cache.shape
    if page_size <= 0 or max_len % page_size:
        raise ValueError(
            f"page_size {page_size} must divide the cache length {max_len}")
    pp = max_len // page_size

    def to_pool(c):
        # (b, max_len, h, d) -> (h, b*pp, page_size, d)
        return c.reshape(b, pp, page_size, h, c.shape[-1]) \
                .transpose(3, 0, 1, 2, 4) \
                .reshape(c.shape[2], b * pp, page_size, c.shape[-1])

    table = (jnp.arange(b)[:, None] * pp + jnp.arange(pp)[None, :]) \
        .astype(jnp.int32)
    return to_pool(k_cache), to_pool(v_cache), table


def decode_page_size(max_len: int, preferred: int = 16) -> int:
    """Largest page size <= preferred dividing max_len (>= 1 always)."""
    p = max(1, min(int(preferred), int(max_len)))
    while max_len % p:
        p -= 1
    return p
