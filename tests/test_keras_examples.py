"""Keras example-suite smoke tests (reference: tests/multi_gpu_tests.sh runs
the examples/python/keras scripts; pass criterion is "trains without
crashing" — SURVEY §4). A representative subset runs here with tiny sizes;
the full tree is runnable by hand with reference-scale defaults.

All scripts share ONE subprocess (tests/_example_runner.py) to amortize the
per-interpreter jax import on this host."""
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
EXAMPLES = os.path.join(ROOT, "examples", "python", "keras")

SCRIPTS = [
    "func_mnist_mlp.py",          # functional API
    "func_mnist_mlp_concat2.py",  # multi-input + nested concat
    "seq_mnist_cnn_nested.py",    # Sequential-of-models nesting
    "func_cifar10_cnn_net2net.py",  # get_layer + weight transfer
    "reduce_sum.py",              # K.sum backend op
    "gather.py",                  # K.internal.gather
    "callback.py",                # LearningRateScheduler
]


@pytest.fixture(scope="module")
def keras_results(tmp_path_factory):
    base = tmp_path_factory.mktemp("keras_examples")
    cases = [{
        "name": script,
        "path": os.path.join(EXAMPLES, script),
        "argv": ["--epochs", "1", "--num-samples", "96",
                 "--batch-size", "32"],
        "cwd": EXAMPLES,
        "extra_sys_path": [ROOT],
    } for script in SCRIPTS]
    spec = base / "spec.json"
    results = base / "results.json"
    spec.write_text(json.dumps({"cases": cases}))
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tests", "_example_runner.py"),
         str(spec), str(results)],
        capture_output=True, text=True, timeout=1800,
        env=dict(os.environ, PYTHONPATH=ROOT),
    )
    assert results.exists(), (
        f"example runner died: rc={proc.returncode}\n{proc.stdout}\n"
        f"{proc.stderr}"
    )
    return json.loads(results.read_text())


@pytest.mark.parametrize("script", SCRIPTS)
def test_keras_example(script, keras_results):
    res = keras_results[script]
    assert res["ok"], f"{script} failed:\n{res['output']}"
