"""Benchmark driver: trains the reference's headline Transformer benchmark
config (examples/cpp/Transformer defaults: hidden 1024, 16 heads, 12 layers,
seq 512; batch 8 per scripts/osdi22ae/bert.sh) and prints ONE JSON line with
per-chip training throughput.

Runs on whatever jax.devices() provides (one real TPU chip under the driver).
Mixed precision (bf16 compute, f32 master weights) is on — the TPU-native
equivalent of the reference's f32 cuDNN path, since bf16 is the MXU's native
input type.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np


def wait_for_backend(max_wait_s: float = 600.0) -> None:
    """The remote-TPU ("axon") tunnel can wedge — a stuck lease makes jax
    backend init block forever IN-PROCESS, where no timeout can save us.
    Probe it in subprocesses (killable) and retry until healthy; if the
    tunnel never recovers, exit loudly instead of hanging the driver."""
    platforms = os.environ.get("JAX_PLATFORMS", "axon")
    if "axon" not in platforms.split(","):
        return  # explicit cpu/tpu config: nothing to probe
    deadline = time.monotonic() + max_wait_s
    while True:
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=90, capture_output=True, text=True,
            )
            if r.returncode == 0:
                return
            # fast non-zero exit = config/import error, not a wedged
            # tunnel: surface the real traceback and stop immediately
            print(r.stderr, file=sys.stderr)
            print("bench: jax backend init failed (see traceback above)",
                  file=sys.stderr)
            sys.exit(1)
        except subprocess.TimeoutExpired:
            pass
        if time.monotonic() > deadline:
            print("bench: TPU backend unreachable (axon tunnel wedged); "
                  "no measurement possible", file=sys.stderr)
            sys.exit(1)
        time.sleep(20)


def read_baseline(metric: str, backend: str = None, smoke: bool = False):
    """(value, source) this round is compared against (the vs_baseline
    field): a published number in BASELINE.json if the driver recorded
    one, else the first measured round (BENCH_r01.json) — the north-star
    file documents configurations, not numbers, so round 1 is the
    de-facto baseline of this build. The source rides along in the JSON
    line so a null/odd vs_baseline is diagnosable from the artifact
    alone.

    Baselines are hardware-tier scoped: bare published.<metric> numbers
    belong to the tier named by published.tier (the driver's axon/TPU
    pool). A round measured on another backend (a CPU-only session) only
    compares against an explicitly scoped published.<metric>@<backend>
    entry — a CPU round vs a TPU baseline is not a regression, it is a
    different machine. FF_BENCH_SMOKE runs are scoped one step further
    (published.<metric>@<backend>+smoke): the smoke shapes amortize
    warmup differently, so a smoke value vs a full-run baseline would
    gate fixed overhead, not throughput."""
    here = os.path.dirname(os.path.abspath(__file__))
    tier = "axon"
    try:
        with open(os.path.join(here, "BASELINE.json")) as f:
            published = json.load(f).get("published", {}) or {}
        tier = published.get("tier") or tier
        if smoke:
            key = f"{metric}@{backend or tier}+smoke"
            v = published.get(key)
            if isinstance(v, (int, float)) and v > 0:
                return float(v), f"BASELINE.json:published.{key}"
            return None, None
        if backend:
            v = published.get(f"{metric}@{backend}")
            if isinstance(v, (int, float)) and v > 0:
                return float(v), f"BASELINE.json:published.{metric}@{backend}"
        if backend in (None, tier):
            v = published.get(metric)
            if isinstance(v, (int, float)) and v > 0:
                return float(v), f"BASELINE.json:published.{metric}"
    except (OSError, ValueError):
        pass
    if smoke or backend not in (None, tier):
        return None, None
    if metric == "transformer_train_throughput":
        # the round-1 artifact measured the transformer workload; the zoo
        # series (moe/longctx) have no baseline until the driver records
        # one, and comparing them against it would be meaningless
        try:
            with open(os.path.join(here, "BENCH_r01.json")) as f:
                v = json.load(f).get("parsed", {}).get("value")
            if isinstance(v, (int, float)) and v > 0:
                return float(v), "BENCH_r01.json"
        except (OSError, ValueError):
            pass
    return None, None


def phase_breakdown(model, x, y, key, *, repeats: int, fetch):
    """Per-phase seconds per step: fwd (forward only), bwd (grad step
    minus forward), opt+sync (full train step minus grad step). Measured
    through separately jitted programs over the same batch — the split
    is approximate (XLA fuses differently per program) but stable enough
    to see which phase a perf round moved."""
    import numpy as np

    ex = model.executor

    def timed(fn, *args):
        out = fn(*args)
        fetch(out)
        out = fn(*args)  # second warmup absorbs relayout recompiles
        fetch(out)
        t0 = time.perf_counter()
        for _ in range(repeats):
            out = fn(*args)
        fetch(out)
        return (time.perf_counter() - t0) / repeats

    fwd = ex.build_forward()
    grad = ex.build_grad_step()
    step = ex.build_train_step(donate=False)
    state = model.state
    fwd_s = timed(lambda: fwd(state.params, [x]))
    grad_s = timed(lambda: grad(state.params, [x], y))
    step_s = timed(lambda: step(state, [x], y, key))
    # the implicit data-parallel grad collectives are the sync phase; on
    # one chip they are zero and the remainder is the optimizer update.
    # Multi-chip: estimated statically (ring all-reduce wire bytes of
    # every replicated weight gradient over ICI) — the jitted step fuses
    # the collectives, so they can't be timed separately.
    sync_s = 0.0
    d = ex.mesh.shape.get("data", 1) if ex.mesh is not None else 1
    if d > 1:
        try:
            from flexflow_tpu.search.cost_model import op_weight_bytes

            machine = model._build_cost_model().machine
            wire = sum(
                2.0 * (d - 1) / d * op_weight_bytes(op)
                for op in model.graph.topo_order()
                if op.weights and not op.is_parallel_op
            )
            sync_s = wire / machine.ici_bandwidth
        except Exception:
            sync_s = 0.0
    return {
        "fwd": round(fwd_s, 6),
        "bwd": round(max(0.0, grad_s - fwd_s), 6),
        "opt": round(max(0.0, step_s - grad_s - sync_s), 6),
        "sync": round(sync_s, 6),
    }


def decode_bench():
    """FF_BENCH_WORKLOAD=decode: serving throughput, not training.

    Builds a CPU-sized decoder-only LM, searches BOTH strategies
    (compile() with the training objective, compile_decode() with the
    HBM-roofline decode objective) and drives the continuous-batching
    loop end to end — admission, prefill, batched single-token decode —
    counting generated tokens. The headline is tokens/s/chip; like the
    zoo series the absolute number is a trend line, so the regression
    gate treats it warn-only until the driver publishes a baseline."""
    import jax

    from flexflow_tpu import (
        ActiMode,
        AggrMode,
        DataType,
        FFConfig,
        FFModel,
        LossType,
        MetricsType,
        SGDOptimizer,
    )
    from flexflow_tpu.runtime.serving import (
        AdmissionQueue,
        ContinuousBatcher,
        GenerationRequest,
        ServingConfig,
    )

    smoke = bool(os.environ.get("FF_BENCH_SMOKE"))
    vocab, hidden, heads, layers, max_len = 64, 64, 4, 2, 32
    prompt_len = 4
    cfg = FFConfig()
    cfg.batch_size = 2
    cfg.search_budget = 1
    model = FFModel(cfg)
    ids = model.create_tensor((2, max_len), DataType.DT_INT32)
    t = model.embedding(ids, vocab, hidden, AggrMode.AGGR_MODE_NONE)
    for _ in range(layers):
        t = model.multihead_attention(t, t, t, hidden, heads, causal=True)
        t = model.dense(t, hidden, ActiMode.AC_MODE_RELU)
    t = model.softmax(model.dense(t, vocab))
    model.compile(SGDOptimizer(lr=0.01),
                  LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  [MetricsType.METRICS_ACCURACY])
    model.compile_decode()

    def run_round(n_req, new_tokens):
        q = AdmissionQueue(max_depth=max(16, n_req))
        b = ContinuousBatcher(
            model,
            ServingConfig(max_len=max_len, slots=4, page_size=8,
                          precompile=False, default_deadline_s=600.0),
            q,
        ).start()
        rng = np.random.RandomState(0)
        try:
            t0 = time.perf_counter()
            reqs = []
            for _ in range(n_req):
                prompt = rng.randint(0, vocab, prompt_len).astype(np.int32)
                r = GenerationRequest(prompt, new_tokens, deadline_s=600.0)
                q.offer(r)
                reqs.append(r)
            toks = sum(len(r.result(timeout=600.0)) - prompt_len
                       for r in reqs)
            return toks, time.perf_counter() - t0, b.decode_strategy_active
        finally:
            b.stop()

    n_req, new_tokens = (2, 4) if smoke else (16, 16)
    run_round(n_req, new_tokens)  # warmup: jit-compiles prefill + step
    toks, elapsed, active = run_round(n_req, new_tokens)

    n_chips = max(1, len(jax.devices()))
    tokens_per_sec_per_chip = toks / elapsed / n_chips
    metric = "decode_tokens_throughput"
    baseline, baseline_source = read_baseline(
        metric, jax.default_backend(), smoke)
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(tokens_per_sec_per_chip, 3),
                "unit": "tokens/s/chip",
                "vs_baseline": (
                    round(tokens_per_sec_per_chip / baseline, 3)
                    if baseline else None
                ),
                "baseline": baseline,
                "baseline_source": baseline_source,
                "phases_s_per_step": None,
                "decode_strategy_active": bool(active),
                "smoke": smoke,
                "n_chips": n_chips,
                "backend": jax.default_backend(),
                "jax_version": jax.__version__,
            }
        )
    )


def main():
    wait_for_backend()
    import jax

    from flexflow_tpu import (
        FFConfig,
        FFModel,
        LossType,
        MetricsType,
        SGDOptimizer,
    )
    from flexflow_tpu.models.transformer import build_transformer

    # FF_BENCH_WORKLOAD selects the zoo series (docs/models.md):
    #   transformer (default) — the reference's headline config
    #   moe                   — top-k gated expert FFN blocks (CPU-sized)
    #   longctx               — the encoder at long seq, small batch
    #   decode                — continuous-batching serving loop under the
    #                           decode-searched strategy (tokens/s/chip)
    # The zoo series sizes are CPU-scale smoke shapes: their value is the
    # per-workload trend line (and the regression gate treats series
    # without a published baseline as warn-only), not absolute numbers.
    workload = os.environ.get("FF_BENCH_WORKLOAD", "transformer")
    if workload == "decode":
        return decode_bench()
    cfg = FFConfig()
    cfg.allow_mixed_precision = True
    labels = None
    if workload == "moe":
        from flexflow_tpu.models import build_moe_transformer

        batch, seq = 8, 16
        cfg.batch_size = batch
        model = FFModel(cfg)
        build_moe_transformer(
            model, batch_size=batch, seq_length=seq, hidden_size=64,
            num_heads=4, num_layers=2, num_experts=4, top_k=2,
        )
        loss = LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY
        metrics = []
        labels = (batch, seq, 1)
    elif workload == "longctx":
        from flexflow_tpu.models import build_long_context_transformer

        batch, seq = 2, 512
        cfg.batch_size = batch
        model = FFModel(cfg)
        build_long_context_transformer(
            model, batch_size=batch, seq_length=seq, hidden_size=64,
            num_heads=4, num_layers=2,
        )
        loss = LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY
        metrics = []
        labels = (batch, seq, 1)
    elif workload == "transformer":
        batch = 8
        seq, hidden, heads, layers = 512, 1024, 16, 12
        cfg.batch_size = batch
        model = FFModel(cfg)
        build_transformer(
            model,
            batch_size=batch,
            seq_length=seq,
            hidden_size=hidden,
            num_heads=heads,
            num_layers=layers,
        )
        loss = LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE
        metrics = [MetricsType.METRICS_MEAN_SQUARED_ERROR]
    else:
        raise SystemExit(
            f"bench: FF_BENCH_WORKLOAD={workload!r} "
            "(want transformer|moe|longctx|decode)"
        )
    model.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=loss,
        metrics=metrics,
    )
    ex = model.executor
    in_pt = ex.input_pts[0]
    rng = np.random.RandomState(0)
    x = ex.shard_batch(in_pt, rng.randn(*in_pt.material_shape()).astype(np.float32))
    if labels is not None:
        y = jax.numpy.asarray(rng.randint(0, 10, labels).astype(np.int32))
    else:
        y = jax.numpy.asarray(
            rng.randn(*in_pt.material_shape()).astype(np.float32))
    key = jax.random.PRNGKey(0)

    state = model.state

    # Force a device->host round-trip that depends on EVERY param leaf.
    # Under the remote-TPU ("axon") platform block_until_ready returns
    # before remote execution finishes, and per-leaf fetches each pay a
    # full tunnel round-trip — so reduce all leaves to one scalar on
    # device and fetch that once.
    probe = jax.jit(
        lambda params: sum(
            leaf.reshape(-1)[0].astype(jax.numpy.float32)
            for leaf in jax.tree_util.tree_leaves(params)
        )
    )

    def sync(st):
        return float(np.asarray(probe(st.params)))

    # Measure through the multi-step scan driver (executor.build_train_scan
    # — the Legion trace-replay analog): per-step host dispatch is folded
    # into one XLA program, so the number reflects device throughput, not
    # the remote-tunnel round-trip latency. The reference's bench likewise
    # replays a Legion trace per iteration (flexflow_cffi.py:2093-2102).
    scan = ex.build_train_scan()
    smoke = bool(os.environ.get("FF_BENCH_SMOKE"))
    spd = 2 if smoke else 50  # steps per dispatch
    xs = [jax.numpy.broadcast_to(x, (spd,) + x.shape)]
    ys = jax.numpy.broadcast_to(y, (spd,) + y.shape)
    keys = jax.random.split(key, spd)

    # warmup: TWO calls, not one — the first compiles against the
    # init-time param layouts, and its donated output comes back in the
    # executable's preferred layouts, which triggers ONE more compile on
    # the next call; the second warmup absorbs it so the timed loop only
    # measures steady-state execution.
    for _ in range(2):
        state, partials = scan(state, xs, ys, keys)
    sync(state)

    chunks = 1 if smoke else 3
    iters = spd * chunks
    t0 = time.perf_counter()
    for _ in range(chunks):
        state, partials = scan(state, xs, ys, keys)
    sync(state)
    elapsed = time.perf_counter() - t0

    n_chips = max(1, len(jax.devices()))
    samples_per_sec_per_chip = batch * iters / elapsed / n_chips

    # per-phase breakdown (fwd/bwd/opt/sync) — measured AFTER the headline
    # number so its extra compiles can't perturb the timed loop; never
    # allowed to fail the bench
    try:
        def fetch(out):
            leaf = jax.tree_util.tree_leaves(out)[0]
            return float(np.asarray(leaf.reshape(-1)[0]))

        phases = phase_breakdown(
            model, x, y, jax.random.PRNGKey(1),
            repeats=2 if smoke else 10, fetch=fetch,
        )
    except Exception as e:  # pragma: no cover - diagnostics only
        print(f"bench: phase breakdown failed: {e}", file=sys.stderr)
        phases = None

    metric = f"{workload}_train_throughput"
    baseline, baseline_source = read_baseline(
        metric, jax.default_backend(), smoke)
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(samples_per_sec_per_chip, 3),
                "unit": "samples/s/chip",
                "vs_baseline": (
                    round(samples_per_sec_per_chip / baseline, 3)
                    if baseline else None
                ),
                "baseline": baseline,
                "baseline_source": baseline_source,
                "phases_s_per_step": phases,
                "smoke": smoke,
                "n_chips": n_chips,
                "backend": jax.default_backend(),
                "jax_version": jax.__version__,
            }
        )
    )


if __name__ == "__main__":
    main()
