"""Concat CIFAR-10 CNN through the experimental Keras frontend (reference:
examples/python/keras_exp/func_cifar10_cnn_concat.py — two conv towers over
one input, channel-axis Concatenate, shared conv trunk + dense head)."""
from types import SimpleNamespace

import numpy as np

from flexflow.core import FFConfig
from flexflow.keras_exp.models import Model
from flexflow.keras.datasets import cifar10

from _example_args import example_args
from _keras_onnx import GraphBuilder


def top_level_task(args):
    num_classes = 10
    (x_train, y_train), _ = cifar10.load_data(args.num_samples)
    x_train = x_train.transpose(0, 3, 1, 2).astype("float32") / 255  # NCHW
    y_train = y_train.astype("int32").reshape(-1, 1)

    g = GraphBuilder()
    src = g.input((3, 32, 32))
    towers = []
    for i in range(2):
        t = g.conv2d(src, 3, 32, 3, activation="relu", name=f"tower{i}_conv1")
        t = g.conv2d(t, 32, 32, 3, activation="relu", name=f"tower{i}_conv2")
        towers.append(t)
    t = g.concat(towers, axis=1)  # channels_first: concat on channel axis
    t = g.maxpool(t)
    t = g.conv2d(t, 64, 64, 3, activation="relu")
    t = g.conv2d(t, 64, 64, 3, activation="relu")
    t = g.maxpool(t)
    t = g.flatten(t)
    t = g.dense(t, 64 * 5 * 5, 512, activation="relu")
    t = g.dense(t, 512, num_classes)
    out = g.activation(t, "softmax")

    ffconfig = FFConfig()
    ffconfig.batch_size = args.batch_size
    model = Model(
        inputs={1: SimpleNamespace(shape=(None, 3, 32, 32), dtype="float32")},
        onnx_model=g.model(out, num_classes),
        ffconfig=ffconfig,
    )
    model.compile(optimizer="SGD", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy", "sparse_categorical_crossentropy"])
    model.fit(x_train, y_train, epochs=args.epochs)


if __name__ == "__main__":
    print("Functional API, cifar10 cnn concat")
    top_level_task(example_args(num_samples=512))
