#!/usr/bin/env bash
# Static-analysis sweep (ISSUE 4), mirroring verify_check.sh: the
# project AST linter, the substitution-rule lint over the shipped
# collection, and the analyzer test suite on CPU meshes of varying
# size — seeded-defect PCGs (wrong reduction axis, degree-vs-devices
# mismatch, cross-shard collective order, over-HBM views) must each
# produce their diagnostic code STATICALLY, and the clean searched zoo
# strategies must produce zero errors. Use before touching pcg/,
# search/, parallel strategies, or the analyzer itself:
#
#   scripts/analyze_check.sh                 # full sweep (8, 4-device)
#   FF_ANALYZE_DEVICES=8 scripts/analyze_check.sh -k collective
set -euo pipefail
cd "$(dirname "$0")/.."

echo "=== fflint: project AST rules over flexflow_tpu/ ==="
python tools/fflint.py flexflow_tpu/

echo "=== substitution-rule lint: shipped collection ==="
env JAX_PLATFORMS=cpu python -m flexflow_tpu.analysis

devices="${FF_ANALYZE_DEVICES:-8 4}"
for n in $devices; do
    echo "=== analysis sweep: ${n}-device CPU mesh ==="
    env JAX_PLATFORMS=cpu \
        JAX_NUM_CPU_DEVICES="$n" \
        XLA_FLAGS="--xla_force_host_platform_device_count=$n" \
        python -m pytest tests/test_analysis.py -v -p no:cacheprovider "$@"
done
