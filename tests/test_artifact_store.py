"""Artifact-store tests (runtime/artifact_store.py): the persistent
strategy/artifact cache that makes fleet cold-start a lookup instead of a
re-search, plus the CheckpointManager retention fixes that rode in the
same PR.

Covered: envelope round-trip, fingerprint-mismatch (stale) rejection,
truncated/bit-flipped entries raising the typed ArtifactCorruptionError
and compile() degrading to a fresh search, the concurrent two-writer
race, bounded LRU retention, tuner quarantine persistence across
"process restarts" (fresh tuner instances), FaultInjector chaos sites,
and — @pytest.mark.slow — the 8->4->8 elastic story performing ZERO
redundant searches (scripts/coldstart_check.sh re-runs it standalone).
"""
import json
import os
import threading
import time

import numpy as np
import pytest

from flexflow_tpu import (
    ActiMode,
    DataType,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
    obs,
)
from flexflow_tpu.obs import TelemetryConfig
from flexflow_tpu.runtime.artifact_store import (
    ArtifactCorruptionError,
    ArtifactStore,
    graph_fingerprint,
    make_key,
)
from flexflow_tpu.runtime.resilience import CheckpointManager, FaultInjector

import jax  # noqa: E402  (conftest configured the platform already)

NDEV = len(jax.devices())
needs8 = pytest.mark.skipif(
    NDEV != 8, reason="encodes the 8-device tier-1 mesh"
)


def small_model(store=None, budget=20, hidden=16, batch=32):
    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.search_budget = budget
    m = FFModel(cfg)
    x = m.create_tensor((batch, 4), DataType.DT_FLOAT)
    t = m.dense(x, hidden, ActiMode.AC_MODE_RELU)
    t = m.dense(t, 3)
    t = m.softmax(t)
    m.compile(SGDOptimizer(lr=0.1, momentum=0.9),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              [MetricsType.METRICS_ACCURACY], artifact_store=store)
    return m


def dataset(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    y = rng.randint(0, 3, (n, 1)).astype(np.int32)
    return x, y


def count_searches(monkeypatch):
    """Instrument _run_strategy_search; returns the call list."""
    calls = []
    orig = FFModel._run_strategy_search

    def spy(self, ndev):
        calls.append(ndev)
        return orig(self, ndev)

    monkeypatch.setattr(FFModel, "_run_strategy_search", spy)
    return calls


# ---------------------------------------------------------------------------
# envelope: round-trip, integrity, staleness
# ---------------------------------------------------------------------------
def test_round_trip(tmp_path):
    st = ArtifactStore(str(tmp_path))
    k = make_key(graph="g", topology="t", calibration="c", num_devices=8)
    assert st.get(k) is None  # miss
    st.put(k, {"kind": "strategy", "ops": [], "mesh_axes": {"data": 8}})
    got = st.get(k)
    assert got["mesh_axes"] == {"data": 8}
    # a different key component misses without touching the entry
    k2 = make_key(graph="g", topology="t", calibration="OTHER",
                  num_devices=8)
    assert st.get(k2) is None
    assert st.get(k) is not None


def test_fingerprint_mismatch_rejected(tmp_path):
    """An entry whose recorded key disagrees with the requested one (a
    tampered/misfiled file) is quarantined as stale and read as a miss,
    never returned."""
    st = ArtifactStore(str(tmp_path))
    k = make_key(graph="g", topology="t", calibration="c", num_devices=8)
    path = st.put(k, {"payload": True})
    # rewrite the envelope claiming a different key, crc intact
    env = json.load(open(path))
    env["key"]["graph"] = "someone-else"
    json.dump(env, open(path, "w"))
    assert st.get(k) is None
    assert not os.path.exists(path)  # quarantined, not left in place
    assert os.listdir(st.quarantine_dir)


@pytest.mark.parametrize("damage", ["truncate", "bitflip", "not_json"])
def test_corrupt_entry_typed_error(tmp_path, damage):
    st = ArtifactStore(str(tmp_path))
    k = make_key(graph="g", topology="t", calibration="c", num_devices=8)
    path = st.put(k, {"ops": list(range(50))})
    if damage == "truncate":
        with open(path, "r+b") as f:
            f.truncate(40)
    elif damage == "bitflip":
        raw = bytearray(open(path, "rb").read())
        raw[len(raw) // 2] ^= 0x40
        open(path, "wb").write(bytes(raw))
    else:
        open(path, "w").write("definitely { not json")
    with pytest.raises(ArtifactCorruptionError):
        st.get(k)
    # quarantined: the poisoned entry can never be read again
    assert not os.path.exists(path)
    assert st.get(k) is None


def test_newer_schema_rejected(tmp_path):
    st = ArtifactStore(str(tmp_path))
    k = make_key(graph="g", topology="t", calibration="c", num_devices=8)
    path = st.put(k, {"x": 1})
    env = json.load(open(path))
    env["schema"] = 999
    json.dump(env, open(path, "w"))
    with pytest.raises(ArtifactCorruptionError, match="schema"):
        st.get(k)


def test_concurrent_two_writer_race(tmp_path):
    """Replicas racing to populate the same key: every interleaving must
    end with ONE intact, readable entry (last writer wins)."""
    st = ArtifactStore(str(tmp_path))
    k = make_key(graph="g", topology="t", calibration="c", num_devices=8)
    errors = []

    def writer(i):
        try:
            for j in range(10):
                st.put(k, {"writer": i, "round": j,
                           "bulk": ["x" * 50] * 20})
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    got = st.get(k)
    assert got is not None and got["round"] == 9


def test_lru_eviction(tmp_path):
    st = ArtifactStore(str(tmp_path), max_entries=3)
    keys = [make_key(graph=f"g{i}", topology="t", calibration="c",
                     num_devices=8) for i in range(5)]
    for i, k in enumerate(keys):
        st.put(k, {"i": i})
        time.sleep(0.01)  # distinct mtimes on coarse filesystems
        if i == 2:
            st.get(keys[0])  # touch g0: it must survive the eviction
            time.sleep(0.01)
    assert len(st.entries()) == 3
    assert st.get(keys[0]) is not None  # LRU-touched entry survived
    assert st.get(keys[1]) is None      # oldest untouched entry evicted


def test_clean_stale_tmp_on_open(tmp_path):
    st = ArtifactStore(str(tmp_path))
    k = make_key(graph="g", topology="t", calibration="c", num_devices=8)
    st.put(k, {"x": 1})
    litter = os.path.join(st.entries_dir, "abc.json.tmp-999-1")
    open(litter, "w").write("half-written")
    st2 = ArtifactStore(str(tmp_path))
    assert not os.path.exists(litter)
    assert st2.get(k) is not None


# ---------------------------------------------------------------------------
# compile() consumer: hit skips the search, corruption degrades
# ---------------------------------------------------------------------------
def test_compile_miss_then_hit_skips_search(tmp_path, monkeypatch):
    st = ArtifactStore(str(tmp_path))
    m1 = small_model(st)
    assert m1.strategy_provenance == {"source": "search",
                                      "cause": "cache_miss"}
    assert len(st.entries()) == 1
    calls = count_searches(monkeypatch)
    m2 = small_model(st)
    assert calls == []
    assert m2.strategy_provenance["source"] == "artifact_cache"
    # the replayed strategy trains, and matches the searched one's loss
    # (sharding is layout-only under GSPMD — same seed, same numbers)
    x, y = dataset()
    p2 = m2.fit(x=[x], y=y, epochs=1, verbose=False)
    p1 = m1.fit(x=[x], y=y, epochs=1, verbose=False)
    assert np.isclose(p1.sparse_cce_loss, p2.sparse_cce_loss, rtol=1e-5)
    assert p1.train_correct == p2.train_correct
    # the replay is FAITHFUL, not merely valid: the rebuilt graph carries
    # the searched winner's exact per-dim sharding state (degree, mesh
    # axis, replica dims) op for op — a replay that "works" by silently
    # demoting everything to replicated must fail here
    def sharding(m):
        return {
            op.name: [
                [(d.size, d.degree, d.parallel_idx, d.is_replica_dim)
                 for d in t.dims]
                for t in list(op.outputs) + list(op.weights)
            ]
            for op in m.graph.ops
        }
    assert sharding(m1) == sharding(m2)


def test_payload_schema_mismatch_degrades_stale(tmp_path, monkeypatch):
    """An entry whose payload predates (or postdates) the current graph
    serialization is stale, never a wrong replay: the payload is a full
    PCG, so fields can't be guessed across versions."""
    st = ArtifactStore(str(tmp_path))
    m1 = small_model(st)
    payload = st.get(m1._artifact_key)
    payload["strategy_schema"] = payload["strategy_schema"] - 1
    st.put(m1._artifact_key, payload)
    calls = count_searches(monkeypatch)
    with pytest.warns(UserWarning, match="could not be replayed"):
        m2 = small_model(st)
    assert len(calls) == 1 and m2.strategy_provenance["source"] == "search"


def test_compile_corrupt_entry_falls_back_to_search(tmp_path, monkeypatch):
    st = ArtifactStore(str(tmp_path))
    small_model(st)
    [entry] = st.entries()
    path = os.path.join(st.entries_dir, entry)
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0x10
    open(path, "wb").write(bytes(raw))
    calls = count_searches(monkeypatch)
    m = small_model(st)  # never crashes, never a wrong strategy
    assert len(calls) == 1
    assert m.strategy_provenance == {"source": "search",
                                     "cause": "cache_corrupt"}
    # the fresh winner was re-cached over the quarantined entry
    assert len(st.entries()) == 1
    calls.clear()
    m2 = small_model(st)
    assert calls == [] and m2.strategy_provenance["source"] == \
        "artifact_cache"


def test_compile_unreplayable_entry_degrades_stale(tmp_path, monkeypatch):
    """An intact entry whose strategy doesn't apply to the live model
    (here: op records naming a different model's compute ops) is
    quarantined as stale and compile searches fresh."""
    st = ArtifactStore(str(tmp_path))
    m1 = small_model(st)
    key = m1._artifact_key
    # overwrite the valid entry with a well-formed v3 payload whose
    # compute ops can't match the live model
    from flexflow_tpu.runtime.artifact_store import STRATEGY_PAYLOAD_SCHEMA
    payload = {
        "kind": "strategy", "strategy_schema": STRATEGY_PAYLOAD_SCHEMA,
        "cost": 1.0, "mesh_axes": {"data": min(8, NDEV)},
        "inputs": [[[4, 1, -1, 0, None], [4, 1, -1, 0, None]]],
        "nodes": [{"name": "op_from_another_model_0",
                   "op_type": "OP_LINEAR", "params": None,
                   "inputs": [["input", 0, 0]],
                   "outputs": [{"dtype": "DT_FLOAT",
                                "dims": [[4, 1, -1, 0, None],
                                         [4, 1, -1, 0, None]]}],
                   "weights": [], "machine_view": None}],
        "provenance": {},
    }
    st.put(key, payload)
    calls = count_searches(monkeypatch)
    with pytest.warns(UserWarning, match="could not be replayed"):
        m2 = small_model(st)
    assert len(calls) == 1
    assert m2.strategy_provenance["source"] == "search"
    x, y = dataset()
    m2.fit(x=[x], y=y, epochs=1, verbose=False)


def test_fault_injection_sites(tmp_path):
    """The artifact_corruption / artifact_stale chaos sites force each
    degradation leg without touching bytes on disk."""
    fi = FaultInjector()
    st = ArtifactStore(str(tmp_path), fault_injector=fi)
    k = make_key(graph="g", topology="t", calibration="c", num_devices=8)
    st.put(k, {"x": 1})
    fi.inject("artifact_stale")
    assert st.get(k) is None            # stale: silent miss
    st.put(k, {"x": 2})
    fi.inject("artifact_corruption")
    with pytest.raises(ArtifactCorruptionError, match="injected"):
        st.get(k)
    assert st.get(k) is None            # quarantined either way
    assert fi.fired["artifact_stale"] == 1
    assert fi.fired["artifact_corruption"] == 1


def test_compile_survives_injected_corruption(tmp_path, monkeypatch):
    fi = FaultInjector()
    st = ArtifactStore(str(tmp_path), fault_injector=fi)
    small_model(st)
    fi.inject("artifact_corruption")
    calls = count_searches(monkeypatch)
    m = small_model(st)
    assert len(calls) == 1
    assert m.strategy_provenance["cause"] == "cache_corrupt"


def test_metrics_counted(tmp_path):
    import tempfile

    with tempfile.TemporaryDirectory() as td, \
            obs.session(TelemetryConfig(dir=td)):
        st = ArtifactStore(str(tmp_path))
        k = make_key(graph="g", topology="t", calibration="c",
                     num_devices=8)
        st.get(k)
        st.put(k, {"x": 1})
        st.get(k)
        st.note_stale(k, "replay failed")
        reg = obs.active().metrics
        for event, expect in [("miss", 1), ("put", 1), ("hit", 1),
                              ("stale", 1)]:
            c = reg.find("ff_artifact_cache_total", event=event)
            assert c is not None and c.value == expect, event


# ---------------------------------------------------------------------------
# tuner quarantine persistence
# ---------------------------------------------------------------------------
def test_quarantine_set_round_trip(tmp_path):
    st = ArtifactStore(str(tmp_path))
    assert st.load_quarantine("scope") == set()
    st.add_quarantine("scope", {"aaa", "bbb"})
    st.add_quarantine("scope", {"ccc"})
    assert st.load_quarantine("scope") == {"aaa", "bbb", "ccc"}
    # corrupt quarantine file degrades to empty, not a crash
    path = st._quarantine_set_path("scope")
    open(path, "w").write("junk{")
    assert st.load_quarantine("scope") == set()


def test_tuner_quarantine_persists_across_restart(tmp_path):
    """A fingerprint quarantined by one process's tuner is honored by
    the next process's tuner (fresh instance, same store)."""
    from flexflow_tpu.runtime.tuner import StrategyTuner

    st = ArtifactStore(str(tmp_path))
    m = small_model(st)
    t1 = StrategyTuner(m)
    t1.attach_artifact_store(st)
    t1._quarantine("deadbeefcafe0000")
    # "restart": new tuner over a freshly compiled model, same store
    m2 = small_model(st)
    t2 = StrategyTuner(m2)
    t2.attach_artifact_store(m2.artifact_store)
    assert "deadbeefcafe0000" in t2.quarantined


def test_tuner_write_through_winner(tmp_path, monkeypatch):
    """A committed tuner winner lands in the store under compile()'s
    key, so the next boot replays the TUNED strategy."""
    from flexflow_tpu.runtime.tuner import StrategyTuner

    st = ArtifactStore(str(tmp_path))
    m = small_model(st)
    tuner = StrategyTuner(m)
    tuner.attach_artifact_store(st)
    tuner._write_through_winner()
    entry = st.get(m._artifact_key)
    assert entry["provenance"]["writer"] == "tuner"
    calls = count_searches(monkeypatch)
    m2 = small_model(st)
    assert calls == [] and m2.strategy_provenance["source"] == \
        "artifact_cache"


# ---------------------------------------------------------------------------
# CheckpointManager retention (satellite bugfix)
# ---------------------------------------------------------------------------
class _Step:
    """Minimal stand-in: CheckpointManager paths don't need a model for
    retention tests — we create checkpoint dirs + sidecars by hand."""


def _fake_ckpt(mgr, step):
    path = mgr.step_path(step)
    os.makedirs(path)
    open(os.path.join(path, "data.npz"), "w").write("x")
    json.dump({"step": step}, open(path + ".meta.json", "w"))


def test_gc_never_prunes_latest_named_step(tmp_path):
    """Rollback-resume regression: saving a LOWER step than the on-disk
    history must not let retention delete the checkpoint LATEST names."""
    mgr = CheckpointManager(str(tmp_path), keep_last_n=3)
    for s in (8, 9, 10):
        _fake_ckpt(mgr, s)
    # an elastic rollback resumed from step 5 and saved it
    _fake_ckpt(mgr, 5)
    mgr._write_latest(5)
    mgr._gc()
    assert os.path.isdir(mgr.step_path(5)), \
        "retention deleted the checkpoint LATEST points at"
    assert mgr.latest_step() == 5
    # newest keep_last_n still kept alongside
    assert sorted(mgr.list_steps()) == [5, 8, 9, 10]


def test_gc_prunes_checkpoint_and_sidecar(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last_n=2)
    for s in (1, 2, 3, 4):
        _fake_ckpt(mgr, s)
    mgr._write_latest(4)
    mgr._gc()
    assert mgr.list_steps() == [3, 4]
    for s in (1, 2):
        assert not os.path.exists(mgr.step_path(s))
        assert not os.path.exists(mgr.step_path(s) + ".meta.json"), \
            "sidecar survived its checkpoint"


def test_gc_crash_between_prune_and_pointer_recovers(tmp_path):
    """Crash mid-GC (dir renamed to tmp, sidecar still in place, process
    dies): the next manager boot sweeps the litter — including the
    orphan sidecar — and restore still sees a consistent directory."""
    mgr = CheckpointManager(str(tmp_path), keep_last_n=2)
    for s in (1, 2, 3):
        _fake_ckpt(mgr, s)
    mgr._write_latest(3)
    # simulate the crash window: step_1's dir renamed to the tmp-gc name
    # (as the fixed _gc does first) but nothing else happened
    victim = mgr.step_path(1)
    os.replace(victim, victim + ".tmp-gc-999")
    assert os.path.exists(victim + ".meta.json")  # orphan sidecar
    mgr2 = CheckpointManager(str(tmp_path), keep_last_n=2)
    assert not os.path.exists(victim + ".tmp-gc-999")
    assert not os.path.exists(victim + ".meta.json"), \
        "orphan sidecar survived recovery"
    assert mgr2.list_steps() == [2, 3]
    assert mgr2.latest_step() == 3


# ---------------------------------------------------------------------------
# the 8->4->8 story: zero redundant searches
# ---------------------------------------------------------------------------
@pytest.mark.slow
@needs8
def test_elastic_848_zero_redundant_searches(tmp_path, monkeypatch):
    """The acceptance story (scripts/coldstart_check.sh runs this
    standalone): once the store holds the 8- and 4-device winners, a
    full 8->4->8 failover cycle performs ZERO strategy searches —
    ff_artifact_cache_total{event=hit} >= 2, ff_elastic_research_total
    absent — and every restored model trains."""
    import tempfile

    from flexflow_tpu.runtime.elastic import restore_elastic, shrunk_devices

    store = ArtifactStore(str(tmp_path / "store"))
    ckpt = str(tmp_path / "ckpt")
    x, y = dataset()

    def model_fn():
        return small_model(store, budget=20)

    m = model_fn()  # populates the 8-device key
    m.fit(x=[x], y=y, epochs=1, checkpoint_dir=ckpt,
          checkpoint_every_n_steps=1, verbose=False)
    with shrunk_devices(4):  # warm phase: populates the 4-device key
        m4, _ = restore_elastic(model_fn, ckpt, verbose=False)
        assert m4.strategy_provenance["cause"] == "cache_miss"
    assert len(store.entries()) == 2

    calls = count_searches(monkeypatch)
    with tempfile.TemporaryDirectory() as td, \
            obs.session(TelemetryConfig(dir=td)):
        with shrunk_devices(4):
            m4b, _ = restore_elastic(model_fn, ckpt, verbose=False)
        m8b, _ = restore_elastic(model_fn, ckpt, verbose=False)
        reg = obs.active().metrics
        hits = reg.find("ff_artifact_cache_total", event="hit")
        assert hits is not None and hits.value >= 2
        for cause in ("cache_miss", "cache_corrupt", "no_store"):
            assert reg.find("ff_elastic_research_total",
                            cause=cause) is None, \
                f"redundant search counted (cause={cause})"
    assert calls == [], f"redundant searches ran: {calls}"
    assert m4b.strategy_provenance["source"] == "artifact_cache"
    assert m8b.strategy_provenance["source"] == "artifact_cache"
    m8b.fit(x=[x], y=y, epochs=1, verbose=False)


@pytest.mark.slow
@needs8
def test_elastic_research_counted_without_store(tmp_path):
    """The no_store cause: restore_elastic without any store counts its
    from-scratch search, so redundant work is observable."""
    import tempfile

    from flexflow_tpu.runtime.elastic import restore_elastic

    ckpt = str(tmp_path / "ckpt")
    x, y = dataset()

    def model_fn():
        return small_model(None, budget=20)

    m = model_fn()
    m.fit(x=[x], y=y, epochs=1, checkpoint_dir=ckpt,
          checkpoint_every_n_steps=1, verbose=False)
    with tempfile.TemporaryDirectory() as td, \
            obs.session(TelemetryConfig(dir=td)):
        m2, _ = restore_elastic(model_fn, ckpt, verbose=False)
        c = obs.active().metrics.find("ff_elastic_research_total",
                                      cause="no_store")
        assert c is not None and c.value >= 1
    assert m2.strategy_provenance == {"source": "search",
                                      "cause": "no_store"}
