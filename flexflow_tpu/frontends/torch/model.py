"""PyTorch-FX frontend: import a torch.nn.Module into FFModel.

TPU-native equivalent of reference python/flexflow/torch/model.py (2607 LoC):
`PyTorchModel(torch_module).torch_to_ff(ffmodel, input_tensors)` traces the
module with torch.fx.symbolic_trace (model.py:2427 _trace_model) and maps
each fx node onto FFModel ops (per-node `to_ff`, model.py:2496). Weights are
transferred from the torch module so imported models start from the same
parameters (the reference does this via set_tensor after compile; we stage
them and FFModel applies at compile).

File format (reference: torch_to_flexflow export + PyTorchModel.file_to_ff
import, model.py:2540): `torch_to_flexflow(module, path)` serializes the
traced graph as JSON-lines — one record per fx node, with module configs
extracted so replay needs no torch — and `PyTorchModel.file_to_ff(path,
ffmodel, input_tensors)` rebuilds the FFModel ops from the file. Both paths
share one builder table (`_MODULE_BUILDERS`), so live trace and file replay
cannot drift apart.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from ...ff_types import ActiMode, AggrMode, DataType, OperatorType, PoolType

try:
    import torch
    import torch.fx

    HAS_TORCH = True
except Exception:  # pragma: no cover
    HAS_TORCH = False


def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v)


# ---------------------------------------------------------------------------
# Module specs: one entry per supported nn.Module type.
#   export(mod)             -> JSON-serializable config dict
#   build(ff, cfg, args, name) -> output Tensor(s)
#   weights(mod)            -> [np arrays] in our layout, or None
# ---------------------------------------------------------------------------

def _linear_export(mod):
    return {"out_features": mod.out_features, "bias": mod.bias is not None}


def _linear_build(ff, cfg, args, name):
    return ff.dense(args[0], cfg["out_features"], use_bias=cfg["bias"], name=name)


def _linear_weights(mod):
    w = [mod.weight.detach().numpy().T]  # torch (out,in) -> ours (in,out)
    if mod.bias is not None:
        w.append(mod.bias.detach().numpy())
    return w


def _conv2d_export(mod):
    return {
        "out_channels": mod.out_channels,
        "kernel": list(_pair(mod.kernel_size)),
        "stride": list(_pair(mod.stride)),
        "padding": list(_pair(mod.padding)),
        "groups": mod.groups,
        "bias": mod.bias is not None,
    }


def _conv2d_build(ff, cfg, args, name):
    k, s, p = cfg["kernel"], cfg["stride"], cfg["padding"]
    return ff.conv2d(
        args[0], cfg["out_channels"], k[0], k[1], s[0], s[1], p[0], p[1],
        groups=cfg["groups"], use_bias=cfg["bias"], name=name,
    )


def _conv2d_weights(mod):
    w = [mod.weight.detach().numpy()]
    if mod.bias is not None:
        w.append(mod.bias.detach().numpy())
    return w


def _pool_export(mod):
    k = _pair(mod.kernel_size)
    s = _pair(mod.stride) if mod.stride is not None else k
    return {"kernel": list(k), "stride": list(s),
            "padding": list(_pair(mod.padding))}


def _maxpool_build(ff, cfg, args, name):
    k, s, p = cfg["kernel"], cfg["stride"], cfg["padding"]
    return ff.pool2d(args[0], k[0], k[1], s[0], s[1], p[0], p[1],
                     PoolType.POOL_MAX, name=name)


def _avgpool_build(ff, cfg, args, name):
    k, s, p = cfg["kernel"], cfg["stride"], cfg["padding"]
    return ff.pool2d(args[0], k[0], k[1], s[0], s[1], p[0], p[1],
                     PoolType.POOL_AVG, name=name)


def _adaptive_export(mod):
    return {"output_size": list(_pair(mod.output_size))}


def _adaptive_build(ff, cfg, args, name):
    x = args[0]
    h, w = x.dims[2], x.dims[3]
    osz = tuple(cfg["output_size"])
    if osz == (1, 1):
        return ff.pool2d(x, h, w, 1, 1, 0, 0, PoolType.POOL_AVG, name=name)
    assert (h, w) == osz, "unsupported AdaptiveAvgPool2d size"
    return x


def _bn_export(mod):
    return {}


def _bn_build(ff, cfg, args, name):
    return ff.batch_norm(args[0], relu=False, name=name)


def _bn_weights(mod):
    if mod.weight is None:  # BatchNorm2d(affine=False)
        return None
    return [mod.weight.detach().numpy(), mod.bias.detach().numpy()]


def _ln_export(mod):
    return {"normalized_shape": list(mod.normalized_shape), "eps": mod.eps,
            "affine": mod.elementwise_affine}


def _ln_build(ff, cfg, args, name):
    return ff.layer_norm(
        args[0], axes=tuple(range(-len(cfg["normalized_shape"]), 0)),
        eps=cfg["eps"], name=name,
    )


def _ln_weights(mod):
    if not mod.elementwise_affine:
        return None
    return [mod.weight.detach().numpy(), mod.bias.detach().numpy()]


def _emb_export(mod):
    return {"num": mod.num_embeddings, "dim": mod.embedding_dim}


def _emb_build(ff, cfg, args, name):
    return ff.embedding(args[0], cfg["num"], cfg["dim"],
                        AggrMode.AGGR_MODE_NONE, name=name)


def _emb_weights(mod):
    return [mod.weight.detach().numpy()]


def _act_build(method):
    def build(ff, cfg, args, name):
        return getattr(ff, method)(args[0], name=name)

    return build


def _softmax_export(mod):
    return {"dim": mod.dim if mod.dim is not None else -1}


def _softmax_build(ff, cfg, args, name):
    return ff.softmax(args[0], axis=cfg["dim"], name=name)


def _dropout_export(mod):
    return {"p": mod.p}


def _dropout_build(ff, cfg, args, name):
    return ff.dropout(args[0], cfg["p"], name=name)


def _mha_export(mod):
    return {"embed_dim": mod.embed_dim, "num_heads": mod.num_heads,
            "dropout": mod.dropout, "bias": mod.in_proj_bias is not None}


def _mha_build(ff, cfg, args, name):
    return ff.multihead_attention(
        args[0], args[1], args[2], cfg["embed_dim"], cfg["num_heads"],
        dropout=cfg["dropout"], bias=cfg["bias"], name=name,
    )


def _none_export(mod):
    return {}


# type name -> (export, build, weights|None)
_MODULE_BUILDERS = {
    "Linear": (_linear_export, _linear_build, _linear_weights),
    "Conv2d": (_conv2d_export, _conv2d_build, _conv2d_weights),
    "MaxPool2d": (_pool_export, _maxpool_build, None),
    "AvgPool2d": (_pool_export, _avgpool_build, None),
    "AdaptiveAvgPool2d": (_adaptive_export, _adaptive_build, None),
    "BatchNorm2d": (_bn_export, _bn_build, _bn_weights),
    "LayerNorm": (_ln_export, _ln_build, _ln_weights),
    "Embedding": (_emb_export, _emb_build, _emb_weights),
    "ReLU": (_none_export, _act_build("relu"), None),
    "GELU": (_none_export, _act_build("gelu"), None),
    "Sigmoid": (_none_export, _act_build("sigmoid"), None),
    "Tanh": (_none_export, _act_build("tanh"), None),
    "ELU": (_none_export, _act_build("elu"), None),
    "Identity": (_none_export, _act_build("identity"), None),
    "Flatten": (_none_export, lambda ff, c, a, n: ff.flat(a[0], name=n), None),
    "Softmax": (_softmax_export, _softmax_build, None),
    "Dropout": (_dropout_export, _dropout_build, None),
    "MultiheadAttention": (_mha_export, _mha_build, None),
}


class PyTorchModel:
    """reference: torch/model.py:2408 PyTorchModel"""

    def __init__(self, module, is_hf_model: bool = False, input_names=None,
                 batch_size: int = 1, seq_length=None):
        assert HAS_TORCH, "torch is not available"
        self.module = module
        self.is_hf_model = is_hf_model
        self.input_names = input_names
        self.batch_size = batch_size
        self.seq_length = seq_length
        self._weight_loads = []  # (ff_layer, [np arrays]) applied post-compile

    def _trace(self):
        """reference: model.py:2427 _trace_model (HF variant uses
        transformers.utils.fx with input_names/batch/seq; plain variant
        torch.fx)."""
        if self.is_hf_model:
            from transformers.utils import fx as hf_fx

            kw = {"input_names": self.input_names}
            if self.seq_length is not None:
                kw["sequence_length"] = self.seq_length
            try:
                return hf_fx.symbolic_trace(self.module, **kw)
            except TypeError:  # older/newer hf signatures
                return hf_fx.symbolic_trace(self.module,
                                            input_names=self.input_names)
        return torch.fx.symbolic_trace(self.module)

    # ------------------------------------------------------------------
    def torch_to_ff(self, ffmodel, input_tensors: List) -> List:
        """Map the traced graph onto ffmodel; returns output tensors."""
        traced = self._trace()
        modules = dict(traced.named_modules())
        env: Dict[str, object] = {}
        inputs = list(input_tensors)
        outputs: List = []

        for node in traced.graph.nodes:
            if node.op != "placeholder" and node.op != "output" and not node.users:
                # dead value (e.g. the discarded attention-weights half of
                # `out, _ = mha(...)`): nothing consumes it, skip
                continue
            if node.op == "placeholder":
                env[node.name] = inputs.pop(0)
            elif node.op == "call_module":
                mod = modules[node.target]
                args = [env[a.name] if isinstance(a, torch.fx.Node) else a
                        for a in node.args]
                env[node.name] = self._module_to_ff(ffmodel, mod, args, node)
            elif node.op == "call_function":
                env[node.name] = self._function_to_ff(ffmodel, node, env)
            elif node.op == "call_method":
                env[node.name] = self._method_to_ff(ffmodel, node, env)
            elif node.op == "get_attr":
                env[node.name] = self._fetch_attr(node.target)
            elif node.op == "output":
                def collect(a):
                    if isinstance(a, torch.fx.Node):
                        outputs.append(env[a.name])
                    elif isinstance(a, (tuple, list)):
                        for x in a:
                            collect(x)
                collect(node.args[0])
        self._ffmodel = ffmodel
        return outputs

    def _fetch_attr(self, target: str):
        obj = self.module
        for part in target.split("."):
            obj = getattr(obj, part)
        return obj

    # -- modules ---------------------------------------------------------
    def _module_to_ff(self, ff, mod, args, node):
        tname = type(mod).__name__
        spec = _MODULE_BUILDERS.get(tname)
        if spec is None:
            raise NotImplementedError(f"torch module {tname}")
        export, build, weights = spec
        out = build(ff, export(mod), args, node.name)
        if weights is not None:
            w = weights(mod)
            if w is not None:
                self._weight_loads.append((ff.layers[-1], w))
        return out

    # -- functions -------------------------------------------------------
    def _function_to_ff(self, ff, node, env):
        def val(a):
            return env[a.name] if isinstance(a, torch.fx.Node) else a

        args = [val(a) for a in node.args]
        kwargs = {k: val(v) for k, v in node.kwargs.items()}
        return _replay_fn(ff, _fn_name(node.target), args, kwargs)

    def _method_to_ff(self, ff, node, env):
        def val(a):
            return env[a.name] if isinstance(a, torch.fx.Node) else a

        args = [val(a) for a in node.args]
        kwargs = {k: val(v) for k, v in node.kwargs.items()}
        return _replay_fn(ff, node.target, args, kwargs)

    # ------------------------------------------------------------------
    def load_weights(self, ffmodel=None):
        """Copy the torch module's parameters into the compiled model
        (reference: torch weight transfer via set_tensor)."""
        for layer, arrays in self._weight_loads:
            for wt, arr in zip(layer.weights, arrays):
                wt.set_tensor(self._ffmodel, arr)

    # -- file-format import (reference: model.py:2540 file_to_ff) -------
    @staticmethod
    def file_to_ff(filename: str, ffmodel, input_tensors: List) -> List:
        """Rebuild FFModel ops from a `torch_to_flexflow` export. Works
        without torch installed (the file carries extracted configs)."""
        env: Dict[str, object] = {}
        inputs = list(input_tensors)
        outputs: List = []

        def val(a):
            if isinstance(a, dict) and "ref" in a:
                return env[a["ref"]]
            if isinstance(a, list):
                return [val(x) for x in a]
            return a

        with open(filename) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                kind, name = rec["op"], rec["name"]
                if kind == "placeholder":
                    env[name] = inputs.pop(0)
                elif kind == "call_module":
                    spec = _MODULE_BUILDERS.get(rec["module_type"])
                    if spec is None:
                        raise NotImplementedError(
                            f"module {rec['module_type']} in {filename}"
                        )
                    _, build, _ = spec
                    args = [val(a) for a in rec["args"]]
                    env[name] = build(ffmodel, rec["config"], args, name)
                elif kind in ("call_function", "call_method"):
                    env[name] = _replay_fn(
                        ffmodel, rec["target"], [val(a) for a in rec["args"]],
                        rec.get("kwargs", {}),
                    )
                elif kind == "output":
                    for a in rec["args"]:
                        outputs.append(val(a))
        return outputs


def _fn_name(fn) -> str:
    """Normalize a live call_function target to its serialized name — the
    same `fn.__name__` torch_to_flexflow writes, so live trace and file
    replay go through the one `_replay_fn` dispatch."""
    return fn if isinstance(fn, str) else fn.__name__


def _replay_fn(ff, target: str, args, kwargs):
    """The single call_function/call_method dispatch, shared by the live fx
    walk (torch_to_ff) and file replay (file_to_ff). Targets are normalized
    names (`operator.add`/`torch.add` → "add", methods keep their string)."""
    x = args[0] if args else None
    if target in ("add", "sub", "subtract", "mul", "multiply", "truediv",
                  "div", "divide"):
        key = {"subtract": "sub", "multiply": "mul", "divide": "div"}.get(
            target, target
        )
        scalar_ops = {"add": ff.scalar_add, "sub": ff.scalar_sub,
                      "mul": ff.scalar_multiply,
                      "truediv": ff.scalar_true_divide,
                      "div": ff.scalar_true_divide}
        pair_ops = {"add": ff.add, "sub": ff.subtract, "mul": ff.multiply,
                    "truediv": ff.divide, "div": ff.divide}
        if _is_scalar(args[1]):
            return scalar_ops[key](x, float(args[1]))
        return pair_ops[key](x, args[1])
    if target in ("relu", "gelu", "sigmoid", "tanh", "elu", "exp", "sin",
                  "cos", "rsqrt", "sqrt", "log"):
        return getattr(ff, target)(x)
    if target == "softmax":
        dim = kwargs.get("dim", args[1] if len(args) > 1 else -1)
        return ff.softmax(x, axis=dim if dim is not None else -1)
    if target in ("cat", "concat"):
        dim = kwargs.get("dim", args[1] if len(args) > 1 else 0)
        return ff.concat(list(args[0]), dim)
    if target in ("flatten", "flat"):
        return ff.flat(x)
    if target in ("matmul", "bmm"):
        return ff.batch_matmul(x, args[1])
    if target == "pow":
        return ff.pow(x, float(args[1]))
    if target == "mean":
        dims = kwargs.get("dim", args[1] if len(args) > 1 else None)
        keep = kwargs.get("keepdim", False)
        if dims is None:  # torch.mean(x): global mean over every dim
            dims = list(range(len(x.dims)))
        dims = [dims] if isinstance(dims, int) else list(dims)
        return ff.mean(x, dims, keep)
    if target == "transpose":
        d0, d1 = args[1], args[2]
        perm = list(range(len(x.dims)))
        perm[d0], perm[d1] = perm[d1], perm[d0]
        return ff.transpose(x, perm)
    if target == "permute":
        perm = args[1] if isinstance(args[1], (list, tuple)) else args[1:]
        return ff.transpose(x, list(perm))
    if target in ("view", "reshape"):
        shape = args[1:] if not isinstance(args[1], (list, tuple)) else args[1]
        shape = [-1 if isinstance(s, str) else int(s) for s in shape]
        return ff.reshape(x, shape)
    if target in ("contiguous", "detach", "clone", "identity"):
        return x
    if target == "size":
        return x.dims if len(args) == 1 else x.dims[args[1]]
    if target == "getitem":
        if isinstance(x, (list, tuple)):
            return x[args[1]]
        owner_op = getattr(getattr(x, "owner_layer", None), "op_type", None)
        if args[1] == 0 and owner_op in (
            OperatorType.OP_MULTIHEAD_ATTENTION, OperatorType.OP_LSTM,
        ):
            # tuple-returning torch ops (MultiheadAttention's
            # (output, weights), LSTM's (output, state)) map to a single
            # output Tensor here; true tensor indexing stays a loud error
            return x
        raise NotImplementedError(f"getitem[{args[1]}] on single-output op")
    raise NotImplementedError(f"torch call {target}")


def _is_scalar(v) -> bool:
    return isinstance(v, (int, float))


def torch_to_flexflow(module, path: str, batch_size: int = 1) -> str:
    """Serialize a torch module's fx graph to the flexflow file format
    (reference: torch/model.py torch_to_flexflow). JSON-lines, one record
    per fx node; module configs are extracted so `file_to_ff` replays
    without torch."""
    assert HAS_TORCH, "torch is not available"
    traced = torch.fx.symbolic_trace(module)
    modules = dict(traced.named_modules())

    def ser(a):
        if isinstance(a, torch.fx.Node):
            return {"ref": a.name}
        if isinstance(a, (tuple, list)):
            return [ser(x) for x in a]
        if isinstance(a, (int, float, str, bool)) or a is None:
            return a
        raise NotImplementedError(f"cannot serialize arg {a!r}")

    with open(path, "w") as f:
        for node in traced.graph.nodes:
            if node.op != "placeholder" and node.op != "output" and not node.users:
                continue  # dead value, same skip as the live walk
            rec = {"op": node.op, "name": node.name}
            if node.op == "placeholder":
                pass
            elif node.op == "call_module":
                mod = modules[node.target]
                tname = type(mod).__name__
                spec = _MODULE_BUILDERS.get(tname)
                if spec is None:
                    raise NotImplementedError(f"torch module {tname}")
                if node.kwargs:
                    # refuse to write a file that silently loses semantics
                    # (e.g. MultiheadAttention key_padding_mask=...)
                    raise NotImplementedError(
                        f"kwargs on module call {tname}: {sorted(node.kwargs)}"
                    )
                rec["module_type"] = tname
                rec["config"] = spec[0](mod)
                rec["args"] = [ser(a) for a in node.args]
            elif node.op in ("call_function", "call_method"):
                t = node.target
                rec["target"] = t if isinstance(t, str) else t.__name__
                rec["args"] = [ser(a) for a in node.args]
                rec["kwargs"] = {k: ser(v) for k, v in node.kwargs.items()}
            elif node.op == "output":
                flat = []

                def collect(a):
                    if isinstance(a, torch.fx.Node):
                        flat.append({"ref": a.name})
                    elif isinstance(a, (tuple, list)):
                        for x in a:
                            collect(x)

                collect(node.args[0])
                rec["args"] = flat
            elif node.op == "get_attr":  # pragma: no cover
                raise NotImplementedError("get_attr not serializable")
            f.write(json.dumps(rec) + "\n")
    return path


# reference model.py:2607 exposes file_to_ff module-level (usable sans torch)
file_to_ff = PyTorchModel.file_to_ff
