"""Inference serving: a batching scheduler over a compiled model.

TPU-native counterpart to the reference's Triton prototype (triton/src/,
~8k LoC "incomplete prototype" serving ONNX models on Legion — SURVEY §2.6).
Instead of a Triton backend we provide the piece that matters on TPU: a
request queue + dynamic batcher that pads/packs incoming requests to the
compiled batch size, runs the jitted forward, and fans results back out.
Models arrive through any frontend (ONNX importer included, matching the
prototype's ONNX surface).
"""
from __future__ import annotations

import queue
import threading
import time
import uuid
from typing import Dict, List, Optional

import numpy as np


class InferenceRequest:
    def __init__(self, inputs: List[np.ndarray]):
        self.id = uuid.uuid4().hex
        self.inputs = inputs
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None


class BatchScheduler:
    """Dynamic batcher (reference: triton/src/instance.cc lifecycle +
    per-request execution, re-thought as a batch queue).

    `max_delay_s`: how long to wait to fill a batch before running partial.
    """

    def __init__(self, model, *, max_delay_s: float = 0.005):
        assert model.executor is not None, "compile() the model first"
        self.model = model
        self.batch_size = model.executor.input_pts[0].material_shape()[0]
        self.max_delay_s = max_delay_s
        self._q: "queue.Queue[InferenceRequest]" = queue.Queue()
        self._fwd = model.executor.build_forward()
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._started = False
        self.stats = {"requests": 0, "batches": 0, "padded_slots": 0}

    # -- client API ------------------------------------------------------
    def start(self):
        if not self._started:
            self._worker.start()
            self._started = True
        return self

    def stop(self):
        self._stop.set()
        if self._started:
            self._worker.join(timeout=5)

    def submit(self, inputs: List[np.ndarray]) -> InferenceRequest:
        """Each request carries ONE sample per model input (no batch dim)."""
        req = InferenceRequest([np.asarray(a) for a in inputs])
        self._q.put(req)
        return req

    def infer(self, inputs: List[np.ndarray], timeout: float = 30.0) -> np.ndarray:
        req = self.submit(inputs)
        assert req.event.wait(timeout), "inference timed out"
        return req.result

    # -- batching loop ---------------------------------------------------
    def _loop(self):
        import jax.numpy as jnp

        n_inputs = len(self.model.executor.input_pts)
        while not self._stop.is_set():
            batch: List[InferenceRequest] = []
            try:
                batch.append(self._q.get(timeout=0.05))
            except queue.Empty:
                continue
            deadline = time.monotonic() + self.max_delay_s
            while len(batch) < self.batch_size:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._q.get(timeout=remaining))
                except queue.Empty:
                    break
            pad = self.batch_size - len(batch)
            arrays = []
            for i in range(n_inputs):
                rows = [r.inputs[i] for r in batch]
                stacked = np.stack(rows + [rows[-1]] * pad, axis=0)
                arrays.append(jnp.asarray(stacked))
            out = np.asarray(self._fwd(self.model.state.params, arrays))
            for j, r in enumerate(batch):
                r.result = out[j]
                r.event.set()
            self.stats["requests"] += len(batch)
            self.stats["batches"] += 1
            self.stats["padded_slots"] += pad
