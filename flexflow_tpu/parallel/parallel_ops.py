"""Parallel operators: Repartition, Combine, Replicate, Reduction,
FusedParallelOp, AllToAll.

TPU-native equivalents of reference src/parallel_ops/{partition,combine,
replicate,reduction,fused_parallel_op}.cc — the "parallelism vocabulary" the
Unity search inserts into the PCG (SURVEY §2.3). The reference implements
each as Legion partition plumbing + device-local copy kernels; under XLA SPMD
each is a resharding annotation, and the partitioner emits the actual
collective (all-gather / reduce-scatter / all-to-all / psum) over ICI.

Semantics (training fwd; bwd is derived by jax.grad through the sharding
constraint, which transposes to exactly the reference's backward):
  Repartition dim,k : split dim into k shards           (bwd: gather)
  Combine     dim,k : gather k shards of dim            (bwd: scatter)
  Replicate   k     : broadcast k copies                (bwd: grad-sum)
  Reduction   k     : sum k partial copies              (bwd: broadcast)
  AllToAll    d1,d2 : reshard dim d1 -> d2 (Ulysses-style sequence<->head
                      exchange; TPU addition, no reference equivalent)
"""
from __future__ import annotations

import dataclasses
from typing import List, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..ff_types import OperatorType
from ..pcg.op import PCGOp


@dataclasses.dataclass(frozen=True)
class RepartitionParams:
    """reference: include/flexflow/parallel_ops/partition_params.h"""

    repartition_dim: int
    repartition_degree: int


@dataclasses.dataclass(frozen=True)
class CombineParams:
    """reference: include/flexflow/parallel_ops/combine_params.h"""

    combine_dim: int
    combine_degree: int


@dataclasses.dataclass(frozen=True)
class ReplicateParams:
    """reference: include/flexflow/parallel_ops/replicate_params.h"""

    replicate_dim: int
    replicate_degree: int


@dataclasses.dataclass(frozen=True)
class ReductionParams:
    """reference: include/flexflow/parallel_ops/reduction_params.h"""

    reduction_dim: int
    reduction_degree: int


@dataclasses.dataclass(frozen=True)
class AllToAllParams:
    """TPU addition: Ulysses-style sequence parallelism exchange."""

    scatter_dim: int
    gather_dim: int
    degree: int


@dataclasses.dataclass(frozen=True)
class FusedParallelOpParams:
    """reference: parallel_ops/fused_parallel_op.h ParallelOpInfo list"""

    stages: Tuple[object, ...]  # sequence of the above param records


def _out_spec(op: PCGOp, mesh: Mesh) -> PartitionSpec:
    from .mesh import pspec_for_parallel_tensor

    return pspec_for_parallel_tensor(op.outputs[0], mesh)


def execute(op: PCGOp, inputs: List[jax.Array], mesh: Mesh) -> List[jax.Array]:
    """Execute a parallel op under GSPMD: the op's *output* ParallelTensor
    already carries the target sharding, so every flavor lowers to a
    with_sharding_constraint and XLA inserts the matching collective.

    Reduction additionally must sum over the vanishing replica dim when the
    graph was built with explicit partial tensors (search-produced PCGs mark
    that with a replica dim on the input)."""
    (x,) = inputs
    t = op.op_type
    if t in (
        OperatorType.OP_REPARTITION,
        OperatorType.OP_COMBINE,
        OperatorType.OP_REPLICATE,
        OperatorType.OP_ALL_TO_ALL,
        OperatorType.OP_FUSED_PARALLEL,
        # WeightShard is an identity on the activation path: the storage
        # semantics (params + optimizer state sharded over the fsdp axis,
        # all-gather-on-use, reduce-scatter grads) live in the target op's
        # weight ParallelDims, lowered at init_params — GSPMD inserts the
        # collectives (parallel/weight_sharding.py).
        OperatorType.OP_WEIGHT_SHARD,
    ):
        spec = _out_spec(op, mesh)
        return [jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))]
    if t == OperatorType.OP_REDUCTION:
        # Under GSPMD the partial-sum state is XLA-internal (a sharded
        # contraction yields the full result with an implicit psum), so the
        # logical replica dim on the input ParallelTensor has no runtime
        # axis. Only sum when the array actually carries the partial axis
        # (shard_map execution path).
        out_ndim = len(op.outputs[0].material_shape())
        if x.ndim == out_ndim + 1:
            x = x.sum(axis=op.params.reduction_dim)
        spec = _out_spec(op, mesh)
        return [jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))]
    raise NotImplementedError(f"parallel op {t.name}")
