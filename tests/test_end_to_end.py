"""End-to-end training tests: the minimum slice of SURVEY §7 stage 1.

Mirrors the reference's integration strategy (tests/multi_gpu_tests.sh runs
example scripts and checks they train; examples/python/native/accuracy.py
thresholds): build a model through the FFModel API, compile, fit, and assert
the loss goes down / accuracy rises on a learnable synthetic task.
"""
import numpy as np
import pytest

from flexflow_tpu import (
    ActiMode,
    AdamOptimizer,
    DataType,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)


def make_config(batch_size=32, epochs=1):
    cfg = FFConfig()
    cfg.batch_size = batch_size
    cfg.epochs = epochs
    return cfg


def synthetic_classification(n, dims, num_classes, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, *dims).astype(np.float32)
    w = rng.randn(int(np.prod(dims)), num_classes).astype(np.float32)
    y = np.argmax(x.reshape(n, -1) @ w, axis=1).astype(np.int32)[:, None]
    return x, y


def test_mlp_trains():
    cfg = make_config(batch_size=64, epochs=5)
    model = FFModel(cfg)
    x = model.create_tensor((64, 16), DataType.DT_FLOAT)
    t = model.dense(x, 64, ActiMode.AC_MODE_RELU)
    t = model.dense(t, 32, ActiMode.AC_MODE_RELU)
    t = model.dense(t, 4)
    t = model.softmax(t)
    model.compile(
        optimizer=SGDOptimizer(lr=0.05),
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.METRICS_ACCURACY],
    )
    xs, ys = synthetic_classification(1024, (16,), 4)
    model.fit(xs, ys, batch_size=64, epochs=20, verbose=False)
    pm = model.eval(xs, ys, batch_size=64)
    assert pm.get_accuracy() > 60.0, f"accuracy {pm.get_accuracy()}"


def test_cnn_trains():
    cfg = make_config(batch_size=16, epochs=3)
    model = FFModel(cfg)
    x = model.create_tensor((16, 3, 16, 16), DataType.DT_FLOAT)
    t = model.conv2d(x, 8, 3, 3, 1, 1, 1, 1, ActiMode.AC_MODE_RELU)
    t = model.pool2d(t, 2, 2, 2, 2, 0, 0)
    t = model.flat(t)
    t = model.dense(t, 3)
    t = model.softmax(t)
    model.compile(
        optimizer=SGDOptimizer(lr=0.02),
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.METRICS_ACCURACY],
    )
    xs, ys = synthetic_classification(256, (3, 16, 16), 3)
    pm = model.fit(xs, ys, batch_size=16, epochs=3, verbose=False)
    assert pm.train_all > 0


def test_adam_mse_regression():
    cfg = make_config(batch_size=32, epochs=10)
    model = FFModel(cfg)
    x = model.create_tensor((32, 8), DataType.DT_FLOAT)
    t = model.dense(x, 16, ActiMode.AC_MODE_TANH)
    t = model.dense(t, 2)
    model.compile(
        optimizer=AdamOptimizer(alpha=0.01),
        loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR],
    )
    rng = np.random.RandomState(1)
    xs = rng.randn(512, 8).astype(np.float32)
    w = rng.randn(8, 2).astype(np.float32)
    ys = (xs @ w).astype(np.float32)
    pm = model.fit(xs, ys, batch_size=32, epochs=10, verbose=False)
    mse = pm.mse_loss / max(1, pm.train_all)
    assert mse < 2.0, f"mse {mse}"


def test_stepwise_api():
    """cffi-parity: forward/zero_gradients/backward/update as separate calls
    (reference: flexflow_cffi.py fit loop body)."""
    model = FFModel(make_config())
    x = model.create_tensor((8, 4), DataType.DT_FLOAT)
    t = model.dense(x, 4)
    t = model.softmax(t)
    model.compile(
        optimizer=SGDOptimizer(lr=0.1),
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.METRICS_ACCURACY],
    )
    xs, ys = synthetic_classification(8, (4,), 4)
    model.set_iteration_batch([xs], ys)
    before = model.forward()
    model.zero_gradients()
    model.backward()
    model.update()
    after = model.forward()
    assert not np.allclose(np.asarray(before), np.asarray(after))


def test_weight_get_set():
    model = FFModel(make_config())
    x = model.create_tensor((8, 4), DataType.DT_FLOAT)
    t = model.dense(x, 3, use_bias=True)
    model.compile(
        optimizer=SGDOptimizer(lr=0.1),
        loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[],
    )
    layer = model.get_layer_by_id(0)
    kernel = layer.weights[0].get_tensor(model)
    assert kernel.shape == (4, 3)
    new = np.ones((4, 3), np.float32)
    layer.weights[0].set_tensor(model, new)
    np.testing.assert_allclose(layer.weights[0].get_tensor(model), new)
