"""AlexNet on CIFAR-10-shaped data — the reference bootcamp benchmark
(reference: bootcamp_demo/ff_alexnet_cifar10.py; BASELINE.md config #1).

Usage: python examples/python/alexnet_cifar10.py -e 2 -b 64
"""
import sys

import numpy as np

sys.path.insert(0, ".")

from flexflow_tpu import (
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.models.alexnet import build_alexnet


def main():
    ffconfig = FFConfig()
    model = FFModel(ffconfig)
    build_alexnet(model, ffconfig.batch_size, num_classes=10)
    model.compile(
        optimizer=SGDOptimizer(lr=0.01, momentum=0.9, weight_decay=1e-4),
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.METRICS_ACCURACY,
                 MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY],
    )
    # synthetic CIFAR-10 upscaled to the AlexNet input size, like the
    # reference's generated data path when no dataset file is given
    n = ffconfig.batch_size * 8
    rng = np.random.RandomState(0)
    x = rng.randn(n, 3, 229, 229).astype(np.float32)
    y = rng.randint(0, 10, (n, 1)).astype(np.int32)
    model.fit(x, y, epochs=ffconfig.epochs)


if __name__ == "__main__":
    main()
