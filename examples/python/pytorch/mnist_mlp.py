"""Train the MNIST MLP replayed from a .ff file (reference:
examples/python/pytorch/mnist_mlp.py — PyTorchModel.file_to_ff)."""
import os

from flexflow.core import *  # noqa: F401,F403
from flexflow.keras.datasets import mnist
from flexflow.torch.model import PyTorchModel

from _example_args import example_args
from mnist_mlp_torch import export


def top_level_task(args):
    ffconfig = FFConfig()
    ffconfig.batch_size = args.batch_size
    print("Python API batchSize(%d) workersPerNodes(%d) numNodes(%d)" % (
        ffconfig.batch_size, ffconfig.workers_per_node, ffconfig.num_nodes))
    ffmodel = FFModel(ffconfig)

    input_tensor = ffmodel.create_tensor([args.batch_size, 784], DataType.DT_FLOAT)

    if not os.path.exists("mlp.ff"):
        export("mlp.ff")
    output_tensors = PyTorchModel.file_to_ff("mlp.ff", ffmodel, [input_tensor])

    ffmodel.optimizer = SGDOptimizer(ffmodel, 0.01)
    ffmodel.compile(loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                    metrics=[MetricsType.METRICS_ACCURACY,
                             MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY])

    (x_train, y_train), _ = mnist.load_data(n_train=args.num_samples)
    x_train = x_train.reshape(-1, 784).astype("float32") / 255
    y_train = y_train.astype("int32").reshape(-1, 1)
    ffmodel.fit(x=x_train, y=y_train, epochs=args.epochs)


if __name__ == "__main__":
    print("mnist mlp")
    top_level_task(example_args())
