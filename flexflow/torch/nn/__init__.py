"""Shim: reference python/flexflow/torch/nn/__init__.py"""
from .modules import Module  # noqa: F401
