"""AlexNet/CIFAR-10 training throughput on the real chip — the second
headline config (BASELINE.md: bootcamp_demo/ff_alexnet_cifar10.py prints
THROUGHPUT; reference input layout 3x229x229, batch 64). Synthetic data,
same measurement discipline as bench.py (scan driver + scalar probe)."""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))


def main():
    import jax

    from flexflow_tpu import (
        FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
    )
    from flexflow_tpu.models.alexnet import build_alexnet

    batch = 64
    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.allow_mixed_precision = True
    model = FFModel(cfg)
    build_alexnet(model, batch_size=batch, num_classes=10,
                  height=229, width=229)
    model.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.METRICS_ACCURACY],
    )
    ex = model.executor
    in_pt = ex.input_pts[0]
    rng = np.random.RandomState(0)
    x = ex.shard_batch(in_pt, rng.rand(*in_pt.material_shape()).astype(np.float32))
    y = jax.numpy.asarray(rng.randint(0, 10, (batch, 1)).astype(np.int32))
    state = model.state
    probe = jax.jit(
        lambda params: sum(
            leaf.reshape(-1)[0].astype(jax.numpy.float32)
            for leaf in jax.tree_util.tree_leaves(params)
        )
    )

    def sync(st):
        return float(np.asarray(probe(st.params)))

    scan = ex.build_train_scan()
    spd = 25
    xs = [jax.numpy.broadcast_to(x, (spd,) + x.shape)]
    ys = jax.numpy.broadcast_to(y, (spd,) + y.shape)
    keys = jax.random.split(jax.random.PRNGKey(0), spd)
    for _ in range(2):
        state, _ = scan(state, xs, ys, keys)
    sync(state)
    chunks = 4
    t0 = time.perf_counter()
    for _ in range(chunks):
        state, _ = scan(state, xs, ys, keys)
    sync(state)
    dt = time.perf_counter() - t0
    iters = spd * chunks
    n_chips = max(1, len(jax.devices()))
    print(json.dumps({
        "metric": "alexnet_cifar10_train_throughput",
        "value": round(batch * iters / dt / n_chips, 2),
        "unit": "samples/s/chip",
        "vs_baseline": None,
    }))


if __name__ == "__main__":
    main()
