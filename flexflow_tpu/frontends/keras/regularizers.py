"""Keras-style weight regularizers (reference: python/flexflow/keras/
regularizers.py:19-36). L2 matches the reference's only supported mode
(linear_kernels.cu:333-350); L1 is a TPU-build addition (trivial under
jax.grad, where the penalty is just a loss term).
"""
from __future__ import annotations

from ...ff_types import RegularizerMode

__all__ = ["Regularizer", "L1", "L2"]


class Regularizer:
    def __init__(self):
        self.type: RegularizerMode = RegularizerMode.REG_MODE_NONE
        self._lambda: float = 0.0


class L1(Regularizer):
    def __init__(self, l1: float):
        super().__init__()
        self.type = RegularizerMode.REG_MODE_L1
        self._lambda = l1


class L2(Regularizer):
    def __init__(self, l2: float):
        super().__init__()
        self.type = RegularizerMode.REG_MODE_L2
        self._lambda = l2
