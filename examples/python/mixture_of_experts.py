"""Mixture-of-Experts with load balancing and dynamic recompilation
(reference: examples/cpp/mixture_of_experts/moe.cc, incl. the
recompile-based expert rebalancing at moe.cc:65-98)."""
import sys

import numpy as np

sys.path.insert(0, ".")

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models.misc import build_moe
from flexflow_tpu.runtime import RecompileState, recompile_on_condition


def main():
    ffconfig = FFConfig()
    model = FFModel(ffconfig)
    build_moe(model, ffconfig.batch_size, input_dim=784, num_classes=10,
              num_exp=5, num_select=2, hidden=64, lambda_bal=0.04)
    model.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.METRICS_ACCURACY],
    )
    n = ffconfig.batch_size * 8
    rng = np.random.RandomState(0)
    x = rng.rand(n, 784).astype(np.float32)
    y = rng.randint(0, 10, (n, 1)).astype(np.int32)

    # reference moe.cc: trigger checked each epoch; here it fires once at
    # epoch boundary and re-jits the (possibly altered) model
    r = RecompileState(trigger_func=lambda m: m.state.step >= 8)
    for epoch in range(ffconfig.epochs):
        model.fit(x, y, epochs=1)
        if recompile_on_condition(model, r):
            print(f"[moe] recompiled after epoch {epoch}")


if __name__ == "__main__":
    main()
