"""Smaller model-zoo members: MLP_Unify, XDL, candle_uno, MoE, BERT-proxy.

Same networks as the reference examples they mirror:
  MLP_Unify — examples/cpp/MLP_Unify/mlp.cc (two parallel 4-layer MLPs
              merged by add)
  XDL       — examples/cpp/XDL/xdl.cc (embeddings + MLP, like slim DLRM)
  candle_uno— examples/cpp/candle_uno/candle_uno.cc (per-feature encoders
              concatenated into a deep regressor)
  MoE       — examples/cpp/mixture_of_experts/moe.cc (gate+experts)
  BERT-proxy— examples/python/native/bert_proxy_native.py (transformer
              encoder stack)
"""
from __future__ import annotations

from typing import List, Sequence

from ..core.model import FFModel
from ..ff_types import ActiMode, AggrMode, DataType
from .transformer import create_attention_encoder


def build_mlp_unify(model: FFModel, batch_size: int,
                    input_dims=(3072, 3072),
                    hidden_dims=(8192, 8192, 8192, 8192)):
    """reference: mlp.cc:40-55 — two towers merged with add."""
    in1 = model.create_tensor((batch_size, input_dims[0]), DataType.DT_FLOAT)
    in2 = model.create_tensor((batch_size, input_dims[1]), DataType.DT_FLOAT)
    t1, t2 = in1, in2
    for i, h in enumerate(hidden_dims):
        act = ActiMode.AC_MODE_RELU if i + 1 < len(hidden_dims) else ActiMode.AC_MODE_NONE
        t1 = model.dense(t1, h, act, use_bias=False)
        t2 = model.dense(t2, h, act, use_bias=False)
    t = model.add(t1, t2)
    t = model.softmax(t)
    return [in1, in2], t


def build_xdl(model: FFModel, batch_size: int,
              embedding_sizes: Sequence[int] = (1000000,) * 4,
              sparse_feature_size: int = 64,
              dense_dim: int = 16,
              mlp_dims=(256, 128, 2)):
    """reference: xdl.cc — embeddings concat + MLP."""
    sparse = [
        model.create_tensor((batch_size, 1), DataType.DT_INT32)
        for _ in embedding_sizes
    ]
    dense = model.create_tensor((batch_size, dense_dim), DataType.DT_FLOAT)
    embs = [
        model.embedding(s, v, sparse_feature_size, AggrMode.AGGR_MODE_SUM)
        for s, v in zip(sparse, embedding_sizes)
    ]
    t = model.concat(embs + [dense], axis=-1)
    for i, d in enumerate(mlp_dims):
        act = (
            ActiMode.AC_MODE_RELU if i + 1 < len(mlp_dims) else ActiMode.AC_MODE_NONE
        )
        t = model.dense(t, d, act)
    t = model.softmax(t)
    return sparse + [dense], t


def build_candle_uno(model: FFModel, batch_size: int,
                     feature_shapes=(942, 5270, 2048),
                     dense_feature_layers=(1000, 1000, 1000),
                     dense_layers=(1000, 1000, 1000, 1000, 1000)):
    """reference: candle_uno.cc:51-130 — per-input feature towers, concat,
    deep regressor to a single output."""
    inputs = [
        model.create_tensor((batch_size, fs), DataType.DT_FLOAT)
        for fs in feature_shapes
    ]
    encoded = []
    for inp in inputs:
        t = inp
        for d in dense_feature_layers:
            t = model.dense(t, d, ActiMode.AC_MODE_RELU, use_bias=False)
        encoded.append(t)
    t = model.concat(encoded, axis=-1)
    for d in dense_layers:
        t = model.dense(t, d, ActiMode.AC_MODE_RELU, use_bias=False)
    out = model.dense(t, 1)
    return inputs, out


def build_moe(model: FFModel, batch_size: int, input_dim: int = 784,
              num_classes: int = 10, num_exp: int = 5, num_select: int = 2,
              hidden: int = 64, alpha: float = 2.0, lambda_bal: float = 0.04):
    """reference: moe.cc:20-44 — moe composite + classifier head."""
    input_t = model.create_tensor((batch_size, input_dim), DataType.DT_FLOAT)
    t = model.moe(input_t, num_exp, num_select, hidden, alpha, lambda_bal)
    t = model.dense(t, num_classes)
    t = model.softmax(t)
    return input_t, t


def build_bert_proxy(model: FFModel, batch_size: int, seq_length: int = 512,
                     hidden_size: int = 768, num_heads: int = 12,
                     num_layers: int = 12):
    """reference: examples/python/native/bert_proxy_native.py — encoder
    stack at BERT-Base shape."""
    input_t = model.create_tensor(
        (batch_size, seq_length, hidden_size), DataType.DT_FLOAT
    )
    t = input_t
    kdim = hidden_size // num_heads
    for _ in range(num_layers):
        t = create_attention_encoder(model, t, hidden_size, num_heads, kdim, kdim)
    return input_t, t
