"""Keras-style initializer classes (reference: python/flexflow/keras/
initializers.py:18-57 — DefaultInitializer/Zeros/GlorotUniform/
RandomUniform/RandomNormal).

These are thin aliases of the core initializers (core/initializers.py) with
the reference's Keras constructor signatures, accepted anywhere a layer takes
`kernel_initializer=`/`bias_initializer=`.
"""
from __future__ import annotations

from ...core.initializers import (
    GlorotUniformInitializer,
    Initializer,
    NormInitializer,
    UniformInitializer,
    ZeroInitializer,
)

__all__ = [
    "Initializer",
    "DefaultInitializer",
    "Zeros",
    "GlorotUniform",
    "RandomUniform",
    "RandomNormal",
]


class DefaultInitializer:
    """Marker: let the layer pick its default (reference initializers.py:26)."""

    def __repr__(self):
        return "DefaultInitializer()"


class Zeros(ZeroInitializer):
    def __init__(self):
        super().__init__()


class GlorotUniform(GlorotUniformInitializer):
    def __init__(self, seed: int = 0):
        super().__init__(seed=seed)


class RandomUniform(UniformInitializer):
    def __init__(self, seed: int = 0, minval: float = -0.05, maxval: float = 0.05):
        super().__init__(seed=seed, min_value=minval, max_value=maxval)


class RandomNormal(NormInitializer):
    def __init__(self, seed: int = 0, mean: float = 0.0, stddev: float = 0.05):
        super().__init__(seed=seed, mean=mean, stddev=stddev)
