"""LSTM seq2seq NMT — encoder/decoder with teacher forcing
(reference: nmt/ standalone CUDA implementation, SURVEY §1 layer 12).

Usage: python examples/python/nmt.py -b 32
"""
import sys

import numpy as np

sys.path.insert(0, ".")

from flexflow_tpu import FFConfig, FFModel, LossType, MetricsType, SGDOptimizer
from flexflow_tpu.models.nmt import build_nmt


def main():
    ffconfig = FFConfig()
    model = FFModel(ffconfig)
    src_vocab = tgt_vocab = 8000
    src_len = tgt_len = 32
    build_nmt(model, ffconfig.batch_size, src_vocab=src_vocab,
              tgt_vocab=tgt_vocab, src_len=src_len, tgt_len=tgt_len)
    model.compile(
        optimizer=SGDOptimizer(lr=0.1),
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY],
    )
    n = ffconfig.batch_size * 4
    rng = np.random.RandomState(0)
    src = rng.randint(0, src_vocab, (n, src_len)).astype(np.int32)
    tgt = rng.randint(0, tgt_vocab, (n, tgt_len)).astype(np.int32)
    labels = rng.randint(0, tgt_vocab, (n, tgt_len, 1)).astype(np.int32)
    model.fit([src, tgt], labels, epochs=ffconfig.epochs)


if __name__ == "__main__":
    main()
