"""Unity-search speedup vs pure data parallelism (the BASELINE.json
north-star's second metric; reference: scripts/osdi22ae/mlp.sh runs
MLP_Unify with --budget 20 vs --only-data-parallel and compares the
printed THROUGHPUT lines).

The comparison is made in the cost model (the reference's artifact
likewise steers by its simulator): for each OSDI'22 model config, cost
the best strategy the search finds against the best pure-DP strategy on
the same simulated machine. Wall-clock cannot substantiate this without
a real multi-chip slice — virtual CPU devices share host cores — so the
simulated ratio is the reported metric, exactly like
`--search-num-nodes/--search-num-workers` lets the reference search for
a machine it isn't running on.

    python benchmarks/unity_speedup.py [--nodes 1] [--workers 8]
"""
import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "..")))


def best_cost(graph, machine, xfers, budget):
    from flexflow_tpu.pcg.machine_view import MachineResource
    from flexflow_tpu.search import CostModel, GraphSearchHelper, SearchHelper

    sh = SearchHelper(CostModel(machine))
    gsh = GraphSearchHelper(sh, xfers, budget=budget)
    res = MachineResource(
        num_nodes=machine.num_nodes,
        all_procs_per_node=machine.workers_per_node,
        available_procs_per_node=machine.workers_per_node,
    )
    _, result = gsh.graph_optimize(graph, res)
    return result.cost


def run(name: str, build, machine, degrees, budget: int = 20):
    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.pcg.lowering import layers_to_pcg
    from flexflow_tpu.search import generate_all_pcg_xfers
    from flexflow_tpu.search.substitution import partition_batch

    cfg = FFConfig()
    model = FFModel(cfg)
    build(model)
    graph, _ = layers_to_pcg(model.layers)
    # pure DP: only sample-dim partition rewrites offered (the reference's
    # --only-data-parallel lowering, model.cc:2637)
    dp = best_cost(graph, machine, [partition_batch(d) for d in degrees],
                   budget=len(degrees) + 1)
    unity = best_cost(graph, machine, generate_all_pcg_xfers(degrees, cfg),
                      budget=budget)
    rec = {
        "config": name,
        "sim_dp_ms": round(dp * 1e3, 3),
        "sim_unity_ms": round(unity * 1e3, 3),
        "speedup": round(dp / unity, 3) if unity > 0 else None,
    }
    print(json.dumps(rec), flush=True)
    return rec["speedup"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=1)
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--skip-inception", action="store_true")
    ap.add_argument("--machine-model-file", default="",
                    help="machine description file (e.g. "
                         "machine_config_v5e32 — selects the topology-"
                         "aware model with its torus/DCN/congestion "
                         "knobs); overrides --nodes/--workers")
    ap.add_argument("--budget", type=int, default=20)
    args = ap.parse_args()

    from flexflow_tpu.models.dlrm import build_dlrm
    from flexflow_tpu.models.misc import build_mlp_unify
    from flexflow_tpu.models.transformer import build_transformer
    from flexflow_tpu.search import MachineModel, parse_machine_config

    if args.machine_model_file:
        machine = parse_machine_config(args.machine_model_file)
        args.nodes = machine.num_nodes
        args.workers = machine.workers_per_node
    else:
        machine = MachineModel(num_nodes=args.nodes,
                               workers_per_node=args.workers)
    n = args.nodes * args.workers
    degrees = []
    d = 2
    while d <= n:
        degrees.append(d)
        d *= 2

    from flexflow_tpu.models.inception import build_inception_v3
    from flexflow_tpu.models.misc import build_candle_uno, build_xdl
    from flexflow_tpu.models.resnet import build_resnext50

    # all seven OSDI'22 artifact configs (scripts/osdi22ae/*.sh)
    speedups = []
    speedups.append(run(
        "mlp_unify_b2048",
        lambda m: build_mlp_unify(m, 2048), machine, degrees, budget=args.budget))
    speedups.append(run(
        "transformer_b64",
        lambda m: build_transformer(m, batch_size=64), machine, degrees, budget=args.budget))
    speedups.append(run(
        "dlrm_b2048",
        lambda m: build_dlrm(m, 2048), machine, degrees, budget=args.budget))
    # the conv giants run at the reference's artifact budget
    # (scripts/osdi22ae/{resnext-50,inception}.sh: --budget 20) — the
    # sink-converge diamond decomposition + degree-1 view collapse in
    # dp_search brought a full Inception search under 2 min on this host
    speedups.append(run(
        "resnext50_b16",
        lambda m: build_resnext50(m, 16), machine, degrees, budget=args.budget))
    if not args.skip_inception:
        speedups.append(run(
            "inception_b64",
            lambda m: build_inception_v3(m, 64), machine, degrees, budget=args.budget))
    speedups.append(run(
        "candle_uno_b64",
        lambda m: build_candle_uno(m, 64), machine, degrees, budget=args.budget))
    speedups.append(run(
        "xdl_b1024",
        lambda m: build_xdl(m, 1024), machine, degrees, budget=args.budget))
    valid = [s for s in speedups if s]
    print(json.dumps({
        "metric": "unity_sim_speedup_vs_dp_geomean",
        "value": round(math.prod(valid) ** (1.0 / len(valid)), 3)
        if valid else None,
        "unit": "x",
        "machine": {"nodes": args.nodes, "workers": args.workers},
    }), flush=True)


if __name__ == "__main__":
    main()
