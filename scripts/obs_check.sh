#!/usr/bin/env bash
# Telemetry end-to-end check (docs/observability.md): train a small model
# with telemetry on, then assert every artifact exists, parses, and
# covers search + steps + at least one checkpoint event. Runs on the
# virtual CPU mesh; CI wires it into the lint workflow.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu
export JAX_NUM_CPU_DEVICES="${JAX_NUM_CPU_DEVICES:-4}"
# jax<0.5 ignores JAX_NUM_CPU_DEVICES; the XLA flag is what actually
# multiplies the host platform (same fallback as tests/conftest.py)
case "${XLA_FLAGS:-}" in *xla_force_host_platform_device_count*) ;; *)
    export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=$JAX_NUM_CPU_DEVICES"
;; esac
TELDIR="$(mktemp -d)"
trap 'rm -rf "$TELDIR"' EXIT

python - "$TELDIR" <<'EOF'
import os
import sys

import numpy as np

from flexflow_tpu import (
    ActiMode, DataType, FFConfig, FFModel, LossType, MetricsType,
    SGDOptimizer, TelemetryConfig,
)

teldir = sys.argv[1]
cfg = FFConfig()
cfg.batch_size = 8
cfg.search_budget = 3  # exercise the Unity search so its events show up
m = FFModel(cfg)
x = m.create_tensor((8, 8), DataType.DT_FLOAT)
t = m.dense(x, 32, ActiMode.AC_MODE_RELU)
t = m.softmax(m.dense(t, 3))
m.compile(SGDOptimizer(lr=0.1),
          LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
          [MetricsType.METRICS_ACCURACY])
rng = np.random.RandomState(0)
X = rng.randn(32, 8).astype(np.float32)
Y = rng.randint(0, 3, (32, 1)).astype(np.int32)
m.fit(X, Y, batch_size=8, epochs=2, verbose=False,
      checkpoint_dir=os.path.join(teldir, "ckpt"),
      telemetry=TelemetryConfig(dir=os.path.join(teldir, "tel"),
                                sync_per_step=True))
EOF

TEL="$TELDIR/tel"
for f in events.jsonl metrics.prom metrics.jsonl trace.json; do
    [ -s "$TEL/$f" ] || { echo "obs_check: missing artifact $f"; exit 1; }
done

python - "$TEL" <<'EOF'
import json
import sys

from flexflow_tpu.obs.metrics import parse_prometheus
from flexflow_tpu.obs.tracer import read_events_jsonl

tel = sys.argv[1]
events, problems = read_events_jsonl(f"{tel}/events.jsonl")
assert not problems, f"schema violations: {problems[:5]}"
names = {e["name"] for e in events}
cats = {e["cat"] for e in events}
assert "search" in cats, f"no search events (cats={cats})"
assert "step" in names, "no per-step events"
assert "checkpoint_save" in names, "no checkpoint events"
series = parse_prometheus(open(f"{tel}/metrics.prom").read())
assert series["ff_steps_total"] == 8.0, series.get("ff_steps_total")
assert series["ff_checkpoint_saves_total"] >= 1.0
trace = json.load(open(f"{tel}/trace.json"))
assert len(trace["traceEvents"]) > 10
print(f"obs_check: {len(events)} events, "
      f"{len(series)} metric series, "
      f"{len(trace['traceEvents'])} trace entries — OK")
EOF

# the CLI must round-trip the same artifacts
python -m flexflow_tpu.obs summary "$TEL/events.jsonl" >/dev/null
python -m flexflow_tpu.obs trace "$TEL/events.jsonl" -o "$TELDIR/t.json"
python -m flexflow_tpu.obs prom "$TEL/metrics.jsonl" >/dev/null
echo "obs_check: CLI OK"

# request flight recorder: a short traced serving run (no kill — the
# failover leg lives in serving_check.sh / tests); load_check's own
# criterion 4 validates the trace schema + lifecycle coverage
REQTEL="$TELDIR/reqtel"
python scripts/load_check.py --no-kill --replicas 1 --slots 2 \
    --warm-s 2 --ramp-s 2 --post-s 1 --base-rate 4 --ramp 3 \
    --search-budget 1 --layers 1 \
    --telemetry-dir "$REQTEL" --request-sample-rate 1.0 \
    --json "$TELDIR/load.json" >/dev/null
python -m flexflow_tpu.obs requests "$REQTEL/events.jsonl" --slowest 3 \
    >/dev/null
echo "obs_check: request tracing OK"

# calibration store: explain -> apply persists; a FRESH process loads
# the store through compile(calibration=...) without re-profiling and
# prices serial-view ops from the measurement
CALIB="$TELDIR/calib.json"
python - "$CALIB" <<'EOF'
import sys

import flexflow_tpu.obs as obs
from flexflow_tpu import (
    ActiMode, DataType, FFConfig, FFModel, LossType, MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.obs.calibration import CalibrationStore


def model():
    cfg = FFConfig()
    cfg.batch_size = 8
    m = FFModel(cfg)
    x = m.create_tensor((8, 8), DataType.DT_FLOAT)
    t = m.dense(x, 32, ActiMode.AC_MODE_RELU)
    t = m.softmax(m.dense(t, 3))
    m.compile(SGDOptimizer(lr=0.1),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
              [MetricsType.METRICS_ACCURACY])
    return m


ex = obs.explain_strategy(model(), repeats=1, warmup=1)
n = ex.apply(model(), store=CalibrationStore(sys.argv[1]))
assert n > 0, "explain produced no measured rows"
print(f"obs_check: calibration store saved ({n} ops)")
EOF
python - "$CALIB" <<'EOF'
import sys

from flexflow_tpu import (
    ActiMode, DataType, FFConfig, FFModel, LossType, MetricsType,
    SGDOptimizer,
)

cfg = FFConfig()
cfg.batch_size = 8
m = FFModel(cfg)
x = m.create_tensor((8, 8), DataType.DT_FLOAT)
t = m.dense(x, 32, ActiMode.AC_MODE_RELU)
t = m.softmax(m.dense(t, 3))
m.compile(SGDOptimizer(lr=0.1),
          LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
          [MetricsType.METRICS_ACCURACY], calibration=sys.argv[1])
cm = m._build_cost_model()
assert cm.calibration_source == sys.argv[1], cm.calibration_source
from flexflow_tpu.pcg.machine_view import MachineView

v1 = MachineView(start_device_id=0, dim=(1,), stride=(1,))
op = next(o for o in m.graph.ops if not o.is_parallel_op)
cm.measure_operator_cost(op, v1)
assert cm.measured_hits >= 1, "calibrated op not priced from measurement"
print("obs_check: fresh-process calibration load OK")
EOF
python -m flexflow_tpu.obs calibrate inspect "$CALIB" >/dev/null
echo "obs_check: calibration round-trip OK"

# step observatory: ONE fit(telemetry=) run captures the measured step
# timeline, overlays it on the simulated schedule in a single Perfetto
# file, exports the realization/HBM gauges + counter tracks, and writes
# the measured overlap efficiency into the calibration store so a FRESH
# process prices overlap from reality
SPTEL="$TELDIR/sptel"
SPCAL="$TELDIR/step_calib.json"
python - "$SPTEL" "$SPCAL" <<'EOF'
import json
import sys

import numpy as np

from flexflow_tpu import (
    ActiMode, DataType, FFConfig, FFModel, LossType, MetricsType,
    SGDOptimizer, TelemetryConfig,
)
from flexflow_tpu.obs.metrics import parse_prometheus
from flexflow_tpu.obs.tracer import read_events_jsonl

teldir, calib = sys.argv[1], sys.argv[2]
cfg = FFConfig()
cfg.batch_size = 8  # manual lowering (no search) -> data degree = ndev
m = FFModel(cfg)
x = m.create_tensor((8, 8), DataType.DT_FLOAT)
t = m.dense(x, 32, ActiMode.AC_MODE_RELU)
t = m.softmax(m.dense(t, 3))
m.compile(SGDOptimizer(lr=0.1),
          LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
          [MetricsType.METRICS_ACCURACY])
rng = np.random.RandomState(0)
X = rng.randn(32, 8).astype(np.float32)
Y = rng.randint(0, 3, (32, 1)).astype(np.int32)
m.fit(X, Y, batch_size=8, epochs=1, verbose=False,
      telemetry=TelemetryConfig(dir=teldir, step_profile=True,
                                calibration_path=calib))

events, problems = read_events_jsonl(f"{teldir}/events.jsonl")
assert not problems, f"schema violations: {problems[:5]}"
counters = [e for e in events if e["ph"] == "C"]
assert counters, "no ph='C' counter events (hbm_bytes tracks missing)"
overlay = json.load(open(f"{teldir}/step_timeline.json"))
pids = {e["args"]["name"] for e in overlay["traceEvents"]
        if e.get("ph") == "M"}
assert {"simulated", "measured"} <= pids, f"overlay process groups: {pids}"
assert min(e["ts"] for e in overlay["traceEvents"] if "ts" in e) == 0.0
series = parse_prometheus(open(f"{teldir}/metrics.prom").read())
assert "ff_overlap_realized_ratio" in series, sorted(series)
hbm = [k for k in series if k.startswith("ff_hbm_peak_bytes")]
assert hbm, "no ff_hbm_peak_bytes gauges"
assert "ff_hbm_static_accuracy" in series, sorted(series)
glb = json.load(open(calib)).get("globals", {})
assert "overlap_efficiency" in glb, glb
assert glb.get("collective_bytes_per_s"), glb
print(f"obs_check: step observatory OK ({len(counters)} counter events, "
      f"{len(hbm)} HBM gauges, realized="
      f"{series['ff_overlap_realized_ratio']:.2f})")
EOF
python - "$SPCAL" <<'EOF'
import sys

from flexflow_tpu import (
    ActiMode, DataType, FFConfig, FFModel, LossType, MetricsType,
    SGDOptimizer,
)

cfg = FFConfig()
cfg.batch_size = 8
m = FFModel(cfg)
x = m.create_tensor((8, 8), DataType.DT_FLOAT)
t = m.dense(x, 32, ActiMode.AC_MODE_RELU)
t = m.softmax(m.dense(t, 3))
m.compile(SGDOptimizer(lr=0.1),
          LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
          [MetricsType.METRICS_ACCURACY], calibration=sys.argv[1])
prov = m._build_cost_model().provenance()
assert prov["overlap_efficiency_source"] == "calibration_store", prov
assert prov["collective_bytes_per_s"], prov
print("obs_check: measured overlap calibration feeds a fresh compile OK")
EOF
echo "obs_check: step observatory round-trip OK"
