"""PCG operator node.

TPU-native equivalent of the reference `Op` base (include/flexflow/operator.h:
51-277). The reference Op owns Legion launch plumbing (init/forward/backward
IndexLaunchers, OpMeta per device); here an Op is a pure IR node — params +
ParallelTensor inputs/outputs/weights + MachineView — and execution is
delegated to the registered forward fn under the PCG executor. Backward
derives from jax.grad, so there is no backward plumbing at all.

ParallelDimMappingRecord equivalent: sharding propagation input→output/weight
is implemented per-op in `propagate_sharding` handlers
(parallel/propagation.py), mirroring operator.h:22-49.
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional

from ..ff_types import OperatorType, PARALLEL_OP_TYPES
from .machine_view import MachineView
from .parallel_tensor import ParallelTensor

_op_guid = itertools.count(2000000)


class PCGOp:
    """A node in the parallel computation graph."""

    def __init__(
        self,
        op_type: OperatorType,
        params,
        inputs: List[ParallelTensor],
        name: str = "",
        layer_guid: int = -1,
    ):
        self.guid: int = next(_op_guid)
        self.op_type = op_type
        self.params = params
        self.name = name or f"{op_type.name.lower()}_{self.guid}"
        self.inputs: List[ParallelTensor] = list(inputs)
        self.outputs: List[ParallelTensor] = []
        self.weights: List[ParallelTensor] = []
        self.weight_names: List[str] = []
        self.machine_view: Optional[MachineView] = None
        self.layer_guid = layer_guid
        # initializer per weight name (resolved at executor init)
        self.initializers: Dict[str, object] = {}

    @property
    def is_parallel_op(self) -> bool:
        return self.op_type in PARALLEL_OP_TYPES

    def get_params_key(self):
        """Hashable identity for node dedup (reference: model.h:678-706
        get_or_create_node keyed on Params hash)."""
        return (self.op_type, self.params, tuple(t.get_shape() for t in self.inputs))

    def __repr__(self):
        return f"PCGOp({self.name})"
