"""Tests for graph utils, memory-aware search, LSTM/NMT, and serving —
mirroring reference tests/unit (dominators, disjoint_set) plus coverage of
the new subsystems."""
import numpy as np
import pytest

from flexflow_tpu import (
    ActiMode,
    DataType,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)


# -- graph utils (reference: tests/unit/test_disjoint_set.cc, test_dominators.cc)

def test_disjoint_set():
    from flexflow_tpu.utils.graph_utils import DisjointSet

    ds = DisjointSet()
    ds.union(1, 2)
    ds.union(3, 4)
    assert ds.same(1, 2) and ds.same(3, 4)
    assert not ds.same(1, 3)
    ds.union(2, 3)
    assert ds.same(1, 4)
    assert len(ds.groups()) == 1


def test_dominators_diamond():
    from flexflow_tpu.utils.graph_utils import dominators, imm_dominator

    #    a -> b -> d
    #    a -> c -> d
    edges = {"a": ["b", "c"], "b": ["d"], "c": ["d"], "d": []}
    dom = dominators(["a", "b", "c", "d"], edges, "a")
    assert dom["d"] == {"a", "d"}  # neither b nor c dominates d
    assert dom["b"] == {"a", "b"}
    topo = {"a": 0, "b": 1, "c": 2, "d": 3}
    assert imm_dominator(dom, "d", topo) == "a"


def test_transitive_reduction():
    from flexflow_tpu.utils.graph_utils import transitive_reduction

    edges = {("a", "b"), ("b", "c"), ("a", "c")}
    red = transitive_reduction(["a", "b", "c"], edges)
    assert red == {("a", "b"), ("b", "c")}


# -- memory-aware search ----------------------------------------------------

def test_memory_search_fits_budget():
    from flexflow_tpu.pcg.lowering import layers_to_pcg
    from flexflow_tpu.pcg.machine_view import MachineResource
    from flexflow_tpu.search import CostModel, MachineModel, generate_all_pcg_xfers
    from flexflow_tpu.search.memory_optimization import (
        graph_optimize_with_memory,
        measure_memory,
    )

    model = FFModel(FFConfig())
    x = model.create_tensor((1024, 1024), DataType.DT_FLOAT)
    t = model.dense(x, 8192, ActiMode.AC_MODE_RELU)
    t = model.dense(t, 1024)
    graph, _ = layers_to_pcg(model.layers)
    machine = MachineModel(num_nodes=1, workers_per_node=4)
    cm = CostModel(machine)
    res = MachineResource(num_nodes=1, all_procs_per_node=4,
                          available_procs_per_node=4)
    # generous budget: plain search result already fits
    g, r, mem, lam = graph_optimize_with_memory(
        graph, cm, res, generate_all_pcg_xfers([2, 4]),
        device_mem_budget=1 << 40, budget=4,
    )
    assert lam == 0.0
    assert mem.max_bytes <= 1 << 40
    # tight budget forces a memory-aware (sharded) strategy
    serial_mem = measure_memory(
        g, r.views, cm
    ).max_bytes
    tight = max(1, serial_mem // 2)
    g2, r2, mem2, lam2 = graph_optimize_with_memory(
        graph, cm, res, generate_all_pcg_xfers([2, 4]),
        device_mem_budget=tight, budget=4, lambda_iters=4,
    )
    assert mem2.max_bytes <= serial_mem  # at least no worse


# -- LSTM / NMT -------------------------------------------------------------

def test_lstm_matches_torch():
    torch = pytest.importorskip("torch")
    import jax.numpy as jnp

    from flexflow_tpu.ff_types import OperatorType
    from flexflow_tpu.ops import FwdCtx, get_op_def
    from flexflow_tpu.ops.lstm import LSTMParams

    rng = np.random.RandomState(0)
    b, s, f, h = 2, 5, 4, 6
    x = rng.randn(b, s, f).astype(np.float32)
    p = LSTMParams(hidden_size=h)
    d = get_op_def(OperatorType.OP_LSTM)

    tl = torch.nn.LSTM(f, h, batch_first=True, bias=True)
    # torch packs (w_ih: (4h, f)) in gate order i,f,g,o — ours matches
    wx = tl.weight_ih_l0.detach().numpy().T  # (f, 4h)
    wh = tl.weight_hh_l0.detach().numpy().T  # (h, 4h)
    bias = (tl.bias_ih_l0 + tl.bias_hh_l0).detach().numpy()
    weights = {"wx": jnp.asarray(wx), "wh": jnp.asarray(wh),
               "bias": jnp.asarray(bias)}
    (ours,) = d.forward(p, weights, [jnp.asarray(x)], FwdCtx(training=False))
    with torch.no_grad():
        theirs, _ = tl(torch.from_numpy(x))
    np.testing.assert_allclose(np.asarray(ours), theirs.numpy(), atol=1e-5)


def test_nmt_trains():
    from flexflow_tpu.models.nmt import build_nmt

    cfg = FFConfig()
    cfg.batch_size = 4
    model = FFModel(cfg)
    build_nmt(model, 4, src_vocab=50, tgt_vocab=50, src_len=6, tgt_len=6,
              embed_dim=8, hidden=16, num_layers=1)
    model.compile(SGDOptimizer(lr=0.1),
                  LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  [MetricsType.METRICS_ACCURACY])
    rng = np.random.RandomState(0)
    src = rng.randint(0, 50, (16, 6)).astype(np.int32)
    tgt = rng.randint(0, 50, (16, 6)).astype(np.int32)
    labels = rng.randint(0, 50, (16, 6, 1)).astype(np.int32)
    pm = model.fit([src, tgt], labels, batch_size=4, epochs=1, verbose=False)
    assert pm.train_all == 16


# -- serving ---------------------------------------------------------------

def test_batch_scheduler_serves():
    from flexflow_tpu.runtime.serving import BatchScheduler

    cfg = FFConfig()
    cfg.batch_size = 4
    model = FFModel(cfg)
    x = model.create_tensor((4, 8), DataType.DT_FLOAT)
    t = model.dense(x, 3)
    t = model.softmax(t)
    model.compile(SGDOptimizer(lr=0.0),
                  LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])
    sched = BatchScheduler(model, max_delay_s=0.01).start()
    try:
        rng = np.random.RandomState(0)
        samples = [rng.randn(8).astype(np.float32) for _ in range(10)]
        results = [sched.infer([s]) for s in samples]
        # results match direct batched predict
        direct = model.predict(np.stack(samples), batch_size=4)
        for r, d in zip(results, direct):
            np.testing.assert_allclose(r, d, atol=1e-5)
        assert sched.stats["requests"] == 10
    finally:
        sched.stop()
