"""Telemetry artifact CLI.

Usage:
    python -m flexflow_tpu.obs trace    <events.jsonl> [-o trace.json]
    python -m flexflow_tpu.obs summary  <events.jsonl>
    python -m flexflow_tpu.obs prom     <metrics.jsonl> [-o metrics.prom]
    python -m flexflow_tpu.obs requests <events.jsonl> [--slowest K]
    python -m flexflow_tpu.obs explain  [--top N] [model shape flags]
    python -m flexflow_tpu.obs calibrate inspect <store.json>
    python -m flexflow_tpu.obs calibrate prune   <store.json> --max-age-h H
    python -m flexflow_tpu.obs calibrate diff    <a.json> <b.json>

``trace`` converts a structured event log to Chrome-trace JSON (open at
https://ui.perfetto.dev). ``summary`` schema-validates the log and
prints per-category/event counts plus step/search aggregates.
``prom`` re-renders the last metrics.jsonl snapshot as Prometheus text.
``requests`` reconstructs per-request lifecycles from the serving
flight recorder's events (cat "requests"): stage breakdown, top-K
slowest, shed and requeue causes. ``explain`` compiles the benchmark
Transformer (CPU-sized by default; pass --seq/--hidden/... for the real
bench shape on a TPU host), joins the cost model against on-device
profile_ops measurements and prints the miscalibrated-op kernel
worklist — each perf round starts from this list (docs/performance.md).
``calibrate`` inspects/maintains a persistent cost-model calibration
store (obs/calibration.py).

This module is a CLI entry point: bare print() is its job (fflint FFL201
allowlists __main__ modules).
"""
from __future__ import annotations

import argparse
import json
import sys
from collections import Counter

from .tracer import lanes_from_events, read_events_jsonl, to_chrome_trace


def _cmd_trace(args) -> int:
    events, problems = read_events_jsonl(args.events)
    for p in problems:
        print(f"warning: {p}", file=sys.stderr)
    out = args.output or "trace.json"
    with open(out, "w") as f:
        json.dump(to_chrome_trace(events,
                                  lane_names=lanes_from_events(events)), f)
    print(f"wrote {out}: {len(events)} event(s) "
          f"({len(problems)} malformed line(s) skipped)")
    return 0


def _cmd_summary(args) -> int:
    events, problems = read_events_jsonl(args.events)
    if problems:
        for p in problems:
            print(f"schema: {p}", file=sys.stderr)
    by_name = Counter((e["cat"], e["name"]) for e in events)
    print(f"{args.events}: {len(events)} event(s), "
          f"{len(problems)} malformed line(s)")
    for (cat, name), n in sorted(by_name.items()):
        print(f"  {cat:<12} {name:<24} {n}")
    steps = [e for e in events
             if e["name"] == "step" and e["ph"] == "X"]
    if steps:
        total = sum(e["dur"] for e in steps)
        print(f"steps: {len(steps)}, total {total:.3f}s, "
              f"mean {total / len(steps) * 1e3:.2f}ms")
    mcmc = [e for e in events if e["name"] == "mcmc_iter"]
    if mcmc:
        acc = sum(1 for e in mcmc if e.get("args", {}).get("accept"))
        print(f"mcmc: {len(mcmc)} proposal(s), {acc} accepted "
              f"({100.0 * acc / len(mcmc):.1f}%)")
    cands = [e for e in events if e["name"] == "xfer_candidate"]
    if cands:
        best = sum(1 for e in cands if e.get("args", {}).get("best"))
        print(f"substitutions: {len(cands)} candidate(s), "
              f"{best} improved the best strategy")
    return 1 if problems else 0


def _cmd_prom(args) -> int:
    from .metrics import MetricsRegistry

    reg = MetricsRegistry()
    with open(args.metrics) as f:
        records = [json.loads(line) for line in f if line.strip()]
    # keep only the newest snapshot per (name, labels)
    latest = {}
    for r in records:
        latest[(r["name"], tuple(sorted(r["labels"].items())))] = r
    for r in latest.values():
        labels = dict(r["labels"])
        if r["kind"] == "counter":
            reg.counter(r["name"], **labels).inc(r["value"])
        elif r["kind"] == "gauge":
            reg.gauge(r["name"], **labels).set(r["value"])
        else:  # histogram snapshots only carry aggregates; re-emit sum
            h = reg.histogram(r["name"], **labels)
            h.sum, h.count = r.get("sum", 0.0), r.get("count", 0)
    text = reg.to_prometheus()
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
        print(f"wrote {args.output}")
    else:
        print(text, end="")
    return 0


def _cmd_requests(args) -> int:
    from .request_trace import REQUEST_CAT

    events, problems = read_events_jsonl(args.events)
    for p in problems:
        print(f"warning: {p}", file=sys.stderr)
    lanes = {tid: name for (cat, name), tid
             in lanes_from_events(events).items() if cat == REQUEST_CAT}
    reqs: dict = {}
    for e in events:
        if e.get("cat") != REQUEST_CAT:
            continue
        rid = e.get("args", {}).get("request")
        if rid is None:
            continue  # lane metadata etc.
        reqs.setdefault(rid, []).append(e)
    if not reqs:
        print(f"{args.events}: no request events (cat={REQUEST_CAT!r}); "
              "was the session started with request_sample_rate > 0?")
        return 1
    rows = []
    shed_causes: Counter = Counter()
    requeues = 0
    for rid, evs in reqs.items():
        stages = {"queue": 0.0, "prefill": 0.0, "decode": 0.0}
        replicas = set()
        sheds = []
        gens = []
        tokens = None
        done = False
        for e in evs:
            name, a = e["name"], e.get("args", {})
            if e["ph"] == "X" and name in stages:
                stages[name] += float(e.get("dur", 0.0))
            if name == "shed":
                sheds.append((a.get("reason"), a.get("stage")))
                shed_causes[a.get("reason")] += 1
            elif name == "requeue":
                gens.append(a.get("generation"))
            elif name == "complete":
                done = True
                tokens = a.get("tokens")
            tid = int(e.get("tid", 0))
            if tid in lanes and lanes[tid] != "admission":
                replicas.add(lanes[tid])
        requeues += len(gens)
        ts = [float(e["ts"]) for e in evs]
        spans = [float(e["ts"]) + float(e.get("dur", 0.0)) for e in evs]
        rows.append({
            "request": rid, "total_s": max(spans) - min(ts),
            "stages": stages, "replicas": sorted(replicas),
            "sheds": sheds, "requeue_generations": gens,
            "completed": done, "tokens": tokens,
        })
    rows.sort(key=lambda r: r["total_s"], reverse=True)
    n_done = sum(1 for r in rows if r["completed"])
    print(f"{args.events}: {len(rows)} traced request(s), "
          f"{n_done} completed, {requeues} requeue(s), "
          f"{sum(shed_causes.values())} shed(s)")
    if shed_causes:
        print("  shed causes: " + ", ".join(
            f"{k}={v}" for k, v in shed_causes.most_common()))
    k = max(1, args.slowest)
    print(f"slowest {min(k, len(rows))} (stage seconds):")
    print(f"  {'request':<14} {'total':>8} {'queue':>8} {'prefill':>8} "
          f"{'decode':>8}  outcome")
    for r in rows[:k]:
        st = r["stages"]
        if r["completed"]:
            outcome = f"completed tokens={r['tokens']}"
        elif r["sheds"]:
            reason, stage = r["sheds"][-1]
            outcome = f"shed {reason}@{stage}"
        else:
            outcome = "in flight"
        if r["requeue_generations"]:
            outcome += (f" (requeued x{len(r['requeue_generations'])}"
                        f" gen={r['requeue_generations']})")
        if r["replicas"]:
            outcome += " on " + ",".join(r["replicas"])
        print(f"  {r['request'][:14]:<14} {r['total_s']:>8.4f} "
              f"{st['queue']:>8.4f} {st['prefill']:>8.4f} "
              f"{st['decode']:>8.4f}  {outcome}")
    return 0


def _cmd_calibrate(args) -> int:
    from .calibration import DEFAULT_MAX_AGE_S, CalibrationStore

    if args.action == "inspect":
        store = CalibrationStore(args.store)
        s = store.summary()
        print(json.dumps(s, indent=2, sort_keys=True, default=str))
        bad = store.problems(max_age_s=args.max_age_h * 3600.0
                             if args.max_age_h else DEFAULT_MAX_AGE_S)
        if bad:
            print("unusable for THIS process:", file=sys.stderr)
            for b in bad:
                print(f"  - {b}", file=sys.stderr)
            return 1
        print("usable: fingerprint/backend match, entries fresh")
        return 0
    if args.action == "prune":
        store = CalibrationStore(args.store)
        if args.max_age_h is None:
            print("prune: --max-age-h is required", file=sys.stderr)
            return 2
        n = store.prune(args.max_age_h * 3600.0)
        if n:
            store.save()
        print(f"pruned {n} entr{'y' if n == 1 else 'ies'}; "
              f"{len(store.ops)} remain")
        return 0
    # diff
    a, b = CalibrationStore(args.store), CalibrationStore(args.other)
    delta = a.diff(b)
    if not delta:
        print("stores agree on every shared key")
        return 0
    for d in delta:
        if d["status"] == "changed":
            print(f"  ~ {d['op_type']:<22} x{d['ratio']:.3f} "
                  f"({d['total_s_a'] * 1e3:.4f} -> "
                  f"{d['total_s_b'] * 1e3:.4f} ms)  {d['key'][:60]}")
        else:
            side = "a only" if d["status"] == "only_in_a" else "b only"
            print(f"  {side:>8}: {d['op_type']:<22} {d['key'][:60]}")
    print(f"{len(delta)} difference(s)")
    return 0


def _cmd_explain(args) -> int:
    from .. import (
        FFConfig,
        FFModel,
        LossType,
        MetricsType,
        SGDOptimizer,
    )
    from ..models.transformer import build_transformer
    from .explain import explain_strategy

    cfg = FFConfig()
    cfg.batch_size = args.batch
    cfg.allow_mixed_precision = args.bf16
    model = FFModel(cfg)
    build_transformer(
        model, batch_size=args.batch, seq_length=args.seq,
        hidden_size=args.hidden, num_heads=args.heads,
        num_layers=args.layers,
    )
    model.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR],
    )
    exp = explain_strategy(model, repeats=args.repeats)
    print(exp.summary(args.top))
    print(f"kernel worklist (top {args.top} by |simulated - measured|):")
    for w in exp.worklist(args.top):
        verdict = ("cost model optimistic — fuse/speed up this kernel"
                   if w["ratio"] > 1.0 else
                   "cost model pessimistic — recalibrate this class")
        print(f"  #{w['rank']} {w['name']} [{w['op_type']}] "
              f"meas {w['meas_total_s'] * 1e3:.4f} ms vs "
              f"sim {w['sim_total_s'] * 1e3:.4f} ms "
              f"(x{w['ratio']:.2f}) — {verdict}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m flexflow_tpu.obs",
        description=__doc__.split("\n\n")[0],
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    t = sub.add_parser("trace", help="events.jsonl -> Chrome/Perfetto trace")
    t.add_argument("events")
    t.add_argument("-o", "--output")
    s = sub.add_parser("summary", help="validate + summarize an event log")
    s.add_argument("events")
    m = sub.add_parser("prom", help="metrics.jsonl -> Prometheus text")
    m.add_argument("metrics")
    m.add_argument("-o", "--output")
    r = sub.add_parser(
        "requests",
        help="per-request stage breakdown + slowest/shed/requeue report "
             "from the serving flight recorder's events",
    )
    r.add_argument("events")
    r.add_argument("--slowest", type=int, default=10,
                   help="how many slowest requests to detail")
    c = sub.add_parser(
        "calibrate",
        help="inspect/prune/diff a persistent cost-model calibration "
             "store (obs/calibration.py)",
    )
    c.add_argument("action", choices=("inspect", "prune", "diff"))
    c.add_argument("store", help="calibration store JSON path")
    c.add_argument("other", nargs="?",
                   help="second store (diff only)")
    c.add_argument("--max-age-h", type=float, default=None,
                   help="staleness horizon in hours (inspect verdict / "
                        "prune cutoff)")
    e = sub.add_parser(
        "explain",
        help="print the miscalibrated-op kernel worklist for the "
             "benchmark Transformer on this host's device",
    )
    e.add_argument("--top", type=int, default=3)
    e.add_argument("--batch", type=int, default=2)
    e.add_argument("--seq", type=int, default=64)
    e.add_argument("--hidden", type=int, default=128)
    e.add_argument("--heads", type=int, default=4)
    e.add_argument("--layers", type=int, default=2)
    e.add_argument("--repeats", type=int, default=1)
    e.add_argument("--bf16", action="store_true")
    args = p.parse_args(argv)
    if args.cmd == "calibrate" and args.action == "diff" \
            and not args.other:
        p.error("calibrate diff needs two store paths")
    return {"trace": _cmd_trace, "summary": _cmd_summary,
            "prom": _cmd_prom, "requests": _cmd_requests,
            "calibrate": _cmd_calibrate,
            "explain": _cmd_explain}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
