"""Net2Net CIFAR-10 CNN: teacher weights seed the student (reference:
examples/python/keras/func_cifar10_cnn_net2net.py)."""
from flexflow.keras.models import Model
from flexflow.keras.layers import (
    Input, Conv2D, MaxPooling2D, Flatten, Dense, Activation)
import flexflow.keras.optimizers

from accuracy import ModelAccuracy
from _cifar import load_cifar
from _example_args import example_args, verify_callbacks


def build(num_classes):
    inp = Input(shape=(3, 32, 32))
    x = Conv2D(filters=32, kernel_size=(3, 3), strides=(1, 1), padding=(1, 1),
               activation="relu", name="conv1")(inp)
    x = Conv2D(filters=64, kernel_size=(3, 3), strides=(1, 1), padding=(1, 1),
               activation="relu", name="conv2")(x)
    x = MaxPooling2D(pool_size=(2, 2), strides=(2, 2), padding="valid")(x)
    x = Flatten()(x)
    x = Dense(256, activation="relu", name="dense1")(x)
    x = Dense(num_classes, name="dense2")(x)
    return Model(inp, Activation("softmax")(x))


def top_level_task(args):
    num_classes = 10
    x_train, y_train = load_cifar(args.num_samples)

    teacher = build(num_classes)
    teacher.compile(optimizer=flexflow.keras.optimizers.SGD(learning_rate=0.01),
                    loss="sparse_categorical_crossentropy",
                    metrics=["accuracy", "sparse_categorical_crossentropy"],
                    batch_size=args.batch_size)
    teacher.fit(x_train, y_train, epochs=args.epochs)

    weights = {
        name: teacher.get_layer(name=name).get_weights(teacher.ffmodel)
        for name in ("conv1", "conv2", "dense1", "dense2")
    }

    student = build(num_classes)
    student.compile(optimizer=flexflow.keras.optimizers.SGD(learning_rate=0.01),
                    loss="sparse_categorical_crossentropy",
                    metrics=["accuracy", "sparse_categorical_crossentropy"],
                    batch_size=args.batch_size)
    for name, w in weights.items():
        student.get_layer(name=name).set_weights(w)
    student.fit(x_train, y_train, epochs=args.epochs,
                callbacks=verify_callbacks(args, ModelAccuracy.CIFAR10_CNN))


if __name__ == "__main__":
    print("Functional API, cifar10 cnn net2net")
    top_level_task(example_args())
