"""Keras-compatible layer classes.

TPU-native equivalent of the reference Keras frontend's layer zoo
(python/flexflow/keras/layers/ — Conv2D, Dense, Embedding, pooling, merge,
normalization, etc., ~4.5k LoC total with base_layer.py). Layers are
deferred configs; calling one on a KerasTensor records an edge; Model build
replays the graph through FFModel methods (reference:
keras/models/base_model.py compile → _create_flexflow_layers).

Shapes are channels-first like the reference's Keras examples
(Input(shape=(3,32,32))), batch dim implicit until compile.
"""
from __future__ import annotations

import itertools
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ...ff_types import ActiMode, AggrMode, DataType, PoolType, to_data_type

_uid = itertools.count(1)


class KerasTensor:
    def __init__(self, shape: Tuple[int, ...], source_layer=None, source_idx: int = 0):
        self.shape = tuple(shape)  # without batch dim
        self.source_layer = source_layer
        self.source_idx = source_idx

    def __repr__(self):
        return f"KerasTensor{self.shape}"


class Layer:
    """Base deferred layer (reference: keras/layers/base_layer.py)."""

    def __init__(self, name: Optional[str] = None, input_shape=None, **kwargs):
        self.name = name or f"{type(self).__name__.lower()}_{next(_uid)}"
        self.inbound: List[KerasTensor] = []
        self.outputs: List[KerasTensor] = []
        # keras-style: first Sequential layer may declare its input shape
        self.input_shape = tuple(input_shape) if input_shape is not None else None
        self._ff_tensors = None  # set during model build

    def __call__(self, inputs):
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        self.inbound = list(ins)
        out_shapes = self.compute_output_shape([t.shape for t in ins])
        self.outputs = [
            KerasTensor(s, source_layer=self, source_idx=i)
            for i, s in enumerate(out_shapes)
        ]
        return self.outputs[0] if len(self.outputs) == 1 else self.outputs

    # subclass API ------------------------------------------------------
    def compute_output_shape(self, input_shapes) -> List[Tuple[int, ...]]:
        return [input_shapes[0]]

    def build_ff(self, ffmodel, ff_inputs):
        raise NotImplementedError

    # weight access (reference: keras layer get/set_weights)
    def get_weights(self, ffmodel=None):
        layer = self._ff_layer
        return [w.get_tensor(None) for w in layer.weights]

    def set_weights(self, weights):
        layer = self._ff_layer
        for wt, val in zip(layer.weights, weights):
            wt.set_tensor(None, np.asarray(val))


def Input(shape: Sequence[int], dtype=DataType.DT_FLOAT, name: str = "") -> KerasTensor:
    """reference: keras input_layer.Input (string dtypes accepted like keras)"""
    t = KerasTensor(tuple(shape), source_layer=None)
    t.dtype = to_data_type(dtype)
    return t


def _init_or_none(init):
    """Map keras initializer specs to core ones. `DefaultInitializer` (and
    the stock string defaults) mean "layer default" → None."""
    if init is None or type(init).__name__ == "DefaultInitializer":
        return None  # the layer's WeightSpec default (glorot kernel, zero bias)
    return init  # strings resolve via core get_initializer (_BY_NAME)


def _acti(activation) -> ActiMode:
    if activation in (None, "linear", "none"):
        return ActiMode.AC_MODE_NONE
    if isinstance(activation, ActiMode):
        return activation
    return {
        "relu": ActiMode.AC_MODE_RELU,
        "sigmoid": ActiMode.AC_MODE_SIGMOID,
        "tanh": ActiMode.AC_MODE_TANH,
        "gelu": ActiMode.AC_MODE_GELU,
        "softmax": "softmax",  # handled by Dense/Activation specially
    }[activation]


class Dense(Layer):
    def __init__(self, units: int, activation=None, use_bias=True,
                 kernel_initializer="glorot_uniform", bias_initializer="zeros",
                 kernel_regularizer=None, **kw):
        super().__init__(**kw)
        self.units = units
        self.activation = activation
        self.use_bias = use_bias
        self.kernel_initializer = kernel_initializer
        self.bias_initializer = bias_initializer
        self.kernel_regularizer = kernel_regularizer

    def compute_output_shape(self, shapes):
        return [tuple(shapes[0][:-1]) + (self.units,)]

    def build_ff(self, ffmodel, ff_inputs):
        act = self.activation
        softmax = act == "softmax"
        t = ffmodel.dense(
            ff_inputs[0],
            self.units,
            _acti(None if softmax else act),
            use_bias=self.use_bias,
            kernel_initializer=_init_or_none(self.kernel_initializer),
            bias_initializer=_init_or_none(self.bias_initializer),
            kernel_regularizer=self.kernel_regularizer,
            name=self.name,
        )
        if softmax:
            t = ffmodel.softmax(t)
        self._ff_layer = ffmodel.layers[-2] if softmax else ffmodel.layers[-1]
        return [t]


class Conv2D(Layer):
    def __init__(self, filters: int, kernel_size, strides=(1, 1), padding="valid",
                 activation=None, use_bias=True, groups=1, **kw):
        super().__init__(**kw)
        self.filters = filters
        self.kernel_size = (
            (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
        )
        self.strides = (strides, strides) if isinstance(strides, int) else tuple(strides)
        self.padding = padding
        self.activation = activation
        self.use_bias = use_bias
        self.groups = groups

    def _pads(self):
        if self.padding == "same":
            return self.kernel_size[0] // 2, self.kernel_size[1] // 2
        if self.padding == "valid":
            return 0, 0
        ph, pw = self.padding if isinstance(self.padding, tuple) else (self.padding,) * 2
        return ph, pw

    def compute_output_shape(self, shapes):
        c, h, w = shapes[0]
        ph, pw = self._pads()
        oh = (h + 2 * ph - self.kernel_size[0]) // self.strides[0] + 1
        ow = (w + 2 * pw - self.kernel_size[1]) // self.strides[1] + 1
        return [(self.filters, oh, ow)]

    def build_ff(self, ffmodel, ff_inputs):
        ph, pw = self._pads()
        t = ffmodel.conv2d(
            ff_inputs[0], self.filters,
            self.kernel_size[0], self.kernel_size[1],
            self.strides[0], self.strides[1], ph, pw,
            _acti(self.activation), groups=self.groups,
            use_bias=self.use_bias, name=self.name,
        )
        self._ff_layer = ffmodel.layers[-1]
        return [t]


class _Pool2D(Layer):
    pool_type = PoolType.POOL_MAX

    def __init__(self, pool_size=(2, 2), strides=None, padding="valid", **kw):
        super().__init__(**kw)
        self.pool_size = (
            (pool_size, pool_size) if isinstance(pool_size, int) else tuple(pool_size)
        )
        self.strides = (
            self.pool_size if strides is None
            else ((strides, strides) if isinstance(strides, int) else tuple(strides))
        )
        self.padding = padding

    def _pads(self):
        if self.padding == "same":
            return self.pool_size[0] // 2, self.pool_size[1] // 2
        return 0, 0

    def compute_output_shape(self, shapes):
        c, h, w = shapes[0]
        ph, pw = self._pads()
        oh = (h + 2 * ph - self.pool_size[0]) // self.strides[0] + 1
        ow = (w + 2 * pw - self.pool_size[1]) // self.strides[1] + 1
        return [(c, oh, ow)]

    def build_ff(self, ffmodel, ff_inputs):
        ph, pw = self._pads()
        t = ffmodel.pool2d(
            ff_inputs[0], self.pool_size[0], self.pool_size[1],
            self.strides[0], self.strides[1], ph, pw,
            pool_type=self.pool_type, name=self.name,
        )
        self._ff_layer = ffmodel.layers[-1]
        return [t]


class MaxPooling2D(_Pool2D):
    pool_type = PoolType.POOL_MAX


class AveragePooling2D(_Pool2D):
    pool_type = PoolType.POOL_AVG


class Flatten(Layer):
    def compute_output_shape(self, shapes):
        return [(int(np.prod(shapes[0])),)]

    def build_ff(self, ffmodel, ff_inputs):
        t = ffmodel.flat(ff_inputs[0], name=self.name)
        self._ff_layer = ffmodel.layers[-1]
        return [t]


class Activation(Layer):
    def __init__(self, activation, **kw):
        super().__init__(**kw)
        self.activation = activation

    def build_ff(self, ffmodel, ff_inputs):
        a = self.activation
        x = ff_inputs[0]
        if a == "softmax":
            t = ffmodel.softmax(x, name=self.name)
        elif a == "relu":
            t = ffmodel.relu(x, name=self.name)
        elif a == "sigmoid":
            t = ffmodel.sigmoid(x, name=self.name)
        elif a == "tanh":
            t = ffmodel.tanh(x, name=self.name)
        elif a == "gelu":
            t = ffmodel.gelu(x, name=self.name)
        elif a == "elu":
            t = ffmodel.elu(x, name=self.name)
        else:
            raise ValueError(f"unknown activation {a}")
        self._ff_layer = ffmodel.layers[-1]
        return [t]


class Dropout(Layer):
    def __init__(self, rate, seed=0, **kw):
        super().__init__(**kw)
        self.rate = rate
        self.seed = seed

    def build_ff(self, ffmodel, ff_inputs):
        t = ffmodel.dropout(ff_inputs[0], self.rate, self.seed, name=self.name)
        self._ff_layer = ffmodel.layers[-1]
        return [t]


class BatchNormalization(Layer):
    def __init__(self, momentum=0.9, epsilon=1e-5, relu=False, **kw):
        super().__init__(**kw)
        self.momentum = momentum
        self.epsilon = epsilon
        self.relu = relu

    def build_ff(self, ffmodel, ff_inputs):
        t = ffmodel.batch_norm(ff_inputs[0], relu=self.relu, name=self.name)
        self._ff_layer = ffmodel.layers[-1]
        return [t]


class LayerNormalization(Layer):
    def __init__(self, axis=-1, epsilon=1e-5, **kw):
        super().__init__(**kw)
        self.axis = axis if isinstance(axis, (list, tuple)) else (axis,)
        self.epsilon = epsilon

    def build_ff(self, ffmodel, ff_inputs):
        t = ffmodel.layer_norm(
            ff_inputs[0], axes=tuple(self.axis), eps=self.epsilon, name=self.name
        )
        self._ff_layer = ffmodel.layers[-1]
        return [t]


class Embedding(Layer):
    def __init__(self, input_dim, output_dim, **kw):
        super().__init__(**kw)
        self.input_dim = input_dim
        self.output_dim = output_dim

    def compute_output_shape(self, shapes):
        return [tuple(shapes[0]) + (self.output_dim,)]

    def build_ff(self, ffmodel, ff_inputs):
        t = ffmodel.embedding(
            ff_inputs[0], self.input_dim, self.output_dim,
            AggrMode.AGGR_MODE_NONE, name=self.name,
        )
        self._ff_layer = ffmodel.layers[-1]
        return [t]


class Reshape(Layer):
    def __init__(self, target_shape, **kw):
        super().__init__(**kw)
        self.target_shape = tuple(target_shape)

    def compute_output_shape(self, shapes):
        return [self.target_shape]

    def build_ff(self, ffmodel, ff_inputs):
        batch = ff_inputs[0].dims[0]
        t = ffmodel.reshape(ff_inputs[0], (batch,) + self.target_shape, name=self.name)
        self._ff_layer = ffmodel.layers[-1]
        return [t]


class Permute(Layer):
    """reference: python/flexflow/keras/layers/core.py Permute — dims are
    1-indexed over non-batch axes, Keras semantics."""

    def __init__(self, dims, **kw):
        super().__init__(**kw)
        self.dims = tuple(dims)

    def compute_output_shape(self, shapes):
        (s,) = shapes
        return [tuple(s[d - 1] for d in self.dims)]

    def build_ff(self, ffmodel, ff_inputs):
        perm = (0,) + tuple(d for d in self.dims)  # batch stays in front
        t = ffmodel.transpose(ff_inputs[0], perm, name=self.name)
        self._ff_layer = ffmodel.layers[-1]
        return [t]


class _Merge(Layer):
    op = None

    def compute_output_shape(self, shapes):
        return [tuple(np.broadcast_shapes(*[tuple(s) for s in shapes]))]

    def build_ff(self, ffmodel, ff_inputs):
        t = ff_inputs[0]
        for other in ff_inputs[1:]:
            t = getattr(ffmodel, self.op)(t, other, name=self.name)
        self._ff_layer = ffmodel.layers[-1]
        return [t]


class Add(_Merge):
    op = "add"


class Subtract(_Merge):
    op = "subtract"


class Multiply(_Merge):
    op = "multiply"


class Maximum(_Merge):
    op = "max"


class Minimum(_Merge):
    op = "min"


class Concatenate(Layer):
    def __init__(self, axis=1, **kw):
        super().__init__(**kw)
        self.axis = axis  # axis includes batch dim at 0, like keras

    def compute_output_shape(self, shapes):
        ax = self.axis - 1 if self.axis > 0 else len(shapes[0]) + self.axis
        out = list(shapes[0])
        out[ax] = sum(s[ax] for s in shapes)
        return [tuple(out)]

    def build_ff(self, ffmodel, ff_inputs):
        t = ffmodel.concat(list(ff_inputs), self.axis, name=self.name)
        self._ff_layer = ffmodel.layers[-1]
        return [t]


class MultiHeadAttention(Layer):
    """reference: keras multihead attention example
    (examples/python/keras/func_multihead_attention.py semantics)."""

    def __init__(self, num_heads, key_dim, dropout=0.0, use_bias=True, **kw):
        super().__init__(**kw)
        self.num_heads = num_heads
        self.key_dim = key_dim
        self.dropout = dropout
        self.use_bias = use_bias

    def compute_output_shape(self, shapes):
        return [shapes[0]]

    def build_ff(self, ffmodel, ff_inputs):
        q = ff_inputs[0]
        k = ff_inputs[1] if len(ff_inputs) > 1 else q
        v = ff_inputs[2] if len(ff_inputs) > 2 else k
        embed = q.dims[-1]
        t = ffmodel.multihead_attention(
            q, k, v, embed, self.num_heads, self.key_dim, self.key_dim,
            dropout=self.dropout, bias=self.use_bias, name=self.name,
        )
        self._ff_layer = ffmodel.layers[-1]
        return [t]


def concatenate(inputs, axis=1, name=""):
    """Functional alias (reference: keras/layers/merge.py `concatenate`)."""
    return Concatenate(axis=axis, name=name)(inputs)


# ---------------------------------------------------------------------------
# Backend op layers (reference: python/flexflow/keras/backend/internal.py —
# BatchMatmul/Sin/Cos/Exp/Pow/ReduceSum/Rsqrt/Gather layer classes backing
# the K.* functional API)
# ---------------------------------------------------------------------------

class _UnaryOp(Layer):
    _ff_call = ""

    def build_ff(self, ffmodel, ff_inputs):
        t = getattr(ffmodel, self._ff_call)(ff_inputs[0], name=self.name)
        self._ff_layer = ffmodel.layers[-1]
        return [t]


class Sin(_UnaryOp):
    _ff_call = "sin"


class Cos(_UnaryOp):
    _ff_call = "cos"


class Exp(_UnaryOp):
    _ff_call = "exp"


class Rsqrt(_UnaryOp):
    _ff_call = "rsqrt"


class Pow(Layer):
    def __init__(self, a: float, **kw):
        super().__init__(**kw)
        self.a = float(a)

    def build_ff(self, ffmodel, ff_inputs):
        t = ffmodel.pow(ff_inputs[0], self.a, name=self.name)
        self._ff_layer = ffmodel.layers[-1]
        return [t]


class ReduceSum(Layer):
    """K.sum over non-batch axes (axis counts the batch dim, like keras)."""

    def __init__(self, axis, keepdims: bool = False, **kw):
        super().__init__(**kw)
        self.axis = [axis] if isinstance(axis, int) else list(axis)
        self.keepdims = keepdims

    def compute_output_shape(self, shapes):
        shape = list(shapes[0])
        # self.axis includes the batch dim at 0; tensor shape here excludes
        # it. Negative axes count from the end of the full (batched) shape.
        rank = len(shape) + 1
        drop = sorted((a if a >= 0 else rank + a) - 1 for a in self.axis)
        if self.keepdims:
            for a in drop:
                shape[a] = 1
        else:
            for a in reversed(drop):
                del shape[a]
        return [tuple(shape)]

    def build_ff(self, ffmodel, ff_inputs):
        t = ffmodel.reduce_sum(
            ff_inputs[0], self.axis, keepdims=self.keepdims, name=self.name
        )
        self._ff_layer = ffmodel.layers[-1]
        return [t]


class Gather(Layer):
    """torch.gather semantics (reference internal.py Gather → ffmodel.gather)."""

    def __init__(self, axis: int, **kw):
        super().__init__(**kw)
        self.axis = axis

    def compute_output_shape(self, shapes):
        return [shapes[1]]

    def build_ff(self, ffmodel, ff_inputs):
        t = ffmodel.gather(ff_inputs[0], ff_inputs[1], self.axis, name=self.name)
        self._ff_layer = ffmodel.layers[-1]
        return [t]


class BatchMatmul(Layer):
    def compute_output_shape(self, shapes):
        a, b = shapes
        return [tuple(a[:-1]) + (b[-1],)]

    def build_ff(self, ffmodel, ff_inputs):
        t = ffmodel.batch_matmul(ff_inputs[0], ff_inputs[1], name=self.name)
        self._ff_layer = ffmodel.layers[-1]
        return [t]


# functional merge aliases (reference: keras/layers/merge.py:63-132)

def add(inputs, name=""):
    return Add(name=name)(inputs)


def subtract(inputs, name=""):
    return Subtract(name=name)(inputs)


def multiply(inputs, name=""):
    return Multiply(name=name)(inputs)


# python operators on KerasTensor (the reference's tensor wrappers support
# `x + y` in examples, e.g. examples/python/keras/rsqrt.py)
KerasTensor.__add__ = lambda self, other: Add()([self, other])
KerasTensor.__sub__ = lambda self, other: Subtract()([self, other])
KerasTensor.__mul__ = lambda self, other: Multiply()([self, other])
