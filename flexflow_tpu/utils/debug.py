"""Interactive debugging helpers: pretty-printers for PCG structures.

TPU-native equivalent of the reference's gdb pretty-printers
(gdb/pretty_print.py registers printers for Node/Edge/Graph/MachineView).
Our graph IR is Python, so these are plain functions usable from any REPL or
debugger (`from flexflow_tpu.utils.debug import pp`), plus a tensor-value
inspector that mirrors the reference's `print_tensor<T>` device helper
(src/runtime/cuda_helper.cu) without a device round-trip per element.

Printing is this module's purpose (REPL dump helpers):
# fflint: disable-file=FFL201
"""
from __future__ import annotations

from typing import Any

import numpy as np


def format_parallel_tensor(pt) -> str:
    """[size/degree(idx)|R] per dim — replica dims marked R (the reference
    prints ParallelDim the same way in its dot exports)."""
    dims = []
    for d in pt.dims:
        tag = f"{d.size}"
        if d.degree > 1:
            tag += f"/{d.degree}"
        if d.parallel_idx >= 0:
            tag += f"({d.parallel_idx})"
        if d.is_replica_dim:
            tag += "R"
        dims.append(tag)
    return f"PT#{pt.guid}[{' x '.join(dims)}] {pt.data_type.name}"


def format_machine_view(mv) -> str:
    devs = list(mv.device_ids()) if hasattr(mv, "device_ids") else []
    short = devs if len(devs) <= 8 else devs[:8] + ["..."]
    return (
        f"MachineView({mv.device_type} start={mv.start_device_id} "
        f"dim={mv.dim} stride={mv.stride} devices={short})"
    )


def format_op(op, *, views: dict | None = None) -> str:
    ins = ", ".join(format_parallel_tensor(t) for t in op.inputs)
    outs = ", ".join(format_parallel_tensor(t) for t in op.outputs)
    line = f"{op.name} <{op.op_type.name}> ({ins}) -> ({outs})"
    if views and op in views:
        line += f"  @ {format_machine_view(views[op])}"
    return line


def format_graph(graph, *, views: dict | None = None) -> str:
    lines = [f"Graph: {len(graph.ops)} ops"]
    for op in graph.topo_order():
        lines.append("  " + format_op(op, views=views))
    return "\n".join(lines)


def summarize_array(x: Any, name: str = "tensor", edge: int = 3) -> str:
    """Shape/dtype/stats plus corner values — the reference's print_tensor
    debug task, but summarized host-side in one transfer."""
    arr = np.asarray(x)
    flat = arr.reshape(-1)
    head = ", ".join(f"{v:.4g}" for v in flat[:edge])
    tail = ", ".join(f"{v:.4g}" for v in flat[-edge:]) if flat.size > edge else ""
    stats = ""
    if arr.size and np.issubdtype(arr.dtype, np.floating):
        stats = (
            f" mean={arr.mean():.4g} std={arr.std():.4g}"
            f" min={arr.min():.4g} max={arr.max():.4g}"
            f" nan={int(np.isnan(arr).sum())}"
        )
    return (
        f"{name}: shape={arr.shape} dtype={arr.dtype}{stats}"
        f" values=[{head}{', ..., ' + tail if tail else ''}]"
    )


def pp(obj: Any, **kw) -> None:
    """Print any PCG object (Graph / PCGOp / ParallelTensor / MachineView /
    array) in its pretty form."""
    for probe, fmt in (
        ("ops", format_graph),
        ("op_type", format_op),
        ("dims", format_parallel_tensor),
        ("start_device_id", format_machine_view),
    ):
        if hasattr(obj, probe):
            print(fmt(obj, **kw) if fmt is format_graph else fmt(obj))
            return
    print(summarize_array(obj, **kw))
