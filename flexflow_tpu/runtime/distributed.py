"""Multi-host bootstrap — the reference's multi-node story rebuilt for jax.

The reference scales across nodes with Legion/GASNet + per-MachineView NCCL
communicators, launched under MPI with per-rank env wrappers
(MULTI-NODE.md, tests/multinode_helpers/mpi_wrapper1.sh; model.cc:3129
NCCL communicator bootstrap). The TPU-native equivalent is
`jax.distributed.initialize`: one process per host joins a coordinator,
after which `jax.devices()` spans every host and XLA compiles collectives
over ICI within a slice and DCN across hosts — the same programs this
framework already emits just see a bigger mesh.

Env contract (mirrors the reference's rank-env wrappers; also what
scripts/multinode_run.sh exports):
    FF_COORDINATOR_ADDRESS  host:port of process 0 (default from TPU/SLURM
                            auto-detect when unset)
    FF_NUM_PROCESSES        total processes (hosts)
    FF_PROCESS_ID           this process's rank
"""
from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger("flexflow_tpu.runtime.distributed")

_initialized = False


def is_initialized() -> bool:
    """Whether the multi-host runtime is up — either because WE brought
    it up (init_distributed) or because the launcher/jax already did
    (externally-initialized jax.distributed, probed via the live process
    count, which only exceeds 1 after a successful coordinator join)."""
    if _initialized:
        return True
    try:
        import jax

        return jax.process_count() > 1
    except Exception:
        return False


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
    retry_policy=None,
) -> tuple:
    """Join (or start) the multi-host runtime. Call before creating any
    FFModel/mesh. Returns (process_id, num_processes, global_devices).

    On TPU pods all three args auto-detect (jax reads the TPU metadata);
    on CPU/GPU clusters pass them or export FF_* (SLURM/OpenMPI envs also
    auto-detect inside jax). Idempotent.

    The coordinator connection is retried with exponential backoff
    (runtime/resilience.py): after a preemption the restarted workers
    race the coordinator pod coming back — first-connect failures are
    expected, not fatal. Tune with `retry_policy` or
    FF_INIT_MAX_ATTEMPTS / FF_INIT_BASE_DELAY_S."""
    import jax

    from .resilience import RetryPolicy, retry

    global _initialized
    if _initialized:
        return (jax.process_index(), jax.process_count(), jax.devices())

    coordinator_address = coordinator_address or os.environ.get(
        "FF_COORDINATOR_ADDRESS"
    )
    if num_processes is None and "FF_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["FF_NUM_PROCESSES"])
    if process_id is None and "FF_PROCESS_ID" in os.environ:
        process_id = int(os.environ["FF_PROCESS_ID"])

    kw = {}
    if coordinator_address is not None:
        kw["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    if local_device_ids is not None:
        kw["local_device_ids"] = local_device_ids
    policy = retry_policy or RetryPolicy(
        max_attempts=int(os.environ.get("FF_INIT_MAX_ATTEMPTS", "4")),
        base_delay_s=float(os.environ.get("FF_INIT_BASE_DELAY_S", "1.0")),
        max_delay_s=30.0,
        # jax surfaces coordinator-unreachable as RuntimeError
        retry_on=(RuntimeError, OSError, ConnectionError, TimeoutError),
    )
    retry(
        lambda: jax.distributed.initialize(**kw),
        policy,
        on_retry=lambda attempt, e, d: logger.warning(
            "coordinator connect attempt %d failed (%s); retrying in %.1fs",
            attempt + 1, e, d,
        ),
    )
    _initialized = True
    logger.info("distributed runtime up: process %d of %d, %d devices",
                jax.process_index(), jax.process_count(),
                len(jax.devices()))
    return (jax.process_index(), jax.process_count(), jax.devices())


def shutdown() -> None:
    """Tear down the multi-host runtime. Safe to call repeatedly (and
    when init_distributed never ran): the flag drops first and an
    already-shut-down jax runtime is a logged no-op, not a crash."""
    import jax

    global _initialized
    was = _initialized
    _initialized = False
    if not was:
        return
    try:
        jax.distributed.shutdown()
    except RuntimeError as e:
        # double shutdown / runtime already gone — idempotent by contract
        logger.debug("jax.distributed.shutdown: %s (ignored)", e)


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()
