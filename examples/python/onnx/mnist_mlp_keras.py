"""Export + train the keras-layout ONNX MNIST MLP (reference:
examples/python/onnx/mnist_mlp_keras.py — ONNXModelKeras; keras exports use
MatMul with (in, out) kernels + Add bias)."""
import numpy as np

from flexflow.core import *  # noqa: F401,F403
from flexflow.keras.datasets import mnist
from flexflow.onnx.model import ONNXModelKeras, proto

from _example_args import example_args


def export(path="mnist_mlp_keras.onnx", seed=0):
    rng = np.random.RandomState(seed)
    dims = [784, 512, 512, 10]
    nodes, inits = [], []
    prev = "input_1"
    for i in range(3):
        w = (rng.randn(dims[i], dims[i + 1]) / np.sqrt(dims[i])).astype(np.float32)
        inits.append(proto.from_array(w, f"dense_{i}/kernel"))
        nodes.append(proto.make_node("MatMul", [prev, f"dense_{i}/kernel"],
                                     [f"mm{i}"], name=f"MatMul_{i}"))
        prev = f"mm{i}"
        if i < 2:
            nodes.append(proto.make_node("Relu", [prev], [f"relu{i}"],
                                         name=f"Relu_{i}"))
            prev = f"relu{i}"
    nodes.append(proto.make_node("Softmax", [prev], ["dense_2"],
                                 name="Softmax_0", axis=-1))
    graph = proto.make_graph(
        nodes, "keras_model",
        [proto.make_tensor_value_info("input_1", proto.TensorProto.FLOAT,
                                      ["N", 784])],
        [proto.make_tensor_value_info("dense_2", proto.TensorProto.FLOAT,
                                      ["N", 10])],
        initializer=inits)
    proto.save_model(proto.make_model(graph), path)
    return path


def top_level_task(args):
    ffconfig = FFConfig()
    ffconfig.batch_size = args.batch_size
    ffmodel = FFModel(ffconfig)
    input1 = ffmodel.create_tensor([args.batch_size, 784], DataType.DT_FLOAT)

    onnx_model = ONNXModelKeras(export(), ffconfig, ffmodel)
    t = onnx_model.apply(ffmodel, {"input_1": input1})

    ffmodel.optimizer = SGDOptimizer(ffmodel, 0.01)
    ffmodel.compile(loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                    metrics=[MetricsType.METRICS_ACCURACY])
    onnx_model.load_weights(ffmodel)

    (x_train, y_train), _ = mnist.load_data(n_train=args.num_samples)
    x_train = x_train.reshape(-1, 784).astype("float32") / 255
    y_train = y_train.astype("int32").reshape(-1, 1)
    ffmodel.fit(x=x_train, y=y_train, epochs=args.epochs)


if __name__ == "__main__":
    print("mnist mlp onnx (keras layout)")
    top_level_task(example_args())
