"""Pool2D operator.

TPU-native equivalent of reference src/ops/pool_2d.cc (688 LoC, cuDNN
pooling): one lax.reduce_window. NCHW layout like the reference API.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax import lax

from ..ff_types import ActiMode, OperatorType, PoolType
from .common import apply_activation
from .registry import register_op


@dataclasses.dataclass(frozen=True)
class Pool2DParams:
    """reference: include/flexflow/ops/pool_2d_params.h"""

    kernel_h: int
    kernel_w: int
    stride_h: int
    stride_w: int
    padding_h: int = 0
    padding_w: int = 0
    pool_type: PoolType = PoolType.POOL_MAX
    activation: ActiMode = ActiMode.AC_MODE_NONE


def _infer(params: Pool2DParams, in_shapes, in_dtypes):
    (s,) = in_shapes
    oh = (s[2] + 2 * params.padding_h - params.kernel_h) // params.stride_h + 1
    ow = (s[3] + 2 * params.padding_w - params.kernel_w) // params.stride_w + 1
    return [(s[0], s[1], oh, ow)], [in_dtypes[0]]


def _forward(params: Pool2DParams, weights, inputs, ctx):
    (x,) = inputs
    window = (1, 1, params.kernel_h, params.kernel_w)
    strides = (1, 1, params.stride_h, params.stride_w)
    pads = (
        (0, 0),
        (0, 0),
        (params.padding_h, params.padding_h),
        (params.padding_w, params.padding_w),
    )
    if params.pool_type == PoolType.POOL_MAX:
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        y = lax.reduce_window(x, init, lax.max, window, strides, pads)
    else:
        ones = jnp.ones_like(x)
        s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
        # cuDNN avg pooling divides by window size *excluding* padding
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        y = s / cnt
    return [apply_activation(params.activation, y)]


register_op(OperatorType.OP_POOL2D, "Pool2D", infer=_infer, forward=_forward)
