"""Spack package recipe for flexflow-tpu (reference: spack/package.py,
which builds the CUDA/Legion stack via CMakePackage).

The TPU build is a pure-Python package plus an optional C++ native runtime
(dataloader + task-graph simulator, built by setup.py), so the recipe is a
PythonPackage: no CUDA/cuDNN/NCCL/GASNet variants — JAX's TPU runtime owns
the device and collectives.
"""
from spack.package import *


class FlexflowTpu(PythonPackage):
    """TPU-native deep-learning framework that accelerates distributed DNN
    training by automatically searching for efficient parallelization
    strategies, with drop-in Keras / PyTorch-FX / ONNX frontends. Rebuild of
    FlexFlow (flexflow.ai) for TPU: XLA SPMD + Pallas kernels instead of
    CUDA/Legion."""

    homepage = "https://flexflow.ai"
    git = "https://github.com/flexflow/flexflow-tpu.git"

    maintainers = ["flexflow-tpu"]
    version("main", branch="main")

    depends_on("python@3.10:", type=("build", "run"))
    depends_on("py-setuptools", type="build")
    depends_on("py-jax@0.4.30:", type=("build", "run"))
    depends_on("py-flax", type=("build", "run"))
    depends_on("py-optax", type=("build", "run"))
    depends_on("py-numpy", type=("build", "run"))

    variant("native", default=True,
            description="Build the C++ native runtime (prefetching "
                        "dataloader, task-graph simulator)")
    variant("torch", default=False,
            description="Enable the PyTorch-FX frontend")

    depends_on("cxx", type="build", when="+native")
    depends_on("py-torch", type=("build", "run"), when="+torch")
