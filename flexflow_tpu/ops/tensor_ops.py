"""Shape/layout operators: reshape, flat, transpose, reverse, concat, split,
cast, gather, pad, slice.

TPU-native equivalents of reference src/ops/{reshape,flat,transpose,reverse,
concat,split,cast,gather,pad}.cc. All of these are pure data-movement ops; on
TPU they are XLA reshapes/transposes/gathers that the compiler folds into
neighboring fusions (the reference needs a CUDA kernel + Legion task for
each).
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

import jax.numpy as jnp
import numpy as np

from ..ff_types import DataType, OperatorType
from .registry import register_op


# -- Reshape (reference: src/ops/reshape.cc) --------------------------------
@dataclasses.dataclass(frozen=True)
class ReshapeParams:
    shape: Tuple[int, ...]


def _reshape_infer(params, in_shapes, in_dtypes):
    (s,) = in_shapes
    vol = int(np.prod(s))
    out = list(params.shape)
    if -1 in out:
        i = out.index(-1)
        rest = int(np.prod([d for d in out if d != -1]))
        out[i] = vol // rest
    assert int(np.prod(out)) == vol, f"reshape {s} -> {params.shape}"
    return [tuple(out)], [in_dtypes[0]]


register_op(
    OperatorType.OP_RESHAPE,
    "Reshape",
    infer=_reshape_infer,
    forward=lambda p, w, x, ctx: [jnp.reshape(x[0], _reshape_infer(p, [x[0].shape], [None])[0][0])],
)


# -- Flat (reference: src/ops/flat.cc — NCHW -> (N, C*H*W)) -----------------
@dataclasses.dataclass(frozen=True)
class FlatParams:
    pass


def _flat_infer(params, in_shapes, in_dtypes):
    (s,) = in_shapes
    return [(s[0], int(np.prod(s[1:])))], [in_dtypes[0]]


register_op(
    OperatorType.OP_FLAT,
    "Flat",
    infer=_flat_infer,
    forward=lambda p, w, x, ctx: [jnp.reshape(x[0], (x[0].shape[0], -1))],
)


# -- Transpose (reference: src/ops/transpose.cc) ----------------------------
@dataclasses.dataclass(frozen=True)
class TransposeParams:
    perm: Tuple[int, ...]


def _transpose_infer(params, in_shapes, in_dtypes):
    (s,) = in_shapes
    return [tuple(s[p] for p in params.perm)], [in_dtypes[0]]


register_op(
    OperatorType.OP_TRANSPOSE,
    "Transpose",
    infer=_transpose_infer,
    forward=lambda p, w, x, ctx: [jnp.transpose(x[0], p.perm)],
)


# -- Reverse (reference: src/ops/reverse.cc) --------------------------------
@dataclasses.dataclass(frozen=True)
class ReverseParams:
    axis: int


register_op(
    OperatorType.OP_REVERSE,
    "Reverse",
    infer=lambda p, s, dt: ([s[0]], [dt[0]]),
    forward=lambda p, w, x, ctx: [jnp.flip(x[0], axis=p.axis)],
)


# -- Concat (reference: src/ops/concat.cc) ----------------------------------
@dataclasses.dataclass(frozen=True)
class ConcatParams:
    axis: int


def _concat_infer(params, in_shapes, in_dtypes):
    ax = params.axis % len(in_shapes[0])
    out = list(in_shapes[0])
    out[ax] = sum(s[ax] for s in in_shapes)
    return [tuple(out)], [in_dtypes[0]]


register_op(
    OperatorType.OP_CONCAT,
    "Concat",
    infer=_concat_infer,
    forward=lambda p, w, x, ctx: [jnp.concatenate(x, axis=p.axis)],
    num_inputs=-1,
)


# -- Split (reference: src/ops/split.cc) ------------------------------------
@dataclasses.dataclass(frozen=True)
class SplitParams:
    sizes: Tuple[int, ...]
    axis: int


def _split_infer(params, in_shapes, in_dtypes):
    (s,) = in_shapes
    ax = params.axis % len(s)
    outs = []
    for sz in params.sizes:
        o = list(s)
        o[ax] = sz
        outs.append(tuple(o))
    return outs, [in_dtypes[0]] * len(params.sizes)


def _split_forward(params, w, x, ctx):
    (t,) = x
    idx = np.cumsum(params.sizes)[:-1].tolist()
    return list(jnp.split(t, idx, axis=params.axis))


register_op(OperatorType.OP_SPLIT, "Split", infer=_split_infer, forward=_split_forward)


# -- Cast (reference: src/ops/cast.cc) --------------------------------------
@dataclasses.dataclass(frozen=True)
class CastParams:
    dtype: DataType


register_op(
    OperatorType.OP_CAST,
    "Cast",
    infer=lambda p, s, dt: ([s[0]], [p.dtype]),
    forward=lambda p, w, x, ctx: [x[0].astype(p.dtype.jnp_dtype)],
)


# -- Gather (reference: src/ops/gather.cc — torch.gather semantics) ---------
@dataclasses.dataclass(frozen=True)
class GatherParams:
    dim: int


def _gather_infer(params, in_shapes, in_dtypes):
    data, index = in_shapes
    return [tuple(index)], [in_dtypes[0]]


def _gather_forward(params, w, x, ctx):
    data, index = x
    return [jnp.take_along_axis(data, index.astype(jnp.int32), axis=params.dim)]


register_op(
    OperatorType.OP_GATHER, "Gather", infer=_gather_infer, forward=_gather_forward,
    num_inputs=2,
)


# -- Pad ---------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class PadParams:
    pads: Tuple[Tuple[int, int], ...]
    value: float = 0.0


def _pad_infer(params, in_shapes, in_dtypes):
    (s,) = in_shapes
    out = tuple(d + lo + hi for d, (lo, hi) in zip(s, params.pads))
    return [out], [in_dtypes[0]]


register_op(
    OperatorType.OP_PAD,
    "Pad",
    infer=_pad_infer,
    forward=lambda p, w, x, ctx: [
        jnp.pad(x[0], p.pads, constant_values=p.value)
    ],
)


# -- Slice -------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class SliceParams:
    starts: Tuple[int, ...]
    ends: Tuple[int, ...]


def _slice_infer(params, in_shapes, in_dtypes):
    (s,) = in_shapes
    out = tuple(e - b for b, e in zip(params.starts, params.ends))
    return [out], [in_dtypes[0]]


register_op(
    OperatorType.OP_SLICE,
    "Slice",
    infer=_slice_infer,
    forward=lambda p, w, x, ctx: [
        x[0][tuple(slice(b, e) for b, e in zip(p.starts, p.ends))]
    ],
)


# -- NoOp / Identity passthrough for PCG source nodes ------------------------
@dataclasses.dataclass(frozen=True)
class NoOpParams:
    pass


register_op(
    OperatorType.OP_NOOP,
    "NoOp",
    infer=lambda p, s, dt: ([s[0]], [dt[0]]),
    forward=lambda p, w, x, ctx: [x[0]],
    seq_pointwise=True,
)


# -- Squeeze / Unsqueeze (ONNX frontend ops; reference handles them in
# python/flexflow/onnx/model.py via reshape) ---------------------------------
@dataclasses.dataclass(frozen=True)
class SqueezeParams:
    axes: Tuple[int, ...] = ()


def _squeeze_infer(params, in_shapes, in_dtypes):
    (s,) = in_shapes
    axes = params.axes or tuple(i for i, d in enumerate(s) if d == 1)
    axes = tuple(a % len(s) for a in axes)  # ONNX allows negative axes
    out = tuple(d for i, d in enumerate(s) if i not in axes)
    return [out], [in_dtypes[0]]


register_op(
    OperatorType.OP_SQUEEZE,
    "Squeeze",
    infer=_squeeze_infer,
    forward=lambda p, w, x, ctx: [
        jnp.reshape(x[0], _squeeze_infer(p, [x[0].shape], [None])[0][0])
    ],
)


@dataclasses.dataclass(frozen=True)
class UnsqueezeParams:
    axes: Tuple[int, ...]


def _unsqueeze_infer(params, in_shapes, in_dtypes):
    (s,) = in_shapes
    # ONNX: axes are positions in the OUTPUT (rank = in + len(axes));
    # negative axes resolve against that final rank, not intermediates
    out_rank = len(s) + len(params.axes)
    axes = sorted(a % out_rank for a in params.axes)
    out = list(s)
    for a in axes:
        out.insert(a, 1)
    return [tuple(out)], [in_dtypes[0]]


register_op(
    OperatorType.OP_UNSQUEEZE,
    "Unsqueeze",
    infer=_unsqueeze_infer,
    forward=lambda p, w, x, ctx: [
        jnp.reshape(x[0], _unsqueeze_infer(p, [x[0].shape], [None])[0][0])
    ],
)


# -- Where (ONNX select) -----------------------------------------------------
@dataclasses.dataclass(frozen=True)
class WhereParams:
    pass


def _where_infer(params, in_shapes, in_dtypes):
    out = tuple(np.broadcast_shapes(*in_shapes))
    return [out], [in_dtypes[1]]


register_op(
    OperatorType.OP_WHERE,
    "Where",
    infer=_where_infer,
    forward=lambda p, w, x, ctx: [jnp.where(x[0].astype(bool), x[1], x[2])],
    num_inputs=3,
)


# -- Resize (nearest; ONNX Resize/Upsample) ---------------------------------
@dataclasses.dataclass(frozen=True)
class ResizeParams:
    out_shape: Tuple[int, ...]  # full output shape


def _resize_forward(p, w, x, ctx):
    import jax

    return [jax.image.resize(x[0], p.out_shape, method="nearest")]


register_op(
    OperatorType.OP_RESIZE,
    "Resize",
    infer=lambda p, s, dt: ([tuple(p.out_shape)], [dt[0]]),
    forward=_resize_forward,
)
