#!/usr/bin/env python
"""Generate the shipped substitution-rule collection.

The reference ships substitutions/graph_subst_3_v2.json (~700 KB of
TASO-generated rewrite rules, loaded by src/runtime/substitution_loader.cc).
This emits our equivalent asset — flexflow_tpu/search/substitutions/
graph_subst_tpu_v1.json — in the SAME `_t`-tagged schema, covering the
per-op partition/combine rewrites the declarative path adds on top of the
programmatic xfers (search/substitution.py):

  * per-op sample-dim (dim 0) partition sandwiches for Linear, Softmax,
    elementwise add/mul, and BatchMatmul — unlike the programmatic
    `partition_batch`, these parallelize ONE op without requiring every
    activation in the graph to have a divisible batch dim;
  * column-parallel BatchMatmul (partition the rhs' LAST dim) — not in
    the programmatic vocabulary at all: it is the only way the search
    can parallelize a batch-1 matmul chain;
  * STRUCTURAL rules: combine->partition elision (removes a redundant
    reshard pair the per-op sandwiches leave between adjacent ops) and
    attention head-partition (attribute parallelism as a declarative
    rule — PM_PARALLEL_DEGREE on the dst compute op shards the
    head-tagged weight dims; reference substitution.cc:1764).

Degrees cover 2..32 so the rules reach pod-scale machines (a degree
that exceeds the searched machine simply never validates).

Regenerate with:  python tools/generate_substitutions.py
"""
import json
import os

DEGREES = (2, 4, 8, 16, 32)


def t(op_id, ts_id=0):
    return {"_t": "Tensor", "opId": op_id, "tsId": ts_id}


def para(dim, degree):
    return [
        {"_t": "Parameter", "key": "PM_PARALLEL_DIM", "value": dim},
        {"_t": "Parameter", "key": "PM_PARALLEL_DEGREE", "value": degree},
    ]


def op(type_str, inputs, params=None):
    return {"_t": "Operator", "type": type_str, "input": inputs,
            "para": params or []}


def rule(name, src, dst, src_out, dst_out):
    return {
        "_t": "Rule", "name": name, "srcOp": src, "dstOp": dst,
        "mappedOutput": [{"_t": "MapOutput", "srcOpId": src_out[0],
                          "srcTsId": src_out[1], "dstOpId": dst_out[0],
                          "dstTsId": dst_out[1]}],
    }


def unary_batch(op_type, short, d):
    """partition(dim0) -> op -> combine(dim0)."""
    return rule(
        f"partition_{short}_batch_{d}",
        src=[op(op_type, [t(-1)])],
        dst=[
            op("OP_PARTITION", [t(-1)], para(0, d)),
            op(op_type, [t(0)]),
            op("OP_COMBINE", [t(1)], para(0, d)),
        ],
        src_out=(0, 0), dst_out=(2, 0),
    )


def binary_batch(op_type, short, d):
    """Both operands partitioned over dim 0. For OP_BATCHMATMUL this is
    only meaningful at rank >= 3 (at rank 2 the rhs dim 0 is the
    contraction dim — a partial sum, not data parallelism); the loader's
    _infer_outputs rejects such matches, so rank-2 sites are skipped."""
    return rule(
        f"partition_{short}_batch_{d}",
        src=[op(op_type, [t(-1), t(-2)])],
        dst=[
            op("OP_PARTITION", [t(-1)], para(0, d)),
            op("OP_PARTITION", [t(-2)], para(0, d)),
            op(op_type, [t(0), t(1)]),
            op("OP_COMBINE", [t(2)], para(0, d)),
        ],
        src_out=(0, 0), dst_out=(3, 0),
    )


def matmul_column(d, rank):
    """Column-parallel batch matmul: shard the rhs' last dim; the lhs is
    consumed whole. Rank-specific because PM_PARALLEL_DIM is absolute."""
    dim = rank - 1
    return rule(
        f"partition_matmul_col{rank}_{d}",
        src=[op("OP_BATCHMATMUL", [t(-1), t(-2)])],
        dst=[
            op("OP_PARTITION", [t(-2)], para(dim, d)),
            op("OP_BATCHMATMUL", [t(-1), t(0)]),
            op("OP_COMBINE", [t(1)], para(dim, d)),
        ],
        src_out=(0, 0), dst_out=(2, 0),
    )


def combine_partition_elide(dim, d):
    """combine(dim,d) -> partition(dim,d) is an identity round-trip: the
    per-op partition sandwiches leave one between every pair of adjacent
    parallelized ops; eliding it removes two reshard collectives. The
    loader's dim+degree matching guarantees the pair really round-trips."""
    return rule(
        f"elide_combine_partition_d{dim}_{d}",
        src=[
            op("OP_COMBINE", [t(-1)], para(dim, d)),
            op("OP_PARTITION", [t(0)], para(dim, d)),
        ],
        dst=[op("OP_NOOP", [t(-1)])],
        src_out=(1, 0), dst_out=(0, 0),
    )


def attention_head_partition(d):
    """Attribute parallelism over attention heads as a DECLARATIVE rule:
    PM_PARALLEL_DEGREE on the dst compute op shards its head-tagged
    weight dims (reference: substitution.cc:1764
    create_partition_attention_combine)."""
    mha_in = [t(-1), t(-2), t(-3)]
    return rule(
        f"partition_attention_heads_{d}",
        src=[op("OP_MULTIHEAD_ATTENTION", mha_in)],
        dst=[op("OP_MULTIHEAD_ATTENTION", mha_in,
                [{"_t": "Parameter", "key": "PM_PARALLEL_DEGREE",
                  "value": d}])],
        src_out=(0, 0), dst_out=(0, 0),
    )


def main():
    rules = []
    for d in DEGREES:
        rules.append(unary_batch("OP_LINEAR", "linear", d))
        rules.append(unary_batch("OP_SOFTMAX", "softmax", d))
        rules.append(unary_batch("OP_RELU", "relu", d))
        rules.append(binary_batch("OP_EW_ADD", "ewadd", d))
        rules.append(binary_batch("OP_EW_MUL", "ewmul", d))
        rules.append(binary_batch("OP_BATCHMATMUL", "matmul", d))
        rules.append(matmul_column(d, rank=3))
        rules.append(matmul_column(d, rank=2))
        rules.append(combine_partition_elide(0, d))
        rules.append(combine_partition_elide(1, d))
        rules.append(attention_head_partition(d))
    out = {"rule": rules}
    path = os.path.join(os.path.dirname(__file__), "..", "flexflow_tpu",
                        "search", "substitutions",
                        "graph_subst_tpu_v1.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path} ({len(rules)} rules)")


if __name__ == "__main__":
    main()
