"""Precision-flow analysis pass (FFA7xx).

Abstract interpretation over dtypes: every tensor's *effective* dtype is
its precision annotation (`ParallelTensor.compute_dtype`, stamped by
`annotate_graph_precision` after the search picks a winner) falling back
to its declared `data_type`. A registry of per-op precision rules —
matmul/attention/reductions accumulate fp32 by default, elementwise
propagates the widest float input, explicit OP_CAST nodes change the
flow — re-derives the precision flow the executor will actually run, so
mixed-precision defects are rejected *before any device time is spent*
(the precision counterpart of the sharding pass's degree re-derivation).

Codes (docs/analysis.md):

  * FFA701 — dtype mismatch at an op boundary: two float inputs of one
    op carry different effective dtypes with no explicit cast (error —
    XLA would insert an implicit convert the author never audited);
  * FFA702 — low-precision accumulation: a reduction/matmul/Aggregate
    accumulating in a <=16-bit dtype without an fp32 accumulator
    (error — the MXU's fp32 accumulate is free, dropping it is never a
    win worth silent drift);
  * FFA703 — a gradient collective (Reduction / WeightShard
    reduce-scatter / the implicit data-parallel weight-grad sync)
    reduces in <=16-bit over a ring where rms error grows ~sqrt(p)
    (warning, names the degree);
  * FFA704 — loss-scale / step-guard range check: guard thresholds and
    loss-scale bounds vs the compute dtype's dynamic range (warning);
  * FFA705 — end-to-end static drift budget: per-op ulp-scaled
    quantization-error estimates accumulated along the longest PCG path
    vs a configurable budget (error when exceeded; the fix_hint names
    the op to promote). `runtime/verify.tolerance_from_budget` derives
    the differential verifier's tolerances from the same budget, so the
    static prediction and the runtime check share one knob
    (`FFConfig.precision_drift_budget`).
"""
from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..ff_types import DataType, OperatorType
from .diagnostics import AnalysisReport, Severity

# Accumulated-error budget (relative, ulp-scaled units) a searched
# strategy may statically incur along its longest path. 0.25 clears the
# full bf16-compute/fp32-accum zoo with headroom while a 16-bit
# accumulator chain blows through it (FFConfig.precision_drift_budget
# overrides; verify.tolerance_from_budget consumes the same value).
DEFAULT_DRIFT_BUDGET = 0.25

# grad collectives over rings this wide get the FFA703 sqrt(p) warning
RING_DEGREE_THRESHOLD = 4

_FLOAT_DTYPES = frozenset({
    DataType.DT_HALF, DataType.DT_FLOAT, DataType.DT_DOUBLE,
    DataType.DT_BF16,
})
_LOW_PRECISION = frozenset({DataType.DT_HALF, DataType.DT_BF16})

# unit roundoff (eps/2 is one rounding's relative error bound)
_EPS = {
    DataType.DT_BF16: 2.0 ** -8,
    DataType.DT_HALF: 2.0 ** -11,
    DataType.DT_FLOAT: 2.0 ** -24,
    DataType.DT_DOUBLE: 2.0 ** -53,
}

# Ops that ACCUMULATE over a contraction/reduction width — the ops whose
# accumulator dtype matters (FFA702) and whose drift contribution scales
# with the reduction width (FFA705). OP_REDUCTION is the parallel
# partial-sum collective; its width is the reduction degree.
_ACCUMULATING = frozenset({
    OperatorType.OP_LINEAR, OperatorType.OP_CONV2D,
    OperatorType.OP_BATCHMATMUL, OperatorType.OP_MATMUL,
    OperatorType.OP_MULTIHEAD_ATTENTION, OperatorType.OP_AGGREGATE,
    OperatorType.OP_AGG_SPEC, OperatorType.OP_REDUCE_SUM,
    OperatorType.OP_REDUCE_MEAN, OperatorType.OP_MEAN,
    OperatorType.OP_POOL2D, OperatorType.OP_LAYERNORM,
    OperatorType.OP_BATCHNORM, OperatorType.OP_SOFTMAX,
    OperatorType.OP_REDUCTION,
})

# ops whose multiple inputs legitimately mix dtypes (int indices/routing
# state next to float payloads) — excluded from the FFA701 boundary check
# even for their float inputs, because the float legs are independent
# payloads, not operands of one arithmetic kernel
_MIXED_DTYPE_OK = frozenset({
    OperatorType.OP_WHERE,
})

# compute_dtype -> accum_dtype inference hook, keyed by OperatorType.
# A rule sees (op, in_flow: List[Optional[DataType]], default_compute)
# and returns (compute_dtype, accum_dtype) for the op's outputs — the
# registration point the int8/fp8 follow-up PR extends per quantized op.
_PRECISION_RULES: Dict[OperatorType, Callable] = {}


def register_precision_rule(op_type: OperatorType, fn: Callable) -> None:
    """Override the default precision inference for one op type."""
    _PRECISION_RULES[op_type] = fn


def _widest(dtypes: List[DataType]) -> Optional[DataType]:
    """Widest float dtype = smallest unit roundoff (f16 beats bf16:
    more mantissa bits; range is FFA704's business, not width's)."""
    floats = [d for d in dtypes if d in _FLOAT_DTYPES]
    if not floats:
        return None
    return min(floats, key=lambda d: _EPS[d])


def effective_dtype(t) -> DataType:
    return t.compute_dtype if t.compute_dtype is not None else t.data_type


def effective_accum_dtype(t) -> DataType:
    """The dtype the producing op accumulates in: the annotation, else
    the compute flow itself (no annotation = no fp32 master accum)."""
    return t.accum_dtype if t.accum_dtype is not None else effective_dtype(t)


def infer_op_precision(op, in_flow: List[Optional[DataType]],
                       default_compute: Optional[DataType]
                       ) -> Tuple[Optional[DataType], Optional[DataType]]:
    """Registry-driven (compute, accum) inference for one op.

    Defaults: OP_CAST sets the flow from its param; source ops start the
    flow at `default_compute`; everything else propagates the widest
    float input; accumulating ops get an fp32 accumulator."""
    rule = _PRECISION_RULES.get(op.op_type)
    if rule is not None:
        return rule(op, in_flow, default_compute)
    if op.op_type == OperatorType.OP_CAST:
        dt = op.params.dtype
        return (dt if dt in _FLOAT_DTYPES else None, None)
    known = [d for d in in_flow if d is not None]
    if not known:
        compute = default_compute
    else:
        compute = _widest(known)
    accum = None
    if op.op_type in _ACCUMULATING and compute in _LOW_PRECISION:
        accum = DataType.DT_FLOAT
    return compute, accum


def annotate_graph_precision(graph,
                             compute_dtype: Optional[DataType] = None
                             ) -> None:
    """Stamp `compute_dtype`/`accum_dtype` on every output tensor of the
    graph from the registry rules, starting the flow at `compute_dtype`
    (the executor's AMP dtype; None = full precision, which CLEARS any
    stale annotation so re-annotation is idempotent).

    Only activations (op outputs) are annotated — weights keep fp32
    master storage under AMP, so their memory accounting must stay at
    data_type width."""
    flow: Dict[int, Optional[DataType]] = {}
    for op in graph.topo_order():
        # graph-input tensors (no producing op) enter the executor
        # through its AMP entry cast, so their flow STARTS at the compute
        # dtype — declared f32 inputs do not keep the whole graph wide
        in_flow = []
        for t in op.inputs:
            if t.guid in flow:
                in_flow.append(flow[t.guid])
            elif t.data_type in _FLOAT_DTYPES:
                in_flow.append(compute_dtype if compute_dtype is not None
                               else t.data_type)
            else:
                in_flow.append(None)
        compute, accum = infer_op_precision(op, in_flow, compute_dtype)
        for t in op.outputs:
            if t.data_type not in _FLOAT_DTYPES:
                t.compute_dtype = None
                t.accum_dtype = None
                flow[t.guid] = None
                continue
            t.compute_dtype = (
                compute if compute is not None and compute != t.data_type
                else None
            )
            t.accum_dtype = accum
            flow[t.guid] = effective_dtype(t)


def _reduction_width(op) -> int:
    """Width of the op's accumulation: the contraction extent for
    matmul-likes, the declared degree for a partial-sum Reduction, the
    normalized axis for softmax/norms. 1 = nothing meaningful."""
    if op.op_type == OperatorType.OP_REDUCTION:
        return max(1, getattr(op.params, "reduction_degree", 1))
    if not op.inputs:
        return 1
    mat = op.inputs[0].material_shape()
    if not mat:
        return 1
    return max(1, mat[-1])


def estimate_drift(graph) -> Tuple[float, Dict[int, float]]:
    """(longest-path accumulated drift, per-op contribution by guid).

    Per-op contribution: one rounding in the compute dtype (eps/2) plus,
    for accumulating ops, a random-walk accumulation term
    eps(accum)/2 * sqrt(width). fp32 contributions (~6e-8) are counted
    but numerically negligible, so a full-precision graph's total is
    effectively zero."""
    contrib: Dict[int, float] = {}
    drift_at: Dict[int, float] = {}
    total = 0.0
    for op in graph.topo_order():
        base = max(
            (drift_at.get(t.guid, 0.0) for t in op.inputs), default=0.0
        )
        c = 0.0
        out = next((t for t in op.outputs
                    if effective_dtype(t) in _FLOAT_DTYPES), None)
        if out is not None:
            c = _EPS[effective_dtype(out)] / 2.0
            if op.op_type in _ACCUMULATING:
                acc = effective_accum_dtype(out)
                if acc in _FLOAT_DTYPES:
                    c += (_EPS[acc] / 2.0) * math.sqrt(_reduction_width(op))
        contrib[op.guid] = c
        here = base + c
        for t in op.outputs:
            drift_at[t.guid] = here
        total = max(total, here)
    return total, contrib


def _check_boundaries(graph, rep: AnalysisReport) -> None:
    """FFA701: float inputs of one op with differing effective dtypes."""
    for op in graph.topo_order():
        if len(op.inputs) < 2 or op.op_type in _MIXED_DTYPE_OK:
            continue
        seen: Dict[DataType, int] = {}
        for i, t in enumerate(op.inputs):
            dt = effective_dtype(t)
            if dt in _FLOAT_DTYPES:
                seen.setdefault(dt, i)
        if len(seen) > 1:
            names = ", ".join(
                f"input {i}: {dt.name}" for dt, i in sorted(
                    seen.items(), key=lambda kv: kv[1])
            )
            rep.add(
                Severity.ERROR, "FFA701",
                f"op boundary mixes float dtypes with no explicit cast "
                f"({names}) — XLA inserts an unaudited implicit convert "
                "whose direction (widen vs silently narrow) depends on "
                "operand order", op=op,
                fix_hint="insert an OP_CAST on the narrower operand "
                         "(model.cast) or annotate both sides to one "
                         "compute dtype",
            )


def _check_accumulation(graph, rep: AnalysisReport) -> None:
    """FFA702: accumulating op whose accumulator is <=16-bit."""
    for op in graph.topo_order():
        if op.op_type not in _ACCUMULATING or not op.outputs:
            continue
        out = op.outputs[0]
        if effective_dtype(out) not in _FLOAT_DTYPES:
            continue
        acc = effective_accum_dtype(out)
        if acc in _LOW_PRECISION:
            w = _reduction_width(op)
            rep.add(
                Severity.ERROR, "FFA702",
                f"{op.op_type.name} accumulates {w} terms in {acc.name} "
                "with no fp32 accumulator — relative error grows "
                f"~sqrt({w})*2^-{int(-math.log2(_EPS[acc]))} and the "
                "MXU's fp32 accumulate costs nothing", op=op,
                fix_hint="set accum_dtype=DT_FLOAT on the op's output "
                         "(the default precision rule does)",
            )


def _check_grad_collectives(graph, views, num_devices,
                            grad_dtype: Optional[DataType],
                            rep: AnalysisReport) -> None:
    """FFA703: low-precision reduction collectives over wide rings."""
    from .collectives import _view_of

    views = views or {}
    for op in graph.topo_order():
        if op.op_type == OperatorType.OP_REDUCTION:
            t = op.inputs[0] if op.inputs else None
            if t is None:
                continue
            dt = effective_dtype(t)
            p = max(1, getattr(op.params, "reduction_degree", 1))
            if dt in _LOW_PRECISION and p >= RING_DEGREE_THRESHOLD:
                rep.add(
                    Severity.WARNING, "FFA703",
                    f"partial-sum all-reduce over ring degree {p} in "
                    f"{dt.name}: rms reduction error grows ~sqrt({p}) "
                    "with the ring width", op=op,
                    fix_hint="reduce in fp32 (cast before the Reduction "
                             "or keep the partial outputs' accum fp32)",
                )
        elif op.op_type == OperatorType.OP_WEIGHT_SHARD:
            p = max(1, getattr(op.params, "shard_degree", 1))
            gdt = grad_dtype
            if gdt in _LOW_PRECISION and p >= RING_DEGREE_THRESHOLD:
                rep.add(
                    Severity.WARNING, "FFA703",
                    f"FSDP weight-grad reduce-scatter over ring degree "
                    f"{p} in {gdt.name}: rms reduction error grows "
                    f"~sqrt({p})", op=op,
                    fix_hint="force fp32 gradient storage "
                             "(FFConfig.bf16_grads=False) for this shard "
                             "degree",
                )
    # implicit data-parallel weight-grad sync: one aggregate warning —
    # every weight-carrying compute op syncs at the data degree, so
    # per-op repeats would just be noise
    if grad_dtype in _LOW_PRECISION:
        synced = [op for op in graph.topo_order()
                  if op.weights and not op.is_parallel_op]
        degrees = []
        for op in synced:
            v = _view_of(op, views)
            p = v.num_parts() if v is not None else (num_devices or 1)
            degrees.append(max(1, p))
        pmax = max(degrees, default=1)
        if pmax >= RING_DEGREE_THRESHOLD:
            rep.add(
                Severity.WARNING, "FFA703",
                f"{len(synced)} weight-grad all-reduce(s) ride the ring "
                f"at degree {pmax} in {grad_dtype.name}: rms reduction "
                f"error grows ~sqrt({pmax})",
                fix_hint="FFConfig.bf16_grads=False trades the wire "
                         "width back for fp32 reduction",
            )


def _check_guard_range(graph, step_guard, rep: AnalysisReport) -> None:
    """FFA704: loss-scale / step-guard bounds vs dtype dynamic range."""
    dtypes = set()
    for op in graph.topo_order():
        for t in op.outputs:
            dt = effective_dtype(t)
            if dt in _LOW_PRECISION:
                dtypes.add(dt)
    if not dtypes:
        return
    if DataType.DT_HALF in dtypes and (
            step_guard is None
            or getattr(step_guard, "init_loss_scale", 1.0) <= 1.0):
        rep.add(
            Severity.WARNING, "FFA704",
            "float16 compute without loss scaling (step guard absent or "
            "init_loss_scale <= 1): f16's dynamic range tops out at "
            "~6.5e4 and small gradients underflow its ~6e-5 smallest "
            "normal",
            fix_hint="fit(step_guard=StepGuardConfig("
                     "init_loss_scale=2**15)) or compute in bf16",
        )
    if step_guard is None:
        return
    init = float(getattr(step_guard, "init_loss_scale", 1.0))
    max_ls = getattr(step_guard, "max_loss_scale", None)
    max_ls = float(max_ls) if max_ls is not None else init
    min_ls = float(getattr(step_guard, "min_loss_scale", 0.0))
    for dt in sorted(dtypes):
        fi = np.finfo(dt.np_dtype)
        if max_ls > float(fi.max):
            rep.add(
                Severity.WARNING, "FFA704",
                f"loss-scale ceiling {max_ls:g} exceeds {dt.name}'s max "
                f"finite value {float(fi.max):g} — the scaled loss "
                "overflows before the guard can back off",
                fix_hint=f"cap max_loss_scale below {float(fi.max):g}",
            )
        if min_ls and min_ls < float(fi.tiny):
            rep.add(
                Severity.WARNING, "FFA704",
                f"min_loss_scale {min_ls:g} is below {dt.name}'s "
                f"smallest normal {float(fi.tiny):g} — backoff can park "
                "the scale in the subnormal range where the guard math "
                "itself flushes to zero",
                fix_hint=f"raise min_loss_scale to >= {float(fi.tiny):g}",
            )


def _check_drift_budget(graph, drift_budget: Optional[float],
                        rep: AnalysisReport) -> None:
    """FFA705: longest-path accumulated drift vs the budget."""
    budget = drift_budget if drift_budget is not None \
        else DEFAULT_DRIFT_BUDGET
    if budget <= 0:
        return
    total, contrib = estimate_drift(graph)
    if total <= budget:
        return
    worst_guid = max(contrib, key=lambda g: contrib[g])
    worst = next(op for op in graph.topo_order() if op.guid == worst_guid)
    rep.add(
        Severity.ERROR, "FFA705",
        f"static drift estimate {total:.4g} exceeds the budget "
        f"{budget:.4g} along the longest path; largest single "
        f"contribution {contrib[worst_guid]:.4g} from {worst.name} "
        f"({worst.op_type.name})", op=worst,
        fix_hint=f"promote {worst.name} (fp32 accum_dtype, or cast its "
                 "inputs up) or raise "
                 "FFConfig.precision_drift_budget if the tolerance "
                 "is intended",
    )


def precision_diagnostics(graph, views: Optional[Dict] = None,
                          num_devices: Optional[int] = None, *,
                          drift_budget: Optional[float] = None,
                          grad_dtype: Optional[DataType] = None,
                          step_guard=None) -> AnalysisReport:
    """Run the FFA7xx precision checks over a (possibly annotated) PCG.

    Un-annotated graphs analyze at their declared data_types — a pure
    fp32 graph is clean by construction, so the pass is safe in every
    pre-annotation hook (strategy validators, rule lint)."""
    rep = AnalysisReport()
    _check_boundaries(graph, rep)
    _check_accumulation(graph, rep)
    _check_grad_collectives(graph, views, num_devices, grad_dtype, rep)
    _check_guard_range(graph, step_guard, rep)
    _check_drift_budget(graph, drift_budget, rep)
    return rep
