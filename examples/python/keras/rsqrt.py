"""rsqrt + tensor addition (reference: examples/python/keras/rsqrt.py)."""
import numpy as np

import flexflow.keras.models
import flexflow.keras.optimizers
from flexflow.keras.layers import Input, Dense
from flexflow.keras.backend.internal import rsqrt

from _example_args import example_args


def top_level_task(args):
    in1 = Input(shape=(32,), dtype="float32")
    in2 = Input(shape=(20,), dtype="float32")
    x = Dense(20, activation="relu")(in1)
    out = rsqrt(x + in2)
    model = flexflow.keras.models.Model([in1, in2], out)
    model.compile(optimizer=flexflow.keras.optimizers.Adam(learning_rate=0.001),
                  loss="mean_squared_error", metrics=["mean_squared_error"],
                  batch_size=args.batch_size)
    n = args.num_samples
    model.fit([np.random.randn(n, 32).astype(np.float32),
               np.ones((n, 20), np.float32)],
              np.random.randn(n, 20).astype(np.float32), epochs=args.epochs)


if __name__ == "__main__":
    print("rsqrt")
    top_level_task(example_args(epochs=2, num_samples=512))
