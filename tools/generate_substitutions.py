#!/usr/bin/env python
"""Generate the shipped substitution-rule collection.

The reference ships substitutions/graph_subst_3_v2.json (~700 KB of
TASO-generated rewrite rules, loaded by src/runtime/substitution_loader.cc).
This emits our equivalent asset — flexflow_tpu/search/substitutions/
graph_subst_tpu_v1.json — in the SAME `_t`-tagged schema, covering the
per-op partition/combine rewrites the declarative path adds on top of the
programmatic xfers (search/substitution.py):

  * per-op sample-dim (dim 0) partition sandwiches for Linear, Softmax,
    elementwise add/mul, and BatchMatmul — unlike the programmatic
    `partition_batch`, these parallelize ONE op without requiring every
    activation in the graph to have a divisible batch dim;
  * column-parallel BatchMatmul (partition the rhs' LAST dim) — not in
    the programmatic vocabulary at all: it is the only way the search
    can parallelize a batch-1 matmul chain;
  * STRUCTURAL rules: combine->partition elision (removes a redundant
    reshard pair the per-op sandwiches leave between adjacent ops) and
    attention head-partition (attribute parallelism as a declarative
    rule — PM_PARALLEL_DEGREE on the dst compute op shards the
    head-tagged weight dims; reference substitution.cc:1764).

Degrees cover 2..32 so the rules reach pod-scale machines (a degree
that exceeds the searched machine simply never validates).

Regenerate with:  python tools/generate_substitutions.py
"""
import json
import os

DEGREES = (2, 4, 8, 16, 32)


def t(op_id, ts_id=0):
    return {"_t": "Tensor", "opId": op_id, "tsId": ts_id}


def para(dim, degree):
    return [
        {"_t": "Parameter", "key": "PM_PARALLEL_DIM", "value": dim},
        {"_t": "Parameter", "key": "PM_PARALLEL_DEGREE", "value": degree},
    ]


def op(type_str, inputs, params=None):
    return {"_t": "Operator", "type": type_str, "input": inputs,
            "para": params or []}


def pm(key, value):
    return {"_t": "Parameter", "key": key, "value": value}


def rule(name, src, dst, src_out=None, dst_out=None, mapped=None):
    """mapped: [(srcOpId, srcTsId, dstOpId, dstTsId), ...] for rules with
    several surviving outputs (merge rules); src_out/dst_out is the
    single-output shorthand."""
    if mapped is None:
        mapped = [(src_out[0], src_out[1], dst_out[0], dst_out[1])]
    return {
        "_t": "Rule", "name": name, "srcOp": src, "dstOp": dst,
        "mappedOutput": [{"_t": "MapOutput", "srcOpId": so, "srcTsId": st,
                          "dstOpId": do, "dstTsId": dt}
                         for (so, st, do, dt) in mapped],
    }


def unary_batch(op_type, short, d):
    """partition(dim0) -> op -> combine(dim0)."""
    return rule(
        f"partition_{short}_batch_{d}",
        src=[op(op_type, [t(-1)])],
        dst=[
            op("OP_PARTITION", [t(-1)], para(0, d)),
            op(op_type, [t(0)]),
            op("OP_COMBINE", [t(1)], para(0, d)),
        ],
        src_out=(0, 0), dst_out=(2, 0),
    )


def binary_batch(op_type, short, d):
    """Both operands partitioned over dim 0. For OP_BATCHMATMUL this is
    only meaningful at rank >= 3 (at rank 2 the rhs dim 0 is the
    contraction dim — a partial sum, not data parallelism); the loader's
    _infer_outputs rejects such matches, so rank-2 sites are skipped."""
    return rule(
        f"partition_{short}_batch_{d}",
        src=[op(op_type, [t(-1), t(-2)])],
        dst=[
            op("OP_PARTITION", [t(-1)], para(0, d)),
            op("OP_PARTITION", [t(-2)], para(0, d)),
            op(op_type, [t(0), t(1)]),
            op("OP_COMBINE", [t(2)], para(0, d)),
        ],
        src_out=(0, 0), dst_out=(3, 0),
    )


def matmul_column(d, rank):
    """Column-parallel batch matmul: shard the rhs' last dim; the lhs is
    consumed whole. Rank-specific because PM_PARALLEL_DIM is absolute."""
    dim = rank - 1
    return rule(
        f"partition_matmul_col{rank}_{d}",
        src=[op("OP_BATCHMATMUL", [t(-1), t(-2)])],
        dst=[
            op("OP_PARTITION", [t(-2)], para(dim, d)),
            op("OP_BATCHMATMUL", [t(-1), t(0)]),
            op("OP_COMBINE", [t(1)], para(dim, d)),
        ],
        src_out=(0, 0), dst_out=(2, 0),
    )


def combine_partition_elide(dim, d):
    """combine(dim,d) -> partition(dim,d) is an identity round-trip: the
    per-op partition sandwiches leave one between every pair of adjacent
    parallelized ops; eliding it removes two reshard collectives. The
    loader's dim+degree matching guarantees the pair really round-trips."""
    return rule(
        f"elide_combine_partition_d{dim}_{d}",
        src=[
            op("OP_COMBINE", [t(-1)], para(dim, d)),
            op("OP_PARTITION", [t(0)], para(dim, d)),
        ],
        dst=[op("OP_NOOP", [t(-1)])],
        src_out=(1, 0), dst_out=(0, 0),
    )


def attention_head_partition(d):
    """Attribute parallelism over attention heads as a DECLARATIVE rule:
    PM_PARALLEL_DEGREE on the dst compute op shards its head-tagged
    weight dims (reference: substitution.cc:1764
    create_partition_attention_combine)."""
    mha_in = [t(-1), t(-2), t(-3)]
    return rule(
        f"partition_attention_heads_{d}",
        src=[op("OP_MULTIHEAD_ATTENTION", mha_in)],
        dst=[op("OP_MULTIHEAD_ATTENTION", mha_in,
                [{"_t": "Parameter", "key": "PM_PARALLEL_DEGREE",
                  "value": d}])],
        src_out=(0, 0), dst_out=(0, 0),
    )


# ActiMode values (reference: ffconst.h ActiMode / our ff_types.ActiMode)
AC_NONE = 10
ACTI_VALUE = {"OP_RELU": 11, "OP_SIGMOID": 12, "OP_TANH": 13, "OP_GELU": 14}


def fuse_epilogue(base, act, short):
    """TASO-class fusion chain: linear/conv + activation -> ONE op with
    the activation folded into its epilogue (PM_ACTI on the dst op; the
    reference corpus carries the analogous fuse_conv_relu rules and the
    C++ ops fuse via cudnnActivationForward). Removes the separate
    HBM-bound elementwise pass entirely — on TPU the epilogue runs in
    the matmul's VPU tail, which is why the cost model prices the fused
    form cheaper and the search adopts it. The PM_ACTI=NONE constraint
    on the src op keeps the rule from stacking onto an already-fused
    epilogue."""
    return rule(
        f"fuse_{short}",
        src=[op(base, [t(-1)], [pm("PM_ACTI", AC_NONE)]),
             op(act, [t(0)])],
        dst=[op(base, [t(-1)], [pm("PM_ACTI", ACTI_VALUE[act])])],
        src_out=(1, 0), dst_out=(0, 0),
    )


def merge_parallel(base, short, axis):
    """TASO merge-parallel-ops: two linears/convs reading the SAME input
    become one op with summed out_channels + a split (reference corpus:
    the merge_group_convs / two-matmuls-one-input family). One bigger
    MXU gemm beats two smaller ones, and the merged op parallelizes as
    a unit. PM_MERGE=2 triggers the loader's merge path (params equal
    except out_channels; fresh weights at the merged shape); the split
    axis is the channel axis (last for linear, 1 for conv NCHW)."""
    return rule(
        f"merge_parallel_{short}s",
        src=[op(base, [t(-1)]), op(base, [t(-1)])],
        dst=[
            op(base, [t(-1)], [pm("PM_MERGE", 2)]),
            op("OP_SPLIT", [t(0)], [pm("PM_AXIS", axis)]),
        ],
        mapped=[(0, 0, 1, 0), (1, 0, 1, 1)],
    )


def a2a_reshard(gather_dim, scatter_dim, d):
    """DCN-aware reshard collapse: combine(gather_dim, d) immediately
    followed by partition(scatter_dim, d) is a resharding round-trip
    that moves the WHOLE tensor twice (all-gather + scatter) — as one
    OP_ALLTOALL each chip exchanges only its 1/d shard pairwise. On a
    flat machine this halves reshard cost; across a DCN boundary
    (machine_config_multislice) it is the difference between the full
    tensor crossing DCN twice and only the cross-slice shard fraction
    crossing once (network.py all_to_all_cost vs 2x reshard_cost)."""
    return rule(
        f"a2a_reshard_d{gather_dim}to{scatter_dim}_{d}",
        src=[
            op("OP_COMBINE", [t(-1)], para(gather_dim, d)),
            op("OP_PARTITION", [t(0)], para(scatter_dim, d)),
        ],
        dst=[op("OP_ALLTOALL", [t(-1)], [
            pm("PM_SCATTER_DIM", scatter_dim),
            pm("PM_GATHER_DIM", gather_dim),
            pm("PM_PARALLEL_DEGREE", d),
        ])],
        src_out=(1, 0), dst_out=(0, 0),
    )


def main():
    rules = []
    for base, short in (("OP_LINEAR", "linear"), ("OP_CONV2D", "conv")):
        for act in ("OP_RELU", "OP_SIGMOID", "OP_TANH"):
            rules.append(fuse_epilogue(base, act,
                                       f"{short}_{act[3:].lower()}"))
    rules.append(fuse_epilogue("OP_LINEAR", "OP_GELU", "linear_gelu"))
    rules.append(merge_parallel("OP_LINEAR", "linear", -1))
    rules.append(merge_parallel("OP_CONV2D", "conv", 1))
    for d in DEGREES:
        rules.append(a2a_reshard(0, 1, d))
        rules.append(a2a_reshard(1, 0, d))
    for d in DEGREES:
        rules.append(unary_batch("OP_LINEAR", "linear", d))
        rules.append(unary_batch("OP_SOFTMAX", "softmax", d))
        rules.append(unary_batch("OP_RELU", "relu", d))
        rules.append(binary_batch("OP_EW_ADD", "ewadd", d))
        rules.append(binary_batch("OP_EW_MUL", "ewmul", d))
        rules.append(binary_batch("OP_BATCHMATMUL", "matmul", d))
        rules.append(matmul_column(d, rank=3))
        rules.append(matmul_column(d, rank=2))
        rules.append(combine_partition_elide(0, d))
        rules.append(combine_partition_elide(1, d))
        rules.append(attention_head_partition(d))
    out = {"rule": rules}
    path = os.path.join(os.path.dirname(__file__), "..", "flexflow_tpu",
                        "search", "substitutions",
                        "graph_subst_tpu_v1.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path} ({len(rules)} rules)")


if __name__ == "__main__":
    main()
