"""Elastic runtime: survive topology changes, not just process restarts.

PR 1's resilience layer (runtime/resilience.py) lets a run survive faults
on the SAME machine; this layer handles the machine itself changing. The
framework's core premise (FlexFlow MLSys'19 / Unity OSDI'22) is that the
best parallelization strategy is a function of the machine — so when a
host of a TPU pod is lost (or capacity grows back), the right move is to
re-run the strategy search for the surviving device set, re-compile, and
reshard the last checkpoint onto the new mesh, not to wait for the
identical slice to return.

Three pieces:

* **Topology fingerprinting + elastic resume** — `save_checkpoint`
  records the mesh/device topology and per-op MachineViews in the
  sidecar (runtime/checkpoint.py meta version 3). `restore_elastic`
  builds a fresh model for the LIVE topology (compile() re-runs the
  strategy search for it), restores the checkpoint with name-based
  weight matching, and validates the re-searched views against the live
  device count. `FFModel.fit(..., elastic=True)` wires the same path
  into the training loop's resume.

* **Health watchdog** — `HealthMonitor` heartbeats in the background (a
  lightweight collective, or a file transport on shared storage) and
  watches per-step progress; a step that outlives `timeout_s` is a hung
  collective (deadlocked psum after a silent host loss, a wedged
  straggler) and escalates hang -> CollectiveTimeout -> fit's
  checkpoint-and-raise, so the orchestrator restarts elastically instead
  of burning TPU-hours in a deadlock.

* **Fault simulation** — `shrunk_devices` shrinks what `jax.devices()`
  reports so host-loss -> re-search -> reshard runs entirely on the CPU
  mesh (tests/test_elastic.py; FaultInjector sites ``hung_step`` and
  ``host_loss`` live in runtime/resilience.py).
"""
from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .resilience import CheckpointManager, CollectiveTimeout  # noqa: F401
from .resilience import RestoreResult

logger = logging.getLogger("flexflow_tpu.runtime.elastic")


class ElasticRestoreError(RuntimeError):
    """restore_elastic could not produce a usable model (no checkpoint,
    or the re-searched strategy is invalid for the live topology)."""


# ----------------------------------------------------------------------
# topology fingerprinting
# ----------------------------------------------------------------------
def topology_fingerprint(mesh=None, fault_domains=None) -> dict:
    """A JSON-serializable description of the device topology a model is
    compiled against (the checkpoint sidecar's ``topology`` entry). With
    a mesh, describes THAT mesh (what the executable actually spans);
    without, the process-visible device set.

    Beyond the aggregate counts, the fingerprint records *structure*:
    ``per_process_devices`` (device ids grouped by owning process) and —
    when a FaultDomainMap is given — ``slices`` (device ids per fault
    domain), so `topology_matches`/`topology_diff` can tell "same device
    count, different failure-domain shape" apart (2 slices x 8 devices
    is NOT 1 x 16: a strategy searched for one shape may shard state
    across a boundary the other doesn't have)."""
    import jax

    if mesh is not None:
        devs = list(mesh.devices.flat)
        axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    else:
        devs = jax.devices()
        axes = {}
    try:
        nproc = jax.process_count()
    except RuntimeError as e:  # backend not initialized yet
        logger.warning(
            "topology_fingerprint: jax.process_count() unavailable (%r); "
            "recording num_processes=1", e,
        )
        nproc = 1
    per_process: Dict[str, List[int]] = {}
    for d in devs:
        per_process.setdefault(
            str(getattr(d, "process_index", 0)), []
        ).append(int(getattr(d, "id", 0)))
    fp = {
        "num_devices": len(devs),
        "num_processes": nproc,
        "platform": devs[0].platform if devs else "unknown",
        "device_kinds": sorted({
            str(getattr(d, "device_kind", "unknown")) for d in devs
        }),
        "mesh_axes": axes,
        "per_process_devices": {k: sorted(v)
                                for k, v in sorted(per_process.items())},
    }
    if fault_domains is not None:
        fp["slices"] = [list(s) for s in fault_domains.slices]
    return fp


def topology_matches(saved: Optional[dict], live: Optional[dict]) -> bool:
    """Whether a checkpoint's recorded topology still describes the live
    machine (device count / process count / platform — mesh axis layout
    may legally differ between equally-sized searches). When BOTH sides
    recorded fault-domain structure, the slice shape must match too:
    2x8 and 1x16 have the same device count but different failure
    domains, and the searched strategy depends on which one it is. Old
    sidecars without structure compare on counts alone."""
    if not saved or not live:
        return True  # old sidecars carry no fingerprint: assume unchanged
    if not all(
        saved.get(k) == live.get(k)
        for k in ("num_devices", "num_processes", "platform")
    ):
        return False
    if saved.get("slices") is not None and live.get("slices") is not None:
        shape = lambda fp: sorted(len(s) for s in fp["slices"])  # noqa: E731
        if shape(saved) != shape(live):
            return False
    return True


def topology_diff(saved: Optional[dict], live: Optional[dict]) -> List[str]:
    """Human-readable differences between two topology fingerprints —
    what elastic restore logs so the operator knows WHICH fault domain
    disappeared, not just that a count changed."""
    if not saved or not live:
        return []
    out: List[str] = []
    for key, noun in (("num_devices", "device"), ("num_processes", "process")):
        a, b = saved.get(key), live.get(key)
        if a is not None and b is not None and a != b:
            out.append(f"{noun} count {a} -> {b}")
    if saved.get("platform") != live.get("platform") and saved.get("platform"):
        out.append(
            f"platform {saved.get('platform')} -> {live.get('platform')}"
        )
    s_slices = saved.get("slices")
    l_slices = live.get("slices")
    if s_slices is not None and l_slices is not None:
        live_devs = {d for s in l_slices for d in s}
        for i, devs in enumerate(s_slices):
            gone = sorted(set(devs) - live_devs)
            if not gone:
                continue
            if len(gone) == len(devs):
                out.append(
                    f"slice {i} ({len(devs)} device(s) "
                    f"{devs[0]}-{devs[-1]}) disappeared"
                )
            else:
                out.append(
                    f"slice {i} lost device(s) {gone} of {len(devs)}"
                )
        if sorted(len(s) for s in s_slices) != sorted(
            len(s) for s in l_slices
        ) and saved.get("num_devices") == live.get("num_devices"):
            out.append(
                "failure-domain shape changed: "
                f"{'x'.join(str(len(s)) for s in s_slices) or '0'} -> "
                f"{'x'.join(str(len(s)) for s in l_slices) or '0'} "
                "(same device count)"
            )
    return out


def validate_machine_views(views: Dict, num_devices: int,
                           fault_domains=None) -> List[str]:
    """Check every searched MachineView addresses only live devices —
    every device each view enumerates, not just its bounding ids (a
    strided view can step OVER a dead device while its first/last ids
    look fine). Given a FaultDomainMap, violations name the slice a
    stale view still points into. Returns violation strings (empty =
    valid)."""
    bad = []
    for guid, view in (views or {}).items():
        if view is None:
            continue
        try:
            ids = sorted(view.device_ids())
        except Exception:  # malformed view: fall back to bound arithmetic
            last = view.start_device_id + sum(
                (d - 1) * s for d, s in zip(view.dim, view.stride)
            )
            ids = [view.start_device_id, last]
        dead = [d for d in ids if d < 0 or d >= num_devices]
        if not dead:
            continue
        msg = (
            f"op {guid}: view {view!r} addresses device"
            f"{'s' if len(dead) > 1 else ''} "
            f"{dead if len(dead) > 1 else dead[0]} of {num_devices}"
        )
        if fault_domains is not None:
            lost = sorted({
                s for s in (fault_domains.slice_of(d) for d in dead)
                if s is not None
            })
            if lost:
                msg += (
                    f" (in lost slice{'s' if len(lost) > 1 else ''} "
                    f"{lost if len(lost) > 1 else lost[0]})"
                )
            else:
                msg += " (outside every known fault domain)"
        bad.append(msg)
    return bad


# ----------------------------------------------------------------------
# elastic resume
# ----------------------------------------------------------------------
def restore_elastic(model_fn: Callable[[], "FFModel"], ckpt_dir: str,
                    *, verbose: bool = True) -> Tuple["FFModel", RestoreResult]:
    """Resume a checkpointed run on the CURRENT device topology, whatever
    it is. `model_fn` rebuilds + compiles the model (compile() runs the
    strategy search against the live device set, so the plan is already
    re-searched for whatever machine survived); the newest checkpoint
    under `ckpt_dir` is then restored with name-based weight matching and
    each array is host-gathered and re-device_put onto the new mesh.

    Returns (model, RestoreResult); `RestoreResult.meta["train"]` carries
    the data-loader cursor, so a follow-up `fit(checkpoint_dir=ckpt_dir,
    elastic=True)` continues exactly where the old topology stopped.
    Raises ElasticRestoreError when no checkpoint restores or the
    re-searched strategy addresses devices that don't exist."""
    model = model_fn()
    assert getattr(model, "executor", None) is not None, (
        "model_fn must return a compiled FFModel (call compile() inside it)"
    )
    if not model.executor.mesh_is_live():
        # model_fn compiled against a stale cached topology (e.g. it was
        # closured over a pre-shrink mesh) — re-plan for the live one
        model.recompile_for_topology()
    import jax

    ndev = len(jax.devices())
    # Redundant-search observability (ROADMAP item 4): a restore that
    # paid for a from-scratch strategy search is exactly what the
    # artifact store (runtime/artifact_store.py) exists to eliminate —
    # count it with why, so an 8->4->8 cycle can assert zero. compile()
    # records the cause in strategy_provenance: no store attached, a
    # cache miss, or a corrupt/stale entry that degraded to fresh
    # search. "manual" and "artifact_cache" sources never searched, so
    # they don't count.
    prov = getattr(model, "strategy_provenance", None) or {}
    if prov.get("source") == "search":
        from .. import obs

        cause = prov.get("cause", "no_store")
        obs.event("elastic_research", cat="runtime", cause=cause,
                  devices=ndev)
        obs.count(
            "ff_elastic_research_total",
            help="from-scratch strategy searches during elastic restore, "
                 "by cause (cache_miss|cache_corrupt|no_store)",
            cause=cause,
        )
    bad = validate_machine_views(getattr(model, "searched_views", None) or {},
                                 ndev)
    if bad:
        # the views address dead devices but the parallel STRUCTURE may
        # still fit the survivors — try a view-only re-assignment
        # (search/dp_search.py research_views) before giving up
        from ..search import for_device_count, research_views
        from ..search.cost_model import CostModel

        cost_model = model._build_cost_model()
        cost_model = CostModel(
            for_device_count(ndev, like=cost_model.machine),
            bf16=model.config.allow_mixed_precision,
        )
        result = research_views(model.graph, cost_model)
        if result.cost != float("inf") and not validate_machine_views(
            result.views, ndev
        ):
            logger.info(
                "[elastic] reassigned %d machine view(s) for the live "
                "%d-device topology (cost %.3g)",
                len(result.views), ndev, result.cost,
            )
            from .. import obs

            obs.event("elastic_research", cat="runtime",
                      views=len(result.views), devices=ndev,
                      cost=result.cost)
            model.searched_views = result.views
            bad = []
    if bad:
        raise ElasticRestoreError(
            "re-searched strategy is invalid for the live topology: "
            + "; ".join(bad)
        )
    info = CheckpointManager(ckpt_dir).restore_latest(model, elastic=True)
    if info is None:
        raise ElasticRestoreError(
            f"no restorable checkpoint under {ckpt_dir!r}"
        )
    saved_topo = (info.meta or {}).get("topology")
    live_topo = topology_fingerprint(
        model.executor.mesh,
        fault_domains=getattr(model, "fault_domains", None),
    )
    if not topology_matches(saved_topo, live_topo) and verbose:
        diff = topology_diff(saved_topo, live_topo)
        logger.warning(
            "[elastic] topology changed: checkpoint step %d was written on "
            "%s device(s), resuming on %s — strategy re-searched and "
            "parameters resharded%s",
            info.step,
            (saved_topo or {}).get("num_devices", "?"),
            live_topo["num_devices"],
            ("; " + "; ".join(diff)) if diff else "",
        )
    report = getattr(model, "_restore_report", None)
    if report and report["unmatched_model"] and verbose:
        logger.warning("[elastic] unmatched weights kept fresh init: %s",
                       ", ".join(report["unmatched_model"]))
    return model, info


# ----------------------------------------------------------------------
# health watchdog
# ----------------------------------------------------------------------
def allreduce_heartbeat() -> Callable[[], Optional[list]]:
    """A lightweight collective heartbeat: sums a tiny array across the
    local device set (and, multi-host, rendezvouses all processes). If
    the interconnect or a peer host is wedged, this call hangs — which
    the HealthMonitor's staleness check then detects."""
    import jax
    import jax.numpy as jnp

    fn = jax.jit(lambda x: x.sum())

    def beat() -> Optional[list]:
        n = len(jax.devices())
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("ff_elastic_heartbeat")
        got = float(fn(jnp.ones((n,), jnp.float32)))
        return None if got == float(n) else [f"allreduce={got}!={n}"]

    return beat


class FileHeartbeat:
    """File-transport heartbeat for CPU tests and clusters with shared
    storage: each host touches ``<dir>/<host_id>.hb``; a peer whose file
    goes stale (or an expected peer that never appeared) is a straggler.
    Usable directly as a HealthMonitor ``heartbeat_fn`` — calling it
    beats and returns the stale-peer list."""

    def __init__(self, directory: str, host_id: str, *,
                 stale_after_s: float = 30.0,
                 expected_peers: Optional[List[str]] = None):
        self.directory = os.path.abspath(directory)
        self.host_id = host_id
        self.stale_after_s = stale_after_s
        self.expected_peers = list(expected_peers or [])
        os.makedirs(self.directory, exist_ok=True)

    def _path(self, host_id: str) -> str:
        return os.path.join(self.directory, f"{host_id}.hb")

    def beat(self) -> None:
        p = self._path(self.host_id)
        tmp = f"{p}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(str(time.time()))
        os.replace(tmp, p)

    def stale_peers(self) -> List[str]:
        now = time.time()
        stale = []
        seen = set()
        for name in os.listdir(self.directory):
            if not name.endswith(".hb"):
                continue
            host = name[:-3]
            seen.add(host)
            if host == self.host_id:
                continue
            try:
                age = now - os.path.getmtime(self._path(host))
            except OSError:
                continue  # racing a peer's atomic replace
            if age > self.stale_after_s:
                stale.append(host)
        stale.extend(p for p in self.expected_peers
                     if p not in seen and p != self.host_id)
        return sorted(stale)

    def __call__(self) -> List[str]:
        self.beat()
        return self.stale_peers()


class HealthMonitor:
    """Watchdog for hung collectives and straggler hosts.

    Two signals, each checked by a poll thread:

    * **step progress** — fit() brackets every step with
      `step_started`/`step_finished` (and blocks on the step's result so
      completion is observable). A step still in flight after
      `timeout_s` is a hung collective.
    * **heartbeat** — `heartbeat_fn` (e.g. `allreduce_heartbeat()` or a
      `FileHeartbeat`) runs every `heartbeat_interval_s` in its own
      thread. A truthy return value names straggler peers; an exception,
      or the beat itself hanging past `timeout_s`, is equally fatal.

    Detection sets `hang_detected`/`hang_info`, calls `on_hang(info)`,
    and releases any simulated hang. fit() then escalates through
    checkpoint-and-raise (CollectiveTimeout). A REAL hung XLA collective
    cannot be unwound in-process — set `exit_on_hang=True` in production
    so the watchdog force-exits (os._exit(75)) after `on_hang` and the
    orchestrator restarts the run elastically; tests leave it False and
    use the FaultInjector's ``hung_step`` site, whose simulated hang IS
    interruptible."""

    def __init__(self, *, timeout_s: float = 60.0,
                 poll_interval_s: Optional[float] = None,
                 heartbeat_fn: Optional[Callable[[], Optional[list]]] = None,
                 heartbeat_interval_s: float = 5.0,
                 on_hang: Optional[Callable[[dict], None]] = None,
                 exit_on_hang: bool = False,
                 compile_grace_s: Optional[float] = None,
                 fault_domains=None):
        self.timeout_s = timeout_s
        # slice-granular failure classification: with a FaultDomainMap
        # (runtime/fault_domains.py), stale heartbeat peers aggregate per
        # slice — every host of a slice stale escalates "slice_loss"
        # (shrink onto the survivors) instead of a flat "straggler", and
        # per-slice health is exported as ff_slice_healthy{slice} gauges
        self.fault_domains = fault_domains
        # until the FIRST step completes, the step is probably inside
        # XLA compilation — which takes minutes at production scale, not
        # timeout_s — so the hung-step check gets extra slack; a timeout
        # tuned to steady-state steps would false-positive every cold
        # start (default: generous but bounded)
        self.compile_grace_s = (compile_grace_s if compile_grace_s is not None
                                else max(300.0, 10.0 * timeout_s))
        self.poll_interval_s = poll_interval_s or max(0.01, timeout_s / 4.0)
        self.heartbeat_fn = heartbeat_fn
        self.heartbeat_interval_s = heartbeat_interval_s
        self.on_hang = on_hang
        self.exit_on_hang = exit_on_hang
        self.hang_detected = False
        self.hang_info: dict = {}
        self._stop = threading.Event()
        self._hang_release = threading.Event()
        self._lock = threading.Lock()
        self._in_step = False
        self._steps_done = 0
        self._step = -1
        self._last_progress = time.monotonic()
        self._last_beat_ok = time.monotonic()
        self._threads: List[threading.Thread] = []
        self._started = False

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "HealthMonitor":
        if self._started:
            return self
        self._started = True
        self._last_progress = time.monotonic()
        self._last_beat_ok = time.monotonic()
        watcher = threading.Thread(target=self._watch_loop, daemon=True,
                                   name="ff-health-watchdog")
        self._threads.append(watcher)
        watcher.start()
        if self.heartbeat_fn is not None:
            hb = threading.Thread(target=self._heartbeat_loop, daemon=True,
                                  name="ff-health-heartbeat")
            self._threads.append(hb)
            hb.start()
        return self

    def stop(self) -> None:
        """Safe from any thread, including the monitor's own watchdog/
        heartbeat threads — serving failover (runtime/serving.ReplicaSet)
        stops the dead replica's monitor from inside its `on_hang`
        callback, which runs ON the watchdog thread; joining yourself
        raises, so the current thread is skipped (it exits on the next
        `_stop` check anyway)."""
        self._stop.set()
        self._hang_release.set()
        me = threading.current_thread()
        for t in self._threads:
            if t is not me:
                t.join(timeout=2.0)
        self._threads = []
        self._started = False

    @property
    def running(self) -> bool:
        return self._started and not self._stop.is_set()

    # -- training-loop hooks --------------------------------------------
    def step_started(self, step: int) -> None:
        with self._lock:
            self._in_step = True
            self._step = step
            self._last_progress = time.monotonic()

    def step_finished(self, step: int) -> None:
        with self._lock:
            self._in_step = False
            self._steps_done += 1
            self._last_progress = time.monotonic()

    def simulate_hang(self) -> None:
        """FaultInjector seam (site ``hung_step``): behave like a step
        blocked in a dead collective — progress stops until the watchdog
        notices and releases us (bounded so a broken watchdog can't
        deadlock the test suite)."""
        with self._lock:
            self._in_step = True
            self._last_progress = time.monotonic()
        self._hang_release.wait(timeout=self.timeout_s * 20.0 + 5.0)
        with self._lock:
            self._in_step = False

    # -- internals -------------------------------------------------------
    def _escalate(self, kind: str, detail: dict) -> None:
        from .. import obs

        with self._lock:
            if self.hang_detected:
                return
            self.hang_detected = True
            self.hang_info = {"kind": kind, "step": self._step,
                              "timeout_s": self.timeout_s, **detail}
        logger.error("health watchdog: %s detected (%s)", kind,
                     self.hang_info)
        obs.event("watchdog_fired", cat="runtime", **self.hang_info)
        obs.count("ff_watchdog_hangs_total",
                  help="hangs/stragglers the health watchdog detected",
                  kind=kind)
        if self.on_hang is not None:
            try:
                self.on_hang(dict(self.hang_info))
            except Exception:
                logger.exception("on_hang callback failed")
        self._hang_release.set()
        if self.exit_on_hang:
            # a wedged collective cannot be interrupted in-process; exit
            # so the orchestrator restarts elastically. 75 = EX_TEMPFAIL.
            logger.critical("health watchdog: force-exiting hung process")
            os._exit(75)

    def _watch_loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            now = time.monotonic()
            with self._lock:
                in_step = self._in_step
                step_age = now - self._last_progress
                beat_age = now - self._last_beat_ok
                step_timeout = (self.timeout_s if self._steps_done
                                else self.timeout_s + self.compile_grace_s)
            if in_step and step_age > step_timeout:
                self._escalate("hung_step", {"stalled_for_s": step_age})
                return
            if self.heartbeat_fn is not None and beat_age > max(
                self.timeout_s, 2.0 * self.heartbeat_interval_s
            ):
                self._escalate("heartbeat_stalled",
                               {"stalled_for_s": beat_age})
                return

    def _heartbeat_loop(self) -> None:
        from .. import obs

        while not self._stop.is_set():
            t0 = time.monotonic()
            try:
                bad = self.heartbeat_fn()
            except Exception as e:
                self._escalate("heartbeat_error", {"error": repr(e)})
                return
            if bad:
                detail: dict = {"peers": list(bad)}
                kind = "straggler"
                if self.fault_domains is not None:
                    cls = self.fault_domains.classify_stale(list(bad))
                    detail["classification"] = cls.describe()
                    detail["lost_slices"] = list(cls.lost_slices)
                    detail["degraded_slices"] = list(cls.degraded_slices)
                    detail["surviving_devices"] = cls.surviving_devices
                    if cls.kind == "slice_loss":
                        kind = "slice_loss"
                    for s in cls.lost_slices:
                        obs.gauge_set("ff_slice_healthy", 0.0,
                                      help="1 while a fault domain's hosts "
                                           "all heartbeat, 0 once lost",
                                      slice=s)
                self._escalate(kind, detail)
                return
            with self._lock:
                self._last_beat_ok = time.monotonic()
            # telemetry feed: each good beat counts, and the beat's own
            # duration is a cheap interconnect-health signal
            obs.count("ff_heartbeats_total",
                      help="successful health-monitor heartbeats")
            if self.fault_domains is not None:
                for s in range(self.fault_domains.num_slices):
                    obs.gauge_set("ff_slice_healthy", 1.0,
                                  help="1 while a fault domain's hosts "
                                       "all heartbeat, 0 once lost",
                                  slice=s)
            obs.gauge_set("ff_heartbeat_seconds",
                          time.monotonic() - t0,
                          help="duration of the last heartbeat probe")
            self._stop.wait(self.heartbeat_interval_s)


# ----------------------------------------------------------------------
# fault simulation (CPU-testable topology changes)
# ----------------------------------------------------------------------
@contextlib.contextmanager
def shrunk_devices(n: int):
    """Make `jax.devices()` / `jax.local_device_count()` report only the
    first `n` devices — the CPU-mesh stand-in for a host dropping out of
    the pod (XLA cannot actually remove devices from a live process).
    Models compiled inside the context plan, search and build meshes for
    the shrunk machine; `PCGExecutor.mesh_is_live()` turns False for
    models compiled before it. Test/simulation use only."""
    import jax

    real_devices = jax.devices
    real_local_count = jax.local_device_count
    devs = real_devices()[:n]
    jax.devices = lambda *a, **k: list(devs)
    jax.local_device_count = lambda *a, **k: len(devs)
    try:
        yield list(devs)
    finally:
        jax.devices = real_devices
        jax.local_device_count = real_local_count
