"""Hand-built multi-head attention from primitive ops (reference:
examples/python/native/multi_head_attention.py — q/k/v dense, reshape to
heads, transpose, batch_matmul score/value products, merge, MLP head)."""
import argparse

from flexflow.core import *  # noqa: F401,F403
import numpy as np


def top_level_task(args):
    ffconfig = FFConfig()
    print("Python API: batch_size(%d) workers/node(%d) nodes(%d)" % (
        ffconfig.batch_size, ffconfig.workers_per_node, ffconfig.num_nodes))
    ffmodel = FFModel(ffconfig)
    bs, seq, hid, heads = (ffconfig.batch_size, args.seq_length,
                           args.hidden_size, args.num_heads)
    hd = hid // heads

    inp = ffmodel.create_tensor([bs, seq, hid], DataType.DT_FLOAT)
    q = ffmodel.dense(inp, hid)
    k = ffmodel.dense(inp, hid)
    v = ffmodel.dense(inp, hid)
    q = ffmodel.reshape(q, shape=(bs, seq, heads, hd))
    k = ffmodel.reshape(k, shape=(bs, seq, heads, hd))
    v = ffmodel.reshape(v, shape=(bs, seq, heads, hd))
    q = ffmodel.transpose(q, perm=(0, 2, 1, 3))
    k = ffmodel.transpose(k, perm=(0, 2, 3, 1))
    v = ffmodel.transpose(v, perm=(0, 2, 1, 3))
    logits = ffmodel.batch_matmul(q, k)
    out = ffmodel.batch_matmul(logits, v)
    out = ffmodel.transpose(out, perm=(0, 2, 1, 3))
    out = ffmodel.reshape(out, shape=(bs, seq, hid))
    out = ffmodel.dense(out, hid, ActiMode.AC_MODE_RELU)
    out = ffmodel.dense(out, hid)

    ffmodel.optimizer = SGDOptimizer(ffmodel)
    ffmodel.compile(
        loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR])
    label_tensor = ffmodel.label_tensor

    n = bs * 4
    x = np.random.rand(n, seq, hid).astype("float32")
    y = np.random.rand(n, seq, hid).astype("float32")
    dl_x = ffmodel.create_data_loader(inp, x)
    dl_y = ffmodel.create_data_loader(label_tensor, y)

    ffmodel.init_layers()
    ts_start = ffconfig.get_current_time()
    ffmodel.fit(x=dl_x, y=dl_y, epochs=ffconfig.epochs)
    ts_end = ffconfig.get_current_time()
    print("ELAPSED TIME = %.4fs" % (1e-6 * (ts_end - ts_start)))


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--seq-length", type=int, default=16)
    p.add_argument("--hidden-size", type=int, default=64)
    p.add_argument("--num-heads", type=int, default=4)
    args, _ = p.parse_known_args()
    print("multi-head attention")
    top_level_task(args)
