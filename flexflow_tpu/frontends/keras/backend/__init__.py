"""Keras backend package (reference: python/flexflow/keras/backend/ —
__init__.py `backend()` + backend_functions.py batch_dot/sin/cos/exp/pow/sum;
examples do `from flexflow.keras import backend as K`).
"""
from __future__ import annotations

from ..layers import BatchMatmul, Cos, Exp, Pow, ReduceSum, Sin

_FLOATX = "float32"
_EPSILON = 1e-7
_IMAGE_DATA_FORMAT = "channels_first"  # reference uses NCHW everywhere


def backend() -> str:
    return "flexflow_tpu"


def epsilon() -> float:
    return _EPSILON


def floatx() -> str:
    return _FLOATX


def set_floatx(value: str) -> None:
    global _FLOATX
    assert value in ("float16", "bfloat16", "float32", "float64")
    _FLOATX = value


def image_data_format() -> str:
    return _IMAGE_DATA_FORMAT


def set_image_data_format(value: str) -> None:
    global _IMAGE_DATA_FORMAT
    assert value in ("channels_first", "channels_last")
    _IMAGE_DATA_FORMAT = value


# functional ops (reference: backend_functions.py)

def batch_dot(x, y, name=""):
    return BatchMatmul(name=name)([x, y])


def sin(x, name=""):
    return Sin(name=name)(x)


def cos(x, name=""):
    return Cos(name=name)(x)


def exp(x, name=""):
    return Exp(name=name)(x)


def pow(x, a, name=""):
    return Pow(a, name=name)(x)


def sum(x, axis, keepdims=False, name=""):
    return ReduceSum(axis, keepdims=keepdims, name=name)(x)


from . import internal  # noqa: E402,F401
