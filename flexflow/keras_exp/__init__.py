"""Shim: reference python/flexflow/keras_exp/ (experimental Keras frontend)."""
from flexflow_tpu.frontends.keras_exp import models  # noqa: F401
