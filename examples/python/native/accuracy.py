"""Accuracy thresholds for example-driven integration tests
(reference: examples/python/native/accuracy.py ModelAccuracy)."""
from enum import Enum


class ModelAccuracy(Enum):
    MNIST_MLP = 90
    MNIST_CNN = 90
    REUTERS_MLP = 90
    CIFAR10_CNN = 90
    CIFAR10_ALEXNET = 90
