"""Import a torchvision model (reference:
examples/python/pytorch/torch_vision.py). torchvision is optional — absent
in this image, the script explains and exits cleanly; with it installed any
fx-traceable tv model imports the same way."""
import sys

from flexflow.core import *  # noqa: F401,F403
from flexflow.torch.model import PyTorchModel

from _example_args import example_args

try:
    import torchvision.models as tv
except ImportError:
    print("torchvision not installed — run examples/python/pytorch/resnet.py "
          "or regnet.py for the equivalent inline-defined models")
    sys.exit(0)


def top_level_task(args):
    ffconfig = FFConfig()
    ffconfig.batch_size = args.batch_size
    ffmodel = FFModel(ffconfig)
    input_tensor = ffmodel.create_tensor(
        [args.batch_size, 3, 224, 224], DataType.DT_FLOAT)
    model = tv.resnet18(weights=None)
    PyTorchModel(model).torch_to_ff(ffmodel, [input_tensor])
    ffmodel.optimizer = SGDOptimizer(ffmodel, 0.01)
    ffmodel.compile(loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                    metrics=[MetricsType.METRICS_ACCURACY])
    print("torchvision resnet18 imported:", len(ffmodel.layers), "layers")


if __name__ == "__main__":
    top_level_task(example_args())
