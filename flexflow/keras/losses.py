"""Shim: reference python/flexflow/keras/losses.py surface."""
from flexflow_tpu.frontends.keras.losses import *  # noqa: F401,F403
