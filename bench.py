"""Benchmark driver: trains the reference's headline Transformer benchmark
config (examples/cpp/Transformer defaults: hidden 1024, 16 heads, 12 layers,
seq 512; batch 8 per scripts/osdi22ae/bert.sh) and prints ONE JSON line with
per-chip training throughput.

Runs on whatever jax.devices() provides (one real TPU chip under the driver).
Mixed precision (bf16 compute, f32 master weights) is on — the TPU-native
equivalent of the reference's f32 cuDNN path, since bf16 is the MXU's native
input type.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np


def wait_for_backend(max_wait_s: float = 600.0) -> None:
    """The remote-TPU ("axon") tunnel can wedge — a stuck lease makes jax
    backend init block forever IN-PROCESS, where no timeout can save us.
    Probe it in subprocesses (killable) and retry until healthy; if the
    tunnel never recovers, exit loudly instead of hanging the driver."""
    platforms = os.environ.get("JAX_PLATFORMS", "axon")
    if "axon" not in platforms.split(","):
        return  # explicit cpu/tpu config: nothing to probe
    deadline = time.monotonic() + max_wait_s
    while True:
        try:
            r = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=90, capture_output=True, text=True,
            )
            if r.returncode == 0:
                return
            # fast non-zero exit = config/import error, not a wedged
            # tunnel: surface the real traceback and stop immediately
            print(r.stderr, file=sys.stderr)
            print("bench: jax backend init failed (see traceback above)",
                  file=sys.stderr)
            sys.exit(1)
        except subprocess.TimeoutExpired:
            pass
        if time.monotonic() > deadline:
            print("bench: TPU backend unreachable (axon tunnel wedged); "
                  "no measurement possible", file=sys.stderr)
            sys.exit(1)
        time.sleep(20)


def main():
    wait_for_backend()
    import jax

    from flexflow_tpu import (
        FFConfig,
        FFModel,
        LossType,
        MetricsType,
        SGDOptimizer,
    )
    from flexflow_tpu.models.transformer import build_transformer

    batch = 8
    seq, hidden, heads, layers = 512, 1024, 16, 12

    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.allow_mixed_precision = True
    model = FFModel(cfg)
    build_transformer(
        model,
        batch_size=batch,
        seq_length=seq,
        hidden_size=hidden,
        num_heads=heads,
        num_layers=layers,
    )
    model.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR],
    )
    ex = model.executor
    in_pt = ex.input_pts[0]
    rng = np.random.RandomState(0)
    x = ex.shard_batch(in_pt, rng.randn(*in_pt.material_shape()).astype(np.float32))
    y = jax.numpy.asarray(rng.randn(*in_pt.material_shape()).astype(np.float32))
    key = jax.random.PRNGKey(0)

    state = model.state

    # Force a device->host round-trip that depends on EVERY param leaf.
    # Under the remote-TPU ("axon") platform block_until_ready returns
    # before remote execution finishes, and per-leaf fetches each pay a
    # full tunnel round-trip — so reduce all leaves to one scalar on
    # device and fetch that once.
    probe = jax.jit(
        lambda params: sum(
            leaf.reshape(-1)[0].astype(jax.numpy.float32)
            for leaf in jax.tree_util.tree_leaves(params)
        )
    )

    def sync(st):
        return float(np.asarray(probe(st.params)))

    # Measure through the multi-step scan driver (executor.build_train_scan
    # — the Legion trace-replay analog): per-step host dispatch is folded
    # into one XLA program, so the number reflects device throughput, not
    # the remote-tunnel round-trip latency. The reference's bench likewise
    # replays a Legion trace per iteration (flexflow_cffi.py:2093-2102).
    scan = ex.build_train_scan()
    smoke = bool(os.environ.get("FF_BENCH_SMOKE"))
    spd = 2 if smoke else 50  # steps per dispatch
    xs = [jax.numpy.broadcast_to(x, (spd,) + x.shape)]
    ys = jax.numpy.broadcast_to(y, (spd,) + y.shape)
    keys = jax.random.split(key, spd)

    # warmup: TWO calls, not one — the first compiles against the
    # init-time param layouts, and its donated output comes back in the
    # executable's preferred layouts, which triggers ONE more compile on
    # the next call; the second warmup absorbs it so the timed loop only
    # measures steady-state execution.
    for _ in range(2):
        state, partials = scan(state, xs, ys, keys)
    sync(state)

    chunks = 1 if smoke else 3
    iters = spd * chunks
    t0 = time.perf_counter()
    for _ in range(chunks):
        state, partials = scan(state, xs, ys, keys)
    sync(state)
    elapsed = time.perf_counter() - t0

    n_chips = max(1, len(jax.devices()))
    samples_per_sec_per_chip = batch * iters / elapsed / n_chips
    print(
        json.dumps(
            {
                "metric": "transformer_train_throughput",
                "value": round(samples_per_sec_per_chip, 3),
                "unit": "samples/s/chip",
                "vs_baseline": None,
            }
        )
    )


if __name__ == "__main__":
    main()
