"""Keras-style loss objects (reference: python/flexflow/keras/losses.py).

Each carries a `.type` LossType consumed by `Model.compile(loss=...)`;
`from_logits`/`reduction`/`label_smoothing` are accepted for API parity (the
reference ignores them too — its loss kernels are fixed-function).
"""
from __future__ import annotations

from ...ff_types import LossType

__all__ = [
    "Loss",
    "CategoricalCrossentropy",
    "SparseCategoricalCrossentropy",
    "MeanSquaredError",
    "Identity",
]


class Loss:
    def __init__(self, name=None):
        self.type: LossType | None = None
        self.name = name


class CategoricalCrossentropy(Loss):
    def __init__(self, from_logits=False, label_smoothing=0, reduction="auto",
                 name="categorical_crossentropy"):
        super().__init__(name=name)
        self.type = LossType.LOSS_CATEGORICAL_CROSSENTROPY


class SparseCategoricalCrossentropy(Loss):
    def __init__(self, from_logits=False, reduction="auto",
                 name="sparse_categorical_crossentropy"):
        super().__init__(name=name)
        self.type = LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY


class MeanSquaredError(Loss):
    def __init__(self, reduction="auto", name="mean_squared_error"):
        super().__init__(name=name)
        self.type = LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE


class Identity(Loss):
    def __init__(self, reduction="auto", name="identity"):
        super().__init__(name=name)
        self.type = LossType.LOSS_IDENTITY
