"""Telemetry session: ties the tracer + metrics registry to an output
directory and to a model's recorded search trajectory.

Activate per-fit via ``model.fit(..., telemetry=TelemetryConfig(dir))``
(fit starts the session, streams per-step events, and finishes it —
flushing ``events.jsonl``, ``metrics.prom``, ``metrics.jsonl`` and the
Perfetto-loadable ``trace.json``), or manually:

    import flexflow_tpu.obs as obs
    with obs.session(obs.TelemetryConfig(dir="/tmp/tel")) as tel:
        model.fit(...)

Only ONE session is active per process (module global in obs/__init__);
runtime subsystems (checkpointing, serving, the health monitor, retry)
emit through the cheap `obs.*` helpers, which no-op when nothing is
active.
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Optional

from .metrics import MetricsRegistry
from .tracer import Tracer, to_chrome_trace


@dataclasses.dataclass
class TelemetryConfig:
    """Knobs for one telemetry session (docs/observability.md).

    dir: output directory (created if missing).
    step_events: emit one span per training step dispatch.
    sync_per_step: block on each step's loss before closing its span —
        true per-step wall time and a live loss gauge, at the cost of
        one device sync per step (off by default: spans then measure
        host dispatch time, and loss is recorded per epoch).
    grad_norm: add the global gradient norm to the jitted step's outputs
        (PCGExecutor.set_step_metrics) and gauge it per epoch — a small
        on-device cost, so opt-in.
    max_events / flush_every: event-log bounds (tracer.py).
    search_replay_limit: how many recorded search-trajectory entries are
        replayed into the event log at attach time.
    request_sample_rate: fraction of serving requests whose flight
        recorder emits spans (obs/request_trace.py; head-based, decided
        once at submit). Stage histograms and SLO counters cover ALL
        requests regardless.
    calibration_path: persistent cost-model calibration store
        (obs/calibration.py) — explain_strategy().apply() writes
        measured per-op costs through to it, and compile() under this
        session loads it back.
    step_profile: capture an in-situ measured timeline of the real
        jitted step after the training loop (obs/step_profile.py):
        measured events + HBM counter tracks into this session's log,
        the overlap-realization / HBM-reconciliation gauges, the
        simulated-vs-measured overlay (``step_timeline.json``), and —
        when calibration_path is set — the measured overlap efficiency
        and collective bandwidths written through to the store.
    step_profile_repeats: timed repeats per measurement in that capture.
    flight_recorder: keep the crash flight recorder armed (a bounded
        ring of recent events + metric samples; typed failures dump
        forensics bundles into ``<dir>/forensics/`` —
        obs/flight_recorder.py).
    flight_recorder_events: ring capacity.
    anomaly_detection: arm the session's AnomalySentinel (step-time
        regressions fire `anomaly` events — obs/anomaly.py).
    fleet_spool_dir: when set, a background thread snapshots this
        session's registry into ``<fleet_spool_dir>/<process>.spool.json``
        every fleet_spool_interval_s for cross-process aggregation
        (obs/fleet.py); a final spool with status "exited" is written at
        finish(). fleet_process defaults to ``proc-<pid>``.
    """

    dir: str
    step_events: bool = True
    sync_per_step: bool = False
    grad_norm: bool = False
    max_events: int = 200_000
    flush_every: int = 256
    search_replay_limit: int = 20_000
    request_sample_rate: float = 1.0
    calibration_path: Optional[str] = None
    step_profile: bool = False
    step_profile_repeats: int = 2
    flight_recorder: bool = True
    flight_recorder_events: int = 2048
    anomaly_detection: bool = True
    fleet_spool_dir: Optional[str] = None
    fleet_spool_interval_s: float = 2.0
    fleet_process: Optional[str] = None
    events_file: str = "events.jsonl"
    prom_file: str = "metrics.prom"
    metrics_jsonl_file: str = "metrics.jsonl"
    trace_file: str = "trace.json"


_TRAJECTORY_CAT = {
    "phase": "compile",
    "mcmc_iter": "search",
    "mcmc_native": "search",
    "xfer_candidate": "search",
    "dp_split": "search",
    "search_begin": "search",
    "search_end": "search",
    "pipeline_search": "search",
}


class Telemetry:
    """One live session: a streaming tracer + a metrics registry."""

    def __init__(self, config: TelemetryConfig):
        self.config = config
        os.makedirs(config.dir, exist_ok=True)
        events_path = os.path.join(config.dir, config.events_file)
        # a fresh session truncates stale artifacts (the tracer appends,
        # and metrics.jsonl accumulates snapshots within ONE session)
        from .step_profile import OOM_FORENSICS_FILE, OVERLAY_FILE

        for name in (config.events_file, config.metrics_jsonl_file,
                     config.prom_file, config.trace_file,
                     OVERLAY_FILE, OOM_FORENSICS_FILE):
            p = os.path.join(config.dir, name)
            if os.path.exists(p):
                os.remove(p)
        self.tracer = Tracer(events_path, flush_every=config.flush_every,
                             max_events=config.max_events)
        self.metrics = MetricsRegistry()
        # satellite of the fleet observatory: overflow past max_events
        # is visible LIVE on the metrics page, not only at close()
        dropped = self.metrics.counter(
            "ff_trace_events_dropped_total",
            "trace events dropped past the tracer's max_events cap")
        self.tracer.on_drop = dropped.inc
        self.calibration = None
        if config.calibration_path:
            from .calibration import CalibrationStore

            self.calibration = CalibrationStore(config.calibration_path)
        self.sentinel = None
        if config.anomaly_detection:
            from .anomaly import AnomalySentinel

            self.sentinel = AnomalySentinel()
        self.recorder = None
        if config.flight_recorder:
            from . import flight_recorder as _fr

            self.recorder = _fr.install(
                config.dir,
                process=config.fleet_process,
                capacity=config.flight_recorder_events)
            self.recorder.register_provider("metrics_snapshot",
                                            self.metrics.snapshot)
            self.tracer.add_sink(self.recorder.record_event)
        self.spool = None
        self._spool_stop = None
        if config.fleet_spool_dir:
            from .fleet import MetricSpool

            self.spool = MetricSpool(
                config.fleet_spool_dir,
                config.fleet_process or f"proc-{os.getpid()}",
                registry=self.metrics)
            self.spool.write()
            self._spool_stop = threading.Event()
            t = threading.Thread(target=self._spool_loop,
                                 name="ff-fleet-spool", daemon=True)
            t.start()
            self._spool_thread = t
        self._finished = False
        self._attached_models: list = []
        self.tracer.instant("session_start", cat="obs",
                            unixtime=time.time())

    def _spool_loop(self) -> None:
        while not self._spool_stop.wait(self.config.fleet_spool_interval_s):
            try:
                self.spool.write()
            except OSError as e:
                import logging

                logging.getLogger("flexflow_tpu.obs").warning(
                    "fleet spool write failed (%s)", e)

    # -- model wiring ----------------------------------------------------
    def attach_model(self, model) -> None:
        """Replay the model's compile/search trajectory into the event
        log, publish PCG-derived gauges (static collective bytes + HBM
        high-water), and arm optional step outputs (grad_norm)."""
        if model in self._attached_models:
            return
        self._attached_models.append(model)
        if self.recorder is not None:
            # forensics bundles carry the strategy + calibration
            # provenance of whatever the model is running at dump time
            self.recorder.register_provider(
                "strategy_provenance",
                lambda m=model: dict(
                    getattr(m, "strategy_provenance", None) or {}))
            if self.calibration is not None:
                self.recorder.register_provider(
                    "calibration_provenance",
                    lambda: {"path": self.config.calibration_path,
                             "dirty": self.calibration.dirty})
        traj = getattr(model, "search_trajectory", None)
        if traj is not None:
            self._replay_trajectory(traj)
        if model.graph is not None:
            self._pcg_gauges(model)
        if self.config.grad_norm and model.executor is not None:
            model.executor.set_step_metrics(("grad_norm",))

    def _replay_trajectory(self, traj) -> None:
        base = self.tracer.t0
        for rec in traj.events[: self.config.search_replay_limit]:
            kind = rec["kind"]
            cat = _TRAJECTORY_CAT.get(kind, "search")
            args = {k: v for k, v in rec.items()
                    if k not in ("kind", "t", "t0", "dur", "name")}
            if kind == "phase":
                self.tracer.emit({
                    "ts": rec["t0"] - base, "ph": "X",
                    "name": rec.get("name", "phase"), "cat": cat,
                    "dur": rec["dur"], "tid": 0, "args": args,
                })
            else:
                self.tracer.emit({
                    "ts": rec["t"] - base, "ph": "i",
                    "name": rec.get("name", kind) if kind == "phase"
                    else kind,
                    "cat": cat, "tid": 0, "args": args,
                })
        dropped = sum(traj.dropped.values())
        if dropped:
            self.tracer.instant("trajectory_truncated", cat="search",
                                dropped=dropped)
        summ = traj.summary()
        if summ.get("final_cost") is not None:
            self.metrics.gauge(
                "ff_search_best_cost_seconds",
                "simulated step time of the chosen strategy",
            ).set(summ["final_cost"])
        self.metrics.counter(
            "ff_search_mcmc_iterations_total",
            "MCMC proposals evaluated during strategy search",
        ).inc(summ["mcmc"]["iterations"])
        self.metrics.counter(
            "ff_search_candidates_total",
            "substitution candidates evaluated by the best-first search",
        ).inc(summ["substitution"]["candidates"])

    def _pcg_gauges(self, model) -> None:
        """Static PCG-derived gauges from the analysis passes."""
        from ..analysis.collectives import estimate_collective_bytes
        from ..analysis.memory import estimate_per_device_bytes

        views = getattr(model, "searched_views", None) or {}
        per_kind: dict = {}
        for rec in estimate_collective_bytes(model.graph, views):
            per_kind[rec["kind"]] = per_kind.get(rec["kind"], 0) \
                + rec["bytes"]
        for kind, nbytes in sorted(per_kind.items()):
            self.metrics.gauge(
                "ff_pcg_collective_bytes",
                "estimated per-step collective payload bytes by kind "
                "(analysis/collectives)",
                kind=kind,
            ).set(nbytes)
        ndev = 1
        if model.executor is not None:
            ndev = max(1, len(list(model.executor.mesh.devices.flat)))
        per_dev = estimate_per_device_bytes(
            model.graph, views, ndev,
            train=model._is_training_compile(),
            optimizer=model.optimizer,
            grad_bytes_ratio=model._grad_bytes_ratio(),
        )
        if per_dev:
            self.metrics.gauge(
                "ff_static_hbm_peak_bytes",
                "static per-device HBM high-water estimate "
                "(analysis/memory)",
            ).set(max(per_dev.values()))

    # -- training-loop feed ---------------------------------------------
    def record_step(self, *, step: int, dur_s: float, batch_size: int,
                    n_chips: int, loss: Optional[float] = None,
                    t0: Optional[float] = None) -> None:
        """One training step completed (or dispatched, when
        sync_per_step is off)."""
        if self.config.step_events:
            args = {"step": step, "batch_size": batch_size}
            if loss is not None:
                args["loss"] = loss
            self.tracer.emit({
                "ts": (t0 - self.tracer.t0) if t0 is not None
                else time.perf_counter() - self.tracer.t0 - dur_s,
                "ph": "X", "name": "step", "cat": "train",
                "dur": dur_s, "tid": 0, "args": args,
            })
        self.metrics.counter("ff_steps_total", "training steps run").inc()
        self.metrics.counter("ff_samples_total",
                             "training samples consumed").inc(batch_size)
        self.metrics.histogram(
            "ff_step_wall_seconds",
            "per-step wall time (dispatch time unless sync_per_step)",
        ).observe(dur_s)
        if dur_s > 0:
            self.metrics.gauge(
                "ff_samples_per_second_per_chip",
                "instantaneous training throughput per chip",
            ).set(batch_size / dur_s / max(1, n_chips))
        if loss is not None:
            self.metrics.gauge("ff_loss", "last observed loss").set(loss)
        if self.recorder is not None:
            self.recorder.record_metric("step_time_s", dur_s)
        if self.sentinel is not None:
            # min_delta keeps dispatch-time jitter (sub-ms on the async
            # path) from ever reading as a regression
            self.sentinel.observe("step_time_s", dur_s, min_delta=0.005)

    def record_chunk(self, *, first_step: int, steps: int, dur_s: float,
                     batch_size: int, n_chips: int,
                     t0: Optional[float] = None) -> None:
        """A fused multi-step dispatch completed (lax.scan driver,
        fit(iterations_per_dispatch>1)): one span covering `steps`
        steps, metrics counted per step."""
        if self.config.step_events:
            self.tracer.emit({
                "ts": (t0 - self.tracer.t0) if t0 is not None
                else time.perf_counter() - self.tracer.t0 - dur_s,
                "ph": "X", "name": "step_chunk", "cat": "train",
                "dur": dur_s, "tid": 0,
                "args": {"first_step": first_step, "steps": steps,
                         "batch_size": batch_size},
            })
        self.metrics.counter("ff_steps_total", "training steps run") \
            .inc(steps)
        self.metrics.counter("ff_samples_total",
                             "training samples consumed") \
            .inc(batch_size * steps)
        self.metrics.histogram(
            "ff_step_wall_seconds",
            "per-step wall time (dispatch time unless sync_per_step)",
        ).observe(dur_s / max(1, steps))
        if dur_s > 0:
            self.metrics.gauge(
                "ff_samples_per_second_per_chip",
                "instantaneous training throughput per chip",
            ).set(batch_size * steps / dur_s / max(1, n_chips))

    def record_epoch(self, *, epoch: int, loss: float,
                     grad_norm_sum: Optional[float] = None,
                     steps: int = 0, skipped: float = 0.0) -> None:
        """Epoch-end fold: loss gauge (always available here without a
        per-step sync), mean grad norm when the step emits it, and the
        guard's skipped-step count."""
        self.tracer.instant("epoch_end", cat="train", epoch=epoch,
                            loss=loss, steps=steps)
        self.metrics.gauge("ff_loss", "last observed loss").set(loss)
        if grad_norm_sum is not None and steps > 0:
            self.metrics.gauge(
                "ff_global_grad_norm",
                "mean global gradient norm over the last epoch",
            ).set(float(grad_norm_sum) / steps)
        if skipped:
            self.metrics.counter(
                "ff_nonfinite_skips_total",
                "steps skipped by the NaN/Inf step guard",
            ).inc(float(skipped))

    # -- output ----------------------------------------------------------
    def write_metrics(self) -> None:
        cfg = self.config
        with open(os.path.join(cfg.dir, cfg.prom_file), "w") as f:
            f.write(self.metrics.to_prometheus())
        with open(os.path.join(cfg.dir, cfg.metrics_jsonl_file), "a") as f:
            f.write(self.metrics.to_jsonl())

    def finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        self.tracer.instant("session_end", cat="obs", unixtime=time.time())
        self.tracer.close()
        self.write_metrics()
        if self.spool is not None:
            self._spool_stop.set()
            self._spool_thread.join(timeout=5.0)
            try:
                self.spool.write(status="exited")
            except OSError:  # fflint: disable=FFL002 — best-effort final
                pass
        if self.recorder is not None:
            from . import flight_recorder as _fr

            self.tracer.remove_sink(self.recorder.record_event)
            _fr.uninstall(self.recorder)
        if self.calibration is not None and self.calibration.dirty:
            self.calibration.save()
        with open(os.path.join(self.config.dir,
                               self.config.trace_file), "w") as f:
            json.dump(to_chrome_trace(self.tracer.events,
                                      lane_names=self.tracer.lane_names), f)
