#!/usr/bin/env bash
# Slice fault-domain rehearsal (ISSUE 12 satellite): the whole
# slice-granular resilience matrix — classification, drain protocol,
# FFA6xx survivability lint, whole-slice-loss failover — hardware-free.
#
# Leg 1 runs ALL of tests/test_fault_domains.py on the tier-1-shaped
# 8-device mesh (2 slices x 4). Legs 2 and 3 then scale the chaos
# stories up to a 16-device 2x8 mesh whose machine description is
# DERIVED from machine_config_multislice (same chip and DCN/ICI
# constants, 8 chips per slice so the file describes the live CPU
# mesh): leg 2 kills slice 1 mid-run and requires the same fit() call
# to finish on the 8 survivors; leg 3 delivers a deadline-bearing
# preemption notice and requires a drain (extra steps + final
# checkpoint) before the failover. Use before touching
# runtime/fault_domains.py, the drain path in fit(), or
# search/survivability.py:
#
#   scripts/multislice_check.sh              # all three legs
#   scripts/multislice_check.sh -k drain     # filter leg 1's pytest
set -euo pipefail
cd "$(dirname "$0")/.."

echo "=== multislice leg 1: fault-domain suite (8-device 2x4 mesh) ==="
env JAX_PLATFORMS=cpu \
    JAX_NUM_CPU_DEVICES=8 \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_fault_domains.py -v -p no:cacheprovider "$@"

run16() {
    env JAX_PLATFORMS=cpu \
        JAX_NUM_CPU_DEVICES=16 \
        XLA_FLAGS="--xla_force_host_platform_device_count=16" \
        python - "$@"
}

export MULTISLICE_TMP="$(mktemp -d)"
trap 'rm -rf "$MULTISLICE_TMP"' EXIT

# 2x8 machine file with machine_config_multislice's hardware constants
run16 <<'PY'
import os
from flexflow_tpu.search import parse_machine_config

base = parse_machine_config("machine_config_multislice")
assert base.num_nodes == 2
with open(os.path.join(os.environ["MULTISLICE_TMP"], "m2x8.cfg"), "w") as f:
    f.write(f"""# 2x8 derivation of machine_config_multislice (live CPU mesh)
machine_model_version = 1
num_nodes = 2
workers_per_node = 8
peak_flops_bf16 = {base.chip.peak_flops_bf16}
hbm_bandwidth = {base.chip.hbm_bandwidth}
hbm_capacity = {base.chip.hbm_capacity}
ici_bandwidth = {base.ici_bandwidth}
dcn_bandwidth = {base.dcn_bandwidth}
""")
print("wrote", f.name)
PY

echo "=== multislice leg 2: whole-slice loss -> failover (16-device 2x8) ==="
run16 <<'PY'
import os

import jax
import numpy as np

from flexflow_tpu import (
    ActiMode, DataType, FFConfig, FFModel, FaultInjector, LossType,
    SGDOptimizer,
)
from flexflow_tpu.search.survivability import strategy_survivability

assert len(jax.devices()) == 16, jax.devices()
tmp = os.environ["MULTISLICE_TMP"]
cfg = FFConfig()
cfg.batch_size = 32
cfg.machine_model_file = os.path.join(tmp, "m2x8.cfg")
m = FFModel(cfg)
x = m.create_tensor((32, 4), DataType.DT_FLOAT)
t = m.dense(x, 16, ActiMode.AC_MODE_RELU)
t = m.dense(t, 3)
t = m.softmax(t)
m.compile(SGDOptimizer(lr=0.1), LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
assert m.fault_domains is not None and m.fault_domains.num_slices == 2, \
    m.fault_domains
assert m.fault_domains.devices_in_slice(1) == tuple(range(8, 16))
cm = m._build_cost_model()
assert cm.survivability_penalty > 0  # auto-armed on the 2-slice machine
s = strategy_survivability(m.graph, getattr(m, "searched_views", None),
                           machine=cm.machine)
assert s.survivable, [o for o in s.ops if not o.survivable]

rng = np.random.RandomState(0)
xd = rng.randn(64, 4).astype(np.float32)
yd = rng.randint(0, 3, (64, 1)).astype(np.int32)
fi = FaultInjector().inject("slice_loss", at_step=1, slice=1)
m.fit(xd, yd, epochs=3, verbose=False,
      checkpoint_dir=os.path.join(tmp, "ckpt_loss"),
      checkpoint_every_n_steps=1, fault_injector=fi, elastic=True)
assert fi.fired.get("slice_loss") == 1
assert int(m.executor.mesh.devices.size) == 8, m.executor.mesh
assert {d.id for d in m.executor.mesh.devices.flat} == set(range(8))
assert m.state.step == 6, m.state.step
print("leg 2 OK: slice 1 lost at step 1, run finished on devices 0-7")
PY

echo "=== multislice leg 3: preemption drain -> failover (16-device 2x8) ==="
run16 <<'PY'
import os

import numpy as np

from flexflow_tpu import (
    ActiMode, DataType, FFConfig, FFModel, FaultInjector, LossType,
    SGDOptimizer,
)

tmp = os.environ["MULTISLICE_TMP"]
cfg = FFConfig()
cfg.batch_size = 32
cfg.machine_model_file = os.path.join(tmp, "m2x8.cfg")
m = FFModel(cfg)
x = m.create_tensor((32, 4), DataType.DT_FLOAT)
t = m.dense(x, 16, ActiMode.AC_MODE_RELU)
t = m.dense(t, 3)
t = m.softmax(t)
m.compile(SGDOptimizer(lr=0.1), LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

rng = np.random.RandomState(0)
xd = rng.randn(64, 4).astype(np.float32)
yd = rng.randint(0, 3, (64, 1)).astype(np.int32)
fi = FaultInjector().inject(
    "preemption_notice", at_step=1, deadline_s=60.0,
    max_drain_steps=2, slice=1, surviving_devices=8,
)
traj = m.search_trajectory  # failover recompile swaps in a fresh one
m.fit(xd, yd, epochs=3, verbose=False,
      checkpoint_dir=os.path.join(tmp, "ckpt_drain"),
      checkpoint_every_n_steps=2, fault_injector=fi, elastic=True)
assert fi.fired.get("preemption_notice") == 1
drains = [e for e in traj.events if e.get("kind") == "slice_drain"]
assert drains and drains[0]["drained_steps"] == 2, drains
assert drains[0]["met_deadline"], drains
assert int(m.executor.mesh.devices.size) == 8, m.executor.mesh
assert m.state.step == 6, m.state.step
print("leg 3 OK: drained 2 steps inside the 60s notice, "
      "failed over to slice 0")
PY

echo "multislice_check: all legs passed"
