"""Sharding/parallelism tests on the virtual 8-device CPU mesh.

Covers what the reference can only test with real multi-GPU runs
(tests/multi_gpu_tests.sh): data parallel, tensor parallel, and dp×tp hybrid
training steps compile and execute, and DP matches the single-device result.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu import (
    ActiMode,
    DataType,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.models.transformer import build_transformer
from flexflow_tpu.parallel.mesh import build_mesh, pspec_for_parallel_tensor
from flexflow_tpu.pcg.parallel_tensor import ParallelDim, ParallelTensor


def _small_transformer(tp=1, sp=1, batch=8, seq=16, hidden=64, heads=4, layers=2):
    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.tensor_parallel_degree = tp
    cfg.sequence_parallel_degree = sp
    model = FFModel(cfg)
    build_transformer(
        model, batch_size=batch, seq_length=seq, hidden_size=hidden,
        num_heads=heads, num_layers=layers,
    )
    model.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.METRICS_MEAN_SQUARED_ERROR],
    )
    return model


def _one_step_loss(model):
    ex = model.executor
    step = ex.build_train_step()
    in_pt = ex.input_pts[0]
    rng = np.random.RandomState(0)
    x = ex.shard_batch(in_pt, rng.randn(*in_pt.material_shape()).astype(np.float32))
    y = jnp.asarray(rng.randn(*in_pt.material_shape()).astype(np.float32))
    state, partials = step(model.state, [x], y, jax.random.PRNGKey(0))
    jax.block_until_ready(state.params)
    return float(partials["loss"])


def test_dp_transformer_step():
    model = _small_transformer()  # dp=8 on the virtual mesh
    assert model.executor.mesh.shape["data"] == 8
    loss = _one_step_loss(model)
    assert np.isfinite(loss)


def test_tp_transformer_step():
    model = _small_transformer(tp=4, batch=2)
    assert model.executor.mesh.shape["model"] == 4
    loss = _one_step_loss(model)
    assert np.isfinite(loss)


def test_dp_tp_hybrid_step():
    model = _small_transformer(tp=2, batch=8)
    m = model.executor.mesh.shape
    assert m["data"] == 4 and m["model"] == 2
    loss = _one_step_loss(model)
    assert np.isfinite(loss)


def test_tp_weight_shardings_applied():
    """TP must shard linear kernels' out dim and attention head dims."""
    model = _small_transformer(tp=2, batch=4)
    mesh = model.executor.mesh
    sharded = []
    for op in model.graph.ops:
        for name, wpt in zip(op.weight_names, op.weights):
            spec = pspec_for_parallel_tensor(wpt, mesh)
            if any(s == "model" for s in spec):
                sharded.append((op.name, name))
    assert len(sharded) > 0, "no weight is model-sharded under tp=2"


def test_dp_matches_single_device():
    """One DP training step must produce the same loss as single-device."""
    losses = []
    for ndev in (1, 8):
        cfg = FFConfig()
        cfg.batch_size = 8
        cfg.workersPerNode = ndev
        cfg.numNodes = 1
        model = FFModel(cfg)
        x = model.create_tensor((8, 12), DataType.DT_FLOAT)
        t = model.dense(x, 16, ActiMode.AC_MODE_RELU)
        t = model.dense(t, 4)
        t = model.softmax(t)
        model.compile(
            optimizer=SGDOptimizer(lr=0.1),
            loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
            metrics=[MetricsType.METRICS_ACCURACY],
        )
        ex = model.executor
        step = ex.build_train_step()
        rng = np.random.RandomState(0)
        xv = ex.shard_batch(ex.input_pts[0], rng.randn(8, 12).astype(np.float32))
        yv = jnp.asarray(rng.randint(0, 4, (8, 1)), jnp.int32)
        state, partials = step(model.state, [xv], yv, jax.random.PRNGKey(0))
        losses.append(float(partials["loss"]))
    assert losses[0] == pytest.approx(losses[1], rel=1e-5)


def test_pspec_lowering():
    """ParallelTensor dims -> PartitionSpec mapping."""
    mesh = build_mesh({"data": 4, "model": 2})
    pt = ParallelTensor(
        dims=[
            ParallelDim(size=32, degree=4, parallel_idx=0),
            ParallelDim(size=16, degree=1),
            ParallelDim(size=64, degree=2, parallel_idx=1),
        ]
    )
    spec = pspec_for_parallel_tensor(pt, mesh)
    assert tuple(spec) == ("data", None, "model")


def test_ring_attention_dispatch_under_sequence_parallel(monkeypatch):
    """With a seq-sharded mesh, the MHA op routes through ring attention
    (KV rotating over the seq axis) instead of letting XLA all-gather K/V;
    numerics must match the dense path and training must step."""
    import jax.numpy as jnp

    from flexflow_tpu import (ActiMode, DataType, FFConfig, FFModel,
                              LossType, MetricsType, SGDOptimizer)

    def build(sp, impl):
        monkeypatch.setenv("FF_ATTENTION_IMPL", impl)
        cfg = FFConfig()
        cfg.batch_size = 4
        cfg.sequence_parallel_degree = sp
        m = FFModel(cfg)
        x = m.create_tensor((4, 16, 32), DataType.DT_FLOAT)
        t = m.multihead_attention(x, x, x, 32, 4)
        t = m.dense(t, 32)
        m.compile(SGDOptimizer(lr=0.1),
                  LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
                  [MetricsType.METRICS_MEAN_SQUARED_ERROR])
        return m

    rng = np.random.RandomState(0)
    xv = rng.randn(4, 16, 32).astype(np.float32)

    m_dense = build(sp=1, impl="dense")
    want = np.asarray(m_dense.executor.build_forward()(
        m_dense.state.params, [jnp.asarray(xv)]))

    m_ring = build(sp=2, impl="ring")
    # identical weights
    for op_name, ws in m_dense.state.params.items():
        for w_name, w in ws.items():
            m_ring.state.params[op_name][w_name] = jnp.asarray(np.asarray(w))
    got = np.asarray(m_ring.executor.build_forward()(
        m_ring.state.params, [jnp.asarray(xv)]))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    # training steps through the ring path (grad via scan + ppermute)
    yv = rng.randn(4, 16, 32).astype(np.float32)
    m_ring.fit(xv, yv, epochs=1, verbose=False)


def test_flash_impl_on_sharded_mesh_routes_through_shard_map(monkeypatch):
    """FF_ATTENTION_IMPL=flash on a dp×tp mesh must not hand GSPMD-sharded
    tensors to pallas_call (it has no SPMD partitioning rule): the op wraps
    the kernel in shard_map over the data/model axes. Numerics must match
    the dense path and training must step."""
    import jax.numpy as jnp

    from flexflow_tpu import (DataType, FFConfig, FFModel,
                              LossType, MetricsType, SGDOptimizer)

    def build(tp, impl):
        monkeypatch.setenv("FF_ATTENTION_IMPL", impl)
        cfg = FFConfig()
        cfg.batch_size = 4
        cfg.tensor_parallel_degree = tp
        m = FFModel(cfg)
        x = m.create_tensor((4, 16, 32), DataType.DT_FLOAT)
        t = m.multihead_attention(x, x, x, 32, 4)
        t = m.dense(t, 32)
        m.compile(SGDOptimizer(lr=0.1),
                  LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
                  [MetricsType.METRICS_MEAN_SQUARED_ERROR])
        return m

    rng = np.random.RandomState(0)
    xv = rng.randn(4, 16, 32).astype(np.float32)

    m_dense = build(tp=1, impl="dense")
    want = np.asarray(m_dense.executor.build_forward()(
        m_dense.state.params, [jnp.asarray(xv)]))

    m_flash = build(tp=2, impl="flash")
    for op_name, ws in m_dense.state.params.items():
        for w_name, w in ws.items():
            m_flash.state.params[op_name][w_name] = jnp.asarray(np.asarray(w))
    got = np.asarray(m_flash.executor.build_forward()(
        m_flash.state.params, [jnp.asarray(xv)]))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    yv = rng.randn(4, 16, 32).astype(np.float32)
    m_flash.fit(xv, yv, epochs=1, verbose=False)


def test_flash_impl_indivisible_heads_falls_back_to_chunked(monkeypatch):
    """heads=6 on a model-degree-4 mesh can't shard the Pallas kernel:
    forced flash must warn and use chunked attention (GSPMD-partitionable)
    instead of crashing or replicating."""
    import warnings as _w

    import jax.numpy as jnp

    from flexflow_tpu import (DataType, FFConfig, FFModel,
                              LossType, MetricsType, SGDOptimizer)

    monkeypatch.setenv("FF_ATTENTION_IMPL", "flash")
    cfg = FFConfig()
    cfg.batch_size = 2
    cfg.tensor_parallel_degree = 4
    m = FFModel(cfg)
    x = m.create_tensor((2, 16, 36), DataType.DT_FLOAT)
    t = m.multihead_attention(x, x, x, 36, 6)
    m.dense(t, 36)
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        m.compile(SGDOptimizer(lr=0.1),
                  LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
                  [MetricsType.METRICS_MEAN_SQUARED_ERROR])
        rng = np.random.RandomState(0)
        xv = rng.randn(2, 16, 36).astype(np.float32)
        out = np.asarray(m.executor.build_forward()(
            m.state.params, [jnp.asarray(xv)]))
    assert np.isfinite(out).all()
    assert any("chunked" in str(w.message) for w in rec)


def test_ulysses_attention_dispatch_under_sequence_parallel(monkeypatch):
    """FF_ATTENTION_IMPL=ulysses on a seq-sharded mesh routes through the
    all_to_all head-scatter path; numerics must match dense and training
    must step (grads flow through both all_to_alls)."""
    import jax.numpy as jnp

    from flexflow_tpu import (DataType, FFConfig, FFModel,
                              LossType, MetricsType, SGDOptimizer)

    def build(sp, impl):
        monkeypatch.setenv("FF_ATTENTION_IMPL", impl)
        cfg = FFConfig()
        cfg.batch_size = 4
        cfg.sequence_parallel_degree = sp
        m = FFModel(cfg)
        x = m.create_tensor((4, 16, 32), DataType.DT_FLOAT)
        t = m.multihead_attention(x, x, x, 32, 4)
        t = m.dense(t, 32)
        m.compile(SGDOptimizer(lr=0.1),
                  LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE,
                  [MetricsType.METRICS_MEAN_SQUARED_ERROR])
        return m

    rng = np.random.RandomState(0)
    xv = rng.randn(4, 16, 32).astype(np.float32)

    m_dense = build(sp=1, impl="dense")
    want = np.asarray(m_dense.executor.build_forward()(
        m_dense.state.params, [jnp.asarray(xv)]))

    m_uly = build(sp=2, impl="ulysses")
    for op_name, ws in m_dense.state.params.items():
        for w_name, w in ws.items():
            m_uly.state.params[op_name][w_name] = jnp.asarray(np.asarray(w))
    got = np.asarray(m_uly.executor.build_forward()(
        m_uly.state.params, [jnp.asarray(xv)]))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    yv = rng.randn(4, 16, 32).astype(np.float32)
    m_uly.fit(xv, yv, epochs=1, verbose=False)
