"""Shim: reference python/flexflow/keras/optimizers.py surface."""
from flexflow_tpu.frontends.keras.optimizers import *  # noqa: F401,F403
