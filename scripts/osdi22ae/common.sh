#!/usr/bin/env bash
# Shared launcher for the OSDI'22 artifact-equivalent benchmarks
# (reference: scripts/osdi22ae/*.sh). The reference runs each example twice
# on 4 GPUs: once with the Unity-searched strategy (--budget N) and once
# with --only-data-parallel. Here the "cluster" is a TPU mesh; without real
# chips, set FF_VIRTUAL_MESH=8 to run on a virtual 8-device CPU mesh.
set -euo pipefail
REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/../.." && pwd)"
if [[ "${FF_VIRTUAL_MESH:-}" != "" ]]; then
  export JAX_PLATFORMS=cpu
  export XLA_FLAGS="--xla_force_host_platform_device_count=${FF_VIRTUAL_MESH}"
fi
run_example() {
  local name="$1"; shift
  ( cd "$REPO" && PYTHONPATH="$REPO" python "examples/python/$name" "$@" )
}
