"""Runtime services: checkpointing, recompile triggers, profiling,
strategy IO (TPU-native equivalents of reference src/runtime/ services +
the checkpoint upgrade SURVEY §5 calls for)."""
from .checkpoint import restore_checkpoint, save_checkpoint  # noqa: F401
from .recompile import RecompileState, recompile_on_condition  # noqa: F401
from .strategy_io import (  # noqa: F401
    apply_imported_strategy,
    export_strategy,
    import_strategy,
)
