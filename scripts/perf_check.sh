#!/usr/bin/env bash
# Step hot-path perf checks (docs/performance.md): interpret-mode flash
# kernel parity (incl. RNG-threaded dropout, fwd+bwd), the overlapped
# reduce-scatter/update/all-gather step's numerical equivalence to the
# all-reduce step (guarded and unguarded), and the cost model's
# overlappable-collective discount invariants — swept over 8- and
# 4-device CPU meshes so the data-degree-dependent paths are exercised
# at two shard counts. CI wires this into the lint workflow.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

for ndev in 8 4; do
    echo "perf_check: JAX_NUM_CPU_DEVICES=$ndev"
    JAX_NUM_CPU_DEVICES="$ndev" python -m pytest tests/test_perf_overlap.py \
        -q -p no:cacheprovider
done

echo "perf_check: OK"
