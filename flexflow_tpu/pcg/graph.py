"""The Parallel Computation Graph.

TPU-native equivalent of reference PCG::Graph (include/flexflow/graph.h:
293-377) and Edge (graph.h:31): a mutable DAG of PCGOp nodes connected by
ParallelTensors. The reference keeps explicit Edge sets keyed by Node; we
derive edges from tensor producer/consumer identity, and provide the same
structural operations the search needs: topo order, subgraph split
(sequence / horizontal), hashing, and dot export.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..ff_types import OperatorType
from .op import PCGOp
from .parallel_tensor import ParallelTensor


@dataclasses.dataclass(frozen=True)
class Edge:
    """reference: graph.h:31 Edge{srcOp,dstOp,srcIdx,dstIdx}"""

    src: PCGOp
    dst: PCGOp
    src_idx: int
    dst_idx: int

    def __hash__(self):
        return hash((self.src.guid, self.dst.guid, self.src_idx, self.dst_idx))


class Graph:
    """PCG container (reference: graph.h:293)."""

    def __init__(self, ops: Optional[List[PCGOp]] = None):
        self.ops: List[PCGOp] = list(ops) if ops else []
        # external inputs: ParallelTensors with no producer inside the graph
        self._producer_cache: Optional[Dict[int, Tuple[PCGOp, int]]] = None

    def add_op(self, op: PCGOp) -> PCGOp:
        self.ops.append(op)
        self._producer_cache = None
        return op

    # -- structure ----------------------------------------------------------
    def producers(self) -> Dict[int, Tuple[PCGOp, int]]:
        """tensor guid -> (producing op, output index)."""
        if self._producer_cache is None:
            m: Dict[int, Tuple[PCGOp, int]] = {}
            for op in self.ops:
                for i, t in enumerate(op.outputs):
                    m[t.guid] = (op, i)
            self._producer_cache = m
        return self._producer_cache

    def in_edges(self, op: PCGOp) -> List[Edge]:
        prod = self.producers()
        es = []
        for j, t in enumerate(op.inputs):
            if t.guid in prod:
                src, i = prod[t.guid]
                es.append(Edge(src, op, i, j))
        return es

    def out_edges(self, op: PCGOp) -> List[Edge]:
        es = []
        out_guids = {t.guid: i for i, t in enumerate(op.outputs)}
        for other in self.ops:
            if other is op:
                continue
            for j, t in enumerate(other.inputs):
                if t.guid in out_guids:
                    es.append(Edge(op, other, out_guids[t.guid], j))
        return es

    def input_tensors(self) -> List[ParallelTensor]:
        prod = self.producers()
        seen: Set[int] = set()
        ins: List[ParallelTensor] = []
        for op in self.ops:
            for t in op.inputs:
                if t.guid not in prod and t.guid not in seen:
                    seen.add(t.guid)
                    ins.append(t)
        return ins

    def output_tensors(self) -> List[ParallelTensor]:
        """Tensors produced but never consumed."""
        consumed = {t.guid for op in self.ops for t in op.inputs}
        outs = []
        for op in self.ops:
            for t in op.outputs:
                if t.guid not in consumed:
                    outs.append(t)
        return outs

    def topo_order(self) -> List[PCGOp]:
        prod = self.producers()
        visited: Set[int] = set()
        order: List[PCGOp] = []

        def visit(op: PCGOp):
            if op.guid in visited:
                return
            visited.add(op.guid)
            for t in op.inputs:
                if t.guid in prod:
                    visit(prod[t.guid][0])
            order.append(op)

        for op in self.ops:
            visit(op)
        return order

    def check_correctness(self) -> bool:
        """reference: Graph::check_correctness — every op input either comes
        from another op or is a graph input; every tensor produced at most
        once; shapes valid; graph acyclic. Delegates to the static
        analyzer's structure pass (analysis/structure.py), which names the
        violation when one wants the details (the search only needs the
        boolean gate)."""
        from ..analysis.structure import graph_is_wellformed

        return graph_is_wellformed(self)

    def hash(self) -> int:
        """Structural hash (reference: Graph::hash used in dp_state_hash).

        MUST fold output and weight shape keys, not just inputs: rewrites
        that only change weight/output parallel degrees (attention
        head-partition, embedding channel-split) are otherwise
        hash-identical to the unrewritten graph — the best-first search
        deduplicates by this hash and would silently drop the whole
        attribute-/parameter-parallel candidate class."""
        h = 17
        for op in self.topo_order():
            key = (op.op_type, op.params)
            mv = op.machine_view.hash() if op.machine_view else 0
            h = hash((
                h, key, mv,
                tuple(t.shape_key() for t in op.inputs),
                tuple(t.shape_key() for t in op.outputs),
                tuple(w.shape_key() for w in op.weights),
            ))
        return h

    # -- dot export (reference: Graph::export_strategy_computation_graph,
    #    include/flexflow/utils/dot/) ---------------------------------------
    def export_dot(self) -> str:
        lines = ["digraph PCG {"]
        for op in self.ops:
            label = op.name
            if op.machine_view is not None:
                label += f"\\n{op.machine_view!r}"
            lines.append(f'  n{op.guid} [label="{label}"];')
        for op in self.ops:
            for e in self.in_edges(op):
                lines.append(f"  n{e.src.guid} -> n{e.dst.guid};")
        lines.append("}")
        return "\n".join(lines)

    def __len__(self):
        return len(self.ops)
