"""Train mt5 through the PyTorch-FX import (reference:
examples/python/pytorch/mt5/mt5_ff.py — PyTorchModel(mt5).torch_to_ff then
ffmodel.fit on tokenized numpy batches)."""
import argparse

import numpy as np

from flexflow.core import *  # noqa: F401,F403
from flexflow.torch.model import PyTorchModel

from mt5_torch import set_seed, small_mt5_config, synthetic_batches


def top_level_task(args):
    from transformers import MT5ForConditionalGeneration

    set_seed()
    model = MT5ForConditionalGeneration(small_mt5_config())

    ffconfig = FFConfig()
    ffconfig.batch_size = args.batch_size
    ffmodel = FFModel(ffconfig)
    seq = args.seq_length
    input_ids = ffmodel.create_tensor([args.batch_size, seq], DataType.DT_INT64)
    decoder_input_ids = ffmodel.create_tensor(
        [args.batch_size, seq], DataType.DT_INT64)

    hf_model = PyTorchModel(
        model, is_hf_model=True,
        input_names=["input_ids", "decoder_input_ids"],
    )
    output_tensors = hf_model.torch_to_ff(ffmodel, [input_ids, decoder_input_ids])

    ffmodel.optimizer = SGDOptimizer(ffmodel, 0.01)
    ffmodel.compile(
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.METRICS_ACCURACY,
                 MetricsType.METRICS_SPARSE_CATEGORICAL_CROSSENTROPY])
    hf_model.load_weights(ffmodel)

    src, tgt = synthetic_batches(512, args.num_samples, seq)
    # teacher forcing: labels are the decoder inputs shifted left; for the
    # synthetic task just predict the target ids themselves
    ffmodel.fit(x=[src, tgt], y=tgt[..., None], epochs=args.epochs)


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("-e", "--epochs", type=int, default=1)
    p.add_argument("--num-samples", type=int, default=64)
    p.add_argument("-b", "--batch-size", type=int, default=8)
    p.add_argument("--seq-length", type=int, default=24)
    args, _ = p.parse_known_args()
    print("mt5 (HF import)")
    top_level_task(args)
