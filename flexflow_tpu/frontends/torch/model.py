"""PyTorch-FX frontend: import a torch.nn.Module into FFModel.

TPU-native equivalent of reference python/flexflow/torch/model.py (2607 LoC):
`PyTorchModel(torch_module).torch_to_ff(ffmodel, input_tensors)` traces the
module with torch.fx.symbolic_trace (model.py:2427 _trace_model) and maps
each fx node onto FFModel ops (per-node `to_ff`, model.py:2496). Weights are
transferred from the torch module so imported models start from the same
parameters (the reference does this via set_tensor after compile; we stage
them and FFModel applies at compile).
"""
from __future__ import annotations

import operator
from typing import Dict, List, Optional

import numpy as np

from ...ff_types import ActiMode, AggrMode, DataType, PoolType

try:
    import torch
    import torch.fx

    HAS_TORCH = True
except Exception:  # pragma: no cover
    HAS_TORCH = False


class PyTorchModel:
    """reference: torch/model.py:2408 PyTorchModel"""

    def __init__(self, module, is_hf_model: bool = False, batch_size: int = 1):
        assert HAS_TORCH, "torch is not available"
        self.module = module
        self.is_hf_model = is_hf_model
        self.batch_size = batch_size
        self._weight_loads = []  # (ff_layer, [np arrays]) applied post-compile

    def _trace(self):
        """reference: model.py:2427 _trace_model (HF variant uses
        transformers.utils.fx; plain variant torch.fx)."""
        if self.is_hf_model:
            from transformers.utils import fx as hf_fx

            return hf_fx.symbolic_trace(self.module)
        return torch.fx.symbolic_trace(self.module)

    # ------------------------------------------------------------------
    def torch_to_ff(self, ffmodel, input_tensors: List) -> List:
        """Map the traced graph onto ffmodel; returns output tensors."""
        traced = self._trace()
        modules = dict(traced.named_modules())
        env: Dict[str, object] = {}
        inputs = list(input_tensors)
        outputs: List = []

        for node in traced.graph.nodes:
            if node.op == "placeholder":
                env[node.name] = inputs.pop(0)
            elif node.op == "call_module":
                mod = modules[node.target]
                args = [env[a.name] if isinstance(a, torch.fx.Node) else a
                        for a in node.args]
                env[node.name] = self._module_to_ff(ffmodel, mod, args, node)
            elif node.op == "call_function":
                env[node.name] = self._function_to_ff(ffmodel, node, env)
            elif node.op == "call_method":
                env[node.name] = self._method_to_ff(ffmodel, node, env)
            elif node.op == "get_attr":
                env[node.name] = self._fetch_attr(node.target)
            elif node.op == "output":
                def collect(a):
                    if isinstance(a, torch.fx.Node):
                        outputs.append(env[a.name])
                    elif isinstance(a, (tuple, list)):
                        for x in a:
                            collect(x)
                collect(node.args[0])
        self._ffmodel = ffmodel
        return outputs

    def _fetch_attr(self, target: str):
        obj = self.module
        for part in target.split("."):
            obj = getattr(obj, part)
        return obj

    # -- modules ---------------------------------------------------------
    def _module_to_ff(self, ff, mod, args, node):
        nn = torch.nn
        x = args[0]
        name = node.name
        if isinstance(mod, nn.Linear):
            out = ff.dense(x, mod.out_features, use_bias=mod.bias is not None,
                           name=name)
            w = [mod.weight.detach().numpy().T]  # torch (out,in) -> ours (in,out)
            if mod.bias is not None:
                w.append(mod.bias.detach().numpy())
            self._weight_loads.append((ff.layers[-1], w))
            return out
        if isinstance(mod, nn.Conv2d):
            out = ff.conv2d(
                x, mod.out_channels, mod.kernel_size[0], mod.kernel_size[1],
                mod.stride[0], mod.stride[1], mod.padding[0], mod.padding[1],
                groups=mod.groups, use_bias=mod.bias is not None, name=name,
            )
            w = [mod.weight.detach().numpy()]
            if mod.bias is not None:
                w.append(mod.bias.detach().numpy())
            self._weight_loads.append((ff.layers[-1], w))
            return out
        if isinstance(mod, nn.MaxPool2d):
            k = mod.kernel_size if isinstance(mod.kernel_size, tuple) else (mod.kernel_size,) * 2
            s = mod.stride if isinstance(mod.stride, tuple) else (mod.stride or k[0],) * 2
            p = mod.padding if isinstance(mod.padding, tuple) else (mod.padding,) * 2
            return ff.pool2d(x, k[0], k[1], s[0], s[1], p[0], p[1],
                             PoolType.POOL_MAX, name=name)
        if isinstance(mod, nn.AvgPool2d):
            k = mod.kernel_size if isinstance(mod.kernel_size, tuple) else (mod.kernel_size,) * 2
            s = mod.stride if isinstance(mod.stride, tuple) else (mod.stride or k[0],) * 2
            p = mod.padding if isinstance(mod.padding, tuple) else (mod.padding,) * 2
            return ff.pool2d(x, k[0], k[1], s[0], s[1], p[0], p[1],
                             PoolType.POOL_AVG, name=name)
        if isinstance(mod, nn.AdaptiveAvgPool2d):
            # only output_size (1,1) or same-size supported, like reference
            h, w_ = x.dims[2], x.dims[3]
            osz = mod.output_size if isinstance(mod.output_size, tuple) else (mod.output_size,) * 2
            if osz == (1, 1):
                return ff.pool2d(x, h, w_, 1, 1, 0, 0, PoolType.POOL_AVG, name=name)
            assert (h, w_) == osz, "unsupported AdaptiveAvgPool2d size"
            return x
        if isinstance(mod, nn.BatchNorm2d):
            out = ff.batch_norm(x, relu=False, name=name)
            self._weight_loads.append((
                ff.layers[-1],
                [mod.weight.detach().numpy(), mod.bias.detach().numpy()],
            ))
            return out
        if isinstance(mod, nn.LayerNorm):
            out = ff.layer_norm(
                x, axes=tuple(range(-len(mod.normalized_shape), 0)),
                eps=mod.eps, name=name,
            )
            if mod.elementwise_affine:
                self._weight_loads.append((
                    ff.layers[-1],
                    [mod.weight.detach().numpy(), mod.bias.detach().numpy()],
                ))
            return out
        if isinstance(mod, nn.Embedding):
            out = ff.embedding(x, mod.num_embeddings, mod.embedding_dim,
                               AggrMode.AGGR_MODE_NONE, name=name)
            self._weight_loads.append(
                (ff.layers[-1], [mod.weight.detach().numpy()])
            )
            return out
        if isinstance(mod, nn.ReLU):
            return ff.relu(x, name=name)
        if isinstance(mod, nn.GELU):
            return ff.gelu(x, name=name)
        if isinstance(mod, nn.Sigmoid):
            return ff.sigmoid(x, name=name)
        if isinstance(mod, nn.Tanh):
            return ff.tanh(x, name=name)
        if isinstance(mod, nn.ELU):
            return ff.elu(x, name=name)
        if isinstance(mod, nn.Softmax):
            return ff.softmax(x, axis=mod.dim if mod.dim is not None else -1, name=name)
        if isinstance(mod, nn.Dropout):
            return ff.dropout(x, mod.p, name=name)
        if isinstance(mod, nn.Flatten):
            return ff.flat(x, name=name)
        if isinstance(mod, nn.Identity):
            return ff.identity(x, name=name)
        if isinstance(mod, nn.MultiheadAttention):
            q, k, v = args[0], args[1], args[2]
            out = ff.multihead_attention(
                q, k, v, mod.embed_dim, mod.num_heads,
                dropout=mod.dropout, bias=mod.in_proj_bias is not None,
                name=name,
            )
            return out
        raise NotImplementedError(f"torch module {type(mod).__name__}")

    # -- functions -------------------------------------------------------
    def _function_to_ff(self, ff, node, env):
        def val(a):
            return env[a.name] if isinstance(a, torch.fx.Node) else a

        args = [val(a) for a in node.args]
        fn = node.target
        if fn in (operator.add, torch.add):
            if _is_scalar(args[1]):
                return ff.scalar_add(args[0], float(args[1]))
            return ff.add(args[0], args[1])
        if fn in (operator.sub, torch.sub):
            if _is_scalar(args[1]):
                return ff.scalar_sub(args[0], float(args[1]))
            return ff.subtract(args[0], args[1])
        if fn in (operator.mul, torch.mul):
            if _is_scalar(args[1]):
                return ff.scalar_multiply(args[0], float(args[1]))
            return ff.multiply(args[0], args[1])
        if fn in (operator.truediv, torch.div):
            if _is_scalar(args[1]):
                return ff.scalar_true_divide(args[0], float(args[1]))
            return ff.divide(args[0], args[1])
        if fn in (torch.relu, torch.nn.functional.relu):
            return ff.relu(args[0])
        if fn is torch.nn.functional.gelu:
            return ff.gelu(args[0])
        if fn in (torch.sigmoid, torch.nn.functional.sigmoid):
            return ff.sigmoid(args[0])
        if fn in (torch.tanh, torch.nn.functional.tanh):
            return ff.tanh(args[0])
        if fn in (torch.softmax, torch.nn.functional.softmax):
            dim = node.kwargs.get("dim", args[1] if len(args) > 1 else -1)
            return ff.softmax(args[0], axis=dim if dim is not None else -1)
        if fn in (torch.cat, torch.concat):
            dim = node.kwargs.get("dim", args[1] if len(args) > 1 else 0)
            return ff.concat(list(args[0]), dim)
        if fn in (torch.flatten,):
            return ff.flat(args[0])
        if fn in (torch.matmul, torch.bmm):
            return ff.batch_matmul(args[0], args[1])
        if fn is operator.getitem:
            return args[0][args[1]]
        if fn in (torch.exp,):
            return ff.exp(args[0])
        if fn in (torch.pow, operator.pow):
            return ff.pow(args[0], float(args[1]))
        if fn in (torch.mean,):
            dims = node.kwargs.get("dim", args[1] if len(args) > 1 else None)
            keep = node.kwargs.get("keepdim", False)
            dims = [dims] if isinstance(dims, int) else list(dims)
            return ff.mean(args[0], dims, keep)
        if fn in (torch.transpose,):
            d0, d1 = args[1], args[2]
            perm = list(range(len(args[0].dims)))
            perm[d0], perm[d1] = perm[d1], perm[d0]
            return ff.transpose(args[0], perm)
        raise NotImplementedError(f"torch function {fn}")

    def _method_to_ff(self, ff, node, env):
        def val(a):
            return env[a.name] if isinstance(a, torch.fx.Node) else a

        args = [val(a) for a in node.args]
        m = node.target
        x = args[0]
        if m in ("view", "reshape"):
            shape = [int(s) if not isinstance(s, str) else -1 for s in args[1:]]
            if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
                shape = list(shape[0])
            return ff.reshape(x, shape)
        if m == "flatten":
            return ff.flat(x)
        if m == "permute":
            perm = args[1] if isinstance(args[1], (list, tuple)) else args[1:]
            return ff.transpose(x, list(perm))
        if m == "transpose":
            d0, d1 = args[1], args[2]
            perm = list(range(len(x.dims)))
            perm[d0], perm[d1] = perm[d1], perm[d0]
            return ff.transpose(x, perm)
        if m == "relu":
            return ff.relu(x)
        if m == "softmax":
            return ff.softmax(x, axis=node.kwargs.get("dim", -1))
        if m == "contiguous" or m == "detach" or m == "clone":
            return x
        if m == "size":
            return x.dims if len(args) == 1 else x.dims[args[1]]
        if m == "mean":
            dims = args[1] if len(args) > 1 else node.kwargs.get("dim")
            keep = node.kwargs.get("keepdim", False)
            dims = [dims] if isinstance(dims, int) else list(dims)
            return ff.mean(x, dims, keep)
        raise NotImplementedError(f"torch method {m}")

    # ------------------------------------------------------------------
    def load_weights(self, ffmodel=None):
        """Copy the torch module's parameters into the compiled model
        (reference: torch weight transfer via set_tensor)."""
        for layer, arrays in self._weight_loads:
            for wt, arr in zip(layer.weights, arrays):
                wt.set_tensor(self._ffmodel, arr)


def _is_scalar(v) -> bool:
    return isinstance(v, (int, float))


def torch_to_flexflow(module, path: str, batch_size: int = 1):
    """File-format export stub for parity with reference
    torch/model.py torch_to_flexflow (serializes the fx graph)."""
    traced = torch.fx.symbolic_trace(module)
    with open(path, "w") as f:
        for node in traced.graph.nodes:
            f.write(f"{node.op}\t{node.name}\t{node.target}\t{node.args}\n")
    return path
