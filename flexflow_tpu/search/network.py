"""Topology-aware network simulation for the cost model.

TPU-native equivalent of reference src/runtime/network.cc (connection
matrices + weighted-ECMP shortest-path routing) and the EnhancedMachineModel
(simulator.h:212-376: per-device comm links with congestion). A TPU slice's
ICI is a 2-D/3-D torus; inter-slice traffic rides DCN. This model routes
transfers over the torus hop-by-hop, tracks per-link utilization, and
applies a congestion factor — the search can therefore distinguish
neighbor-hop collectives from long-haul reshards, which the flat
MachineModel cannot.
"""
from __future__ import annotations

import dataclasses
import heapq
from typing import Dict, List, Optional, Sequence, Tuple

from .machine_model import MachineModel, TPUChipSpec


@dataclasses.dataclass
class TorusTopology:
    """Chip coordinates on an ICI torus (e.g. v5e-32 = 4x8)."""

    dims: Tuple[int, ...]  # e.g. (4, 8)

    @property
    def num_chips(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    def coords(self, chip: int) -> Tuple[int, ...]:
        c = []
        for d in reversed(self.dims):
            c.append(chip % d)
            chip //= d
        return tuple(reversed(c))

    def chip(self, coords: Sequence[int]) -> int:
        idx = 0
        for c, d in zip(coords, self.dims):
            idx = idx * d + (c % d)
        return idx

    def neighbors(self, chip: int) -> List[int]:
        cs = list(self.coords(chip))
        out = []
        for axis, d in enumerate(self.dims):
            if d == 1:
                continue
            for delta in (-1, 1):
                n = list(cs)
                n[axis] = (n[axis] + delta) % d
                out.append(self.chip(n))
        return sorted(set(out))

    def hop_distance(self, a: int, b: int) -> int:
        """Manhattan distance on the torus (wraparound links)."""
        ca, cb = self.coords(a), self.coords(b)
        dist = 0
        for x, y, d in zip(ca, cb, self.dims):
            delta = abs(x - y)
            dist += min(delta, d - delta)
        return dist

    def shortest_path(self, a: int, b: int) -> List[int]:
        """Dijkstra over unit-cost torus links (reference:
        WeightedShortestPathRoutingStrategy, simulator.h:172-399)."""
        if a == b:
            return [a]
        dist = {a: 0}
        prev: Dict[int, int] = {}
        pq = [(0, a)]
        while pq:
            d, u = heapq.heappop(pq)
            if u == b:
                break
            if d > dist.get(u, 1 << 30):
                continue
            for v in self.neighbors(u):
                nd = d + 1
                if nd < dist.get(v, 1 << 30):
                    dist[v] = nd
                    prev[v] = u
                    heapq.heappush(pq, (nd, v))
        path = [b]
        while path[-1] != a:
            path.append(prev[path[-1]])
        return list(reversed(path))


@dataclasses.dataclass
class TopologyAwareMachineModel(MachineModel):
    """MachineModel whose intra-node transfers route over an ICI torus with
    per-link congestion (reference: EnhancedMachineModel's per-device comm
    links + congestion factors, machine_model.cc)."""

    topology: Optional[TorusTopology] = None
    congestion_factor: float = 0.15  # extra latency fraction per active flow

    def __post_init__(self):
        if self.topology is None:
            self.topology = TorusTopology(dims=(self.num_nodes, self.workers_per_node))
        self._link_load: Dict[Tuple[int, int], int] = {}

    def reset_congestion(self):
        self._link_load.clear()

    def xfer_cost(self, num_bytes: float, src: int, dst: int) -> float:
        if src == dst or num_bytes <= 0:
            return 0.0
        path = self.topology.shortest_path(src, dst)
        hops = len(path) - 1
        # per-hop store-and-forward is pipelined: one BW term + per-hop latency
        t = hops * self.ici_latency + num_bytes / self.ici_bandwidth
        # congestion: links already carrying flows slow down
        for u, v in zip(path, path[1:]):
            key = (min(u, v), max(u, v))
            load = self._link_load.get(key, 0)
            t *= 1.0 + self.congestion_factor * load
            self._link_load[key] = load + 1
        return t

    def allreduce_cost(self, num_bytes: float, device_ids) -> float:
        """Ring allreduce over the torus: ring hops are neighbor links when
        the view is contiguous, multi-hop otherwise."""
        ids = list(device_ids)
        n = len(ids)
        if n <= 1 or num_bytes <= 0:
            return 0.0
        max_hops = max(
            self.topology.hop_distance(ids[i], ids[(i + 1) % n]) for i in range(n)
        )
        per_step = num_bytes / n / self.ici_bandwidth * max_hops
        lat = 2 * (n - 1) * self.ici_latency * max_hops
        return 2 * (n - 1) * per_step + lat
