"""Self-healing strategy adaptation (ROADMAP item 1): close the
measure -> act loop the observatory opened.

The Unity search (core/model.py compile) runs once, against a cost model
that the step observatory (obs/step_profile.py) and per-op calibration
(obs/explain.py) routinely prove wrong mid-run: machines drift, the
shipped machine model was never right for this pod, the workload's MoE
routing shifted. The StrategyTuner watches the telemetry the system
already emits, re-runs the strategy search in a background thread under
the drift-corrected cost model, and — when the simulated win is worth it
— hot-swaps the executor at a step boundary TRANSACTIONALLY: host-gather
snapshot, name-matched reshard onto the candidate strategy (bit-exact,
asserted by checksum), a canary step cross-checked against the pre-swap
executor, and a post-swap guard window on measured step time. Any
failure on that path rolls back to the pre-swap strategy and quarantines
the candidate; training never dies to the tuner.

State machine (docs/adaptation.md has the full diagram)::

    IDLE --drift(hysteresis,cooldown)--> SEARCHING (background thread)
    SEARCHING --crash--------------------------> IDLE   [rolled_back]
    SEARCHING --lint fail / win < min_win /
                already quarantined-------------> IDLE   [quarantined]
    SEARCHING --candidate + win >= min_win------> swap at next boundary
    swap --reshard checksum mismatch / canary
           divergence / executor throw----------> IDLE   [rolled_back]
    swap --ok-----------------------------------> POST_SWAP (guard window)
    POST_SWAP --step EMA regression > guard_band-> IDLE  [rolled_back]
    POST_SWAP --N clean steps-------------------> IDLE   [committed]

Every cycle ends in exactly one ``ff_strategy_swaps_total{outcome}``
increment — committed, rolled_back or quarantined — so the counter
accounts for every attempt with no silent outcomes. Rolled-back and
failed candidates are quarantined by strategy fingerprint and never
retried within the run (thrash-proofing), and every trigger obeys
hysteresis + cooldown so transient noise cannot launch a re-search.

FaultInjector sites (runtime/resilience.py) make each failure leg
testable: ``swap_research_crash`` (background search dies),
``swap_reshard_corruption`` (a transplanted weight is corrupted before
the checksum gate), ``swap_regression`` (post-swap measured step time is
inflated past the guard band).

The same loop drives serving: ContinuousBatcher re-runs the decode
search when the admitted batch/sequence distribution shifts
(runtime/serving.py ServingConfig.decode_retune), with the existing
``_decode_executor_mismatch`` fallback as the rollback path.
"""
from __future__ import annotations

import dataclasses
import hashlib
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from .. import obs

logger = logging.getLogger("flexflow_tpu.tuner")

SWAP_METRIC = "ff_strategy_swaps_total"
SWAP_METRIC_HELP = ("Strategy hot-swap cycles by outcome "
                    "(committed|rolled_back|quarantined) and leg "
                    "(train|serving); every tuner cycle increments "
                    "exactly one outcome")
DRIFT_GAUGE = "ff_tuner_drift_score"
DRIFT_GAUGE_HELP = ("Current drift score: max of step-time slowdown vs "
                    "baseline and per-op calibration error; the tuner "
                    "triggers a re-search when it stays above "
                    "drift_threshold for hysteresis_steps synced steps")


class SwapError(RuntimeError):
    """A transactional strategy swap failed one of its gates (reshard
    checksum, canary, executor dispatch) and was rolled back."""


@dataclasses.dataclass
class TunerConfig:
    """Knobs for fit(tuner=...) — see docs/adaptation.md.

    The trigger: ``drift_score = max(slowdown, miscalibration)`` where
    slowdown is the measured step-time EMA relative to the best EMA seen
    (0.1 = 10% slower) and miscalibration is the worst per-op-class
    measured/simulated deviation from an applied calibration probe. The
    tuner re-searches only after the score exceeds ``drift_threshold``
    for ``hysteresis_steps`` consecutive synced steps, and never within
    ``cooldown_steps`` of a previous cycle."""

    drift_threshold: float = 0.5
    hysteresis_steps: int = 3
    cooldown_steps: int = 10
    # steps of EMA warm-up before the slowdown baseline freezes (first
    # steps pay compilation/caching noise)
    warmup_steps: int = 3
    # minimum fractional simulated win a candidate must show over the
    # current strategy (re-simulated under the same refreshed oracle)
    min_win: float = 0.05
    # post-swap measured step EMA may exceed the pre-swap EMA by at most
    # this fraction before the swap is rolled back
    guard_band: float = 0.5
    # length of the post-swap guard window, in synced steps
    post_swap_steps: int = 5
    # post-swap steps excluded from the guard-window EMA before it starts
    # counting: the first step jit-compiles the new executor's step
    # program and the next still pays dispatch/cache warm-up — charging
    # either to the window makes every swap look like a regression
    post_swap_warmup_steps: int = 2
    # background re-search budget (GraphSearchHelper budget)
    search_budget: int = 10
    # run an explain_strategy() calibration probe automatically at this
    # global step (device work, main thread, step boundary); the probe's
    # measurements write through the active CalibrationStore and feed the
    # miscalibration drift signal. None = no automatic probe (feed
    # observe_explanation() yourself, or rely on step-time drift alone).
    probe_after_steps: Optional[int] = None
    probe_repeats: int = 1
    # canary tolerance: the candidate executor's loss on the cached last
    # batch must match the pre-swap executor's within rtol/atol (sharding
    # changes reduction order, so bit-exact loss equality is not expected
    # — the carried WEIGHTS are checked bit-exactly by checksum instead)
    canary_rtol: float = 0.05
    canary_atol: float = 1e-4
    # hard cap on committed swaps per run (0 = unlimited)
    max_swaps: int = 0


@dataclasses.dataclass
class _SearchOutcome:
    graph: Any = None
    views: Optional[Dict[int, Any]] = None
    cost: Optional[float] = None
    error: Optional[BaseException] = None


def strategy_fingerprint(graph, views) -> str:
    """Stable identity of a (graph, views) strategy: op names/types plus
    their machine views. Used for the quarantine set — a rolled-back or
    rejected candidate is never retried within the run."""
    views = views or {}
    lines = sorted(
        f"{op.name}|{op.op_type.name}|{views.get(op.guid, getattr(op, 'machine_view', None))}"
        for op in graph.ops
    )
    return hashlib.sha1("\n".join(lines).encode()).hexdigest()[:16]


def _complete_views(graph, views) -> Dict[int, Any]:
    """simulate_runtime indexes views[guid] for every op; complete a
    possibly-partial search result with per-op machine views (serial
    default)."""
    from ..pcg.machine_view import MachineView

    out = {}
    serial = MachineView()
    for op in graph.topo_order():
        v = (views or {}).get(op.guid) or getattr(op, "machine_view", None)
        out[op.guid] = v if v is not None else serial
    return out


def _guard_to_host(guard):
    """Host-gather a GuardState's counters field-by-field (asdict would
    deep-copy device arrays)."""
    if guard is None:
        return None
    return {f.name: np.array(np.asarray(getattr(guard, f.name)), copy=True)
            for f in dataclasses.fields(guard)}


def _host_tree(tree):
    """Host-gather an arbitrary pytree with copy=True (snapshots must
    survive later donated dispatches — tools/fflint.py FFL101)."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: np.array(jax.device_get(x), copy=True),
        tree, is_leaf=lambda x: x is None,
    )


class StrategyTuner:
    """The fit()-resident adaptation loop. One instance per fit() call;
    ``fit(tuner=TunerConfig(...))`` constructs and drives it:

    - ``observe_step(dur_s)`` after every SYNCED step (wall time measured
      a whole step);
    - ``on_step_boundary(step, batch)`` between steps — runs the probe,
      evaluates the trigger, collects background search results, executes
      pending swaps, and polices the post-swap guard window. Returns True
      when the live executor changed (fit must rebuild its step fn).
    """

    IDLE = "idle"
    SEARCHING = "searching"
    POST_SWAP = "post_swap"

    def __init__(self, model, config: Optional[TunerConfig] = None, *,
                 fault_injector=None, leg: str = "train"):
        self.model = model
        self.cfg = config if config is not None else TunerConfig()
        self.fault = fault_injector
        self.leg = leg
        self.state = self.IDLE
        self.outcomes: Dict[str, int] = {
            "committed": 0, "rolled_back": 0, "quarantined": 0,
        }
        self.quarantined: Set[str] = set()
        self.swap_history: List[dict] = []  # every cycle, with outcome
        self._ema: Optional[float] = None
        self._obs_steps = 0
        self._baseline: Optional[float] = None
        self._miscal = 0.0
        self._breach = 0
        self._cooldown_until = -1
        self._probed = False
        self._thread: Optional[threading.Thread] = None
        self._search_result: Optional[_SearchOutcome] = None
        self._candidate: Optional[dict] = None
        self._last_batch: Optional[Tuple] = None
        # POST_SWAP bookkeeping: pre-swap strategy kept for rollback
        self._preswap: Optional[dict] = None
        self._post_seen = 0
        self._post_skipped = 0
        self._post_ema: Optional[float] = None
        self._pre_swap_ema: Optional[float] = None
        self._regress_factor: Optional[float] = None
        # artifact-store plumbing (runtime/artifact_store.py): quarantined
        # fingerprints persist across process restarts and committed
        # winners are written through for fleet-wide reuse
        self._artifact_store = None
        self._quarantine_scope: Optional[str] = None
        # anomaly sentinel over the drift score (obs/anomaly.py): a
        # drift spike that later trips the re-search trigger becomes the
        # tagged cause on tuner_research_started
        from ..obs.anomaly import AnomalySentinel

        self.sentinel = AnomalySentinel()

    # ------------------------------------------------------------------
    # artifact store: persisted quarantines + winner write-through
    # ------------------------------------------------------------------
    def attach_artifact_store(self, store) -> None:
        """Load the persisted quarantine set for this (graph, topology)
        scope and keep the store for write-through. A rolled-back
        candidate quarantined by a PREVIOUS process is then never
        re-proposed after a restart. No-op without a store."""
        if store is None:
            return
        self._artifact_store = store
        parts = getattr(self.model, "_artifact_key_parts", None)
        if not parts:
            # manual lowering / no compile probe: derive the scope the
            # same way compile() would
            try:
                from .artifact_store import (
                    graph_fingerprint,
                    topology_digest,
                )
                from .elastic import topology_fingerprint

                parts = {
                    "graph": graph_fingerprint(self.model.graph),
                    "topology": topology_digest(topology_fingerprint()),
                }
            except Exception:
                return
        # calibration deliberately excluded: a re-measured machine does
        # not un-poison a strategy the guard window rejected
        self._quarantine_scope = hashlib.sha1(
            f"{parts['graph']}|{parts['topology']}".encode()
        ).hexdigest()[:20]
        try:
            persisted = store.load_quarantine(self._quarantine_scope)
        except Exception as e:
            logger.warning("tuner: could not load persisted quarantines "
                           "(%r); starting from the in-memory set", e)
            return
        if persisted:
            logger.info("tuner: honoring %d persisted quarantine "
                        "fingerprint(s)", len(persisted))
        self.quarantined |= persisted

    def _quarantine(self, fp: str) -> None:
        """Quarantine a fingerprint in memory AND through the store, so
        the decision survives a process restart."""
        self.quarantined.add(fp)
        if self._artifact_store is not None and self._quarantine_scope:
            try:
                self._artifact_store.add_quarantine(self._quarantine_scope,
                                                    [fp])
            except Exception as e:
                logger.warning("tuner: failed to persist quarantine %s "
                               "(%r)", fp, e)

    def _write_through_winner(self) -> None:
        """A committed swap IS a fresh search result the whole fleet can
        reuse: write it through under compile()'s key so the next boot
        replays the tuned strategy instead of the original winner."""
        store = self._artifact_store
        key = getattr(self.model, "_artifact_key", None)
        if store is None or key is None:
            return
        try:
            from .artifact_store import strategy_payload

            mesh = self.model.executor.mesh
            mesh_axes = {
                str(name): int(size)
                for name, size in zip(mesh.axis_names, mesh.devices.shape)
            }
            store.put(key, strategy_payload(
                self.model.graph,
                getattr(self.model, "searched_views", None),
                cost=getattr(self.model, "searched_cost", None),
                mesh_axes=mesh_axes,
                provenance={"writer": "tuner", "leg": self.leg},
            ))
        except Exception as e:
            logger.warning("tuner: winner write-through failed (%r)", e)

    # ------------------------------------------------------------------
    # watch
    # ------------------------------------------------------------------
    def observe_step(self, dur_s: float) -> None:
        """Feed one synced step's wall time (same EMA discipline as
        PCGExecutor.note_step_duration)."""
        if dur_s <= 0:
            return
        if self._regress_factor:
            # injected post-swap regression (swap_regression fault site)
            dur_s *= self._regress_factor
        self._obs_steps += 1
        self._ema = (dur_s if self._ema is None
                     else 0.5 * self._ema + 0.5 * dur_s)
        if self._obs_steps > self.cfg.warmup_steps:
            self._baseline = (self._ema if self._baseline is None
                              else min(self._baseline, self._ema))
        if self.state == self.POST_SWAP:
            if self._post_skipped < self.cfg.post_swap_warmup_steps:
                # jit compilation + warm-up of the new executor's step
                # program; see TunerConfig.post_swap_warmup_steps
                self._post_skipped += 1
                return
            self._post_seen += 1
            self._post_ema = (dur_s if self._post_ema is None
                              else 0.5 * self._post_ema + 0.5 * dur_s)

    def observe_explanation(self, explanation) -> None:
        """Feed a per-op calibration probe (obs.explain.StrategyExplanation):
        the worst per-op-class measured/simulated deviation becomes the
        miscalibration component of the drift score."""
        worst = 0.0
        for ratio in explanation.calibration_ratios().values():
            if ratio > 0 and np.isfinite(ratio):
                worst = max(worst, max(ratio, 1.0 / ratio) - 1.0)
        self._miscal = worst

    def drift_score(self) -> float:
        slowdown = 0.0
        if self._ema is not None and self._baseline:
            slowdown = max(0.0, self._ema / self._baseline - 1.0)
        return max(slowdown, self._miscal)

    # ------------------------------------------------------------------
    # the boundary hook
    # ------------------------------------------------------------------
    def on_step_boundary(self, step: int, batch: Optional[Tuple] = None
                         ) -> bool:
        """Called by fit() between steps (and by tests directly). `batch`
        is the (inputs_list, labels) host batch just trained on — cached
        for the canary. Returns True when the model's executor changed
        (commit or rollback) and fit must rebuild its step function."""
        if batch is not None:
            self._last_batch = batch
        self._maybe_probe(step)
        score = self.drift_score()
        obs.gauge_set(DRIFT_GAUGE, score, help=DRIFT_GAUGE_HELP,
                      leg=self.leg)
        self.sentinel.observe("tuner_drift_score", score, min_delta=0.05)
        if self.state == self.IDLE:
            self._evaluate_trigger(step, score)
            return False
        if self.state == self.SEARCHING:
            if self._thread is not None and self._thread.is_alive():
                return False
            return self._collect_search(step)
        if self.state == self.POST_SWAP:
            return self._police_guard_window(step)
        return False

    def _maybe_probe(self, step: int) -> None:
        cfg = self.cfg
        if (cfg.probe_after_steps is None or self._probed
                or step < cfg.probe_after_steps
                or self.state != self.IDLE):
            return
        self._probed = True
        from ..obs.explain import explain_strategy

        t0 = time.perf_counter()
        expl = explain_strategy(self.model, repeats=cfg.probe_repeats,
                                warmup=0)
        tel = obs.active()
        store = getattr(tel, "calibration", None) if tel else None
        expl.apply(self.model, store=store)  # write-through the store
        self.observe_explanation(expl)
        obs.event("tuner_probe", cat="tuner", step=step,
                  dur_s=round(time.perf_counter() - t0, 4),
                  miscalibration=round(self._miscal, 4))

    def _evaluate_trigger(self, step: int, score: float) -> None:
        cfg = self.cfg
        if step < self._cooldown_until:
            self._breach = 0
            return
        if cfg.max_swaps and self.outcomes["committed"] >= cfg.max_swaps:
            return
        if score > cfg.drift_threshold:
            self._breach += 1
        else:
            self._breach = 0
        if self._breach >= cfg.hysteresis_steps:
            self._breach = 0
            self._start_research(step, score)

    # ------------------------------------------------------------------
    # re-search (background thread)
    # ------------------------------------------------------------------
    def _start_research(self, step: int, score: float) -> None:
        model = self.model
        # refreshed oracle: picks up the probe's _profiled_op_costs and
        # any CalibrationStore globals written through since compile
        cost_model = model._build_cost_model()
        self.state = self.SEARCHING
        self._search_result = None
        self._search_cm = cost_model
        self._search_step = step
        blame = self.sentinel.blame()
        obs.event("tuner_research_started", cat="tuner", step=step,
                  drift_score=round(score, 4), anomaly=blame or "")
        model.search_trajectory.event(
            "tuner_research_started", step=step,
            drift_score=round(score, 4),
        )
        self._thread = threading.Thread(
            target=self._research_main, args=(step, cost_model),
            name="ff-tuner-research", daemon=True,
        )
        self._thread.start()

    def _research_main(self, step: int, cost_model) -> None:
        out = _SearchOutcome()
        try:
            if self.fault is not None:
                plan = self.fault.fire("swap_research_crash", step)
                if plan is not None:
                    raise RuntimeError(
                        "injected background re-search crash "
                        "(swap_research_crash)"
                    )
            out.graph, out.views, out.cost = self._run_search(cost_model)
        except BaseException as e:  # must never kill the training process
            out.error = e
        self._search_result = out

    def _run_search(self, cost_model):
        """The actual search: pure host-side work, safe off-thread. Uses
        parallelization xfers ONLY (no operator-substitution rules) —
        a substitution rewrites compute ops and rebuilds their weights
        fresh, but a hot-swap must carry the TRAINED weights by (op name,
        weight name); compile_decode() makes the same restriction for the
        same reason."""
        from ..pcg.lowering import layers_to_pcg
        from ..pcg.machine_view import MachineResource
        from ..search import (
            GraphSearchHelper,
            SearchHelper,
            generate_all_pcg_xfers,
        )

        model = self.model
        cfg = model.config
        graph, _ = layers_to_pcg(model.layers)
        if cfg.perform_fusion:
            from ..pcg.fusion import apply_fusion

            graph = apply_fusion(graph)
        machine = cost_model.machine
        degrees = []
        d = 2
        while d <= machine.num_workers:
            degrees.append(d)
            d *= 2
        xfers = generate_all_pcg_xfers(degrees or [1], cfg)
        budget = (self.cfg.search_budget if self.cfg.search_budget > 0
                  else (cfg.search_budget if cfg.search_budget > 0 else 10))
        traj = obs.SearchTrajectory()
        sh = SearchHelper(cost_model, trajectory=traj)
        gsh = GraphSearchHelper(sh, xfers, alpha=cfg.search_alpha,
                                budget=budget, trajectory=traj)
        res = MachineResource(
            num_nodes=machine.num_nodes,
            all_procs_per_node=machine.workers_per_node,
            available_procs_per_node=machine.workers_per_node,
        )
        best, result = gsh.graph_optimize(graph, res)
        self.last_trajectory = traj
        return best, result.views, result.cost

    def _collect_search(self, step: int) -> bool:
        """Search thread finished: vet the candidate or account the
        failure, then (maybe) swap — we are at a step boundary."""
        import jax

        self._thread = None
        out = self._search_result or _SearchOutcome(
            error=RuntimeError("search thread vanished without a result")
        )
        self._search_result = None
        cm = self._search_cm
        if out.error is not None:
            logger.warning("tuner: background re-search failed: %r",
                           out.error)
            self._finish_cycle(step, "rolled_back", reason="research_crash",
                               detail=repr(out.error))
            return False
        model = self.model
        ndev = min(model.config.numWorkers, len(jax.devices()))
        fp = strategy_fingerprint(out.graph, out.views)
        if fp in self.quarantined:
            self._finish_cycle(step, "quarantined", reason="already_quarantined",
                               fingerprint=fp)
            return False
        from ..analysis.swap_lint import lint_swap_candidate

        problems = lint_swap_candidate(
            out.graph, out.views, num_devices=ndev, cost_model=cm,
            current_weight_ops=set(model.state.params.keys()),
        )
        if problems:
            self._quarantine(fp)
            self._finish_cycle(step, "quarantined", reason="lint",
                               fingerprint=fp, detail="; ".join(problems[:3]))
            return False
        # apples-to-apples win: both strategies re-simulated under the
        # SAME refreshed oracle (searched_cost was priced by the stale one)
        from ..search import simulate_runtime

        # compile() may have skipped the search (search_budget=-1 /
        # only_data_parallel): searched_views is then unset and the ops'
        # own machine views (from apply_*_parallel) price the incumbent
        cur_views = getattr(model, "searched_views", None)
        cur_sim = simulate_runtime(
            model.graph, _complete_views(model.graph, cur_views), cm,
        )
        cand_sim = simulate_runtime(
            out.graph, _complete_views(out.graph, out.views), cm,
        )
        win = (cur_sim - cand_sim) / cur_sim if cur_sim > 0 else 0.0
        obs.event("tuner_candidate", cat="tuner", step=step,
                  fingerprint=fp, win=round(win, 4),
                  cur_sim_s=cur_sim, cand_sim_s=cand_sim)
        if win < self.cfg.min_win:
            self._quarantine(fp)
            self._finish_cycle(step, "quarantined", reason="below_min_win",
                               fingerprint=fp, win=round(win, 4))
            return False
        self._candidate = {
            "graph": out.graph, "views": out.views, "cost": cand_sim,
            "fingerprint": fp, "win": win, "cost_model": cm,
        }
        return self._execute_swap(step)

    # ------------------------------------------------------------------
    # transactional swap
    # ------------------------------------------------------------------
    def _build_candidate_executor(self, graph, cost_model):
        """Build a PCGExecutor for the candidate graph exactly as
        compile() does (core/model.py), on a mesh sized from the
        candidate's own searched axes."""
        import jax
        import jax.numpy as jnp

        from ..parallel import strategies
        from ..parallel.executor import PCGExecutor
        from ..parallel.mesh import build_mesh

        model = self.model
        cfg = model.config
        ndev = min(cfg.numWorkers, len(jax.devices()))
        cur_inputs = graph.input_tensors()
        ordered_inputs = [cur_inputs[i] for i in model._input_positions]
        constants = {
            cur_inputs[i].guid: (cur_inputs[i], v)
            for i, v in model._constant_positions.items()
        }
        axis_sizes = strategies.assign_mesh_axes(graph, ndev)
        mesh = build_mesh(axis_sizes)
        use_bf16_grads = (cfg.allow_mixed_precision if cfg.bf16_grads is None
                          else cfg.bf16_grads)
        return PCGExecutor(
            graph, mesh, model.optimizer, model.loss_type, model.metrics_obj,
            compute_dtype=jnp.bfloat16 if cfg.allow_mixed_precision else None,
            grad_dtype=jnp.bfloat16 if use_bf16_grads else None,
            seed=cfg.seed,
            input_order=ordered_inputs,
            remat=cfg.remat,
            constants=constants,
            plan_cost_model=cost_model,
            overlap_grad_sync=cfg.overlap_backward_update,
        )

    def _transplant_state(self, new_ex, host_params, host_net, host_opt,
                          step_count, old_guard_host):
        """Name-matched reshard of the live state onto the candidate
        executor's shardings. Params/net by (op name, weight name) via
        verify._copy_named_state; optimizer slots structurally via
        checkpoint._merge_restore; step and guard carried."""
        import jax.numpy as jnp

        from ..parallel.executor import GuardState, TrainState
        from .checkpoint import _merge_restore
        from .verify import _copy_named_state

        state, unmatched = _copy_named_state(new_ex, host_params, host_net)
        if unmatched:
            raise SwapError(
                "candidate strategy orphans trained weights (no name "
                "match): " + ", ".join(unmatched[:5])
            )
        opt_state = _merge_restore(state.opt_state, host_opt)
        guard = None
        if old_guard_host is not None:
            new_ex.set_step_guard(self.model.executor.step_guard)
            guard = GuardState(**{
                k: jnp.asarray(np.asarray(v))
                for k, v in old_guard_host.items()
            })
        return TrainState(params=state.params, opt_state=opt_state,
                          step=step_count, net_state=state.net_state,
                          guard=guard)

    def _canary_losses(self, old_ex, old_state, new_ex, new_state,
                       batch) -> Tuple[float, float]:
        """One undonated, guard-free canary step on BOTH executors from
        equivalent state and the same cached batch; returns (pre-swap
        loss, candidate loss). The stepped states are discarded — the
        canary only vets, it never trains."""
        import jax

        from .verify import _guard_free_step

        xs, y = batch
        key = jax.random.PRNGKey(self.model.config.seed + 104729)
        losses = []
        for ex, state in ((old_ex, old_state), (new_ex, new_state)):
            bx = [ex.shard_batch(pt, np.asarray(a, pt.data_type.np_dtype))
                  for pt, a in zip(ex.input_pts, xs)]
            by = ex.put_replicated(
                np.asarray(y, self.model.label_tensor.data_type.np_dtype)
            )
            fn = _guard_free_step(ex)
            _, partials = fn(state, bx, by, ex.put_replicated(key))
            losses.append(float(jax.device_get(partials["loss"])))
        return losses[0], losses[1]

    def _execute_swap(self, step: int) -> bool:
        """The transaction. Nothing on the model mutates until every gate
        passes; a failure at any gate discards the candidate (the live
        executor/state were never touched) and quarantines it."""
        import jax

        from .verify import _host_params, tensor_checksums

        model = self.model
        cand = self._candidate
        self._candidate = None
        fp = cand["fingerprint"]
        old_ex = model.executor
        t0 = time.perf_counter()
        try:
            host_params = _host_params(model.state.params)
            host_net = _host_tree(model.state.net_state or {})
            host_opt = _host_tree(model.state.opt_state)
            old_guard_host = _guard_to_host(model.state.guard)
            step_count = int(model.state.step)
            pre_crc = tensor_checksums(host_params)

            new_ex = self._build_candidate_executor(cand["graph"],
                                                    cand["cost_model"])
            new_state = self._transplant_state(
                new_ex, host_params, host_net, host_opt, step_count,
                old_guard_host,
            )
            if self.fault is not None:
                plan = self.fault.fire("swap_reshard_corruption", step)
                if plan is not None:
                    new_state = _corrupt_one_param(new_state, plan)
            # bit-exact carryover gate: gather the transplanted params
            # back and compare content checksums against the snapshot
            post_crc = tensor_checksums(_host_params(new_state.params))
            bad = [k for k, rec in pre_crc.items()
                   if post_crc.get(k, {}).get("crc32") != rec["crc32"]]
            if bad:
                raise SwapError(
                    "reshard carryover is not bit-exact: "
                    + ", ".join(sorted(bad)[:5])
                )
            # canary gate: candidate loss vs pre-swap loss on the same
            # batch (also proves the new executor dispatches at all)
            if self._last_batch is not None:
                loss_pre, loss_new = self._canary_losses(
                    old_ex, model.state, new_ex, new_state,
                    self._last_batch,
                )
                tol = (self.cfg.canary_atol
                       + self.cfg.canary_rtol * abs(loss_pre))
                if (not np.isfinite(loss_new)
                        or abs(loss_new - loss_pre) > tol):
                    raise SwapError(
                        f"canary diverged: pre-swap loss {loss_pre:.6g} "
                        f"vs candidate {loss_new:.6g} (tol {tol:.3g})"
                    )
        except Exception as e:
            # the live executor/state were never touched — just discard
            logger.warning("tuner: swap aborted, keeping pre-swap "
                           "strategy: %s", e)
            self._quarantine(fp)
            self._finish_cycle(step, "rolled_back", reason="swap_failed",
                               fingerprint=fp, detail=str(e))
            return False

        # ---- commit point: publish the candidate as the live strategy
        cur_views = getattr(model, "searched_views", None)
        self._preswap = {
            "graph": model.graph, "views": cur_views,
            "cost": getattr(model, "searched_cost", None), "executor": old_ex,
            "pt_by_guid": model._pt_by_guid, "fingerprint":
                strategy_fingerprint(model.graph, cur_views),
        }
        # guard reference: the BEST (min) EMA the pre-swap strategy showed,
        # not the instantaneous EMA — early in a run the EMA still carries
        # the initial compile step and would mask a real regression.
        # (_install resets both, so capture before.)
        pre_ema = min(x for x in (self._ema, self._baseline)
                      if x is not None) if self._ema is not None else None
        self._install(cand["graph"], cand["views"], cand["cost"],
                      new_ex, new_state)
        self._pre_swap_ema = pre_ema
        self._post_seen = 0
        self._post_skipped = 0
        self._post_ema = None
        self._regress_factor = None
        if self.fault is not None:
            plan = self.fault.fire("swap_regression", step)
            if plan is not None:
                self._regress_factor = float(plan.get("factor", 10.0))
        self.state = self.POST_SWAP
        dur = time.perf_counter() - t0
        obs.event("strategy_swap", cat="tuner", step=step, fingerprint=fp,
                  win=round(cand["win"], 4), dur_s=round(dur, 4))
        model.search_trajectory.event(
            "strategy_swap", step=step, fingerprint=fp,
            win=round(cand["win"], 4),
        )
        self._record_overlay_instant(step, fp)
        tel = obs.active()
        if tel is not None and getattr(tel, "tracer", None) is not None:
            tel.tracer.instant("strategy_swap", cat="tuner", step=step,
                               fingerprint=fp)
        logger.info("tuner: strategy swap installed at step %d "
                    "(fingerprint %s, simulated win %.1f%%); guard window "
                    "%d steps", step, fp, 100 * cand["win"],
                    self.cfg.post_swap_steps)
        return True

    def _install(self, graph, views, cost, executor, state) -> None:
        """Point the model at a (graph, views, executor, state) tuple and
        re-register it with the active telemetry session (the elastic
        recompile path does the same dance)."""
        model = self.model
        model.graph = graph
        model.searched_views = views
        model.searched_cost = cost
        model.executor = executor
        model.state = state
        pt = {}
        for op in graph.ops:
            for t in list(op.outputs) + list(op.weights):
                pt[t.guid] = t
        for t in graph.input_tensors():
            pt[t.guid] = t
        model._pt_by_guid = pt
        executor.reset_step_duration()
        self._ema = None
        self._obs_steps = 0
        self._baseline = None
        tel = obs.active()
        if tel is not None and hasattr(tel, "_attached_models"):
            try:
                tel._attached_models.remove(model)
            except ValueError:
                pass
            tel.attach_model(model)

    def _record_overlay_instant(self, step: int, fingerprint: str) -> None:
        """Queue a swap-boundary instant for the step-observatory Perfetto
        overlay (obs/step_profile.py export_overlay extra_events)."""
        model = self.model
        evs = getattr(model, "_strategy_swap_overlay_events", None)
        if evs is None:
            evs = model._strategy_swap_overlay_events = []
        evs.append({
            "name": "strategy_swap", "cat": "tuner", "ph": "i", "s": "g",
            "ts": time.time() * 1e6, "pid": 1, "tid": 0,
            "args": {"step": step, "fingerprint": fingerprint,
                     "leg": self.leg},
        })

    # ------------------------------------------------------------------
    # post-swap guard window
    # ------------------------------------------------------------------
    def _police_guard_window(self, step: int) -> bool:
        cfg = self.cfg
        if self._post_seen < cfg.post_swap_steps:
            # regress fast if the window already shows a blowout
            if (self._post_ema is not None and self._pre_swap_ema
                    and self._post_seen >= 2
                    and self._post_ema > self._pre_swap_ema
                    * (1.0 + cfg.guard_band)):
                return self._rollback_regression(step)
            return False
        if (self._post_ema is not None and self._pre_swap_ema
                and self._post_ema > self._pre_swap_ema
                * (1.0 + cfg.guard_band)):
            return self._rollback_regression(step)
        # guard window survived: the swap is committed
        pre = self._preswap
        self._preswap = None
        self._regress_factor = None
        self._write_through_winner()
        self._finish_cycle(
            step, "committed",
            fingerprint=strategy_fingerprint(self.model.graph,
                                             self.model.searched_views),
            replaced=pre["fingerprint"] if pre else None,
        )
        return False

    def _rollback_regression(self, step: int) -> bool:
        """Measured step time regressed past the guard band: re-transplant
        the CURRENT (evolved) state back onto the pre-swap strategy and
        restore it. Training continues — the regressed candidate is
        quarantined."""
        model = self.model
        pre = self._preswap
        self._preswap = None
        self._regress_factor = None
        bad_fp = strategy_fingerprint(model.graph, model.searched_views)
        self._quarantine(bad_fp)
        from .verify import _host_params

        host_params = _host_params(model.state.params)
        host_net = _host_tree(model.state.net_state or {})
        host_opt = _host_tree(model.state.opt_state)
        old_guard_host = _guard_to_host(model.state.guard)
        step_count = int(model.state.step)
        old_ex = pre["executor"]
        state = self._transplant_state(old_ex, host_params, host_net,
                                       host_opt, step_count, old_guard_host)
        self._install(pre["graph"], pre["views"], pre["cost"], old_ex, state)
        ratio = ((self._post_ema / self._pre_swap_ema)
                 if (self._post_ema and self._pre_swap_ema) else float("nan"))
        logger.warning(
            "tuner: post-swap step time regressed %.2fx past the guard "
            "band; rolled back to pre-swap strategy %s", ratio,
            pre["fingerprint"],
        )
        self._finish_cycle(step, "rolled_back", reason="post_swap_regression",
                           fingerprint=bad_fp,
                           regression_ratio=round(ratio, 3))
        return True

    # ------------------------------------------------------------------
    # accounting
    # ------------------------------------------------------------------
    def _finish_cycle(self, step: int, outcome: str, **detail) -> None:
        self.state = self.IDLE
        self._breach = 0
        self._miscal = 0.0
        self._cooldown_until = step + self.cfg.cooldown_steps
        self.outcomes[outcome] += 1
        self.swap_history.append({"step": step, "outcome": outcome,
                                  **detail})
        obs.count(SWAP_METRIC, help=SWAP_METRIC_HELP, outcome=outcome,
                  leg=self.leg)
        obs.event("tuner_cycle_finished", cat="tuner", step=step,
                  outcome=outcome,
                  **{k: v for k, v in detail.items() if v is not None})
        if outcome in ("rolled_back", "quarantined"):
            # rollbacks are the tuner's crash-equivalent: keep the event
            # tail + strategy provenance around the failed swap
            obs.forensics_dump(
                f"tuner_{outcome}", step=step, leg=self.leg,
                outcomes=dict(self.outcomes),
                swap_history=self.swap_history[-5:],
                detail={k: v for k, v in detail.items()
                        if isinstance(v, (str, int, float, bool))})


def _corrupt_one_param(state, plan):
    """swap_reshard_corruption fault site: flip the first weight's first
    element after the transplant, BEFORE the checksum gate — the gate
    must catch it and the swap must roll back."""
    import jax

    for opn in sorted(state.params):
        for wn in sorted(state.params[opn]):
            like = state.params[opn][wn]
            arr = np.array(jax.device_get(like), copy=True)
            flat = arr.reshape(-1)
            flat[0] = flat[0] + np.asarray(
                plan.get("delta", 1.0), dtype=arr.dtype
            ) if np.issubdtype(arr.dtype, np.floating) else ~flat[0]
            state.params[opn][wn] = jax.device_put(
                arr.astype(like.dtype), like.sharding
            )
            return state
    return state
