"""Transformer block-stack operator with pipeline-parallel execution.

This is the compute-op face of pipeline parallelism (parallel/pipeline.py).
A single PCG node holds ALL `num_layers` encoder blocks with their weights
STACKED along a leading layer dim; that dim shards over the "pipe" mesh
axis, turning stage placement into an ordinary sharding decision — the
TPU-native answer to the reference's unimplemented OP_PIPELINE
(ffconst.h:158, task IDs model.h:190-192, no source file; SURVEY §2.3).

The block replicates the flagship benchmark block exactly
(reference: examples/cpp/Transformer/transformer.cc:33-45
create_attention_encoder — MHA with output bias, then two bias-free dense
layers, ReLU between, no residual/layernorm), so a pipelined model is
numerically identical to the same model built layer-by-layer.
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from ..ff_types import DataType, OperatorType
from .registry import WeightSpec, register_op


@dataclasses.dataclass(frozen=True)
class BlockStackParams:
    hidden: int
    num_heads: int
    num_layers: int
    num_stages: int = 1  # pipeline degree; 1 = plain sequential scan
    num_microbatches: int = 0  # 0 -> auto (= num_stages)
    data_type: DataType = DataType.DT_FLOAT

    @property
    def head_dim(self):
        return self.hidden // self.num_heads


def _infer(params: BlockStackParams, in_shapes, in_dtypes):
    (s,) = in_shapes
    return [tuple(s)], [in_dtypes[0]]


def _weights(params: BlockStackParams, in_shapes, in_dtypes):
    L, e, h, d = params.num_layers, params.hidden, params.num_heads, params.head_dim
    dt = params.data_type
    # leading dim of every weight = layer index; tag "pipeline_stage" so
    # apply_pipeline_parallel shards it over the pipe axis
    stk = ("pipeline_stage",)
    return [
        WeightSpec("wq", (L, e, h, d), dt, "glorot_uniform", stk + ("", "head", "")),
        WeightSpec("wk", (L, e, h, d), dt, "glorot_uniform", stk + ("", "head", "")),
        WeightSpec("wv", (L, e, h, d), dt, "glorot_uniform", stk + ("", "head", "")),
        WeightSpec("wo", (L, h, d, e), dt, "glorot_uniform", stk + ("head", "", "")),
        WeightSpec("bias_o", (L, e), dt, "zero", stk + ("",)),
        WeightSpec("w1", (L, e, e), dt, "glorot_uniform", stk + ("", "")),
        WeightSpec("w2", (L, e, e), dt, "glorot_uniform", stk + ("", "")),
    ]


def _encoder_block(w, x, *, head_dim: int, compute_dtype):
    """One benchmark encoder block on per-layer weights `w` (no layer dim).
    Math matches ops/attention.py's dense path + two Dense ops bit-for-bit."""
    xc = x.astype(compute_dtype) if compute_dtype is not None else x
    wq, wk, wv, wo = w["wq"], w["wk"], w["wv"], w["wo"]
    w1, w2 = w["w1"], w["w2"]
    if compute_dtype is not None:
        wq, wk, wv, wo, w1, w2 = (
            t.astype(compute_dtype) for t in (wq, wk, wv, wo, w1, w2)
        )
    f32 = jnp.float32
    q = jnp.einsum("bse,ehd->bshd", xc, wq, preferred_element_type=f32).astype(xc.dtype)
    k = jnp.einsum("bse,ehd->bshd", xc, wk, preferred_element_type=f32).astype(xc.dtype)
    v = jnp.einsum("bse,ehd->bshd", xc, wv, preferred_element_type=f32).astype(xc.dtype)
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, f32))
    scores = jnp.einsum("bshd,bthd->bhst", q, k, preferred_element_type=f32) * scale
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    attn = jnp.einsum("bhst,bthd->bshd", probs, v, preferred_element_type=f32)
    attn = attn.astype(q.dtype)
    out = jnp.einsum("bshd,hde->bse", attn, wo, preferred_element_type=f32)
    out = out.astype(x.dtype) + w["bias_o"].astype(x.dtype)
    h1 = jnp.dot(
        out.astype(xc.dtype) if compute_dtype is not None else out,
        w1,
        preferred_element_type=f32,
    ).astype(x.dtype)
    h1 = jax.nn.relu(h1)
    h2 = jnp.dot(
        h1.astype(xc.dtype) if compute_dtype is not None else h1,
        w2,
        preferred_element_type=f32,
    ).astype(x.dtype)
    return h2


def _forward(params: BlockStackParams, weights, inputs, ctx):
    from ..parallel.pipeline import gpipe_spmd, scan_blocks

    (x,) = inputs
    block = functools.partial(
        _encoder_block, head_dim=params.head_dim, compute_dtype=ctx.compute_dtype
    )
    mesh = ctx.mesh
    pp = params.num_stages
    if (
        pp > 1
        and mesh is not None
        and "pipe" in mesh.axis_names
        and mesh.shape["pipe"] == pp
    ):
        nm = params.num_microbatches or pp
        return [
            gpipe_spmd(
                block,
                weights,
                x,
                n_stages=pp,
                n_micro=nm,
                mesh=mesh,
            )
        ]
    return [scan_blocks(block, weights, x)]


register_op(
    OperatorType.OP_BLOCK_STACK,
    "TransformerBlockStack",
    infer=_infer,
    weights=_weights,
    forward=_forward,
    num_inputs=1,
)
