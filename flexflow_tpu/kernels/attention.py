"""Attention kernels: chunked online-softmax attention, Pallas flash
attention, and ring attention for sequence/context parallelism.

These replace the reference's cuDNN `cudnnMultiHeadAttnForward` path
(src/ops/attention.cc + attention.cu) with TPU-native kernels, and add the
long-context capability the reference lacks entirely (SURVEY §5: no ring
attention / sequence parallelism there).

Three tiers:
  * chunked_attention — lax.scan over KV chunks with running (max, sum,
    acc): O(seq) memory, jax-differentiable, what XLA fuses well. Default
    for long sequences on any backend.
  * flash_attention  — Pallas TPU kernel for the forward (blocked QK^T on
    the MXU, VMEM-resident accumulators), custom_vjp whose backward reuses
    chunked_attention's VJP (same math, exact gradients).
  * ring_attention   — shard_map over a seq-sharded mesh axis: each step
    computes a partial-attention block against the resident KV shard, then
    ppermutes KV around the ring (compute/ICI overlap is XLA's job);
    online-softmax merge keeps exactness. Differentiable through scan +
    ppermute.

Layout: (batch, seq, heads, head_dim) — "bshd".
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _chunk_scan(q, k, v, *, causal: bool, chunk_size: int, q_offset=0,
                kv_offset=0):
    """Online-softmax accumulation over KV chunks. q: (b, sq, h, d)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    n_chunks = max(1, (sk + chunk_size - 1) // chunk_size)
    pad = n_chunks * chunk_size - sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    scale = 1.0 / math.sqrt(d)
    kc = k.reshape(b, n_chunks, chunk_size, h, d).transpose(1, 0, 2, 3, 4)
    vc = v.reshape(b, n_chunks, chunk_size, h, d).transpose(1, 0, 2, 3, 4)

    q_pos = q_offset + jnp.arange(sq)

    def body(carry, inputs):
        m_prev, l_prev, acc_prev = carry
        ci, k_blk, v_blk = inputs
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                       preferred_element_type=jnp.float32) * scale
        kv_pos = kv_offset + ci * chunk_size + jnp.arange(chunk_size)
        mask = kv_pos[None, :] <= (sk + kv_offset - 1)  # padding mask
        if causal:
            mask = mask & (kv_pos[None, :] <= q_pos[:, None])
        s = jnp.where(mask[None, None, :, :], s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)  # (b,h,q)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[..., None])
        l_new = l_prev * jnp.exp(m_prev - m_new) + jnp.sum(p, axis=-1)
        acc_new = acc_prev * jnp.exp(m_prev - m_new)[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        return (m_new, l_new, acc_new), None

    # Derive carries from q so they inherit q's varying manual axes when
    # running inside shard_map (fresh zeros would be unvarying and scan
    # would reject the carry type mismatch).
    zq = 0.0 * q.astype(jnp.float32).transpose(0, 2, 1, 3)  # (b,h,sq,d)
    m0 = zq[..., 0] + NEG_INF
    l0 = zq[..., 0]
    a0 = zq
    (m, l, acc), _ = lax.scan(
        body, (m0, l0, a0), (jnp.arange(n_chunks), kc, vc)
    )
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype), m, l


def chunked_attention(q, k, v, *, causal: bool = False, chunk_size: int = 256):
    """Memory-efficient exact attention. (b, s, h, d) -> (b, s, h, d)."""
    out, _, _ = _chunk_scan(q, k, v, causal=causal,
                            chunk_size=min(chunk_size, k.shape[1]))
    return out


# ---------------------------------------------------------------------------
# Pallas flash-attention forward
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                      causal: bool, scale: float, seq_k: int):
    """One (batch*head, q-block) program: stream K/V blocks from VMEM,
    online-softmax accumulate in f32."""
    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)
    block_q, d = q.shape
    qi = pl.program_id(1)
    n_kblocks = pl.cdiv(seq_k, block_k)

    def body(ki, carry):
        m_prev, l_prev, acc = carry
        k_blk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        kv_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = kv_pos < seq_k
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            mask = mask & (kv_pos <= q_pos)
        s = jnp.where(mask, s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    a0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_kblocks, body, (m0, l0, a0))
    o_ref[0] = (acc / jnp.maximum(l[:, None], 1e-30)).astype(o_ref.dtype)


try:  # Pallas import is lazy-safe: CPU tests run interpret mode
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAS_PALLAS = True
except Exception:  # pragma: no cover
    HAS_PALLAS = False


def _flash_fwd(q, k, v, *, causal: bool, block_q: int, block_k: int,
               interpret: bool):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    bq = min(block_q, sq)
    # fold batch and heads into the grid's first dim
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    kernel = functools.partial(
        _flash_fwd_kernel, block_k=min(block_k, sk), causal=causal,
        scale=scale, seq_k=sk,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, pl.cdiv(sq, bq)),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """Pallas flash-attention forward with exact chunked-attention VJP."""
    return _flash_fwd(q, k, v, causal=causal, block_q=block_q,
                      block_k=block_k, interpret=interpret)


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _flash_fwd(q, k, v, causal=causal, block_q=block_q,
                     block_k=block_k, interpret=interpret)
    return out, (q, k, v)


def _flash_vjp_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: chunked_attention(q_, k_, v_, causal=causal,
                                             chunk_size=block_k),
        q, k, v,
    )
    return vjp(g)


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ---------------------------------------------------------------------------
# Ring attention (sequence/context parallelism over a mesh axis)
# ---------------------------------------------------------------------------

def ring_attention(q, k, v, axis_name: str, *, causal: bool = False,
                   chunk_size: int = 256):
    """Exact attention when q/k/v are sharded along the sequence dim over
    `axis_name`. Must be called inside shard_map (q/k/v are the LOCAL
    shards). Each of the `n` steps attends against the resident KV shard,
    then rotates KV one hop around the ring (lax.ppermute over ICI),
    merging partial results with online softmax.

    No reference equivalent — this is the TPU build's first-class CP
    (SURVEY §5 gap); the blockwise formulation follows the public
    ring-attention recipe (PAPERS.md)."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, sq_local, h, d = q.shape

    def step(carry, i):
        m, l, acc, k_cur, v_cur = carry
        # whose shard is resident this step
        src = (idx - i) % n
        kv_off = src * sq_local
        out_blk, m_blk, l_blk = _chunk_scan(
            q, k_cur, v_cur, causal=causal,
            chunk_size=min(chunk_size, sq_local),
            q_offset=idx * sq_local, kv_offset=kv_off,
        )
        acc_blk = out_blk.transpose(0, 2, 1, 3).astype(jnp.float32) * \
            jnp.maximum(l_blk[..., None], 1e-30)
        m_new = jnp.maximum(m, m_blk)
        alpha_old = jnp.exp(m - m_new)
        alpha_blk = jnp.exp(m_blk - m_new)
        l_new = l * alpha_old + l_blk * alpha_blk
        acc_new = acc * alpha_old[..., None] + acc_blk * alpha_blk[..., None]
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_nxt, v_nxt), None

    zq = 0.0 * q.astype(jnp.float32).transpose(0, 2, 1, 3)  # (b,h,sq,d)
    m0 = zq[..., 0] + NEG_INF
    l0 = zq[..., 0]
    a0 = zq
    (m, l, acc, _, _), _ = lax.scan(step, (m0, l0, a0, k, v), jnp.arange(n))
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)
