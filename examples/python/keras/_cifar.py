"""Shared CIFAR-10 loading for the example suite (NCHW float like the
reference examples)."""
from flexflow.keras.datasets import cifar10


def load_cifar(num_samples):
    (x_train, y_train), _ = cifar10.load_data(n_train=num_samples)
    x_train = x_train.transpose(0, 3, 1, 2).astype("float32") / 255  # NCHW
    y_train = y_train.astype("int32").reshape(-1, 1)
    return x_train, y_train
