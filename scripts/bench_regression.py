#!/usr/bin/env python3
"""Warn-only bench-regression gate: compare a measured bench value against
the published baseline in BASELINE.json with a tolerance band.

Reads the measurement from (first match wins):
  --bench-json FILE   a bench.py JSON line, or a driver BENCH_r*.json
                      artifact (the {"parsed": {...}} wrapper)
  stdin ("-")         a bench.py JSON line piped in
  BENCH_r*.json       the newest committed round artifact in the repo root

Exit code is 0 unless --strict: CI wires this as a warn-only step (a perf
regression should page a human through the workflow annotation, not block
an unrelated lint PR — CPU runners can't reproduce TPU numbers anyway).
The ::warning:: line is the GitHub Actions annotation format; locally it
just prints.

Usage:
  python scripts/bench_regression.py                      # newest round
  python bench.py | python scripts/bench_regression.py -  # fresh run
  python scripts/bench_regression.py --tolerance 0.10 --strict
"""
import argparse
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_measurement(src):
    """-> (value, metric, where) from a bench.py line or driver artifact."""
    if src == "-":
        doc = json.loads(sys.stdin.read())
        where = "stdin"
    elif src:
        with open(src) as f:
            doc = json.load(f)
        where = src
    else:
        rounds = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
        if not rounds:
            return None, None, None
        with open(rounds[-1]) as f:
            doc = json.load(f)
        where = os.path.basename(rounds[-1])
    if "parsed" in doc:  # driver artifact wraps the bench line
        doc = doc["parsed"] or {}
    v = doc.get("value")
    if not isinstance(v, (int, float)) or v <= 0:
        return None, None, where
    return float(v), doc.get("metric", "transformer_train_throughput"), where


def load_baseline(metric):
    try:
        with open(os.path.join(REPO, "BASELINE.json")) as f:
            published = json.load(f).get("published", {}) or {}
    except (OSError, ValueError):
        return None
    for key in (metric, "transformer_train_throughput"):
        v = published.get(key)
        if isinstance(v, (int, float)) and v > 0:
            return float(v)
    return None


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="warn-only bench vs BASELINE.json comparison")
    ap.add_argument("bench_json", nargs="?", default=None,
                    help="bench JSON line file, driver artifact, or - for "
                         "stdin (default: newest BENCH_r*.json)")
    ap.add_argument("--tolerance", type=float, default=0.15,
                    help="allowed fractional drop below baseline before "
                         "warning (default 0.15)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on regression instead of warn-only")
    args = ap.parse_args(argv)

    value, metric, where = load_measurement(args.bench_json)
    if value is None:
        print(f"bench_regression: no measurement found "
              f"({where or 'no BENCH_r*.json rounds'}); nothing to compare")
        return 0
    baseline = load_baseline(metric)
    if baseline is None:
        print(f"bench_regression: BASELINE.json has no published value for "
              f"{metric}; nothing to compare")
        return 0

    ratio = value / baseline
    line = (f"bench_regression: {metric} = {value:.3f} vs baseline "
            f"{baseline:.3f} ({where}); ratio {ratio:.3f}, "
            f"tolerance -{args.tolerance:.0%}")
    if ratio < 1.0 - args.tolerance:
        print(f"::warning title=bench regression::{line}")
        return 1 if args.strict else 0
    print(f"{line} — OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
