"""Functional MNIST MLP through keras_exp's LIVE-model branch (reference:
examples/python/keras_exp/func_mnist_mlp.py drives a live tf.keras model
through keras2onnx). Here the live functional graph is built with
flexflow.keras layers and converted by the vendored keras->ONNX
converter (frontends/keras_exp/keras2onnx_min.py) — the same Model(...)
entry point the reference uses, no tensorflow required."""
import numpy as np

from flexflow.core import FFConfig
from flexflow.keras import layers as L
from flexflow.keras.datasets import mnist
from flexflow.keras_exp.models import Model

from _example_args import example_args


def top_level_task(args):
    num_classes = 10
    (x_train, y_train), _ = mnist.load_data(n_train=args.num_samples)
    x_train = x_train.reshape(-1, 784).astype("float32") / 255
    y_train = y_train.astype("int32").reshape(-1, 1)
    print("shape: ", x_train.shape)

    x = L.Input((784,))
    t = L.Dense(512, activation="relu")(x)
    t = L.Dense(512, activation="relu")(t)
    t = L.Dense(num_classes)(t)
    out = L.Activation("softmax")(t)

    ffconfig = FFConfig()
    ffconfig.batch_size = args.batch_size
    model = Model(inputs={1: x}, outputs=out, ffconfig=ffconfig)
    print(model.summary())
    model.compile(optimizer="SGD", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy", "sparse_categorical_crossentropy"])
    model.fit(x_train, y_train, epochs=args.epochs)


if __name__ == "__main__":
    print("Functional API, mnist mlp (live model)")
    top_level_task(example_args())
