"""FSDP/ZeRO weight sharding (parallel/weight_sharding.py): the
WeightShard parallel op, its search axis, static analysis, strategy
serialization, and elastic resharding of sharded optimizer state.

Runs on the default 8-device CPU mesh (conftest); device-count-specific
cases skip on smaller meshes (scripts/fsdp_check.sh sweeps 8/4)."""
import json
import warnings

import numpy as np
import pytest

import jax

from flexflow_tpu import (
    ActiMode,
    AdamOptimizer,
    DataType,
    FFConfig,
    FFModel,
    LossType,
    SGDOptimizer,
    verify_strategy,
)
from flexflow_tpu.ff_types import OperatorType

NDEV = len(jax.devices())


def _mlp(fsdp=1, hidden=64, batch=8, features=16, classes=4,
         optimizer=None, **cfg_kw):
    import sys

    sys.argv = [sys.argv[0]]
    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.fsdp_degree = fsdp
    for k, v in cfg_kw.items():
        setattr(cfg, k, v)
    m = FFModel(cfg)
    x = m.create_tensor((batch, features), DataType.DT_FLOAT)
    t = m.dense(x, hidden, ActiMode.AC_MODE_RELU)
    t = m.dense(t, classes)
    t = m.softmax(t)
    m.compile(optimizer or SGDOptimizer(lr=0.1, momentum=0.9),
              LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY, [])
    return m


def _data(n=32, features=16, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, features).astype(np.float32),
            rng.randint(0, classes, (n, 1)).astype(np.int32))


def _ws_ops(graph):
    return [op for op in graph.ops
            if op.op_type == OperatorType.OP_WEIGHT_SHARD]


def _host_params(m):
    return {opn: {wn: np.array(w, copy=True) for wn, w in wd.items()}
            for opn, wd in m.state.params.items()}


# ----------------------------------------------------------------------
# op lowering: exactness vs the replicated reference
# ----------------------------------------------------------------------
@pytest.mark.skipif(NDEV < 2, reason="needs >= 2 devices")
def test_fsdp_lowering_matches_replicated_training():
    """The acceptance core: an FSDP model trains to the SAME parameters
    as the replicated one — all-gather-on-use + reduce-scatter is a
    layout change, not a math change."""
    x, y = _data()
    m_fsdp = _mlp(fsdp=NDEV)
    ws = _ws_ops(m_fsdp.graph)
    assert len(ws) == 2, [o.name for o in m_fsdp.graph.ops]
    # the weights are genuinely sharded over the fsdp mesh axis
    assert m_fsdp.executor.mesh.shape["fsdp"] == NDEV
    k = m_fsdp.state.params["op_linear_0"]["kernel"]
    assert "fsdp" in str(k.sharding.spec)
    m_rep = _mlp(fsdp=1)
    m_fsdp.fit(x, y, epochs=2, verbose=False)
    m_rep.fit(x, y, epochs=2, verbose=False)
    a, b = _host_params(m_fsdp), _host_params(m_rep)
    for opn in b:
        for wn in b[opn]:
            np.testing.assert_allclose(a[opn][wn], b[opn][wn],
                                       rtol=2e-4, atol=1e-5)


@pytest.mark.skipif(NDEV < 2, reason="needs >= 2 devices")
def test_fsdp_optimizer_state_is_sharded():
    """ZeRO's point: the optimizer slots inherit the weight's fsdp
    sharding (zeros_like preserves sharding), so per-device state bytes
    divide by the shard degree."""
    m = _mlp(fsdp=NDEV, optimizer=AdamOptimizer(alpha=0.01))
    mstate = m.state.opt_state["m"]["op_linear_0"]["kernel"]
    assert "fsdp" in str(mstate.sharding.spec)
    shard_rows = mstate.sharding.shard_shape(mstate.shape)[0]
    assert shard_rows == mstate.shape[0] // NDEV


@pytest.mark.skipif(NDEV < 2, reason="needs >= 2 devices")
def test_fsdp_verify_strategy_passes():
    m = _mlp(fsdp=NDEV)
    x, y = _data()
    v = verify_strategy(m, (x, y), steps=2)
    assert v.ok, v.summary()


def test_fsdp_degree_clamped_when_not_dividing():
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        m = _mlp(fsdp=3)  # 3 never divides a power-of-two device count
    assert m.executor.mesh.shape.get("fsdp", 1) in (1, 2)
    assert any("clamped" in str(w.message) for w in rec)


# ----------------------------------------------------------------------
# search axis: the memory-lambda loop chooses FSDP under a tight budget
# ----------------------------------------------------------------------
@pytest.mark.skipif(NDEV < 4, reason="needs >= 4 devices")
def test_memory_lambda_chooses_fsdp_under_tight_budget():
    """Acceptance: a model whose replicated strategy statically fails
    FFA301 compiles and trains after graph_optimize_with_memory chooses
    weight sharding — with zero FFA errors and verify_strategy passing
    against the serial reference."""
    from flexflow_tpu.analysis import analyze_graph, estimate_per_device_bytes

    def build(**kw):
        return _mlp(hidden=256, batch=16, features=64, classes=8,
                    search_budget=6, **kw)

    m0 = build()
    views0 = getattr(m0, "searched_views", None)
    peak0 = max(estimate_per_device_bytes(
        m0.graph, views0, NDEV, optimizer=m0.optimizer).values())
    # a budget the fastest searched strategy overflows but a sharded one
    # fits: weights dominate this model, and FSDP divides them by NDEV
    budget = int(peak0 * 0.55)
    rep0 = analyze_graph(m0.graph, views0, NDEV, hbm_bytes=budget,
                         optimizer=m0.optimizer)
    assert any(d.code == "FFA301" for d in rep0.errors)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m1 = build(perform_memory_search=True, device_mem=budget)
    assert _ws_ops(m1.graph), "memory search did not introduce FSDP"
    assert m1.executor.mesh.shape.get("fsdp", 1) > 1
    rep1 = analyze_graph(m1.graph, getattr(m1, "searched_views", None),
                         NDEV, hbm_bytes=budget, optimizer=m1.optimizer)
    assert not rep1.errors, [d.format() for d in rep1.errors]
    x, y = _data(n=32, features=64, classes=8)
    m1.fit(x, y, epochs=1, verbose=False)
    v = verify_strategy(m1, (x, y), steps=2)
    assert v.ok, v.summary()


def test_plain_search_does_not_choose_fsdp():
    """Without memory pressure FSDP is strictly slower (3(p-1)/p wire
    bytes vs the all-reduce's 2(p-1)/p), so the cost-only search must
    never pick it."""
    m = _mlp(hidden=64, search_budget=4)
    assert not _ws_ops(m.graph)


def test_fsdp_substitution_requires_partitioned_batch():
    from flexflow_tpu.pcg.lowering import layers_to_pcg
    from flexflow_tpu.search.substitution import (
        fsdp_shard_weights,
        fsdp_zero_shard,
        fsdp_unshard_weights,
        partition_batch,
    )

    m = _mlp()
    graph, _ = layers_to_pcg(m.layers)
    deg = max(2, NDEV)
    # per-layer rule: inapplicable until the batch is partitioned
    assert not list(fsdp_shard_weights(deg).apply(graph))
    g_dp = next(partition_batch(deg).apply(graph))
    cands = list(fsdp_shard_weights(deg).apply(g_dp))
    assert len(cands) == 2  # one per weight-carrying layer
    assert len(_ws_ops(cands[0])) == 1
    # the one-shot ZeRO rewrite partitions the batch itself
    zero = list(fsdp_zero_shard(deg).apply(graph))
    assert len(zero) == 1 and len(_ws_ops(zero[0])) == 2
    # unshard backs a layer out and restores replicated weight dims
    back = list(fsdp_unshard_weights().apply(cands[0]))
    assert back and not _ws_ops(back[0])
    for op in back[0].ops:
        for w in op.weights:
            assert all(d.degree == 1 for d in w.dims)


def test_weight_shard_cost_model_entries():
    """The all-gather x2 + reduce-scatter pair must price HIGHER than
    the replicated all-reduce it replaces — FSDP trades time for HBM,
    and a cheaper-looking FSDP would corrupt the plain search."""
    from flexflow_tpu.search import MachineModel

    m = MachineModel(num_nodes=1, workers_per_node=8)
    group = range(8)
    w = 1 << 20
    fsdp = 2 * m.all_gather_cost(w, group) + m.reduce_scatter_cost(w, group)
    assert fsdp > m.allreduce_cost(w, group)
    assert m.all_gather_cost(w, range(1)) == 0.0
    assert m.reduce_scatter_cost(0, group) == 0.0

    from flexflow_tpu.search.cost_model import CostModel

    model = _mlp(fsdp=max(2, NDEV))
    cm = CostModel(m)
    ws = _ws_ops(model.graph)[0]
    assert cm.parallel_op_cost(ws) > 0.0


def test_cost_model_weights_memory_divides_by_shard_degree():
    from flexflow_tpu.pcg.machine_view import MachineView
    from flexflow_tpu.search import CostModel, MachineModel

    deg = max(2, NDEV)
    m_fsdp = _mlp(fsdp=deg)
    m_rep = _mlp(fsdp=1)
    cm = CostModel(MachineModel(num_nodes=1, workers_per_node=8))
    v1 = MachineView(start_device_id=0, dim=(1,), stride=(1,))
    lin_s = next(o for o in m_fsdp.graph.ops if o.name == "op_linear_0")
    lin_r = next(o for o in m_rep.graph.ops if o.name == "op_linear_0")
    ws_mem = cm.measure_operator_cost(lin_s, v1).weights_memory
    rep_mem = cm.measure_operator_cost(lin_r, v1).weights_memory
    # kernel divides by deg; the small bias may stay replicated
    assert ws_mem < rep_mem
    assert ws_mem <= rep_mem // deg + 4 * 64  # kernel/deg + bias slack


# ----------------------------------------------------------------------
# static analysis: FFA coverage for the new op
# ----------------------------------------------------------------------
def _seeded_ws_graph():
    """A well-formed FSDP graph to corrupt per diagnostic case."""
    from flexflow_tpu.pcg.lowering import layers_to_pcg
    from flexflow_tpu.search.substitution import fsdp_zero_shard

    m = _mlp()
    graph, _ = layers_to_pcg(m.layers)
    deg = max(2, NDEV)
    return next(fsdp_zero_shard(deg).apply(graph)), deg


def test_ffa_clean_on_wellformed_fsdp_graph():
    from flexflow_tpu.analysis import analyze_graph

    g, _ = _seeded_ws_graph()
    rep = analyze_graph(g, num_devices=max(2, NDEV))
    assert not rep.errors, [d.format() for d in rep.errors]


def test_ffa207_inert_weight_shard():
    from flexflow_tpu.analysis import analyze_graph
    from flexflow_tpu.parallel.weight_sharding import (
        unshard_op_weights,
        weight_shard_target,
    )

    g, deg = _seeded_ws_graph()
    ws = _ws_ops(g)[0]
    unshard_op_weights(weight_shard_target(ws))
    rep = analyze_graph(g, num_devices=max(2, NDEV))
    assert any(d.code == "FFA207" and "inert" in d.message
               for d in rep.errors)


def test_ffa207_degree_mismatch():
    from flexflow_tpu.analysis import analyze_graph
    from flexflow_tpu.parallel.weight_sharding import weight_shard_target

    g, deg = _seeded_ws_graph()
    ws = _ws_ops(g)[0]
    target = weight_shard_target(ws)
    for w in target.weights:
        for d in w.dims:
            if d.degree == deg:
                d.degree = deg // 2 if deg > 2 else deg * 2
    rep = analyze_graph(g, num_devices=max(4, NDEV))
    assert any(d.code == "FFA207" for d in rep.errors)


def test_ffa207_no_weighted_producer():
    from flexflow_tpu.analysis.collectives import collective_diagnostics
    from flexflow_tpu.parallel.weight_sharding import make_weight_shard_op
    from flexflow_tpu.pcg.lowering import layers_to_pcg

    m = _mlp()
    graph, _ = layers_to_pcg(m.layers)
    softmax = next(o for o in graph.ops
                   if o.op_type == OperatorType.OP_SOFTMAX)
    graph.add_op(make_weight_shard_op(softmax, 2))  # softmax has no weights
    rep = collective_diagnostics(graph)
    assert any(d.code == "FFA207" and "no parameters" in d.message
               for d in rep.errors)


def test_ffa104_weight_shard_output_must_match_input():
    from flexflow_tpu.analysis import analyze_graph

    g, _ = _seeded_ws_graph()
    ws = _ws_ops(g)[0]
    ws.outputs[0].dims[0].degree = 1  # desync the identity
    rep = analyze_graph(g, num_devices=max(2, NDEV))
    assert any(d.code == "FFA104" for d in rep.errors)


def test_collective_bytes_reports_all_gather_and_reduce_scatter():
    from flexflow_tpu.analysis.collectives import estimate_collective_bytes

    deg = max(2, NDEV)
    m = _mlp(fsdp=deg)
    recs = [r for r in estimate_collective_bytes(m.graph)
            if r["kind"] in ("all_gather", "reduce_scatter")]
    by_kind = {}
    for r in recs:
        by_kind.setdefault(r["kind"], 0)
        by_kind[r["kind"]] += r["bytes"]
    assert set(by_kind) == {"all_gather", "reduce_scatter"}
    # the params are gathered twice per step (fwd + bwd), scattered once
    assert by_kind["all_gather"] == 2 * by_kind["reduce_scatter"] > 0


def test_collective_bytes_gauge_exports_new_kinds(tmp_path):
    from flexflow_tpu.obs.telemetry import Telemetry, TelemetryConfig

    deg = max(2, NDEV)
    m = _mlp(fsdp=deg)
    t = Telemetry(TelemetryConfig(dir=str(tmp_path)))
    t._pcg_gauges(m)
    t.finish()
    text = t.metrics.to_prometheus()
    assert 'ff_pcg_collective_bytes{kind="all_gather"}' in text
    assert 'ff_pcg_collective_bytes{kind="reduce_scatter"}' in text


def test_static_memory_divides_param_and_state_bytes():
    from flexflow_tpu.analysis import estimate_per_device_bytes

    deg = max(2, NDEV)
    opt = AdamOptimizer(alpha=0.01)  # 2 state slots: wmul = 4
    m_s = _mlp(fsdp=deg, optimizer=opt)
    m_r = _mlp(fsdp=1, optimizer=opt)
    peak_s = max(estimate_per_device_bytes(
        m_s.graph, None, NDEV, optimizer=opt).values())
    peak_r = max(estimate_per_device_bytes(
        m_r.graph, None, NDEV, optimizer=opt).values())
    assert peak_s < peak_r / 2  # weights dominate; /deg on params+state


def test_missing_state_slots_hook_warns_only_with_weights():
    """Satellite: the PR-1 missing-hook warning must not fire for graphs
    whose ops carry no weights — they contribute zero state bytes
    silently."""
    from flexflow_tpu.pcg.graph import Graph
    from flexflow_tpu.pcg.lowering import layers_to_pcg
    from flexflow_tpu.pcg.machine_view import MachineView
    from flexflow_tpu.search import CostModel, MachineModel
    from flexflow_tpu.search.memory_optimization import measure_memory

    class NoHookOpt:  # deliberately no state_slots_per_weight
        pass

    m = _mlp()
    graph, _ = layers_to_pcg(m.layers)
    cm = CostModel(MachineModel(num_nodes=1, workers_per_node=8))
    v1 = MachineView(start_device_id=0, dim=(1,), stride=(1,))
    views = {op.guid: v1 for op in graph.ops}

    weightless = Graph([op for op in graph.ops if not op.weights])
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        measure_memory(weightless, views, cm, train=True,
                       optimizer=NoHookOpt())
    assert not [w for w in rec if "state_slots_per_weight" in str(w.message)]

    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        measure_memory(graph, views, cm, train=True, optimizer=NoHookOpt())
    assert [w for w in rec if "state_slots_per_weight" in str(w.message)]


# ----------------------------------------------------------------------
# strategy_io schema v2
# ----------------------------------------------------------------------
def test_strategy_export_records_weight_shard(tmp_path):
    from flexflow_tpu.runtime import strategy_io

    deg = max(2, NDEV)
    m = _mlp(fsdp=deg)
    path = str(tmp_path / "strat.json")
    strategy_io.export_strategy(m.graph, None, path)
    blob = json.loads(open(path).read())
    assert blob["version"] == strategy_io.SCHEMA_VERSION == 3
    ws = {r["name"]: r["weight_shard"] for r in blob["ops"]
          if r["weight_shard"]}
    assert ws and all(v == {"axis": "fsdp", "degree": deg}
                      for v in ws.values())
    # round-trips through validation
    strat = strategy_io.import_strategy(path)
    assert any(r.get("weight_shard") for r in strat.values())


def test_old_schema_with_sharded_state_rejected(tmp_path):
    from flexflow_tpu.runtime import strategy_io
    from flexflow_tpu.runtime.strategy_io import StrategyImportError

    deg = max(2, NDEV)
    m = _mlp(fsdp=deg)
    path = str(tmp_path / "strat.json")
    strategy_io.export_strategy(m.graph, None, path)
    blob = json.loads(open(path).read())
    blob["version"] = 1  # an old-schema file claiming sharded state
    open(path, "w").write(json.dumps(blob))
    with pytest.raises(StrategyImportError, match="sharded state"):
        strategy_io.import_strategy(path)


def test_old_schema_replicated_only_still_loads(tmp_path):
    from flexflow_tpu.runtime import strategy_io

    m = _mlp(fsdp=1)
    path = str(tmp_path / "strat.json")
    strategy_io.export_strategy(m.graph, None, path)
    blob = json.loads(open(path).read())
    blob["version"] = 1
    for rec in blob["ops"]:
        rec.pop("weight_shard", None)  # a genuine pre-v2 file
    open(path, "w").write(json.dumps(blob))
    strat = strategy_io.import_strategy(path)
    assert len(strat) == len(m.graph.ops)


def test_weight_shard_degree_must_divide_devices():
    from flexflow_tpu.runtime.strategy_io import (
        StrategyImportError,
        _check_feasible,
    )

    rec = {"name": "weight_shard_op_linear_0",
           "op_type": "OP_WEIGHT_SHARD", "layer_guid": 1,
           "machine_view": None, "output_degrees": [],
           "weight_degrees": [],
           "weight_shard": {"axis": "fsdp", "degree": 8}}
    _check_feasible(rec, 8)  # divides: fine
    with pytest.raises(StrategyImportError, match="weight_shard degree"):
        _check_feasible(rec, 12)


# ----------------------------------------------------------------------
# elastic: sharded optimizer state reshards across topology changes
# ----------------------------------------------------------------------
@pytest.mark.skipif(NDEV < 8, reason="needs the 8-device mesh")
def test_elastic_8_to_4_reshards_sharded_optimizer_state(tmp_path):
    """Acceptance: an 8-way FSDP run checkpoints, the pod shrinks to 4
    devices, the re-planned 4-way FSDP model restores — with the sharded
    Adam slots preserved BIT-EXACTLY across the reshard."""
    from flexflow_tpu.runtime.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
    )
    from flexflow_tpu.runtime.elastic import shrunk_devices

    x, y = _data()
    m8 = _mlp(fsdp=8, optimizer=AdamOptimizer(alpha=0.01))
    m8.fit(x, y, epochs=1, verbose=False)
    path = str(tmp_path / "ckpt")
    save_checkpoint(m8, path)
    want_m = {opn: {wn: np.array(v, copy=True) for wn, v in wd.items()}
              for opn, wd in m8.state.opt_state["m"].items()}
    want_p = _host_params(m8)

    with shrunk_devices(4):
        m4 = _mlp(fsdp=4, optimizer=AdamOptimizer(alpha=0.01))
        assert m4.executor.mesh.shape["fsdp"] == 4
        restore_checkpoint(m4, path, strict_topology=False)
        for opn, wd in want_p.items():
            for wn, v in wd.items():
                got = np.array(m4.state.params[opn][wn])
                np.testing.assert_array_equal(got, v, err_msg=f"{opn}/{wn}")
        for opn, wd in want_m.items():
            for wn, v in wd.items():
                got = np.array(m4.state.opt_state["m"][opn][wn])
                # the 4-way shard layout differs; the VALUES must not
                np.testing.assert_array_equal(got, v, err_msg=f"{opn}/{wn}")
                assert "fsdp" in str(
                    m4.state.opt_state["m"][opn][wn].sharding.spec)
        # and the resumed model still steps
        m4.fit(x, y, epochs=1, verbose=False)


# ----------------------------------------------------------------------
# loader + lint for declarative weight-shard rules
# ----------------------------------------------------------------------
def test_json_weight_shard_rule_applies():
    from flexflow_tpu.pcg.lowering import layers_to_pcg
    from flexflow_tpu.search.substitution_loader import (
        apply_rule,
        load_rule_collection,
    )

    rule_json = {"rule": [{
        "name": "fsdp_linear_test",
        "srcOp": [{"type": "OP_LINEAR",
                   "input": [{"opId": -1, "tsId": 0}], "para": []}],
        "dstOp": [
            {"type": "OP_LINEAR",
             "input": [{"opId": -1, "tsId": 0}], "para": []},
            {"type": "OP_WEIGHT_SHARD",
             "input": [{"opId": 0, "tsId": 0}],
             "para": [{"key": "PM_PARALLEL_DEGREE", "value": 2}]},
        ],
        "mappedOutput": [{"srcOpId": 0, "srcTsId": 0,
                          "dstOpId": 1, "dstTsId": 0}],
    }]}
    rules = load_rule_collection(rule_json, validate=True)
    m = _mlp()
    graph, _ = layers_to_pcg(m.layers)
    got = list(apply_rule(graph, rules[0]))
    assert got
    ws = _ws_ops(got[0])
    assert len(ws) == 1 and ws[0].params.shard_degree == 2


def test_lint_rejects_degreeless_weight_shard_rule():
    from flexflow_tpu.search.substitution_loader import (
        SubstitutionRuleError,
        load_rule_collection,
    )

    rule_json = {"rule": [{
        "name": "fsdp_bad",
        "srcOp": [{"type": "OP_LINEAR",
                   "input": [{"opId": -1, "tsId": 0}], "para": []}],
        "dstOp": [
            {"type": "OP_LINEAR",
             "input": [{"opId": -1, "tsId": 0}], "para": []},
            {"type": "OP_WEIGHT_SHARD",
             "input": [{"opId": 0, "tsId": 0}], "para": []},
        ],
        "mappedOutput": [{"srcOpId": 0, "srcTsId": 0,
                          "dstOpId": 1, "dstTsId": 0}],
    }]}
    with pytest.raises(SubstitutionRuleError, match="FFA404"):
        load_rule_collection(rule_json, validate=True)


# ----------------------------------------------------------------------
# mesh lowering details
# ----------------------------------------------------------------------
@pytest.mark.skipif(NDEV < 2, reason="needs >= 2 devices")
def test_batch_dim_lowers_to_data_fsdp_tuple():
    from flexflow_tpu.parallel.mesh import pspec_for_parallel_tensor

    m = _mlp(fsdp=NDEV)
    lin = next(o for o in m.graph.ops if o.name == "op_linear_0")
    spec = pspec_for_parallel_tensor(lin.outputs[0], m.executor.mesh)
    assert spec[0] == ("data", "fsdp")
