"""L2 kernel regularization (reference:
examples/python/keras/regularizer.py — flexflow.keras.regularizers.L2)."""
import numpy as np

import flexflow.keras.models
import flexflow.keras.optimizers
from flexflow.keras.layers import Input, Dense
from flexflow.keras.regularizers import L2

from _example_args import example_args


def top_level_task(args):
    in0 = Input(shape=(32,), dtype="float32")
    x = Dense(20, activation="relu", kernel_regularizer=L2(0.001))(in0)
    out = Dense(1)(x)
    model = flexflow.keras.models.Model(in0, out)
    model.compile(optimizer=flexflow.keras.optimizers.Adam(learning_rate=0.001),
                  loss="mean_squared_error", metrics=["mean_squared_error"],
                  batch_size=args.batch_size)
    n = args.num_samples
    model.fit(np.random.randn(n, 32).astype(np.float32),
              np.random.randn(n, 1).astype(np.float32), epochs=args.epochs)


if __name__ == "__main__":
    print("regularizer")
    top_level_task(example_args(epochs=2, num_samples=512))
